"""ISSUE-12: chunked prefill + radix/hash prefix cache over the paged
KV pool.

Coverage map (the acceptance surface):

- PageAllocator extensions in isolation: refcount alloc/share/free
  semantics, cache pin/unpin, COW ``fork`` bookkeeping, double-free /
  foreign-free / misuse still raise, seeded-violation red tests for
  ``check()``;
- PrefixCache semantics: full-page chain keys, partial-tail
  exact-prompt match, LRU eviction that NEVER frees a reader-held
  page, flush (the hot-swap barrier), index/allocator coherence;
- scheduler integration: cache-hit admission cursor (capped at
  prompt_len - 1), COW fork emission + refcount bookkeeping,
  ``check_invariants()`` refcount cross-checks (red test included);
- token identity, both ways of the oracle: chunked prefill (any chunk
  size) == token-at-a-time == dense reference, and cache-hit decode ==
  cold decode — across staggered admit/evict/preempt traces, combined
  chunk x cache x tiny-pool preemption;
- eviction-under-pressure chaos property trace: random traces with
  stolen allocations AND forced cache evictions, ``check_invariants()``
  after every step, zero reader-held pages after drain, token identity
  throughout;
- admission/routing satellites: feasibility counts only uncached
  tokens, ``probe``'s post-hit prefill estimate, the
  ``_summarize`` prefill-vs-decode token split;
- the red hot-swap test: a stale prefix-cache entry surviving a
  rolling-update weight swap (``ReplicaFleet.try_join``) is
  impossible;
- CI wiring: the new ``serving_check.py --self`` legs, compare_bench
  gates, and the committed ``prefix_reuse`` CPU smoke artifact.
"""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.serving import (
    PageAllocator,
    PagedKVSpec,
    PrefixCache,
    Request,
    RequestStatus,
    Scheduler,
    ServingEngine,
    reference_decode,
)
from apex_tpu.transformer.testing import GPTConfig, init_gpt_params


def _tiny_cfg(dtype=jnp.float32):
    return GPTConfig(
        num_layers=2, hidden_size=64, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=64,
        hidden_dropout=0.0, attention_dropout=0.0,
        params_dtype=jnp.float32, compute_dtype=dtype)


@pytest.fixture(scope="module", autouse=True)
def _shed_compile_caches():
    """This module compiles many small engine programs late in the
    full suite; shed the executables the preceding files accumulated
    (the full-suite CPU lane runs close to its memory ceiling — the
    same pressure tests/test_crash_resume.py documents)."""
    jax.clear_caches()
    yield
    jax.clear_caches()


@pytest.fixture(scope="module")
def tiny_model():
    cfg = _tiny_cfg()
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    params["embedding"]["position"] = params["embedding"]["position"] * 40.0
    return cfg, params


def _spec(num_pages=8, page_size=16, pages_per_seq=4):
    # head_dim 64 keeps even the 4-token pages ROW-aligned (ROW=1024)
    return PagedKVSpec(1, 4, 64, page_size=page_size,
                       num_pages=num_pages, pages_per_seq=pages_per_seq)


# ---------------------------------------------------------------------------
# allocator: refcounts, pins, COW fork
# ---------------------------------------------------------------------------

def test_allocator_refcount_share_free_semantics():
    al = PageAllocator(5)  # pages 1..4
    p = al.alloc()
    assert al.refcount(p) == 1 and al.used_count == 1
    al.share(p)
    al.share(p)
    assert al.refcount(p) == 3
    al.free([p])
    al.free([p])
    assert al.refcount(p) == 1 and al.free_count == 3
    al.free([p])
    # third reader released -> back on the free list
    assert al.refcount(p) == 0 and al.free_count == 4
    with pytest.raises(ValueError, match="double-free"):
        al.free([p])
    al.check()


def test_allocator_pin_keeps_zero_reader_page_live():
    al = PageAllocator(4)
    p = al.alloc()
    al.pin(p)
    al.free([p])  # last READER gone; the pin keeps it live
    assert al.refcount(p) == 0 and al.is_pinned(p)
    assert al.used_count == 0          # no readers -> not "used"
    assert al.cached_count == 1
    assert al.free_count == 2          # p is NOT free
    al.check()
    al.unpin(p)                        # pin released -> freed
    assert al.free_count == 3
    with pytest.raises(ValueError, match="not live"):
        al.share(p)
    with pytest.raises(ValueError, match="not live"):
        al.pin(p)
    with pytest.raises(ValueError, match="not pinned"):
        al.unpin(p)


def test_allocator_pin_misuse_raises():
    al = PageAllocator(4)
    p = al.alloc()
    al.pin(p)
    with pytest.raises(ValueError, match="already pinned"):
        al.pin(p)
    with pytest.raises(ValueError, match="garbage"):
        al.free([0])


def test_allocator_is_shared():
    al = PageAllocator(5)
    p = al.alloc()
    assert not al.is_shared(p)          # one reader, no pin: exclusive
    al.share(p)
    assert al.is_shared(p)              # second reader
    al.free([p])
    al.pin(p)
    assert al.is_shared(p)              # one reader + index pin
    al.unpin(p)
    assert not al.is_shared(p)


def test_allocator_fork_bookkeeping():
    al = PageAllocator(5)
    src = al.alloc()
    al.share(src)                       # someone else reads src too
    dst = al.fork(src)
    assert dst is not None and dst != src
    assert al.refcount(src) == 1        # our hold moved off src
    assert al.refcount(dst) == 1
    al.check()
    # the scheduler's pressure path: the destination was obtained
    # separately (eviction/preemption machinery); fork just swaps holds
    pre = al.alloc()
    assert al.fork(dst, pre) == pre
    assert al.refcount(dst) == 0 and al.refcount(pre) == 1
    al.share(pre)
    with pytest.raises(ValueError, match="freshly allocated"):
        al.fork(src, pre)               # dst already has two holds
    al.free([pre])
    # fork on a dry pool reports None and leaves src untouched
    while al.alloc() is not None:
        pass
    assert al.fork(pre) is None
    assert al.refcount(pre) == 1


def test_allocator_check_red_seeded_violations():
    al = PageAllocator(5)
    p = al.alloc()
    al._ref[p] = 0  # zero readers, no pin, not released: a leak
    with pytest.raises(AssertionError, match="zero readers"):
        al.check()
    al._ref[p] = 1
    al._pinned.add(99)  # pin on a page that is not live
    with pytest.raises(AssertionError, match="pinned"):
        al.check()


# ---------------------------------------------------------------------------
# prefix cache semantics
# ---------------------------------------------------------------------------

def test_prefix_cache_full_page_chain_match():
    spec = _spec(num_pages=8, page_size=4)
    al = PageAllocator(spec.num_pages)
    cache = PrefixCache(spec, al)
    toks = list(range(10))  # 2 full pages + 2-token tail
    p0, p1 = al.alloc(), al.alloc()
    assert cache.insert(toks[:4], p0)
    assert cache.insert(toks[:8], p1)
    assert cache.match_len(toks) == 8
    assert cache.match_len(toks[:6]) == 4   # only the first page
    assert cache.match_len([99] + toks[1:]) == 0
    pages, matched = cache.acquire(toks)
    assert pages == [p0, p1] and matched == 8
    assert al.refcount(p0) == 2  # original owner + the acquirer
    # re-inserting an indexed key is a no-op (no double pin)
    assert not cache.insert(toks[:4], p0)
    cache.check()


def test_prefix_cache_partial_tail_exact_prompt_only():
    spec = _spec(num_pages=8, page_size=4)
    al = PageAllocator(spec.num_pages)
    cache = PrefixCache(spec, al)
    toks = list(range(6))  # 1 full page + 2-token tail
    p0, p1 = al.alloc(), al.alloc()
    cache.insert(toks[:4], p0)
    cache.insert(toks[:6], p1)  # the tail, keyed by the EXACT prompt
    assert cache.match_len(toks) == 6
    # a longer prompt sharing the head matches only the full page: the
    # tail key covers exactly 6 tokens, not "6 of my 8"
    assert cache.match_len(toks + [7, 8]) == 4
    assert cache.match_len(toks[:5]) == 4


def test_prefix_cache_eviction_never_frees_reader_held_pages():
    spec = _spec(num_pages=8, page_size=4)
    al = PageAllocator(spec.num_pages)
    cache = PrefixCache(spec, al)
    held, loose = al.alloc(), al.alloc()
    cache.insert([1, 2, 3, 4], held)
    cache.insert([5, 6, 7, 8], loose)
    al.free([loose])  # publisher released -> zero readers, LRU-oldest
    # `held` keeps its reader; eviction must pick `loose` even though
    # `held` is older in LRU order after a touch
    cache.acquire([5, 6, 7, 8])        # touch loose: now MRU + a reader
    al.free([loose])                   # release the touch again
    assert cache.evict_one() == loose  # held is skipped: reader-held
    assert al.refcount(held) == 1 and al.is_pinned(held)
    assert cache.evict_one() is None   # nothing evictable remains
    al.free([held])
    assert cache.evict_one() == held   # now it can go
    assert al.free_count == spec.n_usable_pages
    cache.check()


def test_prefix_cache_flush_is_total():
    spec = _spec(num_pages=8, page_size=4)
    al = PageAllocator(spec.num_pages)
    cache = PrefixCache(spec, al)
    a, b = al.alloc(), al.alloc()
    cache.insert([1, 2, 3, 4], a)
    cache.insert([9, 9, 9, 9], b)
    al.free([b])                       # b: index pin only
    assert cache.flush() == 2
    assert len(cache) == 0
    assert al.free_count == spec.n_usable_pages - 1  # a still read
    al.free([a])
    assert al.free_count == spec.n_usable_pages
    al.check()


def test_prefix_cache_check_red():
    spec = _spec(num_pages=8, page_size=4)
    al = PageAllocator(spec.num_pages)
    cache = PrefixCache(spec, al)
    p = al.alloc()
    cache.insert([1, 2, 3, 4], p)
    al._pinned.discard(p)  # corrupt: entry lost its pin
    with pytest.raises(AssertionError, match="pin"):
        cache.check()


# ---------------------------------------------------------------------------
# scheduler integration: hit cursor, COW, invariants
# ---------------------------------------------------------------------------

def _drive_prefill(sched, steps=100):
    """Advance a standalone scheduler like the engine would."""
    for _ in range(steps):
        if sched.idle:
            return
        sched.admit()
        sched.ensure_capacity()
        sched.take_forks()
        sched.take_dirty_slots()
        served = sched.running()
        sched.advance([i for i, _ in served])
        for i, run in served:
            if not run.prefilling:
                run.req.out_tokens.append(0)
            if run.req.done:
                sched.evict(i)
        sched.check_invariants()


def test_scheduler_cache_hit_starts_past_cached_head():
    spec = _spec(num_pages=10, page_size=4, pages_per_seq=6)
    sched = Scheduler(spec, n_slots=1, max_prompt_len=spec.max_seq_len,
                      prefix_cache=True)
    prompt = list(range(10))  # 2 full pages + 2-token tail
    r1 = Request(prompt=list(prompt), max_new_tokens=2)
    sched.submit(r1)
    _drive_prefill(sched)
    assert r1.cached_tokens == 0
    # pages for the full prompt are now indexed (2 full + exact tail)
    assert sched.cache.match_len(prompt) == 10
    r2 = Request(prompt=list(prompt), max_new_tokens=2)
    sched.submit(r2)
    sched.admit()
    (_, run), = sched.running()
    # full-prompt hit, capped: the FINAL prompt token is recomputed
    assert run.pos == 9 and run.cached_tokens == 9
    assert len(run.pages) == 3
    sched.check_invariants()
    # the write at pos 9 lands inside the shared tail -> COW fork
    sched.ensure_capacity()
    forks = sched.take_forks()
    assert len(forks) == 1
    src, dst = forks[0]
    assert src != dst and dst in run.pages and src not in run.pages
    sched.check_invariants()


def test_scheduler_invariants_red_refcount_mismatch():
    spec = _spec(num_pages=10, page_size=4)
    sched = Scheduler(spec, n_slots=1, max_prompt_len=spec.max_seq_len,
                      prefix_cache=True)
    sched.submit(Request(prompt=list(range(6)), max_new_tokens=2))
    sched.admit()
    sched.ensure_capacity()
    (_, run), = sched.running()
    # seed a violation: an extra reader nobody accounts for
    sched.allocator.share(run.pages[0])
    with pytest.raises(AssertionError, match="refcount"):
        sched.check_invariants()


# ---------------------------------------------------------------------------
# token identity: the oracle, both ways
# ---------------------------------------------------------------------------

def _mk_staggered(rng, lens, max_new=6, stride=3):
    return [
        Request(prompt=[int(t) for t in rng.integers(0, 128, size=L)],
                max_new_tokens=max_new, arrival_step=stride * i)
        for i, L in enumerate(lens)
    ]


@pytest.mark.parametrize("chunk", [2, 5, 16])
def test_chunked_prefill_token_identical(tiny_model, chunk):
    """Acceptance: chunked prefill (any chunk size) over a staggered
    continuous-batching trace is token-identical to token-at-a-time
    prefill — and finishes in fewer steps. The chunk=2 case is also
    grounded against the dense reference directly (token-at-a-time
    itself is dense-grounded in tests/test_serving.py)."""
    cfg, params = tiny_model
    rng = np.random.default_rng(42)
    reqs = _mk_staggered(rng, (5, 9, 3, 12, 7))
    eng = ServingEngine(cfg, params, n_slots=2, num_pages=12,
                        max_prompt_len=16, prefill_chunk=chunk)
    out = eng.generate(reqs, max_steps=1000)
    eng.scheduler.check_invariants()
    assert eng.scheduler.allocator.used_count == 0
    if chunk == 2:
        for r in reqs:
            assert out[r.rid] == reference_decode(
                cfg, params, r.prompt, r.max_new_tokens)
    base = ServingEngine(cfg, params, n_slots=2, num_pages=12,
                        max_prompt_len=16, prefill_chunk=1)
    rng = np.random.default_rng(42)
    ref_reqs = _mk_staggered(rng, (5, 9, 3, 12, 7))
    out1 = base.generate(ref_reqs, max_steps=1000)
    for r, rr in zip(reqs, ref_reqs):
        assert out[r.rid] == out1[rr.rid]
    assert eng.last_stats["steps"] < base.last_stats["steps"]


def test_chunked_prefill_identical_under_preemption(tiny_model):
    """Chunk + tiny pool: recompute-mode preemption mid-chunked-prefill
    must not change a single token."""
    cfg, params = tiny_model
    rng = np.random.default_rng(7)
    reqs = [Request(prompt=[int(t) for t in rng.integers(0, 128, size=L)],
                    max_new_tokens=8, arrival_step=i)
            for i, L in enumerate((14, 11, 13, 9))]
    eng = ServingEngine(cfg, params, n_slots=2, num_pages=4,
                        max_prompt_len=16, prefill_chunk=4)
    out = eng.generate(reqs, max_steps=2000)
    eng.scheduler.check_invariants()
    assert eng.last_stats["preemptions"] > 0
    for r in reqs:
        assert out[r.rid] == reference_decode(cfg, params, r.prompt,
                                              r.max_new_tokens)


def test_cache_hit_decode_byte_identical_to_cold(tiny_model):
    """Acceptance: a cache-hit decode is identical to the cold decode
    of the same request — shared heads, an exact-duplicate prompt (the
    COW path), warm stats prove the hits actually happened."""
    cfg, params = tiny_model
    rng = np.random.default_rng(11)
    head = [int(t) for t in rng.integers(0, 128, size=32)]
    prompts = [head + [int(t) for t in rng.integers(0, 128, size=4)],
               head + [int(t) for t in rng.integers(0, 128, size=7)],
               list(head)]
    eng = ServingEngine(cfg, params, n_slots=2, num_pages=24,
                        prefill_chunk=4)
    cold = [Request(prompt=list(p), max_new_tokens=6) for p in prompts]
    out_cold = eng.generate(cold, max_steps=2000)
    cold_steps = eng.last_stats["steps"]
    warm = [Request(prompt=list(p), max_new_tokens=6) for p in prompts]
    out_warm = eng.generate(warm, max_steps=2000)
    eng.scheduler.check_invariants()
    st = eng.last_stats["prefix_cache"]
    assert st["hits"] == len(prompts)
    assert st["hit_tokens"] >= 3 * 32
    assert st["cached_prompt_tokens"] > 0
    assert eng.last_stats["steps"] < cold_steps
    assert eng.scheduler.allocator.used_count == 0
    for p, c, w in zip(prompts, cold, warm):
        ref = reference_decode(cfg, params, p, 6)
        assert out_cold[c.rid] == ref
        assert out_warm[w.rid] == ref


def test_cache_and_chunk_identity_under_preempt_evict_churn(tiny_model):
    """Acceptance: chunk x cache x tiny pool x staggered arrivals —
    preemptions, cache evictions under pressure, COW forks, replay
    self-hits — every request still token-identical, invariants clean,
    zero reader-held pages.

    Oracle: a chunk=1, cache-off engine over the same traces (itself
    pinned to the dense reference by the existing identity tests) —
    one compiled program instead of per-token eager dense forwards, so
    the randomized sweep stays cheap under full-suite load."""
    cfg, params = tiny_model

    def mk(seed):
        rng = np.random.default_rng(seed)
        lens = rng.integers(3, 15, size=6)
        return [Request(
            prompt=[int(t) for t in rng.integers(0, 128, size=int(L))],
            max_new_tokens=int(rng.integers(2, 9)),
            arrival_step=int(rng.integers(0, 12)))
            for L in lens]

    for seed in (3, 7, 19):
        base = ServingEngine(cfg, params, n_slots=2, num_pages=12,
                             max_prompt_len=16, prefill_chunk=1,
                             prefix_cache=False)
        ref_reqs = mk(seed)
        ref_out = base.generate(ref_reqs, max_steps=4000)
        eng = ServingEngine(cfg, params, n_slots=2, num_pages=4,
                            max_prompt_len=16, prefill_chunk=3)
        reqs = mk(seed)
        out = eng.generate(reqs, max_steps=4000)
        eng.scheduler.check_invariants()
        assert eng.scheduler.allocator.used_count == 0
        for ref_r, r in zip(ref_reqs, reqs):
            assert r.prompt == ref_r.prompt
            assert out[r.rid] == ref_out[ref_r.rid], (seed, r.rid)


# ---------------------------------------------------------------------------
# eviction-under-pressure chaos property trace
# ---------------------------------------------------------------------------

def test_chaos_eviction_under_pressure_property_trace(tiny_model):
    """The satellite contract: with stolen allocations AND forced cache
    evictions firing mid-trace, ``check_invariants()`` (refcount
    cross-checks included) passes after EVERY step, eviction never
    frees a page a live reader holds (that is what the invariants
    assert), every request completes token-identically, and the trace
    drains to zero reader-held pages. Oracle: the chunk=1, cache-off
    engine over the same requests (itself pinned to the dense
    reference by the smaller identity tests)."""
    from apex_tpu.resilience import ServingChaos

    cfg, params = tiny_model

    def mk(seed):
        rng = np.random.default_rng(seed)
        return [Request(
            prompt=[int(t) for t in rng.integers(0, 128, size=int(L))],
            max_new_tokens=5, arrival_step=int(rng.integers(0, 8)))
            for L in rng.integers(4, 14, size=5)]

    for seed in (0, 5):
        base = ServingEngine(cfg, params, n_slots=2, num_pages=12,
                             max_prompt_len=16, prefill_chunk=1,
                             prefix_cache=False)
        ref_reqs = mk(seed)
        ref_out = base.generate(ref_reqs, max_steps=3000)
        reqs = mk(seed)
        chaos = (ServingChaos()
                 .fail_allocs(3)
                 .evict_prefix_cache(2)
                 .evict_prefix_cache(2))
        eng = ServingEngine(cfg, params, n_slots=2, num_pages=5,
                            max_prompt_len=16, prefill_chunk=3,
                            chaos=chaos)
        pending = sorted(reqs, key=lambda r: (r.arrival_step, r.rid))
        step = 0
        while pending or not eng.scheduler.idle:
            while pending and pending[0].arrival_step <= step:
                eng.try_submit(pending.pop(0))
            if not eng.scheduler.idle:
                eng.run_step()
            eng.scheduler.check_invariants()
            step += 1
            assert step < 3000, "chaos trace did not terminate"
        assert any(f[0] == "cache_evict" for f in chaos.faults_fired)
        assert eng.scheduler.allocator.used_count == 0
        for ref_r, r in zip(ref_reqs, reqs):
            assert r.status is RequestStatus.COMPLETED
            assert list(r.out_tokens) == ref_out[ref_r.rid], \
                (seed, r.rid)


def test_poisoned_prefill_pages_never_published(tiny_model):
    """Review regression: a slot whose logits go non-finite wrote
    non-finite K/V that same step — the pages it completed this step
    must NOT be published to the prefix index (a later request sharing
    the prefix would decode from NaN K/V and cascade the quarantine).
    The quarantined slot is excluded from advance() before publication
    runs; a subsequent identical-prompt request must decode cold,
    token-identical to the dense reference."""
    from apex_tpu.resilience import ServingChaos

    cfg, params = tiny_model
    rng = np.random.default_rng(21)
    # 20-token prompt = page 0 (16) + partial tail; chunk 16 completes
    # page 0 in the victim's FIRST step — exactly when poison fires
    prompt = [int(t) for t in rng.integers(0, 128, size=20)]
    victim = Request(prompt=list(prompt), max_new_tokens=4)
    chaos = ServingChaos().poison_request(victim.rid)
    eng = ServingEngine(cfg, params, n_slots=1, num_pages=8,
                        max_prompt_len=24, prefill_chunk=16,
                        chaos=chaos)
    eng.generate([victim], max_steps=200)
    assert victim.status is RequestStatus.FAILED
    # nothing of the poisoned prefill may be resident
    assert eng.prefix_cache.match_len(prompt) == 0
    eng.scheduler.check_invariants()
    retry = Request(prompt=list(prompt), max_new_tokens=4)
    out = eng.generate([retry], max_steps=200)
    ref = reference_decode(cfg, params, prompt, 4)
    assert out[retry.rid] == ref
    assert eng.scheduler.allocator.used_count == 0


# ---------------------------------------------------------------------------
# admission / routing satellites
# ---------------------------------------------------------------------------

def test_admission_feasibility_counts_only_uncached_tokens(tiny_model):
    """A request whose deadline is infeasible against its FULL prompt
    but feasible against its uncached head must be refused cold and
    admitted warm — admission bills only the prefill actually owed."""
    from apex_tpu.serving import AdmissionConfig, RejectionCode

    cfg, params = tiny_model
    rng = np.random.default_rng(2)
    prompt = [int(t) for t in rng.integers(0, 128, size=32)]

    def engine():
        return ServingEngine(
            cfg, params, n_slots=2, num_pages=24, prefill_chunk=1,
            admission=AdmissionConfig(step_time_init_s=0.01))

    # 32 prefill steps * 10ms = 320ms lower bound > 100ms budget
    hurried = Request(prompt=list(prompt), max_new_tokens=2,
                      ttft_budget_ms=100.0)
    cold = engine()
    reason = cold.try_submit(hurried)
    assert reason is not None
    assert reason.code is RejectionCode.DEADLINE_INFEASIBLE
    warm = engine()
    warm.generate([Request(prompt=list(prompt), max_new_tokens=2)],
                  max_steps=200)
    # cached head: ~1 uncached token -> ~10ms << 100ms budget
    hurried2 = Request(prompt=list(prompt), max_new_tokens=2,
                       ttft_budget_ms=100.0)
    assert warm._prefill_steps(hurried2) <= 2
    assert warm.try_submit(hurried2) is None


def test_probe_uses_post_hit_prefill_estimate(tiny_model):
    """The router cost satellite: est steps-to-first-token shrink once
    the prompt head is cached, and shrink further with a larger
    prefill chunk."""
    cfg, params = tiny_model
    rng = np.random.default_rng(4)
    prompt = [int(t) for t in rng.integers(0, 128, size=32)]
    eng = ServingEngine(cfg, params, n_slots=2, num_pages=24,
                        prefill_chunk=1)
    probe_req = Request(prompt=list(prompt), max_new_tokens=2)
    _, cold_est = eng.probe(probe_req)
    eng.generate([Request(prompt=list(prompt), max_new_tokens=2)],
                 max_steps=200)
    _, warm_est = eng.probe(probe_req)
    assert warm_est < cold_est
    chunky = ServingEngine(cfg, params, n_slots=2, num_pages=24,
                           prefill_chunk=8)
    _, chunk_est = chunky.probe(probe_req)
    assert chunk_est < cold_est


def test_summarize_splits_prefill_and_decode_tokens(tiny_model):
    """The small-fix satellite: prefill vs decode token counts are
    separate (steps conflated them), and they reconcile with the trace
    — prefill_tokens = prompt tokens actually computed (cached head
    excluded), decode_tokens = generated tokens beyond each first."""
    cfg, params = tiny_model
    rng = np.random.default_rng(9)
    prompt = [int(t) for t in rng.integers(0, 128, size=10)]
    eng = ServingEngine(cfg, params, n_slots=1, num_pages=8,
                        max_prompt_len=16, prefill_chunk=3)
    eng.generate([Request(prompt=list(prompt), max_new_tokens=4)],
                 max_steps=200)
    st = eng.last_stats
    # 10 prompt tokens consumed in ceil(10/3)=4 prefill slot-steps;
    # the first generated token is emitted by the LAST prefill step,
    # the remaining 3 by decode steps
    assert st["prefill_tokens"] == 10
    assert st["prefill_slot_steps"] == 4
    assert st["decode_tokens"] == 3
    assert st["generated_tokens"] == 4
    assert st["prefill_chunk"] == 3
    assert st["cached_prompt_tokens"] == 0
    assert st["prefix_cache"]["hit_rate"] is None \
        or st["prefix_cache"]["hits"] == 0
    # warm re-run: the cached head moves work out of prefill_tokens
    eng.generate([Request(prompt=list(prompt), max_new_tokens=4)],
                 max_steps=200)
    st2 = eng.last_stats
    assert st2["cached_prompt_tokens"] == 9
    assert st2["prefill_tokens"] == 1
    assert st2["prefix_cache"]["hits"] == 1


def test_chunk_step_audits_clean(tiny_model):
    """Both jitted programs (1-token decode + chunked prefill) pass the
    PR-4 auditor: KV/slot/metrics donated, cond-gated callbacks only."""
    from apex_tpu import telemetry

    cfg, params = tiny_model
    eng = ServingEngine(cfg, params, n_slots=2, num_pages=6,
                        max_prompt_len=16, prefill_chunk=4,
                        telemetry_every=4,
                        sink=telemetry.RingBufferRecorder())
    report = eng.audit()  # audits decode AND chunk steps; raises on error
    assert report.ok


# ---------------------------------------------------------------------------
# weight hot-swap: stale cache entries are impossible
# ---------------------------------------------------------------------------

def test_stale_prefix_cache_cannot_survive_weight_swap(tiny_model):
    """RED contract: K/V cached under old weights MUST NOT survive a
    rolling-update weight swap. ``try_join`` goes through
    ``swap_params`` which flushes the per-replica cache — post-swap
    traffic with the SAME prompts decodes per the NEW weights (if a
    stale entry survived, the emitted tokens would match the old
    model's and this test would fail)."""
    from apex_tpu.serving import ReplicaFleet

    cfg, params = tiny_model
    params2 = jax.tree_util.tree_map(lambda x: x, params)
    params2["embedding"]["position"] = (
        params["embedding"]["position"] * 0.5)
    rng = np.random.default_rng(13)
    prompts = [[int(t) for t in rng.integers(0, 128, size=20)]
               for _ in range(2)]
    fleet = ReplicaFleet(cfg, params, n_replicas=2, n_slots=2,
                         num_pages=16, prefill_chunk=4)
    phase1 = [Request(prompt=list(p), max_new_tokens=4)
              for p in prompts for _ in range(2)]
    fleet.generate(phase1, max_steps=2000)
    assert any(len(rep.engine.prefix_cache) > 0
               for rep in fleet.replicas)
    fleet.schedule_rolling_update(params2)
    fleet.generate([], max_steps=200)  # drain the swap wave
    assert fleet.rolling_update_done
    for rep in fleet.replicas:
        assert len(rep.engine.prefix_cache) == 0, (
            f"replica {rep.idx}: stale prefix-cache entries survived "
            "the weight swap")
    # SAME prompts post-swap: must decode per the NEW weights
    phase2 = [Request(prompt=list(p), max_new_tokens=4) for p in prompts]
    out2 = fleet.generate(phase2, max_steps=2000)
    for p, r in zip(prompts, phase2):
        ref_new = reference_decode(cfg, params2, p, 4)
        assert out2[r.rid] == ref_new
    fleet.check_invariants()
    assert fleet.page_leaks() == 0


def test_engine_swap_params_flushes_cache(tiny_model):
    cfg, params = tiny_model
    rng = np.random.default_rng(17)
    prompt = [int(t) for t in rng.integers(0, 128, size=20)]
    eng = ServingEngine(cfg, params, n_slots=1, num_pages=8,
                        prefill_chunk=4)
    eng.generate([Request(prompt=list(prompt), max_new_tokens=3)],
                 max_steps=200)
    assert len(eng.prefix_cache) > 0
    assert eng.scheduler.allocator.cached_count > 0
    eng.swap_params(params)
    assert len(eng.prefix_cache) == 0
    assert eng.scheduler.allocator.cached_count == 0
    eng.scheduler.check_invariants()


def test_restarted_replica_gets_fresh_cache(tiny_model):
    """rebuild_like / recover_from build a NEW engine: a fresh pool and
    a fresh (empty) prefix cache — the restart path cannot carry
    stale entries by construction."""
    cfg, params = tiny_model
    rng = np.random.default_rng(19)
    eng = ServingEngine(cfg, params, n_slots=1, num_pages=8,
                        prefill_chunk=4)
    eng.generate([Request(
        prompt=[int(t) for t in rng.integers(0, 128, size=20)],
        max_new_tokens=3)], max_steps=200)
    assert len(eng.prefix_cache) > 0
    fresh = ServingEngine.rebuild_like(eng)
    assert fresh.prefix_cache is not None
    assert len(fresh.prefix_cache) == 0
    assert fresh.prefill_chunk == eng.prefill_chunk


# ---------------------------------------------------------------------------
# CI wiring: serving_check legs, compare_bench gates, smoke artifact
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("leg", ["chunked_prefill_identity",
                                 "prefix_hit_identity"])
def test_serving_check_prefix_legs_pass(leg):
    import tools.serving_check as sc

    assert sc.main(["--self", "--check", leg, "--json"]) == 0


def test_compare_bench_gates_prefix_reuse_leg():
    from tools.compare_bench import compare, extract_legs

    base = {"prefix_reuse": {"ttft_p99_ms": 100.0, "hit_rate": 0.8,
                             "prefill_flops_saved": 5.0e9}}
    legs = extract_legs(base)
    assert legs["prefix_ttft_p99_ms"] == -100.0  # lower-is-better
    assert legs["prefix_hit_rate"] == 0.8
    worse = {"prefix_reuse": {"ttft_p99_ms": 140.0, "hit_rate": 0.5,
                              "prefill_flops_saved": 5.0e9}}
    rep = compare(base, worse, threshold=0.05)
    assert {r["leg"] for r in rep["regressions"]} == {
        "prefix_ttft_p99_ms", "prefix_hit_rate"}
    missing = {"serving_throughput": {"tokens_per_sec": 1.0}}
    rep = compare(base, missing, threshold=0.05)
    assert "prefix_hit_rate" in rep["only_in_base"]  # schema drift visible


def test_prefix_reuse_smoke_artifact_committed():
    """The acceptance artifact: nonzero hit rate, >0 flops saved, and a
    TTFT reduction on the shared-prefix trace, with zero page leaks."""
    art = json.load(open("bench_artifacts/prefix_reuse_cpu_smoke.json"))
    leg = art["prefix_reuse"]
    assert leg["hit_rate"] > 0
    assert leg["prefill_flops_saved"] > 0
    assert leg["prefill_tokens_saved"] > 0
    assert leg["ttft_p50_ms"] < leg["ttft_cold_p50_ms"]
    assert leg["ttft_reduction_pct"] > 0
    assert leg["page_leaks"] == 0
    assert leg["prefill_chunk"] > 1
