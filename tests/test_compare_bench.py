"""Smoke tests for tools/compare_bench.py — tier-1-safe (pure JSON, no
jax): per-leg regression detection plus schema-drift protection against
the real archived bench captures, so a bench.py output change that
breaks the extractor fails CI here rather than silently in the driver.
"""
import json
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools.compare_bench import (  # noqa: E402
    compare,
    compare_trajectory,
    extract_legs,
    load_bench,
    main,
)

REPO = Path(__file__).resolve().parent.parent


def _bench(tokens=30000.0, bert=12000.0, gbps=600.0):
    return {
        "metric": "gpt2_345m_1chip_bf16_train_throughput",
        "value": tokens,
        "unit": "tokens/sec",
        "true_mfu": 0.33,
        "bert_large_lamb": {"tokens_per_sec": bert},
        "packed_optimizer": {"gbps_achieved": gbps, "vs_pytree": 1.4},
        "telemetry_overhead": {"overhead_pct": 0.3},
    }


def test_extract_legs_orients_lower_is_better():
    legs = extract_legs(_bench())
    assert legs["gpt_tokens_per_sec"] == 30000.0
    # lower-is-better legs are negated so "higher is better" is uniform
    assert legs["telemetry_overhead_pct"] == -0.3


def test_compare_flags_regression_and_improvement():
    base = _bench()
    new = _bench(tokens=20000.0, bert=13000.0)  # gpt -33%, bert +8%
    rep = compare(base, new, threshold=0.05)
    regressed = {r["leg"] for r in rep["regressions"]}
    improved = {r["leg"] for r in rep["improvements"]}
    assert "gpt_tokens_per_sec" in regressed
    assert "bert_tokens_per_sec" in improved
    assert "packed_opt_gbps" in rep["unchanged"]
    # a higher overhead_pct is a REGRESSION even though the number rose,
    # and the report shows the ORIGINAL signed values, not magnitudes
    lucky = _bench()
    lucky["telemetry_overhead"]["overhead_pct"] = -0.5
    worse_overhead = _bench()
    worse_overhead["telemetry_overhead"]["overhead_pct"] = 5.0
    rep2 = compare(lucky, worse_overhead, threshold=0.05)
    (entry,) = [r for r in rep2["regressions"]
                if r["leg"] == "telemetry_overhead_pct"]
    assert entry["base"] == -0.5 and entry["new"] == 5.0
    assert entry["delta_abs"] == pytest.approx(5.5)


def test_overhead_pct_uses_absolute_tolerance():
    """A near-zero percentage metric must not turn sub-point noise into
    a regression via the relative threshold (-0.3 -> +0.4 is noise)."""
    lucky, noisy = _bench(), _bench()
    lucky["telemetry_overhead"]["overhead_pct"] = -0.3
    noisy["telemetry_overhead"]["overhead_pct"] = 0.4
    rep = compare(lucky, noisy, threshold=0.05)
    assert "telemetry_overhead_pct" in rep["unchanged"]


def test_compare_within_threshold_is_unchanged():
    rep = compare(_bench(tokens=10000.0), _bench(tokens=10300.0),
                  threshold=0.05)
    assert not rep["regressions"] and not rep["improvements"]
    assert "gpt_tokens_per_sec" in rep["unchanged"]


def test_compare_reports_schema_drift():
    base, new = _bench(), _bench()
    del new["bert_large_lamb"]  # a leg vanishing must be visible
    rep = compare(base, new)
    assert "bert_tokens_per_sec" in rep["only_in_base"]


def test_load_bench_handles_raw_capture_and_garbage(tmp_path):
    raw = tmp_path / "raw.json"
    raw.write_text(json.dumps(_bench()))
    assert load_bench(str(raw))["value"] == 30000.0

    cap = tmp_path / "cap.json"
    cap.write_text(json.dumps(
        {"n": 3, "rc": 0, "tail": "noise\n" + json.dumps(_bench()),
         "parsed": None}))
    assert load_bench(str(cap))["value"] == 30000.0

    trunc = tmp_path / "trunc.json"
    trunc.write_text(json.dumps(
        {"n": 5, "rc": 0, "tail": 'gbps": 1.0}', "parsed": None}))
    assert load_bench(str(trunc)) is None


@pytest.mark.parametrize("name", ["BENCH_r01", "BENCH_r02", "BENCH_r03",
                                  "BENCH_r04"])
def test_archived_captures_still_extract(name):
    """Schema-drift canary: the real driver captures must keep yielding
    the headline leg (bench.py output format and the extractor evolve
    together or this fails)."""
    bench = load_bench(str(REPO / f"{name}.json"))
    assert bench is not None
    legs = extract_legs(bench)
    assert "gpt_tokens_per_sec" in legs
    assert legs["gpt_tokens_per_sec"] > 0


def test_trajectory_over_archived_captures():
    paths = [str(REPO / f"BENCH_r0{i}.json") for i in (1, 2, 3, 4)]
    rep = compare_trajectory(paths, threshold=0.05)
    assert len(rep["steps"]) == 3
    for step in rep["steps"]:
        assert "regressions" in step and "only_in_new" in step


def test_cli_exit_codes(tmp_path, capsys):
    base = tmp_path / "base.json"
    base.write_text(json.dumps(_bench()))
    good = tmp_path / "good.json"
    good.write_text(json.dumps(_bench(tokens=31000.0)))
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(_bench(tokens=9000.0)))

    assert main([str(base), str(good)]) == 0
    capsys.readouterr()  # drop the first report
    assert main([str(base), str(bad)]) == 1
    report = json.loads(capsys.readouterr().out)
    assert report["regressions"][0]["leg"] == "gpt_tokens_per_sec"
    # custom threshold: a 10% drop passes at --threshold 0.2
    mid = tmp_path / "mid.json"
    mid.write_text(json.dumps(_bench(tokens=27000.0)))
    assert main([str(base), str(mid), "--threshold", "0.2"]) == 0


def test_cli_trajectory_all_unparseable_fails_loudly(tmp_path, capsys):
    """Schema drift truncating EVERY capture must not exit 0 — an empty
    comparison is a failure of the gate, not a pass."""
    paths = []
    for i in range(3):
        p = tmp_path / f"t{i}.json"
        p.write_text(json.dumps({"n": i, "rc": 0, "tail": "}", "parsed": None}))
        paths.append(str(p))
    assert main(paths + ["--trajectory"]) == 2
    report = json.loads(capsys.readouterr().out)
    assert len(report["skipped_unparseable"]) == 3


# ---------------------------------------------------------------------------
# op-breakdown category diffing (ISSUE-9)
# ---------------------------------------------------------------------------

def _bench_with_categories(elementwise, data_movement, matmul):
    b = _bench()
    other = 100.0 - elementwise - data_movement - matmul
    b["op_breakdown"] = {
        "source": "xplane",
        "categories": {
            "fusion(elementwise)": {"ms_per_step": 1.0, "pct": elementwise},
            "data-movement": {"ms_per_step": 1.0, "pct": data_movement},
            "matmul/conv": {"ms_per_step": 1.0, "pct": matmul},
            "attention-kernel": {"ms_per_step": 1.0, "pct": other},
        },
    }
    return b


def test_category_regression_flagged_over_2pp():
    base = _bench_with_categories(20.0, 10.0, 40.0)
    new = _bench_with_categories(25.0, 10.0, 35.0)  # elementwise +5pp
    rep = compare(base, new)
    regressed = {r["leg"] for r in rep["regressions"]}
    assert "op_category:fusion(elementwise)" in regressed
    (entry,) = [r for r in rep["regressions"]
                if r["leg"] == "op_category:fusion(elementwise)"]
    assert entry["delta_pp"] == 5.0
    # the full shift table rides the report
    shifts = {s["category"]: s["delta_pp"]
              for s in rep["op_categories"]["shift"]}
    assert shifts["matmul/conv"] == -5.0


def test_compute_category_growth_not_flagged():
    # winning back elementwise time NECESSARILY grows the matmul share —
    # that is the point of the fused tails, not a regression
    base = _bench_with_categories(42.0, 18.0, 12.0)
    new = _bench_with_categories(25.0, 10.0, 37.0)  # the ISSUE-9 target
    rep = compare(base, new)
    assert not [r for r in rep["regressions"]
                if r["leg"].startswith("op_category:")]


def test_category_shift_within_threshold_not_flagged():
    base = _bench_with_categories(20.0, 10.0, 40.0)
    new = _bench_with_categories(21.5, 10.0, 38.5)  # +1.5pp < 2pp
    rep = compare(base, new)
    assert not [r for r in rep["regressions"]
                if r["leg"].startswith("op_category:")]


def test_missing_breakdown_skips_category_diff():
    rep = compare(_bench(), _bench_with_categories(20.0, 10.0, 40.0))
    assert rep["op_categories"] is None
    # cost_analysis captures (CPU) publish empty categories — also skipped
    empty = _bench()
    empty["op_breakdown"] = {"source": "cost_analysis", "categories": {}}
    rep2 = compare(empty, empty)
    assert rep2["op_categories"] is None


def test_category_appearing_counts_as_shift():
    base = _bench_with_categories(20.0, 10.0, 40.0)
    new = _bench_with_categories(20.0, 10.0, 40.0)
    new["op_breakdown"]["categories"]["fusion(unattributed)"] = {
        "ms_per_step": 2.0, "pct": 6.0}
    rep = compare(base, new)
    regressed = {r["leg"] for r in rep["regressions"]}
    assert "op_category:fusion(unattributed)" in regressed


def _bench_with_grad_lifecycle(speedup=1.9, bytes_ratio=0.95,
                               steps_per_sec=50.0):
    b = _bench()
    b["grad_lifecycle"] = {
        "per_leaf": {"steps_per_sec": steps_per_sec / speedup},
        "flat": {"steps_per_sec": steps_per_sec},
        "speedup": speedup,
        "bytes_ratio": bytes_ratio,
        "flops_ratio": 1.1,
    }
    return b


def test_grad_lifecycle_legs_extract_and_gate():
    """ISSUE-14: the flat-vs-per-leaf A/B is a first-class gated leg —
    speedup and flat steps/s regress like throughput, and bytes_ratio
    regresses when it RISES back toward parity (lower is better)."""
    legs = extract_legs(_bench_with_grad_lifecycle())
    assert legs["grad_lifecycle_speedup"] == 1.9
    assert legs["grad_lifecycle_bytes_ratio"] == -0.95  # lower-is-better
    assert legs["grad_lifecycle_steps_per_sec"] == 50.0

    base = _bench_with_grad_lifecycle()
    worse = _bench_with_grad_lifecycle(speedup=1.2, bytes_ratio=1.05,
                                       steps_per_sec=40.0)
    rep = compare(base, worse, threshold=0.05)
    regressed = {r["leg"] for r in rep["regressions"]}
    assert {"grad_lifecycle_speedup", "grad_lifecycle_bytes_ratio",
            "grad_lifecycle_steps_per_sec"} <= regressed
    # improvement direction: bytes_ratio FALLING is an improvement
    better = _bench_with_grad_lifecycle(bytes_ratio=0.80)
    rep2 = compare(base, better, threshold=0.05)
    improved = {r["leg"] for r in rep2["improvements"]}
    assert "grad_lifecycle_bytes_ratio" in improved


def test_grad_lifecycle_smoke_artifact_carries_gated_legs():
    """The committed CPU smoke artifact records the acceptance numbers
    the gates act on: bytes_ratio < 1.0 and speedup > 1 with equal
    final_loss on both legs (the bit-identity witness)."""
    art = json.loads(
        (REPO / "bench_artifacts/grad_lifecycle_cpu_smoke.json")
        .read_text())
    leg = art["grad_lifecycle"]
    assert leg["bytes_ratio"] < 1.0
    assert leg["speedup"] > 1.0
    assert leg["flat"]["final_loss"] == leg["per_leaf"]["final_loss"]
    assert leg["n_buckets"] >= 2 and leg["world"] >= 2


# ---------------------------------------------------------------------------
# static comm budgets (ISSUE-19): count pins + bytes growth gate
# ---------------------------------------------------------------------------
def _comm(psum_count=3, psum_bytes=1040, gather_bytes=2048):
    return {"psum": {"count": psum_count, "bytes": psum_bytes,
                     "axes": ["tensor"]},
            "all_gather": {"count": 2, "bytes": gather_bytes,
                           "axes": ["tensor"]}}


def _bench_with_comm(**kw):
    b = _bench()
    b["serving_tp"] = {"comm_volume": {"decode": _comm(**kw)}}
    return b


def test_comm_count_change_is_exact_pin_both_directions():
    base = _bench_with_comm()
    grew = _bench_with_comm(psum_count=4)
    rep = compare(base, grew, threshold=0.05)
    (entry,) = [r for r in rep["regressions"]
                if r["leg"].startswith("comm_count:")]
    assert entry["leg"] == "comm_count:serving_tp.decode/psum"
    assert entry["base"] == 3 and entry["new"] == 4
    # a VANISHED collective regresses too (lost reduction != perf win)
    shrank = _bench_with_comm(psum_count=2)
    rep2 = compare(base, shrank, threshold=0.05)
    assert any(r["leg"] == "comm_count:serving_tp.decode/psum"
               for r in rep2["regressions"])


def test_comm_new_collective_family_is_flagged():
    base = _bench_with_comm()
    new = _bench_with_comm()
    new["serving_tp"]["comm_volume"]["decode"]["ppermute"] = {
        "count": 1, "bytes": 64, "axes": ["tensor"]}
    rep = compare(base, new, threshold=0.05)
    assert any(r["leg"] == "comm_count:serving_tp.decode/ppermute"
               and r["base"] == 0 and r["new"] == 1
               for r in rep["regressions"])


def test_comm_bytes_growth_gated_at_threshold():
    base = _bench_with_comm()
    fat = _bench_with_comm(gather_bytes=4096)  # +100% at equal count
    rep = compare(base, fat, threshold=0.05)
    (entry,) = [r for r in rep["regressions"]
                if r["leg"].startswith("comm_bytes:")]
    assert entry["leg"] == "comm_bytes:serving_tp.decode/all_gather"
    assert entry["delta_pct"] == 100.0
    # within the threshold: unchanged
    ok = compare(base, _bench_with_comm(gather_bytes=2080),
                 threshold=0.05)
    assert not any(r["leg"].startswith("comm_")
                   for r in ok["regressions"])


def test_comm_absent_in_either_capture_is_not_a_regression():
    """Captures predating the comm model (or a program dropped from the
    bench matrix) compare on the legs they share, like audit blocks."""
    rep = compare(_bench(), _bench_with_comm(), threshold=0.05)
    assert rep["comm"] is None
    assert not any(r["leg"].startswith("comm_")
                   for r in rep["regressions"])
    rep2 = compare(_bench_with_comm(), _bench(), threshold=0.05)
    assert rep2["comm"] is None


def test_comm_gpt_headline_rides_audit_block():
    base = _bench()
    base["audit"] = {"ok": True, "error": 0, "warning": 0, "codes": [],
                     "comm_volume": {"psum": {"count": 4, "bytes": 100,
                                              "axes": ["data"]}}}
    new = json.loads(json.dumps(base))
    new["audit"]["comm_volume"]["psum"]["count"] = 5
    rep = compare(base, new, threshold=0.05)
    assert rep["comm"]["programs"] == ["gpt_headline"]
    assert any(r["leg"] == "comm_count:gpt_headline/psum"
               for r in rep["regressions"])
