"""Numerics health monitor tests (apex_tpu.telemetry.numerics).

Contracts pinned here:

- **Overflow provenance**: poisoning ONE grad leaf with NaN inside a
  jitted step yields an anomaly event naming exactly that leaf — on the
  pytree path, the packed flat-buffer path (row-aligned ``PackSpec``
  offsets), and the scaler-integrated path (per-leaf flags reused from
  the unscale sweep). Healthy steps emit NOTHING (the ``lax.cond`` drain
  is not taken).
- **Anomaly rules**: grad-norm spike vs the EWMA window, loss-scale
  collapse below the floor (edge-triggered), non-finite grads.
- **Rank-0 gating**: events route through the PR-2 recorder sinks, so
  non-logging ranks drop them at the sink under ``parallel_state``.
- **Packed-vs-pytree parity**: both observation paths produce the same
  per-leaf verdicts for the same poisoned tree.
"""
import functools
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import telemetry
from apex_tpu.telemetry import numerics
from apex_tpu.multi_tensor_apply.packing import ROW, PackSpec

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _params():
    return {
        "embed": jnp.ones((4, 8)),
        "w1": jnp.ones((2 * ROW,)),       # spans exactly 2 rows packed
        "blk": {"w2": jnp.ones((3, 3))},
    }


def _grads(poison=None, value=jnp.nan):
    g = jax.tree_util.tree_map(jnp.ones_like, _params())
    if poison == "w1":
        g["w1"] = g["w1"].at[ROW + 3].set(value)  # second row of the leaf
    elif poison == "embed":
        g["embed"] = g["embed"].at[1, 2].set(value)
    elif poison == "w2":
        g["blk"]["w2"] = g["blk"]["w2"].at[0, 0].set(value)
    return g


# ---------------------------------------------------------------------------
# overflow provenance
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("leaf,name", [
    ("w1", "['w1']"), ("embed", "['embed']"), ("w2", "['blk']['w2']"),
])
def test_pytree_provenance_names_exactly_the_poisoned_leaf(leaf, name):
    mon = numerics.NumericsMonitor(_params())
    ring = telemetry.RingBufferRecorder()

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(ns, grads):
        ns = mon.observe(ns, grads=grads)
        return mon.drain(ns, ring)

    ns = mon.init()
    for _ in range(3):  # healthy steps: no events at all
        ns = step(ns, _grads())
    jax.effects_barrier()
    assert len(ring.records) == 0

    ns = step(ns, _grads(poison=leaf))
    jax.effects_barrier()
    (ev,) = ring.records
    assert ev["event"] == "anomaly" and ev["kind"] == "nonfinite_grads"
    assert [l["name"] for l in ev["leaves"]] == [name]
    assert ev["leaves"][0]["nonfinite"] == 1.0
    assert ev["step"] == 4 and ev["first_bad_step"] == 4
    # back to healthy: no further events
    ring.records.clear()
    ns = step(ns, _grads())
    jax.effects_barrier()
    assert len(ring.records) == 0


@pytest.mark.parametrize("value", [jnp.nan, jnp.inf, -jnp.inf])
def test_packed_provenance_names_exactly_the_poisoned_leaf(value):
    spec = PackSpec(_params(), chunk_size=2 * ROW)
    mon = numerics.NumericsMonitor(spec=spec)
    ring = telemetry.RingBufferRecorder()

    @jax.jit
    def step(ns, flat):
        ns = mon.observe(ns, flat_grads=flat)
        return mon.drain(ns, ring)

    ns = step(mon.init(), spec.pack(_grads(), jnp.float32))
    jax.effects_barrier()
    assert len(ring.records) == 0

    ns = step(ns, spec.pack(_grads(poison="w1", value=value), jnp.float32))
    jax.effects_barrier()
    (ev,) = ring.records
    assert [l["name"] for l in ev["leaves"]] == ["['w1']"]
    assert ev["leaves"][0]["nonfinite"] == 1.0


def test_packed_vs_pytree_provenance_parity():
    """Same poisoned tree through both observation paths: identical
    per-leaf non-finite verdicts and counts."""
    spec = PackSpec(_params())
    mon_tree = numerics.NumericsMonitor(_params())
    mon_flat = numerics.NumericsMonitor(spec=spec)
    assert mon_tree.names == mon_flat.names
    for poison in (None, "w1", "embed", "w2"):
        g = _grads(poison=poison)
        ns_t = mon_tree.observe(mon_tree.init(), grads=g)
        ns_f = mon_flat.observe(
            mon_flat.init(), flat_grads=spec.pack(g, jnp.float32))
        np.testing.assert_array_equal(
            np.asarray(ns_t.grad_nonfinite), np.asarray(ns_f.grad_nonfinite))
        assert bool(ns_t.overflow) == bool(ns_f.overflow) == (
            poison is not None)
        # norms agree too (one poisoned leaf -> that segment nan/inf)
        np.testing.assert_allclose(
            np.asarray(ns_t.grad_sq), np.asarray(ns_f.grad_sq), rtol=1e-5)


def test_scaler_unscale_provenance_is_free_and_exact():
    """The scaler path: per-leaf flags reused from the unscale sweep —
    overflow event names the leaf, found_inf still trips the scaler."""
    from apex_tpu.amp.scaler import LossScaler

    mon = numerics.NumericsMonitor(_params())
    ring = telemetry.RingBufferRecorder()
    sc = LossScaler("dynamic", init_scale=4.0)

    @jax.jit
    def step(sstate, ns, grads):
        grads, sstate, ns = sc.unscale(
            sstate, grads, numerics=(mon, ns))
        sstate, ns = sc.update_scale(sstate, numerics=ns)
        ns = mon.drain(ns, ring)
        return sstate, ns

    sstate, ns = sc.init_state(), mon.init()
    sstate, ns = step(sstate, ns, _grads())
    jax.effects_barrier()
    assert len(ring.records) == 0
    assert float(sstate.loss_scale) == pytest.approx(4.0)

    sstate, ns = step(sstate, ns, _grads(poison="w2"))
    jax.effects_barrier()
    (ev,) = ring.records
    assert [l["name"] for l in ev["leaves"]] == ["['blk']['w2']"]
    # the scaler consumed the overflow: backed off 4 -> 2
    assert float(sstate.loss_scale) == pytest.approx(2.0)
    assert ev["loss_scale"] == pytest.approx(2.0)


def test_model_parallel_grad_scaler_accepts_numerics():
    """The TP/PP GradScaler must support the same numerics= provenance
    wiring as the base scaler (provenance stays per-rank; the sink's
    rank-0 gating decides who writes)."""
    from apex_tpu.transformer.amp import GradScaler

    mon = numerics.NumericsMonitor(_params())
    ring = telemetry.RingBufferRecorder()
    sc = GradScaler("dynamic", init_scale=4.0)

    @jax.jit
    def step(sstate, ns, grads):
        grads, sstate, ns = sc.unscale(sstate, grads, numerics=(mon, ns))
        sstate, ns = sc.update_scale(sstate, numerics=ns)
        return sstate, mon.drain(ns, ring)

    sstate, ns = sc.init_state(), mon.init()
    sstate, ns = step(sstate, ns, _grads(poison="embed"))
    jax.effects_barrier()
    (ev,) = ring.records
    assert [l["name"] for l in ev["leaves"]] == ["['embed']"]
    assert float(sstate.loss_scale) == pytest.approx(2.0)


def test_scaler_update_scale_returns_all_requested_states():
    from apex_tpu.amp.scaler import LossScaler

    sc = LossScaler("dynamic", init_scale=4.0)
    st = sc.init_state()._replace(found_inf=jnp.asarray(True))
    m = telemetry.init_metrics()
    ns = numerics.NumericsMonitor(_params()).init()
    st2, m2, ns2 = sc.update_scale(st, metrics=m, numerics=ns)
    assert int(m2.overflow_skips) == 1
    assert bool(ns2.overflow)
    assert float(ns2.loss_scale) == pytest.approx(2.0)
    assert float(ns2.prev_loss_scale) == pytest.approx(4.0)
    st3, ns3 = sc.update_scale(st, numerics=ns)
    assert isinstance(st3, type(st)) and bool(ns3.overflow)


# ---------------------------------------------------------------------------
# anomaly rules
# ---------------------------------------------------------------------------

def test_grad_spike_vs_ewma_window():
    mon = numerics.NumericsMonitor(
        _params(), spike_warmup=3, spike_factor=5.0)
    ring = telemetry.RingBufferRecorder()

    @jax.jit
    def step(ns, grads):
        ns = mon.observe(ns, grads=grads)
        return mon.drain(ns, ring)

    ns = mon.init()
    for _ in range(5):
        ns = step(ns, _grads())
    jax.effects_barrier()
    assert len(ring.records) == 0  # steady norms: no spike
    big = jax.tree_util.tree_map(lambda g: g * 100.0, _grads())
    ns = step(ns, big)
    jax.effects_barrier()
    (ev,) = ring.records
    assert ev["kind"] == "grad_spike"
    assert ev["ratio"] == pytest.approx(100.0, rel=0.05)
    assert ev["grad_norm"] > ev["ewma_norm"]


def test_spike_needs_warmup():
    mon = numerics.NumericsMonitor(
        _params(), spike_warmup=10, spike_factor=5.0)
    ring = telemetry.RingBufferRecorder()
    ns = mon.init()
    ns = mon.observe(ns, grads=_grads())
    ns = mon.observe(
        ns, grads=jax.tree_util.tree_map(lambda g: g * 100.0, _grads()))
    ns = mon.drain(ns, ring)
    jax.effects_barrier()
    assert len(ring.records) == 0  # inside warmup: spike suppressed


def test_scale_collapse_edge_triggered():
    from apex_tpu.amp.scaler import LossScaler

    mon = numerics.NumericsMonitor(_params(), scale_floor=2.0)
    ring = telemetry.RingBufferRecorder()
    sc = LossScaler("dynamic", init_scale=4.0)

    @jax.jit
    def overflow_step(sstate, ns):
        sstate = sstate._replace(found_inf=jnp.asarray(True))
        sstate, ns = sc.update_scale(sstate, numerics=ns)
        ns = mon.drain(ns, ring)
        return sstate, ns

    sstate, ns = sc.init_state(), mon.init()
    sstate, ns = overflow_step(sstate, ns)  # 4 -> 2: above floor
    sstate, ns = overflow_step(sstate, ns)  # 2 -> 1: CROSSES the floor
    sstate, ns = overflow_step(sstate, ns)  # 1 -> 0.5: already below
    jax.effects_barrier()
    collapses = [r for r in ring.records if r["kind"] == "scale_collapse"]
    assert len(collapses) == 1  # emitted on the crossing only
    assert collapses[0]["loss_scale"] == pytest.approx(1.0)
    assert collapses[0]["prev_loss_scale"] == pytest.approx(2.0)
    assert collapses[0]["floor"] == pytest.approx(2.0)


def test_health_every_periodic_table():
    mon = numerics.NumericsMonitor(_params())
    ring = telemetry.RingBufferRecorder()

    @jax.jit
    def step(ns, grads):
        ns = mon.observe(ns, grads=grads)
        return mon.drain(ns, ring, health_every=2)

    ns = mon.init()
    for _ in range(5):
        ns = step(ns, _grads())
    jax.effects_barrier()
    health = [r for r in ring.records if r["event"] == "numerics_health"]
    assert [r["step"] for r in health] == [2, 4]
    leaves = health[-1]["leaves"]
    assert set(leaves) == set(mon.names)
    assert leaves["['w1']"]["norm"] == pytest.approx(
        float(np.sqrt(2 * ROW)), rel=1e-5)
    assert leaves["['w1']"]["nonfinite"] == 0.0


def test_numerics_state_donatable():
    mon = numerics.NumericsMonitor(_params())
    step = jax.jit(lambda ns, g: mon.observe(ns, grads=g),
                   donate_argnums=(0,))
    ns = step(mon.init(), _grads())
    ns = step(ns, _grads())
    assert int(ns.step) == 2


def test_observe_validates_sources():
    mon = numerics.NumericsMonitor(_params())
    ns = mon.init()
    with pytest.raises(ValueError, match="exactly one"):
        mon.observe(ns)
    with pytest.raises(ValueError, match="exactly one"):
        mon.observe(ns, grads=_grads(),
                    flat_grads=jnp.zeros((ROW,)))
    with pytest.raises(ValueError, match="leaves"):
        mon.observe(ns, grads={"just_one": jnp.ones((3,))})
    with pytest.raises(ValueError, match="PackSpec"):
        mon.observe(ns, flat_grads=jnp.zeros((ROW,)))
    with pytest.raises(ValueError, match="exactly one of"):
        numerics.NumericsMonitor(None)


# ---------------------------------------------------------------------------
# rank-0 gating through the recorder sinks
# ---------------------------------------------------------------------------

def test_anomaly_events_rank_gated_under_parallel_state(tmp_path):
    from apex_tpu.transformer import parallel_state

    if len(jax.devices()) < 4:
        pytest.skip("needs the 4+ virtual-device harness")
    parallel_state.initialize_model_parallel(
        1, 4, devices=jax.devices()[:4])
    try:
        # this process owns the first mesh device -> it IS the logging
        # process; an explicit other-rank gate must drop
        assert telemetry.is_logging_process() is True
        mon = numerics.NumericsMonitor(_params())
        logged = tmp_path / "rank0.jsonl"
        dropped = tmp_path / "rank3.jsonl"
        rec0 = telemetry.JsonlRecorder(logged)
        rec3 = telemetry.JsonlRecorder(dropped, log_rank=3)
        sink = telemetry.MultiRecorder(rec0, rec3)

        @jax.jit
        def step(ns, grads):
            ns = mon.observe(ns, grads=grads)
            return mon.drain(ns, sink)

        step(mon.init(), _grads(poison="w1"))
        jax.effects_barrier()
        rec0.close()
        rec3.close()
        (ev,) = telemetry.read_jsonl(logged)
        assert ev["kind"] == "nonfinite_grads"
        assert not dropped.exists()  # non-logging rank dropped at sink
    finally:
        parallel_state.destroy_model_parallel()


# ---------------------------------------------------------------------------
# activation watch
# ---------------------------------------------------------------------------

def test_tap_identity_and_watch_emission():
    x = jnp.arange(8.0)
    assert numerics.tap("t", x) is x  # no watch: literally identity
    ring = telemetry.RingBufferRecorder()
    with numerics.activation_watch(ring, tag="unit"):
        assert numerics.watching()
        y = jax.jit(lambda v: numerics.tap("t/x", v, layer=3) * 2.0)(x)
        jax.effects_barrier()
    assert not numerics.watching()
    np.testing.assert_allclose(np.asarray(y), np.asarray(x) * 2.0)
    (r,) = ring.records
    assert r["event"] == "activation" and r["name"] == "t/x"
    assert r["layer"] == 3 and r["tag"] == "unit"
    assert r["maxabs"] == pytest.approx(7.0)
    assert r["norm"] == pytest.approx(float(np.linalg.norm(np.arange(8.0))))


def test_tap_only_nonfinite_gates_healthy_activations():
    ring = telemetry.RingBufferRecorder()
    with numerics.activation_watch(ring, only_nonfinite=True):
        f = jax.jit(lambda v: numerics.tap("t", v))
        f(jnp.ones((4,)))
        f(jnp.array([1.0, jnp.nan, 1.0, 1.0]))
        jax.effects_barrier()
    (r,) = ring.records  # only the poisoned call emitted
    assert r["nonfinite"] == 1.0


def test_transformer_layer_taps_report_per_layer():
    from apex_tpu.transformer.testing import GPTConfig, init_gpt_params
    from apex_tpu.transformer.testing.standalone_transformer_lm import (
        gpt_forward,
    )

    cfg = GPTConfig(num_layers=2, hidden_size=64, num_attention_heads=4,
                    vocab_size=128, max_position_embeddings=32,
                    hidden_dropout=0.0, attention_dropout=0.0)
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 128)

    bare = jax.jit(lambda p: gpt_forward(cfg, p, tokens)[0])(params)
    ring = telemetry.RingBufferRecorder()
    with numerics.activation_watch(ring):
        watched = jax.jit(lambda p: gpt_forward(cfg, p, tokens)[0])(params)
        jax.effects_barrier()
    np.testing.assert_allclose(np.asarray(bare), np.asarray(watched))
    recs = list(ring.records)
    # 2 taps (attn, mlp) x 2 layers, layer numbers from the scan
    assert sorted((r["name"].rsplit("/", 1)[1], r["layer"])
                  for r in recs) == [
        ("attn", 1), ("attn", 2), ("mlp", 1), ("mlp", 2)]
    assert all(r["nonfinite"] == 0.0 for r in recs)


def test_transformer_layer_named_scope_reaches_lowered_hlo():
    from apex_tpu.transformer.testing import GPTConfig, init_gpt_params
    from apex_tpu.transformer.testing.standalone_transformer_lm import (
        gpt_forward,
    )

    cfg = GPTConfig(num_layers=1, hidden_size=32, num_attention_heads=2,
                    vocab_size=64, max_position_embeddings=16,
                    hidden_dropout=0.0, attention_dropout=0.0)
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.zeros((1, 16), jnp.int32)
    text = jax.jit(
        lambda p: gpt_forward(cfg, p, tokens)[0]
    ).lower(params).compile().as_text()
    assert "apex_tpu.transformer_layer" in text


def test_packed_adam_grad_tap_names_guilty_leaves():
    from apex_tpu.optimizers import FusedAdam

    params = {"a": jnp.ones((ROW,), jnp.float32),
              "b": jnp.ones((ROW,), jnp.float32)}
    opt = FusedAdam(lr=1e-3, packed=True)
    state = opt.init(params)
    grads = {"a": jnp.ones((ROW,)),
             "b": jnp.ones((ROW,)).at[5].set(jnp.nan)}
    ring = telemetry.RingBufferRecorder()
    with numerics.activation_watch(ring):
        step = jax.jit(lambda g, s, p: opt.step(g, s, p))
        step(grads, state, params)
        jax.effects_barrier()
    tap_recs = [r for r in ring.records
                if r["name"] == "apex_tpu.packed_adam/grads"]
    assert len(tap_recs) == 1
    assert tap_recs[0]["nonfinite"] == 1.0
    assert [l["name"] for l in tap_recs[0]["leaves"]] == ["['b']"]


# ---------------------------------------------------------------------------
# kernel-layer plumbing
# ---------------------------------------------------------------------------

def test_packed_row_stats_kernel_matches_fallback():
    from apex_tpu.ops.packed_optimizer import packed_row_stats

    rng = np.random.default_rng(0)
    x = rng.normal(size=(4 * ROW,)).astype(np.float32)
    x[ROW + 1] = np.nan
    x[3 * ROW + 7] = np.inf
    fb = packed_row_stats(jnp.asarray(x), inv_scale=0.5, use_kernel=False)
    kr = packed_row_stats(jnp.asarray(x), inv_scale=0.5, interpret=True)
    for a, b in zip(fb, kr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
    # exact non-finite counts land in the right rows
    np.testing.assert_array_equal(
        np.asarray(fb[2]), np.array([0.0, 1.0, 0.0, 1.0], np.float32))


def test_multi_tensor_scale_flat_per_row_flags():
    from apex_tpu.ops.packed_optimizer import multi_tensor_scale_flat

    x = jnp.ones((3 * ROW,)).at[2 * ROW + 4].set(jnp.inf)
    for kw in ({"use_kernel": False}, {"interpret": True}):
        out, found, rows = multi_tensor_scale_flat(
            x, 1.0, per_row_flags=True, **kw)
        assert bool(found)
        np.testing.assert_array_equal(
            np.asarray(rows), np.array([False, False, True]))
        # 2-ary contract unchanged
        out2, found2 = multi_tensor_scale_flat(x, 1.0, **kw)
        assert bool(found2)
        np.testing.assert_allclose(np.asarray(out), np.asarray(out2))


def test_multi_tensor_scale_per_tensor_flags():
    from apex_tpu.ops.multi_tensor import multi_tensor_scale

    tree = {"a": jnp.ones((4,)), "b": jnp.array([1.0, jnp.nan])}
    out, found, flags = multi_tensor_scale(tree, 2.0, per_tensor=True)
    assert bool(found)
    np.testing.assert_array_equal(np.asarray(flags),
                                  np.array([False, True]))
    out2, found2 = multi_tensor_scale(tree, 2.0)
    assert bool(found2)


def test_pack_spec_leaf_names_flatten_order():
    spec = PackSpec(_params())
    # dict flattening is key-sorted; names must match tree_leaves order
    assert spec.leaf_names() == ("['blk']['w2']", "['embed']", "['w1']")
    assert spec.leaf_names() == numerics.leaf_names(_params())


# ---------------------------------------------------------------------------
# legacy host-driven scaler provenance
# ---------------------------------------------------------------------------

def test_legacy_dynamic_scaler_provenance_and_sink():
    from apex_tpu.fp16_utils import DynamicLossScaler, nonfinite_leaves

    g = _grads(poison="embed")
    assert nonfinite_leaves(g) == ["['embed']"]
    assert nonfinite_leaves(_grads()) == []

    ring = telemetry.RingBufferRecorder()
    sc = DynamicLossScaler(init_scale=2.0 ** 8, sink=ring)
    assert sc.has_overflow(g) is True
    assert sc.last_overflow_leaves == ["['embed']"]
    sc.update_scale(True)
    assert sc.cur_scale == pytest.approx(2.0 ** 7)
    (ev,) = ring.records
    assert ev["kind"] == "nonfinite_grads"
    assert ev["leaves"] == [{"name": "['embed']"}]
    # clean path emits nothing
    assert sc.has_overflow(_grads()) is False
    sc.update_scale(False)
    assert len(ring.records) == 1


# ---------------------------------------------------------------------------
# health report tool
# ---------------------------------------------------------------------------

def test_health_report_aggregation_and_render(tmp_path):
    from tools.health_report import health_from_records, render_report

    records = [
        {"event": "metrics", "step": 10, "loss": 2.5, "loss_scale": 1024.0,
         "overflow_skips": 1, "scale_growths": 0},
        {"event": "anomaly", "kind": "nonfinite_grads", "step": 7,
         "loss_scale": 2048.0, "first_bad_step": 7,
         "leaves": [{"name": "['w1']", "nonfinite": 3.0,
                     "maxabs": "inf", "norm": "nan"}]},
        {"event": "anomaly", "kind": "grad_spike", "step": 9,
         "grad_norm": 90.0, "ewma_norm": 3.0, "ratio": 30.0},
        {"event": "numerics_health", "step": 8,
         "leaves": {"['w1']": {"norm": 1.5, "maxabs": 0.5,
                               "nonfinite": 0.0},
                    "['embed']": {"norm": 2.0, "maxabs": 1.0,
                                  "nonfinite": 0.0}}},
        {"event": "activation", "name": "apex_tpu.transformer_layer/mlp",
         "layer": 2, "maxabs": 4.0, "nonfinite": 1.0, "norm": 9.0,
         "step": 7},
    ]
    h = health_from_records(records)
    assert h["first_bad_step"] == 7
    assert h["anomaly_counts"] == {"nonfinite_grads": 1, "grad_spike": 1}
    assert h["leaves"]["['w1']"]["first_bad_step"] == 7
    assert h["leaves"]["['w1']"]["nonfinite_events"] == 1
    assert h["leaves"]["['w1']"]["last_norm"] == pytest.approx(1.5)
    assert h["leaves"]["['embed']"]["first_bad_step"] is None
    tap = h["taps"]["apex_tpu.transformer_layer/mlp@layer2"]
    assert tap["nonfinite_events"] == 1 and tap["first_bad_step"] == 7
    assert h["run"]["loss_scale"] == pytest.approx(1024.0)

    text = render_report(h)
    assert "first bad step: 7" in text
    assert "['w1']" in text and "@layer2" in text


def test_health_report_cli_roundtrip(tmp_path):
    from tools.health_report import main

    path = tmp_path / "run.jsonl"
    with telemetry.JsonlRecorder(path) as rec:
        rec.record({"event": "anomaly", "kind": "nonfinite_grads",
                    "step": 3, "loss_scale": 8.0,
                    "leaves": [{"name": "['w1']", "nonfinite": 1.0,
                                "maxabs": float("nan"),
                                "norm": float("nan")}]})
    assert main([str(path)]) == 1          # non-finite run: CI-gateable
    healthy = tmp_path / "ok.jsonl"
    with telemetry.JsonlRecorder(healthy) as rec:
        rec.record({"event": "metrics", "step": 5, "loss": 1.0})
    assert main([str(healthy)]) == 0
