"""Fused transformer-block tail kernels (``apex_tpu/ops/fused_block.py``).

The ISSUE-9 parity contract:

- forward/backward vs the unfused reference — f32 EXACT on the XLA
  fallback path (the fallback IS the reference math, backward via
  ``jax.vjp`` of it), bf16/interpret-kernel tolerance elsewhere;
- dropout determinism: a fixed seed reproduces the identical keep mask
  across kernel (interpret) and fallback, forward and backward;
- grad-of-remat equivalence: ``selective_elementwise`` vs ``full`` give
  the same loss and the same grads, with fewer saved residuals than the
  no-remat trace (measured via jaxpr);
- analysis rule 6: an unscoped kernel invocation trips
  ``unscoped_kernel``; the public (scoped) entry points do not.
"""
import dataclasses
import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from apex_tpu.analysis import assert_step_clean, audit_step  # noqa: E402
from apex_tpu.ops import layer_norm as ln_mod  # noqa: E402
from apex_tpu.ops.fused_block import (  # noqa: E402
    bias_dropout_residual,
    bias_gelu,
    dropout_mask_reference,
    residual_add_layer_norm,
)
from apex_tpu.transformer.testing import (  # noqa: E402
    GPTConfig,
    gpt_loss,
    init_gpt_params,
)
from apex_tpu.transformer.testing.standalone_transformer_lm import (  # noqa: E402
    _selective_elementwise_policy,
    transformer_layer,
)


def _data(h=128, rows=(4, 8), dtype=jnp.float32, key=0):
    ks = jax.random.split(jax.random.PRNGKey(key), 3)
    x = jax.random.normal(ks[0], rows + (h,), dtype)
    b = (jax.random.normal(ks[1], (h,)) * 0.1).astype(dtype)
    r = jax.random.normal(ks[2], rows + (h,), dtype)
    return x, b, r


# ---------------------------------------------------------------------------
# bias_gelu
# ---------------------------------------------------------------------------

def test_bias_gelu_fallback_bitwise():
    x, b, _ = _data()
    y = bias_gelu(x, b)
    ref = jax.nn.gelu(x + b, approximate=True)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(ref))


def test_bias_gelu_fallback_grads_bitwise():
    x, b, _ = _data(key=1)
    gx, gb = jax.grad(lambda x, b: (bias_gelu(x, b) ** 2).sum(),
                      argnums=(0, 1))(x, b)
    rx, rb = jax.grad(
        lambda x, b: (jax.nn.gelu(x + b, approximate=True) ** 2).sum(),
        argnums=(0, 1))(x, b)
    np.testing.assert_array_equal(np.asarray(gx), np.asarray(rx))
    np.testing.assert_array_equal(np.asarray(gb), np.asarray(rb))


@pytest.mark.parametrize("dtype,tol", [(jnp.float32, 1e-6),
                                       (jnp.bfloat16, 2e-2)])
def test_bias_gelu_kernel_parity(dtype, tol):
    x, b, _ = _data(dtype=dtype, key=2)
    y = bias_gelu(x, b, interpret=True)
    ref = jax.nn.gelu(x.astype(jnp.float32) + b.astype(jnp.float32),
                      approximate=True)
    assert jnp.abs(y.astype(jnp.float32) - ref).max() < tol


def test_bias_gelu_kernel_grads_close():
    x, b, _ = _data(key=3)
    gk = jax.grad(lambda x, b: (bias_gelu(x, b, interpret=True) ** 2).sum(),
                  argnums=(0, 1))(x, b)
    gr = jax.grad(
        lambda x, b: (jax.nn.gelu(x + b, approximate=True) ** 2).sum(),
        argnums=(0, 1))(x, b)
    for a, c in zip(gk, gr):
        assert jnp.abs(a - c).max() < 1e-4


def test_bias_gelu_rejects_bad_bias_shape():
    x, b, _ = _data()
    with pytest.raises(ValueError, match="bias must be"):
        bias_gelu(x, b[:64])


# ---------------------------------------------------------------------------
# bias_dropout_residual
# ---------------------------------------------------------------------------

def test_bdr_p0_fallback_exact():
    x, b, r = _data(key=4)
    out = bias_dropout_residual(x, b, r)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(r + (x + b)))


def test_bdr_p0_kernel_matches_fallback():
    x, b, r = _data(key=5)
    out = bias_dropout_residual(x, b, r)
    outk = bias_dropout_residual(x, b, r, interpret=True)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(outk))


def test_bdr_dropout_deterministic_kernel_vs_fallback():
    x, b, r = _data(key=6)
    args = dict(dropout_p=0.3, seed=42)
    out = bias_dropout_residual(x, b, r, **args)
    outk = bias_dropout_residual(x, b, r, interpret=True, **args)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(outk))
    # a different seed is a different mask
    out2 = bias_dropout_residual(x, b, r, dropout_p=0.3, seed=43)
    assert not np.array_equal(np.asarray(out), np.asarray(out2))


def test_bdr_dropout_matches_reference_mask():
    x, b, r = _data(key=7)
    p, seed = 0.25, 1234
    out = bias_dropout_residual(x, b, r, dropout_p=p, seed=seed)
    keep = dropout_mask_reference(seed, 32, 128, p).reshape(x.shape)
    ref = r + keep * (x + b) * (1.0 / (1.0 - p))
    assert jnp.abs(out - ref).max() < 1e-6
    # drop fraction is ~p
    assert abs((1.0 - keep.mean()) - p) < 0.03


def test_bdr_dropout_backward_regenerates_mask():
    x, b, r = _data(key=8)
    p, seed = 0.4, 99
    for interp in (False, True):
        gx = jax.grad(lambda x: bias_dropout_residual(
            x, b, r, dropout_p=p, seed=seed, interpret=interp).sum())(x)
        keep = dropout_mask_reference(seed, 32, 128, p).reshape(x.shape)
        np.testing.assert_allclose(
            np.asarray(gx), np.asarray(keep / (1.0 - p)), atol=1e-6)
        gr = jax.grad(lambda r: bias_dropout_residual(
            x, b, r, dropout_p=p, seed=seed, interpret=interp).sum())(r)
        np.testing.assert_allclose(np.asarray(gr), 1.0, atol=1e-6)


def test_bdr_requires_seed_when_dropout_on():
    x, b, r = _data()
    with pytest.raises(ValueError, match="seed"):
        bias_dropout_residual(x, b, r, dropout_p=0.1)


# ---------------------------------------------------------------------------
# residual_add_layer_norm
# ---------------------------------------------------------------------------

def _raln_reference(x, b, r, w, lb, eps=1e-5):
    """The unfused chain the fused op replaces (p=0): bias add + residual
    add + the repo's own fused_layer_norm on the rounded sum."""
    s = (r + (x + b)).astype(r.dtype)
    y = ln_mod.layer_norm(
        s.astype(jnp.float32), w.astype(jnp.float32),
        lb.astype(jnp.float32), eps=eps).astype(r.dtype)
    return s, y


@pytest.mark.parametrize("interp", [False, True])
def test_raln_matches_unfused_chain(interp):
    x, b, r = _data(key=9)
    w = jnp.ones((128,)) * 1.1
    lb = jnp.full((128,), 0.2)
    s, y = residual_add_layer_norm(x, b, r, w, lb, interpret=interp)
    s_ref, y_ref = _raln_reference(x, b, r, w, lb)
    np.testing.assert_array_equal(np.asarray(s), np.asarray(s_ref))
    tol = 0.0 if not interp else 1e-6
    assert jnp.abs(y - y_ref).max() <= tol


@pytest.mark.parametrize("interp", [False, True])
def test_raln_grads_match_unfused_chain(interp):
    x, b, r = _data(key=10)
    w = jnp.ones((128,)) * 0.9
    lb = jnp.zeros((128,))

    def loss_fused(x, b, r, w, lb):
        s, y = residual_add_layer_norm(x, b, r, w, lb, interpret=interp)
        return ((s * y) ** 2).sum()

    def loss_ref(x, b, r, w, lb):
        s, y = _raln_reference(x, b, r, w, lb)
        return ((s * y) ** 2).sum()

    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3, 4))(x, b, r, w, lb)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4))(x, b, r, w, lb)
    for a, c in zip(gf, gr):
        scale = max(1.0, float(jnp.abs(c).max()))
        assert jnp.abs(a - c).max() / scale < 2e-5


def test_raln_bf16_kernel_close_to_fallback():
    x, b, r = _data(dtype=jnp.bfloat16, key=11)
    w = jnp.ones((128,))
    lb = jnp.zeros((128,))
    s, y = residual_add_layer_norm(x, b.astype(jnp.float32), r, w, lb)
    sk, yk = residual_add_layer_norm(x, b.astype(jnp.float32), r, w, lb,
                                     interpret=True)
    assert jnp.abs(s.astype(jnp.float32) - sk.astype(jnp.float32)).max() < 2e-2
    assert jnp.abs(y.astype(jnp.float32) - yk.astype(jnp.float32)).max() < 2e-2


def test_raln_dropout_deterministic():
    x, b, r = _data(key=12)
    w = jnp.ones((128,))
    lb = jnp.zeros((128,))
    kw = dict(dropout_p=0.2, seed=7)
    s, y = residual_add_layer_norm(x, b, r, w, lb, **kw)
    sk, yk = residual_add_layer_norm(x, b, r, w, lb, interpret=True, **kw)
    np.testing.assert_array_equal(np.asarray(s), np.asarray(sk))
    assert jnp.abs(y - yk).max() < 1e-6


# ---------------------------------------------------------------------------
# model-level parity (GPTConfig.fused_block)
# ---------------------------------------------------------------------------

_CFG = GPTConfig(num_layers=2, hidden_size=64, num_attention_heads=4,
                 vocab_size=128, max_position_embeddings=32,
                 hidden_dropout=0.0, attention_dropout=0.0)


def _tok(key=1, b=2, s=32):
    tokens = jax.random.randint(jax.random.PRNGKey(key), (b, s), 0,
                                _CFG.vocab_size)
    return tokens, jnp.roll(tokens, -1, axis=1)


def test_model_fused_matches_unfused_f32():
    params = init_gpt_params(_CFG, jax.random.PRNGKey(0))
    tokens, labels = _tok()
    cfg_f = dataclasses.replace(_CFG, fused_block=True)
    l0, g0 = jax.value_and_grad(
        lambda p: gpt_loss(_CFG, p, tokens, labels))(params)
    l1, g1 = jax.value_and_grad(
        lambda p: gpt_loss(cfg_f, p, tokens, labels))(params)
    # forward: the fallback is the reference math — bitwise
    assert float(l0) == float(l1)
    for a, c in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        assert jnp.abs(a - c).max() < 2e-6


def test_model_fused_kernels_match_fallback():
    params = init_gpt_params(_CFG, jax.random.PRNGKey(0))
    tokens, labels = _tok()
    cfg_f = dataclasses.replace(_CFG, fused_block=True)
    cfg_i = dataclasses.replace(_CFG, fused_block=True,
                                fused_block_interpret=True)
    l1, g1 = jax.value_and_grad(
        lambda p: gpt_loss(cfg_f, p, tokens, labels))(params)
    l2, g2 = jax.value_and_grad(
        lambda p: gpt_loss(cfg_i, p, tokens, labels))(params)
    assert abs(float(l1) - float(l2)) < 1e-6
    for a, c in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2)):
        assert jnp.abs(a - c).max() < 1e-5


def test_model_fused_bf16_close():
    cfg16 = dataclasses.replace(_CFG, compute_dtype=jnp.bfloat16)
    cfg16_f = dataclasses.replace(cfg16, fused_block=True,
                                  fused_block_interpret=True)
    params = init_gpt_params(cfg16, jax.random.PRNGKey(0))
    tokens, labels = _tok()
    l0 = gpt_loss(cfg16, params, tokens, labels)
    l1 = gpt_loss(cfg16_f, params, tokens, labels)
    assert abs(float(l0) - float(l1)) / abs(float(l0)) < 2e-2


def test_model_fused_dropout_deterministic_given_key():
    cfg = dataclasses.replace(_CFG, fused_block=True,
                              fused_block_interpret=True,
                              hidden_dropout=0.1)
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    tokens, labels = _tok()
    key = jax.random.PRNGKey(5)
    l1 = gpt_loss(cfg, params, tokens, labels, dropout_key=key,
                  deterministic=False)
    l2 = gpt_loss(cfg, params, tokens, labels, dropout_key=key,
                  deterministic=False)
    assert float(l1) == float(l2)
    l3 = gpt_loss(cfg, params, tokens, labels,
                  dropout_key=jax.random.PRNGKey(6), deterministic=False)
    assert float(l1) != float(l3)


# ---------------------------------------------------------------------------
# selective_elementwise remat
# ---------------------------------------------------------------------------

def test_grad_of_remat_equivalence():
    """selective_elementwise replays less but must compute the SAME loss
    and grads as full-layer remat (and as no remat)."""
    cfg_i = dataclasses.replace(_CFG, fused_block=True,
                                fused_block_interpret=True)
    params = init_gpt_params(cfg_i, jax.random.PRNGKey(0))
    tokens, labels = _tok()
    results = {}
    for rg in (None, "full", "selective_elementwise"):
        cfg = dataclasses.replace(cfg_i, recompute_granularity=rg)
        l, g = jax.value_and_grad(
            lambda p, cfg=cfg: gpt_loss(cfg, p, tokens, labels))(params)
        results[rg] = (float(l), g)
    for rg in ("full", "selective_elementwise"):
        assert results[rg][0] == results[None][0]
        for a, c in zip(jax.tree_util.tree_leaves(results[rg][1]),
                        jax.tree_util.tree_leaves(results[None][1])):
            assert jnp.abs(a - c).max() < 1e-7


def test_selective_elementwise_saves_fewer_residuals():
    """Measured via jaxpr (jax's own saved-residuals accounting of the
    checkpointed layer): the policy saves strictly less than running
    without remat, strictly more than full-layer remat (it keeps the
    matmul/attention/fused-tail outputs), and among the kept residuals
    are the fused-block kernel outputs."""
    saved_residuals = pytest.importorskip(
        "jax._src.ad_checkpoint").saved_residuals

    cfg = dataclasses.replace(_CFG, fused_block=True,
                              fused_block_interpret=True)
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    lp = jax.tree_util.tree_map(lambda x: x[0], params["layers"])
    h = jax.random.normal(jax.random.PRNGKey(3), (32, 2, 64))

    def layer(lp, h):
        return transformer_layer(cfg, lp, h, None, None, None, True)

    def res_bytes(fn):
        res = saved_residuals(fn, lp, h)
        return sum(int(np.prod(aval.shape)) * aval.dtype.itemsize
                   for aval, _ in res if hasattr(aval, "shape"))

    b_none = res_bytes(layer)
    b_full = res_bytes(jax.checkpoint(layer))
    b_sel = res_bytes(
        jax.checkpoint(layer, policy=_selective_elementwise_policy))
    assert b_full < b_sel < b_none


# ---------------------------------------------------------------------------
# analysis rule 6 (scopes) + headline-step cleanliness
# ---------------------------------------------------------------------------

def test_unscoped_kernel_variant_trips_rule6():
    """Seeded red test: a variant that launches the fused-tail kernel
    body WITHOUT the apex_tpu.* named scope (the mistake the public
    entry points exist to prevent) must trip the scopes rule."""
    from jax.experimental import pallas as pl

    from apex_tpu.ops.fused_block import (
        _bias_gelu_fwd_kernel, _row_spec, _vec_spec,
    )

    x = jnp.ones((8, 128))
    b = jnp.ones((1, 128))

    def unscoped(x, b):
        y = pl.pallas_call(
            _bias_gelu_fwd_kernel,
            name="apex_tpu_bias_gelu_fwd_unscoped_variant",
            grid=(1,),
            in_specs=[_row_spec(8, 128), _vec_spec(128)],
            out_specs=_row_spec(8, 128),
            out_shape=jax.ShapeDtypeStruct((8, 128), x.dtype),
            interpret=True,
        )(x, b)
        return y.sum()

    rep = audit_step(jax.jit(unscoped), x, b, rules=("scopes",))
    assert "unscoped_kernel" in [f.code for f in rep.findings]


def test_scoped_public_entry_points_clean():
    x = jnp.ones((8, 128))
    b = jnp.ones((128,))
    r = jnp.ones((8, 128))
    w = jnp.ones((128,))

    def scoped(x, b, r, w):
        y = bias_gelu(x, b, interpret=True)
        y = bias_dropout_residual(y, b, r, interpret=True)
        s, y2 = residual_add_layer_norm(y, b, r, w, b, interpret=True)
        return (s * y2).sum()

    rep = audit_step(jax.jit(scoped), x, b, r, w, rules=("scopes",))
    assert [f.code for f in rep.findings] == []


def test_fused_headline_step_audits_clean():
    """The acceptance gate: the REAL fused_block + selective_elementwise
    headline-shaped train step (tools/static_audit.py's 5th self-audit
    target) passes assert_step_clean — donation covered, kernels scoped,
    no error-severity dtype findings."""
    from tools.static_audit import TARGETS

    fn, args, kw = TARGETS["fused_block_step"]()
    rep = assert_step_clean(fn, *args, name="fused_block_step", **kw)
    # and specifically: none of the fused kernels are unscoped, and the
    # kernels introduced no NEW double-cast (the one pre-existing
    # warning is the remat'd XLA-softmax chain, present for any
    # recompute mode since PR 4 — see docs/fused_block.md)
    assert "unscoped_kernel" not in [f.code for f in rep.findings]
    assert sum(f.code == "double_cast" for f in rep.findings) <= 1
