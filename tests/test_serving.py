"""apex_tpu.serving: paged-KV decode engine + continuous batching.

Coverage map (the ISSUE-6 acceptance surface):

- flash-decode parity vs. dense reference attention — single-query
  rows, ragged page tables, fully-masked (empty) slots, bf16 vs f32
  tolerance; XLA fallback AND the real kernel body (interpret mode);
- PagedKVSpec: chunk-aligned PackSpec layout (check_pack_spec clean),
  pack/unpack round trip, alignment validation;
- scheduler property test: random admit/evict/preempt traces never
  leak or double-free pages;
- ServingEngine.generate token-identity vs. the per-request
  dense-attention greedy decode loop across a staggered continuous-
  batching trace, including under forced preemption;
- assert_step_clean on the jitted decode step (KV cache donated, no
  ungated callbacks) with the in-jit telemetry drain ARMED;
- satellites: amp.cast_params_for_inference, telemetry.percentiles,
  tools/serving_check.py exit codes, compare_bench serving legs;
- tensor parallelism (ISSUE-16): TP=2/4 token identity vs TP=1 on the
  8-virtual-device mesh (tools/serving_check tp_identity), the 3-psum-
  per-program jaxpr pin with no pool-shaped all-gather, head-sharded
  PagedKVSpec geometry, sharding-preserving inference cast, the
  top_k<=filter-width submit guard, TP-tagged telemetry + DP x TP fleet
  summary, topology-preserving recover/rebuild/swap, and the committed
  equal-chip DP-vs-TP bench artifact.
"""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.flash_decode import (
    flash_decode,
    flash_decode_available,
    paged_decode_reference,
)
from apex_tpu.serving import (
    PageAllocator,
    PagedKVSpec,
    Request,
    Scheduler,
    SchedulerError,
    ServingEngine,
    reference_decode,
)
from apex_tpu.transformer.testing import GPTConfig, init_gpt_params


def _tiny_cfg(dtype=jnp.float32):
    return GPTConfig(
        num_layers=2, hidden_size=64, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=64,
        hidden_dropout=0.0, attention_dropout=0.0,
        params_dtype=jnp.float32, compute_dtype=dtype)


@pytest.fixture(scope="module")
def tiny_model():
    cfg = _tiny_cfg()
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    # amplified position table: greedy continuations become position-
    # sensitive instead of collapsing to a fixed point, so the identity
    # tests genuinely exercise the growing cache
    params["embedding"]["position"] = params["embedding"]["position"] * 40.0
    return cfg, params


# ---------------------------------------------------------------------------
# flash decode parity
# ---------------------------------------------------------------------------

def _decode_case(dtype, seed=0, P=8, n=4, ps=16, d=16, B=5, mp=3):
    rng = np.random.default_rng(seed)
    k_pages = jnp.asarray(rng.normal(size=(P, n, ps, d)), dtype)
    v_pages = jnp.asarray(rng.normal(size=(P, n, ps, d)), dtype)
    q = jnp.asarray(rng.normal(size=(B, n, d)), dtype)
    pt = jnp.asarray(rng.integers(1, P, size=(B, mp)), jnp.int32)
    lens = jnp.asarray([0, 5, 16, 33, 48], jnp.int32)
    return q, k_pages, v_pages, pt, lens


@pytest.mark.parametrize("mode", ["xla", "kernel"])
def test_flash_decode_matches_reference(mode):
    """Ragged lengths (mid-page tails, full pages, empty slot) against
    the dense gathered softmax."""
    q, k_pages, v_pages, pt, lens = _decode_case(jnp.float32)
    ref = np.asarray(paged_decode_reference(q, k_pages, v_pages, pt, lens))
    if mode == "xla":
        out = flash_decode(q, k_pages, v_pages, pt, lens, use_kernel=False)
        tol = 1e-6
    else:
        out = flash_decode(q, k_pages, v_pages, pt, lens, interpret=True)
        tol = 1e-5
    np.testing.assert_allclose(np.asarray(out), ref, rtol=tol, atol=tol)


def test_flash_decode_matches_dense_attention():
    """The paged path equals plain softmax attention over the tokens the
    page table stitches together (the 'single-query row' contract)."""
    q, k_pages, v_pages, pt, lens = _decode_case(jnp.float32)
    out = np.asarray(
        flash_decode(q, k_pages, v_pages, pt, lens, interpret=True))
    P, n, ps, d = k_pages.shape
    mp = pt.shape[1]
    for b in range(q.shape[0]):
        L = int(lens[b])
        if L == 0:
            np.testing.assert_array_equal(out[b], 0.0)
            continue
        kk = np.asarray(k_pages)[np.asarray(pt)[b]]  # [mp, n, ps, d]
        kk = kk.transpose(1, 0, 2, 3).reshape(n, mp * ps, d)[:, :L]
        vv = np.asarray(v_pages)[np.asarray(pt)[b]]
        vv = vv.transpose(1, 0, 2, 3).reshape(n, mp * ps, d)[:, :L]
        s = np.einsum("nd,nkd->nk", np.asarray(q)[b], kk) / np.sqrt(d)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        naive = np.einsum("nk,nkd->nd", p, vv)
        np.testing.assert_allclose(out[b], naive, rtol=1e-5, atol=1e-5)


def test_flash_decode_fully_masked_pages_inert():
    """Garbage-page entries past the length never contaminate the
    output: same result whether the tail entries point at real pages or
    at the garbage page."""
    q, k_pages, v_pages, pt, lens = _decode_case(jnp.float32)
    pt2 = np.asarray(pt).copy()
    ps = k_pages.shape[2]
    for b in range(pt2.shape[0]):
        used = -(-int(lens[b]) // ps)
        pt2[b, used:] = 0  # garbage page
    a = flash_decode(q, k_pages, v_pages, pt, lens, interpret=True)
    bb = flash_decode(q, k_pages, v_pages, jnp.asarray(pt2), lens,
                      interpret=True)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(bb))


def test_flash_decode_bf16_vs_f32_tolerance():
    """bf16 pages/queries track the f32 math within bf16-level error."""
    qf, kf, vf, pt, lens = _decode_case(jnp.float32, seed=3)
    ref = np.asarray(flash_decode(qf, kf, vf, pt, lens, use_kernel=False))
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (qf, kf, vf))
    out = flash_decode(qb, kb, vb, pt, lens, interpret=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), ref, rtol=5e-2, atol=5e-2)


def test_flash_decode_mixed_kv_dtype_no_pool_cast():
    """f32 compute over a bf16 KV pool (the halve-the-cache config):
    parity holds on both paths WITHOUT materializing a f32 copy of the
    whole pool — the jaxpr must contain no pool-shaped convert."""
    qf, kf, vf, pt, lens = _decode_case(jnp.float32, seed=5)
    ref = np.asarray(flash_decode(qf, kf, vf, pt, lens, use_kernel=False))
    kb, vb = kf.astype(jnp.bfloat16), vf.astype(jnp.bfloat16)
    for kw in ({"use_kernel": False}, {"interpret": True}):
        out = flash_decode(qf, kb, vb, pt, lens, **kw)
        assert out.dtype == jnp.float32
        np.testing.assert_allclose(
            np.asarray(out), ref, rtol=5e-2, atol=5e-2)
    pool_shape = kb.shape
    jaxpr = jax.make_jaxpr(
        lambda *a: flash_decode(*a, use_kernel=False))(qf, kb, vb, pt, lens)
    for eqn in jaxpr.jaxpr.eqns:
        if eqn.primitive.name == "convert_element_type":
            assert tuple(eqn.invars[0].aval.shape) != pool_shape, (
                "whole-pool dtype cast reintroduced")


def test_flash_decode_shape_validation():
    q, k_pages, v_pages, pt, lens = _decode_case(jnp.float32)
    with pytest.raises(ValueError, match="do not match q"):
        flash_decode(q[:, :2], k_pages, v_pages, pt, lens)
    assert flash_decode_available(16, 64)
    assert not flash_decode_available(12, 64)   # page % 8
    assert not flash_decode_available(16, 512)  # head dim


# ---------------------------------------------------------------------------
# paged KV spec / cache layout
# ---------------------------------------------------------------------------

def test_paged_kv_spec_is_chunk_aligned_packspec():
    """Every page is one chunk of the PackSpec view; the PR-4 checker
    passes (the layout gate the packed optimizers run under)."""
    from apex_tpu.analysis import check_pack_spec
    from apex_tpu.multi_tensor_apply.packing import ROW

    spec = PagedKVSpec(2, 4, 16, page_size=16, num_pages=6,
                       pages_per_seq=3, dtype=jnp.float32)
    assert spec.page_elems % ROW == 0
    assert spec.pack_spec.chunk_size == spec.page_elems
    assert check_pack_spec(spec.pack_spec) == []
    # leaf offsets are page multiples: pages start on chunk boundaries
    for off in spec.pack_spec.offsets:
        assert off % spec.page_elems == 0


def test_paged_kv_spec_rejects_misaligned_page():
    with pytest.raises(ValueError, match="ROW-aligned"):
        PagedKVSpec(1, 3, 16, page_size=8, num_pages=4, pages_per_seq=2)
    with pytest.raises(ValueError, match="garbage"):
        PagedKVSpec(1, 4, 16, page_size=16, num_pages=1, pages_per_seq=2)


def test_paged_kv_pack_unpack_roundtrip():
    spec = PagedKVSpec(2, 4, 16, page_size=16, num_pages=4,
                       pages_per_seq=2, dtype=jnp.float32)
    cache = spec.init_cache()
    rng = np.random.default_rng(0)
    cache = cache._replace(pages=jnp.asarray(
        rng.normal(size=cache.pages.shape), jnp.float32))
    flat = spec.pack(cache)
    assert flat.shape == (spec.pack_spec.total,)
    back = spec.unpack(flat)
    np.testing.assert_array_equal(np.asarray(back.pages),
                                  np.asarray(cache.pages))


def test_page_allocator_invariants():
    al = PageAllocator(6)  # pages 1..5 usable
    assert al.free_count == 5
    got = [al.alloc() for _ in range(5)]
    assert sorted(got) == [1, 2, 3, 4, 5]
    assert al.alloc() is None
    al.free(got[:2])
    with pytest.raises(ValueError, match="double-free"):
        al.free(got[:1])
    with pytest.raises(ValueError, match="garbage"):
        al.free([0])
    al.free(got[2:])
    al.check()
    assert al.free_count == 5 and al.used_count == 0


# ---------------------------------------------------------------------------
# scheduler property test
# ---------------------------------------------------------------------------

def test_scheduler_random_traces_never_leak_pages():
    """Randomized admit/advance/evict/preempt traces: page accounting
    stays exact at every boundary and drains to empty."""
    rng = np.random.default_rng(1234)
    for trial in range(8):
        spec = PagedKVSpec(
            1, 4, 16, page_size=16,
            num_pages=int(rng.integers(3, 9)), pages_per_seq=4)
        sched = Scheduler(spec, n_slots=int(rng.integers(1, 4)),
                          max_prompt_len=spec.max_seq_len)
        live = []
        for r in range(int(rng.integers(3, 10))):
            total = int(rng.integers(2, spec.max_seq_len))
            plen = int(rng.integers(1, total))
            req = Request(prompt=list(rng.integers(0, 50, size=plen)),
                          max_new_tokens=total - plen)
            if spec.pages_for(total) > spec.n_usable_pages:
                # a request the pool can never hold is refused at
                # submit (it would sink the whole trace mid-flight)
                with pytest.raises(SchedulerError,
                                   match="never be served"):
                    sched.submit(req)
                continue
            sched.submit(req)
            live.append(req)
        guard = 0
        while not sched.idle:
            guard += 1
            assert guard < 5000, "scheduler trace did not terminate"
            sched.admit()
            # validated traces never sink: ensure_capacity must always
            # succeed (preempting as needed), whatever the pool size
            sched.ensure_capacity()
            sched.check_invariants()
            served = sched.running()
            sched.advance([i for i, _ in served])
            for i, run in served:
                if not run.prefilling:  # a token was generated
                    run.req.out_tokens.append(0)
                if run.req.done:
                    sched.evict(i)
            sched.check_invariants()
        sched.check_invariants()
        assert sched.allocator.used_count == 0
        assert sched.allocator.free_count == spec.n_usable_pages


def test_scheduler_refuses_replay_overflow_at_submit():
    """A request whose preemption-replay prompt could outgrow
    max_prompt_len must be refused at submit(): admit() pops before
    validating, so a late rejection would silently drop the request."""
    spec = PagedKVSpec(1, 4, 16, page_size=16, num_pages=5,
                       pages_per_seq=4)
    sched = Scheduler(spec, n_slots=2, max_prompt_len=16)
    # prompt fits (12 <= 16) and total fits the pages (32 <= 64), but a
    # preemption after 5+ generated tokens would replay a 17+ prompt
    with pytest.raises(SchedulerError, match="replay"):
        sched.submit(Request(prompt=list(range(12)), max_new_tokens=20))
    assert not sched.waiting
    # worst replay exactly at the cap (12 + 5 - 1 = 16) is admissible
    sched.submit(Request(prompt=list(range(12)), max_new_tokens=5))
    assert len(sched.waiting) == 1


# ---------------------------------------------------------------------------
# engine: token identity under continuous batching
# ---------------------------------------------------------------------------

def test_engine_token_identical_staggered_trace(tiny_model):
    """The acceptance criterion: generate() over a staggered
    continuous-batching trace (more requests than slots, arrivals
    mid-flight, evictions freeing slots for waiting requests) emits
    token-for-token what the per-request dense-attention greedy loop
    emits."""
    cfg, params = tiny_model
    rng = np.random.default_rng(42)
    lens = (5, 9, 3, 12, 7)
    reqs = [
        Request(prompt=[int(t) for t in rng.integers(0, 128, size=L)],
                max_new_tokens=6, arrival_step=3 * i)
        for i, L in enumerate(lens)
    ]
    eng = ServingEngine(cfg, params, n_slots=2, num_pages=12,
                        max_prompt_len=16)
    out = eng.generate(reqs, max_steps=1000)
    eng.scheduler.check_invariants()
    assert eng.scheduler.allocator.used_count == 0
    for r in reqs:
        ref = reference_decode(cfg, params, r.prompt, r.max_new_tokens)
        assert out[r.rid] == ref, (
            f"request {r.rid}: engine {out[r.rid]} != reference {ref}")
    st = eng.last_stats
    assert st["completed"] == len(reqs)
    assert 0 < st["occupancy"] <= 1.0
    assert st["generated_tokens"] == sum(len(v) for v in out.values())
    # latency percentiles come from the shared reducer
    assert set(st["latency_ms"]) == {"p50", "p90", "p99"}


def test_engine_token_identical_under_preemption(tiny_model):
    """A pool too small for two full requests forces recompute-mode
    preemption (evict + requeue + prefill replay); the emitted tokens
    must not change."""
    cfg, params = tiny_model
    rng = np.random.default_rng(7)
    reqs = [
        Request(prompt=[int(t) for t in rng.integers(0, 128, size=L)],
                max_new_tokens=8, arrival_step=i)
        for i, L in enumerate((14, 11, 13, 9))
    ]
    eng = ServingEngine(cfg, params, n_slots=2, num_pages=4,
                        max_prompt_len=16)
    out = eng.generate(reqs, max_steps=2000)
    eng.scheduler.check_invariants()
    assert eng.last_stats["preemptions"] > 0, (
        "trace was sized to force preemption")
    for r in reqs:
        ref = reference_decode(cfg, params, r.prompt, r.max_new_tokens)
        assert out[r.rid] == ref


def test_engine_eos_stops_early(tiny_model):
    """EOS termination: the engine stops a request at the token the
    reference loop stops at."""
    cfg, params = tiny_model
    rng = np.random.default_rng(3)
    prompt = [int(t) for t in rng.integers(0, 128, size=6)]
    # pick the 3rd greedy token as the EOS so the cut happens mid-run
    free_run = reference_decode(cfg, params, prompt, 8)
    eos = free_run[2]
    ref = reference_decode(cfg, params, prompt, 8, eos_id=eos)
    eng = ServingEngine(cfg, params, n_slots=2, num_pages=8,
                        max_prompt_len=16)
    out = eng.generate(
        [Request(prompt=prompt, max_new_tokens=8, eos_id=eos)],
        max_steps=200)
    assert list(out.values())[0] == ref
    assert ref[-1] == eos and len(ref) == 3


def test_engine_bf16_serving_smoke(tiny_model):
    """bf16 weights + bf16 paged KV (the deployment configuration,
    weights cast through amp's inference cast): runs to completion with
    in-range tokens and bf16 cache/params."""
    cfg32, params = tiny_model
    cfg = _tiny_cfg(jnp.bfloat16)
    rng = np.random.default_rng(5)
    reqs = [Request(prompt=[int(t) for t in rng.integers(0, 128, size=7)],
                    max_new_tokens=5)]
    eng = ServingEngine(cfg, params, n_slots=2, num_pages=8,
                        max_prompt_len=16)
    assert eng.params["layers"]["qkv_w"].dtype == jnp.bfloat16
    assert eng.spec.dtype == jnp.bfloat16
    out = eng.generate(reqs, max_steps=200)
    toks = list(out.values())[0]
    assert len(toks) == 5 and all(0 <= t < 128 for t in toks)


def test_engine_decode_logits_match_training_forward(tiny_model):
    """Numerics, not just argmax: after prefilling a prompt through the
    paged path, the engine's next-token logits match the training
    forward's last-position logits."""
    from apex_tpu.transformer.testing.standalone_transformer_lm import (
        gpt_forward,
    )

    cfg, params = tiny_model
    rng = np.random.default_rng(11)
    prompt = [int(t) for t in rng.integers(0, 128, size=9)]
    eng = ServingEngine(cfg, params, n_slots=1, num_pages=8,
                        max_prompt_len=16)
    eng.submit(Request(prompt=prompt, max_new_tokens=1))
    # run the prefill steps; capture the logits-bearing emission step
    # via the engine's own step loop
    emitted = None
    for _ in range(len(prompt)):
        em = eng.run_step()
        if em[0] >= 0:
            emitted = int(em[0])
    assert emitted is not None
    ref_logits = gpt_forward(
        cfg, params, jnp.asarray([prompt], jnp.int32), deterministic=True)
    assert emitted == int(jnp.argmax(ref_logits[0, -1]))


# ---------------------------------------------------------------------------
# audit: the serving analogue of the training-step invariants
# ---------------------------------------------------------------------------

def test_decode_step_audits_clean_with_telemetry_armed(tiny_model):
    """assert_step_clean on the REAL jitted decode step: KV cache, slot
    state and MetricsState donated; the armed in-jit telemetry drain is
    cond-gated (an ungated callback would be an error finding)."""
    from apex_tpu import telemetry

    cfg, params = tiny_model
    eng = ServingEngine(cfg, params, n_slots=2, num_pages=6,
                        max_prompt_len=16, telemetry_every=4,
                        sink=telemetry.RingBufferRecorder())
    report = eng.audit()  # raises on error-severity findings
    assert report.ok


def test_decode_step_undonated_kv_is_flagged(tiny_model):
    """Red test: the same step WITHOUT donation must trip the auditor's
    undonated-state rule on the KV cache."""
    from apex_tpu.analysis import audit_step

    cfg, params = tiny_model
    eng = ServingEngine(cfg, params, n_slots=2, num_pages=6,
                        max_prompt_len=16)
    fn, args = eng.step_program()
    undonated = jax.jit(fn.__wrapped__)  # strip jit+donation
    report = audit_step(undonated, *args, name="undonated_serving_step")
    assert not report.ok
    assert "undonated_state" in set(report.codes())


def test_engine_untileable_head_dim_fails_at_construction():
    """A (page_size, head_dim) the kernel cannot tile must raise in
    __init__ when the kernel path is selected — not mid-trace at the
    first decode step — and still construct under the XLA fallback."""
    # 1 head x 8 tokens x 512 dim: ROW-aligned (spec OK) but head_dim
    # 512 > 256 exceeds the kernel's MXU tiling bound
    cfg = GPTConfig(
        num_layers=1, hidden_size=512, num_attention_heads=1,
        vocab_size=128, max_position_embeddings=32,
        hidden_dropout=0.0, attention_dropout=0.0,
        params_dtype=jnp.float32, compute_dtype=jnp.float32)
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="tile"):
        ServingEngine(cfg, params, n_slots=2, num_pages=6, page_size=8,
                      max_prompt_len=16, use_kernel=True)
    eng = ServingEngine(cfg, params, n_slots=2, num_pages=6, page_size=8,
                        max_prompt_len=16, use_kernel=False)
    assert eng.spec.head_dim == 512


def test_engine_in_jit_telemetry_counts_tokens(tiny_model):
    """The PR-2 metrics ride the decode step: drained windows count the
    emitted tokens (prefill steps contribute zero)."""
    from apex_tpu import telemetry

    cfg, params = tiny_model
    ring = telemetry.RingBufferRecorder()
    rng = np.random.default_rng(9)
    reqs = [Request(prompt=[int(t) for t in rng.integers(0, 128, size=4)],
                    max_new_tokens=6)]
    eng = ServingEngine(cfg, params, n_slots=1, num_pages=6,
                        max_prompt_len=16, telemetry_every=1, sink=ring)
    eng.generate(reqs, max_steps=100)
    jax.effects_barrier()
    drains = [r for r in ring.records if r.get("event") == "metrics"]
    assert drains, "telemetry drains must reach the sink"
    assert sum(r["tokens"] for r in drains) == pytest.approx(6.0)
    summaries = [r for r in ring.records
                 if r.get("event") == "serving_summary"]
    assert summaries and summaries[0]["generated_tokens"] == 6


# ---------------------------------------------------------------------------
# satellites
# ---------------------------------------------------------------------------

def test_cast_params_for_inference_dtype_coverage():
    """Every float leaf lands in the target dtype; integer leaves and
    (optionally) batchnorm-ish leaves are untouched."""
    from apex_tpu.amp import cast_params_for_inference

    params = {
        "w": jnp.ones((4, 4), jnp.float32),
        "half": jnp.ones((4,), jnp.float16),
        "ids": jnp.arange(4, dtype=jnp.int32),
        "bn": {"batchnorm_scale": jnp.ones((4,), jnp.float32)},
    }
    out = cast_params_for_inference(params, jnp.bfloat16)
    assert out["w"].dtype == jnp.bfloat16
    assert out["half"].dtype == jnp.bfloat16
    assert out["ids"].dtype == jnp.int32
    assert out["bn"]["batchnorm_scale"].dtype == jnp.bfloat16
    kept = cast_params_for_inference(params, jnp.bfloat16,
                                     keep_batchnorm_fp32=True)
    assert kept["bn"]["batchnorm_scale"].dtype == jnp.float32


def test_cast_params_for_inference_no_copy_when_cast():
    """Already-cast leaves come back as the SAME array objects — a
    second cast is free (no device copies, no new buffers)."""
    from apex_tpu.amp import cast_params_for_inference

    params = {"w": jnp.ones((4, 4), jnp.float32),
              "ids": jnp.arange(4, dtype=jnp.int32)}
    once = cast_params_for_inference(params, jnp.bfloat16)
    twice = cast_params_for_inference(once, jnp.bfloat16)
    assert twice["w"] is once["w"]
    assert twice["ids"] is once["ids"]
    # and an fp32 target over fp32 inputs is the identity
    same = cast_params_for_inference(params, jnp.float32)
    assert same["w"] is params["w"]


def test_percentiles_reducer():
    from apex_tpu.telemetry import percentiles

    vals = list(range(1, 101))
    ps = percentiles(vals)
    assert ps["p50"] == pytest.approx(50.5)
    assert ps["p99"] == pytest.approx(99.01)
    recs = [{"ms": float(v)} for v in vals]
    recs.append({"other": 1.0})            # missing field skipped
    recs.append({"ms": "nan"})             # JSONL non-finite repr skipped
    recs.append({"ms": "inf"})
    assert percentiles(recs, field="ms") == ps
    assert percentiles([], field="ms") == {}
    assert percentiles([{"ms": None}], field="ms") == {}
    assert percentiles([1.0], ps=(25, 75)) == {"p25": 1.0, "p75": 1.0}


def test_health_report_dispatch_interval_percentiles():
    """health_report folds bench per-step dispatch stamps into per-leg
    dispatch-interval percentiles via the shared reducer."""
    from tools.health_report import health_from_records, render_report

    records = [{"event": "step", "leg": "gpt", "step": i,
                "t_dispatch": 1000.0 + 0.010 * i} for i in range(11)]
    h = health_from_records(records)
    assert h["dispatch_interval_ms"]["gpt"]["p50"] == pytest.approx(
        10.0, rel=1e-6)
    assert "dispatch interval [gpt]" in render_report(h)


def test_serving_check_cli_exit_codes():
    """CI contract: --self exits 0 when clean; bad usage exits 2 (via
    argparse); unknown check names are rejected."""
    import tools.serving_check as sc

    assert sc.main(["--self", "--check", "decode_parity", "--json"]) == 0
    with pytest.raises(SystemExit) as e:
        sc.main([])  # no --self: usage error
    assert e.value.code == 2
    with pytest.raises(SystemExit):
        sc.main(["--self", "--check", "nope"])


def test_serving_check_detects_broken_engine(monkeypatch):
    """A mismatching engine turns into exit 1, not a silent pass."""
    import tools.serving_check as sc

    def broken():
        return {"ok": False, "mismatches": [{"rid": 0}]}

    monkeypatch.setitem(sc.CHECKS, "token_identity", broken)
    assert sc.main(["--self", "--check", "token_identity"]) == 1


def test_compare_bench_surfaces_serving_legs():
    """The serving legs ride compare_bench with regression exit codes:
    a throughput drop or a latency increase past threshold regresses."""
    from tools.compare_bench import compare, extract_legs

    base = {"serving_throughput": {
        "tokens_per_sec": 100.0, "p50_ms": 50.0, "p99_ms": 80.0,
        "occupancy": 0.9}}
    legs = extract_legs(base)
    assert legs["serving_tokens_per_sec"] == 100.0
    assert legs["serving_p50_ms"] == -50.0  # lower-is-better inverted
    slower = {"serving_throughput": {
        "tokens_per_sec": 100.0, "p50_ms": 50.0, "p99_ms": 120.0,
        "occupancy": 0.9}}
    rep = compare(base, slower, threshold=0.05)
    assert [r["leg"] for r in rep["regressions"]] == ["serving_p99_ms"]
    assert rep["regressions"][0]["base"] == 80.0
    assert rep["regressions"][0]["new"] == 120.0
    faster = {"serving_throughput": {
        "tokens_per_sec": 120.0, "p50_ms": 40.0, "p99_ms": 80.0,
        "occupancy": 0.95}}
    rep = compare(base, faster, threshold=0.05)
    assert {r["leg"] for r in rep["improvements"]} >= {
        "serving_tokens_per_sec", "serving_p50_ms"}
    # committed CPU smoke artifact parses and carries both legs
    art = json.load(open("bench_artifacts/serving_cpu_smoke.json"))
    assert art["serving_throughput"]["tokens_per_sec"] > 0
    assert art["prefill_decode_split"]["prefill_slot_steps"] > 0


def test_scheduler_rejects_oversized_requests(tiny_model):
    cfg, params = tiny_model
    eng = ServingEngine(cfg, params, n_slots=1, num_pages=8,
                        max_prompt_len=8)
    with pytest.raises(SchedulerError, match="max_prompt_len"):
        eng.submit(Request(prompt=list(range(9)), max_new_tokens=1))
    with pytest.raises(SchedulerError, match="max_position_embeddings"):
        eng.submit(Request(prompt=list(range(8)), max_new_tokens=100))
    with pytest.raises(SchedulerError, match="max_new_tokens"):
        eng.submit(Request(prompt=[1, 2], max_new_tokens=0))


def test_scheduler_rejects_request_pool_can_never_hold(tiny_model):
    """A request needing more pages than the whole pool must be refused
    at submit — admitted, it would preempt everything and then sink the
    batch mid-generate (review finding)."""
    cfg, params = tiny_model
    # pool: 3 usable pages of 16 tokens (48); total = 16+48 = 64 needs
    # 4 pages, yet passes the max_prompt_len / maxpos / max_seq checks
    eng = ServingEngine(cfg, params, n_slots=2, num_pages=4,
                        max_prompt_len=16)
    too_big = 16 + 48
    assert too_big <= cfg.max_position_embeddings <= eng.spec.max_seq_len
    assert eng.spec.pages_for(too_big) > eng.spec.n_usable_pages
    with pytest.raises(SchedulerError, match="never be served"):
        eng.submit(Request(prompt=list(range(1, 17)), max_new_tokens=48))
    # requests the pool CAN hold (2 pages each, 3 usable -> they must
    # timeshare) still run to completion, token-identically
    reqs = [Request(prompt=list(range(1, 17)), max_new_tokens=8),
            Request(prompt=list(range(2, 18)), max_new_tokens=8,
                    arrival_step=1)]
    out = eng.generate(reqs, max_steps=500)
    eng.scheduler.check_invariants()
    assert eng.scheduler.allocator.used_count == 0
    for r in reqs:
        assert out[r.rid] == reference_decode(
            cfg, params, r.prompt, r.max_new_tokens)


# ---------------------------------------------------------------------------
# tensor parallelism (ISSUE-16): TP-sharded engine over the named mesh
# ---------------------------------------------------------------------------

def test_tp_identity_sweep():
    """The ISSUE-16 oracle, wired tier-1: ``tools/serving_check.py``'s
    ``tp_identity`` leg — TP=2 and TP=4 engines on the 8-virtual-device
    mesh are byte-identical to TP=1 across a staggered trace with
    chunked prefill, speculation, sampled + greedy slots and forced
    preemption, and every TP program's jaxpr carries exactly 3 psums."""
    import tools.serving_check as sc

    res = sc.check_tp_identity()
    assert res["tps"] == [2, 4], res
    assert res["ok"], res


def test_tp_spec_shard_and_page_size(tiny_model):
    """Geometry: the per-shard spec holds heads/tp of every page as one
    ROW-aligned PackSpec (check_pack_spec clean at shard_count=tp), and
    the default page size derives from the LOCAL head count."""
    from apex_tpu.analysis.rules import check_pack_spec

    cfg, params = tiny_model
    e1 = ServingEngine(cfg, params, n_slots=2, use_kernel=False)
    e2 = ServingEngine(cfg, params, n_slots=2, tp=2, use_kernel=False)
    assert e2.spec_local.num_heads == e2.spec.num_heads // 2
    assert e2.spec_local.page_size == e2.spec.page_size
    # per-shard K/V page still ROW-aligned -> larger default page than
    # the unsharded engine needs (4 heads/16 dim: 16 -> 32 tokens)
    assert e2.spec.page_size > e1.spec.page_size
    assert not check_pack_spec(e2.spec.pack_spec, shard_count=2)
    assert e2.spec_local.cache_bytes() * 2 == e2.spec.cache_bytes()
    # indivisible head counts / vocab are construction errors
    with pytest.raises(ValueError, match="not divisible"):
        ServingEngine(cfg, params, n_slots=2, tp=3, use_kernel=False)


def test_tp_psum_pin_and_no_pool_gather(tiny_model):
    """The collective budget, pinned on the traced programs: exactly
    one psum per transformer sublayer tail plus ONE fused sampler
    reduction = 3 per program (the fori_loop body appears once in the
    jaxpr) — and no all-gather ever touches a pool-shaped array (the
    only gathered operands are tiny sampler candidate matrices)."""
    import math
    import re

    cfg, params = tiny_model
    eng = ServingEngine(cfg, params, n_slots=2, tp=2, use_kernel=False,
                        prefill_chunk=3, spec_k=2)
    counts = eng.program_psum_counts()
    assert counts == {"decode": 3, "chunk_prefill": 3, "spec_verify": 3}
    pool_elems = math.prod(eng.spec_local.pool_leaf_shape)
    for fn, args in (eng.step_program(), eng.chunk_step_program(),
                     eng.spec_step_program()):
        txt = str(jax.make_jaxpr(fn)(*args))
        gathered = [m for m in txt.splitlines() if "all_gather" in m]
        assert gathered  # the sampler's candidate gather is there
        # an all-gather's output is >= its operand: bounding every
        # gathered RESULT far below one pool leaf proves no KV page
        # (page_size x head_dim trailing dims) ever crossed shards
        for line in gathered:
            for shp in re.findall(r"\[([\d,]+)\]", line):
                dims = tuple(int(x) for x in shp.split(","))
                assert math.prod(dims) < pool_elems // 4, (
                    f"all-gather of pool-scale operand {dims}: {line}")


def test_tp_engine_summary_and_events(tiny_model):
    """_summarize carries tp / per-shard pool bytes / psum counts, and
    fleet telemetry events are tagged with the TP degree."""
    from apex_tpu.serving import ReplicaFleet
    from apex_tpu.telemetry import RingBufferRecorder

    cfg, params = tiny_model
    eng = ServingEngine(cfg, params, n_slots=2, tp=2, use_kernel=False)
    out = eng.generate([Request(prompt=[1, 2, 3], max_new_tokens=4)],
                       max_steps=200)
    assert len(out) == 1
    st = eng.last_stats
    assert st["tp"] == 2
    assert st["kv_bytes_per_shard"] == eng.spec_local.cache_bytes()
    assert st["psum_per_program"] == {"decode": 3}
    # tp=1 engines report the null collective budget
    e1 = ServingEngine(cfg, params, n_slots=2, use_kernel=False)
    e1.generate([Request(prompt=[1, 2, 3], max_new_tokens=2)],
                max_steps=100)
    assert e1.last_stats["tp"] == 1
    assert e1.last_stats["psum_per_program"] is None

    ring = RingBufferRecorder()
    fleet = ReplicaFleet(cfg, params, n_replicas=2, tp=2, sink=ring,
                         n_slots=2, use_kernel=False)
    reqs = [Request(prompt=[2 + i, 3 + i], max_new_tokens=3)
            for i in range(3)]
    fleet.generate(reqs, max_steps=300)
    st = fleet.last_stats
    assert st["tp"] == 2 and st["total_chips"] == 4
    assert st["psum_per_program"] == {"decode": 3}
    tagged = [r for r in ring.records if "tp" in r and "replica_id" in r]
    assert tagged and all(r["tp"] == 2 for r in tagged)
    # DP x TP replicas own disjoint device groups
    groups = [{d.id for d in
               rep.engine._mesh.devices.reshape(-1)}
              for rep in fleet.replicas]
    assert groups[0].isdisjoint(groups[1])


def test_tp_audit_covers_sharded_programs(tiny_model):
    """engine.audit() stays clean on the TP-traced step: KV / slot /
    metrics donation and the cond-gated telemetry callback survive the
    shard_map wrapper, with the pool PackSpec checked at shard_count=tp
    (the in-jit drain ARMED, as in the tp=1 audit)."""
    from apex_tpu.telemetry import RingBufferRecorder

    cfg, params = tiny_model
    eng = ServingEngine(cfg, params, n_slots=2, tp=2, use_kernel=False,
                        telemetry_every=4, prefill_chunk=3, spec_k=2,
                        sink=RingBufferRecorder())
    report = eng.audit()
    assert report.ok


def test_tp_rejects_deep_top_k(tiny_model):
    """The TP sampler has no full-vocab-sort fallback: top_k beyond
    TOP_FILTER_WIDTH is refused at submit with a typed reason (tp=1
    keeps accepting it — the lax.cond deep path serves it there)."""
    from apex_tpu.serving import SamplingParams
    from apex_tpu.serving.robustness import RejectionCode
    from apex_tpu.serving.sampling import TOP_FILTER_WIDTH

    cfg, params = tiny_model
    deep = SamplingParams(temperature=0.9, top_k=TOP_FILTER_WIDTH + 1,
                          seed=3)
    eng = ServingEngine(cfg, params, n_slots=2, tp=2, use_kernel=False)
    reason = eng._engine_reject_reason(
        Request(prompt=[1, 2], max_new_tokens=2, sampling=deep))
    assert reason is not None
    assert reason.code is RejectionCode.UNSUPPORTED_SAMPLING
    with pytest.raises(SchedulerError, match="filter width"):
        eng.submit(Request(prompt=[1, 2], max_new_tokens=2,
                           sampling=deep))
    e1 = ServingEngine(cfg, params, n_slots=2, use_kernel=False)
    assert e1._engine_reject_reason(
        Request(prompt=[1, 2], max_new_tokens=2, sampling=deep)) is None


def test_cast_params_for_inference_preserves_sharding(tiny_model):
    """Satellite 1 (red test): casting a mesh-sharded param tree keeps
    every leaf's NamedSharding — a TP engine's column/row weight slices
    must not silently gather onto one device — and an already-cast
    sharded leaf comes back as the SAME buffer (zero-copy identity)."""
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    from apex_tpu.amp import cast_params_for_inference
    from apex_tpu.transformer import parallel_state

    mesh = parallel_state.tp_submesh(2)
    col = NamedSharding(mesh, PartitionSpec("tensor", None))
    rep = NamedSharding(mesh, PartitionSpec())
    params = {
        "w_col": jax.device_put(
            jnp.asarray(np.arange(32.0).reshape(8, 4), jnp.float32), col),
        "b_rep": jax.device_put(jnp.ones((4,), jnp.float32), rep),
        "ids": jnp.arange(4, dtype=jnp.int32),
    }
    out = cast_params_for_inference(params, jnp.bfloat16)
    assert out["w_col"].dtype == jnp.bfloat16
    assert out["w_col"].sharding.is_equivalent_to(col, 2)
    assert out["b_rep"].sharding.is_equivalent_to(rep, 1)
    assert out["ids"] is params["ids"]
    # idempotent re-cast of the sharded tree: same buffers, no copies
    again = cast_params_for_inference(out, jnp.bfloat16)
    assert again["w_col"] is out["w_col"]
    assert again["b_rep"] is out["b_rep"]


def test_serving_tp_bench_artifact_and_compare_legs():
    """Satellite 4: the committed equal-chip DP-vs-TP smoke artifact
    parses and carries the contract numbers (psum budget, halved
    per-chip pool, zero leaks), and compare_bench extracts + orients
    the two gated serving_tp legs."""
    from tools.compare_bench import extract_legs

    art = json.load(open("bench_artifacts/serving_tp_cpu_smoke.json"))
    tp = art["serving_tp"]
    assert tp["tp"] == 2 and tp["chips"] == 2
    assert tp["tokens_per_sec"] > 0 and tp["dp_tokens_per_sec"] > 0
    assert all(v == 3 for v in tp["psum_per_program"].values())
    assert tp["kv_bytes_per_chip_ratio"] == 0.5
    assert tp["page_leaks"] == 0
    legs = extract_legs(art)
    assert legs["serving_tp_tokens_per_sec"] == tp["tokens_per_sec"]
    # lower-is-better legs are sign-inverted at extraction
    assert legs["serving_tp_p99_ms"] == -tp["p99_ms"]


def test_tp_recover_and_swap_keep_topology(tiny_model):
    """recover_from / rebuild_like / swap_params preserve the TP
    geometry (captured ctor kwargs): the revived engine decodes
    token-identically on the same device group, and a weight swap lays
    the fresh tree down SHARDED before the cast."""
    cfg, params = tiny_model
    eng = ServingEngine(cfg, params, n_slots=2, tp=2, use_kernel=False)
    reqs = [Request(prompt=[3, 4, 5, 6], max_new_tokens=5)]
    ref = reference_decode(cfg, params, [3, 4, 5, 6], 5)
    out = eng.generate(list(reqs), max_steps=200)
    assert out[reqs[0].rid] == ref

    fresh = ServingEngine.rebuild_like(eng)
    assert fresh.tp == 2 and fresh._mesh is not None
    r2 = Request(prompt=[3, 4, 5, 6], max_new_tokens=5)
    assert fresh.generate([r2], max_steps=200)[r2.rid] == ref

    # hot swap: sharded placement preserved, decode follows new weights
    params2 = jax.tree_util.tree_map(lambda x: x, params)
    params2["embedding"]["position"] = (
        params["embedding"]["position"] * 0.5)
    fresh.swap_params(params2)
    qkv = fresh.params["layers"]["qkv_w"]
    assert "tensor" in str(qkv.sharding.spec)
    r3 = Request(prompt=[3, 4, 5, 6], max_new_tokens=5)
    assert (fresh.generate([r3], max_steps=200)[r3.rid]
            == reference_decode(cfg, params2, [3, 4, 5, 6], 5))
