"""Checkpoint tests: orbax-backed sharded save/load + GDSFile parity.

Covers the reference's checkpoint surface (SURVEY §5): model/optimizer
state round-trips, DistributedFusedAdam's sharded (v2) persistence with
restore-onto-a-mesh, cross-layout restore (the v1 gather/rescatter
capability), amp scaler state, and the GDSFile raw-tensor IO analogue.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.checkpoint import load_checkpoint, save_checkpoint
from apex_tpu.contrib.gpu_direct_storage import GDSFile


def _mesh(n=8):
    return Mesh(np.array(jax.devices()[:n]), ("data",))


def test_roundtrip_host_pytree(tmp_path):
    state = {
        "params": {"w": jnp.arange(12.0).reshape(3, 4),
                   "b": jnp.zeros((4,), jnp.bfloat16)},
        "step": jnp.int32(7),
    }
    save_checkpoint(str(tmp_path / "ck"), state)
    back = load_checkpoint(str(tmp_path / "ck"))
    np.testing.assert_array_equal(np.asarray(back["params"]["w"]),
                                  np.asarray(state["params"]["w"]))
    assert back["params"]["b"].dtype == jnp.bfloat16
    assert int(back["step"]) == 7


def test_roundtrip_sharded_arrays(tmp_path):
    """Sharded leaves save per-shard and restore onto the same mesh with
    identical sharding and values (the v2 format property)."""
    mesh = _mesh()
    sh = NamedSharding(mesh, P("data"))
    x = jax.device_put(jnp.arange(64.0).reshape(8, 8), sh)
    rep = jax.device_put(jnp.ones((4,)), NamedSharding(mesh, P()))
    state = {"x": x, "rep": rep}
    save_checkpoint(str(tmp_path / "ck"), state)

    restored = load_checkpoint(str(tmp_path / "ck"), target=state)
    assert restored["x"].sharding == sh
    np.testing.assert_array_equal(np.asarray(restored["x"]), np.asarray(x))
    np.testing.assert_array_equal(np.asarray(restored["rep"]), np.asarray(rep))


def test_restore_onto_different_layout(tmp_path):
    """A checkpoint saved data-sharded restores replicated (and vice
    versa) — the v1 gather/rescatter capability without the gather."""
    mesh = _mesh()
    x = jax.device_put(jnp.arange(64.0).reshape(8, 8),
                       NamedSharding(mesh, P("data")))
    save_checkpoint(str(tmp_path / "ck"), {"x": x})

    target = {"x": jax.ShapeDtypeStruct(
        (8, 8), jnp.float32, sharding=NamedSharding(mesh, P(None, "data")))}
    restored = load_checkpoint(str(tmp_path / "ck"), target=target)
    assert restored["x"].sharding.spec == P(None, "data")
    np.testing.assert_array_equal(np.asarray(restored["x"]),
                                  np.asarray(x))


def test_distributed_fused_adam_state_roundtrip(tmp_path):
    """ZeRO-2 optimizer state: save mid-training, restore, training
    continues bit-identically (reference v1/v2 sharded state dicts,
    distributed_fused_adam.py:2956-3555)."""
    from apex_tpu.contrib.optimizers import DistributedFusedAdam

    params = {"w": jnp.arange(32.0).reshape(4, 8) / 32.0,
              "b": jnp.zeros((8,))}
    opt = DistributedFusedAdam(lr=1e-2, distributed_size=8)
    mesh = _mesh()

    def step(params, state, grads):
        def local(params, state, grads):
            return opt.step(grads, state, params)

        specs = opt.state_specs()
        return jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(), specs, P()),
            out_specs=(P(), specs), check_vma=False,
        )(params, state, grads)

    state = opt.init(params)
    grads = jax.tree_util.tree_map(jnp.ones_like, params)
    params1, state1 = step(params, state, grads)

    save_checkpoint(str(tmp_path / "ck"),
                    {"params": params1, "opt": state1._asdict()})
    back = load_checkpoint(str(tmp_path / "ck"),
                           target={"params": params1,
                                   "opt": state1._asdict()})
    state_re = type(state1)(**back["opt"])

    p_a, _ = step(params1, state1, grads)
    p_b, _ = step(back["params"], state_re, grads)
    for ka in p_a:
        np.testing.assert_array_equal(np.asarray(p_a[ka]), np.asarray(p_b[ka]))


def test_amp_scaler_state_roundtrip(tmp_path):
    from apex_tpu.amp.scaler import LossScaler

    scaler = LossScaler("dynamic", init_scale=2.0 ** 12)
    st = scaler.init_state()
    st = st._replace(loss_scale=jnp.float32(1024.0), unskipped=jnp.int32(17))
    save_checkpoint(str(tmp_path / "ck"), st._asdict())
    back = load_checkpoint(str(tmp_path / "ck"))
    assert float(back["loss_scale"]) == 1024.0
    assert int(back["unskipped"]) == 17


def test_gdsfile_roundtrip(tmp_path):
    fn = str(tmp_path / "t.bin")
    x = jnp.arange(24, dtype=jnp.float32).reshape(4, 6) * 1.5
    with GDSFile(fn, "w") as f:
        f.save_data(x)
    with GDSFile(fn, "r") as f:
        y = f.load_data(jnp.zeros_like(x))
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))


def test_gdsfile_bf16_and_multiple_tensors(tmp_path):
    fn = str(tmp_path / "t.bin")
    a = jnp.arange(8, dtype=jnp.bfloat16)
    b = jnp.ones((2, 3), jnp.int32) * 7
    with GDSFile(fn, "w") as f:
        f.save_data(a)
        f.save_data(b)
    with GDSFile(fn, "r") as f:
        a2 = f.load_data(jnp.zeros_like(a))
        b2 = f.load_data(jnp.zeros_like(b))
    np.testing.assert_array_equal(np.asarray(a2, np.float32),
                                  np.asarray(a, np.float32))
    np.testing.assert_array_equal(np.asarray(b2), np.asarray(b))


def test_gdsfile_mode_enforcement(tmp_path):
    fn = str(tmp_path / "t.bin")
    x = jnp.zeros((2,))
    with GDSFile(fn, "w") as f:
        f.save_data(x)
        with pytest.raises(RuntimeError):
            f.load_data(x)
    with GDSFile(fn, "r") as f:
        with pytest.raises(RuntimeError):
            f.save_data(x)
    with pytest.raises(ValueError):
        with GDSFile(fn, "x"):
            pass
