"""Real-process serving fleet: crash-safe transport, shared liveness,
multi-process telemetry, and the supervised failover CI wiring
(apex_tpu.serving.transport / worker / proc_fleet — ISSUE-20).

Coverage map (the ISSUE-20 acceptance surface):

- transport: length-prefixed newline-JSON framing round-trips typed
  records over a real pipe; a writer SIGKILLed mid-frame leaves a torn
  FINAL frame that is COUNTED (`torn_frames`) and folded into EOF —
  never crashed on — while mid-stream corruption still raises
  `TransportError`; `Request` survives the wire byte-exactly
  (sampling params, budgets, replay carrier fields included);
- shared liveness (satellite): `Heartbeat` lives in
  `resilience.liveness`, `elastic` re-exports the SAME object, and the
  pinned beat file format round-trips; corpse-incarnation hygiene —
  a beat whose recorded writer pid is dead is NOT live, and
  `sweep_stale` removes dead writers' droppings while sparing live
  ones;
- multi-process JsonlRecorder (satellite): two REAL subprocess writers
  hammer one sink file with records larger than a stdio buffer; every
  line reads back intact (O_APPEND + one os.write per record — the
  red test that fails under buffered fwrite);
- retry wiring (satellite): `TRANSPORT_POLICY` retries on OSError,
  `WorkerUnavailable` IS an OSError, and `FleetSupervisor` routes
  RPCs through it by default;
- chaos spec grammar: `WorkerChaos` specs round-trip through
  `to_spec`/`parse` and fire exactly once on step crossing;
- CI wiring: the `proc_fleet_failover` serving_check leg (SIGKILL one
  worker mid-frame AND wedge another in the SAME run) passes tier-1,
  compare_bench gates `requests_lost` absolutely at 0, and the
  committed CPU smoke artifact carries the schema.
"""
import json
import os
import signal
import subprocess
import sys
import tempfile
import textwrap
import time

import pytest

from apex_tpu.resilience import (
    RetryPolicy,
    ServingChaos,
    TRANSPORT_POLICY,
    WorkerChaos,
    live_beat,
    sweep_stale,
    writer_alive,
)
from apex_tpu.resilience.liveness import Heartbeat
from apex_tpu.serving import (
    FrameReader,
    Request,
    TransportError,
    WorkerUnavailable,
    read_frames,
    request_from_wire,
    request_to_wire,
    write_frame,
)
from apex_tpu.serving.sampling import SamplingParams
from apex_tpu.telemetry import JsonlRecorder, read_jsonl


# ---------------------------------------------------------------------------
# transport framing
# ---------------------------------------------------------------------------

def test_frame_roundtrip_over_pipe():
    rfd, wfd = os.pipe()
    try:
        msgs = [{"op": "probe", "rid": "r-0"},
                {"op": "step", "updates": [{"rid": "r-1",
                                            "new_tokens": [1, 2, 3]}]},
                {"unicode": "päivää", "nested": {"a": [None, True]}}]
        for m in msgs:
            write_frame(wfd, m)
        reader = FrameReader(rfd)
        got = [reader.read_frame(timeout=2.0) for _ in msgs]
        assert got == msgs
        assert reader.torn_frames == 0
    finally:
        os.close(rfd)
        os.close(wfd)


def test_read_frame_timeout_is_worker_unavailable():
    rfd, wfd = os.pipe()
    try:
        reader = FrameReader(rfd)
        with pytest.raises(WorkerUnavailable):
            reader.read_frame(timeout=0.05)
        # WorkerUnavailable must be an OSError so TRANSPORT_POLICY
        # (retry_on=(OSError,)) classifies it transient
        assert issubclass(WorkerUnavailable, OSError)
    finally:
        os.close(rfd)
        os.close(wfd)


def test_midstream_corruption_raises_not_skips():
    """A torn frame is only tolerable at EOF; garbage mid-stream is
    corruption and must raise, never be silently resynced over."""
    path = os.path.join(tempfile.mkdtemp(prefix="frames-"), "s.frames")
    with open(path, "wb") as f:
        f.write(b"not a length prefix\n")
        from apex_tpu.serving.transport import frame_bytes

        f.write(frame_bytes({"ok": 1}))
    with pytest.raises(TransportError):
        read_frames(path)


def test_writer_sigkilled_mid_frame_leaves_counted_torn_tail():
    """THE red test for torn-frame tolerance: a REAL subprocess writer
    is SIGKILLed after writing half a frame. The reader must return
    every complete frame, count exactly one torn frame, and not
    raise."""
    wd = tempfile.mkdtemp(prefix="torn-")
    path = os.path.join(wd, "out.frames")
    prog = textwrap.dedent("""
        import os, sys, time
        sys.path.insert(0, %r)
        from apex_tpu.serving.transport import frame_bytes
        fd = os.open(%r, os.O_WRONLY | os.O_CREAT, 0o644)
        for i in range(3):
            os.write(fd, frame_bytes({"seq": i}))
        half = frame_bytes({"seq": 3, "pad": "x" * 256})
        os.write(fd, half[: len(half) // 2])
        os.fsync(fd)
        print("TORN", flush=True)
        time.sleep(60)
    """) % (os.path.dirname(os.path.dirname(__file__)), path)
    proc = subprocess.Popen([sys.executable, "-c", prog],
                            stdout=subprocess.PIPE, text=True)
    try:
        assert proc.stdout.readline().strip() == "TORN"
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10)
        stats = {}
        frames = read_frames(path, stats=stats)
        assert frames == [{"seq": 0}, {"seq": 1}, {"seq": 2}]
        assert stats["torn_frames"] == 1
    finally:
        if proc.poll() is None:
            proc.kill()


def test_request_wire_roundtrip_carries_replay_state():
    req = Request(prompt=[5, 6, 7], max_new_tokens=9, arrival_step=3,
                  priority=2, ttft_budget_ms=120.0,
                  latency_budget_ms=4000.0,
                  sampling=SamplingParams(temperature=0.7, top_k=40,
                                          top_p=0.9, seed=17),
                  labels={"tenant": "a"})
    req.out_tokens.extend([11, 12])   # mid-flight migration state
    req.restarts = 1
    req.retries = 2
    wire = json.loads(json.dumps(request_to_wire(req)))  # must be JSON
    back = request_from_wire(wire)
    assert back.rid == req.rid
    assert back.prompt == [5, 6, 7]
    assert back.out_tokens == [11, 12]
    assert back.restarts == 1 and back.retries == 2
    assert back.sampling == req.sampling
    assert back.ttft_budget_ms == 120.0
    assert back.labels == {"tenant": "a"}


# ---------------------------------------------------------------------------
# shared liveness (satellite): Heartbeat factoring + corpse hygiene
# ---------------------------------------------------------------------------

def test_heartbeat_is_shared_and_format_pinned():
    """elastic re-exports THE liveness.Heartbeat (no fork of the beat
    format), and the on-disk schema is pinned: host/step/pid/t_wall,
    staged via tmp-<pid> then atomic replace."""
    from apex_tpu.resilience import elastic, liveness

    assert elastic.Heartbeat is liveness.Heartbeat
    wd = tempfile.mkdtemp(prefix="hb-")
    path = os.path.join(wd, "hb-0.json")
    hb = Heartbeat(path, host=0)
    hb.beat(7)
    raw = json.load(open(path))
    assert raw == {"host": 0, "step": 7, "pid": os.getpid(),
                   "t_wall": pytest.approx(time.time(), abs=30.0)}
    got = Heartbeat.read(path)
    assert got["step"] == 7
    assert Heartbeat.age_s(path) < 30.0
    assert not [p for p in os.listdir(wd) if ".tmp-" in p]


def _spawn_corpse():
    """A real dead pid: fork a subprocess and let it exit."""
    p = subprocess.Popen([sys.executable, "-c", "pass"])
    p.wait(timeout=10)
    return p.pid


def test_dead_writers_beat_is_never_fresh():
    """Corpse-incarnation hygiene: a beat file whose recorded pid is
    dead must not read as live, however recent its mtime — else a
    supervisor would trust a corpse's last words."""
    wd = tempfile.mkdtemp(prefix="hb-")
    path = os.path.join(wd, "hb-1.json")
    Heartbeat(path, host=1).beat(3)
    beat = json.load(open(path))
    beat["pid"] = _spawn_corpse()
    with open(path, "w") as f:
        json.dump(beat, f)
    assert writer_alive(os.getpid())
    assert not writer_alive(beat["pid"])
    assert live_beat(path) is None          # dead writer => not live
    fresh = os.path.join(wd, "hb-2.json")
    Heartbeat(fresh, host=2).beat(4)
    assert live_beat(fresh)["step"] == 4    # we are alive


def test_sweep_stale_removes_corpse_files_spares_live():
    wd = tempfile.mkdtemp(prefix="sweep-")
    corpse = _spawn_corpse()
    # dead writer's droppings: staging tmp + committed beat
    open(os.path.join(wd, f"hb-9.json.tmp-{corpse}"), "w").write("{")
    dead_beat = os.path.join(wd, "hb-9.json")
    json.dump({"host": 9, "step": 1, "pid": corpse,
               "t_wall": time.time()}, open(dead_beat, "w"))
    # live writer's beat + an unrelated file must survive
    live = os.path.join(wd, "hb-0.json")
    Heartbeat(live, host=0).beat(1)
    other = os.path.join(wd, "replica-0.0.jsonl")
    open(other, "w").write("{}\n")
    removed = sweep_stale(wd, prefix="hb-")
    assert len(removed) >= 2
    assert not os.path.exists(dead_beat)
    assert not [p for p in os.listdir(wd) if ".tmp-" in p]
    assert os.path.exists(live) and os.path.exists(other)


# ---------------------------------------------------------------------------
# multi-process JsonlRecorder (satellite red test)
# ---------------------------------------------------------------------------

def test_jsonl_recorder_two_subprocess_writers_interleave_intact():
    """TWO real subprocess writers append large records (bigger than
    any stdio buffer) to ONE file concurrently. O_APPEND + a single
    os.write per record keeps every line intact; a buffered-fwrite
    implementation shears records across the other writer's output."""
    wd = tempfile.mkdtemp(prefix="mpjsonl-")
    path = os.path.join(wd, "shared.jsonl")
    n, size = 40, 64 * 1024
    prog = textwrap.dedent("""
        import sys
        sys.path.insert(0, %r)
        from apex_tpu.telemetry import JsonlRecorder
        tag, n, size = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
        rec = JsonlRecorder(%r, only_logging_process=False, append=True)
        for i in range(n):
            rec.record({"writer": tag, "i": i, "pad": tag * size})
        rec.close()
    """) % (os.path.dirname(os.path.dirname(__file__)), path)
    procs = [subprocess.Popen(
        [sys.executable, "-c", prog, tag, str(n), str(size)])
        for tag in ("a", "b")]
    for p in procs:
        assert p.wait(timeout=120) == 0
    stats = {}
    records = read_jsonl(path, stats=stats)
    assert stats.get("torn_lines", 0) == 0
    assert len(records) == 2 * n
    by_writer = {"a": [], "b": []}
    for r in records:
        assert r["pad"] == r["writer"] * size   # no shearing
        by_writer[r["writer"]].append(r["i"])
    # per-writer order preserved (O_APPEND never reorders one fd)
    assert by_writer["a"] == list(range(n))
    assert by_writer["b"] == list(range(n))


def test_jsonl_recorder_single_write_per_record(tmp_path):
    """The mechanism itself: record() issues exactly ONE os.write."""
    path = str(tmp_path / "one.jsonl")
    rec = JsonlRecorder(path, only_logging_process=False)
    calls = []
    real_write = os.write

    def counting_write(fd, data):
        calls.append(len(data))
        return real_write(fd, data)

    try:
        os.write = counting_write
        rec.record({"event": "x", "pad": "y" * (64 * 1024)})
    finally:
        os.write = real_write
    rec.close()
    assert len(calls) == 1
    assert read_jsonl(path)[0]["pad"] == "y" * (64 * 1024)


# ---------------------------------------------------------------------------
# retry wiring (satellite)
# ---------------------------------------------------------------------------

def test_transport_policy_shape_and_default_wiring():
    from apex_tpu.serving.proc_fleet import FleetSupervisor

    assert isinstance(TRANSPORT_POLICY, RetryPolicy)
    assert OSError in TRANSPORT_POLICY.retry_on
    assert TRANSPORT_POLICY.deadline is not None  # wall-clock bound
    assert TRANSPORT_POLICY.max_delay <= TRANSPORT_POLICY.deadline
    sup = FleetSupervisor({"kind": "tiny_gpt"}, 1,
                          workdir=tempfile.mkdtemp(prefix="pol-"))
    assert sup.rpc_policy is TRANSPORT_POLICY


# ---------------------------------------------------------------------------
# chaos spec grammar
# ---------------------------------------------------------------------------

def test_worker_chaos_spec_roundtrip_and_single_fire():
    c = (WorkerChaos().kill_at(6, mid_frame=True)
         .wedge_at(9, stall_s=30.0).drop_at(5, n=2))
    spec = c.to_spec()
    back = WorkerChaos.parse(spec)
    assert back.to_spec() == spec
    # crossing the armed step fires exactly once, even if stepped past
    assert back.take_kill(5) is None
    assert back.take_kill(7) is True        # mid_frame flag
    assert back.take_kill(8) is None        # already fired
    assert back.take_wedge(9) == 30.0
    assert back.take_wedge(10) is None
    drops = [back.take_drop(s) for s in range(4, 9)]
    assert drops == [False, True, True, False, False]  # n=2 budget
    assert WorkerChaos.parse("").armed is False
    # ServingChaos hands each replica its own spec string
    sc = ServingChaos().kill_worker_at(1, 4).wedge_worker_at(2, 6)
    assert sc.worker_spec(0) == ""
    assert WorkerChaos.parse(sc.worker_spec(1)).armed
    assert WorkerChaos.parse(sc.worker_spec(2)).armed


# ---------------------------------------------------------------------------
# CI wiring: serving_check proc leg + compare_bench gates + artifact
# ---------------------------------------------------------------------------

def test_serving_check_proc_fleet_leg_passes():
    """THE tier-1 chaos bar: 3 real worker subprocesses, one SIGKILLed
    mid-frame AND one wedged in the SAME run; zero requests lost,
    token-identical migrants, torn frame + torn telemetry line counted
    (see tools/serving_check.py::check_proc_fleet_failover)."""
    import tools.serving_check as sc

    assert sc.main(["--self", "--check", "proc_fleet_failover"]) == 0


def test_compare_bench_gates_proc_fleet_leg():
    """requests_lost is gated ABSOLUTELY at 0 — one lost request from
    a zero base is a regression, not sub-threshold noise; mttr_s gets
    an absolute band (CPU jax startup jitter); goodput/attainment ride
    the relative threshold."""
    from tools.compare_bench import ABS_TOLERANCE, compare, extract_legs

    base = {"serving_proc_fleet": {
        "requests_lost": 0, "mttr_s": 3.0,
        "goodput_tokens_per_sec": 4.0, "slo_attainment": 1.0}}
    legs = extract_legs(base)
    assert legs["proc_fleet_requests_lost"] == 0.0
    assert legs["proc_fleet_mttr_s"] == -3.0      # lower is better
    assert legs["proc_fleet_goodput"] == 4.0
    assert legs["proc_fleet_slo_attainment"] == 1.0
    assert "proc_fleet_requests_lost" in ABS_TOLERANCE
    assert ABS_TOLERANCE["proc_fleet_requests_lost"] < 1.0
    lost = {"serving_proc_fleet": {
        "requests_lost": 1, "mttr_s": 3.0,
        "goodput_tokens_per_sec": 4.0, "slo_attainment": 1.0}}
    rep = compare(base, lost, threshold=0.05)
    assert {r["leg"] for r in rep["regressions"]} == {
        "proc_fleet_requests_lost"}
    # mttr noise inside the absolute band is NOT a regression
    jitter = {"serving_proc_fleet": {
        "requests_lost": 0, "mttr_s": 6.0,
        "goodput_tokens_per_sec": 4.0, "slo_attainment": 1.0}}
    assert not compare(base, jitter, threshold=0.05)["regressions"]


def test_proc_fleet_smoke_artifact_schema():
    art = json.load(
        open("bench_artifacts/serving_proc_fleet_cpu_smoke.json"))
    leg = art["serving_proc_fleet"]
    assert leg["requests_lost"] == 0
    assert leg["replica_deaths"] == 2
    assert sorted(leg["incidents"]) == ["worker_death", "worker_hang"]
    assert leg["migrated"] >= 1
    assert leg["mttr_s"] is not None
    assert leg["torn_frames"] >= 1
    assert leg["slo_attainment"] == 1.0
    assert leg["page_leaks"] == 0
    from tools.compare_bench import extract_legs

    assert extract_legs(art)["proc_fleet_requests_lost"] == 0.0
