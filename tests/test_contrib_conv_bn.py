"""Tests: contrib.conv_bias_relu and contrib.groupbn/cudnn_gbn.

Each vs a torch reference (the reference suites'
`apex/contrib/test/{conv_bias_relu,groupbn,cudnn_gbn}` idiom).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.contrib.conv_bias_relu import (
    ConvBias,
    ConvBiasMaskReLU,
    ConvBiasReLU,
    ConvFrozenScaleBiasReLU,
)
from apex_tpu.contrib.cudnn_gbn import GroupBatchNorm2d
from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC


def _conv_inputs(key=0, n=2, h=8, w=8, cin=4, cout=6, k=3):
    ks = jax.random.split(jax.random.PRNGKey(key), 3)
    x = jax.random.normal(ks[0], (n, h, w, cin))
    weight = jax.random.normal(ks[1], (k, k, cin, cout)) * 0.3
    bias = jax.random.normal(ks[2], (cout,)) * 0.1
    return x, weight, bias


def _torch_conv(x, weight, padding, stride):
    tx = torch.tensor(np.asarray(x)).permute(0, 3, 1, 2)
    tw = torch.tensor(np.asarray(weight)).permute(3, 2, 0, 1)  # HWIO->OIHW
    return torch.nn.functional.conv2d(tx, tw, padding=padding, stride=stride)


@pytest.mark.parametrize("padding,stride", [(1, 1), (1, 2), (0, 1)])
def test_conv_bias_relu_vs_torch(padding, stride):
    x, weight, bias = _conv_inputs()
    out = ConvBiasReLU(x, weight, bias, padding, stride)
    ref = torch.relu(_torch_conv(x, weight, padding, stride)
                     + torch.tensor(np.asarray(bias)).view(1, -1, 1, 1))
    np.testing.assert_allclose(
        np.asarray(out), ref.permute(0, 2, 3, 1).numpy(), atol=1e-5)


def test_conv_bias_and_mask_relu():
    x, weight, bias = _conv_inputs(1)
    out_nb = ConvBias(x, weight, bias, 1, 1)
    ref = (_torch_conv(x, weight, 1, 1)
           + torch.tensor(np.asarray(bias)).view(1, -1, 1, 1))
    np.testing.assert_allclose(
        np.asarray(out_nb), ref.permute(0, 2, 3, 1).numpy(), atol=1e-5)

    mask = (jax.random.uniform(jax.random.PRNGKey(2), out_nb.shape) > 0.5)
    out_m = ConvBiasMaskReLU(x, weight, bias, mask, 1, 1)
    ref_m = np.maximum(np.asarray(out_nb) * np.asarray(mask, np.float32), 0.0)
    np.testing.assert_allclose(np.asarray(out_m), ref_m, atol=1e-5)


def test_conv_frozen_scale_bias_relu_stops_gradients():
    x, weight, bias = _conv_inputs(3)
    scale = jnp.ones((weight.shape[-1],)) * 1.5

    def f(weight, scale, bias):
        return jnp.sum(ConvFrozenScaleBiasReLU(x, weight, scale, bias, 1, 1))

    gw, gs, gb = jax.grad(f, argnums=(0, 1, 2))(weight, scale, bias)
    assert np.abs(np.asarray(gw)).max() > 0  # conv weight trains
    assert np.abs(np.asarray(gs)).max() == 0.0  # frozen
    assert np.abs(np.asarray(gb)).max() == 0.0  # frozen


def test_groupbn_single_group_matches_torch():
    n, h, w, c = 4, 5, 5, 8
    x = jax.random.normal(jax.random.PRNGKey(0), (n, h, w, c))
    bn = BatchNorm2d_NHWC(c)
    params, state = bn.init()
    y, new_state = bn.apply(params, state, x, training=True)

    tbn = torch.nn.BatchNorm2d(c, momentum=0.1, eps=1e-5)
    tx = torch.tensor(np.asarray(x)).permute(0, 3, 1, 2)
    ty = tbn(tx).detach().permute(0, 2, 3, 1).numpy()
    np.testing.assert_allclose(np.asarray(y), ty, atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(new_state["running_mean"]),
        tbn.running_mean.numpy(), atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(new_state["running_var"]),
        tbn.running_var.numpy(), atol=1e-4)


def test_groupbn_addrelu_epilogue():
    c = 8
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 4, 4, c))
    z = jax.random.normal(jax.random.PRNGKey(2), (2, 4, 4, c))
    bn = BatchNorm2d_NHWC(c)
    params, state = bn.init()
    y_plain, _ = bn.apply(params, state, x, training=True)
    y_addrelu, _ = bn.apply(params, state, x, z, training=True)
    np.testing.assert_allclose(
        np.asarray(y_addrelu),
        np.maximum(np.asarray(y_plain) + np.asarray(z), 0.0), atol=1e-5)


def test_groupbn_group_sync_equals_global_bn():
    """bn_group=4 over the mesh axis: per-shard BN with group sync must
    equal single-device BN on the concatenated batch (the reference's
    whole point: small per-GPU batches, full-group statistics)."""
    G = 4
    n, h, w, c = 8, 4, 4, 8  # batch sharded into 4 shards of 2
    x = jax.random.normal(jax.random.PRNGKey(3), (n, h, w, c))
    bn = BatchNorm2d_NHWC(c, bn_group=G, axis_name="bn_group")
    params, state = bn.init()

    mesh = Mesh(np.array(jax.devices()[:G]), ("bn_group",))
    y, new_state = jax.shard_map(
        lambda p, s, x: bn.apply(p, s, x, training=True),
        mesh=mesh, in_specs=(P(), P(), P("bn_group")),
        out_specs=(P("bn_group"), P()), check_vma=False,
    )(params, state, x)

    bn1 = BatchNorm2d_NHWC(c)
    y_ref, state_ref = bn1.apply(params, state, x, training=True)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(new_state["running_mean"]),
        np.asarray(state_ref["running_mean"]), atol=1e-5)


def test_cudnn_gbn_alias():
    gbn = GroupBatchNorm2d(8, group_size=2)
    assert gbn.bn_group == 2
    with pytest.raises(NotImplementedError):
        GroupBatchNorm2d(8, group_size=2, affine=False)


# ------------------------------------------------------------ fused_adam_swa


def test_fused_adam_swa_matches_torch_adam_and_swa_math():
    """PyTorchAdam mode vs torch.optim.Adam state-by-state, and the SWA
    EMA vs hand math (reference `apex/contrib/test/openfold_triton/
    test_fused_adam_swa.py` idiom)."""
    from apex_tpu.contrib.openfold import AdamMathType, FusedAdamSWA

    params = {"w": jnp.asarray(np.random.RandomState(0).randn(4, 5), jnp.float32)}
    compute = jax.tree_util.tree_map(lambda p: p.astype(jnp.bfloat16), params)
    swa = jax.tree_util.tree_map(lambda p: p, params)
    opt = FusedAdamSWA(swa_decay_rate=0.9, lr=1e-2, weight_decay=0.01,
                       adam_math_mode=AdamMathType.PyTorchAdam)
    state = opt.init(params)

    tp = torch.tensor(np.asarray(params["w"]), requires_grad=True)
    topt = torch.optim.Adam([tp], lr=1e-2, weight_decay=0.01)

    rng = np.random.RandomState(1)
    swa_ref = np.asarray(params["w"]).copy()
    for i in range(5):
        g = rng.randn(4, 5).astype(np.float32)
        params, compute, swa, state = opt.step(
            {"w": jnp.asarray(g)}, state, params, compute, swa)
        tp.grad = torch.tensor(g)
        topt.step()
        np.testing.assert_allclose(
            np.asarray(params["w"]), tp.detach().numpy(), atol=1e-6,
            err_msg=f"step {i}")
        if i == 0:
            swa_ref = tp.detach().numpy().copy()
        else:
            swa_ref = swa_ref + (1 - 0.9) * (tp.detach().numpy() - swa_ref)
        np.testing.assert_allclose(
            np.asarray(swa["w"]), swa_ref, atol=1e-6, err_msg=f"swa {i}")
    # compute copy tracks the master in bf16
    np.testing.assert_allclose(
        np.asarray(compute["w"], np.float32),
        np.asarray(params["w"].astype(jnp.bfloat16), np.float32))


def test_fused_adam_swa_apexw_mode_differs():
    from apex_tpu.contrib.openfold import AdamMathType, FusedAdamSWA

    params = {"w": jnp.ones((3,))}
    g = {"w": jnp.full((3,), 0.5)}
    outs = {}
    for mode in (AdamMathType.PyTorchAdam, AdamMathType.ApexAdamW):
        opt = FusedAdamSWA(swa_decay_rate=0.9, lr=1e-2, weight_decay=0.1,
                           adam_math_mode=mode)
        st = opt.init(params)
        p, _, _, _ = opt.step(
            g, st, params, params, params)
        outs[mode] = np.asarray(p["w"])
    assert not np.allclose(outs[AdamMathType.PyTorchAdam],
                           outs[AdamMathType.ApexAdamW])
