"""Ring attention + Ulysses all-to-all context parallelism vs the dense
single-device reference (fwd + grads, causal and bidirectional), on a
cp=4 submesh of the 8-device CPU harness.

These shard the sequence INSIDE attention — the long-context extension
beyond the reference's Megatron SP (SURVEY §2.4: ring/Ulysses noted as
the TPU extension point)."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.ops.flash_attention import mha_reference
from apex_tpu.transformer.context_parallel import (
    ring_attention,
    ulysses_attention,
)

B, N, S, D = 2, 4, 64, 16
CP = 4


def _mesh():
    return Mesh(np.array(jax.devices()[:CP]), ("cp",))


def _qkv(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, N, S, D)) for k in ks)


def _sharded(fn, mesh):
    spec = P(None, None, "cp", None)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense_reference(causal):
    q, k, v = _qkv(0)
    mesh = _mesh()
    fn = _sharded(
        functools.partial(ring_attention, axis_name="cp", causal=causal,
                          block_q=8, block_k=8),
        mesh,
    )
    out = fn(q, k, v)
    ref = mha_reference(q, k, v, causal=causal)
    assert jnp.abs(out - ref).max() < 2e-5


@pytest.mark.parametrize("causal", [False, True])
def test_ring_grads_match_dense_reference(causal):
    q, k, v = _qkv(1)
    mesh = _mesh()
    ring = _sharded(
        functools.partial(ring_attention, axis_name="cp", causal=causal,
                          block_q=8, block_k=8),
        mesh,
    )
    gf = jax.grad(lambda q, k, v: jnp.sum(ring(q, k, v) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(
        lambda q, k, v: jnp.sum(mha_reference(q, k, v, causal=causal) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(gf, gr):
        assert jnp.abs(a - b).max() < 5e-4


def test_ring_jits_and_composes_with_jit():
    q, k, v = _qkv(2)
    mesh = _mesh()
    fn = jax.jit(_sharded(
        functools.partial(ring_attention, axis_name="cp", causal=True,
                          block_q=8, block_k=8),
        mesh,
    ))
    out = fn(q, k, v)
    ref = mha_reference(q, k, v, causal=True)
    assert jnp.abs(out - ref).max() < 2e-5


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense_reference(causal):
    q, k, v = _qkv(3)
    mesh = _mesh()
    fn = _sharded(
        functools.partial(ulysses_attention, axis_name="cp", causal=causal),
        mesh,
    )
    out = fn(q, k, v)
    ref = mha_reference(q, k, v, causal=causal)
    assert jnp.abs(out - ref).max() < 2e-5


def test_ulysses_grads_match_dense_reference():
    q, k, v = _qkv(4)
    mesh = _mesh()
    uly = _sharded(
        functools.partial(ulysses_attention, axis_name="cp", causal=True),
        mesh,
    )
    gf = jax.grad(lambda q, k, v: jnp.sum(uly(q, k, v) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(
        lambda q, k, v: jnp.sum(mha_reference(q, k, v, causal=True) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(gf, gr):
        assert jnp.abs(a - b).max() < 5e-4


def test_ulysses_head_divisibility_check():
    q, k, v = _qkv(5)
    mesh = _mesh()
    bad = shard_map(
        functools.partial(ulysses_attention, axis_name="cp"),
        mesh=mesh,
        in_specs=(P(None, None, "cp", None),) * 3,
        out_specs=P(None, None, "cp", None),
    )
    with pytest.raises(ValueError, match="divisible"):
        bad(q[:, :3], k[:, :3], v[:, :3])  # 3 heads, cp=4


def test_ulysses_dropout_runs_and_is_seeded():
    q, k, v = _qkv(6)
    mesh = _mesh()
    fn = _sharded(
        functools.partial(ulysses_attention, axis_name="cp",
                          dropout_p=0.2, dropout_seed=7),
        mesh,
    )
    o1, o2 = fn(q, k, v), fn(q, k, v)
    assert jnp.abs(o1 - o2).max() == 0.0  # same seed -> deterministic
    fn2 = _sharded(
        functools.partial(ulysses_attention, axis_name="cp",
                          dropout_p=0.2, dropout_seed=8),
        mesh,
    )
    assert jnp.abs(fn2(q, k, v) - o1).max() > 0.0


# ---------------------------------------------------------------------------
# zigzag ring (causal load balance)
# ---------------------------------------------------------------------------


def _zig(x, perm):
    return x[:, :, perm, :]


def test_zigzag_indices_shape_and_inverse():
    from apex_tpu.transformer.context_parallel import zigzag_indices

    perm, inv = zigzag_indices(S, CP)
    assert sorted(perm.tolist()) == list(range(S))
    assert (perm[inv] == np.arange(S)).all()
    # rank 0's shard = chunks 0 and 2cp-1
    h = S // (2 * CP)
    s_loc = S // CP
    assert perm[:h].tolist() == list(range(0, h))
    assert perm[h:s_loc].tolist() == list(range((2 * CP - 1) * h, 2 * CP * h))
    with pytest.raises(ValueError, match="chunks"):
        zigzag_indices(10, 4)


def test_zigzag_ring_matches_dense_reference():
    from apex_tpu.transformer.context_parallel import zigzag_indices

    q, k, v = _qkv(7)
    perm, inv = zigzag_indices(S, CP)
    mesh = _mesh()
    fn = _sharded(
        functools.partial(ring_attention, axis_name="cp", causal=True,
                          zigzag=True, block_q=8, block_k=8),
        mesh,
    )
    out = fn(_zig(q, perm), _zig(k, perm), _zig(v, perm))[:, :, inv, :]
    ref = mha_reference(q, k, v, causal=True)
    assert jnp.abs(out - ref).max() < 2e-5


def test_zigzag_ring_grads_match_dense_reference():
    from apex_tpu.transformer.context_parallel import zigzag_indices

    q, k, v = _qkv(8)
    perm, inv = zigzag_indices(S, CP)
    mesh = _mesh()
    ring = _sharded(
        functools.partial(ring_attention, axis_name="cp", causal=True,
                          zigzag=True, block_q=8, block_k=8),
        mesh,
    )

    def loss_zig(q, k, v):
        out = ring(_zig(q, perm), _zig(k, perm), _zig(v, perm))
        return jnp.sum(out[:, :, inv, :] ** 2)

    gf = jax.grad(loss_zig, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(
        lambda q, k, v: jnp.sum(mha_reference(q, k, v, causal=True) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(gf, gr):
        assert jnp.abs(a - b).max() < 5e-4


def test_zigzag_noncausal_falls_back_to_plain_ring():
    q, k, v = _qkv(9)
    mesh = _mesh()
    plain = _sharded(
        functools.partial(ring_attention, axis_name="cp", causal=False,
                          block_q=8, block_k=8),
        mesh,
    )
    zig = _sharded(
        functools.partial(ring_attention, axis_name="cp", causal=False,
                          zigzag=True, block_q=8, block_k=8),
        mesh,
    )
    assert jnp.abs(plain(q, k, v) - zig(q, k, v)).max() == 0.0


# ---------------------------------------------------------------------------
# end-to-end: GPT with context-parallel ring attention
# ---------------------------------------------------------------------------


def _cp_gpt_cfg(**kw):
    from apex_tpu.transformer.testing import GPTConfig

    return GPTConfig(
        num_layers=2, hidden_size=32, num_attention_heads=4, vocab_size=64,
        max_position_embeddings=S, hidden_dropout=0.0, attention_dropout=0.0,
        apply_query_key_layer_scaling=False, **kw,
    )


@pytest.mark.parametrize("zigzag", [False, True])
def test_gpt_context_parallel_matches_dense(zigzag):
    """Full GPT loss + param grads with the sequence sharded end-to-end
    over cp=4 (ring attention, global position ids, psum'd loss) must
    equal the dense single-device model."""
    from apex_tpu.transformer.context_parallel import zigzag_indices
    from apex_tpu.transformer.testing import init_gpt_params
    from apex_tpu.transformer.testing.standalone_transformer_lm import gpt_loss

    cfg_cp = _cp_gpt_cfg(context_parallel_axis="cp",
                         context_parallel_zigzag=zigzag)
    cfg_dense = _cp_gpt_cfg()
    params = init_gpt_params(cfg_dense, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, S), 0, 64)
    labels = jnp.roll(tokens, -1, axis=1)
    if zigzag:
        perm, _ = zigzag_indices(S, CP)
        tokens_sh, labels_sh = tokens[:, perm], labels[:, perm]
    else:
        tokens_sh, labels_sh = tokens, labels

    mesh = _mesh()
    tspec = P(None, "cp")
    pspec = jax.tree_util.tree_map(lambda _: P(), params)
    loss_cp = shard_map(
        lambda p, t, l: gpt_loss(cfg_cp, p, t, l),
        mesh=mesh, in_specs=(pspec, tspec, tspec), out_specs=P(),
    )

    lc = loss_cp(params, tokens_sh, labels_sh)
    ld = gpt_loss(cfg_dense, params, tokens, labels)
    assert jnp.abs(lc - ld) < 1e-5

    gc = jax.grad(lambda p: loss_cp(p, tokens_sh, labels_sh))(params)
    gd = jax.grad(lambda p: gpt_loss(cfg_dense, p, tokens, labels))(params)
    flat_c = jax.tree_util.tree_leaves(gc)
    flat_d = jax.tree_util.tree_leaves(gd)
    for a, b in zip(flat_c, flat_d):
        assert jnp.abs(a - b).max() < 2e-4


def test_gpt_context_parallel_validations():
    from apex_tpu.transformer.testing import init_gpt_params
    from apex_tpu.transformer.testing.standalone_transformer_lm import gpt_loss

    mesh = _mesh()
    cfg = _cp_gpt_cfg(context_parallel_axis="cp", sequence_parallel=True)
    params = init_gpt_params(_cp_gpt_cfg(), jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, S), 0, 64)
    fn = shard_map(
        lambda p, t: gpt_loss(cfg, p, t, t),
        mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(), params),
                  P(None, "cp")),
        out_specs=P(),
    )
    with pytest.raises(ValueError, match="mutually exclusive"):
        fn(params, tokens)


@pytest.mark.parametrize("bad_kw,match", [
    (dict(apply_query_key_layer_scaling=True,
          compute_dtype=jnp.float16), "static softmax scale"),
    (dict(use_flash_attention=False), "cannot be honored"),
])
def test_gpt_context_parallel_more_validations(bad_kw, match):
    from apex_tpu.transformer.testing import GPTConfig, init_gpt_params
    from apex_tpu.transformer.testing.standalone_transformer_lm import gpt_loss

    mesh = _mesh()
    base = dict(num_layers=2, hidden_size=32, num_attention_heads=4,
                vocab_size=64, max_position_embeddings=S,
                hidden_dropout=0.0, attention_dropout=0.0,
                apply_query_key_layer_scaling=False,
                context_parallel_axis="cp")
    base.update(bad_kw)
    cfg = GPTConfig(**base)
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, S), 0, 64)
    fn = shard_map(
        lambda p, t: gpt_loss(cfg, p, t, t),
        mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(), params),
                  P(None, "cp")),
        out_specs=P(),
    )
    with pytest.raises(ValueError, match=match):
        fn(params, tokens)


def test_gpt_context_parallel_attention_dropout_raises():
    from apex_tpu.transformer.testing import GPTConfig, init_gpt_params
    from apex_tpu.transformer.testing.standalone_transformer_lm import gpt_loss

    mesh = _mesh()
    cfg = GPTConfig(
        num_layers=2, hidden_size=32, num_attention_heads=4, vocab_size=64,
        max_position_embeddings=S, hidden_dropout=0.0, attention_dropout=0.2,
        apply_query_key_layer_scaling=False, context_parallel_axis="cp",
    )
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, S), 0, 64)
    fn = shard_map(
        lambda p, t, k: gpt_loss(cfg, p, t, t, dropout_key=k,
                                 deterministic=False),
        mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(), params),
                  P(None, "cp"), P()),
        out_specs=P(),
    )
    with pytest.raises(ValueError, match="attention dropout"):
        fn(params, tokens, jax.random.PRNGKey(3))


def test_gpt_context_parallel_position_table_guard():
    """An undersized position table must raise, not silently clamp."""
    from apex_tpu.transformer.testing import GPTConfig, init_gpt_params
    from apex_tpu.transformer.testing.standalone_transformer_lm import gpt_loss

    mesh = _mesh()
    cfg = GPTConfig(
        num_layers=2, hidden_size=32, num_attention_heads=4, vocab_size=64,
        max_position_embeddings=S // 2,  # global seq S won't fit
        hidden_dropout=0.0, attention_dropout=0.0,
        apply_query_key_layer_scaling=False, context_parallel_axis="cp",
    )
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, S), 0, 64)
    fn = shard_map(
        lambda p, t: gpt_loss(cfg, p, t, t),
        mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(), params),
                  P(None, "cp")),
        out_specs=P(),
    )
    with pytest.raises(ValueError, match="max_position_embeddings"):
        fn(params, tokens)


def test_gpt_context_parallel_tileability_guard():
    """Non-kernel-tileable head dim must fail loudly on every backend."""
    from apex_tpu.transformer.testing import GPTConfig, init_gpt_params
    from apex_tpu.transformer.testing.standalone_transformer_lm import gpt_loss

    mesh = _mesh()
    cfg = GPTConfig(
        num_layers=1, hidden_size=576, num_attention_heads=2,  # hn=288>256
        vocab_size=64, max_position_embeddings=S,
        hidden_dropout=0.0, attention_dropout=0.0,
        apply_query_key_layer_scaling=False, context_parallel_axis="cp",
    )
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, S), 0, 64)
    fn = shard_map(
        lambda p, t: gpt_loss(cfg, p, t, t),
        mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(), params),
                  P(None, "cp")),
        out_specs=P(),
    )
    with pytest.raises(ValueError, match="tileable"):
        fn(params, tokens)


@pytest.mark.parametrize("cp", [2, 8])
def test_zigzag_ring_other_axis_sizes(cp):
    """Edge parities: cp=2 (single non-diagonal step) and cp=8 (every
    device of the harness; wrap-around selections on most steps)."""
    from apex_tpu.transformer.context_parallel import zigzag_indices

    q, k, v = _qkv(10)
    perm, inv = zigzag_indices(S, cp)
    mesh = Mesh(np.array(jax.devices()[:cp]), ("cp",))
    fn = _sharded(
        functools.partial(ring_attention, axis_name="cp", causal=True,
                          zigzag=True, block_q=8, block_k=8),
        mesh,
    )
    out = fn(_zig(q, perm), _zig(k, perm), _zig(v, perm))[:, :, inv, :]
    ref = mha_reference(q, k, v, causal=True)
    assert jnp.abs(out - ref).max() < 2e-5
    # grads too (the A/D selection differs per device at every step)
    gq = jax.grad(lambda q: jnp.sum(
        fn(_zig(q, perm), _zig(k, perm), _zig(v, perm))[:, :, inv, :] ** 2
    ))(q)
    gr = jax.grad(lambda q: jnp.sum(
        mha_reference(q, k, v, causal=True) ** 2))(q)
    assert jnp.abs(gq - gr).max() < 5e-4
