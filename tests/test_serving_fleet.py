"""Fleet-scale serving: deadline-aware multi-replica router, drain/join
weight swaps, replica-kill migration (apex_tpu.serving.fleet).

Coverage map (the ISSUE-11 acceptance surface):

- routing: requests spread by feasibility x load across replicas, the
  loaded replica is skipped, every completion token-identical to the
  dense greedy reference;
- read-only costing: `AdmissionController.probe` / `ServingEngine.probe`
  leave the hysteresis latch, rejection counters, and request state
  untouched (the router must not act through admission side effects);
- fleet-level refusal: when no replica is feasible the request is
  finalized REJECTED with the typed NO_FEASIBLE_REPLICA reason naming
  each replica's own refusal code;
- THE migration proof: 3 CPU-faked replicas, one killed mid-storm by
  `ServingChaos.kill_replica_at` — every in-flight request of the dead
  replica completes token-identically to an undisturbed run
  (requests_lost == 0), riding the replay carrier through the
  survivors' admission control with original deadlines intact;
- drain/join: a rolling weight update drains each replica, swaps
  weights via `cast_params_for_inference`, rejoins — zero dropped
  requests, and post-update requests decode per the NEW weights;
- replica_id tagging: every engine-side request_end/hang/serving_step
  event in the shared sink carries its replica (TaggedRecorder), and
  the fleet summary carries the per-replica breakdown;
- CI wiring: serving_check fleet legs pass, compare_bench gates
  fleet SLO attainment and requests_lost (absolute tolerance — one
  lost request IS a regression).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.resilience import ChaosError, RetryPolicy, ServingChaos
from apex_tpu.serving import (
    AdmissionConfig,
    AdmissionController,
    DegradationPolicy,
    RejectionCode,
    RejectionError,
    ReplicaFleet,
    ReplicaState,
    Request,
    RequestStatus,
    SchedulerError,
    ServingEngine,
    VirtualClock,
    is_terminal,
    reference_decode,
)
from apex_tpu.telemetry import RingBufferRecorder, TaggedRecorder

from apex_tpu.transformer.testing import GPTConfig, init_gpt_params


def _tiny_cfg(dtype=jnp.float32):
    return GPTConfig(
        num_layers=2, hidden_size=64, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=64,
        hidden_dropout=0.0, attention_dropout=0.0,
        params_dtype=jnp.float32, compute_dtype=dtype)


@pytest.fixture(scope="module")
def tiny_model():
    cfg = _tiny_cfg()
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    # position-sensitive continuations (see test_serving.py)
    params["embedding"]["position"] = params["embedding"]["position"] * 40.0
    return cfg, params


def _toks(rng, n, vocab=128):
    return [int(t) for t in rng.integers(0, vocab, size=n)]


# ---------------------------------------------------------------------------
# read-only probing (satellite: the router must not mutate)
# ---------------------------------------------------------------------------

def test_admission_probe_is_read_only():
    """probe() returns the verdict check() would, without flipping the
    hysteresis latch, counting rejections, or moving high-water marks —
    and estimated_step_time_s is the documented read-only EWMA view."""
    ctl = AdmissionController(
        AdmissionConfig(max_queue=8, high_watermark=0.5,
                        low_watermark=0.25, step_time_init_s=0.010),
        n_slots=2)
    assert ctl.estimated_step_time_s == pytest.approx(0.010)
    ctl.observe_step(0.010)
    assert ctl.estimated_step_time_s == ctl.est_step_s
    req = Request(prompt=[1, 2], max_new_tokens=4)
    # depth at the high watermark: probe says BACKPRESSURE...
    r = ctl.probe(req, queue_depth=4, queued_tokens=24)
    assert r is not None and r.code is RejectionCode.BACKPRESSURE
    # ...but nothing latched or counted
    assert not ctl.backpressure
    assert ctl.rejected == 0 and ctl.max_queue_seen == 0
    # feasible probe agrees with check
    assert ctl.probe(req, queue_depth=0, queued_tokens=0) is None
    # deadline-infeasible probe carries the same typed reason
    doomed = Request(prompt=list(range(8)), max_new_tokens=8,
                     latency_budget_ms=10.0)
    r = ctl.probe(doomed, queue_depth=0, queued_tokens=0)
    assert r is not None and r.code is RejectionCode.DEADLINE_INFEASIBLE
    assert ctl.rejected == 0
    # check() on the same inputs DOES latch and count
    r = ctl.check(req, queue_depth=4, queued_tokens=24)
    assert r is not None and r.code is RejectionCode.BACKPRESSURE
    assert ctl.backpressure and ctl.rejected == 1
    # with the latch ON, probe mirrors the latched state above low
    assert ctl.probe(req, queue_depth=3,
                     queued_tokens=18).code is RejectionCode.BACKPRESSURE
    # ...and the would-release state back at low, still without mutating
    assert ctl.probe(req, queue_depth=2, queued_tokens=12) is None
    assert ctl.backpressure  # latch untouched by the probe


def test_engine_probe_is_read_only_and_costs_load(tiny_model):
    cfg, params = tiny_model
    rng = np.random.default_rng(3)
    eng = ServingEngine(cfg, params, n_slots=2, num_pages=12,
                        max_prompt_len=16,
                        admission=AdmissionConfig(max_queue=8))
    req = Request(prompt=_toks(rng, 6), max_new_tokens=4)
    reason, steps0 = eng.probe(req)
    assert reason is None
    assert steps0 == pytest.approx(6.0)  # empty engine: own prefill only
    # probing stamped/changed nothing
    assert req.t_arrival is None and req.status is RequestStatus.PENDING
    assert not eng.scheduler.waiting
    # load raises the cost: queue another request and re-probe
    other = Request(prompt=_toks(rng, 6), max_new_tokens=4)
    assert eng.try_submit(other) is None
    _, steps1 = eng.probe(req)
    assert steps1 > steps0
    # an in-flight request probes ALREADY_IN_FLIGHT (no finalize)
    reason, _ = eng.probe(other)
    assert reason is not None
    assert reason.code is RejectionCode.ALREADY_IN_FLIGHT
    assert other.status is RequestStatus.QUEUED
    # an engine-infeasible request carries the typed reason
    fat = Request(prompt=_toks(rng, 20), max_new_tokens=4)
    reason, _ = eng.probe(fat)
    assert reason is not None
    assert reason.code is RejectionCode.PROMPT_TOO_LONG
    assert fat.status is RequestStatus.PENDING  # not finalized


def test_attained_ttft_not_refused_at_readmission():
    """Review regression: a request that already produced its first
    token (preempted/recovered/migrated survivor) must not be refused
    DEADLINE_INFEASIBLE against the TTFT budget it already met — same
    rule pick_shed_victim applies."""
    ctl = AdmissionController(
        AdmissionConfig(max_queue=64, step_time_init_s=0.010),
        n_slots=1)
    # 20 prompt steps * 10ms = 200ms >> 50ms budget: infeasible fresh
    fresh = Request(prompt=list(range(20)), max_new_tokens=4,
                    ttft_budget_ms=50.0)
    r = ctl.probe(fresh, queue_depth=0, queued_tokens=0)
    assert r is not None and r.code is RejectionCode.DEADLINE_INFEASIBLE
    # the same shape with its first token attained: admissible
    survivor = Request(prompt=list(range(20)), max_new_tokens=4,
                       ttft_budget_ms=50.0)
    survivor.t_first_token = 1.0
    assert ctl.probe(survivor, queue_depth=0, queued_tokens=0) is None
    assert ctl.check(survivor, queue_depth=0, queued_tokens=0) is None


# ---------------------------------------------------------------------------
# routing
# ---------------------------------------------------------------------------

def test_router_spreads_load_and_keeps_token_identity(tiny_model):
    cfg, params = tiny_model
    rng = np.random.default_rng(7)
    reqs = [Request(prompt=_toks(rng, L), max_new_tokens=6,
                    arrival_step=i)
            for i, L in enumerate((8, 5, 11, 6, 9, 4))]
    fleet = ReplicaFleet(cfg, params, n_replicas=2, n_slots=2,
                         num_pages=12, max_prompt_len=16)
    out = fleet.generate(reqs, max_steps=2000)
    fleet.check_invariants()
    assert fleet.page_leaks() == 0
    st = fleet.last_stats
    assert st["completed"] == len(reqs) and st["requests_lost"] == 0
    # both replicas took work (lowest-cost dispatch alternates under
    # symmetric load) and attribution reached the summary
    assert {r.replica_id for r in reqs} == {0, 1}
    assert sum(st["per_replica"][k]["served"]
               for k in ("0", "1")) == len(reqs)
    for r in reqs:
        assert out[r.rid] == reference_decode(
            cfg, params, r.prompt, r.max_new_tokens), r.rid


def test_router_skips_loaded_replica(tiny_model):
    """A replica carrying a deep queue costs more; a fresh request
    routes to the empty one."""
    cfg, params = tiny_model
    rng = np.random.default_rng(11)
    fleet = ReplicaFleet(cfg, params, n_replicas=2, n_slots=1,
                         num_pages=8, max_prompt_len=16)
    # load replica 0 directly (behind the router's back)
    for _ in range(3):
        hog = Request(prompt=_toks(rng, 6), max_new_tokens=6)
        assert fleet.replicas[0].engine.try_submit(hog) is None
    fresh = Request(prompt=_toks(rng, 6), max_new_tokens=6)
    rep, refusals = fleet.route(fresh)
    assert rep is fleet.replicas[1] and not refusals


def test_no_feasible_replica_is_typed_fleet_rejection(tiny_model):
    """Saturate both replicas' admission doors: the fleet refuses with
    NO_FEASIBLE_REPLICA, the detail names every replica's own code,
    the request is finalized REJECTED with a reject event."""
    cfg, params = tiny_model
    rng = np.random.default_rng(13)
    ring = RingBufferRecorder()
    fleet = ReplicaFleet(
        cfg, params, n_replicas=2, n_slots=1, num_pages=8,
        max_prompt_len=16, sink=ring,
        admission=AdmissionConfig(max_queue=4, high_watermark=0.5,
                                  low_watermark=0.25))
    # two queued per replica -> both at the high watermark (2 of 4)
    for _ in range(4):
        assert fleet.try_submit(
            Request(prompt=_toks(rng, 4), max_new_tokens=4)) is None
    bounced = Request(prompt=_toks(rng, 4), max_new_tokens=4)
    reason = fleet.try_submit(bounced)
    assert reason is not None
    assert reason.code is RejectionCode.NO_FEASIBLE_REPLICA
    assert reason.detail["replicas"] == {
        "0": "backpressure", "1": "backpressure"}
    assert bounced.status is RequestStatus.REJECTED
    assert bounced.end_reason == "no_feasible_replica"
    rejects = ring.events("reject")
    assert any(r["rid"] == bounced.rid
               and r["code"] == "no_feasible_replica" for r in rejects)
    # the raising door throws the same typed error
    with pytest.raises(RejectionError, match="no feasible replica"):
        fleet.submit(Request(prompt=_toks(rng, 4), max_new_tokens=4))
    # drain everything; the fleet ends clean
    fleet.generate([], max_steps=500)
    fleet.check_invariants()
    assert fleet.page_leaks() == 0


# ---------------------------------------------------------------------------
# replica kill + migration (THE acceptance proof)
# ---------------------------------------------------------------------------

def test_kill_replica_mid_storm_migrates_token_identical(tiny_model):
    """3 replicas, one killed mid-storm: its in-flight requests migrate
    to the survivors riding the replay carrier and complete
    BYTE-identically to an undisturbed run (the dense greedy
    reference); requests_lost == 0; events are attributable."""
    cfg, params = tiny_model
    rng = np.random.default_rng(23)
    reqs = [Request(prompt=_toks(rng, int(rng.integers(4, 12))),
                    max_new_tokens=6, arrival_step=i)
            for i in range(9)]
    chaos = ServingChaos().kill_replica_at(1, 6)
    ring = RingBufferRecorder()
    fleet = ReplicaFleet(cfg, params, n_replicas=3, sink=ring,
                         chaos=chaos, n_slots=2, num_pages=12,
                         max_prompt_len=24)
    out = fleet.generate(reqs, max_steps=3000)
    fleet.check_invariants()
    assert chaos.faults_fired == [("kill_replica", 1, 6)]
    st = fleet.last_stats
    assert st["replica_deaths"] == 1
    assert st["requests_lost"] == 0
    assert st["migrated"] >= 1
    assert st["migrated"] == st["migration_readmitted"]
    assert st["by_status"]["completed"] == len(reqs)
    assert fleet.replicas[1].state is ReplicaState.DEAD
    assert st["per_replica"]["1"]["state"] == "dead"
    assert st["per_replica"]["1"]["migrated_out"] == st["migrated"]
    # the dead replica's work survived token-identically — migrated
    # requests kept their generated tokens and replayed on a survivor
    downs = ring.events("replica_down")
    assert len(downs) == 1 and downs[0]["replica_id"] == 1
    migrated_rids = {e["rid"] for e in ring.events("migrate")}
    assert migrated_rids == set(downs[0]["rids"]) and migrated_rids
    for r in reqs:
        assert r.status is RequestStatus.COMPLETED, r.rid
        assert out[r.rid] == reference_decode(
            cfg, params, r.prompt, r.max_new_tokens), r.rid
        if r.rid in migrated_rids:
            assert r.restarts == 1 and r.replica_id != 1
    assert fleet.page_leaks() == 0
    # every engine-side request_end carries its replica
    for e in ring.events("request_end"):
        assert "replica_id" in e, e


def test_migrated_requests_honor_original_deadlines(tiny_model):
    """Migration preserves t_arrival: a migrant whose latency budget
    expires while waiting for placement is finalized TIMED_OUT by the
    fleet (never silently dropped), under the migration RetryPolicy's
    pacing."""
    cfg, params = tiny_model
    rng = np.random.default_rng(29)
    clock = VirtualClock(dt=1.0)
    ring = RingBufferRecorder()
    # one replica only: when it dies there is nowhere to go until the
    # budget expires
    doomed = Request(prompt=_toks(rng, 6), max_new_tokens=6,
                     latency_budget_ms=30_000.0)
    free = Request(prompt=_toks(rng, 6), max_new_tokens=6)
    chaos = ServingChaos().kill_replica_at(0, 3)
    fleet = ReplicaFleet(cfg, params, n_replicas=2, sink=ring,
                         chaos=chaos, clock=clock, n_slots=1,
                         num_pages=8, max_prompt_len=16,
                         migration_retry=RetryPolicy(attempts=1000))
    # pin both requests to replica 0 by loading it directly, then kill
    assert fleet.replicas[0].engine.try_submit(doomed) is None
    doomed.replica_id = 0
    # saturate replica 1 so migrants cannot place (single slot + queue
    # full via admission-free deep queue of long work)
    for _ in range(6):
        assert fleet.replicas[1].engine.try_submit(
            Request(prompt=_toks(rng, 8), max_new_tokens=8)) is None
    fleet.try_submit(free)
    out = fleet.generate([], max_steps=4000)  # noqa: F841 - drive it
    st = fleet.last_stats
    assert fleet.replicas[0].state is ReplicaState.DEAD
    assert is_terminal(doomed.status)
    # the doomed migrant either placed late and timed out on-engine, or
    # expired in the fleet's migration queue — both are typed TIMED_OUT
    # (the budget was virtual-clock tight); it is never lost silently
    assert doomed.status in (RequestStatus.TIMED_OUT,
                             RequestStatus.COMPLETED)
    ends = [e for e in ring.events("request_end")
            if e["rid"] == doomed.rid]
    assert len(ends) == 1


def test_migration_retry_policy_bounds_placement(tiny_model):
    """With a tight RetryPolicy attempts budget and no feasible
    survivor, migrants are finalized REJECTED(migration_exhausted)
    instead of spinning forever."""
    cfg, params = tiny_model
    rng = np.random.default_rng(31)
    ring = RingBufferRecorder()
    chaos = ServingChaos().kill_replica_at(0, 2)
    fleet = ReplicaFleet(
        cfg, params, n_replicas=2, sink=ring, chaos=chaos,
        n_slots=1, num_pages=8, max_prompt_len=16,
        admission=AdmissionConfig(max_queue=2, high_watermark=0.5,
                                  low_watermark=0.25),
        migration_retry=RetryPolicy(attempts=3))
    victim = Request(prompt=_toks(rng, 6), max_new_tokens=6)
    assert fleet.replicas[0].engine.try_submit(victim) is None
    # replica 1 saturated at its admission door: one hog in the slot,
    # one in the queue (depth 1 = high watermark for max_queue=2)
    hogs = [Request(prompt=_toks(rng, 6), max_new_tokens=6)
            for _ in range(2)]
    assert fleet.replicas[1].engine.try_submit(hogs[0]) is None
    fleet.replicas[1].engine.run_step()  # hog 0 takes the slot
    assert fleet.replicas[1].engine.try_submit(hogs[1]) is None
    fleet.generate([], max_steps=2000)
    assert victim.status is RequestStatus.REJECTED
    assert victim.end_reason == "migration_exhausted"
    exhausted = ring.events("migrate_exhausted")
    assert len(exhausted) == 1 and exhausted[0]["rid"] == victim.rid
    assert exhausted[0]["attempts"] == 3
    # the hogs themselves completed; nothing leaked on the survivor
    assert all(h.status is RequestStatus.COMPLETED for h in hogs)
    assert fleet.page_leaks() == 0


# ---------------------------------------------------------------------------
# drain / join (zero-drop weight swap)
# ---------------------------------------------------------------------------

def test_rolling_update_swaps_weights_with_zero_drops(tiny_model):
    """A rolling weight update mid-traffic: every replica drains,
    swaps via cast_params_for_inference, rejoins; zero requests
    dropped; requests submitted AFTER the update decode per the NEW
    weights (and in-flight work finished on the old ones)."""
    cfg, params = tiny_model
    params2 = jax.tree_util.tree_map(lambda x: x, params)
    params2["embedding"]["position"] = (
        params["embedding"]["position"] * 0.5)
    rng = np.random.default_rng(37)
    ring = RingBufferRecorder()
    fleet = ReplicaFleet(cfg, params, n_replicas=2, sink=ring,
                         n_slots=2, num_pages=12, max_prompt_len=16)
    phase1 = [Request(prompt=_toks(rng, 6), max_new_tokens=5,
                      arrival_step=i) for i in range(4)]
    fleet.schedule_rolling_update(params2)
    with pytest.raises(SchedulerError, match="already scheduled"):
        fleet.schedule_rolling_update(params2)
    out1 = fleet.generate(phase1, max_steps=2000)
    assert fleet.rolling_update_done
    fleet.check_invariants()
    assert fleet.page_leaks() == 0
    # zero drops: everything completed, nothing rejected/timed out
    st = fleet.last_stats
    assert st["by_status"]["completed"] == len(phase1)
    assert st["requests_lost"] == 0
    swaps = ring.events("weight_swap")
    assert [e["replica_id"] for e in swaps] == [0, 1]
    assert ring.events("rolling_update_done")
    drains = ring.events("replica_drain")
    joins = ring.events("replica_join")
    assert len(drains) == 2 and len(joins) == 2
    # a request is served wholly by one replica under one params
    # version — its tokens match exactly one of the two references
    for r in phase1:
        ref_old = reference_decode(cfg, params, r.prompt,
                                   r.max_new_tokens)
        ref_new = reference_decode(cfg, params2, r.prompt,
                                   r.max_new_tokens)
        assert out1[r.rid] in (ref_old, ref_new), r.rid
    # post-update traffic decodes per the NEW weights on every replica
    phase2 = [Request(prompt=_toks(rng, 6), max_new_tokens=5)
              for _ in range(4)]
    out2 = fleet.generate(phase2, max_steps=2000)
    assert {r.replica_id for r in phase2} == {0, 1}
    for r in phase2:
        assert out2[r.rid] == reference_decode(
            cfg, params2, r.prompt, r.max_new_tokens), r.rid


def test_drain_excludes_replica_from_routing_until_join(tiny_model):
    cfg, params = tiny_model
    rng = np.random.default_rng(41)
    fleet = ReplicaFleet(cfg, params, n_replicas=2, n_slots=1,
                         num_pages=8, max_prompt_len=16)
    fleet.drain(0)
    assert fleet.replicas[0].state is ReplicaState.DRAINING
    with pytest.raises(SchedulerError, match="not active"):
        fleet.drain(0)
    for _ in range(3):
        req = Request(prompt=_toks(rng, 5), max_new_tokens=4)
        assert fleet.try_submit(req) is None
        assert req.replica_id == 1
    # idle drained replica joins immediately (no swap)
    assert fleet.try_join(0)
    assert fleet.replicas[0].state is ReplicaState.ACTIVE
    fleet.generate([], max_steps=500)
    assert fleet.page_leaks() == 0


def test_restart_replica_rejoins_after_death(tiny_model):
    """The replica-restart path: a DEAD replica comes back as a fresh
    engine (same weights/policies) and takes traffic again."""
    cfg, params = tiny_model
    rng = np.random.default_rng(43)
    chaos = ServingChaos().kill_replica_at(0, 2)
    ring = RingBufferRecorder()
    fleet = ReplicaFleet(cfg, params, n_replicas=2, sink=ring,
                         chaos=chaos, n_slots=2, num_pages=12,
                         max_prompt_len=16)
    reqs = [Request(prompt=_toks(rng, 6), max_new_tokens=5,
                    arrival_step=i) for i in range(4)]
    fleet.generate(reqs, max_steps=2000)
    assert fleet.replicas[0].state is ReplicaState.DEAD
    assert fleet.last_stats["requests_lost"] == 0
    fleet.restart_replica(0)
    assert fleet.replicas[0].state is ReplicaState.ACTIVE
    assert ring.events("replica_restart")
    late = [Request(prompt=_toks(rng, 6), max_new_tokens=5)
            for _ in range(4)]
    out = fleet.generate(late, max_steps=2000)
    assert {r.replica_id for r in late} == {0, 1}
    for r in late:
        assert out[r.rid] == reference_decode(
            cfg, params, r.prompt, r.max_new_tokens)


# ---------------------------------------------------------------------------
# replica_id tagging (satellite)
# ---------------------------------------------------------------------------

def test_tagged_recorder_injects_tags_record_keys_win():
    ring = RingBufferRecorder()
    tagged = TaggedRecorder(ring, replica_id=3)
    tagged.record({"event": "request_end", "rid": 1})
    tagged.record({"event": "custom", "replica_id": 9})  # rec wins
    tagged.add_scalar("loss", 1.5, 10)
    assert ring.events("request_end")[0]["replica_id"] == 3
    assert ring.events("custom")[0]["replica_id"] == 9
    sc = ring.events("scalar")[0]
    assert sc["replica_id"] == 3 and sc["name"] == "loss"
    # dict-style tags compose with kwargs
    t2 = TaggedRecorder(ring, {"pod": "a"}, replica_id=0)
    t2.record({"event": "x"})
    rec = ring.events("x")[0]
    # every sink stamps t_wall (unified schema); tags compose around it
    assert rec.pop("t_wall") > 0
    assert rec == {"event": "x", "pod": "a", "replica_id": 0}


def test_fleet_events_are_replica_attributable(tiny_model):
    """Engine-side telemetry (request_end, serving_step, degrade/shed)
    carries replica_id through the shared sink; fleet-level events
    carry it explicitly."""
    cfg, params = tiny_model
    rng = np.random.default_rng(47)
    ring = RingBufferRecorder()
    fleet = ReplicaFleet(cfg, params, n_replicas=2, sink=ring,
                         n_slots=1, num_pages=8, max_prompt_len=16,
                         record_every=1)
    reqs = [Request(prompt=_toks(rng, 5), max_new_tokens=4,
                    arrival_step=i) for i in range(4)]
    fleet.generate(reqs, max_steps=1000)
    ends = ring.events("request_end")
    assert len(ends) == 4
    assert {e["replica_id"] for e in ends} == {0, 1}
    for e in ring.events("serving_step"):
        assert e["replica_id"] in (0, 1)
    for e in ring.events("dispatch"):
        assert e["replica_id"] in (0, 1)
    # summary carries the per-replica breakdown alongside fleet totals
    st = fleet.last_stats
    assert set(st["per_replica"]) == {"0", "1"}
    for k, row in st["per_replica"].items():
        assert {"state", "steps", "served", "completed", "occupancy",
                "migrated_out", "page_leaks"} <= set(row)


# ---------------------------------------------------------------------------
# CI wiring: serving_check fleet legs + compare_bench fleet gates
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("leg", ["fleet_kill_migrate",
                                 "fleet_drain_join"])
def test_serving_check_fleet_legs_pass(leg):
    import tools.serving_check as sc

    assert sc.main(["--self", "--check", leg]) == 0


def test_compare_bench_gates_fleet_legs():
    """fleet SLO attainment and requests_lost ride compare_bench:
    attainment drops past threshold regress; requests_lost is gated
    ABSOLUTELY — one lost request from a zero base is a regression,
    not sub-threshold noise. The committed CPU smoke artifact parses
    and carries the schema."""
    import json

    from tools.compare_bench import ABS_TOLERANCE, compare, extract_legs

    base = {"serving_fleet": {
        "slo_attainment": 0.95, "goodput_tokens_per_sec": 100.0,
        "requests_lost": 0, "ttft_p99_ms": 40.0}}
    legs = extract_legs(base)
    assert legs["fleet_slo_attainment"] == 0.95
    assert legs["fleet_goodput"] == 100.0
    assert legs["fleet_requests_lost"] == 0.0  # oriented: lower better
    assert legs["fleet_ttft_p99_ms"] == -40.0
    assert "fleet_requests_lost" in ABS_TOLERANCE
    lost_one = {"serving_fleet": {
        "slo_attainment": 0.95, "goodput_tokens_per_sec": 100.0,
        "requests_lost": 1, "ttft_p99_ms": 40.0}}
    rep = compare(base, lost_one, threshold=0.05)
    assert {r["leg"] for r in rep["regressions"]} == {
        "fleet_requests_lost"}
    worse = {"serving_fleet": {
        "slo_attainment": 0.7, "goodput_tokens_per_sec": 80.0,
        "requests_lost": 0, "ttft_p99_ms": 40.0}}
    rep = compare(base, worse, threshold=0.05)
    assert {r["leg"] for r in rep["regressions"]} == {
        "fleet_slo_attainment", "fleet_goodput"}
    art = json.load(open("bench_artifacts/serving_fleet_cpu_smoke.json"))
    leg = art["serving_fleet"]
    assert leg["requests_lost"] == 0
    assert leg["replica_deaths"] == 1
    assert leg["migrated"] >= 1
    assert leg["slo_attainment"] is not None
    assert leg["page_leaks"] == 0
    assert extract_legs(art)["fleet_requests_lost"] == 0.0


def test_resubmit_after_fleet_rejection_is_fresh_attempt(tiny_model):
    """Review regression: resubmitting a fleet-rejected (terminal)
    request must start a fresh lifecycle attempt — not trip the
    double-finalize guard — keeping the original t_arrival; and a
    duplicate submit of in-flight work is refused ALREADY_IN_FLIGHT
    without disturbing the live submission."""
    cfg, params = tiny_model
    rng = np.random.default_rng(61)
    ring = RingBufferRecorder()
    fleet = ReplicaFleet(
        cfg, params, n_replicas=2, n_slots=1, num_pages=8,
        max_prompt_len=16, sink=ring,
        admission=AdmissionConfig(max_queue=4, high_watermark=0.5,
                                  low_watermark=0.25))
    hogs = [Request(prompt=_toks(rng, 4), max_new_tokens=4)
            for _ in range(4)]
    for h in hogs:
        assert fleet.try_submit(h) is None
    bounced = Request(prompt=_toks(rng, 4), max_new_tokens=4)
    r = fleet.try_submit(bounced)
    assert r is not None and r.code is RejectionCode.NO_FEASIBLE_REPLICA
    assert bounced.status is RequestStatus.REJECTED
    t_first = bounced.t_arrival
    # a RUNNING duplicate is refused without finalizing
    fleet.run_boundary()
    running = next(h for h in hogs
                   if h.status is RequestStatus.RUNNING)
    dup = fleet.try_submit(running)
    assert dup is not None
    assert dup.code is RejectionCode.ALREADY_IN_FLIGHT
    assert running.status is RequestStatus.RUNNING  # intact
    fleet.generate([], max_steps=500)  # drain the hogs
    # resubmit the SAME rejected object: fresh attempt, original stamp
    assert fleet.try_submit(bounced) is None
    assert bounced.status is RequestStatus.QUEUED
    assert bounced.t_arrival == t_first
    fleet.generate([], max_steps=500)
    assert bounced.status is RequestStatus.COMPLETED
    assert list(bounced.out_tokens) == reference_decode(
        cfg, params, bounced.prompt, bounced.max_new_tokens)
    ends = [e for e in ring.events("request_end")
            if e["rid"] == bounced.rid]
    assert [e["status"] for e in ends] == ["rejected", "completed"]


def test_replica_dead_during_rolling_update_restarts_on_new_weights(
        tiny_model):
    """Review regression: a replica that dies mid-update misses its
    swap; restart_replica must apply the missed swap — a restarted
    replica never rejoins the router serving the pre-update weights."""
    cfg, params = tiny_model
    params2 = jax.tree_util.tree_map(lambda x: x, params)
    params2["embedding"]["position"] = (
        params["embedding"]["position"] * 0.5)
    rng = np.random.default_rng(67)
    ring = RingBufferRecorder()
    # kill replica 0 at boundary 0 — while the update is draining it
    chaos = ServingChaos().kill_replica_at(0, 0)
    fleet = ReplicaFleet(cfg, params, n_replicas=2, sink=ring,
                         chaos=chaos, n_slots=2, num_pages=12,
                         max_prompt_len=16)
    reqs = [Request(prompt=_toks(rng, 6), max_new_tokens=5,
                    arrival_step=i) for i in range(3)]
    fleet.schedule_rolling_update(params2)
    fleet.generate(reqs, max_steps=2000)
    assert fleet.rolling_update_done
    assert fleet.replicas[0].state is ReplicaState.DEAD
    assert fleet.last_stats["requests_lost"] == 0
    # replica 1 swapped in the wave; replica 0 missed its swap...
    assert fleet.replicas[1].swaps == 1
    assert fleet.replicas[0].swaps == 0
    fleet.restart_replica(0)
    # ...and received it at restart
    assert fleet.replicas[0].swaps == 1
    swaps = ring.events("weight_swap")
    assert sorted(e["replica_id"] for e in swaps) == [0, 1]
    late = [Request(prompt=_toks(rng, 6), max_new_tokens=5)
            for _ in range(4)]
    out = fleet.generate(late, max_steps=2000)
    assert {r.replica_id for r in late} == {0, 1}
    for r in late:  # NEW weights everywhere, incl. the restarted one
        assert out[r.rid] == reference_decode(
            cfg, params2, r.prompt, r.max_new_tokens), r.rid


def test_update_scheduled_after_death_still_reaches_restart(tiny_model):
    """Review regression: a replica already DEAD when the rolling
    update is scheduled misses the wave — restart_replica must still
    deliver its swap (never revive on pre-update weights)."""
    cfg, params = tiny_model
    params2 = jax.tree_util.tree_map(lambda x: x, params)
    params2["embedding"]["position"] = (
        params["embedding"]["position"] * 0.5)
    rng = np.random.default_rng(71)
    chaos = ServingChaos().kill_replica_at(0, 1)
    fleet = ReplicaFleet(cfg, params, n_replicas=2, chaos=chaos,
                         n_slots=2, num_pages=12, max_prompt_len=16)
    reqs = [Request(prompt=_toks(rng, 6), max_new_tokens=5)
            for _ in range(2)]
    fleet.generate(reqs, max_steps=2000)         # replica 0 dies here
    assert fleet.replicas[0].state is ReplicaState.DEAD
    fleet.schedule_rolling_update(params2)       # AFTER the death
    fleet.generate([], max_steps=500)            # wave over survivors
    assert fleet.rolling_update_done
    assert fleet.replicas[1].swaps == 1
    fleet.restart_replica(0)
    assert fleet.replicas[0].swaps == 1          # missed swap applied
    late = [Request(prompt=_toks(rng, 6), max_new_tokens=5)
            for _ in range(4)]
    out = fleet.generate(late, max_steps=2000)
    assert {r.replica_id for r in late} == {0, 1}
    for r in late:
        assert out[r.rid] == reference_decode(
            cfg, params2, r.prompt, r.max_new_tokens), r.rid


def test_migrant_resubmission_refused_in_flight(tiny_model):
    """Review regression: a request sitting in the fleet's migration
    queue (status PENDING, fleet-owned) must refuse resubmission —
    double placement would strand a stale migrant / double-finalize."""
    cfg, params = tiny_model
    rng = np.random.default_rng(73)
    chaos = ServingChaos().kill_replica_at(0, 1)
    fleet = ReplicaFleet(
        cfg, params, n_replicas=2, chaos=chaos, n_slots=1,
        num_pages=8, max_prompt_len=16,
        admission=AdmissionConfig(max_queue=2, high_watermark=0.5,
                                  low_watermark=0.25))
    victim = Request(prompt=_toks(rng, 6), max_new_tokens=6)
    assert fleet.replicas[0].engine.try_submit(victim) is None
    # block the survivor so the migrant stays queued at the fleet
    hogs = [Request(prompt=_toks(rng, 6), max_new_tokens=6)
            for _ in range(2)]
    assert fleet.replicas[1].engine.try_submit(hogs[0]) is None
    fleet.replicas[1].engine.run_step()
    assert fleet.replicas[1].engine.try_submit(hogs[1]) is None
    fleet.run_boundary()  # replica 0 dies; victim joins _migrants
    fleet.run_boundary()  # placement fails (survivor backpressured)
    assert any(m.req is victim for m in fleet._migrants)
    r = fleet.try_submit(victim)
    assert r is not None
    assert r.code is RejectionCode.ALREADY_IN_FLIGHT
    assert not is_terminal(victim.status)  # still fleet-owned
    fleet.generate([], max_steps=2000)     # drains without crashing
    assert is_terminal(victim.status)


def test_manual_join_mid_update_does_not_skip_swap(tiny_model):
    """Review regression: an operator try_join()ing the rolling
    update's current replica rejoins it on old weights; the wave must
    re-drain it and deliver the swap rather than declaring done with
    a stale-weights replica."""
    cfg, params = tiny_model
    params2 = jax.tree_util.tree_map(lambda x: x, params)
    params2["embedding"]["position"] = (
        params["embedding"]["position"] * 0.5)
    rng = np.random.default_rng(83)
    fleet = ReplicaFleet(cfg, params, n_replicas=2, n_slots=2,
                         num_pages=12, max_prompt_len=16)
    fleet.schedule_rolling_update(params2)
    fleet.run_boundary()          # drains replica 0 as plan current
    assert fleet.replicas[0].state is ReplicaState.DRAINING
    assert fleet.try_join(0)      # operator interferes: old weights
    assert fleet.replicas[0].swaps == 0
    fleet.generate([], max_steps=500)   # wave must recover
    assert fleet.rolling_update_done
    assert fleet.replicas[0].swaps == 1
    assert fleet.replicas[1].swaps == 1
    reqs = [Request(prompt=_toks(rng, 6), max_new_tokens=5)
            for _ in range(4)]
    out = fleet.generate(reqs, max_steps=2000)
    assert {r.replica_id for r in reqs} == {0, 1}
    for r in reqs:   # NEW weights everywhere despite the interference
        assert out[r.rid] == reference_decode(
            cfg, params2, r.prompt, r.max_new_tokens), r.rid


def test_fleet_summary_counters_are_per_run(tiny_model):
    """Review regression: a second generate() must not smear the first
    run's deaths/migrations into its summary — migrated/replica_deaths
    /steps are per-run, like the engines' accums."""
    cfg, params = tiny_model
    rng = np.random.default_rng(79)
    chaos = ServingChaos().kill_replica_at(1, 3)
    fleet = ReplicaFleet(cfg, params, n_replicas=3, chaos=chaos,
                         n_slots=2, num_pages=12, max_prompt_len=16)
    reqs = [Request(prompt=_toks(rng, 6), max_new_tokens=5,
                    arrival_step=i) for i in range(6)]
    fleet.generate(reqs, max_steps=2000)
    st1 = fleet.last_stats
    assert st1["replica_deaths"] == 1 and st1["migrated"] >= 1
    late = [Request(prompt=_toks(rng, 6), max_new_tokens=5)
            for _ in range(3)]
    fleet.generate(late, max_steps=2000)
    st2 = fleet.last_stats
    assert st2["replica_deaths"] == 0
    assert st2["migrated"] == 0 and st2["migration_readmitted"] == 0
    assert st2["requests_lost"] == 0
    assert st2["steps"] < fleet.steps_run  # per-run, not lifetime
    for k in ("0", "2"):
        assert st2["per_replica"][k]["migrated_out"] == 0
    # per-replica counters are per-run deltas too: the death happened
    # in run 1, so run 2's breakdown shows none
    assert st1["per_replica"]["1"]["deaths"] == 1
    assert st2["per_replica"]["1"]["deaths"] == 0


def test_migrants_place_before_same_boundary_arrivals(tiny_model):
    """Review regression: a dead replica's in-flight work (older
    t_arrival) must compete for admission capacity BEFORE the same
    boundary's fresh arrivals — not lose its slot to younger requests
    and burn placement retries."""
    cfg, params = tiny_model
    rng = np.random.default_rng(97)
    chaos = ServingChaos().kill_replica_at(0, 0)
    fleet = ReplicaFleet(
        cfg, params, n_replicas=2, chaos=chaos, n_slots=1,
        num_pages=8, max_prompt_len=16,
        admission=AdmissionConfig(max_queue=2, high_watermark=0.5,
                                  low_watermark=0.25))
    victim = Request(prompt=_toks(rng, 6), max_new_tokens=6)
    assert fleet.replicas[0].engine.try_submit(victim) is None
    # survivor has exactly one queue slot (high watermark at depth 1);
    # the fresh arrival lands the boundary after the kill — the
    # migrated victim must get that slot
    fresh = Request(prompt=_toks(rng, 6), max_new_tokens=6,
                    arrival_step=1)
    out = fleet.generate([fresh], max_steps=2000)
    assert victim.status is RequestStatus.COMPLETED
    assert victim.replica_id == 1
    assert list(victim.out_tokens) == reference_decode(
        cfg, params, victim.prompt, victim.max_new_tokens)
    # the younger request was the one refused (typed, not lost)
    assert fresh.status is RequestStatus.REJECTED
    assert fresh.end_reason == "no_feasible_replica"
    assert out[fresh.rid] == []
    assert fleet.last_stats["requests_lost"] == 0


def test_all_replicas_unavailable_fails_migrants_typed(tiny_model):
    """Review regression: migrants with no ACTIVE replica to place on,
    no swap plan, and every live engine idle must reach a TYPED
    terminal state (FAILED/no_active_replica) instead of spinning
    generate() forever (max_steps defaults to None)."""
    cfg, params = tiny_model
    rng = np.random.default_rng(89)
    ring = RingBufferRecorder()
    chaos = ServingChaos().kill_replica_at(0, 0)
    fleet = ReplicaFleet(cfg, params, n_replicas=2, sink=ring,
                         chaos=chaos, n_slots=1, num_pages=8,
                         max_prompt_len=16)
    victim = Request(prompt=_toks(rng, 6), max_new_tokens=6)
    assert fleet.replicas[0].engine.try_submit(victim) is None
    fleet.drain(1)            # the survivor is DRAINING, never joined
    fleet.generate([])        # must TERMINATE (no max_steps guard)
    assert victim.status is RequestStatus.FAILED
    assert victim.end_reason == "no_active_replica"
    ends = [e for e in ring.events("request_end")
            if e["rid"] == victim.rid]
    assert len(ends) == 1 and ends[0]["status"] == "failed"


def test_fleet_chaos_trace_holds_invariants_every_boundary(tiny_model):
    """Random fleet chaos: staggered arrivals, a replica kill, stolen
    allocations, deadline budgets — live replicas hold
    check_invariants() after EVERY boundary, every request ends
    terminal, completions are token-identical, zero leaks."""
    cfg, params = tiny_model
    rng = np.random.default_rng(99)
    reqs = [Request(
        prompt=_toks(rng, int(rng.integers(3, 10))), max_new_tokens=5,
        arrival_step=int(rng.integers(0, 8)),
        priority=int(rng.integers(0, 3)))
        for _ in range(8)]
    chaos = (ServingChaos().kill_replica_at(1, 5)
             .fail_allocs(int(rng.integers(1, 3))))
    fleet = ReplicaFleet(
        cfg, params, n_replicas=3, chaos=chaos, n_slots=2,
        num_pages=6, max_prompt_len=16,
        migration_retry=RetryPolicy(attempts=200))
    pending = sorted(reqs, key=lambda r: (r.arrival_step, r.rid))
    guard = 0
    while True:
        guard += 1
        assert guard < 800, "fleet trace did not drain"
        step = fleet.steps_run
        while pending and pending[0].arrival_step <= step:
            fleet.try_submit(pending.pop(0))
        if not pending and not fleet.busy:
            break
        fleet.run_boundary()
        fleet.check_invariants()
    assert fleet.page_leaks() == 0
    for r in reqs:
        assert is_terminal(r.status), (r.rid, r.status)
        if r.status is RequestStatus.COMPLETED:
            assert list(r.out_tokens) == reference_decode(
                cfg, params, r.prompt, r.max_new_tokens), r.rid
