"""Unit tests for the xplane op-breakdown helpers (tools/op_breakdown.py).

The profiling capture itself needs a real TPU; the parsing/classification
logic is pure and pinned here so a refactor cannot silently misbucket the
published bench breakdown. The golden xplane fixtures at the bottom build
REAL xplane protobufs and pin the corrected category attribution
end-to-end (round-5 VERDICT: generic ``%fusion.N`` ops were all booked as
"fusion(elementwise)", hiding the dense GEMMs — 42.7% of the GPT step
mislabeled).
"""
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools.op_breakdown import _category, _short_op_name  # noqa: E402
from apex_tpu.telemetry.tracing import (  # noqa: E402
    breakdown_table,
    parse_xspace_op_times,
)


def test_short_op_name_strips_hlo_decoration():
    assert _short_op_name(
        "%convolution_tanh_fusion.3 = bf16[4096,4096]{1,0} fusion(...)"
    ) == "convolution_tanh_fusion"
    assert _short_op_name("%while.7 = (s32[], f32[8]) while(...)") == "while"
    assert _short_op_name(
        "%apex_tpu_flash_fwd.65 = (bf16[8,16,1024,64]) custom-call(...)"
    ) == "apex_tpu_flash_fwd"
    # no ' = ' (bare name) and no trailing index both survive
    assert _short_op_name("%copy-done") == "copy-done"
    assert _short_op_name("fusion") == "fusion"


def test_category_buckets():
    assert _category("apex_tpu_flash_fwd") == "attention-kernel"
    assert _category("apex_tpu.flash_attention") == "attention-kernel"
    assert _category("convolution_add_fusion") == "matmul/conv"
    assert _category("all-reduce-start") == "collective"
    assert _category("collective-permute") == "collective"
    assert _category("bitcast_dynamic-update-slice_fusion") == "data-movement"
    assert _category("copy") == "data-movement"
    assert _category("exponential_reduce_fusion") == "reduce"
    assert _category("select_add_fusion") == "fusion(elementwise)"
    assert _category("iota") == "other"


def test_category_hlo_category_stat_is_authoritative():
    """The profiler's per-op category (from the fused computation's root
    op) overrides the generic name — the round-5 fix."""
    assert _category("fusion", "convolution fusion") == "matmul/conv"
    assert _category("fusion", "loop fusion") == "fusion(elementwise)"
    assert _category("fusion", "output fusion") == "fusion(elementwise)"
    assert _category("fusion", "all-reduce fusion") == "collective"
    assert _category("fusion", "reduce fusion") == "reduce"
    # a named fusion with a contradicting stat: the stat wins
    assert _category("select_add_fusion", "convolution fusion") \
        == "matmul/conv"


def test_category_generic_fusion_without_signal_is_unattributed():
    """A bare %fusion.N with no hlo_category and no callee signal must
    NOT be claimed as elementwise — that is the exact round-5 bug."""
    assert _category("fusion") == "fusion(unattributed)"
    assert _category("loop_fusion") == "fusion(unattributed)"
    assert _category("fused_computation") == "fusion(unattributed)"


def test_category_generic_fusion_salvaged_from_callee():
    raw = ("%fusion.3 = bf16[4,4]{1,0} fusion(%p0, %p1), kind=kOutput, "
           "calls=%convolution_fusion.3")
    assert _category("fusion", None, raw) == "matmul/conv"
    raw2 = "%fusion.9 = f32[8] fusion(%p0), kind=kLoop, calls=%fused_computation.9"
    assert _category("fusion", None, raw2) == "fusion(unattributed)"


# ---------------------------------------------------------------------------
# golden xplane fixtures: real protobufs, end-to-end through the parser
# ---------------------------------------------------------------------------

def _build_xplane(tmp_path, ops):
    """Write a minimal real .xplane.pb: ops = [(name, ps, category|None)]."""
    xplane_pb2 = pytest.importorskip(
        "tensorflow.tsl.profiler.protobuf.xplane_pb2")
    xs = xplane_pb2.XSpace()
    plane = xs.planes.add()
    plane.name = "/device:TPU:0"
    cat_md = plane.stat_metadata[1]
    cat_md.id = 1
    cat_md.name = "hlo_category"
    line = plane.lines.add()
    line.name = "XLA Ops"
    for i, (name, ps, cat) in enumerate(ops, start=1):
        md = plane.event_metadata[i]
        md.id = i
        md.name = name
        ev = line.events.add()
        ev.metadata_id = i
        ev.duration_ps = ps
        if cat is not None:
            st = ev.stats.add()
            st.metadata_id = 1
            st.str_value = cat
    # a non-TPU plane that must be ignored
    host = xs.planes.add()
    host.name = "/host:CPU"
    hl = host.lines.add()
    hl.name = "XLA Ops"
    (tmp_path / "plugins").mkdir(exist_ok=True)
    out = tmp_path / "plugins" / "host.xplane.pb"
    out.write_bytes(xs.SerializeToString())
    return str(tmp_path)


GOLDEN_OPS = [
    # the round-5 shape: generic fusions dominated by a conv-rooted one
    ("fusion.1", 700_000, "convolution fusion"),
    ("fusion.2", 150_000, "loop fusion"),
    ("fusion.3", 50_000, None),                      # no stat: unattributed
    ("apex_tpu_flash_fwd.65", 80_000, "custom-call"),
    ("copy.4", 10_000, "copy"),
    ("while.9", 999_999, None),                      # container: excluded
    ("all-reduce.5", 10_000, "all-reduce"),
]

# the pinned golden table for GOLDEN_OPS at n_steps=1
GOLDEN_CATEGORIES = {
    "matmul/conv": 70.0,
    "fusion(elementwise)": 15.0,
    "fusion(unattributed)": 5.0,
    "attention-kernel": 8.0,
    "data-movement": 1.0,
    "collective": 1.0,
}


def test_golden_xplane_fixture_end_to_end(tmp_path):
    trace_dir = _build_xplane(tmp_path, GOLDEN_OPS)
    total, per_op = parse_xspace_op_times(trace_dir)
    assert total == 1_000_000  # container excluded
    assert per_op[("fusion", "matmul/conv")] == 700_000
    assert per_op[("fusion", "fusion(elementwise)")] == 150_000
    assert per_op[("fusion", "fusion(unattributed)")] == 50_000
    table = breakdown_table(total, per_op, n_steps=1, top=10)
    got = {cat: row["pct"] for cat, row in table["categories"].items()}
    assert got == pytest.approx(GOLDEN_CATEGORIES)
    # top op is the conv-rooted fusion, labeled as matmul/conv
    assert table["ops"][0]["op"] == "fusion"
    assert table["ops"][0]["category"] == "matmul/conv"
    assert table["ops"][0]["pct"] == pytest.approx(70.0)


def test_golden_xplane_ref_value_category(tmp_path):
    """hlo_category delivered via stat_metadata ref_value indirection
    (the other xplane encoding) must resolve identically."""
    xplane_pb2 = pytest.importorskip(
        "tensorflow.tsl.profiler.protobuf.xplane_pb2")
    xs = xplane_pb2.XSpace()
    plane = xs.planes.add()
    plane.name = "/device:TPU:0"
    key_md = plane.stat_metadata[1]
    key_md.id = 1
    key_md.name = "hlo_category"
    val_md = plane.stat_metadata[2]
    val_md.id = 2
    val_md.name = "convolution fusion"
    md = plane.event_metadata[1]
    md.id = 1
    md.name = "fusion.7"
    line = plane.lines.add()
    line.name = "XLA Ops"
    ev = line.events.add()
    ev.metadata_id = 1
    ev.duration_ps = 42
    st = ev.stats.add()
    st.metadata_id = 1
    st.ref_value = 2
    out = tmp_path / "t.xplane.pb"
    out.write_bytes(xs.SerializeToString())
    total, per_op = parse_xspace_op_times(str(tmp_path))
    assert total == 42
    assert per_op == {("fusion", "matmul/conv"): 42}
