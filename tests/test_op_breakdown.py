"""Unit tests for the xplane op-breakdown helpers (tools/op_breakdown.py).

The profiling capture itself needs a real TPU; the parsing/classification
logic is pure and pinned here so a refactor cannot silently misbucket the
published bench breakdown.
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools.op_breakdown import _category, _short_op_name  # noqa: E402


def test_short_op_name_strips_hlo_decoration():
    assert _short_op_name(
        "%convolution_tanh_fusion.3 = bf16[4096,4096]{1,0} fusion(...)"
    ) == "convolution_tanh_fusion"
    assert _short_op_name("%while.7 = (s32[], f32[8]) while(...)") == "while"
    assert _short_op_name(
        "%apex_tpu_flash_fwd.65 = (bf16[8,16,1024,64]) custom-call(...)"
    ) == "apex_tpu_flash_fwd"
    # no ' = ' (bare name) and no trailing index both survive
    assert _short_op_name("%copy-done") == "copy-done"
    assert _short_op_name("fusion") == "fusion"


def test_category_buckets():
    assert _category("apex_tpu_flash_fwd") == "attention-kernel"
    assert _category("apex_tpu.flash_attention") == "attention-kernel"
    assert _category("convolution_add_fusion") == "matmul/conv"
    assert _category("all-reduce-start") == "collective"
    assert _category("collective-permute") == "collective"
    assert _category("bitcast_dynamic-update-slice_fusion") == "data-movement"
    assert _category("copy") == "data-movement"
    assert _category("exponential_reduce_fusion") == "reduce"
    assert _category("select_add_fusion") == "fusion(elementwise)"
    assert _category("iota") == "other"
