"""Serving under fire: lifecycle, admission control, fault isolation,
engine recovery (apex_tpu.serving.robustness + resilience.ServingChaos).

Coverage map (the ISSUE-10 acceptance surface):

- typed terminal states + the summary schema fix: percentiles over
  COMPLETED requests only, buckets by terminal state;
- one RejectionReason taxonomy: the legacy PR-6 refusal paths
  (pool-infeasible, replay-prompt-overflow) carry typed codes, the
  malformed-request storm hits every front-door check;
- deadlines: TTFT / total-latency budgets evict queued AND running
  work deterministically (VirtualClock), pages freed, events recorded;
- admission control: bounded queue, watermark hysteresis, token-budget
  (deadline-infeasibility) refusal; degradation: max_new capping and
  priority-ordered shedding under sustained pressure;
- fault isolation PROOF: a chaos-poisoned request terminates FAILED
  with slot/step provenance while every other request's tokens are
  byte-identical to the same trace without poison;
- recovery PROOF: kill-engine-mid-flight -> recover_from -> replay
  completes all in-flight requests token-identical to an uninterrupted
  run; a wedged step sync is caught by the armed HangWatchdog with
  thread stacks (and step provenance) in the hang event;
- request-level retry of FAILED-transient requests under RetryPolicy
  (attempts + wall-clock deadline);
- chaos property traces: random admit/evict/preempt/poison/timeout/
  alloc-fault interleavings hold check_invariants() at every step, end
  with all requests terminal, zero page leaks, and survivors
  token-identical to the dense greedy reference.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.resilience import (
    ChaosError,
    HangError,
    HangWatchdog,
    RetryPolicy,
    ServingChaos,
    request_storm,
)
from apex_tpu.serving import (
    AdmissionConfig,
    AdmissionController,
    DegradationPolicy,
    RejectionCode,
    RejectionError,
    Request,
    RequestStatus,
    Scheduler,
    SchedulerError,
    ServingEngine,
    VirtualClock,
    PagedKVSpec,
    is_terminal,
    reference_decode,
)
from apex_tpu.telemetry import RingBufferRecorder
from apex_tpu.transformer.testing import GPTConfig, init_gpt_params


def _tiny_cfg(dtype=jnp.float32):
    return GPTConfig(
        num_layers=2, hidden_size=64, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=64,
        hidden_dropout=0.0, attention_dropout=0.0,
        params_dtype=jnp.float32, compute_dtype=dtype)


@pytest.fixture(scope="module")
def tiny_model():
    cfg = _tiny_cfg()
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    # position-sensitive continuations (see test_serving.py)
    params["embedding"]["position"] = params["embedding"]["position"] * 40.0
    return cfg, params


def _toks(rng, n, vocab=128):
    return [int(t) for t in rng.integers(0, vocab, size=n)]


# ---------------------------------------------------------------------------
# lifecycle + the summary schema fix
# ---------------------------------------------------------------------------

def test_lifecycle_and_summary_buckets_by_terminal_state(tiny_model):
    """The _summarize fix: one request completes, one times out in the
    queue — the summary buckets them by terminal state and computes the
    latency percentiles over COMPLETED requests ONLY (the timed-out
    request's stamps must not contaminate the distribution)."""
    cfg, params = tiny_model
    rng = np.random.default_rng(0)
    ring = RingBufferRecorder()
    eng = ServingEngine(cfg, params, n_slots=1, num_pages=8,
                        max_prompt_len=16, clock=VirtualClock(dt=1.0),
                        sink=ring)
    ok_req = Request(prompt=_toks(rng, 5), max_new_tokens=6)
    # waits behind ok_req on the single slot and expires in-queue
    # (budget 4 virtual seconds << the ~11 steps ok_req takes)
    late = Request(prompt=_toks(rng, 5), max_new_tokens=6,
                   latency_budget_ms=4000.0)
    eng.generate([ok_req, late], max_steps=500)
    eng.scheduler.check_invariants()
    assert ok_req.status is RequestStatus.COMPLETED
    assert late.status is RequestStatus.TIMED_OUT
    assert late.end_reason == "latency_budget"
    st = eng.last_stats
    # schema pin: the terminal-state buckets and SLO/goodput keys
    assert st["by_status"] == {"completed": 1, "rejected": 0,
                               "timed_out": 1, "failed": 0,
                               "cancelled": 0}
    assert st["completed"] == 1 and st["n_requests"] == 2
    for key in ("slo_attainment", "slo_attained", "goodput_tokens",
                "goodput_tokens_per_sec", "max_queue_depth", "retries"):
        assert key in st, key
    assert st["slo_attained"] == 1 and st["slo_attainment"] == 0.5
    # percentiles over the ONE completed request: a degenerate (equal)
    # distribution. Were the timed-out request included, p50 != p99.
    lat = st["latency_ms"]
    assert set(lat) == {"p50", "p90", "p99"}
    assert lat["p50"] == lat["p99"]
    # generated_tokens still counts all emitted work (the timed-out
    # request may have produced some before expiring)
    assert st["generated_tokens"] == sum(
        len(r.out_tokens) for r in (ok_req, late))
    ends = ring.events("request_end")
    assert {e["status"] for e in ends} == {"completed", "timed_out"}


def test_ttft_budget_evicts_running_prefill(tiny_model):
    """A request whose TTFT budget expires while still prefilling is
    evicted from its SLOT (not just the queue): pages freed, terminal
    TIMED_OUT with reason ttft_budget."""
    cfg, params = tiny_model
    rng = np.random.default_rng(1)
    eng = ServingEngine(cfg, params, n_slots=1, num_pages=8,
                        max_prompt_len=16, clock=VirtualClock(dt=1.0))
    req = Request(prompt=_toks(rng, 12), max_new_tokens=4,
                  ttft_budget_ms=5000.0)  # 12 prefill steps > 5 ticks
    eng.generate([req], max_steps=200)
    assert req.status is RequestStatus.TIMED_OUT
    assert req.end_reason == "ttft_budget"
    assert req.out_tokens == []
    assert eng.scheduler.allocator.used_count == 0
    eng.scheduler.check_invariants()


def test_cancel_queued_and_running(tiny_model):
    cfg, params = tiny_model
    rng = np.random.default_rng(2)
    eng = ServingEngine(cfg, params, n_slots=1, num_pages=8,
                        max_prompt_len=16)
    running = Request(prompt=_toks(rng, 6), max_new_tokens=8)
    queued = Request(prompt=_toks(rng, 6), max_new_tokens=8)
    eng.submit(running)
    eng.submit(queued)
    for _ in range(3):
        eng.run_step()
    assert running.status is RequestStatus.RUNNING
    assert eng.cancel(queued) and queued.status is RequestStatus.CANCELLED
    assert eng.cancel(running) and running.status is RequestStatus.CANCELLED
    assert not eng.cancel(running)  # already terminal: not in flight
    assert eng.scheduler.allocator.used_count == 0
    eng.scheduler.check_invariants()
    assert eng.scheduler.idle


# ---------------------------------------------------------------------------
# typed rejection taxonomy (satellite: legacy paths regression)
# ---------------------------------------------------------------------------

def test_legacy_pool_infeasible_carries_typed_reason():
    """The PR-6 'pool can never hold it' refusal now raises
    RejectionError (still a SchedulerError, same message) with code
    POOL_INFEASIBLE and structured detail."""
    spec = PagedKVSpec(1, 4, 16, page_size=16, num_pages=4,
                       pages_per_seq=4)
    sched = Scheduler(spec, n_slots=2, max_prompt_len=64)
    req = Request(prompt=list(range(1, 17)), max_new_tokens=48)
    with pytest.raises(SchedulerError, match="never be served") as e:
        sched.submit(req)
    assert isinstance(e.value, RejectionError)
    assert e.value.reason.code is RejectionCode.POOL_INFEASIBLE
    assert e.value.reason.detail["pages_needed"] == 4
    assert e.value.reason.detail["n_usable_pages"] == 3
    # validate() is the non-raising face of the same taxonomy
    reason = sched.validate(req)
    assert reason is not None
    assert reason.code is RejectionCode.POOL_INFEASIBLE
    assert not sched.waiting


def test_legacy_replay_overflow_carries_typed_reason():
    """The PR-6 preemption-replay-overflow refusal, typed."""
    spec = PagedKVSpec(1, 4, 16, page_size=16, num_pages=5,
                       pages_per_seq=4)
    sched = Scheduler(spec, n_slots=2, max_prompt_len=16)
    with pytest.raises(SchedulerError, match="replay") as e:
        sched.submit(Request(prompt=list(range(12)), max_new_tokens=20))
    assert isinstance(e.value, RejectionError)
    assert e.value.reason.code is RejectionCode.REPLAY_OVERFLOW
    assert e.value.reason.detail["worst_replay"] == 31
    # the boundary case stays admissible (12 + 5 - 1 = 16)
    sched.submit(Request(prompt=list(range(12)), max_new_tokens=5))
    assert len(sched.waiting) == 1


def test_request_storm_all_refused_with_typed_codes(tiny_model):
    """The chaos request storm: every malformed/oversized shape is
    refused with exactly the expected code, REJECTED status, a reject
    event — and zero scheduler/allocator state left behind."""
    cfg, params = tiny_model
    ring = RingBufferRecorder()
    eng = ServingEngine(cfg, params, n_slots=2, num_pages=4,
                        max_prompt_len=16, sink=ring)
    storm = request_storm(eng)
    assert len(storm) == 5  # incl. the pool-infeasible case
    for req, code in storm:
        reason = eng.try_submit(req)
        assert reason is not None and reason.code is code, (
            f"rid {req.rid}: expected {code}, got {reason}")
        assert req.status is RequestStatus.REJECTED
        assert req.end_reason == code.value
    # the raising door throws the same typed error
    bad, code = request_storm(eng, seed=1)[0]
    with pytest.raises(RejectionError) as e:
        eng.submit(bad)
    assert e.value.reason.code is code
    rejects = ring.events("reject")
    assert len(rejects) == len(storm) + 1
    assert all("code" in r for r in rejects)
    assert not eng.scheduler.waiting
    assert eng.scheduler.allocator.used_count == 0
    eng.scheduler.check_invariants()


def test_resubmit_after_rejection_is_a_fresh_attempt(tiny_model):
    """Review regressions: resubmitting a rejected request must start a
    fresh lifecycle attempt (not trip the double-finalize guard), keep
    the ORIGINAL t_arrival (deadline budgets span resubmits), and —
    under a virtual clock — the admission EWMA must be denominated in
    that same clock (boundary-to-boundary ticks, not wall seconds)."""
    cfg, params = tiny_model
    rng = np.random.default_rng(5)
    clock = VirtualClock(dt=1.0)
    ring = RingBufferRecorder()
    eng = ServingEngine(
        cfg, params, n_slots=1, num_pages=8, max_prompt_len=16,
        clock=clock, sink=ring,
        admission=AdmissionConfig(max_queue=2, high_watermark=0.75,
                                  low_watermark=0.5))
    hog = Request(prompt=_toks(rng, 4), max_new_tokens=6)
    assert eng.try_submit(hog) is None
    bumped = Request(prompt=_toks(rng, 4), max_new_tokens=6)
    r = eng.try_submit(bumped)  # depth 1 >= high(1): backpressure
    assert r is not None and r.code is RejectionCode.BACKPRESSURE
    assert bumped.status is RequestStatus.REJECTED
    t_first_submit = bumped.t_arrival
    eng.generate([], max_steps=200)  # drain the hog
    assert hog.status is RequestStatus.COMPLETED
    # the EWMA runs in virtual time: one clock tick per boundary
    assert eng.admission.est_step_s == pytest.approx(1.0)
    # resubmit the SAME object: fresh attempt, original arrival stamp
    assert eng.try_submit(bumped) is None
    assert bumped.status is RequestStatus.QUEUED
    assert bumped.t_arrival == t_first_submit
    eng.generate([], max_steps=200)
    assert bumped.status is RequestStatus.COMPLETED
    ends = [e for e in ring.events("request_end")
            if e["rid"] == bumped.rid]
    assert [e["status"] for e in ends] == ["rejected", "completed"]


def test_duplicate_submit_of_in_flight_request_refused(tiny_model):
    """Review regression: submitting a request that is already QUEUED
    or RUNNING must be refused (ALREADY_IN_FLIGHT) without disturbing
    the live submission — a duplicate would put one Request object in
    two slots (shared out_tokens, double finalize)."""
    cfg, params = tiny_model
    rng = np.random.default_rng(6)
    eng = ServingEngine(cfg, params, n_slots=1, num_pages=8,
                        max_prompt_len=16)
    req = Request(prompt=_toks(rng, 4), max_new_tokens=6)
    assert eng.try_submit(req) is None
    dup = eng.try_submit(req)  # QUEUED
    assert dup is not None
    assert dup.code is RejectionCode.ALREADY_IN_FLIGHT
    assert req.status is RequestStatus.QUEUED  # live submission intact
    eng.run_step()  # now RUNNING
    dup = eng.try_submit(req)
    assert dup is not None and dup.code is RejectionCode.ALREADY_IN_FLIGHT
    with pytest.raises(RejectionError, match="already in flight"):
        eng.submit(req)
    eng.generate([], max_steps=200)
    assert req.status is RequestStatus.COMPLETED
    assert list(req.out_tokens) == reference_decode(
        cfg, params, req.prompt, 6)


# ---------------------------------------------------------------------------
# admission control + degradation
# ---------------------------------------------------------------------------

def test_admission_controller_watermark_hysteresis():
    """Pure host logic, no engine: hard bound, two-level watermark
    (ON at high, OFF only back at low)."""
    ctl = AdmissionController(
        AdmissionConfig(max_queue=8, high_watermark=0.5,
                        low_watermark=0.25), n_slots=1)
    req = Request(prompt=[1, 2], max_new_tokens=4)
    assert ctl.check(req, queue_depth=0, queued_tokens=0) is None
    full = ctl.check(req, queue_depth=8, queued_tokens=48)
    assert full.code is RejectionCode.QUEUE_FULL
    # depth 4 = high: backpressure latches
    bp = ctl.check(req, queue_depth=4, queued_tokens=24)
    assert bp.code is RejectionCode.BACKPRESSURE
    # still latched at depth 3 (above low=2)
    assert ctl.check(req, queue_depth=3,
                     queued_tokens=18).code is RejectionCode.BACKPRESSURE
    # drains below low: admits again
    assert ctl.check(req, queue_depth=2, queued_tokens=12) is None
    assert ctl.rejected == 3


def test_admission_token_budget_deadline_infeasible():
    """Token-budget admission: at a known step time, a budget below the
    service lower bound is refused DEADLINE_INFEASIBLE with the
    estimate in the detail; a generous budget passes."""
    ctl = AdmissionController(
        AdmissionConfig(max_queue=64, step_time_init_s=0.010),
        n_slots=2)
    # service: 8 prompt + 8 new = 16 steps ~ 160ms; queue adds
    # 32 tokens / 2 slots = 16 steps ~ 160ms -> total lb ~ 320ms
    tight = Request(prompt=list(range(8)), max_new_tokens=8,
                    latency_budget_ms=200.0)
    r = ctl.check(tight, queue_depth=2, queued_tokens=32)
    assert r is not None and r.code is RejectionCode.DEADLINE_INFEASIBLE
    assert r.detail["latency_lb_ms"] == pytest.approx(320.0)
    roomy = Request(prompt=list(range(8)), max_new_tokens=8,
                    latency_budget_ms=1000.0)
    assert ctl.check(roomy, queue_depth=2, queued_tokens=32) is None
    # TTFT-only budget: lb = (16 wait + 8 prompt) * 10ms = 240ms
    t = Request(prompt=list(range(8)), max_new_tokens=8,
                ttft_budget_ms=100.0)
    r = ctl.check(t, queue_depth=2, queued_tokens=32)
    assert r is not None and r.code is RejectionCode.DEADLINE_INFEASIBLE
    assert "ttft_lb_ms" in r.detail


def test_degradation_caps_and_sheds_under_sustained_pressure(tiny_model):
    """One long occupant pins the single slot; the queue sits at the
    high watermark for shed_after boundaries -> the policy sheds down
    to the low watermark, lowest-priority-youngest first, with shed
    events; meanwhile newly admitted work had max_new capped (degrade
    event). Everything terminal, nothing leaked."""
    cfg, params = tiny_model
    rng = np.random.default_rng(3)
    ring = RingBufferRecorder()
    eng = ServingEngine(
        cfg, params, n_slots=1, num_pages=8, max_prompt_len=16,
        sink=ring,
        admission=AdmissionConfig(max_queue=8, high_watermark=0.5,
                                  low_watermark=0.25),
        degradation=DegradationPolicy(shed_after=2, cap_max_new=4))
    hog = Request(prompt=_toks(rng, 4), max_new_tokens=12)
    eng.submit(hog)
    eng.run_step()  # hog takes the slot
    assert hog.status is RequestStatus.RUNNING
    # fill the queue to the high watermark (4); priorities distinguish
    # shed order; the last submit is capped (queue >= high -> pressure)
    queued = [Request(prompt=_toks(rng, 4), max_new_tokens=12,
                      priority=p) for p in (2, 1, 0)]
    for q in queued:
        assert eng.try_submit(q) is None
    capped = Request(prompt=_toks(rng, 4), max_new_tokens=12, priority=5)
    # depth is 3 (below high=4): accepted uncapped... so push one more
    assert eng.try_submit(capped) is None
    assert capped.max_new_tokens == 12  # depth was 3 < high at submit
    overflow = Request(prompt=_toks(rng, 4), max_new_tokens=12)
    r = eng.try_submit(overflow)  # depth 4 = high -> backpressure
    assert r is not None and r.code is RejectionCode.BACKPRESSURE
    # two pressured boundaries (slot still held by hog, queue depth 4)
    eng.run_step()
    eng.run_step()
    shed_events = ring.events("shed")
    assert shed_events, "sustained pressure must shed"
    # shed down to low watermark (2): two victims, lowest priority
    # first, youngest among equals — priorities 0 then 1
    assert len(eng.scheduler.waiting) == 2
    shed_reqs = [q for q in queued + [capped]
                 if q.status is RequestStatus.REJECTED]
    assert sorted(q.priority for q in shed_reqs) == [0, 1], (
        "shedding must take the lowest-priority victims")
    assert all(q.end_reason == "shed" for q in shed_reqs)
    # the shed event stream names the lowest-priority victim first
    assert shed_events[0]["priority"] == 0
    # drive the rest home
    eng.generate([], max_steps=300)
    eng.scheduler.check_invariants()
    assert eng.scheduler.allocator.used_count == 0
    for q in [hog, capped] + queued + [overflow]:
        assert is_terminal(q.status), q.rid
    # a pressured submit WOULD be capped: prime pressure state again
    # via the controller directly
    assert eng.admission.cap_for(
        Request(prompt=[1], max_new_tokens=12), queue_depth=4) == 4


# ---------------------------------------------------------------------------
# fault isolation (acceptance proof)
# ---------------------------------------------------------------------------

def test_poisoned_request_quarantined_others_byte_identical(tiny_model):
    """THE fault-isolation proof: the same staggered trace is run clean
    and with one request's logits chaos-poisoned mid-decode. The victim
    terminates FAILED with slot/step provenance; every other request's
    token list is BYTE-identical between the two runs (and equals the
    dense greedy reference)."""
    cfg, params = tiny_model
    rng = np.random.default_rng(11)
    lens = (6, 9, 4, 7)

    def mk_trace():
        r = np.random.default_rng(99)
        return [Request(prompt=_toks(r, L), max_new_tokens=6,
                        arrival_step=2 * i)
                for i, L in enumerate(lens)]

    clean = mk_trace()
    eng0 = ServingEngine(cfg, params, n_slots=2, num_pages=12,
                         max_prompt_len=16)
    out_clean = eng0.generate(list(clean), max_steps=2000)

    poisoned = mk_trace()
    victim = poisoned[1]
    chaos = ServingChaos().poison_request(victim.rid, at_step=9)
    ring = RingBufferRecorder()
    eng1 = ServingEngine(cfg, params, n_slots=2, num_pages=12,
                         max_prompt_len=16, chaos=chaos, sink=ring)
    out_poison = eng1.generate(list(poisoned), max_steps=2000)
    eng1.scheduler.check_invariants()
    assert eng1.scheduler.allocator.used_count == 0

    assert chaos.faults_fired == [("poison", victim.rid, 9)]
    assert victim.status is RequestStatus.FAILED
    f = victim.failure
    assert f["kind"] == "nonfinite_logits" and f["step"] == 9
    assert f["rid"] == victim.rid and "slot" in f and f["transient"]
    ends = [e for e in ring.events("request_end")
            if e["status"] == "failed"]
    assert len(ends) == 1 and ends[0]["failure"]["slot"] == f["slot"]
    # every NON-victim request: byte-identical to the undisturbed run
    # and to the dense greedy reference
    for i, (c, p) in enumerate(zip(clean, poisoned)):
        if p is victim:
            continue
        assert out_poison[p.rid] == out_clean[c.rid], f"request {i}"
        assert out_poison[p.rid] == reference_decode(
            cfg, params, p.prompt, p.max_new_tokens)
        assert p.status is RequestStatus.COMPLETED


def test_retry_failed_transient_completes_token_identical(tiny_model):
    """Satellite: request-level retry under RetryPolicy. The quarantined
    (transient) FAILED request is resubmitted through the replay path
    and completes token-identical to a never-poisoned run."""
    cfg, params = tiny_model
    rng = np.random.default_rng(21)
    reqs = [Request(prompt=_toks(rng, L), max_new_tokens=6)
            for L in (5, 8)]
    chaos = ServingChaos().poison_request(reqs[0].rid, at_step=6)
    eng = ServingEngine(cfg, params, n_slots=2, num_pages=12,
                        max_prompt_len=16, chaos=chaos)
    out = eng.generate(
        list(reqs), max_steps=2000,
        retry_failed=RetryPolicy(attempts=3, retry_on=(Exception,),
                                 deadline=60.0))
    assert chaos.faults_fired and chaos.faults_fired[0][0] == "poison"
    for r in reqs:
        assert r.status is RequestStatus.COMPLETED
        assert out[r.rid] == reference_decode(cfg, params, r.prompt, 6)
    assert reqs[0].retries == 1 and reqs[1].retries == 0
    assert eng.last_stats["retries"] == 1
    assert eng.last_stats["by_status"]["completed"] == 2


# ---------------------------------------------------------------------------
# engine recovery (acceptance proof)
# ---------------------------------------------------------------------------

def test_kill_engine_mid_flight_recovers_token_identical(tiny_model):
    """THE recovery proof: chaos kills the engine mid-flight with
    requests prefilling, decoding, and queued; recover_from builds a
    fresh engine and replays them all to completion, token-identical
    to an uninterrupted run (the dense greedy reference)."""
    cfg, params = tiny_model
    rng = np.random.default_rng(31)
    reqs = [Request(prompt=_toks(rng, L), max_new_tokens=6,
                    arrival_step=i)
            for i, L in enumerate((8, 5, 11))]
    chaos = ServingChaos().kill_engine_at(10)
    ring = RingBufferRecorder()
    eng = ServingEngine(cfg, params, n_slots=2, num_pages=12,
                        max_prompt_len=16, chaos=chaos, sink=ring)
    with pytest.raises(ChaosError, match="injected engine kill"):
        eng.generate(list(reqs), max_steps=2000)
    in_flight = [r for r in reqs if not is_terminal(r.status)]
    assert in_flight, "the kill must strand work"
    eng2, survivors = ServingEngine.recover_from(eng)
    assert {r.rid for r in survivors} == {r.rid for r in in_flight}
    eng2.generate(survivors, max_steps=2000)
    eng2.scheduler.check_invariants()
    assert eng2.scheduler.allocator.used_count == 0
    for r in reqs:
        assert r.status is RequestStatus.COMPLETED
        assert list(r.out_tokens) == reference_decode(
            cfg, params, r.prompt, r.max_new_tokens), r.rid
    assert all(r.restarts == 1 for r in survivors)
    recs = ring.events("engine_recovery")
    assert recs and recs[0]["recovered"] == len(survivors)


def test_wedged_step_caught_by_armed_watchdog(tiny_model):
    """THE wedge proof: the chaos-wedged host sync is caught by the
    armed HangWatchdog — HangError raised, hang event in the sink with
    ALL-thread stacks and the serving step number — and the stranded
    request recovers onto a fresh engine."""
    cfg, params = tiny_model
    rng = np.random.default_rng(41)
    req = Request(prompt=_toks(rng, 4), max_new_tokens=6)
    chaos = ServingChaos().wedge_step_at(5, stall_s=3.0)
    ring = RingBufferRecorder()
    wd = HangWatchdog(timeout_s=0.3, poll_s=0.02, sink=ring)
    eng = ServingEngine(cfg, params, n_slots=1, num_pages=8,
                        max_prompt_len=16, chaos=chaos, watchdog=wd,
                        sink=ring)
    with pytest.raises(HangError) as e:
        eng.generate([req], max_steps=100)
    wd.close()
    assert e.value.what == "serving_step_host_sync"
    assert "thread" in e.value.stacks
    hangs = ring.events("hang")
    assert len(hangs) == 1
    assert hangs[0]["step"] == 5                  # context= provenance
    assert "MainThread" in hangs[0]["stacks"]     # the dump is real
    assert chaos.faults_fired == [("wedge", 5)]
    # the wedge strands the request mid-flight; recovery replays it
    eng2, survivors = ServingEngine.recover_from(eng, watchdog=None)
    assert [r.rid for r in survivors] == [req.rid]
    eng2.generate(survivors, max_steps=200)
    assert req.status is RequestStatus.COMPLETED
    assert list(req.out_tokens) == reference_decode(
        cfg, params, req.prompt, 6)


# ---------------------------------------------------------------------------
# chaos property traces (satellite)
# ---------------------------------------------------------------------------

def test_chaos_property_traces_hold_invariants_every_step(tiny_model):
    """Random chaos traces: staggered admissions, tiny pool (forced
    preemption), stolen allocations, one poisoned request, deadline
    budgets, bounded-queue admission. After EVERY step:
    check_invariants() (no page leaks, no double frees, lifecycle/
    occupancy coherence). At the end: every request terminal, the
    allocator drained, and every COMPLETED request token-identical to
    the dense greedy reference. Termination within the step guard IS
    the seniority-contract check — a livelock would blow it."""
    cfg, params = tiny_model
    rng = np.random.default_rng(1234)
    for trial in range(2):
        n_req = 6
        reqs = []
        for i in range(n_req):
            plen = int(rng.integers(3, 10))
            reqs.append(Request(
                prompt=_toks(rng, plen), max_new_tokens=6,
                arrival_step=int(rng.integers(0, 10)),
                priority=int(rng.integers(0, 3)),
                # roughly half get budgets; some generous, some doomed
                latency_budget_ms=(float(rng.integers(8, 80)) * 1e3
                                   if rng.random() < 0.5 else None)))
        chaos = ServingChaos().fail_allocs(int(rng.integers(1, 4)))
        victim = reqs[int(rng.integers(0, n_req))]
        chaos.poison_request(victim.rid)
        eng = ServingEngine(
            cfg, params, n_slots=2, num_pages=5, max_prompt_len=16,
            chaos=chaos, clock=VirtualClock(dt=1.0),
            admission=AdmissionConfig(max_queue=6, high_watermark=0.84,
                                      low_watermark=0.5),
            degradation=DegradationPolicy(shed_after=3))
        pending = sorted(reqs, key=lambda r: (r.arrival_step, r.rid))
        step_i = 0
        guard = 0
        while True:
            guard += 1
            assert guard < 600, f"trial {trial}: trace did not drain"
            while pending and pending[0].arrival_step <= step_i:
                eng.try_submit(pending.pop(0))
            if not pending and eng.scheduler.idle:
                break
            if not eng.scheduler.idle:
                eng.run_step()
            step_i += 1
            eng.scheduler.check_invariants()
        eng.scheduler.check_invariants()
        assert eng.scheduler.allocator.used_count == 0, f"trial {trial}"
        for r in reqs:
            assert is_terminal(r.status), (trial, r.rid, r.status)
            if r.status is RequestStatus.COMPLETED:
                assert list(r.out_tokens) == reference_decode(
                    cfg, params, r.prompt, r.max_new_tokens), (
                    trial, r.rid)
        assert victim.status in (RequestStatus.FAILED,
                                 RequestStatus.REJECTED,
                                 RequestStatus.TIMED_OUT), (
            "the poisoned request must not complete normally")


def test_recover_from_under_admission_pressure(tiny_model):
    """ISSUE-11 satellite: recover_from composed with admission
    pressure. A killed engine's survivors land on an engine whose
    queue already sits at the high watermark: re-admission must not
    deadlock or leak pages — the recovered work either queues (when
    the door opens) or is refused/shed in DegradationPolicy order
    (lowest-priority-youngest), with check_invariants() holding after
    every step and every request terminal."""
    cfg, params = tiny_model
    rng = np.random.default_rng(53)
    # engine A dies mid-flight with work running and queued
    chaos = ServingChaos().kill_engine_at(4)
    eng_a = ServingEngine(cfg, params, n_slots=1, num_pages=8,
                          max_prompt_len=16, chaos=chaos)
    a_reqs = [Request(prompt=_toks(rng, 5), max_new_tokens=5,
                      priority=3) for _ in range(2)]
    with pytest.raises(ChaosError):
        eng_a.generate(list(a_reqs), max_steps=500)
    from apex_tpu.serving import recover_requests

    survivors = recover_requests(eng_a)
    assert survivors, "the kill must strand work"
    # engine B: bounded queue ALREADY at the high watermark (4 of
    # max_queue 8, high=0.5), slot pinned by a hog, shedding armed
    ring = RingBufferRecorder()
    eng_b = ServingEngine(
        cfg, params, n_slots=1, num_pages=8, max_prompt_len=16,
        sink=ring,
        admission=AdmissionConfig(max_queue=8, high_watermark=0.5,
                                  low_watermark=0.25),
        degradation=DegradationPolicy(shed_after=2))
    hog = Request(prompt=_toks(rng, 4), max_new_tokens=10)
    eng_b.submit(hog)
    eng_b.run_step()  # hog takes the slot
    primed = [Request(prompt=_toks(rng, 4), max_new_tokens=5,
                      priority=p) for p in (2, 1, 0, 2)]
    for q in primed:
        assert eng_b.try_submit(q) is None
    assert len(eng_b.scheduler.waiting) == eng_b.admission.high_count
    # recovered work re-enters through the same admission door: at the
    # high watermark it is refused typed (BACKPRESSURE), never dropped
    readmitted, refused = [], []
    for r in survivors:
        reason = eng_b.try_submit(r)
        (refused if reason is not None else readmitted).append(r)
        if reason is not None:
            assert reason.code is RejectionCode.BACKPRESSURE
            assert r.status is RequestStatus.REJECTED
    assert refused, "pressure must push back on recovery"
    # drive to drain with invariants checked after EVERY step; the
    # sustained pressure sheds queued work in DegradationPolicy order
    guard = 0
    while not eng_b.scheduler.idle:
        guard += 1
        assert guard < 400, "recovery-under-pressure deadlocked"
        eng_b.run_step()
        eng_b.scheduler.check_invariants()
    shed = [e for e in ring.events("shed")]
    assert shed, "sustained pressure must shed"
    shed_reqs = [q for q in primed if q.end_reason == "shed"]
    assert shed_reqs and min(q.priority for q in primed) in {
        q.priority for q in shed_reqs}, (
        "shedding must take the lowest-priority victims first")
    assert shed[0]["priority"] == min(
        q.priority for q in primed)
    for r in [hog] + primed + survivors:
        assert is_terminal(r.status), (r.rid, r.status)
    assert eng_b.scheduler.allocator.used_count == 0
    # the recovered request that got through completed token-identical
    # (replay carried its pre-kill tokens across BOTH the kill and the
    # pressure) — the composition the satellite pins
    for r in readmitted:
        if r.status is RequestStatus.COMPLETED:
            assert list(r.out_tokens) == reference_decode(
                cfg, params, r.prompt, r.max_new_tokens), r.rid


# ---------------------------------------------------------------------------
# CI wiring: serving_check chaos legs + compare_bench overload legs
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("leg", ["poison_quarantine", "timeout_eviction",
                                 "kill_recover"])
def test_serving_check_chaos_legs_pass(leg):
    """The tier-1 CI smoke: each chaos leg runs clean under the 0/1/2
    exit-code contract."""
    import tools.serving_check as sc

    assert sc.main(["--self", "--check", leg]) == 0


def test_serving_check_chaos_leg_failure_is_exit_1(monkeypatch):
    import tools.serving_check as sc

    monkeypatch.setitem(sc.CHECKS, "poison_quarantine",
                        lambda: {"ok": False, "victim_status": "completed"})
    assert sc.main(["--self", "--check", "poison_quarantine"]) == 1


def test_compare_bench_tracks_overload_legs():
    """serving_goodput / serving_slo_attainment ride compare_bench: a
    drop past threshold is a regression; the committed CPU smoke
    artifact parses and carries the schema."""
    import json

    from tools.compare_bench import compare, extract_legs

    base = {"serving_overload": {
        "goodput_tokens_per_sec": 100.0, "slo_attainment": 0.9,
        "ttft_p99_ms": 50.0}}
    legs = extract_legs(base)
    assert legs["serving_goodput"] == 100.0
    assert legs["serving_slo_attainment"] == 0.9
    assert legs["serving_overload_ttft_p99_ms"] == -50.0  # inverted
    worse = {"serving_overload": {
        "goodput_tokens_per_sec": 80.0, "slo_attainment": 0.7,
        "ttft_p99_ms": 50.0}}
    rep = compare(base, worse, threshold=0.05)
    assert {r["leg"] for r in rep["regressions"]} == {
        "serving_goodput", "serving_slo_attainment"}
    art = json.load(open("bench_artifacts/serving_overload_cpu_smoke.json"))
    leg = art["serving_overload"]
    assert leg["page_leaks"] == 0
    assert leg["max_queue_depth"] <= leg["max_queue"]
    assert leg["slo_attainment"] is not None
    assert leg["by_status"]["completed"] + leg["by_status"]["rejected"] \
        + leg["by_status"]["timed_out"] == leg["n_requests"]
    assert extract_legs(art)["serving_goodput"] > 0
