"""Fleet health plane: streaming metrics aggregation, SLO error budgets
with burn-rate alerts, and alert-driven auto-response (ISSUE-18).

Coverage map (the acceptance surface):

- LogBucketHistogram: every quantile within the documented ``alpha``
  relative error of the exact nearest-rank quantile, agreement with
  `telemetry.percentiles` on smooth streams, byte-identical
  order-independent merges, alpha-mismatch refusal;
- MetricsAggregator: event routing (request_end/serving_step/reject)
  into counters/gauges/histograms, label plumbing — TaggedRecorder
  stream labels merged under per-request labels (record keys win);
- SLOTracker: the multi-window multi-burn-rate state machine —
  pending(for_count) -> firing exactly once per episode -> resolved
  only after clear_after clean evaluations (hysteresis, no flapping),
  and a second episode fires again;
- determinism: two identical VirtualClock fleet runs produce
  byte-identical aggregator snapshots and alert timelines;
- auto-response on a REAL fleet: a firing attainment alert arms
  DegradationPolicy on every live replica and relaxes it on resolve; a
  firing availability alert restarts the dead replica; a page-severity
  alert mid-rolling-update aborts the wave;
- chaos property test: replica kill + overload burst under
  VirtualClock — every alert episode fires exactly once, alert and
  response events reconcile with the aggregator's own counters, fleet
  invariants stay clean;
- CI wiring: tools/fleet_status.py --self checks pass (parametrized),
  CLI exit codes (0 healthy / 1 firing / 2 unreadable), and
  compare_bench gates the serving_slo_guard leg.
"""
import copy
import json
import math
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.resilience import ServingChaos
from apex_tpu.serving import (
    AdmissionConfig,
    ReplicaFleet,
    Request,
    VirtualClock,
    is_terminal,
)
from apex_tpu.telemetry import (
    SLO,
    HealthMonitor,
    LogBucketHistogram,
    MetricsAggregator,
    RingBufferRecorder,
    SLOTracker,
    TaggedRecorder,
    default_serving_slos,
    percentiles,
)
from apex_tpu.transformer.testing import GPTConfig, init_gpt_params

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools import fleet_status  # noqa: E402
from tools.compare_bench import compare, extract_legs  # noqa: E402

REPO = Path(__file__).resolve().parent.parent


def _tiny_cfg(dtype=jnp.float32):
    return GPTConfig(
        num_layers=2, hidden_size=64, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=64,
        hidden_dropout=0.0, attention_dropout=0.0,
        params_dtype=jnp.float32, compute_dtype=dtype)


@pytest.fixture(scope="module")
def tiny_model():
    cfg = _tiny_cfg()
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    params["embedding"]["position"] = params["embedding"]["position"] * 40.0
    return cfg, params


def _toks(rng, n, vocab=128):
    return [int(t) for t in rng.integers(0, vocab, size=n)]


def _attainment_src(agg):
    return (agg.counter_total("slo_good_total"),
            agg.counter_total("slo_bad_total"))


def _availability_src(agg):
    ups = agg.gauge_values("replica_up")
    if not ups:
        return None
    return sum(1.0 for v in ups.values() if v > 0) / len(ups)


def _mk_attainment_tracker(objective=0.5, fast=4.0, slow=8.0,
                           fast_burn=1.5, slow_burn=1.2, **kw):
    """A bench/test-scale attainment SLO: windows a handful of virtual
    seconds, burns reachable against a fat (1 - objective) budget."""
    return SLOTracker(
        SLO(name="slo_attainment", objective=objective, kind="ratio",
            fast_window_s=fast, fast_burn=fast_burn,
            slow_window_s=slow, slow_burn=slow_burn, **kw),
        _attainment_src)


# ---------------------------------------------------------------------------
# LogBucketHistogram: documented error + exact order-independent merges
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("alpha", [0.05, 0.01])
def test_histogram_quantiles_within_documented_error(alpha):
    rng = np.random.default_rng(7)
    vals = np.exp(rng.normal(3.0, 1.0, size=5000))
    h = LogBucketHistogram(alpha=alpha)
    for v in vals:
        h.add(float(v))
    srt = np.sort(vals)
    for q in (0.5, 0.9, 0.99):
        exact = float(srt[max(0, math.ceil(q * len(vals)) - 1)])
        got = h.quantile(q)
        assert got is not None
        assert abs(got - exact) / exact <= alpha + 1e-9, (q, got, exact)
    # and the interpolating percentiles() convention agrees on a smooth
    # stream to within the same order of error (1.5x allowance: the two
    # conventions straddle adjacent order statistics)
    ref = percentiles(list(map(float, vals)))
    for p in (50, 90, 99):
        got = h.quantile(p / 100.0)
        assert abs(got - ref[f"p{p}"]) / ref[f"p{p}"] <= 1.5 * alpha


def test_histogram_merges_are_exact_and_order_independent():
    rng = np.random.default_rng(11)
    streams = [np.exp(rng.normal(2.0, 0.7, size=300)),
               rng.uniform(0.5, 4.0, size=200),
               np.concatenate([rng.normal(10.0, 0.1, size=150),
                               rng.normal(400.0, 5.0, size=150)])]
    parts = []
    for s in streams:
        h = LogBucketHistogram(alpha=0.05)
        for v in s:
            h.add(float(v))
        parts.append(h)
    fwd = LogBucketHistogram(alpha=0.05)
    for p in parts:
        fwd.merge(p)
    rev = LogBucketHistogram(alpha=0.05)
    for p in reversed(parts):
        rev.merge(p)
    assert (json.dumps(fwd.snapshot(), sort_keys=True)
            == json.dumps(rev.snapshot(), sort_keys=True))
    # merged counts are exact: identical buckets to one sketch that saw
    # the concatenated stream (counts are integers — no approximation)
    one = LogBucketHistogram(alpha=0.05)
    for s in streams:
        for v in s:
            one.add(float(v))
    assert one.buckets == fwd.buckets
    assert one.count == fwd.count == sum(p.count for p in parts)
    assert one.min == fwd.min and one.max == fwd.max
    # mixed-resolution merges would silently void the error bound
    with pytest.raises(ValueError):
        fwd.merge(LogBucketHistogram(alpha=0.01))


def test_histogram_merged_classmethod_does_not_mutate_inputs():
    a, b = LogBucketHistogram(), LogBucketHistogram()
    for v in (1.0, 2.0, 3.0):
        a.add(v)
    b.add(10.0)
    snap_a, snap_b = a.snapshot(), b.snapshot()
    ab = LogBucketHistogram.merged(a, b)
    ba = LogBucketHistogram.merged(b, a)
    assert ab.snapshot() == ba.snapshot()
    assert ab.count == 4
    assert a.snapshot() == snap_a and b.snapshot() == snap_b


# ---------------------------------------------------------------------------
# MetricsAggregator: routing + the label plumbing satellite
# ---------------------------------------------------------------------------


def test_aggregator_routes_events_and_labels_with_precedence():
    agg = MetricsAggregator()
    # stream-level labels (the multi-tenant hook) ride a TaggedRecorder
    tagged = TaggedRecorder(agg, replica_id=0, labels={"tenant": "a"})
    tagged.record({"event": "serving_step", "step": 1, "queue_depth": 3,
                   "occupancy": 0.5, "free_pages": 7, "active": 2})
    tagged.record({"event": "request_end", "rid": 1, "status": "completed",
                   "slo_ok": True, "generated": 8, "ttft_ms": 12.0,
                   "latency_ms": 30.0})
    # per-request labels win over the stream's on collision
    tagged.record({"event": "request_end", "rid": 2, "status": "completed",
                   "slo_ok": False, "generated": 4, "latency_ms": 90.0,
                   "labels": {"tenant": "b"}})
    tagged.record({"event": "request_end", "rid": 3, "status": "rejected",
                   "slo_ok": True})
    tagged.record({"event": "reject", "code": "QUEUE_FULL"})

    assert agg.counter_total("slo_good_total") == 1.0
    # budget burns on violation AND on never-completing
    assert agg.counter_total("slo_bad_total") == 2.0
    assert agg.counter_total("goodput_tokens_total") == 8.0
    assert agg.counter_total("generated_tokens_total") == 12.0

    keys = set(agg.counters["requests_total"])
    assert (("replica_id", "0"), ("status", "completed"),
            ("tenant", "a")) in keys
    assert (("replica_id", "0"), ("status", "completed"),
            ("tenant", "b")) in keys
    rej = agg.counters["serving_rejects_total"]
    assert (("code", "QUEUE_FULL"), ("replica_id", "0"),
            ("tenant", "a")) in rej

    step_key = (("replica_id", "0"), ("tenant", "a"))
    assert agg.gauges["serving_queue_depth"][step_key] == 3.0
    assert agg.gauges["replica_up"][step_key] == 1.0

    lat = agg.hist_merged("latency_ms")
    assert lat is not None and lat.count == 2
    assert agg.hist_merged("ttft_ms").count == 1


def test_aggregator_bounds_series_cardinality():
    agg = MetricsAggregator(max_series=4)
    for i in range(10):
        agg.record({"event": "request_end", "status": "completed",
                    "slo_ok": True, "labels": {"tenant": str(i)}})
    assert len(agg.counters["slo_good_total"]) == 4
    assert agg.dropped_series > 0  # counted, never silently folded


# ---------------------------------------------------------------------------
# SLOTracker: burn-rate alerting state machine
# ---------------------------------------------------------------------------


def test_slo_state_machine_fires_once_per_episode_with_hysteresis():
    tr = _mk_attainment_tracker(
        objective=0.9, fast=4.0, slow=16.0, fast_burn=4.0, slow_burn=2.0,
        for_count=2, clear_after=3)
    agg = MetricsAggregator()
    t = 0.0

    def feed(counter, n, evals=1):
        nonlocal t
        out = []
        for _ in range(evals):
            t += 1.0
            agg.inc(counter, (), n)
            out.append(tr.evaluate(agg, t)["state"])
        return out

    assert set(feed("slo_good_total", 4, evals=20)) == {"ok"}
    collapse = feed("slo_bad_total", 4, evals=10)
    # for_count=2: one tripped evaluation is PENDING, not yet FIRING
    assert "pending" in collapse and "firing" in collapse
    assert collapse.index("pending") < collapse.index("firing")
    # one episode == one firing transition, no flapping while it burns
    assert tr.fired_count == 1
    assert all(s == "firing" for s in collapse[collapse.index("firing"):])

    recovery = feed("slo_good_total", 4, evals=30)
    assert "resolved" in recovery
    assert tr.resolved_count >= 1
    r = recovery.index("resolved")
    # hysteresis: at least clear_after firing evaluations precede the
    # resolve (burns must stay below resolve_frac for 3 in a row)
    assert all(s == "firing" for s in recovery[:max(1, r - 3)][:3])
    assert all(s == "ok" for s in recovery[r + 1:])

    # a SECOND collapse is a new episode: it fires again
    feed("slo_bad_total", 4, evals=10)
    assert tr.fired_count == 2
    firing_entries = [e for e in tr.timeline if e["state"] == "firing"]
    assert len(firing_entries) == tr.fired_count


def test_slo_multi_window_confirmation_blocks_single_blip():
    """One bad boundary cannot page: the fast window spikes but the slow
    window stays below confirm_frac of the page threshold."""
    tr = _mk_attainment_tracker(
        objective=0.9, fast=2.0, slow=60.0, fast_burn=4.0, slow_burn=2.0,
        confirm_frac=0.25)
    agg = MetricsAggregator()
    t = 0.0
    for _ in range(50):
        t += 1.0
        agg.inc("slo_good_total", (), 4)
        tr.evaluate(agg, t)
    t += 1.0
    agg.inc("slo_bad_total", (), 4)  # a single all-bad boundary
    rec = tr.evaluate(agg, t)
    # fast window is 100% bad (burn 10 >= 4) but the long window holds
    # 200 goods: 4/204 / 0.1 = 0.2 < 4 * 0.25 — no page
    assert rec["burn_fast"] >= 4.0
    assert rec["state"] == "ok", rec


def test_error_budget_accounting():
    tr = _mk_attainment_tracker(objective=0.9)
    agg = MetricsAggregator()
    agg.inc("slo_good_total", (), 90)
    agg.inc("slo_bad_total", (), 10)
    tr.evaluate(agg, 1.0)
    # 10% bad on a 10% budget: exactly spent
    assert tr.budget.attainment == pytest.approx(0.9)
    assert tr.budget.consumed == pytest.approx(1.0)
    assert tr.budget.remaining == pytest.approx(0.0)


# ---------------------------------------------------------------------------
# determinism: byte-identical VirtualClock runs (tentpole acceptance)
# ---------------------------------------------------------------------------


def _run_guarded_fleet(tiny_model):
    cfg, params = tiny_model
    rng = np.random.default_rng(17)
    clock = VirtualClock(dt=1.0)
    health = HealthMonitor(slos=[_mk_attainment_tracker()])
    ring = RingBufferRecorder(capacity=4096)
    fleet = ReplicaFleet(
        cfg, params, n_replicas=2, clock=clock, sink=ring, n_slots=1,
        num_pages=16, max_prompt_len=32, health=health,
        admission=AdmissionConfig(max_queue=6, high_watermark=0.75,
                                  low_watermark=0.25))
    reqs = []
    for i in range(10):
        # half the trace blows an impossible budget -> bad slo events
        reqs.append(Request(
            prompt=_toks(rng, 4), max_new_tokens=3, arrival_step=2 * i,
            latency_budget_ms=0.5 if i % 2 else None))
    fleet.generate(reqs, max_steps=600)
    return fleet, health


def test_virtual_clock_runs_byte_identical(tiny_model):
    f1, h1 = _run_guarded_fleet(tiny_model)
    f2, h2 = _run_guarded_fleet(tiny_model)
    # streaming aggregates: byte-identical serialized snapshots
    assert h1.aggregator.snapshot_json() == h2.aggregator.snapshot_json()
    # alert timelines: identical transition sequences at identical
    # virtual clock values
    t1 = h1.manager.tracker("slo_attainment")
    t2 = h2.manager.tracker("slo_attainment")
    assert t1.timeline == t2.timeline
    assert (json.dumps(h1.snapshot(), sort_keys=True)
            == json.dumps(h2.snapshot(), sort_keys=True))
    # the signal actually flowed: budget events were observed
    assert t1.budget.total > 0
    assert f1.last_stats["slo_attainment"] == f2.last_stats["slo_attainment"]


# ---------------------------------------------------------------------------
# auto-response against a REAL fleet (not fakes)
# ---------------------------------------------------------------------------


def test_responder_arms_and_relaxes_degradation(tiny_model):
    cfg, params = tiny_model
    rng = np.random.default_rng(23)
    clock = VirtualClock(dt=1.0)
    tracker = _mk_attainment_tracker(clear_after=2)
    health = HealthMonitor(slos=[tracker])
    ring = RingBufferRecorder(capacity=4096)
    fleet = ReplicaFleet(
        cfg, params, n_replicas=2, clock=clock, sink=ring, n_slots=1,
        num_pages=16, max_prompt_len=32, health=health,
        admission=AdmissionConfig(max_queue=8))
    bad = [Request(prompt=_toks(rng, 4), max_new_tokens=2,
                   arrival_step=i, latency_budget_ms=0.5)
           for i in range(8)]
    fleet.generate(bad, max_steps=400)
    resp = health.fleet_responder
    armed = [a for a in resp.actions if a["action"] == "arm_degradation"]
    # every live replica's admission controller got the policy
    assert {a["replica_id"] for a in armed} == {0, 1}
    assert resp.armed
    for rep in fleet.replicas:
        assert rep.engine.admission.degradation is resp.degradation

    # recovery traffic: the alert resolves and the original (None)
    # policy is restored — the operator's config, not a guess
    good = [Request(prompt=_toks(rng, 4), max_new_tokens=2,
                    arrival_step=2 * i) for i in range(14)]
    fleet.generate(good, max_steps=800)
    assert any(a["action"] == "relax_degradation" for a in resp.actions)
    assert not resp.armed
    for rep in fleet.replicas:
        assert rep.engine.admission.degradation is None
    # actions landed as structured response events in the shared stream
    acts = {e.get("action") for e in ring.events("response")}
    assert {"arm_degradation", "relax_degradation"} <= acts


def test_responder_restarts_dead_replica(tiny_model):
    cfg, params = tiny_model
    rng = np.random.default_rng(29)
    clock = VirtualClock(dt=1.0)
    # small windows so the availability ticket fires within the trace
    health = HealthMonitor(slos=default_serving_slos(
        fast_window_s=4.0, slow_window_s=8.0))
    ring = RingBufferRecorder(capacity=4096)
    chaos = ServingChaos().kill_replica_at(1, 3)
    fleet = ReplicaFleet(
        cfg, params, n_replicas=2, chaos=chaos, clock=clock, sink=ring,
        n_slots=1, num_pages=16, max_prompt_len=32, health=health)
    reqs = [Request(prompt=_toks(rng, 4), max_new_tokens=3,
                    arrival_step=2 * i) for i in range(12)]
    fleet.generate(reqs, max_steps=600)
    restarts = [a for a in health.fleet_responder.actions
                if a["action"] == "restart_replica"]
    assert restarts and restarts[0]["replica_id"] == 1
    assert fleet.replicas[1].live  # the actuator actually ran
    assert any(e for e in ring.events("replica_restart"))
    # the firing episode is on the availability SLO
    avail = health.manager.tracker("replica_available")
    assert avail.fired_count >= 1


def test_responder_aborts_rolling_update_on_page(tiny_model):
    cfg, params = tiny_model
    rng = np.random.default_rng(31)
    clock = VirtualClock(dt=1.0)
    tracker = _mk_attainment_tracker()  # all-bad burn 2 >= 1.5: page
    health = HealthMonitor(slos=[tracker])
    ring = RingBufferRecorder(capacity=4096)
    fleet = ReplicaFleet(
        cfg, params, n_replicas=2, clock=clock, sink=ring, n_slots=1,
        num_pages=16, max_prompt_len=32, health=health,
        admission=AdmissionConfig(max_queue=8))
    # long-running work keeps the drain wave in flight while the burst
    # of impossible-budget requests burns the error budget
    keep = [Request(prompt=_toks(rng, 4), max_new_tokens=20,
                    arrival_step=0) for _ in range(2)]
    bad = [Request(prompt=_toks(rng, 4), max_new_tokens=2,
                   arrival_step=1 + i, latency_budget_ms=0.5)
           for i in range(8)]
    new_params = jax.tree_util.tree_map(lambda x: x + 0.0, params)
    fleet.schedule_rolling_update(new_params)
    fleet.generate(keep + bad, max_steps=600)
    acts = [a["action"] for a in health.fleet_responder.actions]
    assert "abort_rolling_update" in acts
    assert fleet._swap_plan is None
    assert ring.events("rolling_update_aborted")
    # the firing record that drove the abort carried page severity
    fire = [e for e in tracker.timeline if e["state"] == "firing"]
    assert fire and fire[0]["severity"] == "page"


# ---------------------------------------------------------------------------
# chaos property test (satellite f)
# ---------------------------------------------------------------------------


def test_chaos_alert_episodes_fire_once_and_reconcile(tiny_model):
    cfg, params = tiny_model
    rng = np.random.default_rng(37)
    clock = VirtualClock(dt=1.0)
    trackers = [
        _mk_attainment_tracker(),
        SLOTracker(
            SLO(name="replica_available", objective=0.5, kind="threshold",
                target=0.99, higher_is_better=True, fast_window_s=4.0,
                fast_burn=1.5, slow_window_s=8.0, slow_burn=1.2),
            _availability_src),
    ]
    health = HealthMonitor(slos=trackers)
    ring = RingBufferRecorder(capacity=8192)
    chaos = ServingChaos().kill_replica_at(1, 6)
    fleet = ReplicaFleet(
        cfg, params, n_replicas=2, chaos=chaos, clock=clock, sink=ring,
        n_slots=1, num_pages=16, max_prompt_len=32, health=health,
        admission=AdmissionConfig(max_queue=6, high_watermark=0.75,
                                  low_watermark=0.25))
    reqs = []
    for i in range(16):
        # overload burst with tight budgets after a short healthy head
        tight = i >= 4
        reqs.append(Request(
            prompt=_toks(rng, 4), max_new_tokens=3,
            arrival_step=(3 * i if i < 4 else 12 + (i - 4)),
            latency_budget_ms=2000.0 if tight else None))
    fleet.generate(reqs, max_steps=800)
    fleet.check_invariants()
    assert all(is_terminal(r.status) for r in reqs)

    agg = health.aggregator
    transitions = sum(len(t.timeline) for t in trackers)
    for t in trackers:
        fires = [e for e in t.timeline if e["state"] == "firing"]
        # each episode fires exactly once: firing count equals distinct
        # firing transitions, and no two consecutive transitions both
        # enter FIRING (the state machine must leave it in between)
        assert len(fires) == t.fired_count
        states = [e["state"] for e in t.timeline]
        assert all(not (a == b == "firing")
                   for a, b in zip(states, states[1:]))
    # alert/response events rode the fleet fan-in, so the aggregator
    # counted the health plane's own activity as metrics
    assert agg.counter_total("alerts_total") == transitions
    assert (agg.counter_total("alert_responses_total")
            == len(health.fleet_responder.actions))
    # the availability episode restarted the dead replica
    if health.manager.tracker("replica_available").fired_count:
        assert fleet.replicas[1].live


# ---------------------------------------------------------------------------
# CI wiring: fleet_status CLI + compare_bench gates (satellite e)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", sorted(fleet_status.CHECKS))
def test_fleet_status_self_checks(name):
    res = fleet_status.CHECKS[name]()
    assert res["ok"], res


def _write_jsonl(path, records):
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


def test_fleet_status_cli_exit_codes(tmp_path, capsys):
    healthy = [{"event": "request_end", "rid": i, "status": "completed",
                "slo_ok": True, "generated": 4, "replica_id": i % 2,
                "latency_ms": 25.0, "t_wall": float(i)}
               for i in range(40)]
    p = tmp_path / "healthy.jsonl"
    _write_jsonl(p, healthy)
    assert fleet_status.main([str(p)]) == 0
    capsys.readouterr()

    burning = [{"event": "request_end", "rid": i, "status": "timed_out",
                "slo_ok": False, "replica_id": 0, "t_wall": float(i)}
               for i in range(48)]
    p2 = tmp_path / "burning.jsonl"
    _write_jsonl(p2, burning)
    assert fleet_status.main([str(p2)]) == 1
    capsys.readouterr()

    assert fleet_status.main([str(tmp_path / "missing.jsonl")]) == 2
    capsys.readouterr()

    # machine formats parse/expose
    assert fleet_status.main([str(p), "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert "slos" in out and "replicas" in out
    assert fleet_status.main([str(p), "--prom"]) == 0
    prom = capsys.readouterr().out
    assert "# TYPE requests_total counter" in prom
    assert "latency_ms_count" in prom


def test_compare_bench_gates_slo_guard_metrics():
    base = {
        "value": 1000.0,
        "serving_slo_guard": {"guarded_attainment": 0.9,
                              "alert_detection_steps": 12},
    }
    legs = extract_legs(base)
    assert legs["slo_guard_attainment"] == 0.9
    # lower-is-better legs are negated into the uniform orientation
    assert legs["alert_detection_steps"] == -12

    collapse = copy.deepcopy(base)
    collapse["serving_slo_guard"] = {"guarded_attainment": 0.7,
                                     "alert_detection_steps": 40}
    rep = compare(base, collapse, threshold=0.05)
    regressed = {r["leg"] for r in rep["regressions"]}
    assert {"slo_guard_attainment", "alert_detection_steps"} <= regressed

    # detection jitter inside the absolute tolerance is not a regression
    jitter = copy.deepcopy(base)
    jitter["serving_slo_guard"]["alert_detection_steps"] = 26
    rep2 = compare(base, jitter, threshold=0.05)
    assert "alert_detection_steps" in rep2["unchanged"]


def test_slo_guard_smoke_artifact_carries_gated_legs():
    art = REPO / "bench_artifacts" / "serving_slo_guard_cpu_smoke.json"
    data = json.loads(art.read_text())
    legs = extract_legs(data)
    assert legs["slo_guard_attainment"] is not None
    assert legs["alert_detection_steps"] is not None
    guard = data["serving_slo_guard"]
    # the acceptance pair: detection beat collapse, and the guarded arm
    # held attainment at least as high as the unguarded arm
    assert guard["fired_before_collapse"] is True
    assert (guard["guarded_attainment"]
            >= guard["unguarded_attainment"])
