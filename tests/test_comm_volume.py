"""Communication-VOLUME accounting from compiled HLO (VERDICT r4 #4/5).

The pair/structure assertions in ``test_observability.py`` catch a
missing collective; they cannot catch a silently-oversized one (e.g. a
reduce-scatter regressing to a full all-gather + local slice, or a
bucketing change doubling traffic). These tests parse every collective
op's output shape out of the compiled HLO and assert total bytes per
collective KIND against the analytic expectation for the parallelism
scheme — the strongest multi-chip comm-efficiency signal available
without hardware. Reference behavior being mirrored: the bucketed
allreduce economics of ``apex/parallel/distributed.py:429-479`` (volume
= parameter bytes, not 2x), the reduce-scatter/all-gather split of
DistributedFusedAdam (``:1920``, ``:926``), and ring context
parallelism's (cp-1)-hop kv rotation.

Byte accounting convention: each collective is charged its OUTPUT buffer
size (tuple outputs summed). For all-reduce that equals the payload; for
all-gather the gathered (full) size; for reduce-scatter the shard size;
for collective-permute the hopped buffer. Async start/done pairs are
counted once (the ``-done`` op has the same result repeated; only
``-start``-less or ``-start`` forms are charged).
"""
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8e4m3fn": 1, "f8e5m2": 1,
    "s64": 8, "s32": 4, "s16": 2, "s8": 1, "u64": 8, "u32": 4, "u16": 2,
    "u8": 1, "pred": 1, "c64": 8, "c128": 16,
}

_KINDS = ("all-reduce", "all-gather", "reduce-scatter", "collective-permute",
          "all-to-all")

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")


def _shape_bytes(text: str) -> int:
    total = 0
    for dt, dims in _SHAPE_RE.findall(text):
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dt]
    return total


def collective_bytes(hlo_text: str):
    """{kind: (count, total_output_bytes)} over all collective ops in the
    module text. '-done' halves of async pairs are skipped."""
    out = {k: [0, 0] for k in _KINDS}
    for line in hlo_text.splitlines():
        m = re.search(r"=\s+(.*?)\s+([a-z0-9-]+)\(", line)
        if not m:
            continue
        shapes, op = m.groups()
        for kind in _KINDS:
            if op == kind or op == kind + "-start":
                out[kind][0] += 1
                out[kind][1] += _shape_bytes(shapes)
    return {k: tuple(v) for k, v in out.items()}


def _hlo(jitted, *args):
    return jitted.lower(*args).compile().as_text()


def _mesh(axis):
    return Mesh(np.array(jax.devices()), (axis,))


TOL = 0.05  # 5% + 1 KB scalar slack on every analytic expectation


def _assert_bytes(actual, expected, what):
    assert abs(actual - expected) <= expected * TOL + 1024, (
        f"{what}: {actual} bytes vs analytic {expected}"
    )


# ---------------------------------------------------------------------------
# TP=8: column+row linear pair, fwd+bwd
# ---------------------------------------------------------------------------

def test_tp_step_allreduce_volume():
    """One TP=8 (column -> row) block, grad w.r.t. (x, wc, wr): exactly
    two all-reduces of the [B, S=binned, H] activation — the row
    forward's partial-sum reduce and the column backward's dx reduce
    (copy_to transpose). Volume = 2 * B*T*H * 4 bytes; anything more
    means a collective regressed to a bigger one."""
    from apex_tpu.transformer.tensor_parallel import (
        column_parallel_linear,
        row_parallel_linear,
    )

    mesh = _mesh("tensor")
    T, H = 64, 128
    ks = jax.random.split(jax.random.PRNGKey(0), 4)
    x = jax.random.normal(ks[0], (T, H))
    wc = jax.random.normal(ks[1], (256 // 8, H))
    wr = jax.random.normal(ks[2], (H, 256 // 8))
    tgt = jax.random.normal(ks[3], (T, H))

    def f(x, wc, wr):
        def loss(x, wc, wr):
            y, _, _ = column_parallel_linear(
                x, wc, axis_name="tensor", gather_output=False)
            z, _, _ = row_parallel_linear(
                jnp.tanh(y), wr, axis_name="tensor", input_is_parallel=True)
            return jnp.mean((z - tgt) ** 2)

        return jax.grad(loss, argnums=(0, 1, 2))(x, wc, wr)

    g = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=(P(), P("tensor"), P(None, "tensor")),
        out_specs=(P(), P("tensor"), P(None, "tensor")), check_vma=True,
    ))
    vols = collective_bytes(_hlo(g, x, wc, wr))
    expected = 2 * T * H * 4
    _assert_bytes(vols["all-reduce"][1], expected, "TP all-reduce")
    for kind in ("all-gather", "reduce-scatter"):
        assert vols[kind][1] == 0, (kind, vols[kind])


# ---------------------------------------------------------------------------
# SP (Megatron sequence parallelism): gather/scatter pair, fwd+bwd
# ---------------------------------------------------------------------------

def test_sp_step_gather_scatter_volume():
    """One SP column->row block, fwd+bwd. Analytic volume:

    - all-gather: column fwd gathers the seq-scattered input ([S,B,H]
      full out); the weight grad reuses the SAVED gathered activation
      (an [S,B,H] residual, trading memory for one less gather than
      Megatron's recompute-the-gather); the row bwd gathers d(out) —
      2 full activations total.
    - reduce-scatter: row fwd scatters its output and column bwd
      scatters dx (the all-gather transpose) — 2 shard-sized outputs.
    """
    from apex_tpu.transformer.tensor_parallel import (
        column_parallel_linear,
        row_parallel_linear,
    )

    mesh = _mesh("tensor")
    S, B, H = 32, 2, 128
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    x = jax.random.normal(ks[0], (S, B, H))
    wc = jax.random.normal(ks[1], (256 // 8, H))
    wr = jax.random.normal(ks[2], (H, 256 // 8))

    def f(x, wc, wr):
        def loss(x, wc, wr):
            y, _, _ = column_parallel_linear(
                x, wc, axis_name="tensor", gather_output=False,
                sequence_parallel_enabled=True)
            z, _, _ = row_parallel_linear(
                jnp.tanh(y), wr, axis_name="tensor", input_is_parallel=True,
                sequence_parallel_enabled=True)
            return jnp.sum(z ** 2)

        return jax.grad(loss, argnums=(0, 1, 2))(x, wc, wr)

    g = jax.jit(jax.shard_map(
        f, mesh=mesh,
        in_specs=(P("tensor"), P("tensor"), P(None, "tensor")),
        out_specs=(P("tensor"), P("tensor"), P(None, "tensor")),
        check_vma=True,
    ))
    vols = collective_bytes(_hlo(g, x, wc, wr))
    act_full = S * B * H * 4
    act_shard = act_full // 8
    _assert_bytes(vols["all-gather"][1], 2 * act_full, "SP all-gather")
    _assert_bytes(vols["reduce-scatter"][1], 2 * act_shard,
                  "SP reduce-scatter")
    assert vols["all-reduce"][1] <= 1024, vols["all-reduce"]


# ---------------------------------------------------------------------------
# Ring context parallelism: kv rotation volume
# ---------------------------------------------------------------------------

def test_ring_cp_permute_volume():
    """Ring attention fwd+bwd at cp=8. Naively the backward re-rotates
    (k, v) alongside its (dk, dv) accumulators — but the backward's kv
    chain replays the forward's exactly, and XLA CSEs them into ONE
    shared rotation. Analytic (post-CSE) volume: (k, v) hop cp-1 times
    (shared), (dk, dv) hop cp-1 times plus the final home hop = 30
    buffers at cp=8, each one [b, n, s_loc, d] f32 collective-permute.
    This pin is exactly the kind of thing the pair assertions can't
    see: a CSE regression would double the kv traffic with the same op
    STRUCTURE."""
    from apex_tpu.transformer.context_parallel import ring_attention

    mesh = _mesh("cp")
    cp = 8
    b, n, s_glob, d = 1, 2, 128, 8
    s_loc = s_glob // cp  # per-device shard: the hopped buffer size
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(ks[0], (b, n, s_glob, d))
    k = jax.random.normal(ks[1], (b, n, s_glob, d))
    v = jax.random.normal(ks[2], (b, n, s_glob, d))

    def f(q, k, v):
        def loss(q, k, v):
            o = ring_attention(
                q, k, v, axis_name="cp", causal=True, interpret=True)
            return jnp.sum(o.astype(jnp.float32) ** 2)

        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    g = jax.jit(jax.shard_map(
        f, mesh=mesh,
        in_specs=(P(None, None, "cp"), P(None, None, "cp"),
                  P(None, None, "cp")),
        out_specs=(P(None, None, "cp"), P(None, None, "cp"),
                   P(None, None, "cp")),
        check_vma=True,
    ))
    vols = collective_bytes(_hlo(g, q, k, v))
    buf = b * n * s_loc * d * 4  # f32 inputs; dk/dv accumulators f32 too
    kv_shared = 2 * (cp - 1) * buf      # one CSE'd (k, v) rotation
    dkv = 2 * (cp - 1) * buf + 2 * buf  # (dk, dv) + final home hop
    _assert_bytes(vols["collective-permute"][1], kv_shared + dkv,
                  "ring CP hops")
    assert vols["collective-permute"][0] == 4 * cp - 2, vols


# ---------------------------------------------------------------------------
# ZeRO-2 (DistributedFusedAdam): reduce-scatter + all-gather split
# ---------------------------------------------------------------------------

def test_zero2_step_volume():
    """One DistributedFusedAdam step at dp=8: grads reduce-scatter to a
    1/8 shard, updated params all-gather back — the defining ZeRO-2
    economics (vs DDP's full all-reduce = 2x the reduce-scatter volume
    at equal dtype). Volumes derive from the padded flat size."""
    from apex_tpu.contrib.optimizers.distributed_fused_adam import (
        DistributedFusedAdam,
    )

    mesh = _mesh("data")
    kp = jax.random.split(jax.random.PRNGKey(3), 2)
    params = {
        "w": jax.random.normal(kp[0], (100, 64), jnp.float32),
        "b": jax.random.normal(kp[1], (100,), jnp.float32),
    }
    opt = DistributedFusedAdam(
        lr=1e-3, distributed_size=8, distributed_axis="data")
    layout = opt.layout_for(params)
    state = opt.init(params)
    grads = jax.tree_util.tree_map(lambda p: p * 0.01, params)

    def step(grads, state, params):
        return opt.step(grads, state, params)

    g = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), opt.state_specs(), P()),
        out_specs=(P(), opt.state_specs()), check_vma=False,
    ))
    vols = collective_bytes(_hlo(g, grads, state, params))
    flat_bytes = layout.padded * 4  # f32 grad-sync and param-sync
    _assert_bytes(vols["reduce-scatter"][1], flat_bytes // 8,
                  "ZeRO-2 reduce-scatter")
    _assert_bytes(vols["all-gather"][1], flat_bytes, "ZeRO-2 all-gather")
    # the whole point vs DDP: total sync volume ~= 1.125x param bytes,
    # NOT the 2x of reduce-scatter-as-all-reduce + gather-as-broadcast
    total = vols["reduce-scatter"][1] + vols["all-gather"][1]
    assert total <= flat_bytes * 1.25 + 1024, total


# ---------------------------------------------------------------------------
# HLO-parse helpers shared with tools/op_breakdown.py
# ---------------------------------------------------------------------------

def test_shape_bytes_parser():
    """The byte parser behind the volume accounting: dtype table, dims
    products, tuples, and unknown dtypes ignored."""
    assert _shape_bytes("f32[4,128]{1,0}") == 4 * 128 * 4
    assert _shape_bytes("bf16[8,16,1024,64]{3,2,1,0:T(8,128)(2,1)}") == \
        8 * 16 * 1024 * 64 * 2
    assert _shape_bytes("(f32[2,4]{1,0}, s32[8]{0})") == 2 * 4 * 4 + 8 * 4
    assert _shape_bytes("token[]") == 0
    assert _shape_bytes("pred[16]{0}") == 16


def test_collective_bytes_counts_start_once():
    """Async pairs must be charged once (the -start op), never the
    -done half."""
    hlo = """
  %ar = f32[4,4]{1,0} all-reduce(%x), replica_groups={}
  %ag-s = (f32[8]{0}, f32[64]{0}) all-gather-start(%y), dimensions={0}
  %ag-d = f32[64]{0} all-gather-done(%ag-s)
  %cp = bf16[2,2]{1,0} collective-permute(%z), source_target_pairs={{0,1}}
"""
    vols = collective_bytes(hlo)
    assert vols["all-reduce"] == (1, 64)
    assert vols["all-gather"] == (1, (8 + 64) * 4)
    assert vols["collective-permute"] == (1, 8)
