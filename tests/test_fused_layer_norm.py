"""Fused LayerNorm/RMSNorm vs torch references — mirrors
``tests/L0/run_fused_layer_norm/test_fused_layer_norm.py`` tolerance asserts,
plus Pallas-interpret vs XLA equivalence and memory_efficient grad parity."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu.normalization import (
    FusedLayerNorm,
    FusedRMSNorm,
    fused_layer_norm_affine,
    fused_rms_norm_affine,
    manual_rms_norm,
)
from apex_tpu.ops.layer_norm import layer_norm as ln_op
from apex_tpu.ops.layer_norm import rms_norm as rms_op

H = 256
SHAPES = [(4, H), (2, 3, H)]


def _np(seed, shape):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


@pytest.mark.parametrize("shape", SHAPES)
def test_layer_norm_matches_torch(shape):
    x = _np(0, shape)
    w = _np(1, (H,)) * 0.1 + 1.0
    b = _np(2, (H,)) * 0.1
    got = fused_layer_norm_affine(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b), H)
    expect = torch.nn.functional.layer_norm(
        torch.tensor(x), (H,), torch.tensor(w), torch.tensor(b)
    ).numpy()
    np.testing.assert_allclose(np.asarray(got), expect, rtol=1e-5, atol=1e-5)


def test_layer_norm_grads_match_torch():
    x = _np(0, (8, H))
    w = _np(1, (H,)) * 0.1 + 1.0
    b = _np(2, (H,)) * 0.1

    def loss(x, w, b):
        return jnp.sum(fused_layer_norm_affine(x, w, b, H) ** 2)

    gx, gw, gb = jax.grad(loss, argnums=(0, 1, 2))(
        jnp.asarray(x), jnp.asarray(w), jnp.asarray(b)
    )

    tx = torch.tensor(x, requires_grad=True)
    tw = torch.tensor(w, requires_grad=True)
    tb = torch.tensor(b, requires_grad=True)
    tloss = (torch.nn.functional.layer_norm(tx, (H,), tw, tb) ** 2).sum()
    tloss.backward()
    np.testing.assert_allclose(np.asarray(gx), tx.grad.numpy(), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gw), tw.grad.numpy(), rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(np.asarray(gb), tb.grad.numpy(), rtol=1e-4, atol=1e-3)


def test_rms_norm_matches_torch():
    x = _np(3, (8, H))
    w = _np(4, (H,)) * 0.1 + 1.0
    got = fused_rms_norm_affine(jnp.asarray(x), jnp.asarray(w), H, eps=1e-6)
    expect = torch.nn.functional.rms_norm(
        torch.tensor(x), (H,), torch.tensor(w), eps=1e-6
    ).numpy()
    np.testing.assert_allclose(np.asarray(got), expect, rtol=1e-5, atol=1e-5)


def test_rms_norm_grads_match_torch():
    x = _np(3, (8, H))
    w = _np(4, (H,)) * 0.1 + 1.0

    def loss(x, w):
        return jnp.sum(fused_rms_norm_affine(x, w, H, eps=1e-6) ** 2)

    gx, gw = jax.grad(loss, argnums=(0, 1))(jnp.asarray(x), jnp.asarray(w))
    tx = torch.tensor(x, requires_grad=True)
    tw = torch.tensor(w, requires_grad=True)
    tloss = (torch.nn.functional.rms_norm(tx, (H,), tw, eps=1e-6) ** 2).sum()
    tloss.backward()
    np.testing.assert_allclose(np.asarray(gx), tx.grad.numpy(), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gw), tw.grad.numpy(), rtol=1e-4, atol=1e-3)


@pytest.mark.parametrize("memory_efficient", [False, True])
def test_memory_efficient_grads_equal(memory_efficient):
    """memory_efficient recompute path must produce identical grads."""
    x = jnp.asarray(_np(5, (8, H)))
    w = jnp.asarray(_np(6, (H,)) * 0.1 + 1.0)
    b = jnp.asarray(_np(7, (H,)) * 0.1)

    def loss(x, w, b, me):
        return jnp.sum(ln_op(x, w, b, 1, 1e-5, me) ** 2)

    g_ref = jax.grad(loss, argnums=(0, 1, 2))(x, w, b, False)
    g_me = jax.grad(loss, argnums=(0, 1, 2))(x, w, b, memory_efficient)
    for a, e in zip(g_me, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-5)


def test_rms_memory_efficient_grads_equal():
    x = jnp.asarray(_np(5, (8, H)))
    w = jnp.asarray(_np(6, (H,)) * 0.1 + 1.0)

    def loss(x, w, me):
        return jnp.sum(rms_op(x, w, 1, 1e-6, me) ** 2)

    g_ref = jax.grad(loss, argnums=(0, 1))(x, w, False)
    g_me = jax.grad(loss, argnums=(0, 1))(x, w, True)
    for a, e in zip(g_me, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-5)


class TestPallasKernelInterpret:
    """Run the Pallas kernels in interpreter mode on CPU and compare with XLA."""

    def test_ln_fwd_bwd(self):
        x = jnp.asarray(_np(8, (16, H)))
        w = jnp.asarray(_np(9, (H,)) * 0.1 + 1.0)
        b = jnp.asarray(_np(10, (H,)) * 0.1)

        def loss(x, w, b, interp):
            return jnp.sum(ln_op(x, w, b, 1, 1e-5, False, interp) ** 2)

        ref = jax.grad(loss, argnums=(0, 1, 2))(x, w, b, False)
        pal = jax.grad(loss, argnums=(0, 1, 2))(x, w, b, True)
        np.testing.assert_allclose(
            float(loss(x, w, b, True)), float(loss(x, w, b, False)), rtol=1e-5
        )
        for a, e in zip(pal, ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-5)

    def test_rms_fwd_bwd(self):
        x = jnp.asarray(_np(11, (16, H)))
        w = jnp.asarray(_np(12, (H,)) * 0.1 + 1.0)

        def loss(x, w, interp):
            return jnp.sum(rms_op(x, w, 1, 1e-6, False, interp) ** 2)

        ref = jax.grad(loss, argnums=(0, 1))(x, w, False)
        pal = jax.grad(loss, argnums=(0, 1))(x, w, True)
        for a, e in zip(pal, ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(e), rtol=1e-4, atol=1e-5)


class TestModules:
    def test_fused_layer_norm_module(self):
        m = FusedLayerNorm(normalized_shape=H)
        x = jnp.asarray(_np(13, (4, H)))
        params = m.init(jax.random.PRNGKey(0), x)
        y = m.apply(params, x)
        expect = torch.nn.functional.layer_norm(torch.tensor(np.asarray(x)), (H,)).numpy()
        np.testing.assert_allclose(np.asarray(y), expect, rtol=1e-5, atol=1e-5)

    def test_mixed_fused_rms_norm_bf16_input_fp32_params(self):
        from apex_tpu.normalization import MixedFusedRMSNorm

        m = MixedFusedRMSNorm(normalized_shape=H)
        x = jnp.asarray(_np(14, (4, H)), jnp.bfloat16)
        params = m.init(jax.random.PRNGKey(0), x)
        assert params["params"]["weight"].dtype == jnp.float32
        y = m.apply(params, x)
        assert y.dtype == jnp.bfloat16

    def test_non_affine(self):
        m = FusedLayerNorm(normalized_shape=H, elementwise_affine=False)
        x = jnp.asarray(_np(15, (4, H)))
        params = m.init(jax.random.PRNGKey(0), x)
        y = m.apply(params, x)
        assert np.allclose(np.asarray(y).mean(axis=-1), 0.0, atol=1e-5)


def test_manual_rms_norm_matches_fused():
    x = jnp.asarray(_np(16, (4, H)))
    w = jnp.asarray(_np(17, (H,)) * 0.1 + 1.0)
    np.testing.assert_allclose(
        np.asarray(manual_rms_norm(x, (H,), w, 1e-6)),
        np.asarray(fused_rms_norm_affine(x, w, H, eps=1e-6)),
        rtol=1e-5,
        atol=1e-5,
    )


def test_shape_mismatch_raises():
    x = jnp.zeros((4, 256))
    with pytest.raises(ValueError, match="normalized_shape"):
        fused_layer_norm_affine(x, jnp.ones((512,)), jnp.zeros((512,)), 512)


def test_memory_efficient_zero_gamma_no_nan():
    """Zero-init gamma (common for residual norms) must not NaN under
    memory_efficient (clamped inverse-affine)."""
    x = jnp.asarray(_np(18, (8, H)))
    w = jnp.zeros((H,))
    b = jnp.zeros((H,))

    def loss(x, w, b):
        return jnp.sum(ln_op(x, w, b, 1, 1e-5, True) ** 2)

    grads = jax.grad(loss, argnums=(0, 1, 2))(x, w, b)
    for g in grads:
        assert np.all(np.isfinite(np.asarray(g)))


def test_memory_efficient_bf16_grads_close():
    """me path keeps xhat in fp32 — bf16 grads should track the non-me path."""
    x = jnp.asarray(_np(19, (8, H)), jnp.bfloat16)
    w = jnp.asarray(_np(20, (H,)) * 0.1 + 1.0, jnp.bfloat16)
    b = jnp.asarray(_np(21, (H,)) * 0.1, jnp.bfloat16)

    def loss(x, w, b, me):
        return jnp.sum(ln_op(x, w, b, 1, 1e-5, me).astype(jnp.float32) ** 2)

    g_me = jax.grad(loss, argnums=(0, 1, 2))(x, w, b, True)
    g_ref = jax.grad(loss, argnums=(0, 1, 2))(x, w, b, False)
    for a, e in zip(g_me, g_ref):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(e, np.float32), rtol=0.05, atol=0.05
        )


def test_mixed_pins_param_dtype():
    from apex_tpu.normalization import MixedFusedLayerNorm

    with pytest.raises(ValueError, match="pins param_dtype"):
        MixedFusedLayerNorm(normalized_shape=H, param_dtype=jnp.bfloat16)
