"""Test harness: 8 virtual CPU devices, mirroring the reference's
single-node multi-process testing strategy (SURVEY.md §4,
``apex/transformer/testing/distributed_test_base.py``) — but SPMD: one
process, an 8-device mesh, deterministic seeds."""
import os

# Must run before jax initialises its backends. NB: the environment's
# sitecustomize imports jax at interpreter boot (axon TPU plugin), so plain
# env vars are too late — use jax.config.update, which works as long as no
# backend has been initialised yet.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

assert jax.default_backend() == "cpu" and len(jax.devices()) == 8, (
    "test harness expects 8 virtual CPU devices; got "
    f"{jax.default_backend()} x{len(jax.devices())}"
)


# ---------------------------------------------------------------------------
# Test tiers (the reference's L0-default vs full-suite split,
# ``tests/L0/run_test.py:29-33``): tests measured slow on the 8-device CPU
# harness are listed in tests/slow_tests.txt and marked ``slow`` here, so
#   python -m pytest tests/ -q -m "not slow"
# is the quick tier (~2 min) and the bare run is the full suite. New tests
# are quick by default; re-generate the list with --durations when a test
# grows past a few seconds.
# ---------------------------------------------------------------------------
import pathlib

import pytest as _pytest

_SLOW_LIST = pathlib.Path(__file__).parent / "slow_tests.txt"
_SLOW_IDS = frozenset(
    line.strip() for line in _SLOW_LIST.read_text().splitlines()
    if line.strip()
) if _SLOW_LIST.exists() else frozenset()


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: measured slow on the CPU harness (excluded from "
        "the quick tier; see tests/slow_tests.txt)"
    )


def pytest_collection_modifyitems(config, items):
    matched = set()
    for item in items:
        nodeid = item.nodeid.replace("\\", "/")
        if not nodeid.startswith("tests/"):
            nodeid = "tests/" + nodeid
        if nodeid in _SLOW_IDS:
            matched.add(nodeid)
            item.add_marker(_pytest.mark.slow)
    # a renamed/re-parametrized slow test would silently re-enter the quick
    # tier; warn only about stale entries whose FILE was collected, so
    # partial runs (--ignore, single files) don't fire spuriously
    collected_files = {
        item.nodeid.replace("\\", "/").split("::")[0] for item in items
    }
    collected_files |= {"tests/" + f for f in collected_files}
    stale = {
        sid for sid in _SLOW_IDS - matched
        if sid.split("::")[0] in collected_files
    }
    node_selected = any("::" in str(a) for a in config.args)
    if stale and not config.getoption("-k") and not node_selected:
        import warnings

        warnings.warn(
            "tests/slow_tests.txt entries match no collected test "
            f"(rename/param drift?): {sorted(stale)[:5]}"
            + (" ..." if len(stale) > 5 else "")
        )
