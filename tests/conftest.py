"""Test harness: 8 virtual CPU devices, mirroring the reference's
single-node multi-process testing strategy (SURVEY.md §4,
``apex/transformer/testing/distributed_test_base.py``) — but SPMD: one
process, an 8-device mesh, deterministic seeds."""
import os

# Must run before jax initialises its backends. NB: the environment's
# sitecustomize imports jax at interpreter boot (axon TPU plugin), so plain
# env vars are too late — use jax.config.update, which works as long as no
# backend has been initialised yet.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

assert jax.default_backend() == "cpu" and len(jax.devices()) == 8, (
    "test harness expects 8 virtual CPU devices; got "
    f"{jax.default_backend()} x{len(jax.devices())}"
)
