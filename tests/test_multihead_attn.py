"""contrib.multihead_attn tests — vs an explicit torch-style reference.

Mirrors the reference suite (`apex/contrib/test/multihead_attn/`): the
fused module against a plain composition of the same math, across the
variant matrix (bias, separate qkv, padding mask, additive mask,
norm-add residual, encdec).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.multihead_attn import (
    EncdecMultiheadAttn,
    SelfMultiheadAttn,
    mask_softmax_dropout,
)

S, B, H, N = 16, 2, 32, 4


def _x(key=0, s=S):
    return jax.random.normal(jax.random.PRNGKey(key), (s, B, H)) * 0.5


def _ref_self_attn(params, x, module, key_padding_mask=None, attn_mask=None):
    """Plain-composition reference for SelfMultiheadAttn (no dropout)."""
    h, n = module.embed_dim, module.num_heads
    d = h // n
    if module.include_norm_add:
        residual = x
        mu = x.mean(-1, keepdims=True)
        var = ((x - mu) ** 2).mean(-1, keepdims=True)
        xn = (x - mu) * jax.lax.rsqrt(var + 1e-5)
        x = xn * params["lyr_nrm_gamma_weights"] + params["lyr_nrm_beta_weights"]
    s_len = x.shape[0]
    if module.separate_qkv_params:
        # project with the raw per-matrix weights (NOT module._in_proj —
        # that is the code under test); split heads directly
        def proj(wk, bk):
            y = jnp.einsum("sbh,oh->sbo", x, params[wk])
            if module.bias:
                y = y + params[bk]
            return y.reshape(s_len, B, n, d).transpose(1, 2, 0, 3)

        q = proj("q_weight", "q_bias")
        k = proj("k_weight", "k_bias")
        v = proj("v_weight", "v_bias")
    else:
        w = params["in_proj_weight"]
        qkv = jnp.einsum("sbh,oh->sbo", x, w)
        if module.bias:
            qkv = qkv + params["in_proj_bias"]
        qkv = qkv.reshape(s_len, B, n, 3, d)
        q = qkv[..., 0, :].transpose(1, 2, 0, 3)  # [b, n, s, d]
        k = qkv[..., 1, :].transpose(1, 2, 0, 3)
        v = qkv[..., 2, :].transpose(1, 2, 0, 3)
    scores = jnp.einsum("bnqd,bnkd->bnqk", q, k) * module.scaling
    if key_padding_mask is not None:
        scores = jnp.where(
            key_padding_mask[:, None, None, :] != 0, -1e30, scores)
    if attn_mask is not None:
        scores = scores + attn_mask
    p = jax.nn.softmax(scores, axis=-1)
    ctx = jnp.einsum("bnqk,bnkd->bnqd", p, v)
    ctx = ctx.transpose(2, 0, 1, 3).reshape(s_len, B, h)
    out = jnp.einsum("sbh,oh->sbo", ctx, params["out_proj_weight"])
    if module.bias:
        out = out + params["out_proj_bias"]
    if module.include_norm_add:
        out = residual + out
    return out


@pytest.mark.parametrize("bias", [False, True])
@pytest.mark.parametrize("separate", [False, True])
def test_self_attn_matches_reference(bias, separate):
    m = SelfMultiheadAttn(H, N, bias=bias, separate_qkv_params=separate)
    params = m.init(jax.random.PRNGKey(0))
    x = _x()
    out, _ = m(params, x, is_training=False)
    ref = _ref_self_attn(params, x, m)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_self_attn_key_padding_mask():
    m = SelfMultiheadAttn(H, N, bias=True)
    params = m.init(jax.random.PRNGKey(1))
    x = _x(1)
    kpm = jnp.zeros((B, S), jnp.int32).at[:, -5:].set(1)  # 1 = masked out
    out, _ = m(params, x, key_padding_mask=kpm, is_training=False)
    ref = _ref_self_attn(params, x, m, key_padding_mask=kpm)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    # masked keys truly don't contribute: poisoning them changes nothing
    x2 = x.at[-5:].set(1e3)
    out2, _ = m(params, x2, key_padding_mask=kpm, is_training=False)
    np.testing.assert_allclose(
        np.asarray(out[:-5]), np.asarray(out2[:-5]), atol=2e-4)


def test_self_attn_additive_mask():
    m = SelfMultiheadAttn(H, N, bias=True, mask_additive=True)
    params = m.init(jax.random.PRNGKey(2))
    x = _x(2)
    causal = jnp.where(
        jnp.triu(jnp.ones((S, S)), k=1) > 0, -1e30, 0.0)[None, None]
    out, _ = m(params, x, attn_mask=causal, is_training=False)
    ref = _ref_self_attn(params, x, m, attn_mask=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_self_attn_norm_add_residual():
    m = SelfMultiheadAttn(H, N, bias=True, include_norm_add=True)
    params = m.init(jax.random.PRNGKey(3))
    x = _x(3)
    out, _ = m(params, x, is_training=False)
    ref = _ref_self_attn(params, x, m)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-5)
    # with zeroed out-proj the block must be the identity (pure residual)
    p0 = dict(params)
    p0["out_proj_weight"] = jnp.zeros_like(params["out_proj_weight"])
    p0["out_proj_bias"] = jnp.zeros_like(params["out_proj_bias"])
    out0, _ = m(p0, x, is_training=False)
    np.testing.assert_allclose(np.asarray(out0), np.asarray(x), atol=1e-6)


def test_self_attn_dropout_determinism_and_effect():
    m = SelfMultiheadAttn(H, N, bias=True, dropout=0.3)
    params = m.init(jax.random.PRNGKey(4))
    x = _x(4)
    k = jax.random.PRNGKey(7)
    o1, _ = m(params, x, is_training=True, dropout_key=k)
    o2, _ = m(params, x, is_training=True, dropout_key=k)
    o3, _ = m(params, x, is_training=True, dropout_key=jax.random.PRNGKey(8))
    oe, _ = m(params, x, is_training=False)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    assert not np.allclose(np.asarray(o1), np.asarray(o3))
    assert not np.allclose(np.asarray(o1), np.asarray(oe))
    with pytest.raises(ValueError, match="dropout"):
        m(params, x, is_training=True)


def test_self_attn_grads_finite():
    m = SelfMultiheadAttn(H, N, bias=True, include_norm_add=True)
    params = m.init(jax.random.PRNGKey(5))
    x = _x(5)
    g = jax.grad(lambda p: jnp.sum(m(p, x, is_training=False)[0] ** 2))(params)
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()
        assert np.abs(np.asarray(leaf)).max() > 0


def test_encdec_attn():
    m = EncdecMultiheadAttn(H, N, bias=True)
    params = m.init(jax.random.PRNGKey(6))
    q = _x(6, s=8)
    enc = _x(7, s=S)

    out, _ = m(params, q, enc, is_training=False)
    assert out.shape == (8, B, H)

    # reference composition
    h, n, d = H, N, H // N
    qq = jnp.einsum("sbh,oh->sbo", q, params["q_weight"]) + params["q_bias"]
    kv = jnp.einsum("sbh,oh->sbo", enc, params["kv_weight"]) + params["kv_bias"]
    kv = kv.reshape(S, B, n, 2, d)
    qh = qq.reshape(8, B, n, d).transpose(1, 2, 0, 3)
    kh = kv[..., 0, :].transpose(1, 2, 0, 3)
    vh = kv[..., 1, :].transpose(1, 2, 0, 3)
    p = jax.nn.softmax(
        jnp.einsum("bnqd,bnkd->bnqk", qh, kh) * m.scaling, axis=-1)
    ctx = jnp.einsum("bnqk,bnkd->bnqd", p, vh)
    ref = jnp.einsum(
        "sbh,oh->sbo",
        ctx.transpose(2, 0, 1, 3).reshape(8, B, h),
        params["out_proj_weight"]) + params["out_proj_bias"]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_mask_softmax_dropout_func():
    scores = jax.random.normal(jax.random.PRNGKey(0), (B, N, S, S))
    kpm = jnp.zeros((B, S), jnp.int32).at[:, -3:].set(1)
    p = mask_softmax_dropout(scores, kpm)
    pn = np.asarray(p)
    np.testing.assert_allclose(pn.sum(-1), 1.0, atol=1e-5)
    assert np.abs(pn[..., -3:]).max() == 0.0


def test_self_attn_additive_2d_key_padding_mask():
    """mask_additive with a [b, sk] additive key-padding mask (the
    reference contract for the flag) must broadcast over heads/queries."""
    m = SelfMultiheadAttn(H, N, bias=True, mask_additive=True)
    params = m.init(jax.random.PRNGKey(9))
    x = _x(9)
    add_kpm = jnp.zeros((B, S)).at[:, -4:].set(-1e30)  # additive padding
    out, _ = m(params, x, key_padding_mask=add_kpm, is_training=False)
    # equivalent boolean padding through the non-additive module
    m2 = SelfMultiheadAttn(H, N, bias=True)
    kpm = jnp.zeros((B, S), jnp.int32).at[:, -4:].set(1)
    ref, _ = m2(params, x, key_padding_mask=kpm, is_training=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_additive_mask_carries_no_gradient_on_both_paths(monkeypatch):
    """Reference parity: autograd functions return None for mask inputs.
    The flash dispatch (bias_grad=False) and the fallback softmax path
    (stop_gradient) must agree: zero cotangent for additive masks."""
    monkeypatch.delenv("APEX_TPU_DISABLE_FLASH", raising=False)
    mod = SelfMultiheadAttn(embed_dim=32, num_heads=2, mask_additive=True)
    params = mod.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (16, 2, 32))
    kpm = jnp.zeros((2, 16))

    def loss(m):
        out = mod(params, x, key_padding_mask=m, is_training=False)
        out = out[0] if isinstance(out, tuple) else out
        return jnp.sum(out ** 2)

    g_flash = jax.grad(loss)(kpm)
    assert jnp.abs(g_flash).max() == 0.0
    monkeypatch.setenv("APEX_TPU_DISABLE_FLASH", "1")
    g_fallback = jax.grad(loss)(kpm)
    assert jnp.abs(g_fallback).max() == 0.0
