"""apex_tpu.analysis collectives & sharding rules (ISSUE-19).

Red tests: one seeded violation per new rule family (over-budget psum,
vanished psum, undeclared axis, oversized gather, cond-divergent
collective, unbucketed loop reductions, indivisible/unknown/duplicate
shard specs, broken Megatron psum pairing). Green tests: the repo's own
tensor-parallel serving programs and the bucketed DDP step reproduce
their pinned communication budgets *statically* via ``comm_volume``, and
self-audit clean with the collective/sharding rules on.

Everything here is jaxpr tracing on the 8-virtual-CPU-device harness —
no execution, no kernels.
"""
import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from apex_tpu.analysis import (  # noqa: E402
    CollectiveBudget,
    assert_step_clean,
    audit_step,
    check_collective_budget,
    check_shard_specs,
    collective_inventory,
    comm_volume,
)
from apex_tpu.parallel import DistributedDataParallel, GradBuckets  # noqa: E402
from tools import static_audit  # noqa: E402


def _mesh(*axes, shape=None):
    devs = np.array(jax.devices()[: int(np.prod(shape or [8]))])
    return Mesh(devs.reshape(shape or (8,)), axes)


def _codes(findings, severity=None):
    return [f.code for f in findings
            if severity is None or f.severity == severity]


def _inventory(fn, *args):
    return collective_inventory(jax.make_jaxpr(fn)(*args).jaxpr)


# ---------------------------------------------------------------------------
# comm_volume: the structured inventory
# ---------------------------------------------------------------------------
def test_comm_volume_counts_axes_and_bytes():
    mesh = _mesh("data")

    def body(x):
        y = jax.lax.psum(x, "data")             # out: 16*4 B
        g = jax.lax.all_gather(y, "data")       # out: 8*16*4 B
        return g.sum()

    f = shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P(),
                  check_rep=False)
    vol = comm_volume(f, jnp.zeros((128,), jnp.float32))
    assert vol["psum"] == {"count": 1, "bytes": 64, "axes": ["data"]}
    assert vol["all_gather"] == {"count": 1, "bytes": 512, "axes": ["data"]}


def test_comm_volume_counts_loop_bodies_once():
    """Static program shape: a psum inside a scan body is ONE eqn —
    the convention the serving 3-psum pin is stated in."""
    mesh = _mesh("data")

    def body(x):
        def it(c, t):
            return c + jax.lax.psum(t, "data"), ()

        c, _ = jax.lax.scan(it, jnp.float32(0), x)
        return c

    f = shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P(),
                  check_rep=False)
    vol = comm_volume(f, jnp.zeros((64,), jnp.float32))
    assert vol["psum"]["count"] == 1


def test_comm_volume_abstract_args():
    """ShapeDtypeStruct args trace without any real buffers."""
    mesh = _mesh("data")
    f = shard_map(lambda x: jax.lax.psum(x, "data"), mesh=mesh,
                  in_specs=P("data"), out_specs=P(), check_rep=False)
    vol = comm_volume(f, jax.ShapeDtypeStruct((64,), jnp.bfloat16))
    assert vol["psum"]["count"] == 1 and vol["psum"]["bytes"] == 16


# ---------------------------------------------------------------------------
# collective budgets: red, one per failure mode
# ---------------------------------------------------------------------------
def _psum_program():
    mesh = _mesh("data")
    f = shard_map(lambda x: jax.lax.psum(x, "data"), mesh=mesh,
                  in_specs=P("data"), out_specs=P(), check_rep=False)
    return f, (jnp.zeros((64,), jnp.float32),)


def test_budget_red_over_budget_psum():
    fn, args = _psum_program()
    rep = audit_step(fn, *args,
                     collective_budget=CollectiveBudget(counts={}))
    assert "over_budget_collective" in _codes(rep.findings, "error")
    f = [x for x in rep.errors if x.code == "over_budget_collective"][0]
    assert f.data == {"collective": "psum", "budget": 0, "actual": 1}


def test_budget_red_missing_collective():
    """Exact pin: a vanished reduction is a numerics hazard, not a win."""
    fn, args = _psum_program()
    rep = audit_step(fn, *args,
                     collective_budget=CollectiveBudget(
                         counts={"psum": 1, "all_gather": 1}))
    assert "missing_collective" in _codes(rep.findings, "error")


def test_budget_red_unknown_axis():
    fn, args = _psum_program()  # psums over "data"
    rep = audit_step(fn, *args,
                     collective_budget=CollectiveBudget(
                         counts={"psum": 1}, axes=("tensor",)))
    assert "unknown_axis_collective" in _codes(rep.findings, "error")


def test_budget_red_oversized_gather():
    mesh = _mesh("data")
    f = shard_map(lambda x: jax.lax.all_gather(x, "data"), mesh=mesh,
                  in_specs=P("data"), out_specs=P(None, "data"),
                  check_rep=False)
    x = jnp.zeros((8 * 1024,), jnp.float32)  # gathered output: 32 KiB
    rep = audit_step(f, x, collective_budget=CollectiveBudget(
        max_gather_bytes=1 << 14))
    assert "oversized_gather" in _codes(rep.findings, "error")
    ok = audit_step(f, x, collective_budget=CollectiveBudget(
        max_gather_bytes=1 << 20))
    assert "oversized_gather" not in ok.codes()


def test_budget_green_matching_pin():
    fn, args = _psum_program()
    rep = assert_step_clean(
        fn, *args, collective_budget=CollectiveBudget(
            counts={"psum": 1}, axes=("data",)))
    assert rep.ok


def test_check_collective_budget_standalone():
    fn, args = _psum_program()
    inv = _inventory(fn, *args)
    bad = check_collective_budget(inv, CollectiveBudget(counts={}))
    assert _codes(bad) == ["over_budget_collective"]
    assert check_collective_budget(
        inv, CollectiveBudget(counts={"psum": 1}, axes=("data",))) == []


# ---------------------------------------------------------------------------
# SPMD divergence lints
# ---------------------------------------------------------------------------
def test_red_cond_divergent_collective():
    mesh = _mesh("data")

    def body(x):
        return jax.lax.cond(
            x.sum() > 0,
            lambda v: jax.lax.psum(v, "data"),  # collective in ONE branch
            lambda v: v * 2.0,
            x)

    f = shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P("data"),
                  check_rep=False)
    rep = audit_step(f, jnp.zeros((64,), jnp.float32))
    assert "cond_divergent_collective" in _codes(rep.findings, "warning")
    br = [x for x in rep.findings
          if x.code == "cond_divergent_collective"][0].data["branches"]
    assert {"psum@data": 1} in br and {} in br


def test_green_cond_with_matching_branches():
    mesh = _mesh("data")

    def body(x):
        return jax.lax.cond(
            x.sum() > 0,
            lambda v: jax.lax.psum(v, "data") * 2.0,
            lambda v: jax.lax.psum(v, "data") * 0.5,
            x)

    f = shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P("data"),
                  check_rep=False)
    rep = audit_step(f, jnp.zeros((64,), jnp.float32))
    assert "cond_divergent_collective" not in rep.codes()


def test_red_unbucketed_loop_collectives():
    """Per-leaf psums in a scan body — the anti-pattern GradBuckets
    exists to kill — trip the hoist-and-bucket warning."""
    mesh = _mesh("data")

    def body(xs):
        def it(c, t):
            # four per-leaf reductions per iteration
            return c + sum(jax.lax.psum(t * k, "data")
                           for k in (1.0, 2.0, 3.0, 4.0)), ()

        c, _ = jax.lax.scan(it, jnp.float32(0), xs)
        return c

    f = shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P(),
                  check_rep=False)
    rep = audit_step(f, jnp.zeros((64,), jnp.float32))
    hits = [x for x in rep.findings
            if x.code == "unbucketed_loop_collectives"]
    assert hits and hits[0].severity == "warning"
    assert hits[0].data["count"] == 4 and hits[0].data["axes"] == "data"


# ---------------------------------------------------------------------------
# sharding specs
# ---------------------------------------------------------------------------
def test_red_indivisible_shard_dim():
    """jax itself raises at trace time on this layout; the standalone
    checker is the pre-trace gate the mesh-rebase workflow runs."""
    bad = check_shard_specs({"data": 8}, [P("data")], shapes=[(63,)])
    assert _codes(bad, "error") == ["indivisible_shard_dim"]
    assert bad[0].data["dim_size"] == 63 and bad[0].data["factor"] == 8
    assert check_shard_specs({"data": 8}, [P("data")], shapes=[(64,)]) == []


def test_red_unknown_mesh_axis_spec():
    bad = check_shard_specs({"data": 8}, [P("model")])
    assert "unknown_mesh_axis" in _codes(bad, "error")


def test_red_duplicate_mesh_axis_spec():
    bad = check_shard_specs({"data": 8}, [P("data", "data")],
                            shapes=[(64, 64)])
    assert "duplicate_mesh_axis" in _codes(bad, "error")


def test_check_shard_specs_accepts_real_mesh_and_multi_axis():
    mesh = _mesh("dp", "tp", shape=(4, 2))
    assert check_shard_specs(mesh, [P(("dp", "tp"), None)],
                             shapes=[(16, 32)]) == []
    bad = check_shard_specs(mesh, [P(("dp", "tp"), None)],
                            shapes=[(12, 32)])  # 12 % 8 != 0
    assert "indivisible_shard_dim" in _codes(bad)


def test_red_unpaired_psum_tail():
    """psum(psum(x @ w)) over the same axis with no GEMM between — the
    classic double-reduction tensor-parallel bug."""
    mesh = _mesh("tensor")

    def body(x, w):
        y = jax.lax.psum(x @ w, "tensor")
        return jax.lax.psum(y * 2.0, "tensor")  # already reduced!

    f = shard_map(body, mesh=mesh,
                  in_specs=(P(None, "tensor"), P("tensor", None)),
                  out_specs=P(), check_rep=False)
    rep = audit_step(f, jnp.zeros((16, 64), jnp.float32),
                     jnp.zeros((64, 16), jnp.float32))
    assert "unpaired_psum_tail" in _codes(rep.findings, "warning")


def test_green_column_row_psum_pairing():
    """The legal Megatron shape: column GEMM -> row GEMM -> one psum."""
    mesh = _mesh("tensor")

    def body(x, wc, wr):
        y = x @ wc                    # column-parallel (no comm)
        z = jnp.tanh(y) @ wr          # row-parallel partial
        return jax.lax.psum(z, "tensor")  # exactly one tail

    f = shard_map(body, mesh=mesh,
                  in_specs=(P(), P(None, "tensor"), P("tensor", None)),
                  out_specs=P(), check_rep=False)
    rep = audit_step(f, jnp.zeros((16, 64), jnp.float32),
                     jnp.zeros((64, 32), jnp.float32),
                     jnp.zeros((32, 64), jnp.float32))
    assert "unpaired_psum_tail" not in rep.codes()


def test_red_large_replicated_operand():
    mesh = _mesh("data")

    def body(w, x):
        return (x @ w).sum()

    f = shard_map(body, mesh=mesh, in_specs=(P(), P("data", None)),
                  out_specs=P(), check_rep=False)
    w = jnp.zeros((512, 512), jnp.float32)  # 1 MiB, replicated
    x = jnp.zeros((64, 512), jnp.float32)
    rep = audit_step(f, w, x)
    hits = [h for h in rep.findings
            if h.code == "large_replicated_operand"]
    assert hits and hits[0].severity == "warning"
    assert hits[0].data["bytes"] == 512 * 512 * 4
    # raising the threshold silences the scouting report
    quiet = audit_step(f, w, x, replicated_bytes=1 << 24)
    assert "large_replicated_operand" not in quiet.codes()


# ---------------------------------------------------------------------------
# deep nesting: the inventory (and _contains_prim) see through
# shard_map -> scan -> cond -> pjit stacks of any depth
# ---------------------------------------------------------------------------
def _deeply_nested_program():
    mesh = _mesh("data")

    def body(xs):
        def it(c, t):
            def deep(v):
                return jax.jit(
                    lambda u: jax.lax.psum(jnp.sin(u), "data"))(v)

            y = jax.lax.cond(t.sum() > 0, deep, deep, t)
            return c + y.sum(), ()

        c, _ = jax.lax.scan(it, jnp.float32(0), xs)
        return c

    f = shard_map(body, mesh=mesh, in_specs=P(None, "data"), out_specs=P(),
                  check_rep=False)
    return f, (jnp.zeros((4, 64), jnp.float32),)


def test_deep_nesting_inventory_finds_collective():
    fn, args = _deeply_nested_program()
    inv = _inventory(fn, *args)
    psums = [r for r in inv if r.name == "psum"]
    # one per cond branch (each counted once; the scan body once)
    assert psums and all(r.axes == ("data",) for r in psums)
    assert all(r.cond_depth >= 1 and r.loop_depth >= 1 for r in psums)


def test_deep_nesting_contains_prim_unbounded():
    """The old default depth cap (4) stopped exactly at shard_map ->
    scan -> cond -> pjit; the lifted default must see the psum."""
    from apex_tpu.analysis.rules import _contains_prim

    fn, args = _deeply_nested_program()
    closed = jax.make_jaxpr(fn)(*args)
    assert _contains_prim(closed.jaxpr, ("psum",))
    # an explicit cap still works as an opt-in bound
    assert not _contains_prim(closed.jaxpr, ("psum",), max_depth=2)


def test_deep_nesting_budget_enforced():
    fn, args = _deeply_nested_program()
    rep = audit_step(fn, *args,
                     collective_budget=CollectiveBudget(counts={}))
    assert "over_budget_collective" in _codes(rep.findings, "error")


# ---------------------------------------------------------------------------
# the pinned budgets, machine-derived: serving TP + DDP
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def tp_engine():
    from apex_tpu.serving import ServingEngine
    from apex_tpu.transformer.testing import GPTConfig, init_gpt_params

    cfg = GPTConfig(
        num_layers=2, num_attention_heads=4, hidden_size=64,
        vocab_size=128, max_position_embeddings=64,
        hidden_dropout=0.0, attention_dropout=0.0,
        compute_dtype=jnp.float32,
    )
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    return ServingEngine(cfg, params, n_slots=2, tp=2, use_kernel=False,
                        prefill_chunk=16, spec_k=2, telemetry_every=4)


def test_serving_psum_pins_are_comm_volume_derived(tp_engine):
    """The PR-16 3-psum pin, now stated per program by the walker: the
    textual str(jaxpr).count is gone and the counts come from
    program_comm_volume."""
    vol = tp_engine.program_comm_volume()
    assert set(vol) == {"decode", "chunk_prefill", "spec_verify"}
    for prog, v in vol.items():
        assert v["psum"]["count"] == 3, (prog, v)
        assert v["psum"]["axes"] == ["tensor"], (prog, v)
        # every collective in every program rides the tensor axis only
        assert all(c["axes"] == ["tensor"] for c in v.values()), (prog, v)
    assert tp_engine.program_psum_counts() == {
        "decode": 3, "chunk_prefill": 3, "spec_verify": 3}


def test_serving_comm_budget_target_green(tp_engine):
    fn, args = tp_engine.step_program()
    budget = CollectiveBudget(
        counts={"psum": 3, "all_gather": 2, "pmax": 1, "pmin": 1},
        axes=("tensor",), max_gather_bytes=1 << 20)
    inv = _inventory(fn, *args)
    assert check_collective_budget(inv, budget) == []


def test_ddp_psum_budget_is_n_buckets_plus_loss(tp_engine):
    """psum count == n_buckets + 1 (the pmean'd loss lowers to psum +
    divide), all over 'data' — the PR-14 pin, derived statically."""
    fn, args, _ = static_audit.build_ddp_step()
    buckets = GradBuckets(args[0], bucket_cap_mb=0.5)
    vol = comm_volume(fn, *args)
    assert buckets.n_buckets >= 2  # the config actually buckets
    assert vol["psum"]["count"] == buckets.n_buckets + 1
    assert vol["psum"]["axes"] == ["data"]
    assert set(vol) == {"psum"}  # no other collective family at all


def test_ddp_collective_budget_helper():
    fn, args, _ = static_audit.build_ddp_step()
    buckets = GradBuckets(args[0], bucket_cap_mb=0.5)
    ddp = DistributedDataParallel(axis_name="data",
                                  gradient_average=False,
                                  bucket_cap_mb=0.5)
    budget = ddp.collective_budget(buckets, extra_psums=1)
    assert budget.counts == {"psum": buckets.n_buckets + 1}
    assert budget.axes == ("data",)
    assert check_collective_budget(_inventory(fn, *args), budget) == []


def test_self_audit_comm_targets_clean():
    """The budget-checked CLI targets (tp_serving_comm / ddp_comm) pass
    with their declared budgets — tier-1 wiring for the comm gates."""
    for target in ("tp_serving_comm", "ddp_comm"):
        fn, args, kw = static_audit.TARGETS[target]()
        assert kw.get("collective_budget") is not None
        rep = assert_step_clean(fn, *args, name=target, **kw)
        assert rep.ok
