"""Tied input/output embeddings under pipeline parallelism vs dense.

Two layouts, both pinned against a single-device dense reference:

1. **Vocab-sharded over (pipeline, tensor)** — the `__graft_entry__`
   layout: no stage stores the full table, the lookup/head are
   vocab-parallel over the combined axes, and the tied gradient lands on
   each owner's rows. Under ``check_vma=True`` the grads need NO manual
   sync at all: the vma type system inserts the exact psums (replicated
   inputs get their cotangents all-reduced; a replicated-typed loss seeds
   its cotangent exactly once).
2. **Replicated over pipeline** — the reference layout
   (``apex/transformer/parallel_state.py:319-407``: first/last stage own a
   copy of the tied table and all-reduce its grad over the embedding
   group). Driven as a MANUAL flow (``check_vma=False``): autodiff then
   leaves per-stage partial grads exactly like the reference's per-rank
   ``.grad`` fields — input-side on the first stage, head-side on the
   last — and ``sync_embedding_grads`` performs the embedding-group
   all-reduce.

Plus unit tests of the group masking itself (junk on non-group ranks must
be dropped; split-rank groups must include the split stage).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_pipelining_without_interleaving import (
    pipeline_rounds,
)
from apex_tpu.transformer.pipeline_parallel.utils import (
    pvary_full,
    sync_embedding_grads,
    sync_position_embedding_grads,
)
from apex_tpu.transformer.tensor_parallel import (
    column_parallel_linear,
    vocab_parallel_cross_entropy,
    vocab_parallel_embedding,
)

N_MICRO = 4
H = 8
V = 32
S = 8


def _make_params(key, pp):
    ks = jax.random.split(key, 2 + pp)
    return {
        "word": jax.random.normal(ks[0], (V, H)) * 0.5,
        "pos": jax.random.normal(ks[1], (S, H)) * 0.1,
        "w": jnp.stack(
            [jax.random.normal(k, (H, H)) * 0.5 for k in ks[2:]]
        ),
        "b": jnp.zeros((pp, H)),
    }


def _dense_loss(pp):
    def loss(params, tokens, labels):
        emb = jnp.take(params["word"], tokens, axis=0) + params["pos"][:S]
        h = emb  # [n, b, s, h]
        for st in range(pp):
            h = jnp.tanh(
                jnp.einsum("nbsh,oh->nbso", h, params["w"][st])
                + params["b"][st]
            )
        logits = jnp.einsum("nbsh,vh->nbsv", h, params["word"])
        logp = jax.nn.log_softmax(logits, axis=-1)
        ce = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        return jnp.mean(ce)

    return loss


@pytest.mark.parametrize("pp,dp,tp", [(2, 2, 2), (4, 1, 2), (2, 1, 1)])
def test_vocab_sharded_tied_embedding_matches_dense(pp, dp, tp):
    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=tp, pipeline_model_parallel_size_=pp,
        devices=jax.devices()[: pp * dp * tp],
    )
    try:
        mesh = parallel_state.get_mesh()
        pl, d, t = (
            parallel_state.PIPELINE_AXIS,
            parallel_state.DATA_AXIS,
            parallel_state.TENSOR_AXIS,
        )
        all_axes = (pl, d, t)
        mbs = 2 * dp
        params = _make_params(jax.random.PRNGKey(0), pp)
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (N_MICRO, mbs, S), 0, V
        )
        labels = jax.random.randint(
            jax.random.PRNGKey(2), (N_MICRO, mbs, S), 0, V
        )

        pspec = {
            "word": P((pl, t), None), "pos": P(),
            "w": P(pl, t, None), "b": P(pl, t),
        }
        data_spec = P(None, d, None)

        def stage_fn(lp, x):
            y, _, _ = column_parallel_linear(
                x, lp["w"], lp["b"], axis_name=t, gather_output=True
            )
            return jnp.tanh(y)

        def local(params, tokens, labels):
            stage_p = {"w": params["w"][0], "b": params["b"][0]}
            params = pvary_full(params, all_axes)
            stage_p = pvary_full(stage_p, all_axes)
            tokens = pvary_full(tokens, all_axes)
            labels = pvary_full(labels, all_axes)
            pp_sz = jax.lax.axis_size(pl)
            rank = jax.lax.axis_index(pl)

            def embed_micro(tok):  # [b, s] -> [b, s, h]
                word = vocab_parallel_embedding(
                    tok, params["word"], axis_name=(pl, t)
                )
                return word + params["pos"][: tok.shape[-1]]

            emb = jax.vmap(embed_micro)(tokens)  # [n, b, s, h]
            outs = pipeline_rounds(stage_fn, (stage_p,), emb, pl, False)
            # broadcast the last stage's output; every device then computes
            # only its v/(pp*tp) logit shard
            keep = (rank == pp_sz - 1) & (jax.lax.axis_index(t) == 0)
            y = jax.lax.psum(
                jnp.where(keep, outs, jnp.zeros_like(outs)), (pl, t)
            )
            logits = jnp.einsum("nbsh,vh->nbsv", y, params["word"])
            n, b, s, vloc = logits.shape
            losses = vocab_parallel_cross_entropy(
                logits.reshape(n * b, s, vloc),
                labels.reshape(n * b, s), 0.0, (pl, t),
            )
            # the CE's psums leave the loss replicated-TYPED over (pl, t):
            # it seeds once; pmean over data closes the d axis. No masks,
            # no manual grad sync — the vma transposes do the whole
            # collective gradient structure.
            return jax.lax.pmean(jnp.mean(losses), d)

        loss, grads = jax.jit(
            jax.shard_map(
                lambda p, x, y: jax.value_and_grad(local)(p, x, y),
                mesh=mesh,
                in_specs=(pspec, data_spec, data_spec),
                out_specs=(P(), pspec),
                check_vma=True,
            )
        )(params, tokens, labels)

        ref_loss, ref_grads = jax.value_and_grad(_dense_loss(pp))(
            params, tokens, labels
        )
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        for k in ("word", "pos", "w", "b"):
            np.testing.assert_allclose(
                np.asarray(grads[k]), np.asarray(ref_grads[k]), atol=2e-5,
                err_msg=f"grad {k}",
            )
    finally:
        parallel_state.destroy_model_parallel()


def test_replicated_tied_embedding_sync_matches_dense():
    """Reference layout as a MANUAL flow: tied table replicated over the
    pipeline axis, per-stage partial grads (input-side on stage 0,
    head-side on the last stage, zeros in the middle — the reference's
    per-rank ``weight.grad`` state), combined by ``sync_embedding_grads``
    exactly like the reference's embedding-group all-reduce."""
    pp = 4
    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=1, pipeline_model_parallel_size_=pp,
        devices=jax.devices()[:pp],
    )
    try:
        mesh = parallel_state.get_mesh()
        pl = parallel_state.PIPELINE_AXIS
        mbs = 2
        params = _make_params(jax.random.PRNGKey(3), pp)
        tokens = jax.random.randint(
            jax.random.PRNGKey(4), (N_MICRO, mbs, S), 0, V
        )
        labels = jax.random.randint(
            jax.random.PRNGKey(5), (N_MICRO, mbs, S), 0, V
        )

        pspec = {"word": P(), "pos": P(), "w": P(pl, None, None),
                 "b": P(pl, None)}

        def stage_fn(lp, x):
            return jnp.tanh(jnp.einsum("...h,oh->...o", x, lp["w"]) + lp["b"])

        def local(params, tokens, labels):
            stage_p = {"w": params["w"][0], "b": params["b"][0]}
            pp_sz = jax.lax.axis_size(pl)
            rank = jax.lax.axis_index(pl)
            # stage 0 embeds (other stages' results are dead inputs to the
            # schedule, exactly like the reference where only stage 0 holds
            # the embedding layer)
            emb = (
                jnp.take(params["word"], tokens, axis=0)
                + params["pos"][: tokens.shape[-1]]
            )
            outs = pipeline_rounds(stage_fn, (stage_p,), emb, pl, False)
            # the LAST stage computes the full tied head (reference layout)
            logits = jnp.einsum("nbsh,vh->nbsv", outs, params["word"])
            logp = jax.nn.log_softmax(logits, axis=-1)
            ce = -jnp.take_along_axis(
                logp, labels[..., None], axis=-1
            )[..., 0]
            return jnp.where(rank == pp_sz - 1, jnp.mean(ce), 0.0)

        def grads_fn(params, tokens, labels):
            loss, grads = jax.value_and_grad(local)(params, tokens, labels)
            # manual flow: grads are per-stage partials. Tied table: the
            # embedding-group all-reduce; position table: the
            # position-group all-reduce; stage params are pipeline-sharded
            # (no sync).
            word = sync_embedding_grads(grads["word"])
            pos = sync_position_embedding_grads(grads["pos"])
            loss = jax.lax.psum(loss, pl)
            return loss, {
                "word": word, "pos": pos, "w": grads["w"], "b": grads["b"],
            }

        loss, grads = jax.jit(
            jax.shard_map(
                grads_fn, mesh=mesh,
                in_specs=(pspec, P(), P()),
                out_specs=(P(), pspec),
                check_vma=False,
            )
        )(params, tokens, labels)

        ref_loss, ref_grads = jax.value_and_grad(_dense_loss(pp))(
            params, tokens, labels
        )
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        for k in ("word", "pos", "w", "b"):
            np.testing.assert_allclose(
                np.asarray(grads[k]), np.asarray(ref_grads[k]), atol=2e-5,
                err_msg=f"grad {k}",
            )
    finally:
        parallel_state.destroy_model_parallel()


# ---------------------------------------------------------------------------
# group-mask unit tests
# ---------------------------------------------------------------------------

def _pp8():
    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=1, pipeline_model_parallel_size_=8,
    )
    return parallel_state.get_mesh()


def test_sync_embedding_grads_drops_non_group_junk():
    """Middle stages may carry garbage in the tied-table grad slot (the
    reference's middle ranks simply are not in the embedding group); the
    masked psum must drop those contributions."""
    mesh = _pp8()
    try:
        gw = jnp.arange(12.0).reshape(3, 4)

        def local(gw):
            rank = jax.lax.axis_index(parallel_state.PIPELINE_AXIS)
            contrib = jnp.where(
                rank == 0, gw, jnp.where(rank == 7, 2.0 * gw, 777.0)
            )
            return sync_embedding_grads({"word": contrib})["word"]

        out = jax.jit(
            jax.shard_map(
                local, mesh=mesh, in_specs=(P(),), out_specs=P(None, None),
                check_vma=False,
            )
        )(gw)
        np.testing.assert_allclose(np.asarray(out), np.asarray(3.0 * gw))
    finally:
        parallel_state.destroy_model_parallel()


def test_sync_embedding_grads_split_rank_included():
    """With a pipeline split rank (encoder-decoder), the split stage joins
    the embedding group (reference parallel_state.py:352-375)."""
    mesh = _pp8()
    try:
        parallel_state.set_pipeline_model_parallel_split_rank(4)
        gw = jnp.ones((2, 2))

        def local(gw):
            rank = jax.lax.axis_index(parallel_state.PIPELINE_AXIS)
            contrib = jnp.where(
                rank == 0, gw,
                jnp.where(rank == 4, 10.0 * gw,
                          jnp.where(rank == 7, 100.0 * gw, 555.0)),
            )
            return sync_embedding_grads({"word": contrib})["word"]

        out = jax.jit(
            jax.shard_map(
                local, mesh=mesh, in_specs=(P(),), out_specs=P(None, None),
                check_vma=False,
            )
        )(gw)
        np.testing.assert_allclose(np.asarray(out), 111.0 * np.ones((2, 2)))

        def pos_local(gw):
            rank = jax.lax.axis_index(parallel_state.PIPELINE_AXIS)
            contrib = jnp.where(
                rank == 0, gw, jnp.where(rank == 4, 10.0 * gw, 555.0)
            )
            return sync_position_embedding_grads({"pos": contrib})["pos"]

        out = jax.jit(
            jax.shard_map(
                pos_local, mesh=mesh, in_specs=(P(),),
                out_specs=P(None, None), check_vma=False,
            )
        )(gw)
        np.testing.assert_allclose(np.asarray(out), 11.0 * np.ones((2, 2)))
    finally:
        parallel_state.destroy_model_parallel()
