"""Tests for fused softmax + RoPE vs pure-JAX references.

Mirrors the reference's ``tests/L0/run_transformer/test_fused_softmax.py``
(kernel vs ``forward_torch_softmax``) and the fused_rope contrib tests.
Pallas kernels run in interpret mode on CPU.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.transformer.enums import AttnMaskType
from apex_tpu.transformer.functional import (
    FusedScaleMaskSoftmax,
    fused_apply_rotary_pos_emb,
    fused_apply_rotary_pos_emb_2d,
    fused_apply_rotary_pos_emb_cached,
    fused_apply_rotary_pos_emb_thd,
    generic_scaled_masked_softmax,
    scaled_masked_softmax,
    scaled_softmax,
    scaled_upper_triang_masked_softmax,
)

B, NP, SQ, SK = 2, 3, 8, 128  # sk=128 satisfies the pallas constraint


def _ref_softmax(x, scale, causal=False, mask=None):
    xf = np.asarray(x, np.float32) * scale
    if causal:
        q = np.arange(xf.shape[-2])[:, None]
        k = np.arange(xf.shape[-1])[None, :]
        xf = np.where(k > q, -10000.0, xf)
    if mask is not None:
        xf = np.where(np.broadcast_to(np.asarray(mask) != 0, xf.shape), -10000.0, xf)
    e = np.exp(xf - xf.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


@pytest.mark.parametrize("interpret", [True, False])
@pytest.mark.parametrize("scale", [1.0, 0.125])
def test_scaled_upper_triang_masked_softmax(interpret, scale):
    x = jax.random.normal(jax.random.PRNGKey(0), (B * NP, SK, SK), jnp.bfloat16)
    y = scaled_upper_triang_masked_softmax(x, scale, interpret)
    ref = _ref_softmax(x.astype(jnp.float32), scale, causal=True)
    np.testing.assert_allclose(np.asarray(y, np.float32), ref, atol=2e-2)
    # upper triangle strictly zero
    assert np.all(np.triu(np.asarray(y, np.float32)[0], k=1) == 0)


@pytest.mark.parametrize("interpret", [True, False])
def test_scaled_masked_softmax(interpret):
    x = jax.random.normal(jax.random.PRNGKey(1), (B, NP, SQ, SK), jnp.bfloat16)
    mask = (jax.random.uniform(jax.random.PRNGKey(2), (B, 1, SQ, SK)) > 0.7).astype(
        jnp.int8
    )
    y = scaled_masked_softmax(x, mask, 0.5, interpret)
    ref = _ref_softmax(x.astype(jnp.float32), 0.5, mask=mask)
    np.testing.assert_allclose(np.asarray(y, np.float32), ref, atol=2e-2)


@pytest.mark.parametrize("interpret", [True, False])
def test_scaled_softmax(interpret):
    x = jax.random.normal(jax.random.PRNGKey(3), (B, NP, SQ, SK), jnp.bfloat16)
    y = scaled_softmax(x, 2.0, interpret)
    ref = _ref_softmax(x.astype(jnp.float32), 2.0)
    np.testing.assert_allclose(np.asarray(y, np.float32), ref, atol=2e-2)


def test_softmax_backward_matches_autodiff():
    x = jax.random.normal(jax.random.PRNGKey(4), (NP, 32, 32), jnp.float32)

    def fused_loss(x):
        return jnp.sum(scaled_upper_triang_masked_softmax(x, 0.5) ** 2)

    def ref_loss(x):
        q = jax.lax.broadcasted_iota(jnp.int32, (32, 32), 0)
        k = jax.lax.broadcasted_iota(jnp.int32, (32, 32), 1)
        masked = jnp.where(k > q, -10000.0, x * 0.5)
        return jnp.sum(jax.nn.softmax(masked, -1) ** 2)

    g1 = jax.grad(fused_loss)(x)
    g2 = jax.grad(ref_loss)(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-4)


def test_generic_scaled_masked_softmax_odd_shapes():
    x = jax.random.normal(jax.random.PRNGKey(5), (2, 2, 5, 37))
    mask = jnp.zeros((2, 1, 5, 37), jnp.int8)
    y = generic_scaled_masked_softmax(x, mask, 1.0)
    np.testing.assert_allclose(np.asarray(jnp.sum(y, -1)), 1.0, atol=1e-5)


def test_fused_scale_mask_softmax_dispatch():
    m = FusedScaleMaskSoftmax(
        input_in_fp16=False, input_in_bf16=True,
        attn_mask_type=AttnMaskType.causal, scale=0.5,
    )
    x = jax.random.normal(jax.random.PRNGKey(6), (B, NP, SK, SK), jnp.bfloat16)
    assert m.is_kernel_available(None, B, NP, SK, SK)
    y = m(x)
    ref = _ref_softmax(x.astype(jnp.float32), 0.5, causal=True)
    np.testing.assert_allclose(np.asarray(y, np.float32), ref, atol=2e-2)

    # fallback path: fp32 input → not kernel-eligible
    m32 = FusedScaleMaskSoftmax(
        input_in_fp16=False, input_in_bf16=False,
        attn_mask_type=AttnMaskType.padding, softmax_in_fp32=True,
    )
    assert not m32.is_kernel_available(None, B, NP, SQ, 48)
    x32 = jax.random.normal(jax.random.PRNGKey(7), (B, NP, SQ, 48))
    mask = (jax.random.uniform(jax.random.PRNGKey(8), (B, 1, SQ, 48)) > 0.5)
    y32 = m32(x32, mask)
    ref32 = _ref_softmax(x32, 1.0, mask=mask)
    np.testing.assert_allclose(np.asarray(y32), ref32, atol=1e-5)

    with pytest.raises(RuntimeError):
        FusedScaleMaskSoftmax(input_in_fp16=True, input_in_bf16=True)
    with pytest.raises(RuntimeError):
        FusedScaleMaskSoftmax(softmax_in_fp32=False, scale=2.0)


# --- RoPE -------------------------------------------------------------------

def _ref_rope(t, freqs):
    t, freqs = np.asarray(t, np.float64), np.asarray(freqs, np.float64)
    d2 = freqs.shape[-1]
    cos, sin = np.cos(freqs), np.sin(freqs)
    tr = t[..., :d2]
    x1, x2 = tr[..., : d2 // 2], tr[..., d2 // 2 :]
    rot = np.concatenate([-x2, x1], -1)
    out = tr * cos + rot * sin
    return np.concatenate([out, t[..., d2:]], -1)


@pytest.mark.parametrize("d2", [16, 8])  # full-dim and partial rope
def test_fused_rope_sbhd(d2):
    s, b, h, d = 10, 2, 3, 16
    t = jax.random.normal(jax.random.PRNGKey(9), (s, b, h, d))
    freqs = jnp.arange(s)[:, None, None, None] * 0.3 * jnp.ones((1, 1, 1, d2))
    y = fused_apply_rotary_pos_emb(t, freqs)
    np.testing.assert_allclose(np.asarray(y), _ref_rope(t, freqs), atol=1e-5)

    # grad: rope is orthogonal on the rotated block ⇒ grad of sum(y*c) rotates c back
    g = jax.grad(lambda t: jnp.sum(fused_apply_rotary_pos_emb(t, freqs) ** 2))(t)
    g_ref = jax.grad(
        lambda t: jnp.sum(
            jnp.asarray(_ref_rope_jnp(t, freqs)) ** 2
        )
    )(t)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=1e-5)


def _ref_rope_jnp(t, freqs):
    d2 = freqs.shape[-1]
    cos, sin = jnp.cos(freqs), jnp.sin(freqs)
    tr = t[..., :d2]
    x1, x2 = tr[..., : d2 // 2], tr[..., d2 // 2 :]
    rot = jnp.concatenate([-x2, x1], -1)
    out = tr * cos + rot * sin
    return jnp.concatenate([out, t[..., d2:]], -1)


def test_fused_rope_cached_matches_uncached():
    s, b, h, d = 6, 2, 2, 8
    t = jax.random.normal(jax.random.PRNGKey(10), (s, b, h, d))
    freqs = jnp.linspace(0, 3, s)[:, None, None, None] * jnp.ones((1, 1, 1, d))
    y1 = fused_apply_rotary_pos_emb(t, freqs)
    y2 = fused_apply_rotary_pos_emb_cached(t, jnp.cos(freqs), jnp.sin(freqs))
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), atol=1e-6)


def test_fused_rope_thd_restarts_positions():
    h, d = 2, 8
    lens = [3, 5, 2]
    cu = jnp.array([0, 3, 8, 10])
    total = 10
    t = jax.random.normal(jax.random.PRNGKey(11), (total, h, d))
    freqs = jnp.arange(8)[:, None, None, None] * 0.2 * jnp.ones((1, 1, 1, d))
    y = fused_apply_rotary_pos_emb_thd(t, cu, freqs)
    # manual: per-sequence sbhd rope
    out = []
    start = 0
    for L in lens:
        seg = t[start : start + L][:, None]  # [L, 1, h, d]
        out.append(np.asarray(fused_apply_rotary_pos_emb(seg, freqs[:L]))[:, 0])
        start += L
    np.testing.assert_allclose(np.asarray(y), np.concatenate(out, 0), atol=1e-5)


def test_fused_rope_2d():
    b, H, W, h, d = 2, 3, 4, 2, 8
    s = H * W
    t = jax.random.normal(jax.random.PRNGKey(12), (b, s, h, d))
    fh = jnp.arange(H)[None, :, None, None] * 0.3 * jnp.ones((1, H, 1, d // 2))
    fw = jnp.arange(W)[None, :, None, None] * 0.5 * jnp.ones((1, W, 1, d // 2))
    y = fused_apply_rotary_pos_emb_2d(
        t, H, W, jnp.cos(fh), jnp.sin(fh), jnp.cos(fw), jnp.sin(fw)
    )
    # reference: first half rotated by row freq, second by col freq
    x = np.asarray(t).reshape(b, H, W, h, d)
    first = _ref_rope(x[..., : d // 2], np.asarray(fh)[:, :, None, :, :])
    second = _ref_rope(
        x[..., d // 2 :], np.asarray(fw)[:, None, :, :, :]
    )
    ref = np.concatenate([first, second], -1).reshape(b, s, h, d)
    np.testing.assert_allclose(np.asarray(y), ref, atol=1e-5)

    g = jax.grad(
        lambda t: jnp.sum(
            fused_apply_rotary_pos_emb_2d(
                t, H, W, jnp.cos(fh), jnp.sin(fh), jnp.cos(fw), jnp.sin(fw)
            )
            ** 2
        )
    )(t)
    assert g.shape == t.shape and np.isfinite(np.asarray(g)).all()


def test_transformer_layers_ln_sp_tag():
    from apex_tpu.transformer.layers import FastLayerNorm, FusedLayerNorm

    ln = FusedLayerNorm(normalized_shape=8, sequence_parallel_enabled=True)
    assert ln.sequence_parallel_param_names == ("weight", "bias")
    # the exported names match the actual flax param names
    vars_probe = ln.init(jax.random.PRNGKey(7), jax.random.normal(
        jax.random.PRNGKey(8), (2, 8)))
    assert set(ln.sequence_parallel_param_names) == set(vars_probe["params"])
    ln2 = FastLayerNorm(normalized_shape=8)
    assert ln2.sequence_parallel_param_names == ()
    x = jax.random.normal(jax.random.PRNGKey(13), (4, 8))
    vars_ = ln.init(jax.random.PRNGKey(0), x)
    y = ln.apply(vars_, x)
    np.testing.assert_allclose(np.asarray(y.mean(-1)), 0.0, atol=1e-5)
