"""amp behavioural tests — mirrors ``tests/L0/run_amp``: basic casts,
cast caching, loss-scaler dynamics, checkpointing, frontend presets."""
import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import amp
from apex_tpu.amp.scaler import LossScaler
from apex_tpu.optimizers import FusedAdam, FusedSGD


class TestAutocast:
    def test_matmul_casts_low_precision(self):
        a = jnp.ones((8, 8), jnp.float32)
        with amp.autocast(dtype=jnp.bfloat16):
            out = jnp.matmul(a, a)
        assert out.dtype == jnp.bfloat16

    def test_fp32_list_upcasts(self):
        x = jnp.linspace(-1, 1, 16, dtype=jnp.bfloat16)
        with amp.autocast(dtype=jnp.bfloat16):
            out = jax.nn.softmax(x)
        assert out.dtype == jnp.float32

    def test_restores_namespace(self):
        orig = jnp.matmul
        with amp.autocast():
            assert jnp.matmul is not orig
        assert jnp.matmul is orig

    def test_under_jit(self):
        a = jnp.ones((4, 4), jnp.float32)

        @jax.jit
        def f(a):
            with amp.autocast(dtype=jnp.bfloat16):
                return jnp.matmul(a, a)

        assert f(a).dtype == jnp.bfloat16

    def test_disable_casts(self):
        a = jnp.ones((4, 4), jnp.float32)
        with amp.autocast(dtype=jnp.bfloat16):
            with amp.disable_casts():
                out = jnp.matmul(a, a)
        assert out.dtype == jnp.float32

    def test_disabled_noop(self):
        a = jnp.ones((4, 4), jnp.float32)
        with amp.autocast(enabled=False):
            assert jnp.matmul(a, a).dtype == jnp.float32


class TestLossScaler:
    def test_static_scale(self):
        s = LossScaler(loss_scale=128.0)
        st = s.init_state()
        assert float(st.loss_scale) == 128.0
        st2 = s.update_scale(st._replace(found_inf=jnp.asarray(True)))
        assert float(st2.loss_scale) == 128.0  # static never moves

    def test_dynamic_backoff_and_growth(self):
        s = LossScaler(loss_scale="dynamic", init_scale=2.0 ** 10, scale_window=2)
        st = s.init_state()
        st = s.update_scale(st._replace(found_inf=jnp.asarray(True)))
        assert float(st.loss_scale) == 2.0 ** 9
        st = s.update_scale(st)  # clean
        st = s.update_scale(st)  # clean -> growth (window=2)
        assert float(st.loss_scale) == 2.0 ** 10

    def test_unscale_detects_inf(self):
        s = LossScaler(loss_scale=2.0 ** 16)
        st = s.init_state()
        grads = {"w": jnp.asarray([1.0, np.inf], jnp.float32)}
        _, st = s.unscale(st, grads)
        assert bool(st.found_inf)

    def test_scaled_value_and_grad_end_to_end(self):
        scaler = LossScaler(loss_scale="dynamic", init_scale=8.0)
        params = {"w": jnp.asarray([2.0, -1.0], jnp.float32)}

        def loss_fn(params, x):
            return jnp.sum(params["w"] * x) ** 2

        fn = amp.scaled_value_and_grad(loss_fn, scaler)
        x = jnp.asarray([1.0, 3.0])
        loss, grads, st = jax.jit(fn)(scaler.init_state(), params, x)
        expect = jax.grad(loss_fn)(params, x)
        np.testing.assert_allclose(grads["w"], expect["w"], rtol=1e-6)
        assert not bool(st.found_inf)

    def test_state_dict_roundtrip(self):
        s = LossScaler(loss_scale="dynamic", init_scale=4096.0)
        st = s.init_state()
        st = s.update_scale(st._replace(found_inf=jnp.asarray(True)))
        sd = s.state_dict(st)
        st2 = s.load_state_dict(sd)
        assert float(st2.loss_scale) == float(st.loss_scale)


class TestFrontend:
    def _params(self):
        return {
            "dense": {"kernel": jnp.ones((4, 4), jnp.float32)},
            "BatchNorm_0": {"scale": jnp.ones((4,), jnp.float32)},
        }

    def test_o2_casts_keeps_bn_fp32(self):
        params, opt, st = amp.initialize(self._params(), FusedAdam(), opt_level="O2")
        assert params["dense"]["kernel"].dtype == jnp.bfloat16
        assert params["BatchNorm_0"]["scale"].dtype == jnp.float32
        assert opt.master_weights is True
        assert st.opt_properties.opt_level == "O2"

    def test_o0_is_fp32_static(self):
        params, opt, st = amp.initialize(self._params(), FusedSGD(lr=0.1), opt_level="O0")
        assert params["dense"]["kernel"].dtype == jnp.float32
        assert float(st.scaler_state().loss_scale) == 1.0

    def test_o0_upcasts_bf16_params(self):
        bf16 = jax.tree_util.tree_map(lambda x: x.astype(jnp.bfloat16), self._params())
        params, _, _ = amp.initialize(bf16, None, opt_level="O0")
        assert params["dense"]["kernel"].dtype == jnp.float32

    def test_getattr_with_default_does_not_raise(self):
        import apex_tpu

        assert getattr(apex_tpu, "RNN", None) is None or True  # must not raise

    def test_o1_patches_functions(self):
        _, _, st = amp.initialize(self._params(), None, opt_level="O1")
        a = jnp.ones((4, 4), jnp.float32)
        with st.autocast():
            assert jnp.matmul(a, a).dtype == jnp.bfloat16

    def test_o3_bf16_everything(self):
        params, _, _ = amp.initialize(self._params(), None, opt_level="O3")
        assert params["BatchNorm_0"]["scale"].dtype == jnp.bfloat16

    def test_override_loss_scale(self):
        _, _, st = amp.initialize(self._params(), None, opt_level="O2", loss_scale=512.0)
        assert float(st.scaler_state().loss_scale) == 512.0

    def test_checkpoint_roundtrip(self):
        _, _, st = amp.initialize(self._params(), None, opt_level="O2", num_losses=2)
        sd = amp.state_dict(st)
        assert set(sd) == {"loss_scaler0", "loss_scaler1"}
        st2 = amp.load_state_dict(st, sd)
        assert float(st2.scaler_state(1).loss_scale) == float(st.scaler_state(1).loss_scale)

    def test_skip_step_on_overflow(self):
        params = {"w": jnp.asarray([1.0, 2.0], jnp.float32)}
        opt = FusedAdam(lr=0.1)
        state = opt.init(params)
        scaler = LossScaler(loss_scale="dynamic", init_scale=2.0 ** 20)

        def loss_fn(p, x):
            return jnp.sum(p["w"] * x) * 1e30  # force overflow after scaling

        fn = amp.scaled_value_and_grad(loss_fn, scaler)
        _, grads, sstate = fn(scaler.init_state(), params, jnp.asarray([1e8, 1e8]))
        assert bool(sstate.found_inf)
        new_params, _ = opt.step(grads, state, params, found_inf=sstate.found_inf)
        np.testing.assert_array_equal(np.asarray(new_params["w"]), np.asarray(params["w"]))
