"""Tests for the standalone GPT/BERT models.

Key check (reference idiom from ``test_pipeline_parallel_fwd_bwd.py`` and
the GPT/BERT minimal tests): the TP=8 sharded forward/loss must equal the
dense single-device computation.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import (
    DistributedTestBase,
    GPTConfig,
    bert_model_provider,
    gpt_loss,
    gpt_model_provider,
    gpt_partition_specs,
    init_gpt_params,
    set_random_seed,
)

TP = 8


def _small_cfg(**kw):
    defaults = dict(
        num_layers=2,
        hidden_size=32,
        num_attention_heads=8,
        vocab_size=128,
        max_position_embeddings=32,
        hidden_dropout=0.0,
        attention_dropout=0.0,
        tensor_model_parallel_size=1,
    )
    defaults.update(kw)
    return GPTConfig(**defaults)


def test_gpt_forward_shapes_and_loss():
    cfg = _small_cfg()
    key = set_random_seed(1234)
    params, fwd, loss = gpt_model_provider(cfg, key)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, 128)
    logits = fwd(params, tokens)
    assert logits.shape == (2, 16, 128)
    labels = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 128)
    l = loss(params, tokens, labels)
    assert np.isfinite(float(l)) and float(l) > 0


def test_gpt_tp_matches_dense():
    cfg_dense = _small_cfg()
    cfg_tp = _small_cfg(tensor_model_parallel_size=TP)
    parallel_state.initialize_model_parallel(tensor_model_parallel_size_=TP)
    mesh = parallel_state.get_mesh()
    key = jax.random.PRNGKey(7)
    params = init_gpt_params(cfg_dense, key)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, 128)
    labels = jax.random.randint(jax.random.PRNGKey(3), (2, 16), 0, 128)

    dense_loss = gpt_loss(cfg_dense, params, tokens, labels)
    dense_grads = jax.grad(
        lambda p: gpt_loss(cfg_dense, p, tokens, labels)
    )(params)

    specs = gpt_partition_specs(cfg_tp)

    def local_loss(p, t, lab):
        return gpt_loss(cfg_tp, p, t, lab, axis_name="tensor")

    tp_loss = jax.shard_map(
        local_loss, mesh=mesh, in_specs=(specs, P(), P()), out_specs=P(),
        check_vma=True,
    )(params, tokens, labels)
    np.testing.assert_allclose(float(tp_loss), float(dense_loss), rtol=2e-4)

    # gradients of the sharded model match the dense ones (shard-for-shard)
    tp_grads = jax.shard_map(
        jax.grad(local_loss), mesh=mesh,
        in_specs=(specs, P(), P()), out_specs=specs, check_vma=True,
    )(params, tokens, labels)
    for name in ("qkv_w", "fc2_w", "input_ln_w"):
        np.testing.assert_allclose(
            np.asarray(tp_grads["layers"][name]),
            np.asarray(dense_grads["layers"][name]),
            atol=5e-4, err_msg=name,
        )
    np.testing.assert_allclose(
        np.asarray(tp_grads["embedding"]["word"]),
        np.asarray(dense_grads["embedding"]["word"]),
        atol=5e-4,
    )
    parallel_state.destroy_model_parallel()


def test_gpt_recompute_matches_plain():
    cfg = _small_cfg()
    cfg_r = _small_cfg(recompute_granularity="full")
    params = init_gpt_params(cfg, jax.random.PRNGKey(5))
    tokens = jax.random.randint(jax.random.PRNGKey(6), (2, 8), 0, 128)
    labels = jax.random.randint(jax.random.PRNGKey(7), (2, 8), 0, 128)
    l1 = gpt_loss(cfg, params, tokens, labels)
    l2 = gpt_loss(cfg_r, params, tokens, labels)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    g1 = jax.grad(lambda p: gpt_loss(cfg, p, tokens, labels))(params)
    g2 = jax.grad(lambda p: gpt_loss(cfg_r, p, tokens, labels))(params)
    np.testing.assert_allclose(
        np.asarray(g1["layers"]["qkv_w"]), np.asarray(g2["layers"]["qkv_w"]),
        atol=1e-6,
    )


def test_gpt_ce_save_logits_matches_remat():
    """`ce_save_logits=True` (save-the-compact-logits CE backward, the
    round-5 bench configuration) must match the default remat-chunk CE
    in both loss and gradients (fp32: the saved dtype = compute dtype,
    so the comparison is exact up to reduction order)."""
    cfg = _small_cfg()
    cfg_s = _small_cfg(ce_save_logits=True)
    params = init_gpt_params(cfg, jax.random.PRNGKey(5))
    tokens = jax.random.randint(jax.random.PRNGKey(6), (2, 8), 0, 128)
    labels = jax.random.randint(jax.random.PRNGKey(7), (2, 8), 0, 128)
    l1 = gpt_loss(cfg, params, tokens, labels)
    l2 = gpt_loss(cfg_s, params, tokens, labels)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    g1 = jax.grad(lambda p: gpt_loss(cfg, p, tokens, labels))(params)
    g2 = jax.grad(lambda p: gpt_loss(cfg_s, p, tokens, labels))(params)
    np.testing.assert_allclose(
        np.asarray(g1["embedding"]["word"]),
        np.asarray(g2["embedding"]["word"]), atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(g1["layers"]["qkv_w"]), np.asarray(g2["layers"]["qkv_w"]),
        atol=1e-5,
    )


def test_gpt_cpu_offload_matches():
    cfg = _small_cfg()
    params, fwd, loss = gpt_model_provider(
        cfg, jax.random.PRNGKey(8), cpu_offload=True
    )
    params2, fwd2, loss2 = gpt_model_provider(cfg, jax.random.PRNGKey(8))
    tokens = jax.random.randint(jax.random.PRNGKey(9), (1, 8), 0, 128)
    labels = jax.random.randint(jax.random.PRNGKey(10), (1, 8), 0, 128)
    np.testing.assert_allclose(
        float(loss(params, tokens, labels)),
        float(loss2(params2, tokens, labels)),
        rtol=1e-6,
    )


def test_gpt_dropout_determinism():
    cfg = _small_cfg(hidden_dropout=0.1, attention_dropout=0.1)
    params = init_gpt_params(cfg, jax.random.PRNGKey(11))
    tokens = jax.random.randint(jax.random.PRNGKey(12), (2, 8), 0, 128)
    labels = jnp.zeros_like(tokens)
    k = jax.random.PRNGKey(13)
    l1 = gpt_loss(cfg, params, tokens, labels, dropout_key=k, deterministic=False)
    l2 = gpt_loss(cfg, params, tokens, labels, dropout_key=k, deterministic=False)
    l3 = gpt_loss(
        cfg, params, tokens, labels, dropout_key=jax.random.PRNGKey(99),
        deterministic=False,
    )
    assert float(l1) == float(l2)  # same key -> identical
    assert float(l1) != float(l3)  # different key -> different dropout


def test_bert_forward_and_loss():
    cfg = _small_cfg(add_binary_head=True)
    params, fwd, loss_fn = bert_model_provider(cfg, jax.random.PRNGKey(14))
    tokens = jax.random.randint(jax.random.PRNGKey(15), (2, 12), 0, 128)
    padding = jnp.concatenate(
        [jnp.ones((2, 8), jnp.int32), jnp.zeros((2, 4), jnp.int32)], axis=1
    )
    lm_logits, bin_logits = fwd(params, tokens, padding)
    assert lm_logits.shape == (2, 12, 128)
    assert bin_logits.shape == (2, 2)

    labels = jax.random.randint(jax.random.PRNGKey(16), (2, 12), 0, 128)
    loss_mask = padding
    l = loss_fn(
        params, tokens, labels, loss_mask,
        padding_mask=padding, binary_labels=jnp.array([0, 1]),
    )
    assert np.isfinite(float(l))

    # padding tokens must not influence unpadded positions' logits
    tokens2 = tokens.at[:, 8:].set(7)  # change padded region
    lm_logits2, _ = fwd(params, tokens2, padding)
    np.testing.assert_allclose(
        np.asarray(lm_logits[:, :8]), np.asarray(lm_logits2[:, :8]), atol=1e-5
    )


def test_distributed_test_base():
    class MyTest(DistributedTestBase):
        MAX_WORLD_SIZE = 4

        def test_world(self):
            assert self.world_size == 4
            mesh = self.initialize_model_parallel(tp=2, pp=2)
            assert parallel_state.get_tensor_model_parallel_world_size() == 2
            assert parallel_state.get_pipeline_model_parallel_world_size() == 2

    import unittest

    suite = unittest.TestLoader().loadTestsFromTestCase(MyTest)
    result = unittest.TextTestRunner(verbosity=0).run(suite)
    assert result.wasSuccessful()
    assert not parallel_state.model_parallel_is_initialized()


def test_arguments_parse_and_validate():
    from apex_tpu.transformer.testing.arguments import parse_args

    args = parse_args(args=[
        "--num-layers", "4", "--hidden-size", "64",
        "--num-attention-heads", "4", "--seq-length", "32",
        "--max-position-embeddings", "32",
        "--micro-batch-size", "2", "--global-batch-size", "16",
        "--tensor-model-parallel-size", "2", "--bf16",
        "--world-size", "8",
    ])
    assert args.data_parallel_size == 4
    assert args.params_dtype == "bfloat16"
    assert args.ffn_hidden_size == 256
    assert args.kv_channels == 16

    with pytest.raises(ValueError):
        parse_args(args=["--hidden-size", "64", "--num-attention-heads", "4",
                         "--fp16", "--bf16", "--world-size", "8"])
    with pytest.raises(ValueError):
        parse_args(args=[
            "--hidden-size", "64", "--num-attention-heads", "4",
            "--tensor-model-parallel-size", "3", "--world-size", "8",
        ])


def test_global_vars_lifecycle():
    from apex_tpu.transformer.testing import global_vars as gv

    gv.destroy_global_vars()
    args = gv.set_global_variables(override_args=[
        "--hidden-size", "64", "--num-attention-heads", "4",
        "--micro-batch-size", "2", "--global-batch-size", "8",
        "--world-size", "2",
    ])
    assert gv.get_args() is args
    assert gv.get_num_microbatches() == 2  # 8 / (mbs 2 * dp 2)
    timers = gv.get_timers()
    timers("step").start()
    timers("step").stop()
    assert timers("step").elapsed() >= 0
    gv.destroy_global_vars()


def test_selective_policy_saves_named_pallas_outputs():
    """The flash-aware selective remat policy matches pallas kernels by
    their pallas_call `name` param — a JAX upgrade that renames that param
    would silently degrade selective remat back to replaying every flash
    forward. Pin that the named kernel outputs appear in saved residuals."""
    from jax._src.ad_checkpoint import saved_residuals

    from apex_tpu.ops.flash_attention import flash_attention
    from apex_tpu.transformer.testing.standalone_transformer_lm import (
        _selective_policy,
    )

    def body(q, k, v):
        o = flash_attention(
            q, k, v, causal=True, interpret=True, block_q=16, block_k=16
        )
        return jnp.sum(o.astype(jnp.float32) ** 2)

    q = jax.random.normal(jax.random.PRNGKey(0), (1, 2, 32, 16), jnp.float32)
    fn = jax.checkpoint(body, policy=_selective_policy)
    res = saved_residuals(fn, q, q, q)
    # the flash fwd kernel outputs must be saved, not rematted
    pallas_saved = [d for _, d in res if "output of pallas_call" in str(d)]
    assert pallas_saved, [str(d) for _, d in res]
