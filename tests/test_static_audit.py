"""apex_tpu.analysis + tools/static_audit.py: the jaxpr step auditor.

One red test per rule family (seeded violation -> expected finding,
with a golden-JSON fixture pinning the report schema) plus green
self-audit tests asserting the repo's own hot paths — the headline GPT
step, the packed FusedAdam/LAMB steps, the telemetry drain path —
produce zero error-severity findings. Tier-1: this file IS the CI wiring
for ``tools/static_audit.py --self`` (``not slow``, pure CPU tracing).
"""
import copy
import json
import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from apex_tpu import analysis, telemetry  # noqa: E402
from apex_tpu.analysis import (  # noqa: E402
    assert_step_clean,
    audit_step,
    check_pack_spec,
)
from apex_tpu.multi_tensor_apply.packing import ROW, PackSpec  # noqa: E402
from apex_tpu.optimizers import FusedAdam  # noqa: E402
from tools import static_audit  # noqa: E402

GOLDEN = Path(__file__).parent / "data" / "static_audit_golden.json"


def _codes(report, severity=None):
    return [f.code for f in report.findings
            if severity is None or f.severity == severity]


# ---------------------------------------------------------------------------
# rule 1: donation / aliasing
# ---------------------------------------------------------------------------
def _packed_setup():
    params = {f"w{i}": jnp.zeros((4096,), jnp.bfloat16) for i in range(4)}
    grads = {k: jnp.full((4096,), 1e-3, jnp.bfloat16) for k in params}
    opt = FusedAdam(lr=1e-3, master_weights=True, packed=True,
                    packed_chunk_size=4096, packed_interpret=True)
    return params, grads, opt, opt.init(params)


def test_donation_red_undonated_packed_state():
    params, grads, opt, state = _packed_setup()
    step = jax.jit(lambda g, s, p: opt.step(g, s, p))  # NO donation
    rep = audit_step(step, grads, state, params, min_bytes=4096)
    assert "undonated_state" in _codes(rep, "error")
    # the finding names the argnum to donate
    f = [x for x in rep.errors if x.code == "undonated_state"][0]
    assert f.data["argnum"] == 1 and f.data["bytes"] > 0


def test_donation_flags_all_shadowed_carries():
    """When grads and params share an aval and NOTHING is donated, both
    must be named — neither may shadow the other (donating either gives
    the param output an in-place home)."""
    params, grads, opt, state = _packed_setup()
    step = jax.jit(lambda g, s, p: opt.step(g, s, p))
    rep = audit_step(step, grads, state, params, min_bytes=4096)
    flagged = {f.data["argnum"] for f in rep.findings
               if f.code in ("undonated_state", "undonated_carry")}
    assert {0, 1, 2} <= flagged


def test_donation_green_packed_state_donated():
    params, grads, opt, state = _packed_setup()
    step = jax.jit(lambda g, s, p: opt.step(g, s, p), donate_argnums=(1, 2))
    rep = assert_step_clean(step, grads, state, params, min_bytes=4096)
    assert rep.ok and "undonated_state" not in rep.codes()


def test_donation_plain_fn_donate_argnums_spelling():
    """Un-jitted step + explicit donate_argnums= (the jax.jit spelling)."""
    params, grads, opt, state = _packed_setup()
    fn = lambda g, s, p: opt.step(g, s, p)  # noqa: E731
    bad = audit_step(fn, grads, state, params, min_bytes=4096)
    good = audit_step(fn, grads, state, params, min_bytes=4096,
                      donate_argnums=(1, 2))
    assert "undonated_state" in _codes(bad, "error")
    assert good.ok


def test_donation_red_double_donation():
    x = jnp.zeros((65536,), jnp.float32)
    step = jax.jit(lambda a, b: (a + 1.0, b * 2.0), donate_argnums=(0, 1))
    rep = audit_step(step, x, x)  # same buffer donated twice
    assert "double_donation" in _codes(rep, "error")


def test_donation_green_master_copy_guard():
    """packed_init's copy=True guard: a single fp32 leaf of exact
    chunk-multiple size would alias its master without it (the
    no_update_mv hazard, optimizers/_packed.py) — donation must be clean."""
    params = {"w": jnp.zeros((4096,), jnp.float32)}
    opt = FusedAdam(lr=1e-3, master_weights=True, packed=True,
                    packed_chunk_size=4096, packed_interpret=True)
    state = opt.init(params)
    grads = {"w": jnp.zeros((4096,), jnp.float32)}
    step = jax.jit(lambda g, s, p: opt.step(g, s, p), donate_argnums=(1, 2))
    rep = audit_step(step, grads, state, params, min_bytes=4096)
    assert "double_donation" not in rep.codes()


def test_donation_red_pallas_without_aliases():
    def k(x_ref, o_ref):
        o_ref[:] = x_ref[:] * 2.0

    def make_step(scope):
        @jax.named_scope(scope)
        def step(x):
            return pl.pallas_call(
                k, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                interpret=True)(x)

        return step

    x = jnp.zeros((64, ROW), jnp.float32)
    # the packed/multi-tensor family contract is in-place: warning
    rep = audit_step(make_step("apex_tpu.packed_seeded"), x,
                     min_bytes=4096)
    assert "pallas_no_alias" in _codes(rep, "warning")
    # other kernels are often deliberately out-of-place: informational
    rep = audit_step(make_step("apex_tpu.some_attention"), x,
                     min_bytes=4096)
    assert "pallas_no_alias" in _codes(rep, "info")


# ---------------------------------------------------------------------------
# rule 2: host-sync discipline
# ---------------------------------------------------------------------------
def test_host_sync_red_ungated_callback():
    def step(x):
        jax.debug.callback(lambda v: None, x)
        return x * 2.0

    rep = audit_step(step, jnp.zeros((8,)))
    assert "ungated_callback" in _codes(rep, "error")


def test_host_sync_red_callback_in_scan():
    def step(x):
        def body(c, t):
            jax.debug.callback(lambda v: None, c)
            return c * t, c

        y, _ = jax.lax.scan(body, x, jnp.arange(4.0))
        return y

    rep = audit_step(step, jnp.float32(1))
    codes = rep.codes()
    assert "callback_in_scan" in codes and "ungated_callback" in codes


def test_host_sync_red_ordered_io_callback():
    from jax.experimental import io_callback

    def step(x):
        io_callback(lambda v: None, None, x, ordered=True)
        return x + 1.0

    rep = audit_step(step, jnp.zeros((8,)))
    assert "ordered_io_callback" in _codes(rep, "error")


def test_host_sync_green_cond_gated_drain():
    """The telemetry drain path: the callback lives under lax.cond, so
    the audit must be silent (the sync-free discipline holds)."""
    sink = telemetry.NullRecorder()

    def step(m, loss):
        m = telemetry.accumulate(m, loss=loss, tokens=64)
        m = telemetry.drain(m, sink, every_n=10)
        return m, loss * 0.5

    rep = assert_step_clean(
        jax.jit(step, donate_argnums=(0,)),
        telemetry.init_metrics(), jnp.float32(0))
    assert not rep.by_rule("host_sync")


# ---------------------------------------------------------------------------
# rule 3: amp dtype flow
# ---------------------------------------------------------------------------
def test_dtype_red_fp32_matmul_in_bf16_step():
    def step(x16, w16, m32):
        y = (x16 @ w16).astype(jnp.float32)
        z = m32 @ m32  # the leak: a large fp32 GEMM in a bf16 step
        return y.sum() + z.sum()

    args = (jnp.zeros((256, 256), jnp.bfloat16),
            jnp.zeros((256, 256), jnp.bfloat16),
            jnp.zeros((256, 256), jnp.float32))
    rep = audit_step(step, *args, compute_dtype="bfloat16", min_bytes=1024)
    assert "fp32_matmul" in _codes(rep, "warning")
    strict = audit_step(step, *args, compute_dtype="bfloat16",
                        min_bytes=1024, strict_dtype=True)
    assert "fp32_matmul" in _codes(strict, "error")


def test_dtype_policy_inferred_from_matmul_mix():
    """With equal bf16/f32 matmul weight the step reads as
    low-precision-intent and the f32 dot is flagged without an explicit
    compute_dtype."""
    def step(x16, w16, m32):
        return (x16 @ w16).astype(jnp.float32).sum() + (m32 @ m32).sum()

    rep = audit_step(step, jnp.zeros((256, 256), jnp.bfloat16),
                     jnp.zeros((256, 256), jnp.bfloat16),
                     jnp.zeros((256, 256), jnp.float32), min_bytes=1024)
    assert "fp32_matmul" in rep.codes()


def test_dtype_green_pure_fp32_step():
    def step(a, b):
        return (a @ b).sum()

    rep = audit_step(step, jnp.zeros((128, 128)), jnp.zeros((128, 128)))
    assert not rep.by_rule("dtype_flow")


def test_dtype_red_double_cast():
    def step(x):
        y = jnp.exp(x)  # a live f32 value, not a fresh matmul output
        return y.astype(jnp.bfloat16).astype(jnp.float32) * 2.0

    rep = audit_step(step, jnp.zeros((65536,), jnp.float32),
                     compute_dtype="bfloat16")
    assert "double_cast" in _codes(rep, "warning")


def test_double_cast_inside_pallas_body_not_flagged():
    """Kernel bodies are opaque (walk._OPAQUE): ref arithmetic inside a
    pallas_call must not leak whole-program dtype findings."""
    def k(x_ref, o_ref):
        y = x_ref[:].astype(jnp.float32) * 2.0
        o_ref[:] = y.astype(jnp.bfloat16).astype(jnp.float32)

    @jax.named_scope("apex_tpu.packed_casty")
    def step(x):
        return pl.pallas_call(
            k, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            input_output_aliases={0: 0}, interpret=True)(x)

    rep = audit_step(step, jnp.zeros((64, ROW), jnp.float32),
                     compute_dtype="bfloat16")
    assert "double_cast" not in rep.codes()


def test_dtype_matmul_rail_truncation_not_flagged():
    """Truncating a fresh MXU accumulation to the bf16 rail (and its
    AD-transposed upcast twin) is amp policy, not a double-cast."""
    def step(x16, w16):
        y = jnp.einsum("ij,jk->ik", x16, w16,
                       preferred_element_type=jnp.float32)
        return y.astype(jnp.bfloat16).astype(jnp.float32).sum()

    rep = audit_step(step, jnp.zeros((256, 256), jnp.bfloat16),
                     jnp.zeros((256, 256), jnp.bfloat16), min_bytes=1024)
    assert "double_cast" not in rep.codes()


# ---------------------------------------------------------------------------
# rule 4: constant bloat & recompile hazards
# ---------------------------------------------------------------------------
def test_constants_red_large_baked_constant():
    big = np.ones((512, 1024), np.float32)  # 2 MiB closure capture

    def step(x):
        return x * jnp.asarray(big).sum()

    rep = audit_step(step, jnp.float32(3))
    assert "large_constant" in _codes(rep, "warning")
    f = [x for x in rep.findings if x.code == "large_constant"][0]
    assert f.data["bytes"] == big.nbytes


def test_constants_error_at_hbm_scale():
    big = np.ones((512, 1024), np.float32)

    def step(x):
        return x * jnp.asarray(big).sum()

    rep = audit_step(step, jnp.float32(3), const_bytes_error=1 << 20)
    assert "large_constant" in _codes(rep, "error")


def test_constants_red_weak_type_input():
    rep = audit_step(lambda x: x * 2.0, 3.0)  # Python scalar arg
    assert "weak_type_input" in _codes(rep, "warning")
    strong = audit_step(lambda x: x * 2.0, jnp.float32(3))
    assert "weak_type_input" not in strong.codes()


# ---------------------------------------------------------------------------
# rule 5: PackSpec invariants
# ---------------------------------------------------------------------------
def test_packing_green_spec():
    spec = PackSpec({"a": jnp.zeros((2048,)), "b": jnp.zeros((100,))},
                    chunk_size=ROW)
    assert check_pack_spec(spec) == []


def test_packing_red_misaligned_offsets():
    spec = PackSpec({"a": jnp.zeros((2048,)), "b": jnp.zeros((100,))},
                    chunk_size=ROW)
    bad = copy.copy(spec)
    bad.offsets = (0, 2100)  # not ROW-aligned, overlaps a's padded extent
    codes = [f.code for f in check_pack_spec(bad)]
    assert "misaligned_offset" in codes
    assert all(f.severity == "error" for f in check_pack_spec(bad))


def test_packing_red_truncated_leaf_tables():
    """A leaf with no offset entry at all must not audit clean (zip over
    the per-leaf tuples would silently drop the unmatched tail)."""
    spec = PackSpec({"a": jnp.zeros((2048,)), "b": jnp.zeros((100,))},
                    chunk_size=ROW)
    bad = copy.copy(spec)
    bad.offsets = spec.offsets[:-1]
    assert "inconsistent_leaf_tables" in [
        f.code for f in check_pack_spec(bad)]


def test_packing_red_total_not_chunk_multiple():
    spec = PackSpec({"a": jnp.zeros((2048,))}, chunk_size=ROW)
    bad = copy.copy(spec)
    bad.total = spec.total + 1
    assert "total_not_chunk_multiple" in [
        f.code for f in check_pack_spec(bad)]


def test_packing_shard_alignment_precondition():
    """The ROADMAP sharded-packed follow-on needs ROW-aligned equal
    shards; the checker prices both failure modes."""
    spec = PackSpec({"a": jnp.zeros((3 * ROW,))}, chunk_size=ROW)
    assert check_pack_spec(spec, shard_count=3) == []
    assert "shard_unaligned_total" in [
        f.code for f in check_pack_spec(spec, shard_count=5)]
    wide = PackSpec({"a": jnp.zeros((2 * ROW,))}, chunk_size=2 * ROW)
    bad = copy.copy(wide)
    bad.total = 2 * ROW  # divisible by 4 shards, but ROW/2 per shard
    assert "shard_not_row_aligned" in [
        f.code for f in check_pack_spec(bad, shard_count=4)]


def test_packing_rule_picks_spec_from_packed_state():
    params, grads, opt, state = _packed_setup()
    bad_state = copy.copy(state)
    bad_spec = copy.copy(state.spec)
    bad_spec.offsets = tuple(o + 1 for o in bad_spec.offsets[1:]) + (3,)
    bad_state.spec = bad_spec
    step = jax.jit(lambda g, s, p: opt.step(g, s, p), donate_argnums=(1, 2))
    rep = audit_step(step, grads, state, params, rules=("packing",),
                     pack_specs=[bad_spec])
    assert "misaligned_offset" in _codes(rep, "error")


# ---------------------------------------------------------------------------
# scope coverage
# ---------------------------------------------------------------------------
def test_scopes_red_unscoped_pallas_kernel():
    def k(x_ref, o_ref):
        o_ref[:] = x_ref[:] * 2.0

    def step(x):  # no jax.named_scope("apex_tpu....")
        return pl.pallas_call(
            k, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            input_output_aliases={0: 0}, interpret=True)(x)

    rep = audit_step(step, jnp.zeros((8, ROW), jnp.float32))
    assert "unscoped_kernel" in _codes(rep, "warning")


def test_scopes_green_packed_kernels_are_scoped():
    params, grads, opt, state = _packed_setup()
    step = jax.jit(lambda g, s, p: opt.step(g, s, p), donate_argnums=(1, 2))
    rep = audit_step(step, grads, state, params, rules=("scopes",))
    assert "unscoped_kernel" not in rep.codes()


# ---------------------------------------------------------------------------
# golden JSON fixture: the report schema is pinned byte-for-byte
# ---------------------------------------------------------------------------
def seeded_violation_report():
    """One deterministic step violating every rule family at once."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from apex_tpu.analysis import CollectiveBudget

    big = np.ones((300, 1024), np.float32)  # ~1.2 MiB baked constant
    # one-device mesh: the traced shard_map (and its psums) is identical
    # on the 8-device harness and a standalone 1-device run
    mesh = Mesh(np.array(jax.devices()[:1]), ("tensor",))

    def unscoped_kernel(x_ref, o_ref):
        o_ref[:] = x_ref[:] * 2.0

    def tp_body(a, b):
        t = jax.lax.psum(a @ b, "tensor")
        return jax.lax.psum(t, "tensor")  # unpaired double reduction

    tp = shard_map(tp_body, mesh=mesh, in_specs=(P(), P()),
                   out_specs=P(), check_rep=False)

    def step(state, x16, w16, scale):
        jax.debug.callback(lambda v: None, x16)       # ungated callback
        y = x16 @ w16                                  # bf16 policy GEMM
        z = state["exp_avg"] @ state["exp_avg"]        # fp32 leak
        z = pl.pallas_call(                            # unscoped kernel
            unscoped_kernel,
            out_shape=jax.ShapeDtypeStruct(z.shape, z.dtype),
            input_output_aliases={0: 0}, interpret=True)(z)
        out = (y.astype(jnp.float32).sum() + z.sum()
               + jnp.asarray(big).sum()
               + tp(x16, w16).sum().astype(jnp.float32)) * scale
        return {"exp_avg": state["exp_avg"] * 0.9}, out  # carried, undonated

    args = ({"exp_avg": jnp.ones((256, 256), jnp.float32)},
            jnp.ones((256, 256), jnp.bfloat16),
            jnp.ones((256, 256), jnp.bfloat16),
            3.0)                                       # weak-type scalar
    corrupt = PackSpec({"a": jnp.zeros((2048,)), "b": jnp.zeros((100,))},
                       chunk_size=ROW)
    corrupt = copy.copy(corrupt)
    corrupt.offsets = (0, 2100)                        # mid-row offset
    return audit_step(step, *args, name="seeded", min_bytes=1024,
                      pack_specs=[corrupt],
                      # budget declares ONE psum over no axes: the body's
                      # two tensor-axis psums land over_budget + unknown
                      collective_budget=CollectiveBudget(
                          counts={"psum": 1}, axes=()),
                      # the replicated bf16 GEMM operands (128 KiB each)
                      # trip the scouting warning at this threshold
                      replicated_bytes=1 << 16)


def test_golden_fixture_matches():
    got = seeded_violation_report().to_dict()
    want = json.loads(GOLDEN.read_text())
    assert got == want, (
        "audit JSON drifted from the golden fixture; if the change is "
        "intentional, regenerate with:\n  python -c \"import json, "
        "tests.test_static_audit as t; print(json.dumps("
        "t.seeded_violation_report().to_dict(), indent=2))\" "
        "> tests/data/static_audit_golden.json")


def test_golden_fixture_covers_every_family():
    want = json.loads(GOLDEN.read_text())
    rules = {f["rule"] for f in want["findings"]}
    assert rules == {"donation", "host_sync", "dtype_flow", "constants",
                     "packing", "scopes", "collectives", "sharding"}
    assert want["ok"] is False


def test_audit_json_is_deterministic():
    a = seeded_violation_report().to_json()
    b = seeded_violation_report().to_json()
    assert a == b


# ---------------------------------------------------------------------------
# assert_step_clean gating
# ---------------------------------------------------------------------------
def test_assert_step_clean_raises_with_table():
    params, grads, opt, state = _packed_setup()
    step = jax.jit(lambda g, s, p: opt.step(g, s, p))  # undonated
    with pytest.raises(AssertionError, match="undonated_state"):
        assert_step_clean(step, grads, state, params, min_bytes=4096)


def test_assert_step_clean_severity_warning_gate():
    def step(x):
        return x.astype(jnp.bfloat16).astype(jnp.float32) * 2.0

    x = jnp.zeros((65536,), jnp.float32)
    # double_cast is warning-severity: clean at the default error gate...
    assert_step_clean(step, x, compute_dtype="bfloat16")
    # ...but the warning gate trips on it
    with pytest.raises(AssertionError, match="double_cast"):
        assert_step_clean(step, x, compute_dtype="bfloat16",
                          severity="warning")


def test_unknown_rule_rejected():
    with pytest.raises(ValueError, match="unknown rules"):
        audit_step(lambda x: x, jnp.float32(0), rules=("no_such_rule",))


# ---------------------------------------------------------------------------
# self-audit: the repo's own hot paths are clean (tier-1 CI gate for
# tools/static_audit.py --self)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("target", sorted(static_audit.TARGETS))
def test_self_audit_target_clean(target):
    fn, args, kw = static_audit.TARGETS[target]()
    rep = assert_step_clean(fn, *args, name=target, **kw)
    assert rep.ok


def test_self_audit_cli_json_exit_zero(capsys):
    rc = static_audit.main(["--self", "--target", "telemetry_drain",
                            "--target", "packed_adam_step", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["ok"] is True
    assert set(out["targets"]) == {"telemetry_drain", "packed_adam_step"}


def test_self_audit_cli_exits_nonzero_on_errors(monkeypatch, capsys):
    def bad_target():
        params, grads, opt, state = _packed_setup()
        step = jax.jit(lambda g, s, p: opt.step(g, s, p))  # undonated
        return step, (grads, state, params), {"min_bytes": 4096}

    monkeypatch.setitem(static_audit.TARGETS, "seeded_bad", bad_target)
    rc = static_audit.main(["--self", "--target", "seeded_bad", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1 and out["ok"] is False


def test_self_audit_cli_fail_on_warning(monkeypatch, capsys):
    def warn_target():
        def step(x):
            y = jnp.exp(x)
            return y.astype(jnp.bfloat16).astype(jnp.float32)

        return (step, (jnp.zeros((65536,), jnp.float32),),
                {"compute_dtype": "bfloat16"})

    monkeypatch.setitem(static_audit.TARGETS, "warny", warn_target)
    assert static_audit.main(
        ["--self", "--target", "warny", "--json"]) == 0
    capsys.readouterr()
    assert static_audit.main(
        ["--self", "--target", "warny", "--json", "--fail-on",
         "warning"]) == 1


# ---------------------------------------------------------------------------
# compare_bench integration: audit status rides the perf gate
# ---------------------------------------------------------------------------
def test_compare_bench_reports_audit_status():
    from tools.compare_bench import compare

    base = {"value": 30000.0,
            "audit": {"ok": True, "error": 0, "warning": 0, "codes": []}}
    new = {"value": 30000.0,
           "audit": {"ok": False, "error": 2, "warning": 1,
                     "codes": ["undonated_state", "ungated_callback"]}}
    rep = compare(base, new)
    assert rep["audit"]["base"]["ok"] is True
    assert rep["audit"]["new"]["ok"] is False
    legs = [r["leg"] for r in rep["regressions"]]
    assert "static_audit" in legs


def test_compare_bench_audit_absent_is_not_a_regression():
    from tools.compare_bench import compare

    rep = compare({"value": 30000.0}, {"value": 30000.0})
    assert rep["audit"] == {"base": None, "new": None}
    assert rep["regressions"] == []
