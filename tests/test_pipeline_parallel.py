"""Pipeline-parallel tests.

The heart is the reference's equivalence idiom
(``tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py``): the
pipelined schedules must reproduce the loss and gradients of a
single-device sequential run of the same stacked model.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_no_pipelining,
    get_forward_backward_func,
    p2p_communication,
    run_pipeline,
    run_pipeline_interleaved,
)
from apex_tpu.transformer.pipeline_parallel.schedules import build_model
from apex_tpu.transformer.pipeline_parallel.utils import (
    _reconfigure_microbatch_calculator,
    destroy_num_microbatches_calculator,
    get_kth_microbatch,
    get_ltor_masks_and_position_ids,
    get_num_microbatches,
    split_into_microbatches,
    update_num_microbatches,
)

PP = 4
N_MICRO = 6
MBS, H = 2, 8


@pytest.fixture
def pp_mesh():
    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=1, pipeline_model_parallel_size_=PP,
        devices=jax.devices()[:PP],
    )
    yield parallel_state.get_mesh()
    parallel_state.destroy_model_parallel()


def _stage_fn(params, x):
    """One pipeline stage: a little MLP block."""
    h = jnp.tanh(x @ params["w"] + params["b"])
    return h


def _loss_fn(y, target):
    return jnp.mean((y - target) ** 2)


def _make_params(key, n_stages):
    keys = jax.random.split(key, n_stages)
    return {
        "w": jnp.stack(
            [jax.random.normal(k, (H, H)) * 0.5 for k in keys]
        ),
        "b": jnp.zeros((n_stages, H)),
    }


def _sequential_reference(stacked_params, inputs, targets, n_stages):
    """Run the same stacked model sequentially on one device."""

    def full_model(params, x):
        for s in range(n_stages):
            x = _stage_fn(
                jax.tree_util.tree_map(lambda p: p[s], params), x
            )
        return x

    def loss(params):
        total = 0.0
        for m in range(inputs.shape[0]):
            total = total + _loss_fn(full_model(params, inputs[m]), targets[m])
        return total / inputs.shape[0]

    return jax.value_and_grad(loss)(stacked_params)


def test_pipeline_matches_sequential(pp_mesh):
    key = jax.random.PRNGKey(0)
    params = _make_params(key, PP)
    inputs = jax.random.normal(jax.random.PRNGKey(1), (N_MICRO, MBS, H))
    targets = jax.random.normal(jax.random.PRNGKey(2), (N_MICRO, MBS, H))

    loss, grads, dinp = run_pipeline(
        pp_mesh, _stage_fn, _loss_fn, params, inputs, targets
    )
    ref_loss, ref_grads = _sequential_reference(params, inputs, targets, PP)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(grads["w"]), np.asarray(ref_grads["w"]), atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(grads["b"]), np.asarray(ref_grads["b"]), atol=1e-5
    )
    # dinputs also matches the sequential model's input gradient
    ref_dinp = jax.grad(lambda inp: _seq_loss(params, inp, targets))(inputs)
    np.testing.assert_allclose(np.asarray(dinp), np.asarray(ref_dinp), atol=1e-5)


def _seq_loss(params, inputs, targets):
    def full_model(params, x):
        for s in range(PP):
            x = _stage_fn(jax.tree_util.tree_map(lambda p: p[s], params), x)
        return x

    total = 0.0
    for m in range(inputs.shape[0]):
        total = total + _loss_fn(full_model(params, inputs[m]), targets[m])
    return total / inputs.shape[0]


def test_pipeline_forward_only(pp_mesh):
    params = _make_params(jax.random.PRNGKey(3), PP)
    inputs = jax.random.normal(jax.random.PRNGKey(4), (N_MICRO, MBS, H))
    targets = jnp.zeros((N_MICRO, MBS, H))
    loss = run_pipeline(
        pp_mesh, _stage_fn, _loss_fn, params, inputs, targets, forward_only=True
    )
    ref_loss, _ = _sequential_reference(params, inputs, targets, PP)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)


def test_interleaved_matches_sequential(pp_mesh):
    """vpp=2: every microbatch crosses the ring twice; equivalence vs the
    8-block sequential model with the interleaved chunk->layer mapping
    (chunk v on stage s holds global block v*pp + s)."""
    VPP = 2
    NM = 2 * PP  # the interleaved schedule requires n_micro % pp == 0
    parallel_state.set_virtual_pipeline_model_parallel_world_size(VPP)
    key = jax.random.PRNGKey(5)
    flat = _make_params(key, PP * VPP)  # global blocks 0..7
    # reorder to [pp, vpp]: stage s, chunk v = global block v*PP + s
    params = {
        k: jnp.stack(
            [jnp.stack([flat[k][v * PP + s] for v in range(VPP)]) for s in range(PP)]
        )
        for k in flat
    }
    inputs = jax.random.normal(jax.random.PRNGKey(6), (NM, MBS, H))
    targets = jax.random.normal(jax.random.PRNGKey(7), (NM, MBS, H))

    loss, grads, _ = run_pipeline_interleaved(
        pp_mesh, _stage_fn, _loss_fn, params, inputs, targets
    )
    ref_loss, ref_grads = _sequential_reference(flat, inputs, targets, PP * VPP)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for k in flat:
        got = np.asarray(grads[k])  # [pp, vpp, ...]
        for s in range(PP):
            for v in range(VPP):
                np.testing.assert_allclose(
                    got[s, v], np.asarray(ref_grads[k][v * PP + s]), atol=1e-5,
                    err_msg=f"{k} stage {s} chunk {v}",
                )
    parallel_state.set_virtual_pipeline_model_parallel_world_size(None)


def test_no_pipelining_schedule():
    params = {"w": jax.random.normal(jax.random.PRNGKey(8), (H, H)) * 0.5,
              "b": jnp.zeros((H,))}
    inputs = jax.random.normal(jax.random.PRNGKey(9), (N_MICRO, MBS, H))
    targets = jax.random.normal(jax.random.PRNGKey(10), (N_MICRO, MBS, H))

    loss, grads = forward_backward_no_pipelining(
        _stage_fn, _loss_fn, params, inputs, targets
    )

    def ref(params):
        total = 0.0
        for m in range(N_MICRO):
            total = total + _loss_fn(_stage_fn(params, inputs[m]), targets[m])
        return total / N_MICRO

    ref_loss, ref_grads = jax.value_and_grad(ref)(params)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(grads["w"]), np.asarray(ref_grads["w"]), atol=1e-6
    )

    loss_fo, grads_fo = forward_backward_no_pipelining(
        _stage_fn, _loss_fn, params, inputs, targets, forward_only=True
    )
    assert grads_fo is None
    np.testing.assert_allclose(float(loss_fo), float(ref_loss), rtol=1e-6)


def test_get_forward_backward_func_dispatch(pp_mesh):
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        forward_backward_no_pipelining as nopipe,
        pipeline_forward_backward as pipe,
        pipeline_forward_backward_interleaved as inter,
    )

    assert get_forward_backward_func(None, 1) is nopipe
    assert get_forward_backward_func(None, PP) is pipe
    assert get_forward_backward_func(2, PP) is inter


def test_p2p_rotation(pp_mesh):
    x = jnp.arange(PP * 3, dtype=jnp.float32).reshape(PP, 3)

    out = jax.shard_map(
        lambda t: p2p_communication.send_forward(t, "pipeline"),
        mesh=pp_mesh, in_specs=P("pipeline"), out_specs=P("pipeline"),
        check_vma=False,
    )(x)
    np.testing.assert_allclose(np.asarray(out), np.roll(np.asarray(x), 1, 0))

    back = jax.shard_map(
        lambda t: p2p_communication.send_backward(t, "pipeline"),
        mesh=pp_mesh, in_specs=P("pipeline"), out_specs=P("pipeline"),
        check_vma=False,
    )(x)
    np.testing.assert_allclose(np.asarray(back), np.roll(np.asarray(x), -1, 0))


def test_build_model_virtual_chunks(pp_mesh):
    built_ranks = []

    def provider():
        built_ranks.append(
            parallel_state.get_virtual_pipeline_model_parallel_rank()
        )
        return {"w": jnp.zeros((2, 2))}

    chunks = build_model(provider, virtual_pipeline_model_parallel_size=3)
    assert len(chunks) == 3 and built_ranks == [0, 1, 2]
    single = build_model(provider, virtual_pipeline_model_parallel_size=None)
    assert len(single) == 1


def test_microbatch_calculator_and_utils():
    destroy_num_microbatches_calculator()
    _reconfigure_microbatch_calculator(0, None, 24, 2, 3)
    assert get_num_microbatches() == 4
    update_num_microbatches(100)  # constant: no-op
    assert get_num_microbatches() == 4

    # rampup: 8 -> 24 by 8 over 90 samples
    _reconfigure_microbatch_calculator(0, [8, 8, 90], 24, 2, 2)
    assert get_num_microbatches() == 2  # start 8 / (2*2)
    update_num_microbatches(50, consistency_check=True)
    assert get_num_microbatches() == 4  # 16 / 4
    update_num_microbatches(1000)
    assert get_num_microbatches() == 6  # 24 / 4
    destroy_num_microbatches_calculator()

    batch = {"x": jnp.arange(24).reshape(12, 2)}
    _reconfigure_microbatch_calculator(0, None, 12, 3, 1)
    mb1 = get_kth_microbatch(batch, 1)
    np.testing.assert_array_equal(np.asarray(mb1["x"]), np.arange(6, 12).reshape(3, 2))
    destroy_num_microbatches_calculator()

    split = split_into_microbatches(batch, 4)
    assert split["x"].shape == (4, 3, 2)


def test_rampup_consistency_check_boundaries():
    """VERDICT r4 weak #5: the reference's consistency-check semantics on
    non-divisible rampup boundaries (``apex/transformer/microbatches.py:
    169-195``) — a mid-rampup global batch that is NOT divisible by
    micro_batch*dp must raise when checked, pass silently when not, and
    the exact-boundary/overshoot sample counts must land on the right
    batch sizes."""
    import pytest as _pytest

    from apex_tpu.transformer.microbatches import (
        RampupBatchsizeNumMicroBatches,
        build_num_microbatches_calculator,
    )

    # rampup 4 -> 16 by +2 over 60 samples, mbs*dp = 4: the intermediate
    # global batches 6, 10, 14 are NOT divisible by 4
    calc = RampupBatchsizeNumMicroBatches(4, 2, 60, 16, 2, 2)
    assert calc.get_current_global_batch_size() == 4
    # consumed=10 -> steps=1 -> gbs 6: divisible check must fire
    with _pytest.raises(ValueError, match="not divisible"):
        calc.update(10, consistency_check=True)
    # ... and the unchecked update (the reference's mid-epoch data-loader
    # path) must accept it, flooring num_micro_batches
    calc.update(10, consistency_check=False)
    assert calc.get_current_global_batch_size() == 6
    assert calc.get() == 1  # floor(6 / 4)

    # exact increment boundary: consumed == k * samples-per-increment
    calc2 = RampupBatchsizeNumMicroBatches(4, 4, 60, 16, 2, 2)
    per_inc = 60 / 3
    calc2.update(int(per_inc), consistency_check=True)
    assert calc2.get_current_global_batch_size() == 8
    # one sample before the boundary stays on the previous size
    calc2.update(int(per_inc) - 1, consistency_check=True)
    assert calc2.get_current_global_batch_size() == 4
    # consumed == ramup_samples exactly: the LAST increment (not the
    # post-rampup branch) — reference's `>` comparison, not `>=`
    calc2.update(60, consistency_check=True)
    assert calc2.get_current_global_batch_size() == 16
    # past the rampup: pinned at the full global batch
    calc2.update(10_000, consistency_check=True)
    assert calc2.get_current_global_batch_size() == 16
    assert calc2.get() == 4

    # zero-length rampup (start == global): per-increment is guarded and
    # every consumed count lands on the full batch
    calc3 = RampupBatchsizeNumMicroBatches(16, 4, 0, 16, 2, 2)
    calc3.update(0, consistency_check=True)
    assert calc3.get_current_global_batch_size() == 16
    calc3.update(5, consistency_check=True)
    assert calc3.get_current_global_batch_size() == 16

    # the build-time format error (reference print/raise parity)
    with _pytest.raises(ValueError, match="rampup-batch-size"):
        build_num_microbatches_calculator(0, [8, 8], 24, 2, 2)


def test_get_ltor_masks_and_position_ids():
    eod = 0
    data = jnp.array([[5, 3, eod, 7, 2, eod, 4, 9]])
    am, lm, pid = get_ltor_masks_and_position_ids(
        data, eod, reset_position_ids=True, reset_attention_mask=True,
        eod_mask_loss=True,
    )
    # positions restart after each eod
    np.testing.assert_array_equal(
        np.asarray(pid[0]), [0, 1, 2, 0, 1, 2, 0, 1]
    )
    # loss masked at eod positions
    np.testing.assert_array_equal(np.asarray(lm[0]), [1, 1, 0, 1, 1, 0, 1, 1])
    # token 3 (first of doc 1) cannot attend to doc 0
    assert bool(am[0, 0, 3, 1])  # masked
    assert not bool(am[0, 0, 4, 3])  # same doc, earlier position: visible
    # causal upper triangle masked
    assert bool(am[0, 0, 1, 2])


def _collect_scan_lengths(jaxpr, acc):
    """Recursively collect lax.scan trip counts from a jaxpr."""

    def _sub(v):
        # ClosedJaxpr has .jaxpr; raw Jaxpr has .eqns
        if hasattr(v, "jaxpr"):
            return v.jaxpr
        if hasattr(v, "eqns"):
            return v
        return None

    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "scan":
            acc.append(eqn.params["length"])
        for v in eqn.params.values():
            items = v if isinstance(v, (list, tuple)) else (v,)
            for item in items:
                sub = _sub(item)
                if sub is not None:
                    _collect_scan_lengths(sub, acc)
    return acc


def test_interleaved_is_single_scan_no_round_barrier(pp_mesh):
    """Structural guarantee of actual interleaving: the whole vpp-round
    traversal is ONE scan of n*vpp + pp - 1 ticks — round r+1 enters stage 0
    while round r drains. A barriered implementation would show vpp scans of
    n + pp - 1 ticks instead."""
    VPP = 2
    NM = 2 * PP
    params = {
        "w": jnp.zeros((PP, VPP, H, H)),
        "b": jnp.zeros((PP, VPP, H)),
    }
    inputs = jnp.zeros((NM, MBS, H))
    targets = jnp.zeros((NM, MBS, H))

    jaxpr = jax.make_jaxpr(
        lambda p, i, t: run_pipeline_interleaved(
            pp_mesh, _stage_fn, _loss_fn, p, i, t, forward_only=True
        )
    )(params, inputs, targets)
    lengths = _collect_scan_lengths(jaxpr.jaxpr, [])
    expected = NM * VPP + PP - 1
    assert expected in lengths, f"no {expected}-tick scan found: {lengths}"
    assert NM + PP - 1 not in lengths, (
        f"found a per-round {NM + PP - 1}-tick scan — schedule is barriered"
    )


def test_local_form_works_without_vma_tracking(pp_mesh):
    """pipeline_forward_backward is exported for embedding in user shard_maps,
    including check_vma=False ones where every aval has an empty vma — the
    loss/dinputs pipeline psum must still run there (regression: the
    vma-conditional sync must not silently skip it)."""
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        pipeline_forward_backward,
    )

    params = _make_params(jax.random.PRNGKey(11), PP)
    inputs = jax.random.normal(jax.random.PRNGKey(12), (N_MICRO, MBS, H))
    targets = jax.random.normal(jax.random.PRNGKey(13), (N_MICRO, MBS, H))
    pspec = jax.tree_util.tree_map(lambda _: P("pipeline"), params)

    def local(p, i, t):
        p = jax.tree_util.tree_map(lambda x: x[0], p)
        loss, grads, dinp = pipeline_forward_backward(
            _stage_fn, _loss_fn, p, i, t, axis_name="pipeline"
        )
        return loss, jax.tree_util.tree_map(lambda g: g[None], grads), dinp

    loss, grads, dinp = jax.shard_map(
        local, mesh=pp_mesh, in_specs=(pspec, P(), P()),
        out_specs=(P(), pspec, P()), check_vma=False,
    )(params, inputs, targets)

    ref_loss, ref_grads = _sequential_reference(params, inputs, targets, PP)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(grads["w"]), np.asarray(ref_grads["w"]), atol=1e-5)
    ref_dinp = jax.grad(lambda inp: _seq_loss(params, inp, targets))(inputs)
    np.testing.assert_allclose(np.asarray(dinp), np.asarray(ref_dinp), atol=1e-5)


def test_pipeline_mixed_precision_loss_dtype(pp_mesh):
    """bf16 stage outputs with an fp32 loss_fn — the canonical mixed
    precision setup — must work (regression: the loss accumulator's dtype
    was once pinned to the stage-output dtype)."""
    params = jax.tree_util.tree_map(
        lambda p: p.astype(jnp.bfloat16), _make_params(jax.random.PRNGKey(20), PP)
    )
    inputs = jax.random.normal(
        jax.random.PRNGKey(21), (N_MICRO, MBS, H)
    ).astype(jnp.bfloat16)
    targets = jax.random.normal(jax.random.PRNGKey(22), (N_MICRO, MBS, H))

    def f32_loss(y, tgt):
        return jnp.mean((y.astype(jnp.float32) - tgt) ** 2)

    loss, grads, _ = run_pipeline(
        pp_mesh, _stage_fn, f32_loss, params, inputs, targets
    )
    assert loss.dtype == jnp.float32
    assert np.isfinite(float(loss))
    assert all(np.isfinite(np.asarray(g, np.float32)).all()
               for g in jax.tree_util.tree_leaves(grads))


def test_interleaved_requires_divisible_microbatches(pp_mesh):
    VPP = 2
    params = {"w": jnp.zeros((PP, VPP, H, H)), "b": jnp.zeros((PP, VPP, H))}
    inputs = jnp.zeros((PP + 1, MBS, H))  # not divisible by PP
    targets = jnp.zeros((PP + 1, MBS, H))
    with pytest.raises(ValueError, match="divisible"):
        run_pipeline_interleaved(
            pp_mesh, _stage_fn, _loss_fn, params, inputs, targets,
            forward_only=True)


def test_model_parallel_grad_scaler():
    from apex_tpu.transformer.amp import GradScaler

    scaler = GradScaler(model_parallel_axes=("tensor",))
    state = scaler.init_state()
    parallel_state.initialize_model_parallel(tensor_model_parallel_size_=8)
    mesh = parallel_state.get_mesh()

    def f(grads):
        rank = jax.lax.axis_index("tensor")
        # only rank 3 overflows
        g = {"w": jnp.where(rank == 3, jnp.inf, 1.0) * grads["w"]}
        out, new_state = scaler.unscale(state, g)
        return new_state.found_inf[None]

    found = jax.shard_map(
        f, mesh=mesh, in_specs=({"w": P()},), out_specs=P("tensor"),
        check_vma=False,
    )({"w": jnp.ones((8, 2))})
    # every rank agrees: overflow
    assert np.asarray(found).all()
    parallel_state.destroy_model_parallel()


def test_tick_checkpoint_equivalent(pp_mesh):
    """sqrt-style tick checkpointing (tick_checkpoint=K): identical loss and
    grads, including with a K that does not divide the tick count (padded
    harmless ticks)."""
    key = jax.random.PRNGKey(30)
    params = _make_params(key, PP)
    inputs = jax.random.normal(jax.random.PRNGKey(31), (N_MICRO, MBS, H))
    targets = jax.random.normal(jax.random.PRNGKey(32), (N_MICRO, MBS, H))

    base_loss, base_grads, base_dinp = run_pipeline(
        pp_mesh, _stage_fn, _loss_fn, params, inputs, targets)
    for k in (3, 5):  # total = 9 ticks: k=3 divides exactly, k=5 pads
        # nested remat needs jit around the shard_map (JAX can't eval
        # closed_call eagerly inside shard_map) — the real usage anyway
        loss, grads, dinp = jax.jit(
            lambda p, i, t, k=k: run_pipeline(
                pp_mesh, _stage_fn, _loss_fn, p, i, t, tick_checkpoint=k)
        )(params, inputs, targets)
        np.testing.assert_allclose(float(loss), float(base_loss), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(grads["w"]), np.asarray(base_grads["w"]), atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(dinp), np.asarray(base_dinp), atol=1e-6)


def test_tick_checkpoint_interleaved_equivalent(pp_mesh):
    """tick_checkpoint composed with virtual chunks (vpp=2): the
    emission-slot capacity depends on vpp — pin exact equality vs the
    un-checkpointed interleaved run."""
    VPP = 2
    NM = 2 * PP
    flat = _make_params(jax.random.PRNGKey(40), PP * VPP)
    params = {
        k: jnp.stack(
            [jnp.stack([flat[k][v * PP + s] for v in range(VPP)])
             for s in range(PP)])
        for k in flat
    }
    inputs = jax.random.normal(jax.random.PRNGKey(41), (NM, MBS, H))
    targets = jax.random.normal(jax.random.PRNGKey(42), (NM, MBS, H))

    base_loss, base_grads, _ = run_pipeline_interleaved(
        pp_mesh, _stage_fn, _loss_fn, params, inputs, targets)
    for k in (4, 6):  # total = 19 ticks: both pad
        loss, grads, _ = jax.jit(
            lambda p, i, t, k=k: run_pipeline_interleaved(
                pp_mesh, _stage_fn, _loss_fn, p, i, t, tick_checkpoint=k)
        )(params, inputs, targets)
        np.testing.assert_allclose(float(loss), float(base_loss), rtol=1e-6)
        for key in params:
            np.testing.assert_allclose(
                np.asarray(grads[key]), np.asarray(base_grads[key]),
                atol=1e-6, err_msg=f"{key} k={k}")


def test_semantic_parity_kwargs_warn_once(pp_mesh):
    """Accepted-and-ignored SEMANTIC kwargs must be loud (once), mechanical
    ones silent (VERDICT r2 weak #7)."""
    import warnings

    from apex_tpu.transformer.pipeline_parallel.schedules import (
        forward_backward_no_pipelining,
    )
    from apex_tpu.transformer.pipeline_parallel.schedules import common

    params = {"w": jnp.zeros((H, H)), "b": jnp.zeros((H,))}
    inputs = jnp.zeros((2, MBS, H))
    targets = jnp.zeros((2, MBS, H))
    common._warned_parity_kwargs.clear()

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        forward_backward_no_pipelining(
            _stage_fn, _loss_fn, params, inputs, targets,
            custom_sync_context_handler=lambda: None,  # semantic -> warn
            tensor_shape=(2, MBS, H),                  # mechanical -> silent
        )
        semantic = [x for x in w if "custom_sync_context_handler" in str(x.message)]
        assert len(semantic) == 1
        assert not any("tensor_shape" in str(x.message) for x in w)

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        forward_backward_no_pipelining(
            _stage_fn, _loss_fn, params, inputs, targets,
            custom_sync_context_handler=lambda: None,
        )
        # warned once already (filter on OUR message: unrelated jax
        # warnings must not fail this test)
        assert not any("parity kwarg" in str(x.message) for x in w)
