"""Tensor-parallel tests: mappings, layers, cross entropy, RNG, data, memory.

Mirrors the reference's run_transformer distributed suites
(``tests/L0/run_transformer/test_{mapping,layers,cross_entropy,random,data}.py``)
on the 8-virtual-device CPU mesh: collective fwd/bwd duality, TP layers vs
dense single-device equivalence, vocab-parallel CE vs plain CE.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer import tensor_parallel as tp

shard_map = jax.shard_map

TP = 8


@pytest.fixture(autouse=True)
def _init_parallel():
    parallel_state.initialize_model_parallel(tensor_model_parallel_size_=TP)
    yield
    parallel_state.destroy_model_parallel()


def _mesh():
    return parallel_state.get_mesh()


def _smap(f, in_specs, out_specs, check_vma=True):
    return shard_map(
        f, mesh=_mesh(), in_specs=in_specs, out_specs=out_specs,
        check_vma=check_vma,
    )


def test_parallel_state_sizes():
    assert parallel_state.get_tensor_model_parallel_world_size() == TP
    assert parallel_state.get_pipeline_model_parallel_world_size() == 1
    assert parallel_state.get_data_parallel_world_size() == 1
    assert parallel_state.model_parallel_is_initialized()
    # trivial axes give static rank 0
    assert parallel_state.get_pipeline_model_parallel_rank() == 0
    assert parallel_state.is_pipeline_first_stage()
    assert parallel_state.is_pipeline_last_stage()


def test_parallel_state_split_rank():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=1,
        pipeline_model_parallel_size_=4,
        pipeline_model_parallel_split_rank_=2,
    )
    assert parallel_state.get_pipeline_model_parallel_split_rank() == 2
    assert parallel_state.get_data_parallel_world_size() == 2


# --- mappings fwd/bwd duality (reference test_mapping.py) --------------------

def test_copy_region_fwd_identity_bwd_allreduce():
    x = jax.random.normal(jax.random.PRNGKey(0), (4,))

    def f(x_rep):
        return tp.copy_to_tensor_model_parallel_region(x_rep, "tensor")

    # forward: identity (replicated input passes through)
    out = _smap(f, P(), P("tensor"))(x)
    np.testing.assert_allclose(
        np.asarray(out), np.tile(np.asarray(x), TP) / 1.0
    )

    # backward: a replicated input feeding device-varying compute gets the
    # per-rank cotangents ALL-REDUCED (the Megatron copy-region dual, here
    # produced by the vma transpose): each rank contributes rank+1 → psum
    def g(x_rep):
        rank = jax.lax.axis_index("tensor")
        y = tp.copy_to_tensor_model_parallel_region(x_rep, "tensor")
        return jnp.sum(y * (rank + 1.0))

    grads = _smap(jax.grad(g), P(), P())(x)
    np.testing.assert_allclose(np.asarray(grads), sum(range(1, TP + 1)) * 1.0)


def test_reduce_region_fwd_allreduce():
    x = jnp.ones((TP, 3))
    out = _smap(
        lambda xs: tp.reduce_from_tensor_model_parallel_region(xs, "tensor"),
        P("tensor", None), P("tensor", None),
    )(x)
    np.testing.assert_allclose(np.asarray(out), TP)


def test_scatter_gather_roundtrip():
    full = jax.random.normal(jax.random.PRNGKey(1), (4, TP * 5))

    def f(x_rep):
        local = tp.scatter_to_tensor_model_parallel_region(x_rep, "tensor")
        assert local.shape == (4, 5)
        return tp.gather_from_tensor_model_parallel_region(local, "tensor")

    out = _smap(f, P(), P(), check_vma=False)(full)
    np.testing.assert_allclose(np.asarray(out), np.asarray(full))


def test_sequence_parallel_roundtrip_and_reduce_scatter():
    seq = TP * 3
    full = jax.random.normal(jax.random.PRNGKey(2), (seq, 2, 4))

    def f(x_rep):
        local = tp.scatter_to_sequence_parallel_region(x_rep, "tensor")
        assert local.shape == (3, 2, 4)
        return tp.gather_from_sequence_parallel_region(local, "tensor", True)

    out = _smap(f, P(), P(), check_vma=False)(full)
    np.testing.assert_allclose(np.asarray(out), np.asarray(full))

    # reduce_scatter: each shard ends with the summed slice
    def g(x_rep):
        return tp.reduce_scatter_to_sequence_parallel_region(x_rep, "tensor")

    rs = _smap(g, P(), P("tensor", None, None))(full)
    np.testing.assert_allclose(np.asarray(rs), TP * np.asarray(full), rtol=1e-6)


# --- TP linears vs dense (reference test_layers.py) --------------------------

def test_column_parallel_linear_matches_dense():
    key = jax.random.PRNGKey(3)
    in_f, out_f = 12, TP * 4
    x = jax.random.normal(key, (6, in_f))
    w_full = jax.random.normal(jax.random.PRNGKey(4), (out_f, in_f)) * 0.1
    b_full = jax.random.normal(jax.random.PRNGKey(5), (out_f,)) * 0.1

    def f(x_rep, w_shard, b_shard):
        out, _, _ = tp.column_parallel_linear(
            x_rep, w_shard, b_shard, axis_name="tensor", gather_output=True
        )
        return out

    out = _smap(
        f, (P(), P("tensor", None), P("tensor")), P(), check_vma=False
    )(x, w_full, b_full)
    ref = x @ w_full.T + b_full
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_row_parallel_linear_matches_dense():
    in_f, out_f = TP * 4, 6
    x = jax.random.normal(jax.random.PRNGKey(6), (5, in_f))
    w_full = jax.random.normal(jax.random.PRNGKey(7), (out_f, in_f)) * 0.1
    b = jax.random.normal(jax.random.PRNGKey(8), (out_f,)) * 0.1

    def f(x_rep, w_shard, b_rep):
        out, _, _ = tp.row_parallel_linear(
            x_rep, w_shard, b_rep, axis_name="tensor", input_is_parallel=False
        )
        return out

    out = _smap(f, (P(), P(None, "tensor"), P()), P())(x, w_full, b)
    ref = x @ w_full.T + b
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_column_row_pair_backward_matches_dense():
    """MLP block: column(gather=False) -> row(input_is_parallel): fwd + grads
    must equal the dense computation (reference test_layers.py idiom)."""
    in_f, hid, out_f = 8, TP * 4, 8
    x = jax.random.normal(jax.random.PRNGKey(9), (3, in_f))
    w1 = jax.random.normal(jax.random.PRNGKey(10), (hid, in_f)) * 0.2
    w2 = jax.random.normal(jax.random.PRNGKey(11), (out_f, hid)) * 0.2

    def dense_loss(x, w1, w2):
        h = jax.nn.gelu(x @ w1.T)
        return jnp.sum((h @ w2.T) ** 2)

    def tp_loss(x_rep, w1_s, w2_s):
        h, _, _ = tp.column_parallel_linear(
            x_rep, w1_s, None, axis_name="tensor", gather_output=False
        )
        h = jax.nn.gelu(h)
        y, _, _ = tp.row_parallel_linear(
            h, w2_s, None, axis_name="tensor", input_is_parallel=True
        )
        return jnp.sum(y**2)

    grads_tp = _smap(
        jax.grad(tp_loss, argnums=(0, 1, 2)),
        (P(), P("tensor", None), P(None, "tensor")),
        (P(), P("tensor", None), P(None, "tensor")),
    )(x, w1, w2)
    gx_tp, gw1_tp, gw2_tp = [np.asarray(g) for g in grads_tp]

    gx, gw1, gw2 = [
        np.asarray(g) for g in jax.grad(dense_loss, argnums=(0, 1, 2))(x, w1, w2)
    ]
    np.testing.assert_allclose(gx_tp, gx, atol=2e-4)
    np.testing.assert_allclose(gw1_tp, gw1, atol=2e-4)
    np.testing.assert_allclose(gw2_tp, gw2, atol=2e-4)


def test_vocab_parallel_embedding_matches_dense():
    vocab, hidden = TP * 6, 5
    ids = jnp.array([[0, 3, 17, 47], [5, 46, 23, 11]])
    table = jax.random.normal(jax.random.PRNGKey(12), (vocab, hidden))

    out = _smap(
        lambda i, t: tp.vocab_parallel_embedding(i, t, axis_name="tensor"),
        (P(), P("tensor", None)), P(),
    )(ids, table)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(table)[np.asarray(ids)], atol=1e-6
    )


# --- vocab-parallel CE (reference test_cross_entropy.py) ---------------------

@pytest.mark.parametrize("label_smoothing", [0.0, 0.1])
def test_vocab_parallel_cross_entropy_matches_dense(label_smoothing):
    vocab = TP * 8
    logits = jax.random.normal(jax.random.PRNGKey(13), (4, 7, vocab)) * 2
    targets = jax.random.randint(jax.random.PRNGKey(14), (4, 7), 0, vocab)

    loss_tp = _smap(
        lambda lg, t: tp.vocab_parallel_cross_entropy(
            lg, t, label_smoothing, "tensor"
        ),
        (P(None, None, "tensor"), P()), P(),
    )(logits, targets)

    # dense reference with the same smoothing formula
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], -1)[..., 0]
    if label_smoothing > 0:
        smoothing = label_smoothing * vocab / (vocab - 1)
        ref = (1 - smoothing) * nll - smoothing * jnp.mean(logp, -1)
    else:
        ref = nll
    np.testing.assert_allclose(np.asarray(loss_tp), np.asarray(ref), atol=1e-5)


def test_vocab_parallel_cross_entropy_grad():
    vocab = TP * 4
    logits = jax.random.normal(jax.random.PRNGKey(15), (3, vocab))
    targets = jnp.array([1, 17, 30])

    # check_vma=True: JAX tracks replication through the psums so the
    # replicated loss back-propagates exactly once into the sharded logits.
    g_tp = shard_map(
        jax.grad(
            lambda lg, t: jnp.sum(
                tp.vocab_parallel_cross_entropy(lg, t, 0.0, "tensor")
            )
        ),
        mesh=_mesh(), in_specs=(P(None, "tensor"), P()),
        out_specs=P(None, "tensor"), check_vma=True,
    )(logits, targets)

    g_ref = jax.grad(
        lambda lg: jnp.sum(
            -jnp.take_along_axis(jax.nn.log_softmax(lg), targets[..., None], -1)
        )
    )(logits)
    np.testing.assert_allclose(np.asarray(g_tp), np.asarray(g_ref), atol=1e-5)


# --- RNG tracker (reference test_random.py) ----------------------------------

def test_rng_tracker_fork_and_seed():
    tp.model_parallel_manual_seed(123)
    tracker = tp.get_rng_state_tracker()
    with tracker.fork() as k1:
        pass
    with tracker.fork() as k2:
        pass
    assert not np.array_equal(np.asarray(k1), np.asarray(k2))
    with pytest.raises(Exception):
        tracker.add("model-parallel-rng", 99)  # duplicate name
    with pytest.raises(Exception):
        tracker.fork("missing").__enter__()


def test_model_parallel_rng_key_diverges_per_rank():
    tp.model_parallel_manual_seed(7)
    base = jax.random.PRNGKey(7 + 2718)

    def f(_):
        k = tp.model_parallel_rng_key(base, "tensor")
        return jax.random.normal(k, (1, 4))

    out = np.asarray(
        _smap(f, P("tensor", None), P("tensor", None))(jnp.zeros((TP, 1)))
    )
    # every rank drew different randomness
    assert len({tuple(np.round(r, 6)) for r in out}) == TP


def test_checkpoint_matches_plain():
    def f(x):
        return jnp.sum(jnp.tanh(x) ** 2)

    x = jax.random.normal(jax.random.PRNGKey(16), (8,))
    assert np.allclose(
        tp.checkpoint(f, False, x), f(x)
    )
    g1 = jax.grad(lambda x: tp.checkpoint(f, False, x))(x)
    g2 = jax.grad(f)(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-6)


# --- data + memory ----------------------------------------------------------

def test_broadcast_data_host_and_traced():
    data = {"text": jnp.arange(6).reshape(2, 3), "mask": jnp.ones((2, 3))}
    out = tp.broadcast_data(["text"], data, jnp.int32)
    assert set(out) == {"text"} and out["text"].dtype == jnp.int32

    def f(x):
        rank = jax.lax.axis_index("tensor")
        local = {"v": x + rank.astype(x.dtype)}  # diverged per rank
        return tp.broadcast_data(["v"], local, axis_name="tensor")["v"]

    out2 = _smap(f, P(), P("tensor", None))(jnp.zeros((1, 2)))
    np.testing.assert_allclose(np.asarray(out2), 0.0)  # rank-0 value everywhere


def test_memory_buffer():
    buf = tp.MemoryBuffer("test", 32, jnp.float32, track_usage=True)
    t = buf.get((4, 4), 0)
    assert t.shape == (4, 4)
    with pytest.raises(ValueError):
        buf.get((33,), 0)
    ring = tp.RingMemBuffer("ring", 2, 16, jnp.float32)
    b1, b2, b3 = (ring.get_next_buffer() for _ in range(3))
    assert b1 is b3 and b1 is not b2


def test_utils():
    with pytest.raises(ValueError):
        tp.ensure_divisibility(7, 2)
    assert tp.divide(12, 4) == 3
    parts = tp.split_tensor_along_last_dim(jnp.ones((2, 8)), 4)
    assert len(parts) == 4 and parts[0].shape == (2, 2)
    first, last = tp.VocabUtility.vocab_range_from_global_vocab_size(64, 3, 8)
    assert (first, last) == (24, 32)


def test_gather_seq_split_backward_under_vma_tracking():
    """The to_model_parallel=False gather (custom-vjp slice backward) must
    work under check_vma=True — the mode the rest of the SP stack runs in
    (ADVICE r2: its only test used check_vma=False)."""
    seq = TP * 2
    x = jax.random.normal(jax.random.PRNGKey(7), (seq, 3))

    def local_loss(xl):
        y = tp.gather_from_sequence_parallel_region(xl, "tensor", False)
        return jnp.sum(y * y)

    g = _smap(
        lambda xl: jax.grad(local_loss)(xl),
        P("tensor"), P("tensor"), check_vma=True,
    )(x)
    # backward takes this rank's slice of the (identical) cotangent: 2x
    np.testing.assert_allclose(np.asarray(g), 2 * np.asarray(x), rtol=1e-6)

    # forward value still the all-gather (pmean to leave the vma region:
    # the gathered copies are identical, so the mean IS the gather)
    out = _smap(
        lambda xl: jax.lax.pmean(
            tp.gather_from_sequence_parallel_region(xl, "tensor", False),
            "tensor"),
        P("tensor"), P(), check_vma=True,
    )(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=1e-6)
