"""O1 autocast behavioral tests — the reference L0/run_amp port.

Mirrors ``tests/L0/run_amp/test_basic_casts.py`` (whitelist ops produce
half, blacklist ops produce float, unlisted ops match input),
``test_promotion.py`` (mixed-dtype n-ary ops produce the widest type),
``test_cache.py`` (the cast cache does not change gradients), and
``test_rnn.py`` (RNN cells are covered by the policy) — on the JAX O1
surface (``apex_tpu/amp/lists/jax_overrides.py``), with bf16 playing
fp16's role.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.amp.lists import jax_overrides as jo
from apex_tpu.RNN.cells import (
    GRUCell,
    LSTMCell,
    RNNReLUCell,
    RNNTanhCell,
)

B, H = 4, 16


def _x(dtype, key=0, shape=(B, H)):
    return jax.random.normal(jax.random.PRNGKey(key), shape).astype(dtype)


# ---------------------------------------------------------------------------
# basic casts (reference test_basic_casts.py)
# ---------------------------------------------------------------------------

LOW_PRECISION_CALLS = [
    ("matmul", lambda x: jnp.matmul(x, x.T)),
    ("dot", lambda x: jnp.dot(x, x.T)),
    ("einsum", lambda x: jnp.einsum("bh,oh->bo", x, x)),
    ("tensordot", lambda x: jnp.tensordot(x, x, axes=((1,), (1,)))),
    ("inner", lambda x: jnp.inner(x, x)),
    ("vdot", lambda x: jnp.vdot(x, x)),
    ("outer", lambda x: jnp.outer(x[0], x[0])),
    ("kron", lambda x: jnp.kron(x[:2, :2], x[:2, :2])),
    ("lax.dot", lambda x: jax.lax.dot(x, x.T)),
]


@pytest.mark.parametrize("name,fn", LOW_PRECISION_CALLS,
                         ids=[n for n, _ in LOW_PRECISION_CALLS])
@pytest.mark.parametrize("in_dtype", [jnp.float32, jnp.bfloat16])
def test_whitelist_is_low_precision(name, fn, in_dtype):
    """ALWAYS_HALF: whitelist ops produce bf16 from either input dtype."""
    with amp.autocast(dtype=jnp.bfloat16):
        y = fn(_x(in_dtype))
    assert y.dtype == jnp.bfloat16, (name, in_dtype, y.dtype)


FP32_CALLS = [
    ("exp", lambda x: jnp.exp(x)),
    ("log", lambda x: jnp.log(jnp.abs(x) + 1.0)),
    ("power", lambda x: jnp.power(jnp.abs(x) + 0.5, 2.5)),
    ("sum", lambda x: jnp.sum(x)),
    ("mean", lambda x: jnp.mean(x)),
    ("std", lambda x: jnp.std(x)),
    ("var", lambda x: jnp.var(x)),
    ("nanmean", lambda x: jnp.nanmean(x)),
    ("cumsum", lambda x: jnp.cumsum(x, axis=-1)),
    ("softmax", lambda x: jax.nn.softmax(x, axis=-1)),
    ("log_softmax", lambda x: jax.nn.log_softmax(x, axis=-1)),
    ("logsumexp", lambda x: jax.nn.logsumexp(x, axis=-1)),
    ("gelu", lambda x: jax.nn.gelu(x)),
    ("norm", lambda x: jnp.linalg.norm(x)),
    ("erf", lambda x: jax.scipy.special.erf(x)),
    ("xlogy", lambda x: jax.scipy.special.xlogy(
        jnp.abs(x), jnp.abs(x) + 1.0)),
]


@pytest.mark.parametrize("name,fn", FP32_CALLS,
                         ids=[n for n, _ in FP32_CALLS])
@pytest.mark.parametrize("in_dtype", [jnp.float32, jnp.bfloat16])
def test_blacklist_is_float(name, fn, in_dtype):
    """ALWAYS_FLOAT: blacklist ops produce fp32 from either input dtype."""
    with amp.autocast(dtype=jnp.bfloat16):
        y = fn(_x(in_dtype))
    assert y.dtype == jnp.float32, (name, in_dtype, y.dtype)


def test_loss_helpers_are_float():
    """The functional_overrides losses (mse/cross-entropy class) — optax
    is this stack's home for them."""
    import optax

    with amp.autocast(dtype=jnp.bfloat16):
        l2 = optax.l2_loss(_x(jnp.bfloat16), _x(jnp.bfloat16, 1))
        ce = optax.softmax_cross_entropy_with_integer_labels(
            _x(jnp.bfloat16), jnp.zeros((B,), jnp.int32))
    assert l2.dtype == jnp.float32
    assert ce.dtype == jnp.float32


@pytest.mark.parametrize("in_dtype", [jnp.float32, jnp.bfloat16])
def test_unlisted_matches_input(in_dtype):
    """MATCH_INPUT: ops on neither list keep their input dtype."""
    with amp.autocast(dtype=jnp.bfloat16):
        y = jax.nn.relu(_x(in_dtype))
        z = jnp.tanh(_x(in_dtype))
    assert y.dtype == in_dtype
    assert z.dtype == in_dtype


def test_backward_matches_input_dtype():
    """Reference run_layer_test(test_backward=True): the grad w.r.t. an
    input has the INPUT's dtype regardless of the op's cast."""
    for in_dtype in (jnp.float32, jnp.bfloat16):
        x = _x(in_dtype)

        def loss(x):
            with amp.autocast(dtype=jnp.bfloat16):
                return jnp.sum(jnp.matmul(x, x.T).astype(jnp.float32))

        g = jax.grad(loss)(x)
        assert g.dtype == in_dtype


def test_every_registered_entry_is_patchable():
    """Every (module, name) on both lists must exist, wrap on entry, and
    restore on exit — the per-op structural guarantee behind the
    behavioral samples above."""
    originals = {}
    for module, name in jo.LOW_PRECISION_FUNCS + jo.FP32_FUNCS:
        originals[(id(module), name)] = getattr(module, name)
    with amp.autocast(dtype=jnp.bfloat16):
        for module, name in jo.LOW_PRECISION_FUNCS + jo.FP32_FUNCS:
            assert hasattr(getattr(module, name), "__apex_tpu_wrapped__"), (
                module, name)
    for module, name in jo.LOW_PRECISION_FUNCS + jo.FP32_FUNCS:
        assert getattr(module, name) is originals[(id(module), name)], (
            module, name)


def test_list_sizes_cover_reference_surface():
    """The reference ships ~230 entries over three lists; the JAX surface
    is denser (one op covers several torch spellings) but must stay wide:
    >= 120 entries with the promote list, >= 100 patched."""
    patched = len(jo.LOW_PRECISION_FUNCS) + len(jo.FP32_FUNCS)
    assert patched >= 100, patched
    assert patched + len(jo.PROMOTE_FUNCS) >= 120
    # reference parity (ADVICE round 5): sqrt/square are NOT fp32 entries
    # in the reference lists — only rsqrt is. Pin them off the list.
    fp32_names = {name for _, name in jo.FP32_FUNCS}
    assert "sqrt" not in fp32_names
    assert "square" not in fp32_names


def test_sqrt_square_keep_input_dtype():
    """sqrt/square behave like unlisted ops under O1 (the reference keeps
    them off its FP32 lists; bf16 graphs with sqrt-heavy code stay bf16)."""
    for in_dtype in (jnp.float32, jnp.bfloat16):
        with amp.autocast(dtype=jnp.bfloat16):
            assert jnp.sqrt(jnp.abs(_x(in_dtype))).dtype == in_dtype
            assert jnp.square(_x(in_dtype)).dtype == in_dtype


# ---------------------------------------------------------------------------
# promotion (reference test_promotion.py)
# ---------------------------------------------------------------------------

PROMOTE_BINARY_CALLS = [
    ("add", jnp.add),
    ("multiply", jnp.multiply),
    ("subtract", jnp.subtract),
    ("maximum", jnp.maximum),
    ("fmod", jnp.fmod),
    ("copysign", jnp.copysign),
]


@pytest.mark.parametrize("name,fn", PROMOTE_BINARY_CALLS,
                         ids=[n for n, _ in PROMOTE_BINARY_CALLS])
def test_binary_promotes_to_widest(name, fn):
    """Out-of-place binary ops match the widest input type (the behavior
    the reference's promote wrapper creates; JAX provides it natively —
    these tests pin that the native behavior keeps matching)."""
    hi = _x(jnp.float32)
    lo = _x(jnp.bfloat16, 1)
    with amp.autocast(dtype=jnp.bfloat16):
        assert fn(hi, lo).dtype == jnp.float32, name
        assert fn(lo, hi).dtype == jnp.float32, name
        assert fn(lo, lo).dtype == jnp.bfloat16, name


def test_cat_matches_widest():
    ys = [_x(jnp.bfloat16, k) for k in range(5)]
    with amp.autocast(dtype=jnp.bfloat16):
        out = jnp.concatenate(ys + [_x(jnp.float32, 9)])
        assert out.dtype == jnp.float32
        out = jnp.concatenate(ys + [_x(jnp.bfloat16, 9)])
        assert out.dtype == jnp.bfloat16


def test_where_promotes_to_widest():
    with amp.autocast(dtype=jnp.bfloat16):
        out = jnp.where(_x(jnp.float32) > 0, _x(jnp.bfloat16, 1),
                        _x(jnp.float32, 2))
    assert out.dtype == jnp.float32


# ---------------------------------------------------------------------------
# cast cache (reference test_cache.py)
# ---------------------------------------------------------------------------

def test_cache_does_not_change_gradients():
    """Reference test_cache's property: training with the cast cache on
    gives the same gradients as with it off, to bf16 tolerance (the
    cache must be a pure memoization of casts, never a stale value).
    The residual difference is the reuse itself: a shared cast node sums
    its two cotangents in bf16 where separate casts sum in fp32 — the
    same accumulate-at-the-cast behavior the reference's cached half
    weights have — so both are compared against the fp32 gradient."""
    w1 = _x(jnp.float32, 1, (H, H))
    w2 = _x(jnp.float32, 2, (H, H))
    x = _x(jnp.float32, 3)

    def loss(w1, w2, cache):
        with amp.autocast(dtype=jnp.bfloat16, cache_casts=cache):
            # w1 used twice: the second use must hit the cache (when on)
            h = jnp.matmul(jnp.matmul(x, w1), w2)
            h = jnp.matmul(h, w1)
            return jnp.sum(h.astype(jnp.float32))

    def loss_fp32(w1, w2):
        h = jnp.matmul(jnp.matmul(x, w1), w2)
        return jnp.sum(jnp.matmul(h, w1))

    g_on = jax.grad(loss, argnums=(0, 1))(w1, w2, True)
    g_off = jax.grad(loss, argnums=(0, 1))(w1, w2, False)
    g_ref = jax.grad(loss_fp32, argnums=(0, 1))(w1, w2)
    for a, b, r in zip(g_on, g_off, g_ref):
        scale = float(jnp.abs(r).max())
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=0.02 * scale)
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(r), atol=0.05 * scale)
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(r), atol=0.05 * scale)


# ---------------------------------------------------------------------------
# RNN cells under the policy (reference test_rnn.py + rnn_compat)
# ---------------------------------------------------------------------------

def _cell_params(gates, key=0):
    ks = jax.random.split(jax.random.PRNGKey(key), 2)
    return {
        "w_ih": jax.random.normal(ks[0], (gates * H, H)) * 0.1,
        "w_hh": jax.random.normal(ks[1], (gates * H, H)) * 0.1,
    }


CELLS = [
    ("rnn_relu", RNNReLUCell, 1, False),
    ("rnn_tanh", RNNTanhCell, 1, False),
    ("lstm", LSTMCell, 4, True),
    ("gru", GRUCell, 3, False),
]


@pytest.mark.parametrize("name,cell,gates,tuple_state", CELLS,
                         ids=[c[0] for c in CELLS])
def test_rnn_cell_is_low_precision(name, cell, gates, tuple_state):
    """The scan cells' gate GEMMs ride the patched ``jnp.einsum``, so an
    fp32 cell under autocast computes (and returns) bf16 — the reference
    rnn_compat behavior without a special case. Gradients stay finite
    and input-dtyped."""
    params = _cell_params(gates)
    x = _x(jnp.float32, 7)
    h0 = jnp.zeros((B, H), jnp.float32)
    state = (h0, h0) if tuple_state else h0

    with amp.autocast(dtype=jnp.bfloat16):
        # two steps: step 1's fp32 zero state promotes the gated update
        # (f*c + i*g) back to fp32 for LSTM/GRU; in steady state the
        # carry is the previous bf16 output and the cell runs bf16
        # end-to-end — assert THAT, the dtype a scan actually carries
        out = cell(params, x, state)
        out = cell(params, x,
                   jax.tree_util.tree_map(
                       lambda t: t.astype(jnp.bfloat16), out))
    y = out[0] if tuple_state else out
    assert y.dtype == jnp.bfloat16, (name, y.dtype)

    def loss(params, x):
        with amp.autocast(dtype=jnp.bfloat16):
            o = cell(params, x, state)
        o = o[0] if tuple_state else o
        return jnp.sum(o.astype(jnp.float32))

    gp, gx = jax.grad(loss, argnums=(0, 1))(params, x)
    assert gx.dtype == x.dtype
    assert all(bool(jnp.isfinite(g).all())
               for g in jax.tree_util.tree_leaves(gp))


def test_rnn_scan_traces_under_policy():
    """A full lax.scan over an LSTM cell inside autocast: the policy must
    survive tracing (patched fns are looked up at trace time)."""
    params = _cell_params(4, key=1)
    xs = jax.random.normal(jax.random.PRNGKey(8), (6, B, H))
    h0 = jnp.zeros((B, H), jnp.float32)

    @jax.jit
    def run(params, xs):
        with amp.autocast(dtype=jnp.bfloat16):
            # the carry must be dtype-stable across scan ticks: start it
            # in the compute dtype the cell emits under the policy
            c0 = (h0.astype(jnp.bfloat16), h0.astype(jnp.bfloat16))

            def step(carry, x):
                h, c = LSTMCell(params, x, carry)
                return (h, c), h

            _, ys = jax.lax.scan(step, c0, xs)
            return ys

    ys = run(params, xs)
    assert ys.dtype == jnp.bfloat16
    assert bool(jnp.isfinite(ys.astype(jnp.float32)).all())
