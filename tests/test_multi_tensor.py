"""Tests for apex_tpu.ops.multi_tensor — mirrors
``tests/L0/run_amp/test_multi_tensor_scale.py`` etc.: op-vs-eager-math plus
overflow-flag cases."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops import (
    multi_tensor_axpby,
    multi_tensor_l2norm,
    multi_tensor_scale,
    multi_tensor_unscale_l2norm,
    update_scale_hysteresis,
)


def _tree(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "a": jnp.asarray(rng.randn(4, 5), jnp.float32),
        "b": [jnp.asarray(rng.randn(37), jnp.float32), jnp.asarray(rng.randn(2, 3, 4), jnp.float32)],
    }


def test_scale_matches_eager():
    t = _tree()
    out, found = jax.jit(lambda x: multi_tensor_scale(x, 0.125))(t)
    for o, i in zip(jax.tree_util.tree_leaves(out), jax.tree_util.tree_leaves(t)):
        np.testing.assert_allclose(o, np.asarray(i) * 0.125, rtol=1e-6)
    assert not bool(found)


@pytest.mark.parametrize("bad", [np.inf, -np.inf, np.nan])
def test_scale_flags_overflow(bad):
    t = _tree()
    t["a"] = t["a"].at[1, 2].set(bad)
    _, found = multi_tensor_scale(t, 1.0)
    assert bool(found)


def test_scale_cross_dtype():
    t = jax.tree_util.tree_map(lambda x: x.astype(jnp.bfloat16), _tree())
    out, _ = multi_tensor_scale(t, 2.0, out_dtype=jnp.float32)
    assert all(o.dtype == jnp.float32 for o in jax.tree_util.tree_leaves(out))


def test_axpby():
    x, y = _tree(0), _tree(1)
    out, found = multi_tensor_axpby(2.0, -3.0, x, y)
    for o, a, b in zip(*(jax.tree_util.tree_leaves(t) for t in (out, x, y))):
        np.testing.assert_allclose(o, 2.0 * np.asarray(a) - 3.0 * np.asarray(b), rtol=1e-6)
    assert not bool(found)


def test_l2norm_global_and_per_tensor():
    t = _tree()
    leaves = jax.tree_util.tree_leaves(t)
    gnorm, per = multi_tensor_l2norm(t, per_tensor=True)
    expect = np.sqrt(sum(float(np.sum(np.asarray(l) ** 2)) for l in leaves))
    np.testing.assert_allclose(float(gnorm), expect, rtol=1e-6)
    assert per.shape == (len(leaves),)
    for p, l in zip(np.asarray(per), leaves):
        np.testing.assert_allclose(p, np.linalg.norm(np.asarray(l).ravel()), rtol=1e-5)


def test_unscale_l2norm_flags_inf():
    t = _tree()
    t["a"] = t["a"].at[0, 0].set(np.inf)
    gnorm, _, found = multi_tensor_unscale_l2norm(t, 0.5)
    assert bool(found)


class TestUpdateScaleHysteresis:
    def run(self, scale, growth, hyst, found, **kw):
        s, g, h = update_scale_hysteresis(
            jnp.float32(scale), jnp.int32(growth), jnp.int32(hyst),
            jnp.asarray(found), **kw
        )
        return float(s), int(g), int(h)

    def test_clean_step_grows_at_interval(self):
        s, g, h = self.run(1024.0, 1999, 2, False, growth_interval=2000, hysteresis=2)
        assert s == 2048.0 and g == 0 and h == 2

    def test_clean_step_increments(self):
        s, g, h = self.run(1024.0, 10, 2, False, growth_interval=2000, hysteresis=2)
        assert s == 1024.0 and g == 11 and h == 2

    def test_overflow_consumes_hysteresis_before_backoff(self):
        # hysteresis=2: first overflow only decrements
        s, g, h = self.run(1024.0, 500, 2, True, hysteresis=2)
        assert s == 1024.0 and g == 0 and h == 1
        # second overflow backs off
        s, g, h = self.run(1024.0, 0, 1, True, hysteresis=2)
        assert s == 512.0 and g == 0 and h == 0

    def test_growth_clamps_to_finite(self):
        big = float(np.float32(3.0e38))
        s, _, _ = self.run(big, 1999, 1, False, growth_interval=2000)
        assert s == big  # growing would overflow fp32 -> unchanged
