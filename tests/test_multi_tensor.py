"""Tests for apex_tpu.ops.multi_tensor — mirrors
``tests/L0/run_amp/test_multi_tensor_scale.py`` etc.: op-vs-eager-math plus
overflow-flag cases."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops import (
    multi_tensor_axpby,
    multi_tensor_l2norm,
    multi_tensor_scale,
    multi_tensor_unscale_l2norm,
    update_scale_hysteresis,
)


def _tree(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "a": jnp.asarray(rng.randn(4, 5), jnp.float32),
        "b": [jnp.asarray(rng.randn(37), jnp.float32), jnp.asarray(rng.randn(2, 3, 4), jnp.float32)],
    }


def test_scale_matches_eager():
    t = _tree()
    out, found = jax.jit(lambda x: multi_tensor_scale(x, 0.125))(t)
    for o, i in zip(jax.tree_util.tree_leaves(out), jax.tree_util.tree_leaves(t)):
        np.testing.assert_allclose(o, np.asarray(i) * 0.125, rtol=1e-6)
    assert not bool(found)


@pytest.mark.parametrize("bad", [np.inf, -np.inf, np.nan])
def test_scale_flags_overflow(bad):
    t = _tree()
    t["a"] = t["a"].at[1, 2].set(bad)
    _, found = multi_tensor_scale(t, 1.0)
    assert bool(found)


def test_scale_cross_dtype():
    t = jax.tree_util.tree_map(lambda x: x.astype(jnp.bfloat16), _tree())
    out, _ = multi_tensor_scale(t, 2.0, out_dtype=jnp.float32)
    assert all(o.dtype == jnp.float32 for o in jax.tree_util.tree_leaves(out))


def test_axpby():
    x, y = _tree(0), _tree(1)
    out, found = multi_tensor_axpby(2.0, -3.0, x, y)
    for o, a, b in zip(*(jax.tree_util.tree_leaves(t) for t in (out, x, y))):
        np.testing.assert_allclose(o, 2.0 * np.asarray(a) - 3.0 * np.asarray(b), rtol=1e-6)
    assert not bool(found)


def test_l2norm_global_and_per_tensor():
    t = _tree()
    leaves = jax.tree_util.tree_leaves(t)
    gnorm, per = multi_tensor_l2norm(t, per_tensor=True)
    expect = np.sqrt(sum(float(np.sum(np.asarray(l) ** 2)) for l in leaves))
    np.testing.assert_allclose(float(gnorm), expect, rtol=1e-6)
    assert per.shape == (len(leaves),)
    for p, l in zip(np.asarray(per), leaves):
        np.testing.assert_allclose(p, np.linalg.norm(np.asarray(l).ravel()), rtol=1e-5)


def test_unscale_l2norm_flags_inf():
    t = _tree()
    t["a"] = t["a"].at[0, 0].set(np.inf)
    gnorm, _, found = multi_tensor_unscale_l2norm(t, 0.5)
    assert bool(found)


class TestUpdateScaleHysteresis:
    def run(self, scale, growth, hyst, found, **kw):
        s, g, h = update_scale_hysteresis(
            jnp.float32(scale), jnp.int32(growth), jnp.int32(hyst),
            jnp.asarray(found), **kw
        )
        return float(s), int(g), int(h)

    def test_clean_step_grows_at_interval(self):
        s, g, h = self.run(1024.0, 1999, 2, False, growth_interval=2000, hysteresis=2)
        assert s == 2048.0 and g == 0 and h == 2

    def test_clean_step_increments(self):
        s, g, h = self.run(1024.0, 10, 2, False, growth_interval=2000, hysteresis=2)
        assert s == 1024.0 and g == 11 and h == 2

    def test_overflow_consumes_hysteresis_before_backoff(self):
        # hysteresis=2: first overflow only decrements
        s, g, h = self.run(1024.0, 500, 2, True, hysteresis=2)
        assert s == 1024.0 and g == 0 and h == 1
        # second overflow backs off
        s, g, h = self.run(1024.0, 0, 1, True, hysteresis=2)
        assert s == 512.0 and g == 0 and h == 0

    def test_growth_clamps_to_finite(self):
        big = float(np.float32(3.0e38))
        s, _, _ = self.run(big, 1999, 1, False, growth_interval=2000)
        assert s == big  # growing would overflow fp32 -> unchanged


# ---------------------------------------------------------------------------
# flat-buffer ops + the packing bookkeeping behind them
# ---------------------------------------------------------------------------
from apex_tpu.multi_tensor_apply import (  # noqa: E402
    MultiTensorApply,
    PackSpec,
    ROW,
)
from apex_tpu.ops import (  # noqa: E402
    multi_tensor_axpby_flat,
    multi_tensor_l2norm_flat,
    multi_tensor_scale_flat,
)


class TestPackSpec:
    def test_roundtrip(self):
        t = _tree()
        spec = PackSpec(t)
        flat = spec.pack(t)
        assert flat.shape == (spec.total,)
        assert spec.total % spec.chunk_size == 0
        out = spec.unpack(flat)
        for a, b in zip(jax.tree_util.tree_leaves(out), jax.tree_util.tree_leaves(t)):
            assert a.dtype == b.dtype and a.shape == b.shape
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_padding_is_zero_and_rows_leaf_aligned(self):
        t = _tree()
        spec = PackSpec(t)
        flat = np.asarray(spec.pack(t))
        mask = spec.valid_mask()
        assert not flat[~mask].any()  # padding strictly zero
        # every ROW-sized row belongs to at most one leaf
        ids = spec.row_leaf_ids()
        assert ids.shape == (spec.n_rows,)
        for i, (o, n) in enumerate(zip(spec.offsets, spec.sizes)):
            assert o % ROW == 0
            assert (ids[o // ROW] == i)

    def test_mixed_dtype_falls_back_to_f32(self):
        t = {"a": jnp.ones((4,), jnp.bfloat16), "b": jnp.ones((4,), jnp.float32)}
        spec = PackSpec(t)
        assert spec.pack(t).dtype == jnp.float32
        out = spec.unpack(spec.pack(t))
        assert out["a"].dtype == jnp.bfloat16

    def test_shape_mismatch_raises(self):
        spec = PackSpec(_tree())
        with pytest.raises(ValueError):
            spec.pack({"a": jnp.zeros((3, 3))})

    def test_spec_hashable_static(self):
        s1, s2 = PackSpec(_tree()), PackSpec(_tree())
        assert s1 == s2 and hash(s1) == hash(s2)


@pytest.mark.parametrize("interpret", [False, True])
@pytest.mark.parametrize("n", [ROW * 3, ROW * 3 - 5])  # aligned + ragged
def test_flat_scale(n, interpret):
    x = jnp.asarray(np.random.RandomState(0).randn(n), jnp.float32)
    out, found = multi_tensor_scale_flat(x, 0.125, interpret=interpret)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) * 0.125, rtol=1e-6)
    assert out.shape == x.shape and not bool(found)
    bad = x.at[7].set(np.inf)
    _, found = multi_tensor_scale_flat(bad, 1.0, interpret=interpret)
    assert bool(found)


@pytest.mark.parametrize("interpret", [False, True])
def test_flat_scale_cross_dtype(interpret):
    x = jnp.asarray(np.random.RandomState(0).randn(ROW), jnp.bfloat16)
    out, _ = multi_tensor_scale_flat(
        x, 2.0, out_dtype=jnp.float32, interpret=interpret)
    assert out.dtype == jnp.float32


@pytest.mark.parametrize("interpret", [False, True])
def test_flat_axpby(interpret):
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(2 * ROW + 3), jnp.float32)
    y = jnp.asarray(rng.randn(2 * ROW + 3), jnp.float32)
    out, found = multi_tensor_axpby_flat(2.0, -3.0, x, y, interpret=interpret)
    np.testing.assert_allclose(
        np.asarray(out), 2.0 * np.asarray(x) - 3.0 * np.asarray(y), rtol=1e-6)
    assert not bool(found)


@pytest.mark.parametrize("interpret", [False, True])
def test_flat_l2norm(interpret):
    x = jnp.asarray(np.random.RandomState(2).randn(3 * ROW), jnp.float32)
    norm, row_sq = multi_tensor_l2norm_flat(x, interpret=interpret)
    np.testing.assert_allclose(
        float(norm), np.linalg.norm(np.asarray(x)), rtol=1e-5)
    assert row_sq.shape == (3,)


def test_flat_ops_pad_awkward_lengths_to_full_chunks():
    """A buffer whose row count has no divisor near the chunk (e.g. a
    prime row count) must be chunk-padded, not silently degraded to
    1-row blocks / an n_rows-step grid."""
    from apex_tpu.ops.packed_optimizer import _block_rows, _pad_to_rows

    x = jnp.ones((13 * ROW - 5,), jnp.float32)  # 13 rows: prime count
    padded, n = _pad_to_rows(x, chunk_size=4 * ROW)
    assert n == 13 * ROW - 5
    assert padded.shape[0] == 16 * ROW  # next chunk multiple
    assert _block_rows(16, 4 * ROW) == 4  # full blocks, not 1-row fallback
    # and end-to-end correctness through the public op (kernel body)
    v = jnp.asarray(np.random.RandomState(7).randn(13 * ROW - 5), jnp.float32)
    out, found = multi_tensor_scale_flat(
        v, 0.5, chunk_size=4 * ROW, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(v) * 0.5,
                               rtol=1e-6)
    assert out.shape == v.shape and not bool(found)
    norm, row_sq = multi_tensor_l2norm_flat(
        v, chunk_size=4 * ROW, interpret=True)
    np.testing.assert_allclose(float(norm), np.linalg.norm(np.asarray(v)),
                               rtol=1e-5)
    assert row_sq.shape == (13,)  # padding rows not reported


def test_chunk_size_is_honored():
    """Different chunk sizes tile the same buffer to identical results —
    and the grid actually changes (the kernel runs per chunk)."""
    x = jnp.asarray(np.random.RandomState(3).randn(8 * ROW), jnp.float32)
    outs = [
        multi_tensor_scale_flat(x, 0.5, chunk_size=c, interpret=True)[0]
        for c in (ROW, 2 * ROW, 8 * ROW, 2048 * 32)
    ]
    for o in outs[1:]:
        np.testing.assert_array_equal(np.asarray(outs[0]), np.asarray(o))


def test_applier_forwards_chunk_size():
    """MultiTensorApply(chunk_size=...) injects its chunk size into flat
    ops (the reference contract, previously accepted-and-ignored)."""
    seen = {}

    def spy_op(x, *, chunk_size=None):
        seen["chunk"] = chunk_size
        return x

    spy_op.accepts_chunk_size = True
    applier = MultiTensorApply(chunk_size=4 * ROW)
    applier(spy_op, jnp.zeros((8,)))
    assert seen["chunk"] == 4 * ROW

    # pytree ops (no accepts_chunk_size) are called untouched
    out, found = applier(multi_tensor_scale, _tree(), 2.0)
    assert not bool(found)

    # end-to-end with a real flat op
    x = jnp.asarray(np.random.RandomState(4).randn(8 * ROW), jnp.float32)
    out, _ = applier(multi_tensor_scale_flat, x, 0.25)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) * 0.25, rtol=1e-6)


def test_flat_clip_grad_norm_matches_tree():
    from apex_tpu.contrib.clip_grad import clip_grad_norm_, clip_grad_norm_flat

    t = _tree()
    spec = PackSpec(t)
    flat = spec.pack(t)
    clipped_t, norm_t = clip_grad_norm_(t, 0.5)
    clipped_f, norm_f = clip_grad_norm_flat(flat, 0.5)
    np.testing.assert_allclose(float(norm_f), float(norm_t), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(spec.unpack(clipped_f)),
                    jax.tree_util.tree_leaves(clipped_t)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                                   atol=1e-7)
