"""fp8 (e4m3) fused dense + the amax-reduction group.

Reference parity surface: ``apex/transformer/parallel_state.py:280-292``
builds amax-reduction groups over the tp x dp ranks when
``use_fp8_=True`` and exposes ``get_amax_reduction_group`` (``:472``);
here the group is the (data, tensor) axis pair and the all-reduce is a
pmax. The GEMM side is the TE-style delayed-scaling recipe.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.fused_dense import (
    FP8_E4M3_MAX,
    fp8_fused_dense,
    fused_dense,
    init_fp8_dense_state,
    quantize_e4m3,
)
from apex_tpu.transformer import parallel_state


def test_amax_reduction_group_api():
    parallel_state.initialize_model_parallel(2, 2, use_fp8_=True)
    try:
        assert parallel_state.get_amax_reduction_group() == (
            parallel_state.DATA_AXIS, parallel_state.TENSOR_AXIS,
        )
    finally:
        parallel_state.destroy_model_parallel()
    # without fp8: the reference asserts; we raise
    parallel_state.initialize_model_parallel(2, 2)
    try:
        with pytest.raises(RuntimeError, match="amax reduction group"):
            parallel_state.get_amax_reduction_group()
    finally:
        parallel_state.destroy_model_parallel()


def test_reduce_amax_is_pmax_over_group():
    parallel_state.initialize_model_parallel(2, 2, use_fp8_=True)
    try:
        mesh = parallel_state.get_mesh()

        def local(x):
            amax = jnp.max(jnp.abs(x))
            return parallel_state.reduce_amax(amax)[None]

        x = jnp.arange(8.0).reshape(2, 2, 2) - 3.0
        out = jax.jit(jax.shard_map(
            local, mesh=mesh,
            in_specs=(P("pipeline", "data", "tensor"),),
            out_specs=P("pipeline"), check_vma=False,
        ))(x)
        # pmax over (data, tensor) only: each PIPELINE slice keeps its own
        # max (slice 0 holds -3..0 -> 3; slice 1 holds 1..4 -> 4)
        np.testing.assert_allclose(np.asarray(out), [3.0, 4.0])
    finally:
        parallel_state.destroy_model_parallel()


def test_quantize_e4m3_saturates_and_rounds():
    x = jnp.array([0.0, 1.0, -1.0, 1000.0, -1000.0], jnp.float32)
    q = quantize_e4m3(x, jnp.float32(1.0))
    assert q.dtype == jnp.float8_e4m3fn
    qf = q.astype(jnp.float32)
    np.testing.assert_allclose(qf[:3], [0.0, 1.0, -1.0])
    np.testing.assert_allclose(qf[3:], [FP8_E4M3_MAX, -FP8_E4M3_MAX])


def test_fp8_dense_matches_fp32_within_e4m3_tolerance():
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    x = jax.random.normal(k1, (32, 64), jnp.float32)
    w = jax.random.normal(k2, (48, 64), jnp.float32) * 0.1
    b = jax.random.normal(k3, (48,), jnp.float32) * 0.1
    state = init_fp8_dense_state()
    # one warmup call records amaxes so scales are calibrated
    _, state = fp8_fused_dense(x, w, b, state)
    y8, state = fp8_fused_dense(x, w, b, state)
    y32 = fused_dense(x, w, b)
    # e4m3: 3 mantissa bits => ~6% per-element rel error, reduced by the
    # K=64 accumulation; compare against the output RMS
    rms = float(jnp.sqrt(jnp.mean(y32 ** 2)))
    err = float(jnp.abs(y8 - y32).max())
    assert err < 0.15 * rms, (err, rms)


def test_fp8_delayed_scaling_state_updates():
    x = jnp.full((4, 8), 2.0)
    w = jnp.full((4, 8), 0.5)
    state = init_fp8_dense_state(history_len=4)
    _, s1 = fp8_fused_dense(x, w, None, state)
    # history rolled: newest amax at slot 0
    np.testing.assert_allclose(float(s1.x.amax_history[0]), 2.0)
    np.testing.assert_allclose(float(s1.w.amax_history[0]), 0.5)
    # delayed: the NEXT scale derives from the updated history max
    np.testing.assert_allclose(float(s1.x.scale), FP8_E4M3_MAX / 2.0)
    np.testing.assert_allclose(float(s1.w.scale), FP8_E4M3_MAX / 0.5)
    # a smaller step keeps the history max (window semantics)
    _, s2 = fp8_fused_dense(x * 0.1, w, None, s1)
    np.testing.assert_allclose(float(s2.x.scale), FP8_E4M3_MAX / 2.0)
    # after the big value ages out of the window, the scale tightens
    s = s2
    for _ in range(4):
        _, s = fp8_fused_dense(x * 0.1, w, None, s)
    np.testing.assert_allclose(float(s.x.scale), FP8_E4M3_MAX / 0.2)


def test_fp8_dense_grads_flow_high_precision():
    k1, k2 = jax.random.split(jax.random.PRNGKey(1))
    x = jax.random.normal(k1, (16, 32), jnp.float32)
    w = jax.random.normal(k2, (8, 32), jnp.float32) * 0.1
    state = init_fp8_dense_state()
    _, state = fp8_fused_dense(x, w, None, state)

    def loss8(x, w):
        y, _ = fp8_fused_dense(x, w, None, state)
        return jnp.sum(y ** 2)

    def loss32(x, w):
        return jnp.sum(fused_dense(x, w, jnp.zeros((8,))) ** 2)

    g8 = jax.grad(loss8, argnums=(0, 1))(x, w)
    g32 = jax.grad(loss32, argnums=(0, 1))(x, w)
    for a, b in zip(g8, g32):
        assert jnp.all(jnp.isfinite(a))
        # bwd runs in fp32 on the exact x/w; the only divergence is the
        # quantized forward feeding dy magnitudes — expect close-not-equal
        rel = float(jnp.abs(a - b).max() / jnp.abs(b).max())
        assert rel < 0.1, rel


def test_fp8_amax_reduction_inside_shard_map():
    parallel_state.initialize_model_parallel(1, 1, use_fp8_=True)
    parallel_state.destroy_model_parallel()
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("data", "tensor"))
    x = jnp.arange(32.0).reshape(8, 4)  # max 31 on the last data shard
    w = jnp.ones((4, 4))

    def local(x, w):
        state = init_fp8_dense_state(history_len=2)
        _, new_state = fp8_fused_dense(
            x, w, None, state, amax_reduction_axes=("data", "tensor"))
        return new_state.x.amax_history[0]

    out = jax.jit(jax.shard_map(
        local, mesh=mesh, in_specs=(P("data", None), P()),
        out_specs=P(), check_vma=False,
    ))(x, w)
    # every rank must report the GLOBAL amax
    np.testing.assert_allclose(float(out), 31.0)


def test_fp8_qgrad_full_recipe():
    """The full TE recipe: e5m2-quantized gradients with the grad amax
    surfacing as the carrier's cotangent, folded back by
    record_grad_amax (delayed gradient scaling)."""
    from apex_tpu.fused_dense import (
        FP8_E5M2_MAX,
        fp8_fused_dense_qgrad,
        record_grad_amax,
    )

    k1, k2 = jax.random.split(jax.random.PRNGKey(2))
    x = jax.random.normal(k1, (16, 32), jnp.float32)
    w = jax.random.normal(k2, (8, 32), jnp.float32) * 0.1
    state = init_fp8_dense_state(with_grad_meta=True)
    # calibrate fwd scales
    _, state = fp8_fused_dense_qgrad(x, w, None, state, jnp.float32(0.0))

    def loss(w, carrier):
        y, _ = fp8_fused_dense_qgrad(x, w, None, state, carrier)
        return jnp.sum(y ** 2)

    dw, damax = jax.grad(loss, argnums=(0, 1))(w, jnp.float32(0.0))
    assert jnp.all(jnp.isfinite(dw))
    # the carrier cotangent IS max|dY| = max|2y|
    y8, _ = fp8_fused_dense_qgrad(x, w, None, state, jnp.float32(0.0))
    expect = float(jnp.max(jnp.abs(2.0 * y8)))
    np.testing.assert_allclose(float(damax), expect, rtol=1e-6)

    # folding it in rolls the g history and sets the e5m2 delayed scale
    state2 = record_grad_amax(state, damax)
    np.testing.assert_allclose(float(state2.g.amax_history[0]),
                               float(damax), rtol=1e-6)
    np.testing.assert_allclose(float(state2.g.scale),
                               FP8_E5M2_MAX / float(damax), rtol=1e-6)

    # dw under e5m2-quantized dY stays close to the unquantized-bwd path
    def loss_plain(w):
        y, _ = fp8_fused_dense(x, w, None, state)
        return jnp.sum(y ** 2)

    dw_plain = jax.grad(loss_plain)(w)
    rel = float(jnp.abs(dw - dw_plain).max() / jnp.abs(dw_plain).max())
    assert rel < 0.1, rel


def test_fp8_qgrad_requires_grad_meta_and_e5m2_saturates():
    from apex_tpu.fused_dense import (
        FP8_E5M2_MAX,
        fp8_fused_dense_qgrad,
        quantize_e5m2,
    )

    state = init_fp8_dense_state()  # no grad meta
    with pytest.raises(ValueError, match="grad"):
        fp8_fused_dense_qgrad(
            jnp.ones((4, 8)), jnp.ones((2, 8)), None, state,
            jnp.float32(0.0))
    q = quantize_e5m2(jnp.array([1e9, -1e9, 3.0]), jnp.float32(1.0))
    assert q.dtype == jnp.float8_e5m2
    np.testing.assert_allclose(
        q.astype(jnp.float32)[:2], [FP8_E5M2_MAX, -FP8_E5M2_MAX])


def test_fp8_gpt_end_to_end_single_device():
    """The round-5 wiring (VERDICT r4 #3): every projection GEMM of the
    standalone GPT on the e4m3/e5m2 path, state threaded through the
    layer scan, grad amaxes recorded via the carriers. Loss must track
    the exact path to e4m3 noise and the delayed-scaling state must
    calibrate."""
    from apex_tpu.transformer.testing import (
        GPTConfig, gpt_loss, init_gpt_fp8_carriers, init_gpt_fp8_states,
        init_gpt_params, record_gpt_grad_amaxes,
    )

    import dataclasses

    cfg = GPTConfig(
        num_layers=2, hidden_size=32, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=32, hidden_dropout=0.0,
        attention_dropout=0.0, fp8=True,
    )
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    states = init_gpt_fp8_states(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 128)
    labels = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, 128)
    # exact reference: same model, fp8 OFF (the flag and the states must
    # agree — gpt_hidden validates the pairing)
    ref = float(gpt_loss(
        dataclasses.replace(cfg, fp8=False), params, tokens, labels))

    with pytest.raises(ValueError, match="must agree"):
        gpt_loss(cfg, params, tokens, labels)  # flag without states

    def loss_fn(p, c, states):
        return gpt_loss(cfg, p, tokens, labels, fp8_states=states,
                        fp8_carriers=c)

    for _ in range(2):
        carriers = init_gpt_fp8_carriers(cfg)
        (loss, new_states), (grads, amaxes) = jax.value_and_grad(
            loss_fn, argnums=(0, 1), has_aux=True)(
                params, carriers, states)
        states = record_gpt_grad_amaxes(cfg, new_states, amaxes)
    assert abs(float(loss) - ref) / ref < 0.1, (float(loss), ref)
    # histories populated for all four GEMMs; g scales derived
    for name in ("qkv", "proj", "fc1", "fc2"):
        assert float(states[name].x.amax_history[0, 0]) > 0, name
        assert float(states[name].w.amax_history[0, 0]) > 0, name
        assert float(states[name].g.amax_history[0, 0]) > 0, name
        assert float(states[name].g.scale[0]) != 1.0, name
    # gradients flow to the params through the quantized GEMMs
    gnorm = jnp.linalg.norm(grads["layers"]["qkv_w"].reshape(-1))
    assert float(gnorm) > 0


def test_fp8_gpt_tensor_parallel_amax_synced():
    """TP=8 fp8 GPT step: the column/row projections run fp8 per-shard
    with amax group-reduced over (data, tensor) — every rank derives the
    same scale (the reference amax group's purpose,
    ``parallel_state.py:280-292``)."""
    from apex_tpu.transformer.testing import (
        GPTConfig, gpt_loss, gpt_partition_specs, init_gpt_fp8_carriers,
        init_gpt_fp8_states, init_gpt_params, record_gpt_grad_amaxes,
    )

    parallel_state.initialize_model_parallel(8, 1, use_fp8_=True)
    try:
        mesh = parallel_state.get_mesh()
        ta = parallel_state.TENSOR_AXIS
        da = parallel_state.DATA_AXIS
        cfg = GPTConfig(
            num_layers=2, hidden_size=32, num_attention_heads=8,
            vocab_size=128, max_position_embeddings=32,
            hidden_dropout=0.0, attention_dropout=0.0,
            tensor_model_parallel_size=8, fp8=True,
            fp8_amax_reduction_axes=(da, ta),
        )
        params = init_gpt_params(cfg, jax.random.PRNGKey(3))
        states = init_gpt_fp8_states(cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 16), 0, 128)
        labels = jax.random.randint(jax.random.PRNGKey(5), (2, 16), 0, 128)
        specs = gpt_partition_specs(cfg)

        def local(params, states, tokens, labels):
            carriers = init_gpt_fp8_carriers(cfg)

            def loss_fn(p, c):
                return gpt_loss(cfg, p, tokens, labels, axis_name=ta,
                                fp8_states=states, fp8_carriers=c)

            (loss, new_states), (_, amaxes) = jax.value_and_grad(
                loss_fn, argnums=(0, 1), has_aux=True)(params, carriers)
            new_states = record_gpt_grad_amaxes(cfg, new_states, amaxes)
            probe = jnp.stack([
                new_states["qkv"].x.scale[0],
                new_states["fc2"].g.amax_history[0, 0],
            ])
            return loss, jax.lax.all_gather(probe, (da, ta)).reshape(-1, 2)

        st_specs = jax.tree_util.tree_map(lambda _: P(), states)
        loss, probes = jax.jit(jax.shard_map(
            local, mesh=mesh,
            in_specs=(specs, st_specs, P(), P()),
            out_specs=(P(), P()), check_vma=False,
        ))(params, states, tokens, labels)
        assert np.isfinite(float(loss))
        probes = np.asarray(probes)
        assert np.all(probes == probes[0:1]), probes
        assert probes[0, 0] != 1.0  # scale actually derived
        assert probes[0, 1] > 0  # grad amax recorded
    finally:
        parallel_state.destroy_model_parallel()
