"""Flash attention kernel vs materialised-score reference (fwd + grads),
plus GPT/BERT model equivalence between the flash and XLA attention paths.

Mirrors the reference's contrib test style (``apex/contrib/test/fmha/``,
``apex/contrib/test/multihead_attn/``): kernel-vs-reference tolerance
asserts including backward.
"""
import dataclasses

import jax
import numpy as np
import jax.numpy as jnp
import pytest

from apex_tpu.ops.flash_attention import (
    flash_attention,
    mha_reference,
)


def _qkv(key, b=2, n=2, s=64, d=32, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    mk = lambda k: jax.random.normal(k, (b, n, s, d), dtype)
    return mk(ks[0]), mk(ks[1]), mk(ks[2])


@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_matches_reference(causal):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    o = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    ref = mha_reference(q, k, v, causal=causal)
    assert jnp.abs(o - ref).max() < 2e-5


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_reference(causal):
    q, k, v = _qkv(jax.random.PRNGKey(1))

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    gf = jax.grad(
        loss(lambda q, k, v: flash_attention(
            q, k, v, causal=causal, block_q=16, block_k=16
        )),
        argnums=(0, 1, 2),
    )(q, k, v)
    gr = jax.grad(
        loss(lambda q, k, v: mha_reference(q, k, v, causal=causal)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(gf, gr):
        assert jnp.abs(a - b).max() < 5e-4


def test_flash_key_padding_mask():
    key = jax.random.PRNGKey(2)
    q, k, v = _qkv(key)
    mask = jax.random.bernoulli(jax.random.fold_in(key, 9), 0.75, (2, 64))
    o = flash_attention(q, k, v, kv_mask=mask, block_q=16, block_k=16)
    ref = mha_reference(q, k, v, kv_mask=mask)
    assert jnp.abs(o - ref).max() < 2e-5
    gf = jax.grad(
        lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, kv_mask=mask, block_q=16, block_k=16)
            ** 2
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    gr = jax.grad(
        lambda q, k, v: jnp.sum(mha_reference(q, k, v, kv_mask=mask) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(gf, gr):
        assert jnp.abs(a - b).max() < 5e-4


def test_flash_uneven_blocks():
    # seq not a multiple of the requested block: block shrinks to divide
    q, k, v = _qkv(jax.random.PRNGKey(3), s=48)
    o = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
    ref = mha_reference(q, k, v, causal=True)
    assert jnp.abs(o - ref).max() < 2e-5


def test_flash_rectangular_qk():
    key = jax.random.PRNGKey(4)
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (2, 2, 32, 32))
    k = jax.random.normal(ks[1], (2, 2, 64, 32))
    v = jax.random.normal(ks[2], (2, 2, 64, 32))
    o = flash_attention(q, k, v, block_q=16, block_k=16)
    ref = mha_reference(q, k, v)
    assert jnp.abs(o - ref).max() < 2e-5


def test_gpt_flash_matches_xla_path():
    """Model-level: forward+grads identical between flash and XLA scores."""
    from apex_tpu.transformer.testing import (
        GPTConfig,
        gpt_loss,
        init_gpt_params,
    )

    base = GPTConfig(
        num_layers=2,
        hidden_size=64,
        num_attention_heads=2,
        vocab_size=128,
        max_position_embeddings=32,
        hidden_dropout=0.0,
        attention_dropout=0.0,
    )
    params = init_gpt_params(base, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 128)
    labels = jnp.roll(tokens, -1, axis=1)

    def run(use_flash):
        cfg = dataclasses.replace(base, use_flash_attention=use_flash)
        return jax.value_and_grad(
            lambda p: gpt_loss(cfg, p, tokens, labels)
        )(params)

    loss_f, grads_f = run(True)
    loss_x, grads_x = run(False)
    assert jnp.abs(loss_f - loss_x) < 1e-5
    flat_f = jax.tree_util.tree_leaves(grads_f)
    flat_x = jax.tree_util.tree_leaves(grads_x)
    for a, b in zip(flat_f, flat_x):
        assert jnp.abs(a - b).max() < 1e-4


def test_bert_flash_matches_xla_path():
    """BERT padding-mask path: flash consumes the [b,1,1,s] key-padding
    mask; results match the materialised-mask XLA path."""
    from apex_tpu.transformer.testing import GPTConfig
    from apex_tpu.transformer.testing.standalone_transformer_lm import (
        bert_forward,
        init_gpt_params,
    )

    base = GPTConfig(
        num_layers=2,
        hidden_size=64,
        num_attention_heads=2,
        vocab_size=128,
        max_position_embeddings=32,
        hidden_dropout=0.0,
        attention_dropout=0.0,
    )
    params = init_gpt_params(base, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 128)
    padding = jnp.concatenate(
        [jnp.ones((2, 24), jnp.int32), jnp.zeros((2, 8), jnp.int32)], axis=1
    )

    def run(use_flash):
        cfg = dataclasses.replace(base, use_flash_attention=use_flash)
        logits, _ = bert_forward(cfg, params, tokens, padding)
        return logits

    lf = run(True)
    lx = run(False)
    # compare only non-padded query positions (padded queries attend to
    # everything in both paths but their logits are irrelevant)
    assert jnp.abs(lf[:, :24] - lx[:, :24]).max() < 1e-4


# ---------------------------------------------------------------------- new
# in-kernel dropout + varlen (cu_seqlens)


def test_flash_dropout_matches_reference_mask():
    """The kernel's hash dropout must equal mha_reference's materialised
    mask elementwise (same counters), at any block size."""
    from apex_tpu.ops.flash_attention import mha_reference

    q, k, v = _qkv(jax.random.PRNGKey(0), s=64)
    for blocks in ((512, 512), (16, 32)):
        out = flash_attention(
            q, k, v, dropout_p=0.3, dropout_seed=123,
            block_q=blocks[0], block_k=blocks[1],
        )
        ref = mha_reference(q, k, v, dropout_p=0.3, dropout_seed=123)
        assert jnp.abs(out - ref).max() < 2e-5, blocks


def test_flash_dropout_zero_p_equals_no_dropout():
    q, k, v = _qkv(jax.random.PRNGKey(1))
    a = flash_attention(q, k, v)
    b = flash_attention(q, k, v, dropout_p=0.0, dropout_seed=7)
    assert jnp.array_equal(a, b)


def test_flash_dropout_requires_seed():
    q, k, v = _qkv(jax.random.PRNGKey(2))
    with pytest.raises(ValueError, match="dropout_seed"):
        flash_attention(q, k, v, dropout_p=0.1)


def test_flash_dropout_rate_and_seed_dependence():
    from apex_tpu.ops.flash_attention import dropout_mask_reference

    m1 = dropout_mask_reference(11, 1, 2, 128, 128, 0.25)
    m2 = dropout_mask_reference(12, 1, 2, 128, 128, 0.25)
    rate = 1.0 - float(m1.mean())
    assert abs(rate - 0.25) < 0.02
    assert not jnp.array_equal(m1, m2)  # seed changes the mask
    # heads get distinct masks
    assert not jnp.array_equal(m1[0, 0], m1[0, 1])


def test_flash_dropout_grads_match_reference():
    """Backward regenerates the identical mask: grads must equal autodiff
    through the materialised-mask reference."""
    from apex_tpu.ops.flash_attention import mha_reference

    q, k, v = _qkv(jax.random.PRNGKey(3), s=32)

    def f_flash(q, k, v):
        return (flash_attention(
            q, k, v, causal=True, dropout_p=0.2, dropout_seed=99,
        ) ** 2).sum()

    def f_ref(q, k, v):
        return (mha_reference(
            q, k, v, causal=True, dropout_p=0.2, dropout_seed=99,
        ) ** 2).sum()

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        assert jnp.abs(gf - gr).max() < 5e-4, name


def _packed(key, lens, n=2, d=16, pad_to=None):
    total = sum(lens)
    if pad_to:
        total = pad_to
    cu = jnp.asarray(np_cumsum0(lens), jnp.int32)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (total, n, d), jnp.float32)
    k = jax.random.normal(kk, (total, n, d), jnp.float32)
    v = jax.random.normal(kv, (total, n, d), jnp.float32)
    return q, k, v, cu


def np_cumsum0(lens):
    import numpy as np

    return np.concatenate([[0], np.cumsum(lens)])


@pytest.mark.parametrize("causal", [False, True])
def test_flash_varlen_matches_per_sequence_reference(causal):
    from apex_tpu.ops.flash_attention import (
        flash_attention_varlen,
        mha_reference,
    )
    import numpy as np

    lens = [24, 8, 32]  # total 64
    q, k, v, cu = _packed(jax.random.PRNGKey(4), lens)
    out = flash_attention_varlen(q, k, v, cu, causal=causal)

    # reference: run each sequence separately through dense attention
    for i, L in enumerate(lens):
        s, e = int(cu[i]), int(cu[i + 1])
        ref = mha_reference(
            q[s:e].transpose(1, 0, 2)[None],
            k[s:e].transpose(1, 0, 2)[None],
            v[s:e].transpose(1, 0, 2)[None],
            causal=causal,
        )[0].transpose(1, 0, 2)
        np.testing.assert_allclose(
            np.asarray(out[s:e]), np.asarray(ref), atol=2e-5,
            err_msg=f"sequence {i}",
        )


def test_flash_varlen_grads_match_reference():
    from apex_tpu.ops.flash_attention import (
        flash_attention_varlen,
        mha_reference_varlen,
    )
    import numpy as np

    lens = [16, 48]
    q, k, v, cu = _packed(jax.random.PRNGKey(5), lens)

    g_flash = jax.grad(
        lambda q, k, v: (flash_attention_varlen(q, k, v, cu, causal=True) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: (mha_reference_varlen(q, k, v, cu, causal=True) ** 2).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-4, err_msg=name
        )


def test_flash_varlen_padding_tail_isolated():
    """Tokens past cu_seqlens[-1] form their own padding segment and must
    not influence real sequences."""
    from apex_tpu.ops.flash_attention import flash_attention_varlen
    import numpy as np

    lens = [24, 24]  # 48 real tokens, padded buffer of 64
    q, k, v, cu = _packed(jax.random.PRNGKey(6), lens, pad_to=64)
    out = flash_attention_varlen(q, k, v, cu)
    q2 = q.at[48:].set(1e3)  # poison the padding tokens
    k2 = k.at[48:].set(1e3)
    v2 = v.at[48:].set(1e3)
    out2 = flash_attention_varlen(q2, k2, v2, cu)
    np.testing.assert_allclose(
        np.asarray(out[:48]), np.asarray(out2[:48]), atol=1e-6
    )


def test_segment_ids_from_cu_seqlens():
    from apex_tpu.ops.flash_attention import segment_ids_from_cu_seqlens
    import numpy as np

    cu = jnp.asarray([0, 3, 3, 7], jnp.int32)  # empty middle sequence
    segs = segment_ids_from_cu_seqlens(cu, 9)
    np.testing.assert_array_equal(
        np.asarray(segs), [0, 0, 0, 2, 2, 2, 2, 3, 3]
    )


def test_gpt_flash_with_attention_dropout():
    """Attention dropout now runs in-kernel on the flash path: a forced-on
    flash config with attention_dropout > 0 must train (no raise), be
    deterministic per key, and vary across keys."""
    from apex_tpu.transformer.testing import GPTConfig, gpt_loss, init_gpt_params

    cfg = GPTConfig(
        num_layers=2, hidden_size=64, num_attention_heads=2, vocab_size=128,
        max_position_embeddings=32, hidden_dropout=0.0,
        attention_dropout=0.25, use_flash_attention=True,
    )
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 128)
    labels = jnp.roll(tokens, -1, axis=1)

    k = jax.random.PRNGKey(5)
    l1 = gpt_loss(cfg, params, tokens, labels, dropout_key=k, deterministic=False)
    l2 = gpt_loss(cfg, params, tokens, labels, dropout_key=k, deterministic=False)
    l3 = gpt_loss(cfg, params, tokens, labels,
                  dropout_key=jax.random.PRNGKey(9), deterministic=False)
    ld = gpt_loss(cfg, params, tokens, labels, deterministic=True)
    assert float(l1) == float(l2)      # same key -> same in-kernel mask
    assert float(l1) != float(l3)      # key changes the mask
    assert float(l1) != float(ld)      # dropout actually active
    # grads flow through the dropped kernel
    g = jax.grad(lambda p: gpt_loss(cfg, p, tokens, labels, dropout_key=k,
                                    deterministic=False))(params)
    assert all(jnp.isfinite(x).all() for x in jax.tree_util.tree_leaves(g))


# ---------------------------------------------------------------------------
# additive logit bias (AlphaFold pair bias / ALiBi; reference openfold MHA's
# ``bias=`` argument, apex/contrib/openfold_triton/mha.py:133)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("bias_shape", [(2, 2, 64, 64), (1, 2, 64, 64),
                                        (2, 1, 64, 64), (1, 1, 64, 64),
                                        (2, 1, 1, 64), (1, 1, 1, 64)])
def test_flash_bias_forward_matches_reference(causal, bias_shape):
    key = jax.random.PRNGKey(11)
    q, k, v = _qkv(key)
    bias = jax.random.normal(jax.random.fold_in(key, 1), bias_shape) * 0.5
    o = flash_attention(q, k, v, bias=bias, causal=causal,
                        block_q=16, block_k=16)
    ref = mha_reference(q, k, v, bias=bias, causal=causal)
    assert jnp.abs(o - ref).max() < 2e-5


@pytest.mark.parametrize("bias_shape", [(2, 2, 64, 64), (1, 2, 64, 64),
                                        (2, 1, 1, 64)])
def test_flash_bias_grads_match_reference(bias_shape):
    """dq/dk/dv/dbias vs the materialised reference — incl. the broadcast
    reduction of dbias over a collapsed batch dim."""
    key = jax.random.PRNGKey(12)
    q, k, v = _qkv(key)
    bias = jax.random.normal(jax.random.fold_in(key, 2), bias_shape) * 0.5

    def loss(fn):
        return lambda q, k, v, bias: jnp.sum(fn(q, k, v, bias) ** 2)

    gf = jax.grad(
        loss(lambda q, k, v, b: flash_attention(
            q, k, v, bias=b, block_q=16, block_k=16)),
        argnums=(0, 1, 2, 3),
    )(q, k, v, bias)
    gr = jax.grad(
        loss(lambda q, k, v, b: mha_reference(q, k, v, bias=b)),
        argnums=(0, 1, 2, 3),
    )(q, k, v, bias)
    for a, b in zip(gf, gr):
        assert a.shape == b.shape
        assert jnp.abs(a - b).max() < 5e-4


def test_flash_bias_causal_grads_zero_above_diagonal():
    """Causal-skipped tiles must leave dbias zero-filled (the dq kernel
    writes the zero block before the masked compute)."""
    key = jax.random.PRNGKey(13)
    q, k, v = _qkv(key, s=64)
    bias = jax.random.normal(jax.random.fold_in(key, 3), (2, 2, 64, 64))
    db = jax.grad(
        lambda b: jnp.sum(flash_attention(
            q, k, v, bias=b, causal=True, block_q=16, block_k=16) ** 2)
    )(bias)
    qi = jnp.arange(64)[:, None]
    ki = jnp.arange(64)[None, :]
    above = jnp.broadcast_to(ki > qi, db.shape)
    assert jnp.abs(jnp.where(above, db, 0.0)).max() == 0.0


def test_flash_bias_with_dropout_matches_reference():
    key = jax.random.PRNGKey(14)
    q, k, v = _qkv(key)
    bias = jax.random.normal(jax.random.fold_in(key, 4), (1, 2, 64, 64)) * 0.3
    o = flash_attention(q, k, v, bias=bias, dropout_p=0.2, dropout_seed=21,
                        block_q=16, block_k=16)
    ref = mha_reference(q, k, v, bias=bias, dropout_p=0.2, dropout_seed=21)
    assert jnp.abs(o - ref).max() < 2e-5


def test_flash_bias_shape_validation():
    q, k, v = _qkv(jax.random.PRNGKey(15))
    with pytest.raises(ValueError, match="bias shape"):
        flash_attention(q, k, v, bias=jnp.zeros((3, 2, 64, 64)))
    with pytest.raises(ValueError, match="bias shape"):
        flash_attention(q, k, v, bias=jnp.zeros((2, 2, 32, 64)))


def test_lane_block_picks():
    """Mosaic lane-dim rule for mask/seg/bias blocks: %128 or whole dim
    (regression for varlen totals like 320 failing to lower on TPU)."""
    from apex_tpu.ops.flash_attention import _lane_block
    assert _lane_block(320, 64) == 320      # no %128 divisor -> whole dim
    assert _lane_block(384, 64) == 128      # closest %128 divisor
    assert _lane_block(1024, 512) == 512    # already legal
    assert _lane_block(1024, 1024) == 1024  # whole dim always legal
    assert _lane_block(72, 8) == 72         # small odd seq -> whole dim


@pytest.mark.parametrize("block_k,bias_grad", [
    (16, True),   # generic two-kernel backward (n_k=2)
    (32, True),   # n_k=1 but dbias emission keeps the two-kernel path
    (32, False),  # the fused single-k-block backward, bias streamed
])
def test_bias_folded_full_row_mask_returns_zeros(block_k, bias_grad):
    """A bias row folded to the library's own _NEG_INF (-1e30) fully masks
    that query row: the kernel must keep the zeros/-inf lse convention
    (guards stay active on the bias path), matching mha_reference — on
    the generic AND fused backward paths."""
    b, n, s, d = 1, 2, 32, 16
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q, k, v = (jax.random.normal(kk, (b, n, s, d), jnp.float32) for kk in ks)
    bias = jnp.zeros((1, 1, s, s), jnp.float32).at[:, :, 5, :].set(-1e30)
    out = flash_attention(q, k, v, bias=bias, block_q=16, block_k=block_k,
                          bias_grad=bias_grad)
    ref = mha_reference(q, k, v, bias=bias)
    assert jnp.abs(out[:, :, 5]).max() == 0.0
    assert jnp.abs(out - ref).max() < 2e-5
    # backward: the bwd-kernel guards must keep masked-row grads at exact
    # zero and everything finite (lse = -inf rows flow through exp)
    grads = jax.grad(
        lambda q, k, v: jnp.sum(flash_attention(
            q, k, v, bias=bias, block_q=16, block_k=block_k,
            bias_grad=bias_grad) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for g in grads:
        assert jnp.all(jnp.isfinite(g))
    assert jnp.abs(grads[0][:, :, 5]).max() == 0.0  # dq of the masked row


@pytest.mark.parametrize("causal", [False, True])
def test_explicit_bwd_blocks_match_default(causal):
    """The bwd_block_q/bwd_block_k hooks (round-5: fwd and bwd tiles can
    diverge) must produce the same gradients as the default tiling —
    guards the custom-vjp nondiff-arg plumbing."""
    q, k, v = _qkv(jax.random.PRNGKey(9), s=64, d=32)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    g_def = jax.grad(loss(lambda q, k, v: flash_attention(
        q, k, v, causal=causal, block_q=32, block_k=32)),
        argnums=(0, 1, 2))(q, k, v)
    g_exp = jax.grad(loss(lambda q, k, v: flash_attention(
        q, k, v, causal=causal, block_q=32, block_k=32,
        bwd_block_q=16, bwd_block_k=16)), argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_def, g_exp, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5, err_msg=name)
