"""Flash attention kernel vs materialised-score reference (fwd + grads),
plus GPT/BERT model equivalence between the flash and XLA attention paths.

Mirrors the reference's contrib test style (``apex/contrib/test/fmha/``,
``apex/contrib/test/multihead_attn/``): kernel-vs-reference tolerance
asserts including backward.
"""
import dataclasses

import jax
import jax.numpy as jnp
import pytest

from apex_tpu.ops.flash_attention import (
    flash_attention,
    mha_reference,
)


def _qkv(key, b=2, n=2, s=64, d=32, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    mk = lambda k: jax.random.normal(k, (b, n, s, d), dtype)
    return mk(ks[0]), mk(ks[1]), mk(ks[2])


@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_matches_reference(causal):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    o = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    ref = mha_reference(q, k, v, causal=causal)
    assert jnp.abs(o - ref).max() < 2e-5


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_reference(causal):
    q, k, v = _qkv(jax.random.PRNGKey(1))

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    gf = jax.grad(
        loss(lambda q, k, v: flash_attention(
            q, k, v, causal=causal, block_q=16, block_k=16
        )),
        argnums=(0, 1, 2),
    )(q, k, v)
    gr = jax.grad(
        loss(lambda q, k, v: mha_reference(q, k, v, causal=causal)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(gf, gr):
        assert jnp.abs(a - b).max() < 5e-4


def test_flash_key_padding_mask():
    key = jax.random.PRNGKey(2)
    q, k, v = _qkv(key)
    mask = jax.random.bernoulli(jax.random.fold_in(key, 9), 0.75, (2, 64))
    o = flash_attention(q, k, v, kv_mask=mask, block_q=16, block_k=16)
    ref = mha_reference(q, k, v, kv_mask=mask)
    assert jnp.abs(o - ref).max() < 2e-5
    gf = jax.grad(
        lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, kv_mask=mask, block_q=16, block_k=16)
            ** 2
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    gr = jax.grad(
        lambda q, k, v: jnp.sum(mha_reference(q, k, v, kv_mask=mask) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(gf, gr):
        assert jnp.abs(a - b).max() < 5e-4


def test_flash_uneven_blocks():
    # seq not a multiple of the requested block: block shrinks to divide
    q, k, v = _qkv(jax.random.PRNGKey(3), s=48)
    o = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
    ref = mha_reference(q, k, v, causal=True)
    assert jnp.abs(o - ref).max() < 2e-5


def test_flash_rectangular_qk():
    key = jax.random.PRNGKey(4)
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (2, 2, 32, 32))
    k = jax.random.normal(ks[1], (2, 2, 64, 32))
    v = jax.random.normal(ks[2], (2, 2, 64, 32))
    o = flash_attention(q, k, v, block_q=16, block_k=16)
    ref = mha_reference(q, k, v)
    assert jnp.abs(o - ref).max() < 2e-5


def test_gpt_flash_matches_xla_path():
    """Model-level: forward+grads identical between flash and XLA scores."""
    from apex_tpu.transformer.testing import (
        GPTConfig,
        gpt_loss,
        init_gpt_params,
    )

    base = GPTConfig(
        num_layers=2,
        hidden_size=64,
        num_attention_heads=2,
        vocab_size=128,
        max_position_embeddings=32,
        hidden_dropout=0.0,
        attention_dropout=0.0,
    )
    params = init_gpt_params(base, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 128)
    labels = jnp.roll(tokens, -1, axis=1)

    def run(use_flash):
        cfg = dataclasses.replace(base, use_flash_attention=use_flash)
        return jax.value_and_grad(
            lambda p: gpt_loss(cfg, p, tokens, labels)
        )(params)

    loss_f, grads_f = run(True)
    loss_x, grads_x = run(False)
    assert jnp.abs(loss_f - loss_x) < 1e-5
    flat_f = jax.tree_util.tree_leaves(grads_f)
    flat_x = jax.tree_util.tree_leaves(grads_x)
    for a, b in zip(flat_f, flat_x):
        assert jnp.abs(a - b).max() < 1e-4


def test_bert_flash_matches_xla_path():
    """BERT padding-mask path: flash consumes the [b,1,1,s] key-padding
    mask; results match the materialised-mask XLA path."""
    from apex_tpu.transformer.testing import GPTConfig
    from apex_tpu.transformer.testing.standalone_transformer_lm import (
        bert_forward,
        init_gpt_params,
    )

    base = GPTConfig(
        num_layers=2,
        hidden_size=64,
        num_attention_heads=2,
        vocab_size=128,
        max_position_embeddings=32,
        hidden_dropout=0.0,
        attention_dropout=0.0,
    )
    params = init_gpt_params(base, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 128)
    padding = jnp.concatenate(
        [jnp.ones((2, 24), jnp.int32), jnp.zeros((2, 8), jnp.int32)], axis=1
    )

    def run(use_flash):
        cfg = dataclasses.replace(base, use_flash_attention=use_flash)
        logits, _ = bert_forward(cfg, params, tokens, padding)
        return logits

    lf = run(True)
    lx = run(False)
    # compare only non-padded query positions (padded queries attend to
    # everything in both paths but their logits are irrelevant)
    assert jnp.abs(lf[:, :24] - lx[:, :24]).max() < 1e-4
