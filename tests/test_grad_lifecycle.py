"""Bucketed flat-buffer gradient lifecycle oracle (ISSUE-14).

The acceptance contract for ``GradBuckets`` + ``reduce_flat`` +
``unscale_flat`` + the packed optimizer fed the reduced buffer: training
with the flat-bucket lifecycle must be **step-for-step bit-identical**
(f32-hex loss records) to the per-leaf reference — per-leaf ``psum`` via
``sync_gradients``, pytree amp unscale, pytree ``FusedAdam`` — on the
8-virtual-device CPU mesh under ``shard_map``, including overflow-skip
steps (a NaN-poisoned batch trips ``found_inf`` identically on both
paths) and with ``allreduce_always_fp32`` both off and on. Plus the
layout/scope/telemetry unit contracts the lifecycle rests on.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.amp import LossScaler
from apex_tpu.analysis import check_pack_spec
from apex_tpu.multi_tensor_apply.packing import ROW, PackSpec
from apex_tpu.optimizers import FusedAdam, FusedSGD
from apex_tpu.parallel import (
    DistributedDataParallel,
    GradBuckets,
    sync_gradients,
    sync_gradients_bucketed,
)

CHUNK = 2 * ROW  # small kernel chunk so multi-bucket layouts stay tiny


def _mesh():
    return Mesh(np.array(jax.devices()), ("data",))


def _params(dtype=jnp.bfloat16):
    ks = jax.random.split(jax.random.PRNGKey(0), 4)
    mk = lambda k, shape: (  # noqa: E731
        0.1 * jax.random.normal(k, shape, jnp.float32)).astype(dtype)
    return {
        "w1": mk(ks[0], (12, 64)),
        "b1": mk(ks[1], (64,)),
        "w2": mk(ks[2], (64, 4)),
        "b2": mk(ks[3], (4,)),
    }


def _batches(steps, batch=16, poison_at=None):
    """Deterministic regression batches; ``poison_at`` plants a NaN
    feature in that step's batch (NaN grads -> overflow skip)."""
    out = []
    for s in range(steps):
        k = jax.random.PRNGKey(100 + s)
        x = jax.random.normal(k, (batch, 12), jnp.float32)
        y = jnp.sum(x, axis=1, keepdims=True) * jnp.ones((1, 4))
        if s == poison_at:
            x = x.at[0, 0].set(jnp.nan)
        out.append((x, y))
    return out


def _loss_fn(params, x, y):
    h = jnp.tanh(x.astype(params["w1"].dtype) @ params["w1"]
                 + params["b1"])
    pred = h @ params["w2"] + params["b2"]
    return jnp.mean((pred.astype(jnp.float32) - y) ** 2)


def _run(steps, batches, flat, always_fp32, parity_downcast,
         bucket_cap_mb=0.002):
    """One training run; returns the per-step f32 loss records as hex.

    ``flat=True`` is the bucketed lifecycle (reduce_flat -> unscale_flat
    -> packed FusedAdam on the reduced buffer); ``False`` the per-leaf
    reference; ``flat="fused"`` the one-sweep fused spelling (raw
    per-bucket psum, read-only ``found_inf_flat``, the unscale multiply
    AND the deferred gradient average riding ``grad_scale`` into
    ``step_flat``'s in-kernel noop update, forward from views of the
    master buffer — exact vs the reference because loss scale and world
    size are powers of two). ``parity_downcast`` selects
    reference-parity cast-back after an fp32 reduction (per leaf vs per
    bucket) — with it off, both paths keep the reduction's fp32
    (``keep_fp32`` / the flat default).
    """
    params = _params()
    buckets = GradBuckets(params, bucket_cap_mb=bucket_cap_mb,
                          chunk_size=CHUNK)
    assert buckets.n_buckets >= 2, "oracle must exercise multiple buckets"
    scaler = LossScaler(loss_scale="dynamic", init_scale=2.0 ** 4,
                        scale_window=3)
    sstate = scaler.init_state()
    fused = flat == "fused"
    world = len(jax.devices())
    if flat:
        opt = FusedAdam(lr=1e-2, master_weights=True, packed=True,
                        packed_spec=buckets.spec)
        ddp = DistributedDataParallel(
            "data", allreduce_always_fp32=always_fp32,
            gradient_average=not fused,
            bucket_cap_mb=bucket_cap_mb)
    else:
        opt = FusedAdam(lr=1e-2, master_weights=True)
    opt_state = opt.init(params)

    def shard_step(params, opt_state, sstate, x, y):
        if fused:
            # masters ARE the params; bf16 leaves are unpack views
            params = buckets.unpack(opt_state.master_params)

        def scaled(p):
            loss = _loss_fn(p, x, y)
            return scaler.scale_loss(sstate, loss.astype(jnp.float32)), loss

        (_, loss), grads = jax.value_and_grad(scaled, has_aux=True)(params)
        if fused:
            bufs, _ = ddp.reduce_flat(grads, buckets=buckets,
                                      concat=False)
            new_ss = scaler.found_inf_flat(sstate, bufs)
            opt_state = opt.step_flat(
                bufs, opt_state, found_inf=new_ss.found_inf,
                grad_scale=new_ss.loss_scale * world)
        elif flat:
            g, _ = ddp.reduce_flat(grads, buckets=buckets,
                                   match_leaf_dtype=parity_downcast)
            g, new_ss = scaler.unscale_flat(sstate, g,
                                            out_dtype=jnp.float32)
            params, opt_state = opt.step(g, opt_state, params,
                                         found_inf=new_ss.found_inf)
        else:
            grads = sync_gradients(
                grads, "data", allreduce_always_fp32=always_fp32,
                keep_fp32=not parity_downcast)
            g, new_ss = scaler.unscale(sstate, grads,
                                       out_dtype=jnp.float32)
            params, opt_state = opt.step(g, opt_state, params,
                                         found_inf=new_ss.found_inf)
        new_ss = scaler.update_scale(new_ss)
        loss = jax.lax.pmean(loss.astype(jnp.float32), "data")
        return params, opt_state, new_ss, loss

    step = jax.jit(shard_map(
        shard_step, mesh=_mesh(),
        in_specs=(P(), P(), P(), P("data"), P("data")),
        out_specs=(P(), P(), P(), P()), check_rep=False))

    records = []
    for x, y in batches:
        params, opt_state, sstate, loss = step(params, opt_state, sstate,
                                               x, y)
        records.append(np.float32(loss).tobytes().hex())
    return records


@pytest.mark.parametrize(
    "always_fp32,parity_downcast",
    [(False, True),   # half-precision reduction, reference cast-back
     (True, True),    # fp32 reduction + reference per-leaf/bucket downcast
     (True, False)],  # fp32 reduction kept fp32 (the audit-clean default)
    ids=["bf16_reduce", "fp32_reduce_parity", "fp32_reduce_keep"])
def test_flat_lifecycle_bit_identical_to_per_leaf(always_fp32,
                                                  parity_downcast):
    steps = 8
    # step 3 overflows (NaN batch): found_inf must trip, the update must
    # skip and the scaler must back off IDENTICALLY on both paths
    batches = _batches(steps, poison_at=3)
    ref = _run(steps, batches, flat=False, always_fp32=always_fp32,
               parity_downcast=parity_downcast)
    got = _run(steps, batches, flat=True, always_fp32=always_fp32,
               parity_downcast=parity_downcast)
    assert got == ref, (
        "flat-bucket lifecycle diverged from the per-leaf reference: "
        f"\nref={ref}\ngot={got}")
    # the poisoned step really produced a NaN loss record (the overflow
    # path was exercised, not dodged)
    poisoned = np.frombuffer(bytes.fromhex(ref[3]), np.float32)[0]
    assert np.isnan(poisoned)
    healthy = np.frombuffer(bytes.fromhex(ref[4]), np.float32)[0]
    assert np.isfinite(healthy)


def test_fused_lifecycle_bit_identical_to_per_leaf():
    """The one-sweep fused spelling (the bench/headline lifecycle):
    raw-sum bucket psums, read-only found_inf, unscale AND gradient
    average deferred into step_flat's in-kernel noop update, forward
    from master-buffer views — still bit-identical to the per-leaf
    reference, overflow-skip steps included (the noop select must leave
    step/m/v/masters untouched exactly like the reference's skipped
    lax.cond)."""
    steps = 8
    batches = _batches(steps, poison_at=3)
    ref = _run(steps, batches, flat=False, always_fp32=True,
               parity_downcast=False)
    got = _run(steps, batches, flat="fused", always_fp32=True,
               parity_downcast=False)
    assert got == ref, (
        "fused flat lifecycle diverged from the per-leaf reference: "
        f"\nref={ref}\ngot={got}")
    poisoned = np.frombuffer(bytes.fromhex(ref[3]), np.float32)[0]
    assert np.isnan(poisoned)


def test_step_flat_matches_step_and_noop_contract():
    """step_flat == step on the same reduced buffer (modulo the carry
    shape), and its in-kernel noop leaves step/m/v/masters bit-frozen."""
    params = _params(jnp.float32)
    buckets = GradBuckets(params, bucket_cap_mb=0.002, chunk_size=CHUNK)
    grads = jax.tree_util.tree_map(
        lambda p: jnp.ones_like(p) * 1e-2, params)
    flat = buckets.concat(buckets.pack(grads))
    bufs = jax.tree_util.tree_map(lambda x: x, buckets.pack(grads))
    from apex_tpu.parallel import BucketBuffers

    opt = FusedAdam(lr=1e-2, master_weights=True, packed=True,
                    packed_spec=buckets.spec)
    s0 = opt.init(params)
    no = jnp.asarray(False)
    # compare jit-to-jit: the contract is bit-identity of the compiled
    # steps (XLA's fusion choices differ between eager and traced runs)
    p_ref, s_ref = jax.jit(opt.step)(flat, opt.init(params), params,
                                     found_inf=no)
    s_got = jax.jit(lambda b, s: opt.step_flat(b, s, found_inf=no))(
        BucketBuffers(tuple(bufs)), s0)
    # same state bits, and the master buffer IS the params (unpack views
    # equal the step()-returned tree)
    np.testing.assert_array_equal(np.asarray(s_got.exp_avg),
                                  np.asarray(s_ref.exp_avg))
    np.testing.assert_array_equal(np.asarray(s_got.master_params),
                                  np.asarray(s_ref.master_params))
    got_tree = buckets.unpack(s_got.master_params)
    for k in params:
        np.testing.assert_array_equal(np.asarray(got_tree[k]),
                                      np.asarray(p_ref[k]))
    # overflow: every field frozen, including the step counter
    s_skip = opt.step_flat(flat, s_got, found_inf=jnp.asarray(True),
                           grad_scale=2.0)
    assert int(s_skip.step) == int(s_got.step)
    for a, b in zip(jax.tree_util.tree_leaves(s_skip),
                    jax.tree_util.tree_leaves(s_got)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # guard: the flat-carry contract needs resident masters
    with pytest.raises(ValueError, match="master_weights"):
        FusedAdam(lr=1e-2, packed=True).step_flat(flat, s0)


def test_found_inf_flat_matches_unscale_flat_verdict():
    """The read-only overflow probe agrees with the unscale sweep's
    verdict on both clean and poisoned buffers, from the flat buffer or
    the BucketBuffers handoff."""
    from apex_tpu.parallel import BucketBuffers

    params = _params(jnp.float32)
    buckets = GradBuckets(params, bucket_cap_mb=0.002, chunk_size=CHUNK)
    scaler = LossScaler(loss_scale=4.0)
    for poison in (False, True):
        grads = jax.tree_util.tree_map(jnp.ones_like, params)
        if poison:
            grads["w1"] = grads["w1"].at[0, 0].set(jnp.inf)
        flat = buckets.concat(buckets.pack(grads))
        bufs = BucketBuffers(tuple(buckets.pack(grads)))
        _, ref = scaler.unscale_flat(scaler.init_state(), flat,
                                     out_dtype=jnp.float32)
        got_flat = scaler.found_inf_flat(scaler.init_state(), flat)
        got_bufs = scaler.found_inf_flat(scaler.init_state(), bufs)
        assert bool(got_flat.found_inf) == bool(ref.found_inf) == poison
        assert bool(got_bufs.found_inf) == poison


def test_bucket_layout_structure_and_invariants():
    params = _params(jnp.float32)
    buckets = GradBuckets(params, bucket_cap_mb=0.002, chunk_size=CHUNK)
    spec = buckets.spec
    assert buckets.n_buckets >= 2
    buckets.check()
    assert check_pack_spec(spec) == []
    # bucket bounds are chunk-aligned and cover [0, total)
    assert spec.bucket_bounds[0] == 0
    assert spec.bucket_bounds[-1] == spec.total
    assert all(b % spec.chunk_size == 0 for b in spec.bucket_bounds)
    # leaf ranges partition the leaves in order
    flatranges = [r for lo, hi in spec.bucket_leaf_ranges
                  for r in range(lo, hi)]
    assert flatranges == list(range(spec.n_leaves))
    # per-bucket packing concatenates into exactly the global pack
    glob = spec.pack(params, jnp.float32)
    cat = buckets.concat(buckets.pack(params, jnp.float32))
    np.testing.assert_array_equal(np.asarray(glob), np.asarray(cat))
    # and the global buffer unpacks back to the tree
    out = buckets.unpack(glob)
    for k in params:
        np.testing.assert_array_equal(np.asarray(out[k]),
                                      np.asarray(params[k]))


def test_autobuilt_fp32_reduction_sizes_cap_at_fp32():
    """allreduce_always_fp32 must not double the collective buffers:
    the default-built buckets size bucket_cap_mb in fp32 (the dtype the
    psum actually moves), not the bf16 grad dtype."""
    # 4 x 1-chunk bf16 leaves; cap = 2 fp32 chunks. fp32 sizing -> 2
    # buckets of cap bytes each; bf16 sizing would cram all 4 into one
    # 2x-cap fp32 buffer.
    chunk = 65536  # the GradBuckets default (auto-build has no knob)
    tree = {f"w{i}": jnp.zeros((chunk,), jnp.bfloat16) for i in range(4)}
    cap_mb = 2 * chunk * 4 / 2 ** 20
    assert GradBuckets(tree, bucket_cap_mb=cap_mb).n_buckets == 1
    assert GradBuckets(tree, bucket_cap_mb=cap_mb,
                       reduce_dtype=jnp.float32).n_buckets == 2

    def reduce_fn(t):
        return sync_gradients_bucketed(
            t, "data", bucket_cap_mb=cap_mb,
            allreduce_always_fp32=True)[0]

    f = shard_map(reduce_fn, mesh=_mesh(), in_specs=P(), out_specs=P(),
                  check_rep=False)
    from apex_tpu.analysis import comm_volume
    assert comm_volume(f, tree)["psum"]["count"] == 2


def test_adopted_spec_rejects_conflicting_chunk_size():
    params = _params(jnp.float32)
    buckets = GradBuckets(params, bucket_cap_mb=0.002, chunk_size=CHUNK)
    with pytest.raises(ValueError, match="chunk_size"):
        FusedAdam(lr=1e-3, packed=True, packed_chunk_size=4 * CHUNK,
                  packed_spec=buckets.spec).init(params)
    # matching or omitted chunk_size still adopts the spec
    s = FusedAdam(lr=1e-3, packed=True, packed_chunk_size=CHUNK,
                  packed_spec=buckets.spec).init(params)
    assert s.spec is buckets.spec


def test_oversized_leaf_gets_its_own_bucket():
    # one leaf larger than the cap must not raise — it becomes its own
    # bucket (the reference's message_size overflow behaviour)
    tree = {"big": jnp.zeros((8 * CHUNK,), jnp.float32),
            "small": jnp.zeros((8,), jnp.float32)}
    buckets = GradBuckets(tree, bucket_cap_mb=0.001, chunk_size=CHUNK)
    assert buckets.n_buckets == 2
    buckets.check()


def test_corrupt_bucket_bounds_fail_check():
    import copy

    spec = GradBuckets(_params(jnp.float32), bucket_cap_mb=0.002,
                       chunk_size=CHUNK).spec
    bad = copy.copy(spec)
    bad.bucket_bounds = tuple(
        list(spec.bucket_bounds[:-1]) + [spec.total + 1])
    codes = {f.code for f in check_pack_spec(bad)}
    assert "bucket_bounds_cover" in codes
    assert "bucket_not_chunk_aligned" in codes
    # mismatched range/bounds tables produce a finding, not an
    # IndexError aborting the audit
    worse = copy.copy(spec)
    worse.bucket_bounds = spec.bucket_bounds[:-1]
    assert "bucket_tables_mismatch" in {
        f.code for f in check_pack_spec(worse)}


def test_bucketed_reduce_one_psum_per_bucket_with_named_scopes():
    """The collective structure the overlap story rests on: exactly one
    psum per bucket, each under its apex_tpu.grad_bucket/<i> scope (the
    PR-2 xplane parser's attribution hook)."""
    params = _params(jnp.float32)
    buckets = GradBuckets(params, bucket_cap_mb=0.002, chunk_size=CHUNK)

    def reduce_fn(tree):
        return sync_gradients_bucketed(tree, "data", buckets=buckets)[0]

    f = shard_map(reduce_fn, mesh=_mesh(), in_specs=P(),
                  out_specs=P(), check_rep=False)
    # one data psum per bucket (the world-size psum of a literal 1
    # constant-folds at trace time) — eqn-counted by the walker, not
    # text-matched (ISSUE-19)
    from apex_tpu.analysis import comm_volume
    vol = comm_volume(f, params)
    assert vol["psum"] == {"count": buckets.n_buckets,
                           "bytes": buckets.spec.total * 4,
                           "axes": ["data"]}
    # scopes ride the name stack into the compiled program — the xplane
    # attribution surface (test_observability.py's convention)
    hlo = jax.jit(f).lower(params).compile().as_text()
    for i in range(buckets.n_buckets):
        assert f"apex_tpu.grad_bucket/{i}" in hlo


def test_sync_gradients_keep_fp32_is_audit_clean():
    """The PR-4 double_cast fix: the legacy per-leaf fp32 round-trip
    trips the auditor; keep_fp32 (and the flat path) do not."""
    from apex_tpu.analysis import audit_step

    grads = {"w": jnp.ones((256, 256), jnp.bfloat16)}

    def legacy(g):
        g = sync_gradients(g, "data", allreduce_always_fp32=True)
        return jax.tree_util.tree_map(
            lambda x: x.astype(jnp.float32) * 0.5, g)

    def clean(g):
        g = sync_gradients(g, "data", allreduce_always_fp32=True,
                           keep_fp32=True)
        return jax.tree_util.tree_map(lambda x: x * 0.5, g)

    def run(fn):
        mapped = shard_map(fn, mesh=_mesh(), in_specs=P(), out_specs=P(),
                           check_rep=False)
        return audit_step(mapped, grads, rules=("dtype_flow",))

    assert "double_cast" in run(legacy).codes()
    assert "double_cast" not in run(clean).codes()


def test_flat_grads_reject_layout_mismatch():
    params = _params(jnp.float32)
    opt = FusedAdam(lr=1e-3, packed=True)
    state = opt.init(params)
    wrong = jnp.zeros((state.spec.total + ROW,), jnp.float32)
    with pytest.raises(ValueError, match="PackSpec"):
        opt.step(wrong, state, params)
    with pytest.raises(ValueError, match="packed_spec requires"):
        FusedAdam(packed_spec=state.spec)
    # the flat wrapper cannot hand back a buffer in a layout nothing
    # else shares: buckets= is required
    with pytest.raises(ValueError, match="buckets"):
        DistributedDataParallel("data", bucket_cap_mb=1.0).wrap_grad_fn(
            lambda p: p, flat=True)


def test_single_bare_leaf_pytree_still_packs():
    """A grads pytree that IS a bare 1-D array must keep the pytree
    reading (packed, dtype-normalised) — not be mistaken for a
    pre-packed buffer and rejected for its unpadded length."""
    w = jnp.ones((1000,), jnp.float32)
    opt = FusedAdam(lr=1e-3, packed=True, packed_chunk_size=CHUNK)
    state = opt.init(w)
    assert state.spec.total != w.shape[0]  # the ambiguity under test
    p1, _ = opt.step(jnp.ones_like(w) * 1e-2, state, w)
    # and the genuinely pre-packed spelling of the same update agrees
    flat = state.spec.pack(jnp.ones_like(w) * 1e-2, jnp.float32)
    p2, _ = opt.step(flat, opt.init(w), w)
    np.testing.assert_array_equal(np.asarray(p1), np.asarray(p2))


def test_found_inf_flat_flags_overflow_under_collapsed_scale():
    """scale < 1: a finite scaled gradient whose deferred 1/scale
    multiply would overflow fp32 must trip the read-only probe (the
    fused spelling has no later sweep to catch it)."""
    scaler = LossScaler(loss_scale=2.0 ** -10)
    big = jnp.full((8,), 1e36, jnp.float32)  # finite; 1e36/2**-10 = inf
    state = scaler.found_inf_flat(scaler.init_state(), big)
    assert bool(state.found_inf)
    # same magnitude at scale >= 1 stays clean (verdict-parity regime)
    ok = LossScaler(loss_scale=1.0)
    assert not bool(ok.found_inf_flat(ok.init_state(), big).found_inf)


def test_fused_sgd_accepts_reduced_flat_buffer():
    """The SGD spelling of the handoff: flat grads == packed pytree
    grads, bit-for-bit."""
    params = _params(jnp.float32)
    grads = jax.tree_util.tree_map(
        lambda p: jnp.ones_like(p) * 1e-2, params)
    buckets = GradBuckets(params, bucket_cap_mb=0.002, chunk_size=CHUNK)
    opt = FusedSGD(lr=0.1, momentum=0.9, packed=True,
                   packed_spec=buckets.spec)
    s1, s2 = opt.init(params), opt.init(params)
    flat = buckets.concat(buckets.pack(grads))
    p_flat, s_flat = opt.step(flat, s1, params)
    p_tree, s_tree = opt.step(grads, s2, params)
    for k in params:
        np.testing.assert_array_equal(np.asarray(p_flat[k]),
                                      np.asarray(p_tree[k]))
    np.testing.assert_array_equal(np.asarray(s_flat.exp_avg),
                                  np.asarray(s_tree.exp_avg))


def test_unscale_flat_found_inf_and_provenance():
    """One flat sweep yields unscale + found_inf + per-leaf overflow
    provenance through the row-aligned offsets."""
    from apex_tpu.telemetry.numerics import NumericsMonitor

    params = _params(jnp.float32)
    buckets = GradBuckets(params, bucket_cap_mb=0.002, chunk_size=CHUNK)
    grads = jax.tree_util.tree_map(jnp.ones_like, params)
    grads["w2"] = grads["w2"].at[3, 1].set(jnp.inf)
    flat = buckets.concat(buckets.pack(grads))

    scaler = LossScaler(loss_scale=2.0)
    sstate = scaler.init_state()
    monitor = NumericsMonitor(spec=buckets.spec)
    nstate = monitor.init()
    out, sstate, nstate = scaler.unscale_flat(
        sstate, flat, out_dtype=jnp.float32,
        numerics=(monitor, nstate))
    assert bool(sstate.found_inf)
    # provenance names exactly the poisoned leaf (flatten order:
    # b1, b2, w1, w2 — dict keys sort)
    names = buckets.spec.leaf_names()
    bad = [n for n, f in zip(names, np.asarray(nstate.grad_nonfinite))
           if f > 0]
    assert bad == ["['w2']"]
    # the healthy positions really got unscaled (x * 1/2)
    np.testing.assert_allclose(np.asarray(out)[0], 0.5)


def test_sweep_bytes_feeds_telemetry_gbps():
    """GradBuckets.sweep_bytes mirrors PackedState.sweep_bytes and wires
    the per-drain achieved-GB/s denominator."""
    from apex_tpu import telemetry

    params = _params(jnp.bfloat16)
    buckets = GradBuckets(params, bucket_cap_mb=0.002, chunk_size=CHUNK)
    total = buckets.spec.total
    # bf16 grads read (2 B) + bf16 bucket write, local read+write of the
    # reduced buckets (2 B each): 4 sweeps of the padded length
    assert buckets.sweep_bytes() == 2 * total + 3 * 2 * total
    f32 = GradBuckets(params, bucket_cap_mb=0.002, chunk_size=CHUNK,
                      reduce_dtype=jnp.float32)
    assert f32.sweep_bytes() == 2 * total + 3 * 4 * total

    records = []
    metrics = telemetry.init_metrics()
    step = jax.jit(functools.partial(
        telemetry.drain, sink=records.append, every_n=1,
        bytes_per_step=buckets.sweep_bytes()))
    for _ in range(3):
        metrics = telemetry.accumulate(metrics, loss=jnp.float32(1.0),
                                       tokens=8)
        metrics = step(metrics)
    jax.effects_barrier()
    assert len(records) == 3
    # from the second drain on, the denominator yields achieved_gbps
    assert "achieved_gbps" in records[-1]
    assert records[-1]["achieved_gbps"] > 0
