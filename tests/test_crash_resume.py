"""Crash/resume bit-exactness: the acceptance test for resumable state.

A CPU training run (packed FusedAdam + masters, dynamic scaler, carried
PRNG key, IndexedBatches, telemetry counters — see
``tests/_resilience_train.py``) is hard-killed mid-run (``os._exit``, no
cleanup: async checkpoint threads die mid-write) and resumed from the
manager. The per-step loss records of crashed-prefix + resumed-suffix
must be **byte-identical** to an uninterrupted run — covering packed
optimizer state, scaler state, RNG stream and data-iterator position in
one assertion. A second test delivers a real SIGTERM and proves the
emergency-flush / resume path end to end.
"""
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest

SCRIPT = str(Path(__file__).parent / "_resilience_train.py")


def _run(*args, timeout=180):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    return subprocess.run(
        [sys.executable, SCRIPT, *map(str, args)],
        env=env, capture_output=True, text=True, timeout=timeout)


def _loss_lines(path):
    """{step: full line} for the per-step records, plus the final
    summary line (or None)."""
    steps, final = {}, None
    with open(path) as f:
        for line in f:
            line = line.rstrip("\n")
            if line.startswith("S "):
                steps[int(line.split()[1])] = line
            elif line.startswith("F "):
                final = line
    return steps, final


@pytest.mark.parametrize("die_at", [7])
def test_crash_resume_loss_curve_bit_exact(tmp_path, die_at):
    steps = 11
    # 1) uninterrupted reference
    base = _run("--steps", steps, "--root", tmp_path / "ref_ckpt",
                "--losses", tmp_path / "ref.txt")
    assert base.returncode == 0, base.stderr
    ref, ref_final = _loss_lines(tmp_path / "ref.txt")
    assert sorted(ref) == list(range(steps)) and ref_final

    # 2) crashed run: hard os._exit after step die_at's loss record —
    #    the async save in flight dies mid-write (tmp dir left behind)
    crash = _run("--steps", steps, "--root", tmp_path / "ckpt",
                 "--losses", tmp_path / "crash.txt", "--die-at", die_at)
    assert crash.returncode == 13, crash.stderr
    crashed, crashed_final = _loss_lines(tmp_path / "crash.txt")
    assert crashed_final is None  # it really died mid-run
    assert sorted(crashed) == list(range(die_at))

    # 3) resume from the manager (automatic: resume_or_init)
    resume = _run("--steps", steps, "--root", tmp_path / "ckpt",
                  "--losses", tmp_path / "resume.txt")
    assert resume.returncode == 0, resume.stderr
    resumed, resumed_final = _loss_lines(tmp_path / "resume.txt")

    # the resumed run restarted from a checkpointed step < die_at, not
    # from scratch
    first_resumed = min(resumed)
    assert 0 < first_resumed < die_at
    assert sorted(resumed) == list(range(first_resumed, steps))

    # 4) BYTE-identical loss curve: replayed overlap AND new suffix both
    #    match the uninterrupted run exactly (hex-formatted f32 losses)
    for s in range(first_resumed, die_at):
        assert resumed[s] == crashed[s], f"replay diverged at step {s}"
    combined = {**crashed, **resumed}
    assert combined == ref
    # telemetry counters (total_steps) and scaler state continued too
    assert resumed_final == ref_final


def test_sigterm_preemption_flush_and_resume(tmp_path):
    """A real SIGTERM mid-run flushes an emergency checkpoint; the next
    invocation resumes from it and completes."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    losses = tmp_path / "pre.txt"
    proc = subprocess.Popen(
        [sys.executable, SCRIPT, "--steps", "100000",
         "--root", str(tmp_path / "ckpt"), "--losses", str(losses),
         "--preemptable", "--step-sleep", "0.05"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if losses.exists() and len(losses.read_text().splitlines()) >= 4:
                break
            if proc.poll() is not None:
                pytest.fail(f"train process died early: "
                            f"{proc.communicate()[1]}")
            time.sleep(0.1)
        else:
            pytest.fail("train process produced no steps in time")
        proc.send_signal(signal.SIGTERM)
        _, err = proc.communicate(timeout=60)
        assert proc.returncode == 17, err  # clean preempted exit
    finally:
        if proc.poll() is None:
            proc.kill()

    # an emergency checkpoint exists at the preempted step
    import json

    root = tmp_path / "ckpt"
    step_dirs = sorted(d for d in os.listdir(root)
                       if d.startswith("step_") and ".tmp-" not in d)
    assert step_dirs
    with open(root / step_dirs[-1] / "meta.json") as f:
        newest = json.load(f)
    assert newest["emergency"] is True

    # resume completes from there (a short remaining budget)
    target = newest["step"] + 3
    done = _run("--steps", target, "--root", root,
                "--losses", tmp_path / "post.txt")
    assert done.returncode == 0, done.stderr
    resumed, final = _loss_lines(tmp_path / "post.txt")
    assert min(resumed) == newest["step"]
    assert sorted(resumed) == list(range(newest["step"], target))
    assert final is not None
