"""Crash/resume bit-exactness: the acceptance test for resumable state.

A CPU training run (packed FusedAdam + masters, dynamic scaler, carried
PRNG key, IndexedBatches, telemetry counters — see
``tests/_resilience_train.py``) is hard-killed mid-run (``os._exit``, no
cleanup: async checkpoint threads die mid-write) and resumed from the
manager. The per-step loss records of crashed-prefix + resumed-suffix
must be **byte-identical** to an uninterrupted run — covering packed
optimizer state, scaler state, RNG stream and data-iterator position in
one assertion. A second test delivers a real SIGTERM and proves the
emergency-flush / resume path end to end.
"""
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest

SCRIPT = str(Path(__file__).parent / "_resilience_train.py")


def test_bucketed_lifecycle_state_roundtrips_bit_exact(tmp_path):
    """Quick-tier ISSUE-14 coverage: a TrainState carrying the bucketed
    flat-buffer lifecycle's state (packed FusedAdam over
    ``GradBuckets.spec`` — flat m/v/masters — plus the scaler) survives
    ``capture`` -> ``CheckpointManager.save`` -> ``resume_or_init``
    bit-exactly, and a resumed run continues the loss records of an
    uninterrupted one byte-for-byte."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.amp import LossScaler
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.parallel import GradBuckets
    from apex_tpu.resilience import (
        CheckpointManager, capture, resume_or_init,
    )

    def init():
        ks = jax.random.split(jax.random.PRNGKey(7), 2)
        params = {"w": 0.1 * jax.random.normal(ks[0], (48, 32)),
                  "b": 0.1 * jax.random.normal(ks[1], (32,))}
        buckets = GradBuckets(params, bucket_cap_mb=0.005,
                              chunk_size=2048)
        opt = FusedAdam(lr=1e-2, master_weights=True, packed=True,
                        packed_spec=buckets.spec)
        scaler = LossScaler(loss_scale="dynamic", init_scale=4.0,
                            scale_window=2)
        return params, buckets, opt, scaler

    def loss_fn(params, x):
        return jnp.mean((jnp.tanh(x @ params["w"]) + params["b"]) ** 2)

    def run(steps, start_state=None):
        params, buckets, opt, scaler = init()
        opt_state, sstate, s0 = opt.init(params), scaler.init_state(), 0
        if start_state is not None:
            s0 = start_state.step
            params, opt_state = start_state.params, start_state.opt_state
            sstate = start_state.scaler

        @jax.jit
        def step(params, opt_state, sstate, x):
            def scaled(p):
                loss = loss_fn(p, x)
                return scaler.scale_loss(sstate, loss), loss

            (_, loss), grads = jax.value_and_grad(
                scaled, has_aux=True)(params)
            flat = buckets.concat(buckets.pack(grads))
            flat, new_ss = scaler.unscale_flat(sstate, flat,
                                               out_dtype=jnp.float32)
            params, opt_state = opt.step(flat, opt_state, params,
                                         found_inf=new_ss.found_inf)
            return params, opt_state, scaler.update_scale(new_ss), loss

        records = {}
        for s in range(s0, steps):
            x = jax.random.normal(jax.random.PRNGKey(1000 + s), (16, 48))
            params, opt_state, sstate, loss = step(params, opt_state,
                                                   sstate, x)
            records[s] = np.float32(loss).tobytes().hex()
        return records, capture(steps, params, opt_state, scaler=sstate)

    ref_records, _ = run(6)

    # save at step 3, resume from the manager, continue to 6
    mgr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
    head_records, head_state = run(3)
    mgr.save(head_state, blocking=True)

    def fresh():
        params, _, opt, scaler = init()
        return capture(0, params, opt.init(params),
                       scaler=scaler.init_state())

    restored, resumed = resume_or_init(
        CheckpointManager(str(tmp_path / "ckpt"), async_save=False), fresh)
    assert resumed and restored.step == 3
    # bucket state round-trips bit-exact (flat buffers AND the static
    # bucketed spec riding the template)
    for a, b in zip(jax.tree_util.tree_leaves(restored.opt_state),
                    jax.tree_util.tree_leaves(head_state.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert restored.opt_state.spec == head_state.opt_state.spec
    assert restored.opt_state.spec.bucket_bounds == \
        head_state.opt_state.spec.bucket_bounds

    tail_records, _ = run(6, start_state=restored)
    assert {**head_records, **tail_records} == ref_records


def _run(*args, timeout=180):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    return subprocess.run(
        [sys.executable, SCRIPT, *map(str, args)],
        env=env, capture_output=True, text=True, timeout=timeout)


def _loss_lines(path):
    """{step: full line} for the per-step records, plus the final
    summary line (or None)."""
    steps, final = {}, None
    with open(path) as f:
        for line in f:
            line = line.rstrip("\n")
            if line.startswith("S "):
                steps[int(line.split()[1])] = line
            elif line.startswith("F "):
                final = line
    return steps, final


@pytest.mark.parametrize("die_at", [7])
def test_crash_resume_loss_curve_bit_exact(tmp_path, die_at):
    steps = 11
    # 1) uninterrupted reference
    base = _run("--steps", steps, "--root", tmp_path / "ref_ckpt",
                "--losses", tmp_path / "ref.txt")
    assert base.returncode == 0, base.stderr
    ref, ref_final = _loss_lines(tmp_path / "ref.txt")
    assert sorted(ref) == list(range(steps)) and ref_final

    # 2) crashed run: hard os._exit after step die_at's loss record —
    #    the async save in flight dies mid-write (tmp dir left behind)
    crash = _run("--steps", steps, "--root", tmp_path / "ckpt",
                 "--losses", tmp_path / "crash.txt", "--die-at", die_at)
    assert crash.returncode == 13, crash.stderr
    crashed, crashed_final = _loss_lines(tmp_path / "crash.txt")
    assert crashed_final is None  # it really died mid-run
    assert sorted(crashed) == list(range(die_at))

    # 3) resume from the manager (automatic: resume_or_init)
    resume = _run("--steps", steps, "--root", tmp_path / "ckpt",
                  "--losses", tmp_path / "resume.txt")
    assert resume.returncode == 0, resume.stderr
    resumed, resumed_final = _loss_lines(tmp_path / "resume.txt")

    # the resumed run restarted from a checkpointed step < die_at, not
    # from scratch
    first_resumed = min(resumed)
    assert 0 < first_resumed < die_at
    assert sorted(resumed) == list(range(first_resumed, steps))

    # 4) BYTE-identical loss curve: replayed overlap AND new suffix both
    #    match the uninterrupted run exactly (hex-formatted f32 losses)
    for s in range(first_resumed, die_at):
        assert resumed[s] == crashed[s], f"replay diverged at step {s}"
    combined = {**crashed, **resumed}
    assert combined == ref
    # telemetry counters (total_steps) and scaler state continued too
    assert resumed_final == ref_final


def test_sigterm_preemption_flush_and_resume(tmp_path):
    """A real SIGTERM mid-run flushes an emergency checkpoint; the next
    invocation resumes from it and completes."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    losses = tmp_path / "pre.txt"
    proc = subprocess.Popen(
        [sys.executable, SCRIPT, "--steps", "100000",
         "--root", str(tmp_path / "ckpt"), "--losses", str(losses),
         "--preemptable", "--step-sleep", "0.05"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if losses.exists() and len(losses.read_text().splitlines()) >= 4:
                break
            if proc.poll() is not None:
                pytest.fail(f"train process died early: "
                            f"{proc.communicate()[1]}")
            time.sleep(0.1)
        else:
            pytest.fail("train process produced no steps in time")
        proc.send_signal(signal.SIGTERM)
        _, err = proc.communicate(timeout=60)
        assert proc.returncode == 17, err  # clean preempted exit
    finally:
        if proc.poll() is None:
            proc.kill()

    # an emergency checkpoint exists at the preempted step
    import json

    root = tmp_path / "ckpt"
    step_dirs = sorted(d for d in os.listdir(root)
                       if d.startswith("step_") and ".tmp-" not in d)
    assert step_dirs
    with open(root / step_dirs[-1] / "meta.json") as f:
        newest = json.load(f)
    assert newest["emergency"] is True

    # resume completes from there (a short remaining budget)
    target = newest["step"] + 3
    done = _run("--steps", target, "--root", root,
                "--losses", tmp_path / "post.txt")
    assert done.returncode == 0, done.stderr
    resumed, final = _loss_lines(tmp_path / "post.txt")
    assert min(resumed) == newest["step"]
    assert sorted(resumed) == list(range(newest["step"], target))
    assert final is not None
