"""Composed TP x PP x DP gradients vs a dense single-device reference.

This pins the exact math of the multi-chip entry (`__graft_entry__.py`'s
`dryrun_multichip`) as a library-level test — the number-one place a
silent wrong-gradient bug could hide when TP, PP and DP compose on one
mesh.

Two gradient regimes exist under ``check_vma=True`` and this test pins
the manual one:

- differentiating AROUND the ``pvary_full`` (``value_and_grad`` of a
  function that pvary's its own inputs, as ``__graft_entry__`` and
  ``test_tied_embedding_pipeline`` do) returns FULLY-SYNCED grads — the
  transpose of ``pvary`` is a psum over the axes it added; adding
  ``sync_grads_by_spec`` on top double-counts;
- differentiating w.r.t. ALREADY-pvary'd values (what
  ``pipeline_forward_backward`` does internally with the stage params it
  is handed) returns per-shard partials on the replicated axes, and
  ``sync_grads_by_spec`` + the 1/DP mean normalisation are required —
  this file's pattern.

Model: PP pipeline stages, each stage a column-parallel linear (TP-sharded
output dim, gathered) + tanh; batch sharded over the data axis; every
gradient leaf compared elementwise against jax.grad of the equivalent
dense model on one device.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel import run_pipeline  # noqa: F401
from apex_tpu.transformer.pipeline_parallel.schedules import (
    pipeline_forward_backward,
)
from apex_tpu.transformer.pipeline_parallel.utils import (
    mask_to_axis_root,
    pvary_full,
    sync_grads_by_spec,
)
from apex_tpu.transformer.tensor_parallel import column_parallel_linear

PP, DP, TP = 2, 2, 2
N_MICRO = 4
MBS = 4  # global microbatch size (DP shards see MBS // DP)
H = 8


@pytest.fixture
def mesh3d():
    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=TP, pipeline_model_parallel_size_=PP,
    )
    yield parallel_state.get_mesh()
    parallel_state.destroy_model_parallel()


def _dense_stage(w, b, x):
    return jnp.tanh(x @ w.T + b)


def _make_dense_params(key):
    keys = jax.random.split(key, PP)
    return {
        "w": jnp.stack([jax.random.normal(k, (H, H)) * 0.5 for k in keys]),
        "b": jnp.zeros((PP, H)),
    }


def _dense_loss(params, inputs, targets):
    total = 0.0
    for m in range(N_MICRO):
        h = inputs[m]
        for s in range(PP):
            h = _dense_stage(params["w"][s], params["b"][s], h)
        total = total + jnp.mean((h - targets[m]) ** 2)
    return total / N_MICRO


def test_tp_pp_dp_composed_gradients_match_dense(mesh3d):
    pl = parallel_state.PIPELINE_AXIS
    d = parallel_state.DATA_AXIS
    t = parallel_state.TENSOR_AXIS
    all_axes = (pl, d, t)

    params = _make_dense_params(jax.random.PRNGKey(0))
    inputs = jax.random.normal(jax.random.PRNGKey(1), (N_MICRO, MBS, H))
    targets = jax.random.normal(jax.random.PRNGKey(2), (N_MICRO, MBS, H))

    # shardings: stage axis over pipeline; weight out-dim over tensor;
    # microbatch dim over data
    pspec = {"w": P(pl, t, None), "b": P(pl, t)}
    data_spec = P(None, d, None)

    def stage_fn(lp, x):
        y, _, _ = column_parallel_linear(
            x, lp["w"], lp["b"], axis_name=t, gather_output=True
        )
        return jnp.tanh(y)

    def loss_fn(y, tgt):
        # the gathered-output loss is REPLICATED over the tensor axis: mask
        # to t-rank 0 so it seeds its cotangent exactly once (else every
        # grad comes out scaled by TP — see mask_to_axis_root)
        return mask_to_axis_root(jnp.mean((y - tgt) ** 2), t)

    def local(params, inputs, targets):
        # strip the sharded-away leading stage axis (size 1 per device)
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        params = pvary_full(params, all_axes)
        inputs = pvary_full(inputs, all_axes)
        targets = pvary_full(targets, all_axes)
        loss, grads, _ = pipeline_forward_backward(
            stage_fn, loss_fn, params, inputs, targets, axis_name=pl,
        )
        # pipeline_forward_backward differentiates w.r.t. the already-
        # pvary'd stage params it was handed, so its grads are per-shard
        # PARTIALS on the replicated axes: sync them explicitly — psum over
        # every axis the param spec does not shard (here: data only).
        grads = sync_grads_by_spec(grads, pspec, all_axes)
        # grads are sums over data shards of per-shard mean losses; the
        # dense reference means over the full batch -> divide by DP
        grads = jax.tree_util.tree_map(lambda g: g[None] / DP, grads)
        # pipeline_forward_backward already psummed loss over pipeline;
        # undo the t mask with a psum, average over data shards
        loss = jax.lax.pmean(jax.lax.psum(loss, t), d)
        return loss, grads

    loss, grads = jax.jit(
        jax.shard_map(
            local, mesh=mesh3d,
            in_specs=(pspec, data_spec, data_spec),
            out_specs=(P(), pspec),
            check_vma=True,
        )
    )(params, inputs, targets)

    ref_loss, ref_grads = jax.value_and_grad(_dense_loss)(
        params, inputs, targets
    )

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for k in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(grads[k]), np.asarray(ref_grads[k]), atol=1e-5,
            err_msg=f"grad {k}",
        )


def test_composed_forward_only_loss(mesh3d):
    pl = parallel_state.PIPELINE_AXIS
    d = parallel_state.DATA_AXIS
    t = parallel_state.TENSOR_AXIS
    all_axes = (pl, d, t)

    params = _make_dense_params(jax.random.PRNGKey(3))
    inputs = jax.random.normal(jax.random.PRNGKey(4), (N_MICRO, MBS, H))
    targets = jax.random.normal(jax.random.PRNGKey(5), (N_MICRO, MBS, H))
    pspec = {"w": P(pl, t, None), "b": P(pl, t)}

    def stage_fn(lp, x):
        y, _, _ = column_parallel_linear(
            x, lp["w"], lp["b"], axis_name=t, gather_output=True
        )
        return jnp.tanh(y)

    def local(params, inputs, targets):
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        params = pvary_full(params, all_axes)
        inputs = pvary_full(inputs, all_axes)
        targets = pvary_full(targets, all_axes)
        loss, _, _ = pipeline_forward_backward(
            stage_fn,
            lambda y, tgt: mask_to_axis_root(jnp.mean((y - tgt) ** 2), t),
            params, inputs, targets, axis_name=pl, forward_only=True,
        )
        return jax.lax.pmean(jax.lax.psum(loss, t), d)

    loss = jax.jit(
        jax.shard_map(
            local, mesh=mesh3d,
            in_specs=(pspec, P(None, d, None), P(None, d, None)),
            out_specs=P(),
            check_vma=True,
        )
    )(params, inputs, targets)

    ref = _dense_loss(params, inputs, targets)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)
