"""Speculative decoding + carried sampling (ISSUE-13).

The two halves of pushing decode below one model pass per token, held
to the same oracle discipline as everything before them:

- **carried sampling** (``serving.sampling``) — temperature/top-k/top-p
  with the stateless ``(seed, rid, position)`` hash-counter PRNG:
  greedy stays bit-identical to argmax, sampled decode is BYTE-
  identical to the seeded dense reference
  (``reference_sample_decode``), and draws survive preemption replay
  because they are keyed by position, not by an RNG state chain;
- **speculative decoding** (``serving.spec_decode``) — on-device n-gram
  drafting over each slot's own history, one chunk-shaped target pass
  verifying ``spec_k + 1`` positions, in-jit longest-matched-prefix
  accept, and page-bookkeeping rollback of the rejected tail through
  the SAME ``Scheduler.rollback_kv`` helper the PR-12 cache-pressure
  path uses (seeded-violation red test included);
- the robustness interplay: invariants after every step of a chaos
  trace with speculation + sampling armed, zero page leaks, survivor
  token identity, a quarantined slot's drafted pages never published,
  and admission/router billing UNCHANGED (worst-case offered tokens —
  speculation can only improve feasibility, never overcommit).
"""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.serving import (
    Request,
    RequestStatus,
    SamplingParams,
    Scheduler,
    ServingEngine,
    ngram_propose,
    reference_decode,
    reference_sample_decode,
    sample_tokens,
)
from apex_tpu.transformer.testing import GPTConfig, init_gpt_params


def _tiny_cfg(dtype=jnp.float32, max_pos=64):
    return GPTConfig(
        num_layers=2, hidden_size=64, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=max_pos,
        hidden_dropout=0.0, attention_dropout=0.0,
        params_dtype=jnp.float32, compute_dtype=dtype)


@pytest.fixture(scope="module", autouse=True)
def _shed_compile_caches():
    """Many small engine programs compile in this module; shed the
    executables the preceding files accumulated (the full-suite CPU
    lane runs close to its memory ceiling)."""
    jax.clear_caches()
    yield
    jax.clear_caches()


@pytest.fixture(scope="module")
def tiny_model():
    cfg = _tiny_cfg()
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    params["embedding"]["position"] = params["embedding"]["position"] * 40.0
    return cfg, params


@pytest.fixture(scope="module")
def cyclic_model():
    """Position-independent weights: greedy decode falls into a cycle,
    so the n-gram draft actually accepts — the accept-rate half of the
    acceptance criteria needs repetition to exist."""
    cfg = _tiny_cfg(max_pos=128)
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    params["embedding"]["position"] = params["embedding"]["position"] * 0.0
    return cfg, params


# ---------------------------------------------------------------------------
# sampling: the carried stateless PRNG
# ---------------------------------------------------------------------------

def _policy_arrays(sp: SamplingParams, rid: int, pos: int):
    return (jnp.asarray([sp.temperature], jnp.float32),
            jnp.asarray([sp.top_k], jnp.int32),
            jnp.asarray([sp.top_p], jnp.float32),
            jnp.asarray([sp.seed], jnp.int32),
            jnp.asarray([rid], jnp.int32),
            jnp.asarray([pos], jnp.int32))


def test_sample_tokens_greedy_is_argmax():
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(5, 33)), jnp.float32)
    B = 5
    out = sample_tokens(logits, jnp.zeros(B), jnp.zeros(B, jnp.int32),
                        jnp.ones(B), jnp.zeros(B, jnp.int32),
                        jnp.arange(B, dtype=jnp.int32),
                        jnp.arange(B, dtype=jnp.int32))
    assert (np.asarray(out) == np.asarray(jnp.argmax(logits, -1))).all()


def test_sample_tokens_deterministic_and_row_independent():
    """The identity precondition: a batched row draws exactly what the
    [1, V] reference row draws (sorting/cumsum/argmax are all
    row-local), and the draw is a pure function of its key."""
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(4, 50)), jnp.float32)
    t = jnp.full(4, 0.8)
    k = jnp.asarray([0, 7, 0, 3], jnp.int32)
    p = jnp.asarray([1.0, 0.9, 0.7, 1.0], jnp.float32)
    s = jnp.asarray([3, 3, 5, 5], jnp.int32)
    r = jnp.asarray([10, 11, 10, 11], jnp.int32)
    pos = jnp.asarray([2, 2, 9, 9], jnp.int32)
    a = sample_tokens(logits, t, k, p, s, r, pos)
    b = sample_tokens(logits, t, k, p, s, r, pos)
    assert (np.asarray(a) == np.asarray(b)).all()
    for i in range(4):
        row = sample_tokens(logits[i:i + 1], t[i:i + 1], k[i:i + 1],
                            p[i:i + 1], s[i:i + 1], r[i:i + 1],
                            pos[i:i + 1])
        assert int(row[0]) == int(a[i])


def test_sample_tokens_respects_topk_and_topp():
    rng = np.random.default_rng(2)
    row = rng.normal(size=(1, 64)).astype(np.float32)
    top3 = set(np.argsort(-row[0])[:3].tolist())
    # one batched call = 150 independent positions of the same row
    R = 150
    logits = jnp.asarray(np.repeat(row, R, axis=0))
    toks = sample_tokens(logits, jnp.full(R, 1.5),
                         jnp.full(R, 3, jnp.int32), jnp.ones(R),
                         jnp.zeros(R, jnp.int32),
                         jnp.zeros(R, jnp.int32),
                         jnp.arange(R, dtype=jnp.int32))
    seen = set(np.asarray(toks).tolist())
    assert seen <= top3 and len(seen) > 1
    # a sharply peaked distribution under small top_p is greedy
    sharp = jnp.zeros((20, 64)).at[:, 5].add(10.0)
    toks = sample_tokens(sharp, jnp.full(20, 1.0),
                         jnp.zeros(20, jnp.int32),
                         jnp.full(20, 0.5, jnp.float32),
                         jnp.zeros(20, jnp.int32),
                         jnp.zeros(20, jnp.int32),
                         jnp.arange(20, dtype=jnp.int32))
    assert (np.asarray(toks) == 5).all()
    # top_k=1 is greedy at any temperature
    tok = sample_tokens(jnp.asarray(row), jnp.full(1, 2.0),
                        jnp.asarray([1], jnp.int32), jnp.ones(1),
                        jnp.zeros(1, jnp.int32), jnp.zeros(1, jnp.int32),
                        jnp.asarray([0], jnp.int32))
    assert int(tok[0]) == int(np.argmax(row[0]))


def test_topk_prefilter_matches_full_sort_per_row():
    """ISSUE-14 satellite: the lax.top_k prefilter and the full-sort
    fallback must be bitwise interchangeable per row — a batch whose
    OTHER rows force the deep path returns identical tokens for a row
    the prefix already covered (the engine-vs-reference byte-identity
    contract cannot depend on batch composition)."""
    from apex_tpu.serving.sampling import TOP_FILTER_WIDTH, _thresholds

    rng = np.random.default_rng(11)
    V = 4 * TOP_FILTER_WIDTH
    logits = jnp.asarray(rng.normal(size=(3, V)).astype(np.float32))
    temps = jnp.full(3, 1.0)
    # row 0: ordinary nucleus config (prefix covers it); row 1: top_k
    # beyond the prefix width (forces the fallback); row 2: near-flat
    # logits at high temperature with p close to 1 (top-width mass
    # cannot reach p — the other fallback trigger)
    flat_row = jnp.asarray(
        0.01 * rng.normal(size=(V,)).astype(np.float32))
    logits = logits.at[2].set(flat_row)
    top_ks = jnp.asarray([8, TOP_FILTER_WIDTH + 7, 0], jnp.int32)
    top_ps = jnp.asarray([0.9, 1.0, 0.999], jnp.float32)
    seeds = jnp.zeros(3, jnp.int32)
    rids = jnp.asarray([4, 5, 6], jnp.int32)
    pos = jnp.asarray([10, 11, 12], jnp.int32)

    # rows 1 and 2 genuinely trigger the deep path; row 0 does not
    scaled = logits / temps[:, None]
    _, _, covered = _thresholds(
        jax.lax.top_k(scaled, TOP_FILTER_WIDTH)[0], scaled, top_ks,
        top_ps)
    assert np.asarray(covered).tolist() == [True, False, False]

    batched = np.asarray(sample_tokens(
        logits, temps, top_ks, top_ps, seeds, rids, pos))
    for i in range(3):
        single = np.asarray(sample_tokens(
            logits[i:i + 1], temps[i:i + 1], top_ks[i:i + 1],
            top_ps[i:i + 1], seeds[i:i + 1], rids[i:i + 1],
            pos[i:i + 1]))
        assert batched[i] == single[0], f"row {i} depends on the batch"
    # the top_k>width row still respects its filter
    topk_set = set(np.argsort(-np.asarray(logits[1]))
                   [:TOP_FILTER_WIDTH + 7].tolist())
    assert int(batched[1]) in topk_set


def test_sample_tokens_key_separation():
    """Different (seed | rid | position) keys decorrelate draws — the
    carried-PRNG contract that makes two same-seed requests sample
    independent streams."""
    rng = np.random.default_rng(3)
    row = (rng.normal(size=(1, 40)) * 0.1).astype(np.float32)
    R = 24
    logits = jnp.asarray(np.repeat(row, R, axis=0))

    def draws(seed, rid, base_pos):
        return np.asarray(sample_tokens(
            logits, jnp.full(R, 1.5), jnp.zeros(R, jnp.int32),
            jnp.ones(R), jnp.full(R, seed, jnp.int32),
            jnp.full(R, rid, jnp.int32),
            base_pos + jnp.arange(R, dtype=jnp.int32))).tolist()

    base = draws(0, 0, 0)
    assert draws(0, 0, 0) == base              # pure function of the key
    assert draws(1, 0, 0) != base              # seed lane
    assert draws(0, 1, 0) != base              # rid lane
    assert draws(0, 0, 100) != base            # position lane
    assert len(set(base)) > 1                  # actually random-ish


def test_sampling_params_validation():
    with pytest.raises(ValueError, match="temperature"):
        SamplingParams(temperature=-0.1)
    with pytest.raises(ValueError, match="top_p"):
        SamplingParams(top_p=0.0)
    with pytest.raises(ValueError, match="top_k"):
        SamplingParams(top_k=-1)
    assert SamplingParams().is_greedy
    assert not SamplingParams(temperature=0.5).is_greedy


def test_engine_spec_knob_validation(tiny_model):
    """Bad speculation knobs fail at construction with a clear error,
    not deep inside the first traced step."""
    cfg, params = tiny_model
    with pytest.raises(ValueError, match="spec_ngram"):
        ServingEngine(cfg, params, n_slots=1, num_pages=4,
                      spec_k=2, spec_ngram=0)
    with pytest.raises(ValueError, match="spec_ngram"):
        ServingEngine(cfg, params, n_slots=1, num_pages=4,
                      spec_k=2, spec_ngram=5000)
    with pytest.raises(ValueError, match="spec_k"):
        ServingEngine(cfg, params, n_slots=1, num_pages=4,
                      spec_k=5000)


# ---------------------------------------------------------------------------
# n-gram drafting
# ---------------------------------------------------------------------------

def test_ngram_propose_matches_most_recent_occurrence():
    hist = jnp.asarray([[1, 2, 3, 4, 1, 2, 3, 9, 1, 2, 0, 0, 0]],
                       jnp.int32)  # known: 1 2 3 4 1 2 3 9 1 2
    drafts, n = ngram_propose(hist, jnp.asarray([10]), k=3, n=2)
    # tail (1,2) last matched at s=4 -> continuation 3, 9, 1
    assert list(np.asarray(drafts[0])) == [3, 9, 1]
    assert int(n[0]) == 3


def test_ngram_propose_no_match_and_short_history():
    hist = jnp.asarray([[1, 2, 3, 4, 5, 0, 0]], jnp.int32)
    drafts, n = ngram_propose(hist, jnp.asarray([5]), k=3, n=2)
    assert int(n[0]) == 0
    # history shorter than the n-gram: no drafting, no crash
    drafts, n = ngram_propose(hist, jnp.asarray([2]), k=3, n=3)
    assert int(n[0]) == 0
    # disabled row (len 0)
    drafts, n = ngram_propose(hist, jnp.asarray([0]), k=3, n=2)
    assert int(n[0]) == 0


def test_ngram_propose_caps_at_history_end():
    # tail (7, 8) matches at s=0; the continuation (9, 7, 8) runs to
    # the END of the known history and stops there — never past it
    hist = jnp.asarray([[7, 8, 9, 7, 8, 0]], jnp.int32)
    drafts, n = ngram_propose(hist, jnp.asarray([5]), k=4, n=2)
    assert int(n[0]) == 3
    assert list(np.asarray(drafts[0])) == [9, 7, 8, 0]  # zero-padded
    # shrinking the window: k caps the proposal
    drafts, n = ngram_propose(hist, jnp.asarray([5]), k=2, n=2)
    assert int(n[0]) == 2
    assert list(np.asarray(drafts[0])) == [9, 7]


# ---------------------------------------------------------------------------
# rollback_kv: the shared un-write helper (+ seeded-violation red test)
# ---------------------------------------------------------------------------

def _sched_with_slot(n_tokens, spec_k=0, page_size=16):
    from apex_tpu.serving import PagedKVSpec

    spec = PagedKVSpec(1, 4, 64, page_size=page_size, num_pages=8,
                       pages_per_seq=4)
    sched = Scheduler(spec, 1, max_prompt_len=48, spec_k=spec_k)
    req = Request(prompt=list(range(1, 9)), max_new_tokens=40)
    sched.submit(req)
    sched.admit()
    run = sched.slots[0]
    run.pos = n_tokens
    run.pages = [sched.allocator.alloc()
                 for _ in range(spec.pages_for(max(n_tokens, 1)))]
    return sched, run


def test_rollback_kv_frees_speculative_tail_pages():
    """The spec-decode rejection path: pages allocated for the
    worst-case draft write-ahead are returned once the accepted run is
    known, and the accounting still balances."""
    sched, run = _sched_with_slot(4)
    # simulate worst-case paging for pos + 1 + k = 4 + 1 + 36: grab 2
    # extra pages past the cursor's page
    extra = [sched.allocator.alloc(), sched.allocator.alloc()]
    run.pages.extend(extra)
    free_before = sched.allocator.free_count
    sched.rollback_kv(0, run, run.pos)
    assert len(run.pages) == sched.spec.pages_for(run.pos)
    assert sched.allocator.free_count == free_before + 2
    assert not sched.take_dirty_slots()  # cursor unmoved: no resync
    sched.check_invariants()


def test_rollback_kv_rewinds_cursor_and_marks_dirty():
    sched, run = _sched_with_slot(40)
    assert len(run.pages) == 3
    sched.rollback_kv(0, run, 16, keep_pages=1)
    assert run.pos == 16 and len(run.pages) == 1
    assert sched.take_dirty_slots() == {0}
    sched.check_invariants()


def test_rollback_kv_seeded_violation_red():
    """Red test: un-writing WITHOUT the helper (dropping the pages
    from the slot's list but never releasing the holds) leaks — the
    refcount cross-check in check_invariants must catch it."""
    sched, run = _sched_with_slot(40)
    run.pages = run.pages[:1]  # the bug: no allocator.free / helper
    with pytest.raises(AssertionError, match="refcount|reader"):
        sched.check_invariants()


def test_release_tail_red_on_double_release():
    from apex_tpu.serving import PageAllocator

    alloc = PageAllocator(6)
    pages = [alloc.alloc() for _ in range(3)]
    kept = alloc.release_tail(pages, 1)
    assert kept == pages[:1]
    with pytest.raises(ValueError, match="double-free|foreign"):
        alloc.release_tail(pages, 1)  # tail holds already dropped
    with pytest.raises(ValueError, match="keep"):
        alloc.release_tail(pages, -1)


# ---------------------------------------------------------------------------
# greedy spec-decode: the lossless contract
# ---------------------------------------------------------------------------

def _mk_staggered(cfg, seed=7, lens=(14, 11, 13, 9), max_new=8):
    rng = np.random.default_rng(seed)
    return [
        Request(prompt=[int(t) for t in
                        rng.integers(0, cfg.vocab_size, size=L)],
                max_new_tokens=max_new, arrival_step=2 * i)
        for i, L in enumerate(lens)
    ]


@pytest.fixture(scope="module")
def staggered_refs(tiny_model):
    """Dense greedy references for the staggered trace, computed once
    (reference_decode recompiles per prefix length — the expensive
    half of every identity test)."""
    cfg, params = tiny_model
    return [reference_decode(cfg, params, r.prompt, r.max_new_tokens)
            for r in _mk_staggered(cfg)]


def test_spec_greedy_token_identity_staggered(tiny_model,
                                              staggered_refs):
    """spec_k > 0 greedy == plain greedy == dense reference across the
    staggered continuous-batching trace on a tiny pool (shared slots,
    preemption pressure)."""
    cfg, params = tiny_model
    refs = staggered_refs
    for k in (1, 3):
        reqs = _mk_staggered(cfg)
        eng = ServingEngine(cfg, params, n_slots=2, num_pages=6,
                            max_prompt_len=16, spec_k=k)
        out = eng.generate(reqs, max_steps=2000)
        eng.scheduler.check_invariants()
        for i, r in enumerate(reqs):
            assert out[r.rid] == refs[i], (k, i)
            assert r.status is RequestStatus.COMPLETED
        assert eng.scheduler.allocator.used_count == 0


def test_spec_greedy_identity_under_preemption(tiny_model):
    """Chaos-stolen allocations force preemption mid-speculation: the
    replay path must still reproduce plain greedy decode exactly (the
    drafted/rolled-back state never leaks into the replay). Oracle:
    the undisturbed spec-off engine over the same trace — itself
    pinned to the dense reference by the staggered identity test and
    the `spec_greedy_identity` CLI leg."""
    from apex_tpu.resilience import ServingChaos

    cfg, params = tiny_model
    base = ServingEngine(cfg, params, n_slots=2, num_pages=12,
                         max_prompt_len=16, prefix_cache=False)
    ref_reqs = _mk_staggered(cfg)
    ref_out = base.generate(ref_reqs, max_steps=2000)
    reqs = _mk_staggered(cfg)
    chaos = ServingChaos().fail_allocs(4)
    eng = ServingEngine(cfg, params, n_slots=2, num_pages=5,
                        max_prompt_len=16, spec_k=3, chaos=chaos)
    out = eng.generate(reqs, max_steps=2000)
    eng.scheduler.check_invariants()
    assert sum(r.preemptions for r in reqs) > 0
    for ref_r, r in zip(ref_reqs, reqs):
        assert out[r.rid] == ref_out[ref_r.rid], r.rid
    assert eng.scheduler.allocator.used_count == 0


def test_spec_accepts_and_shortens_on_repetitive_trace(cyclic_model):
    """The point of the tentpole: on repetition, accepted drafts push
    decode tokens/step above 1 and the trace finishes in fewer engine
    steps — while staying token-identical."""
    cfg, params = cyclic_model
    rng = np.random.default_rng(3)
    prompt = [int(t) for t in rng.integers(0, cfg.vocab_size, size=8)]
    ref = reference_decode(cfg, params, prompt, 24)
    steps = {}
    for k in (0, 4):
        req = Request(prompt=list(prompt), max_new_tokens=24)
        eng = ServingEngine(cfg, params, n_slots=2, num_pages=12,
                            max_prompt_len=64, prefill_chunk=4,
                            spec_k=k)
        out = eng.generate([req], max_steps=500)
        eng.scheduler.check_invariants()
        assert out[req.rid] == ref, k
        assert eng.scheduler.allocator.used_count == 0
        steps[k] = eng.last_stats["steps"]
        if k > 0:
            st = eng.last_stats
            assert st["drafted_tokens"] > 0
            assert st["accepted_tokens"] > 0
            assert st["accept_rate"] > 0
            assert st["tokens_per_step"] > 1.0
            assert st["spec_k"] == k
        else:
            assert eng.last_stats["tokens_per_step"] == 1.0
    assert steps[4] < steps[0]


def test_spec_greedy_identity_with_prefix_cache(cyclic_model):
    """Speculation composes with the radix prefix cache: a warm pass
    (cache hits + COW forks on the shared head) under spec_k > 0 stays
    byte-identical to the cold dense reference, with zero leaks."""
    cfg, params = cyclic_model
    rng = np.random.default_rng(11)
    head = [int(t) for t in rng.integers(0, cfg.vocab_size, size=16)]
    prompts = [head + [int(t) for t in
                       rng.integers(0, cfg.vocab_size, size=4)],
               list(head)]
    eng = ServingEngine(cfg, params, n_slots=2, num_pages=16,
                        max_prompt_len=48, prefill_chunk=4, spec_k=3)
    cold = [Request(prompt=list(p), max_new_tokens=6) for p in prompts]
    out_cold = eng.generate(cold, max_steps=2000)
    warm = [Request(prompt=list(p), max_new_tokens=6) for p in prompts]
    out_warm = eng.generate(warm, max_steps=2000)
    eng.scheduler.check_invariants()
    st = eng.last_stats["prefix_cache"]
    assert st["hits"] == len(prompts)
    for p, c, w in zip(prompts, cold, warm):
        ref = reference_decode(cfg, params, p, 6)
        assert out_cold[c.rid] == ref
        assert out_warm[w.rid] == ref
    assert eng.scheduler.allocator.used_count == 0


def test_spec_respects_eos_and_max_new(cyclic_model):
    """A mid-burst EOS (or max_new) truncates the accepted run: the
    surplus accepted tokens are discarded with the completed request,
    never published or fed back."""
    cfg, params = cyclic_model
    rng = np.random.default_rng(13)
    prompt = [int(t) for t in rng.integers(0, cfg.vocab_size, size=8)]
    ref = reference_decode(cfg, params, prompt, 16)
    # pick the cycle token as EOS so it fires mid-repetition (when
    # speculation is accepting whole bursts)
    eos = ref[-1]
    ref_eos = reference_decode(cfg, params, prompt, 16, eos_id=eos)
    req = Request(prompt=list(prompt), max_new_tokens=16, eos_id=eos)
    eng = ServingEngine(cfg, params, n_slots=1, num_pages=12,
                        max_prompt_len=48, spec_k=4)
    out = eng.generate([req], max_steps=500)
    eng.scheduler.check_invariants()
    assert out[req.rid] == ref_eos
    assert req.status is RequestStatus.COMPLETED
    assert eng.scheduler.allocator.used_count == 0
    # surplus accepted tokens truncated at EOS must not inflate the
    # gated metrics: delivered decode tokens = generated minus the
    # prefill-completion first token, and the summary reconciles
    st = eng.last_stats
    assert st["generated_tokens"] == len(out[req.rid])
    assert st["decode_tokens"] == len(out[req.rid]) - 1
    assert st["decode_tokens"] == \
        st["decode_slot_steps"] + st["accepted_tokens"]


# ---------------------------------------------------------------------------
# sampled decode: the seeded oracle
# ---------------------------------------------------------------------------

def _mk_sampled(cfg, rid_base=41_000):
    sps = [SamplingParams(temperature=0.9, top_k=20, seed=11),
           SamplingParams(temperature=1.2, top_p=0.85, seed=42),
           None,  # greedy rider in the same batch
           SamplingParams(temperature=0.7, top_k=12, top_p=0.9, seed=7)]
    rng = np.random.default_rng(5)
    return [Request(prompt=[int(t) for t in
                            rng.integers(0, cfg.vocab_size, size=L)],
                    max_new_tokens=8, arrival_step=i, sampling=sp,
                    rid=rid_base + i)
            for i, (L, sp) in enumerate(zip((12, 9, 11, 8), sps))]


@pytest.fixture(scope="module")
def sampled_refs(tiny_model):
    """Seeded dense references for the mixed sampled/greedy trace —
    shared (draws key on (seed, rid, position) only, so any engine
    running the same rids reproduces them)."""
    cfg, params = tiny_model
    return {r.rid: reference_sample_decode(
        cfg, params, r.prompt, r.max_new_tokens,
        sampling=r.sampling, rid=r.rid) for r in _mk_sampled(cfg)}


def test_sampled_decode_byte_identical_to_reference(tiny_model,
                                                    sampled_refs):
    """Engine sampled decode == reference_sample_decode, byte for
    byte, with speculation off AND on — mixed sampled/greedy batch,
    tiny pool."""
    cfg, params = tiny_model
    refs = sampled_refs
    for k in (0, 3):
        reqs = _mk_sampled(cfg)
        eng = ServingEngine(cfg, params, n_slots=2, num_pages=6,
                            max_prompt_len=16, prefill_chunk=3,
                            spec_k=k)
        out = eng.generate(reqs, max_steps=2000)
        eng.scheduler.check_invariants()
        for r in reqs:
            assert out[r.rid] == refs[r.rid], (k, r.rid)
        assert eng.scheduler.allocator.used_count == 0


def test_sampled_decode_survives_preemption_replay(tiny_model,
                                                   sampled_refs):
    """The carried-PRNG point: a preempted sampled request's replay
    regenerates the SAME draws (position-keyed, not state-chained), so
    its final tokens match the undisturbed reference."""
    from apex_tpu.resilience import ServingChaos

    cfg, params = tiny_model
    refs = sampled_refs
    reqs = _mk_sampled(cfg)
    chaos = ServingChaos().fail_allocs(4)
    eng = ServingEngine(cfg, params, n_slots=2, num_pages=5,
                        max_prompt_len=16, spec_k=2, chaos=chaos)
    out = eng.generate(reqs, max_steps=2000)
    eng.scheduler.check_invariants()
    assert sum(r.preemptions for r in reqs) > 0
    for r in reqs:
        assert out[r.rid] == refs[r.rid], r.rid
    assert eng.scheduler.allocator.used_count == 0


def test_sampled_spec_equals_plain_sampled(cyclic_model):
    """Spec-decode under SAMPLING is sequence-identical to plain
    sampled decode (the reparameterized rejection rule: acceptance =
    match against the position's own deterministic draw), even while
    drafts are accepted."""
    cfg, params = cyclic_model
    rng = np.random.default_rng(17)
    prompt = [int(t) for t in rng.integers(0, cfg.vocab_size, size=8)]
    sp = SamplingParams(temperature=0.3, top_k=2, seed=3)
    req = Request(prompt=list(prompt), max_new_tokens=16,
                  sampling=sp, rid=43_000)
    eng = ServingEngine(cfg, params, n_slots=1, num_pages=12,
                        max_prompt_len=64, spec_k=4)
    out = eng.generate([req], max_steps=500)[req.rid]
    st = eng.last_stats
    eng.scheduler.check_invariants()
    assert eng.scheduler.allocator.used_count == 0
    # the reference IS plain sequential sampling (the k=0 engine is
    # pinned byte-identical to it elsewhere) — spec-on must match it
    # even while drafts are being accepted
    ref = reference_sample_decode(cfg, params, prompt, 16, sampling=sp,
                                  rid=43_000)
    assert out == ref
    # low temperature + top_k=2 on a cyclic model repeats enough for
    # the n-gram draft to land accepts
    assert st["accepted_tokens"] > 0


# ---------------------------------------------------------------------------
# billing: speculation never changes admission / router accounting
# ---------------------------------------------------------------------------

def test_admission_billing_unchanged_by_spec(tiny_model):
    """Satellite contract: admission and the fleet router keep billing
    worst-case offered tokens (one per slot-step) — a spec engine's
    probe/queued-token estimates equal the k=0 engine's, so
    speculation can only improve feasibility, never overcommit."""
    from apex_tpu.serving import AdmissionConfig

    cfg, params = tiny_model
    rng = np.random.default_rng(19)
    prompt = [int(t) for t in rng.integers(0, cfg.vocab_size, size=10)]

    def probe_est(spec_k):
        eng = ServingEngine(cfg, params, n_slots=2, num_pages=8,
                            max_prompt_len=16, prefill_chunk=2,
                            spec_k=spec_k,
                            admission=AdmissionConfig(max_queue=8))
        for j in range(3):
            eng.try_submit(Request(prompt=list(prompt),
                                   max_new_tokens=4))
        reason, est = eng.probe(Request(prompt=list(prompt),
                                        max_new_tokens=4))
        return reason, est, eng._queued_tokens()

    r0, est0, q0 = probe_est(0)
    r4, est4, q4 = probe_est(4)
    assert r0 is None and r4 is None
    assert est0 == est4
    assert q0 == q4


# ---------------------------------------------------------------------------
# chaos: speculation + sampling under fire
# ---------------------------------------------------------------------------

def test_chaos_property_trace_spec_and_sampling(tiny_model):
    """The chaos satellite: random admit/evict/preempt/poison/prefix-
    eviction churn with speculation AND sampling armed —
    ``check_invariants()`` after EVERY step, zero page leaks, and
    SURVIVOR token identity against a spec-off engine over the same
    requests (itself pinned to the dense references by the tests
    above). The poisoned request must quarantine alone."""
    from apex_tpu.resilience import ServingChaos

    cfg, params = tiny_model

    def mk(seed):
        rng = np.random.default_rng(seed)
        out = []
        for j, L in enumerate(rng.integers(4, 14, size=5)):
            sp = (SamplingParams(temperature=0.8, top_k=16,
                                 seed=int(rng.integers(0, 99)))
                  if j % 2 else None)
            out.append(Request(
                prompt=[int(t) for t in rng.integers(0, 128, size=int(L))],
                max_new_tokens=5, arrival_step=int(rng.integers(0, 8)),
                sampling=sp, rid=50_000 + 100 * seed + j))
        return out

    for seed in (5,):
        base = ServingEngine(cfg, params, n_slots=2, num_pages=12,
                             max_prompt_len=16, spec_k=0,
                             prefix_cache=False)
        ref_reqs = mk(seed)
        ref_out = base.generate(ref_reqs, max_steps=3000)
        reqs = mk(seed)
        victim = reqs[2]
        chaos = (ServingChaos()
                 .fail_allocs(3)
                 .evict_prefix_cache(2)
                 .poison_request(victim.rid))
        eng = ServingEngine(cfg, params, n_slots=2, num_pages=6,
                            max_prompt_len=16, prefill_chunk=3,
                            spec_k=3, chaos=chaos)
        pending = sorted(reqs, key=lambda r: (r.arrival_step, r.rid))
        step = 0
        while pending or not eng.scheduler.idle:
            while pending and pending[0].arrival_step <= step:
                eng.try_submit(pending.pop(0))
            if not eng.scheduler.idle:
                eng.run_step()
            eng.scheduler.check_invariants()
            step += 1
            assert step < 3000, "chaos trace did not terminate"
        assert eng.scheduler.allocator.used_count == 0
        assert victim.status is RequestStatus.FAILED
        assert (victim.failure or {}).get("kind") == "nonfinite_logits"
        for ref_r, r in zip(ref_reqs, reqs):
            if r is victim:
                continue
            assert r.status is RequestStatus.COMPLETED, (seed, r.rid)
            assert list(r.out_tokens) == ref_out[ref_r.rid], \
                (seed, r.rid)


def test_quarantined_drafted_tokens_never_publish(cyclic_model):
    """Satellite: a quarantined slot's drafted/generated tokens must
    never enter the prefix cache. Decode-phase pages are never
    published by design; this pins the composed behaviour — poison a
    request AFTER its prompt published, while speculation is
    accepting, and assert the cache serves later requests the clean
    prompt K/V only (byte-identical decode) with zero leaks."""
    from apex_tpu.resilience import ServingChaos

    cfg, params = cyclic_model
    rng = np.random.default_rng(21)
    prompt = [int(t) for t in rng.integers(0, cfg.vocab_size, size=20)]
    victim = Request(prompt=list(prompt), max_new_tokens=12)
    # poison fires at step 4: prompt (20 tokens / chunk 16) done by
    # step 2, so the victim is mid-decode with drafts in flight
    chaos = ServingChaos().poison_request(victim.rid, at_step=4)
    eng = ServingEngine(cfg, params, n_slots=1, num_pages=12,
                        max_prompt_len=64, prefill_chunk=16, spec_k=4,
                        chaos=chaos)
    eng.generate([victim], max_steps=200)
    assert victim.status is RequestStatus.FAILED
    eng.scheduler.check_invariants()
    # the published entries cover at most the PROMPT; nothing the
    # quarantined decode drafted/emitted is indexed
    assert eng.prefix_cache.match_len(
        prompt + list(victim.out_tokens) + [1]) <= len(prompt)
    retry = Request(prompt=list(prompt), max_new_tokens=12)
    out = eng.generate([retry], max_steps=200)
    ref = reference_decode(cfg, params, prompt, 12)
    assert out[retry.rid] == ref
    assert eng.scheduler.allocator.used_count == 0


# ---------------------------------------------------------------------------
# summary / fleet plumbing
# ---------------------------------------------------------------------------

def test_summarize_spec_fields_reconcile(cyclic_model):
    cfg, params = cyclic_model
    rng = np.random.default_rng(23)
    prompt = [int(t) for t in rng.integers(0, cfg.vocab_size, size=8)]
    req = Request(prompt=list(prompt), max_new_tokens=24)
    eng = ServingEngine(cfg, params, n_slots=1, num_pages=12,
                        max_prompt_len=64, spec_k=4)
    eng.generate([req], max_steps=500)
    st = eng.last_stats
    assert st["spec_k"] == 4
    assert st["accepted_tokens"] <= st["drafted_tokens"]
    assert st["accept_rate"] == pytest.approx(
        st["accepted_tokens"] / st["drafted_tokens"], abs=1e-3)
    # decode tokens = one per decode slot-step + every accepted draft
    assert st["decode_tokens"] == \
        st["decode_slot_steps"] + st["accepted_tokens"]
    assert st["tokens_per_step"] == pytest.approx(
        st["decode_tokens"] / st["decode_slot_steps"], abs=1e-3)
    assert st["generated_tokens"] == 24


def test_fleet_summary_aggregates_spec_counters(cyclic_model):
    from apex_tpu.serving import ReplicaFleet

    cfg, params = cyclic_model
    rng = np.random.default_rng(29)
    reqs = [Request(prompt=[int(t) for t in
                            rng.integers(0, cfg.vocab_size, size=8)],
                    max_new_tokens=16, arrival_step=i)
            for i in range(4)]
    fleet = ReplicaFleet(cfg, params, n_replicas=2, n_slots=2,
                         num_pages=12, max_prompt_len=64, spec_k=4)
    fleet.generate(reqs, max_steps=2000)
    st = fleet.last_stats
    assert st["drafted_tokens"] > 0
    assert st["accepted_tokens"] > 0
    assert st["spec_accept_rate"] > 0
    assert st["decode_tokens_per_step"] > 1.0
    per = st["per_replica"]
    assert sum(v["drafted_tokens"] for v in per.values()) \
        == st["drafted_tokens"]
    assert fleet.page_leaks() == 0


def test_spec_engine_audits_clean(tiny_model):
    """All three jitted programs (1-token, chunked prefill,
    speculative) pass the PR-4 static auditor with telemetry armed."""
    from apex_tpu.telemetry import RingBufferRecorder

    cfg, params = tiny_model
    eng = ServingEngine(cfg, params, n_slots=2, num_pages=8,
                        max_prompt_len=16, prefill_chunk=3, spec_k=2,
                        telemetry_every=4, sink=RingBufferRecorder())
    report = eng.audit()
    assert report.ok


def test_spec_recover_from_replays_token_identical(cyclic_model):
    """Engine kill mid-speculation + recover_from: survivors replay to
    completion token-identical (generated tokens ride the replay
    prompt; the spec/sampling state is carried, not lost)."""
    from apex_tpu.resilience import ChaosError, ServingChaos

    cfg, params = cyclic_model
    rng = np.random.default_rng(31)
    reqs = [Request(prompt=[int(t) for t in
                            rng.integers(0, cfg.vocab_size, size=8)],
                    max_new_tokens=16, arrival_step=i)
            for i in range(3)]
    refs = {r.rid: reference_decode(cfg, params, r.prompt,
                                    r.max_new_tokens) for r in reqs}
    chaos = ServingChaos().kill_engine_at(6)
    eng = ServingEngine(cfg, params, n_slots=2, num_pages=12,
                        max_prompt_len=64, spec_k=3, chaos=chaos)
    with pytest.raises(ChaosError):
        eng.generate(list(reqs), max_steps=2000)
    eng2, survivors = ServingEngine.recover_from(eng)
    eng2.generate(survivors, max_steps=2000)
    eng2.scheduler.check_invariants()
    for r in reqs:
        assert list(r.out_tokens) == refs[r.rid]
        assert r.status is RequestStatus.COMPLETED
    assert eng2.scheduler.allocator.used_count == 0


# ---------------------------------------------------------------------------
# CI wiring: serving_check legs, compare_bench gates, smoke artifact
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("leg", ["spec_greedy_identity",
                                 "sampled_seeded_identity"])
def test_serving_check_spec_legs_pass(leg):
    import tools.serving_check as sc

    assert sc.main(["--self", "--check", leg, "--json"]) == 0


def test_compare_bench_gates_spec_decode_leg():
    from tools.compare_bench import compare, extract_legs

    base = {"spec_decode": {"goodput_tokens_per_sec": 120.0,
                            "accept_rate": 0.8,
                            "tokens_per_step": 2.5}}
    legs = extract_legs(base)
    assert legs["spec_goodput"] == 120.0
    assert legs["spec_accept_rate"] == 0.8
    assert legs["spec_tokens_per_step"] == 2.5
    worse = {"spec_decode": {"goodput_tokens_per_sec": 90.0,
                             "accept_rate": 0.4,
                             "tokens_per_step": 2.5}}
    rep = compare(base, worse, threshold=0.05)
    assert {r["leg"] for r in rep["regressions"]} == {
        "spec_goodput", "spec_accept_rate"}
    missing = {"serving_throughput": {"tokens_per_sec": 1.0}}
    rep = compare(base, missing, threshold=0.05)
    assert "spec_accept_rate" in rep["only_in_base"]


def test_spec_decode_smoke_artifact_committed():
    """The acceptance artifact: accept rate > 0, decode tokens/step >
    1, goodput >= the k=0 baseline at equal (or better) SLO
    attainment, zero page leaks."""
    art = json.load(open("bench_artifacts/spec_decode_cpu_smoke.json"))
    leg = art["spec_decode"]
    assert leg["spec_k"] > 0
    assert leg["accept_rate"] > 0
    assert leg["tokens_per_step"] > 1.0
    assert leg["goodput_tokens_per_sec"] >= \
        leg["baseline_goodput_tokens_per_sec"]
    assert leg["slo_attainment"] >= leg["baseline_slo_attainment"]
    assert leg["page_leaks"] == 0
