"""ZeRO-2 DistributedFusedAdam / DistributedFusedLAMB tests.

Mirrors the reference's ``apex/contrib/test/optimizers/test_dist_adam.py``
strategy: the distributed (sharded-state) optimizer must match the plain
fused optimizer step-for-step, on an 8-virtual-device data-parallel mesh,
plus checkpoint round-trip and the ZeRO memory property (state sharded 1/dp).
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from apex_tpu.contrib.optimizers import (
    DistributedFusedAdam,
    DistributedFusedLAMB,
)
from apex_tpu.optimizers import FusedAdam, FusedLAMB

DP = 8


def _mesh():
    return Mesh(np.array(jax.devices()), ("data",))


@pytest.fixture(scope="module")
def sharded_packed_precondition():
    """Gate for the sharded tests: the ROADMAP sharded-packed follow-on
    (run ``packed_adam_apply`` on the ``(shard_size,)`` shard inside
    shard_map) requires the packed layout to split into DP equal
    ROW-aligned shards — machine-checked by
    ``analysis.check_pack_spec(spec, shard_count=dp)`` (PR 4). The spec
    is built through ``packed_init`` — the ACTUAL constructor the packed
    upgrade would use over these params, default chunking — so a layout
    change in `_packed.py`/`packing.py` that breaks the precondition
    (chunk no longer DP-divisible into ROW-aligned shards, padding
    scheme change, offset misalignment) fails HERE, by name, before it
    silently blocks the packed upgrade."""
    from apex_tpu.analysis import check_pack_spec
    from apex_tpu.optimizers._packed import packed_init

    params = _toy_params(jax.random.PRNGKey(0))
    spec = packed_init(params).spec
    findings = check_pack_spec(spec, shard_count=DP)
    assert not findings, (
        "sharded-packed precondition violated:\n"
        + "\n".join(f"{f.code}: {f.message}" for f in findings))
    return spec


def _toy_params(key, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "w1": jax.random.normal(k1, (7, 5), dtype),
        "b1": jax.random.normal(k2, (5,), dtype),
        "w2": jax.random.normal(k3, (5, 3), dtype),
    }


def _loss(params, x, y):
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    out = h @ params["w2"]
    return jnp.mean((out - y) ** 2)


def _make_batch(key, n=DP * 4):
    kx, ky = jax.random.split(key)
    x = jax.random.normal(kx, (n, 7), jnp.float32)
    y = jax.random.normal(ky, (n, 3), jnp.float32)
    return x, y


def _dist_train_step(opt, mesh):
    """Jitted DP train step: per-shard grads -> opt.step inside shard_map."""
    specs = opt.state_specs()

    def shard_fn(params, state, x, y):
        grads = jax.grad(_loss)(params, x, y)
        # opt averages grads over the axis itself (average_grad_sync)
        return opt.step(grads, state, params)

    fn = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(), specs, P("data"), P("data")),
        out_specs=(P(), specs),
        check_vma=False,
    )
    return jax.jit(fn)


def _ref_train_step(opt):
    def fn(params, state, x, y):
        grads = jax.grad(_loss)(params, x, y)
        return opt.step(grads, state, params)

    return jax.jit(fn)


@pytest.mark.parametrize("adam_w_mode,weight_decay", [(True, 0.01), (False, 0.0)])
def test_dist_adam_matches_fused_adam(adam_w_mode, weight_decay,
                                      sharded_packed_precondition):
    """dp=8 sharded step == single-device FusedAdam, several steps
    (reference test_dist_adam.py main equivalence)."""
    mesh = _mesh()
    params = _toy_params(jax.random.PRNGKey(0))
    dist = DistributedFusedAdam(
        lr=1e-2, adam_w_mode=adam_w_mode, weight_decay=weight_decay,
        distributed_size=DP,
    )
    ref = FusedAdam(lr=1e-2, adam_w_mode=adam_w_mode, weight_decay=weight_decay)

    d_state = dist.init(params)
    r_state = ref.init(params)
    d_params = params
    r_params = params
    d_step = _dist_train_step(dist, mesh)
    r_step = _ref_train_step(ref)

    for i in range(5):
        x, y = _make_batch(jax.random.PRNGKey(100 + i))
        d_params, d_state = d_step(d_params, d_state, x, y)
        r_params, r_state = r_step(r_params, r_state, x, y)

    for k in params:
        np.testing.assert_allclose(
            np.asarray(d_params[k]), np.asarray(r_params[k]), rtol=2e-5, atol=2e-6
        )
    assert int(d_state.step) == 5


def test_dist_adam_state_is_sharded(sharded_packed_precondition):
    """ZeRO property: each device holds 1/dp of each state buffer."""
    mesh = _mesh()
    params = _toy_params(jax.random.PRNGKey(1))
    dist = DistributedFusedAdam(lr=1e-2, distributed_size=DP)
    state = dist.init(params)
    x, y = _make_batch(jax.random.PRNGKey(2))
    new_params, new_state = _dist_train_step(dist, mesh)(params, state, x, y)

    layout = dist.layout_for(params)
    assert layout.padded % DP == 0
    for buf in (new_state.exp_avg, new_state.exp_avg_sq, new_state.param_shard):
        assert buf.shape == (layout.padded,)
        shard_shapes = {s.data.shape for s in buf.addressable_shards}
        assert shard_shapes == {(layout.shard_size,)}, (
            "optimizer state must be sharded 1/dp over the mesh"
        )


def test_dist_adam_overflow_skips_step(sharded_packed_precondition):
    mesh = _mesh()
    params = _toy_params(jax.random.PRNGKey(3))
    dist = DistributedFusedAdam(lr=1e-2, distributed_size=DP)
    state = dist.init(params)
    specs = dist.state_specs()

    def shard_fn(params, state, x, y, found_inf):
        grads = jax.grad(_loss)(params, x, y)
        return dist.step(grads, state, params, found_inf=found_inf)

    step = jax.jit(shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(), specs, P("data"), P("data"), P()),
        out_specs=(P(), specs), check_vma=False,
    ))
    x, y = _make_batch(jax.random.PRNGKey(4))
    new_params, new_state = step(params, state, x, y, jnp.bool_(True))
    for k in params:
        np.testing.assert_array_equal(np.asarray(new_params[k]), np.asarray(params[k]))
    assert int(new_state.step) == 0


def test_dist_adam_grad_scale_and_clip(sharded_packed_precondition):
    """grad_scale unscaling + max_grad_norm clip match a manual reference."""
    mesh = _mesh()
    params = _toy_params(jax.random.PRNGKey(5))
    scale = 128.0
    max_norm = 0.05
    dist = DistributedFusedAdam(
        lr=1e-2, distributed_size=DP, max_grad_norm=max_norm
    )
    state = dist.init(params)
    specs = dist.state_specs()

    def shard_fn(params, state, x, y):
        grads = jax.grad(lambda p, x, y: _loss(p, x, y) * scale)(params, x, y)
        return dist.step(grads, state, params, grad_scale=scale)

    step = jax.jit(shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(), specs, P("data"), P("data")),
        out_specs=(P(), specs), check_vma=False,
    ))
    x, y = _make_batch(jax.random.PRNGKey(6))
    d_params, _ = step(params, state, x, y)

    # manual: mean grads, clip to max_norm, plain Adam step
    grads = jax.grad(_loss)(params, x, y)
    gnorm = jnp.sqrt(sum(jnp.sum(g ** 2) for g in jax.tree_util.tree_leaves(grads)))
    coef = jnp.minimum(1.0, max_norm / gnorm)
    clipped = jax.tree_util.tree_map(lambda g: g * coef, grads)
    ref = FusedAdam(lr=1e-2)
    r_params, _ = ref.step(clipped, ref.init(params), params)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(d_params[k]), np.asarray(r_params[k]), rtol=2e-5, atol=2e-6
        )


@pytest.mark.parametrize("format", ["v1", "v2"])
def test_dist_adam_checkpoint_roundtrip(format,
                                        sharded_packed_precondition):
    """Sharded state_dict v1/v2 round-trips and training continues identically
    (reference sharded checkpoints distributed_fused_adam.py:2956-3555)."""
    mesh = _mesh()
    params = _toy_params(jax.random.PRNGKey(7))
    dist = DistributedFusedAdam(lr=1e-2, distributed_size=DP)
    state = dist.init(params)
    step = _dist_train_step(dist, mesh)

    x, y = _make_batch(jax.random.PRNGKey(8))
    params1, state1 = step(params, state, x, y)

    sd = dist.state_dict(state1, format=format)
    if format == "v2":
        assert sd["exp_avg"].shape == (DP, dist.layout_for(params).shard_size)
    restored = dist.load_state_dict(sd)

    x2, y2 = _make_batch(jax.random.PRNGKey(9))
    p_a, s_a = step(params1, state1, x2, y2)
    p_b, s_b = step(params1, restored, x2, y2)
    for k in params:
        np.testing.assert_allclose(np.asarray(p_a[k]), np.asarray(p_b[k]), rtol=1e-6)
    assert int(s_b.step) == 2


def test_dist_adam_bf16_params_master_weights(
        sharded_packed_precondition):
    """bf16 model params + fp32 sharded masters: matches FusedAdam with
    master_weights=True."""
    mesh = _mesh()
    params32 = _toy_params(jax.random.PRNGKey(10))
    params = jax.tree_util.tree_map(lambda p: p.astype(jnp.bfloat16), params32)
    dist = DistributedFusedAdam(lr=1e-2, distributed_size=DP)
    ref = FusedAdam(lr=1e-2, master_weights=True)
    d_state = dist.init(params)
    r_state = ref.init(params)
    d_step = _dist_train_step(dist, mesh)
    r_step = _ref_train_step(ref)
    d_params, r_params = params, params
    for i in range(3):
        x, y = _make_batch(jax.random.PRNGKey(200 + i))
        d_params, d_state = d_step(d_params, d_state, x, y)
        r_params, r_state = r_step(r_params, r_state, x, y)
    for k in params:
        assert d_params[k].dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(d_params[k], np.float32),
            np.asarray(r_params[k], np.float32),
            rtol=2e-2, atol=1e-3,
        )
    # masters stay fp32 and track the reference's masters. Tolerance is
    # bf16-level: grads are rounded to bf16 per-device (batch 4) here but
    # once full-batch (32) in the reference, so inputs to the two optimizers
    # differ by bf16 rounding.
    np.testing.assert_allclose(
        np.asarray(d_state.param_shard[5 : 5 + 7 * 5]),
        np.asarray(r_state.master_params["w1"]).reshape(-1),
        rtol=2e-3, atol=2e-3,
    )


@pytest.mark.parametrize("use_nvlamb,weight_decay", [(False, 0.01), (True, 0.0)])
def test_dist_lamb_matches_fused_lamb(use_nvlamb, weight_decay,
                                      sharded_packed_precondition):
    """dp=8 sharded LAMB == single-device FusedLAMB (trust ratios exact via
    segment-sum psum)."""
    mesh = _mesh()
    params = _toy_params(jax.random.PRNGKey(11))
    dist = DistributedFusedLAMB(
        lr=1e-2, weight_decay=weight_decay, use_nvlamb=use_nvlamb,
        max_grad_norm=1.0, distributed_size=DP,
    )
    ref = FusedLAMB(
        lr=1e-2, weight_decay=weight_decay, use_nvlamb=use_nvlamb,
        max_grad_norm=1.0,
    )
    d_state = dist.init(params)
    r_state = ref.init(params)
    d_step = _dist_train_step(dist, mesh)
    r_step = _ref_train_step(ref)
    d_params, r_params = params, params
    for i in range(4):
        x, y = _make_batch(jax.random.PRNGKey(300 + i))
        d_params, d_state = d_step(d_params, d_state, x, y)
        r_params, r_state = r_step(r_params, r_state, x, y)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(d_params[k]), np.asarray(r_params[k]), rtol=5e-5, atol=5e-6
        )


def test_dist_lamb_checkpoint_roundtrip(sharded_packed_precondition):
    mesh = _mesh()
    params = _toy_params(jax.random.PRNGKey(12))
    dist = DistributedFusedLAMB(lr=1e-2, distributed_size=DP)
    state = dist.init(params)
    step = _dist_train_step(dist, mesh)
    x, y = _make_batch(jax.random.PRNGKey(13))
    params1, state1 = step(params, state, x, y)
    restored = dist.load_state_dict(dist.state_dict(state1))
    x2, y2 = _make_batch(jax.random.PRNGKey(14))
    p_a, _ = step(params1, state1, x2, y2)
    p_b, _ = step(params1, restored, x2, y2)
    for k in params:
        np.testing.assert_allclose(np.asarray(p_a[k]), np.asarray(p_b[k]), rtol=1e-6)


def test_contrib_imports():
    """ADVICE r2 medium: every advertised contrib name must import."""
    import apex_tpu.contrib as contrib

    assert contrib.optimizers.DistributedFusedAdam is not None
    assert contrib.optimizers.DistributedFusedLAMB is not None
    # legacy aliases (reference apex/contrib/optimizers legacy copies)
    assert contrib.optimizers.FusedAdam is not None
    assert contrib.optimizers.FP16_Optimizer is not None


# ---------------------------------------------------------------------------
# Legacy contrib optimizer step surface (apex/contrib/optimizers/
# fused_adam.py:64-124, fused_sgd.py:115-127; update math:
# contrib/csrc/optimizers/fused_adam_cuda_kernel.cu:60-70)
# ---------------------------------------------------------------------------

def _legacy_toy(key=0):
    ks = jax.random.split(jax.random.PRNGKey(key), 2)
    params = {"w": jax.random.normal(ks[0], (8, 8)),
              "b": jax.random.normal(ks[1], (8,))}
    grads = jax.tree_util.tree_map(lambda p: p * 0.1 + 0.01, params)
    return params, grads


def _ref_legacy_adam_leaf(p, g, m, v, t, lr, beta1, beta2, eps,
                          eps_inside_sqrt, decay, bias_correction=True):
    """The reference legacy kernel, re-derived in numpy: raw-moment
    denominator, bias corrections folded into the step size, decay
    POST-denominator (fused_adam_cuda_kernel.cu:60-70)."""
    p, g, m, v = (np.asarray(x, np.float64) for x in (p, g, m, v))
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * g * g
    denom = np.sqrt(v + eps) if eps_inside_sqrt else np.sqrt(v) + eps
    step_size = lr * (
        np.sqrt(1 - beta2 ** t) / (1 - beta1 ** t) if bias_correction
        else 1.0)
    update = m / denom + decay * p
    return p - step_size * update, m, v


def _run_ref_legacy_adam(params, grads, steps, lr, eps, eps_inside_sqrt,
                         decay, scale=1.0):
    out = {}
    for k, p in params.items():
        p = np.asarray(p, np.float64)
        g = np.asarray(grads[k], np.float64) / scale
        m = np.zeros_like(p)
        v = np.zeros_like(p)
        for t in range(1, steps + 1):
            p, m, v = _ref_legacy_adam_leaf(
                p, g, m, v, t, lr, 0.9, 0.999, eps, eps_inside_sqrt, decay)
        out[k] = p
    return out


@pytest.mark.parametrize("eps_inside_sqrt", [False, True])
@pytest.mark.parametrize("decay", [0.0, 0.05])
def test_legacy_fused_adam_matches_reference_kernel_math(
        eps_inside_sqrt, decay):
    """Multi-step parity with the reference kernel semantics — which
    differ from BOTH maintained modes: raw-v denominator, bias-corrected
    step size, post-denominator decay."""
    from apex_tpu.contrib.optimizers import FusedAdam as LegacyAdam

    params, grads = _legacy_toy()
    opt = LegacyAdam(lr=1e-2, eps=1e-3, weight_decay=decay,
                     eps_inside_sqrt=eps_inside_sqrt)
    state = opt.init(params)
    p = params
    for _ in range(3):
        p, state = opt.step(grads, state, p, scale=4.0)
    ref = _run_ref_legacy_adam(params, grads, 3, 1e-2, 1e-3,
                               eps_inside_sqrt, decay, scale=4.0)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(p[k]), ref[k], rtol=1e-5, err_msg=k)


def test_legacy_fused_adam_combined_scale_clip():
    """The legacy clip derives a combined scale from the SCALED-grad
    norm: clip = ((norm/scale)+1e-6)/max_norm, applied only when > 1."""
    from apex_tpu.contrib.optimizers import FusedAdam as LegacyAdam

    params, grads = _legacy_toy(1)
    scale = 2.0
    flat = jnp.concatenate(
        [g.reshape(-1) for g in jax.tree_util.tree_leaves(grads)])
    norm_scaled = float(jnp.linalg.norm(flat)) * scale  # norm of scaled
    max_norm = (norm_scaled / scale) / 3.0  # forces clip = 3 > 1
    leg = LegacyAdam(lr=1e-2, max_grad_norm=max_norm)
    lp, _ = leg.step(grads, leg.init(params), params, scale=scale,
                     grad_norms=norm_scaled)
    # equivalent: a plain legacy step with combined scale = clip * scale
    clip = ((norm_scaled / scale) + 1e-6) / max_norm
    leg2 = LegacyAdam(lr=1e-2)
    lp2, _ = leg2.step(grads, leg2.init(params), params,
                       scale=scale * clip)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(lp[k]), np.asarray(lp2[k]), rtol=1e-6)
    # norms below the threshold leave the scale untouched
    leg3 = LegacyAdam(lr=1e-2, max_grad_norm=1e9)
    lp3, _ = leg3.step(grads, leg3.init(params), params, scale=scale,
                       grad_norms=norm_scaled)
    leg4 = LegacyAdam(lr=1e-2)
    lp4, _ = leg4.step(grads, leg4.init(params), params, scale=scale)
    np.testing.assert_allclose(
        np.asarray(lp3["w"]), np.asarray(lp4["w"]), rtol=1e-6)


def test_legacy_fused_adam_eps_placement_and_output_params():
    from apex_tpu.contrib.optimizers import FusedAdam as LegacyAdam

    params, grads = _legacy_toy(2)
    inside = LegacyAdam(lr=1e-2, eps=1e-3, eps_inside_sqrt=True)
    outside = LegacyAdam(lr=1e-2, eps=1e-3, eps_inside_sqrt=False)
    pi, _ = inside.step(grads, inside.init(params), params)
    po, _ = outside.step(grads, outside.init(params), params)
    # the two eps placements genuinely differ at eps=1e-3
    assert float(jnp.abs(pi["w"] - po["w"]).max()) > 1e-6
    # output_params: a reduced-precision copy of the UPDATED weights
    p3, _, out = inside.step(
        grads, inside.init(params), params,
        output_params_dtype=jnp.bfloat16)
    assert out["w"].dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out["w"]),
        np.asarray(p3["w"].astype(jnp.bfloat16)))


def test_legacy_fused_sgd_scale_and_momentum():
    from apex_tpu.contrib.optimizers import FusedSGD as LegacySGD
    from apex_tpu.optimizers import FusedSGD as ModernSGD

    params, grads = _legacy_toy(3)
    leg = LegacySGD(lr=0.1, momentum=0.9)
    ref = ModernSGD(lr=0.1, momentum=0.9)
    state = leg.init(params)
    rstate = ref.init(params)
    lp, ls = leg.step(grads, state, params, scale=2.0)
    scaled = jax.tree_util.tree_map(lambda g: g / 2.0, grads)
    rp, rs = ref.step(scaled, rstate, params)
    np.testing.assert_allclose(
        np.asarray(lp["w"]), np.asarray(rp["w"]), rtol=1e-6)
    # second step exercises the momentum buffer through the legacy path
    lp2, _, out = leg.step(grads, ls, lp, scale=2.0,
                           output_params_dtype=jnp.float16)
    rp2, _ = ref.step(scaled, rs, rp)
    np.testing.assert_allclose(
        np.asarray(lp2["w"]), np.asarray(rp2["w"]), rtol=1e-6)
    assert out["b"].dtype == jnp.float16
