"""Fused optimizers vs torch.optim references — mirrors
``tests/L0/run_optimizers/test_fused_optimizer.py`` (state-by-state
comparisons) plus overflow/noop and master-weight behaviour."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu.optimizers import (
    FusedAdagrad,
    FusedAdam,
    FusedLAMB,
    FusedNovoGrad,
    FusedSGD,
)


def _make_params(seed=0, shapes=((4, 5), (17,), (2, 3, 4))):
    rng = np.random.RandomState(seed)
    return {f"p{i}": rng.randn(*s).astype(np.float32) for i, s in enumerate(shapes)}


def _make_grads(seed=1, shapes=((4, 5), (17,), (2, 3, 4))):
    rng = np.random.RandomState(seed)
    return {f"p{i}": rng.randn(*s).astype(np.float32) for i, s in enumerate(shapes)}


def _torch_run(opt_cls, params_np, grads_seq, **kw):
    tparams = [torch.nn.Parameter(torch.tensor(v)) for v in params_np.values()]
    opt = opt_cls(tparams, **kw)
    for grads_np in grads_seq:
        for p, g in zip(tparams, grads_np.values()):
            p.grad = torch.tensor(g)
        opt.step()
    return [p.detach().numpy() for p in tparams]


def _jax_run(opt, params_np, grads_seq):
    params = jax.tree_util.tree_map(jnp.asarray, params_np)
    state = opt.init(params)
    step = jax.jit(lambda g, s, p: opt.step(g, s, p))
    for grads_np in grads_seq:
        grads = jax.tree_util.tree_map(jnp.asarray, grads_np)
        params, state = step(grads, state, params)
    return params, state


GRADS = [_make_grads(seed) for seed in range(5)]


@pytest.mark.parametrize("adam_w", [True, False])
@pytest.mark.parametrize("wd", [0.0, 0.1])
def test_fused_adam_matches_torch(adam_w, wd):
    params_np = _make_params()
    torch_cls = torch.optim.AdamW if adam_w else torch.optim.Adam
    expect = _torch_run(torch_cls, params_np, GRADS, lr=1e-2, betas=(0.9, 0.999), eps=1e-8, weight_decay=wd)
    opt = FusedAdam(lr=1e-2, betas=(0.9, 0.999), eps=1e-8, adam_w_mode=adam_w, weight_decay=wd)
    got, _ = _jax_run(opt, params_np, GRADS)
    for e, g in zip(expect, got.values()):
        np.testing.assert_allclose(g, e, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("momentum,nesterov,wd", [(0.0, False, 0.0), (0.9, False, 0.0), (0.9, True, 0.0), (0.9, False, 0.05)])
def test_fused_sgd_matches_torch(momentum, nesterov, wd):
    params_np = _make_params()
    expect = _torch_run(
        torch.optim.SGD, params_np, GRADS, lr=0.1, momentum=momentum, nesterov=nesterov, weight_decay=wd
    )
    opt = FusedSGD(lr=0.1, momentum=momentum, nesterov=nesterov, weight_decay=wd)
    got, _ = _jax_run(opt, params_np, GRADS)
    for e, g in zip(expect, got.values()):
        np.testing.assert_allclose(g, e, rtol=2e-4, atol=2e-5)


def test_fused_adagrad_matches_torch():
    params_np = _make_params()
    expect = _torch_run(torch.optim.Adagrad, params_np, GRADS, lr=0.05, eps=1e-10)
    opt = FusedAdagrad(lr=0.05, eps=1e-10)
    got, _ = _jax_run(opt, params_np, GRADS)
    for e, g in zip(expect, got.values()):
        np.testing.assert_allclose(g, e, rtol=2e-4, atol=2e-5)


def test_fused_lamb_trust_ratio_direction():
    """LAMB with wd: per-tensor update norm scaled by ||p||/||update||."""
    params_np = _make_params()
    opt = FusedLAMB(lr=1e-2, weight_decay=0.01, max_grad_norm=0.0)
    got, state = _jax_run(opt, params_np, GRADS[:1])
    assert int(state.step) == 1
    for k in params_np:
        assert not np.allclose(np.asarray(got[k]), params_np[k])


def test_fused_lamb_grad_clipping_invariance():
    """Scaling all grads up should be undone by max_grad_norm clipping."""
    params_np = _make_params()
    g1 = [GRADS[0]]
    g_big = [{k: v * 100.0 for k, v in GRADS[0].items()}]
    opt = FusedLAMB(lr=1e-2, weight_decay=0.01, max_grad_norm=1.0)
    out1, _ = _jax_run(opt, params_np, g1)
    # grads large enough that both runs clip to the same direction
    opt2 = FusedLAMB(lr=1e-2, weight_decay=0.01, max_grad_norm=1.0)
    out2, _ = _jax_run(opt2, params_np, g_big)
    for k in params_np:
        np.testing.assert_allclose(np.asarray(out1[k]), np.asarray(out2[k]), rtol=1e-3, atol=1e-5)


def test_fused_novograd_layerwise_moment():
    params_np = _make_params()
    opt = FusedNovoGrad(lr=1e-2, betas=(0.95, 0.98), weight_decay=0.01)
    got, state = _jax_run(opt, params_np, GRADS[:3])
    # second moment is scalar per tensor
    for v in jax.tree_util.tree_leaves(state.exp_avg_sq):
        assert v.shape == ()
    for k in params_np:
        assert not np.allclose(np.asarray(got[k]), params_np[k])


def test_overflow_skips_step():
    params_np = _make_params()
    opt = FusedAdam(lr=1e-2)
    params = jax.tree_util.tree_map(jnp.asarray, params_np)
    state = opt.init(params)
    grads = jax.tree_util.tree_map(jnp.asarray, GRADS[0])
    new_params, new_state = jax.jit(
        lambda g, s, p: opt.step(g, s, p, found_inf=jnp.asarray(True))
    )(grads, state, params)
    assert int(new_state.step) == 0
    for a, b in zip(jax.tree_util.tree_leaves(new_params), jax.tree_util.tree_leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_no_update_mv_step_preserves_moments():
    params_np = _make_params()
    opt = FusedAdam(lr=1e-2)
    params = jax.tree_util.tree_map(jnp.asarray, params_np)
    state = opt.init(params)
    grads = jax.tree_util.tree_map(jnp.asarray, GRADS[0])
    # regular step then a no_update_mv step
    params1, state1 = opt.step(grads, state, params)
    params2, state2 = opt.no_update_mv_step(grads, state1, params1)
    # params moved, moments + step unchanged
    assert int(state2.step) == int(state1.step)
    for a, b in zip(jax.tree_util.tree_leaves(state2.exp_avg), jax.tree_util.tree_leaves(state1.exp_avg)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    changed = any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(params2), jax.tree_util.tree_leaves(params1))
    )
    assert changed


def test_master_weights_bf16_params():
    params_np = _make_params()
    params = jax.tree_util.tree_map(lambda x: jnp.asarray(x, jnp.bfloat16), params_np)
    opt = FusedAdam(lr=1e-3, master_weights=True)
    state = opt.init(params)
    assert all(m.dtype == jnp.float32 for m in jax.tree_util.tree_leaves(state.master_params))
    grads = jax.tree_util.tree_map(lambda x: jnp.asarray(x, jnp.bfloat16), GRADS[0])
    new_params, new_state = opt.step(grads, state, params)
    assert all(p.dtype == jnp.bfloat16 for p in jax.tree_util.tree_leaves(new_params))
    # master params advanced in fp32
    for m, p in zip(
        jax.tree_util.tree_leaves(new_state.master_params),
        jax.tree_util.tree_leaves(new_params),
    ):
        np.testing.assert_allclose(
            np.asarray(m, np.float32), np.asarray(p, np.float32), rtol=1e-2, atol=1e-2
        )


def test_grad_scale_unscales():
    params_np = _make_params()
    opt = FusedAdam(lr=1e-2)
    scaled = [{k: v * 128.0 for k, v in GRADS[0].items()}]
    out_scaled, _ = _jax_run_with_scale(opt, params_np, scaled, 128.0)
    opt2 = FusedAdam(lr=1e-2)
    out_plain, _ = _jax_run(opt2, params_np, [GRADS[0]])
    for k in params_np:
        np.testing.assert_allclose(np.asarray(out_scaled[k]), np.asarray(out_plain[k]), rtol=1e-5, atol=1e-6)


def _jax_run_with_scale(opt, params_np, grads_seq, scale):
    params = jax.tree_util.tree_map(jnp.asarray, params_np)
    state = opt.init(params)
    for grads_np in grads_seq:
        grads = jax.tree_util.tree_map(jnp.asarray, grads_np)
        params, state = opt.step(grads, state, params, grad_scale=scale)
    return params, state


# ---------------------------------------------------------------------------
# packed flat-buffer path: numerical parity with the pytree path
# ---------------------------------------------------------------------------

_PACKED_MAKERS = {
    "adam": lambda **kw: FusedAdam(
        lr=1e-2, weight_decay=0.1, adam_w_mode=True, **kw),
    "adam_l2": lambda **kw: FusedAdam(
        lr=1e-2, weight_decay=0.1, adam_w_mode=False, **kw),
    "lamb": lambda **kw: FusedLAMB(
        lr=1e-2, weight_decay=0.01, max_grad_norm=1.0, **kw),
    "lamb_nvlamb": lambda **kw: FusedLAMB(
        lr=1e-2, weight_decay=0.0, max_grad_norm=0.0, use_nvlamb=True, **kw),
    "sgd": lambda **kw: FusedSGD(
        lr=0.1, momentum=0.9, nesterov=True, **kw),
    "sgd_wd": lambda **kw: FusedSGD(
        lr=0.1, momentum=0.9, weight_decay=0.05, wd_after_momentum=True, **kw),
    "novograd": lambda **kw: FusedNovoGrad(lr=1e-2, weight_decay=0.01, **kw),
    "novograd_inf": lambda **kw: FusedNovoGrad(
        lr=1e-2, norm_type=0, reg_inside_moment=True, weight_decay=0.01, **kw),
}

GRADS10 = [_make_grads(seed) for seed in range(10)]


def _run_seq(opt, params_np, grads_seq, dtype=None):
    cast = (lambda x: jnp.asarray(x)) if dtype is None else (
        lambda x: jnp.asarray(x, dtype))
    params = jax.tree_util.tree_map(cast, params_np)
    state = opt.init(params)
    step = jax.jit(lambda g, s, p: opt.step(g, s, p))
    for grads_np in grads_seq:
        params, state = step(
            jax.tree_util.tree_map(cast, grads_np), state, params)
    return params, state


def _moments_tree(state):
    """m/v pytrees from either state flavor (packed states unpack)."""
    from apex_tpu.optimizers import PackedState

    if isinstance(state, PackedState):
        m = state.spec.unpack(state.exp_avg, cast=False)
        v = (state.spec.unpack(state.exp_avg_sq, cast=False)
             if state.exp_avg_sq is not None
             and state.exp_avg_sq.shape == state.exp_avg.shape else None)
        return m, v
    m = getattr(state, "exp_avg", None) or getattr(
        state, "momentum_buffer", None)
    return m, getattr(state, "exp_avg_sq", None)


@pytest.mark.parametrize("name", sorted(_PACKED_MAKERS))
def test_packed_matches_pytree(name):
    """packed=True is numerically equivalent to the pytree path over 10
    chained steps — params AND first/second moments."""
    mk = _PACKED_MAKERS[name]
    params_np = _make_params()
    p_ref, s_ref = _run_seq(mk(), params_np, GRADS10)
    p_pk, s_pk = _run_seq(mk(packed=True), params_np, GRADS10)
    for k in params_np:
        np.testing.assert_allclose(
            np.asarray(p_pk[k]), np.asarray(p_ref[k]), rtol=2e-5, atol=1e-6)
    m_ref, _ = _moments_tree(s_ref)
    m_pk, _ = _moments_tree(s_pk)
    for a, b in zip(jax.tree_util.tree_leaves(m_ref),
                    jax.tree_util.tree_leaves(m_pk)):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=2e-5, atol=1e-6)
    assert int(s_pk.step) == len(GRADS10)


@pytest.mark.parametrize("name", ["adam", "lamb", "sgd", "novograd"])
def test_packed_kernel_interpret_matches_fallback(name):
    """The actual Pallas kernel bodies (run under the interpreter on CPU)
    agree with the XLA fallback path."""
    mk = _PACKED_MAKERS[name]
    params_np = _make_params()
    p_fb, _ = _run_seq(mk(packed=True), params_np, GRADS10[:3])
    p_it, _ = _run_seq(
        mk(packed=True, packed_interpret=True), params_np, GRADS10[:3])
    for k in params_np:
        np.testing.assert_allclose(
            np.asarray(p_it[k]), np.asarray(p_fb[k]), rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("name", ["adam", "lamb", "sgd"])
def test_packed_master_weights_bf16(name):
    """bf16 params + fp32 flat masters: recast params bit-identical to the
    pytree master path, masters tracked in fp32."""
    mk = _PACKED_MAKERS[name]
    params_np = _make_params()
    p_ref, s_ref = _run_seq(
        mk(master_weights=True), params_np, GRADS10[:5], jnp.bfloat16)
    p_pk, s_pk = _run_seq(
        mk(master_weights=True, packed=True), params_np, GRADS10[:5],
        jnp.bfloat16)
    for k in params_np:
        assert p_pk[k].dtype == jnp.bfloat16
        np.testing.assert_array_equal(
            np.asarray(p_pk[k], np.float32), np.asarray(p_ref[k], np.float32))
    masters_ref = jax.tree_util.tree_leaves(s_ref.master_params)
    masters_pk = jax.tree_util.tree_leaves(
        s_pk.spec.unpack(s_pk.master_params, cast=False))
    for a, b in zip(masters_ref, masters_pk):
        assert b.dtype == jnp.float32
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=2e-5, atol=1e-6)


def test_packed_overflow_skips_step():
    params_np = _make_params()
    opt = FusedAdam(lr=1e-2, packed=True)
    params = jax.tree_util.tree_map(jnp.asarray, params_np)
    state = opt.init(params)
    grads = jax.tree_util.tree_map(jnp.asarray, GRADS[0])
    new_params, new_state = jax.jit(
        lambda g, s, p: opt.step(g, s, p, found_inf=jnp.asarray(True))
    )(grads, state, params)
    assert int(new_state.step) == 0
    for a, b in zip(jax.tree_util.tree_leaves(new_params),
                    jax.tree_util.tree_leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(
        np.asarray(new_state.exp_avg), np.asarray(state.exp_avg))


def test_packed_no_update_mv_matches_pytree():
    """The fork's transient-m/v step: packed kernel writes only params;
    moments/step/masters stay; params match the pytree no_update_mv."""
    params_np = _make_params()
    params = jax.tree_util.tree_map(jnp.asarray, params_np)
    grads = jax.tree_util.tree_map(jnp.asarray, GRADS[0])

    opt_pk = FusedAdam(lr=1e-2, packed=True)
    s_pk = opt_pk.init(params)
    p1_pk, s1_pk = opt_pk.step(grads, s_pk, params)
    p2_pk, s2_pk = opt_pk.no_update_mv_step(grads, s1_pk, p1_pk)
    assert int(s2_pk.step) == int(s1_pk.step)
    np.testing.assert_array_equal(
        np.asarray(s2_pk.exp_avg), np.asarray(s1_pk.exp_avg))
    np.testing.assert_array_equal(
        np.asarray(s2_pk.exp_avg_sq), np.asarray(s1_pk.exp_avg_sq))

    opt_pt = FusedAdam(lr=1e-2)
    s_pt = opt_pt.init(params)
    p1_pt, s1_pt = opt_pt.step(grads, s_pt, params)
    p2_pt, _ = opt_pt.no_update_mv_step(grads, s1_pt, p1_pt)
    for k in params_np:
        np.testing.assert_allclose(
            np.asarray(p2_pk[k]), np.asarray(p2_pt[k]), rtol=1e-6, atol=1e-7)


def test_packed_grad_scale_unscales():
    params_np = _make_params()
    p_ref, _ = _run_seq(FusedAdam(lr=1e-2, packed=True), params_np, GRADS10[:4])
    opt = FusedAdam(lr=1e-2, packed=True)
    params = jax.tree_util.tree_map(jnp.asarray, params_np)
    state = opt.init(params)
    for g_np in GRADS10[:4]:
        grads = jax.tree_util.tree_map(lambda x: jnp.asarray(x * 64.0), g_np)
        params, state = opt.step(grads, state, params, grad_scale=64.0)
    for k in params_np:
        np.testing.assert_allclose(
            np.asarray(params[k]), np.asarray(p_ref[k]), rtol=1e-5, atol=1e-6)


def test_packed_optax_adapter():
    import optax

    params_np = _make_params()
    params = jax.tree_util.tree_map(jnp.asarray, params_np)
    tx = FusedAdam(lr=1e-2, packed=True).as_gradient_transformation()
    state = tx.init(params)
    grads = jax.tree_util.tree_map(jnp.asarray, GRADS[0])
    updates, state = tx.update(grads, state, params)
    params2 = optax.apply_updates(params, updates)
    for k in params_np:
        assert not np.allclose(np.asarray(params2[k]), params_np[k])


def test_packed_master_never_aliases_params():
    """Single fp32 leaf of exact chunk-multiple size: pack() is the
    identity, so init must force a copy or params+state donation would
    donate one device buffer twice (the tree_f32 hazard)."""
    from apex_tpu.multi_tensor_apply import DEFAULT_CHUNK

    params = {"w": jnp.ones((DEFAULT_CHUNK,), jnp.float32)}
    opt = FusedAdam(lr=1e-2, master_weights=True, packed=True)
    state = opt.init(params)
    assert (state.master_params.unsafe_buffer_pointer()
            != params["w"].unsafe_buffer_pointer())
    # and the double-donation scenario the copy exists for must work
    step = jax.jit(lambda g, s, p: opt.step(g, s, p), donate_argnums=(1, 2))
    new_params, new_state = step(
        {"w": jnp.full((DEFAULT_CHUNK,), 0.1, jnp.float32)}, state, params)
    assert int(new_state.step) == 1


def test_packed_state_is_flat_and_donatable():
    """The packed state is 1-D chunk-padded buffers (the whole point:
    one contiguous sweep), and survives a donated jit step."""
    from apex_tpu.multi_tensor_apply import DEFAULT_CHUNK

    params_np = _make_params()
    params = jax.tree_util.tree_map(jnp.asarray, params_np)
    opt = FusedAdam(lr=1e-2, master_weights=True, packed=True)
    state = opt.init(params)
    assert state.exp_avg.ndim == 1
    assert state.exp_avg.shape[0] % DEFAULT_CHUNK == 0
    assert state.master_params.dtype == jnp.float32
    step = jax.jit(lambda g, s, p: opt.step(g, s, p), donate_argnums=(1, 2))
    grads = jax.tree_util.tree_map(jnp.asarray, GRADS[0])
    new_params, new_state = step(grads, state, params)
    assert int(new_state.step) == 1
