"""True-1F1B schedule: gradient parity + the O(pp) memory bound.

The headline claim (VERDICT round-3 item 2): unlike the scan-autodiff
schedules, :func:`pipeline_forward_backward_1f1b`'s peak activation
memory is INDEPENDENT of the number of microbatches at fixed pp —
asserted here via ``compile().memory_analysis()``, not just documented.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel.schedules import (
    pipeline_forward_backward,
    pipeline_forward_backward_1f1b,
)
from apex_tpu.transformer.pipeline_parallel.utils import pvary_full

PP = 4
H = 8
MBS = 4


@pytest.fixture
def pp_mesh():
    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=1, pipeline_model_parallel_size_=PP,
        devices=jax.devices()[:PP],
    )
    yield parallel_state.get_mesh()
    parallel_state.destroy_model_parallel()


def _jit_pipeline(mesh, local_fn, pspec):
    """jit(shard_map(...)) with the file's standard vma setup: local_fn
    receives (stage_params, inputs, targets) already stripped+pvary'd."""
    pl = parallel_state.PIPELINE_AXIS

    def local(params, inputs, targets):
        stage_p = jax.tree_util.tree_map(lambda p: p[0], params)
        stage_p = pvary_full(stage_p, (pl,))
        inputs = pvary_full(inputs, (pl,))
        targets = pvary_full(targets, (pl,))
        return local_fn(stage_p, inputs, targets)

    return jax.jit(jax.shard_map(
        local, mesh=mesh, in_specs=(pspec, P(), P()),
        out_specs=(P(), pspec), check_vma=True,
    ))


def _temp_bytes(fn, *args):
    return fn.lower(*args).compile().memory_analysis().temp_size_in_bytes


def _stage_fn(lp, x):
    return jnp.tanh(jnp.einsum("...h,oh->...o", x, lp["w"]) + lp["b"])


def _loss_fn(y, tgt):
    return jnp.mean((y - tgt) ** 2)


def _make(n_micro, key=0):
    ks = jax.random.split(jax.random.PRNGKey(key), PP + 2)
    params = {
        "w": jnp.stack([jax.random.normal(k, (H, H)) * 0.5
                        for k in ks[:PP]]),
        "b": jnp.zeros((PP, H)),
    }
    inputs = jax.random.normal(ks[PP], (n_micro, MBS, H))
    targets = jax.random.normal(ks[PP + 1], (n_micro, MBS, H))
    return params, inputs, targets


def _dense(params, inputs, targets):
    total = 0.0
    for m in range(inputs.shape[0]):
        h = inputs[m]
        for s in range(PP):
            h = _stage_fn({"w": params["w"][s], "b": params["b"][s]}, h)
        total = total + _loss_fn(h, targets[m])
    return total / inputs.shape[0]


def _run_1f1b(mesh, params, inputs, targets):
    pl = parallel_state.PIPELINE_AXIS
    pspec = {"w": P(pl, None, None), "b": P(pl, None)}

    def local(params, inputs, targets):
        stage_p = jax.tree_util.tree_map(lambda p: p[0], params)
        stage_p = pvary_full(stage_p, (pl,))
        inputs = pvary_full(inputs, (pl,))
        targets = pvary_full(targets, (pl,))
        loss, grads, dinp = pipeline_forward_backward_1f1b(
            _stage_fn, _loss_fn, stage_p, inputs, targets, axis_name=pl,
        )
        grads = jax.tree_util.tree_map(lambda g: g[None], grads)
        return loss, grads, dinp

    return jax.jit(jax.shard_map(
        local, mesh=mesh, in_specs=(pspec, P(), P()),
        out_specs=(P(), pspec, P()), check_vma=True,
    ))


def test_1f1b_matches_dense_and_scan_schedule(pp_mesh):
    n = 8
    params, inputs, targets = _make(n)
    loss, grads, dinp = _run_1f1b(pp_mesh, params, inputs, targets)(
        params, inputs, targets
    )
    ref_loss, (ref_grads, ref_dinp) = jax.value_and_grad(
        _dense, argnums=(0, 1)
    )(params, inputs, targets)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for k in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(grads[k]), np.asarray(ref_grads[k]), atol=1e-5,
            err_msg=f"grad {k}",
        )
    np.testing.assert_allclose(
        np.asarray(dinp), np.asarray(ref_dinp), atol=1e-5,
    )

    # and against the scan-autodiff schedule (same mesh, same math)
    pl = parallel_state.PIPELINE_AXIS
    pspec = {"w": P(pl, None, None), "b": P(pl, None)}

    def local_scan(params, inputs, targets):
        stage_p = jax.tree_util.tree_map(lambda p: p[0], params)
        stage_p = pvary_full(stage_p, (pl,))
        inputs = pvary_full(inputs, (pl,))
        targets = pvary_full(targets, (pl,))
        loss, grads, _ = pipeline_forward_backward(
            _stage_fn, _loss_fn, stage_p, inputs, targets, axis_name=pl,
        )
        return loss, jax.tree_util.tree_map(lambda g: g[None], grads)

    loss2, grads2 = jax.jit(jax.shard_map(
        local_scan, mesh=pp_mesh, in_specs=(pspec, P(), P()),
        out_specs=(P(), pspec), check_vma=True,
    ))(params, inputs, targets)
    np.testing.assert_allclose(float(loss), float(loss2), rtol=1e-5)
    for k in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(grads[k]), np.asarray(grads2[k]), atol=1e-5,
        )


def test_1f1b_odd_microbatch_counts(pp_mesh):
    """n not divisible by pp and n < pp both schedule correctly."""
    for n in (2, 5):
        params, inputs, targets = _make(n, key=n)
        loss, grads, _ = _run_1f1b(pp_mesh, params, inputs, targets)(
            params, inputs, targets
        )
        ref_loss, ref_grads = jax.value_and_grad(_dense)(
            params, inputs, targets
        )
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(grads["w"]), np.asarray(ref_grads["w"]), atol=1e-5,
        )


def test_1f1b_peak_memory_independent_of_n_micro(pp_mesh):
    """The headline memory claim: peak temp bytes at n_micro=32 stay
    within ~10% of n_micro=8. The [n, ...] inputs are arguments, not
    temp; dinputs (also inherently [n, ...]) is disabled as a trainer
    that owns the embedding gradient would — temp then holds the O(pp)
    residual ring + per-tick workspace only."""
    pl = parallel_state.PIPELINE_AXIS
    pspec = {"w": P(pl, None, None), "b": P(pl, None)}

    def local_fn(stage_p, inputs, targets):
        loss, grads, _ = pipeline_forward_backward_1f1b(
            _stage_fn, _loss_fn, stage_p, inputs, targets,
            axis_name=pl, with_dinputs=False,
        )
        return loss, jax.tree_util.tree_map(lambda g: g[None], grads)

    def temp_bytes(n):
        args = _make(n)
        return _temp_bytes(_jit_pipeline(pp_mesh, local_fn, pspec), *args)

    small = temp_bytes(8)
    big = temp_bytes(32)
    assert big <= small * 1.1, (
        f"1F1B peak temp grew with n_micro: {small} -> {big} bytes"
    )

    # contrast: the scan-autodiff schedule's backward residuals DO grow
    # with n_micro (that is the deficiency 1F1B exists to fix)
    def scan_local(stage_p, inputs, targets):
        loss, grads, _ = pipeline_forward_backward(
            _stage_fn, _loss_fn, stage_p, inputs, targets, axis_name=pl,
        )
        return loss, jax.tree_util.tree_map(lambda g: g[None], grads)

    def scan_temp_bytes(n):
        args = _make(n)
        return _temp_bytes(_jit_pipeline(pp_mesh, scan_local, pspec), *args)

    assert scan_temp_bytes(32) > scan_temp_bytes(8) * 1.5


def test_tick_checkpoint_memory_claim(pp_mesh):
    """VERDICT r3 weak #3: the scan schedule's `tick_checkpoint=K`
    docstring claims O(total/K) saved boundary ring states instead of
    O(total) — assert it via memory_analysis. The ring-state count is
    n_micro * vpp, so the interleaved (vpp=4) configuration is where the
    claim carries real weight (without vpp, the chunk-emission buffers
    can outweigh the saving at small state sizes)."""
    pl = parallel_state.PIPELINE_AXIS
    VPP, BH = 4, 64
    pspec = {"w": P(pl, None, None, None), "b": P(pl, None, None)}

    def temp_bytes(n, tick_checkpoint):
        k1, k2 = jax.random.split(jax.random.PRNGKey(0))
        # zero params are fine: only compile-time memory_analysis is read
        params = {
            "w": jnp.zeros((PP, VPP, BH, BH)),
            "b": jnp.zeros((PP, VPP, BH)),
        }
        inputs = jax.random.normal(k1, (n, MBS, BH))
        targets = jax.random.normal(k2, (n, MBS, BH))

        def local_fn(stage_p, inputs, targets):
            loss, grads, _ = pipeline_forward_backward(
                _stage_fn, _loss_fn, stage_p, inputs, targets,
                axis_name=pl, num_chunks=VPP,
                tick_checkpoint=tick_checkpoint,
            )
            return loss, jax.tree_util.tree_map(lambda g: g[None], grads)

        return _temp_bytes(
            _jit_pipeline(pp_mesh, local_fn, pspec),
            params, inputs, targets)

    plain = temp_bytes(32, None)
    chunked = temp_bytes(32, 16)
    # measured ~2.4 MB vs ~0.5 MB on the CPU harness; require a decisive cut
    assert chunked < plain / 2, (chunked, plain)


VPP = 2


def _make_chunked(n_micro, key=0):
    ks = jax.random.split(jax.random.PRNGKey(key), 3)
    params = {
        "w": jax.random.normal(ks[0], (PP, VPP, H, H)) * 0.5,
        "b": jnp.zeros((PP, VPP, H)),
    }
    inputs = jax.random.normal(ks[1], (n_micro, MBS, H))
    targets = jax.random.normal(ks[2], (n_micro, MBS, H))
    return params, inputs, targets


def _dense_chunked(params, inputs, targets):
    """Chunk c on stage s holds global block c*pp + s (reference layout:
    ``fwd_bwd_pipelining_with_interleaving.py`` model-chunk order)."""
    total = 0.0
    for m in range(inputs.shape[0]):
        h = inputs[m]
        for c in range(VPP):
            for s in range(PP):
                h = _stage_fn(
                    {"w": params["w"][s, c], "b": params["b"][s, c]}, h)
        total = total + _loss_fn(h, targets[m])
    return total / inputs.shape[0]


def test_interleaved_1f1b_matches_dense_and_scan(pp_mesh):
    """The vpp>1 true-1F1B schedule: gradient parity against the dense
    composition AND the scan-autodiff interleaved schedule."""
    pl = parallel_state.PIPELINE_AXIS
    n = 8
    params, inputs, targets = _make_chunked(n)
    pspec = {"w": P(pl, None, None, None), "b": P(pl, None, None)}

    def local(stage_p, inputs, targets):
        loss, grads, dinp = pipeline_forward_backward_1f1b(
            _stage_fn, _loss_fn, stage_p, inputs, targets,
            axis_name=pl, num_chunks=VPP,
        )
        return loss, jax.tree_util.tree_map(lambda g: g[None], grads)

    loss, grads = _jit_pipeline(pp_mesh, local, pspec)(
        params, inputs, targets)
    ref_loss, ref_grads = jax.value_and_grad(_dense_chunked)(
        params, inputs, targets)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for kk in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(grads[kk]), np.asarray(ref_grads[kk]), atol=1e-5,
            err_msg=f"grad {kk}",
        )

    # and against the scan-autodiff interleaved schedule
    def local_scan(stage_p, inputs, targets):
        loss, grads, _ = pipeline_forward_backward(
            _stage_fn, _loss_fn, stage_p, inputs, targets,
            axis_name=pl, num_chunks=VPP,
        )
        return loss, jax.tree_util.tree_map(lambda g: g[None], grads)

    loss2, grads2 = _jit_pipeline(pp_mesh, local_scan, pspec)(
        params, inputs, targets)
    np.testing.assert_allclose(float(loss), float(loss2), rtol=1e-5)
    for kk in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(grads[kk]), np.asarray(grads2[kk]), atol=1e-5,
        )


def test_interleaved_1f1b_requires_divisible_n(pp_mesh):
    pl = parallel_state.PIPELINE_AXIS
    params, inputs, targets = _make_chunked(6)  # 6 % 4 != 0
    pspec = {"w": P(pl, None, None, None), "b": P(pl, None, None)}

    def local(stage_p, inputs, targets):
        loss, grads, _ = pipeline_forward_backward_1f1b(
            _stage_fn, _loss_fn, stage_p, inputs, targets,
            axis_name=pl, num_chunks=VPP,
        )
        return loss, jax.tree_util.tree_map(lambda g: g[None], grads)

    with pytest.raises(ValueError, match="divisible"):
        _jit_pipeline(pp_mesh, local, pspec)(params, inputs, targets)


def test_interleaved_1f1b_peak_memory_independent_of_n_micro(pp_mesh):
    """VERDICT r4 missing #1: the O(pp·vpp) bound for the INTERLEAVED
    schedule — temp bytes at n_micro=32 within ~10% of n_micro=8 at
    pp=4, vpp=2 (dinputs disabled as in the plain-1F1B memory test)."""
    pl = parallel_state.PIPELINE_AXIS
    pspec = {"w": P(pl, None, None, None), "b": P(pl, None, None)}

    def local_fn(stage_p, inputs, targets):
        loss, grads, _ = pipeline_forward_backward_1f1b(
            _stage_fn, _loss_fn, stage_p, inputs, targets,
            axis_name=pl, with_dinputs=False, num_chunks=VPP,
        )
        return loss, jax.tree_util.tree_map(lambda g: g[None], grads)

    def temp_bytes(n):
        args = _make_chunked(n)
        return _temp_bytes(_jit_pipeline(pp_mesh, local_fn, pspec), *args)

    small = temp_bytes(8)
    big = temp_bytes(32)
    assert big <= small * 1.1, (
        f"interleaved 1F1B peak temp grew with n_micro: "
        f"{small} -> {big} bytes"
    )


def test_1f1b_with_flash_attention_stage(pp_mesh):
    """1F1B stores flattened jax.vjp closures in its ring buffer; a stage
    containing the Pallas flash kernel (a custom_vjp primitive) must
    flatten/unflatten cleanly and still match dense grads."""
    from apex_tpu.ops.flash_attention import flash_attention

    pl = parallel_state.PIPELINE_AXIS
    B, NH, S, D = 2, 2, 16, 8

    def attn_stage(lp, x):  # x [B, NH, S, D]
        q = jnp.einsum("bnsd,de->bnse", x, lp["wq"])
        o = flash_attention(
            q, x, x, causal=True, interpret=True, block_q=8, block_k=8)
        return x + o.astype(x.dtype)

    ks = jax.random.split(jax.random.PRNGKey(11), 3)
    params = {"wq": jax.random.normal(ks[0], (PP, D, D)) * 0.5}
    n = 6
    inputs = jax.random.normal(ks[1], (n, B, NH, S, D))
    targets = jax.random.normal(ks[2], (n, B, NH, S, D))
    pspec = {"wq": P(pl, None, None)}

    def local_fn(stage_p, inputs, targets):
        loss, grads, _ = pipeline_forward_backward_1f1b(
            attn_stage, _loss_fn, stage_p, inputs, targets,
            axis_name=pl, with_dinputs=False,
        )
        return loss, jax.tree_util.tree_map(lambda g: g[None], grads)

    loss, grads = _jit_pipeline(pp_mesh, local_fn, pspec)(
        params, inputs, targets)

    def dense(params):
        total = 0.0
        for m in range(n):
            h = inputs[m]
            for s in range(PP):
                h = attn_stage({"wq": params["wq"][s]}, h)
            total = total + _loss_fn(h, targets[m])
        return total / n

    ref_loss, ref_grads = jax.value_and_grad(dense)(params)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
    np.testing.assert_allclose(
        np.asarray(grads["wq"]), np.asarray(ref_grads["wq"]), atol=5e-4)
