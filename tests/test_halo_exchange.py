"""Halo exchange + spatial parallelism tests (apex_tpu.contrib.bottleneck).

The core claim, mirroring the reference's
`apex/contrib/test/peer_memory/test_peer_halo_exchange_module.py` and
`test_bottleneck_module.py`: a height-sharded conv/bottleneck with
ppermute halo exchange equals the unsharded computation on one device.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.contrib.bottleneck import (
    HaloExchangerAllGather,
    HaloExchangerNoComm,
    HaloExchangerPeer,
    HaloExchangerSendRecv,
    halo_pad_1d,
    spatial_conv3x3,
)

SP = 4  # spatial group size
N, H, W, C = 2, 32, 16, 8


@pytest.fixture
def sp_mesh():
    return Mesh(np.array(jax.devices()[:SP]), ("spatial",))


def _x(key=0, h=H):
    return jax.random.normal(jax.random.PRNGKey(key), (N, h, W, C))


def test_send_recv_halo_exchange_semantics(sp_mesh):
    """left_input = left neighbor's right halo (zeros at rank 0);
    right_input = right neighbor's left halo (zeros at the last rank)."""
    x = _x()  # H sharded into SP slabs of 8

    def local(x):
        ex = HaloExchangerSendRecv("spatial")
        left_out = x[:, :1]
        right_out = x[:, -1:]
        li, ri = ex.left_right_halo_exchange(left_out, right_out)
        return li, ri

    li, ri = jax.shard_map(
        local, mesh=sp_mesh, in_specs=P(None, "spatial"),
        out_specs=(P(None, "spatial"), P(None, "spatial")),
        check_vma=False,
    )(x)
    # shard s's left_input is shard s-1's last row
    h_loc = H // SP
    for s in range(SP):
        got_left = np.asarray(li[:, s])
        got_right = np.asarray(ri[:, s])
        if s == 0:
            np.testing.assert_array_equal(got_left, 0.0)
        else:
            np.testing.assert_array_equal(
                got_left, np.asarray(x[:, s * h_loc - 1])
            )
        if s == SP - 1:
            np.testing.assert_array_equal(got_right, 0.0)
        else:
            np.testing.assert_array_equal(
                got_right, np.asarray(x[:, (s + 1) * h_loc])
            )


def test_allgather_matches_sendrecv(sp_mesh):
    x = _x(1)

    def run(ex_cls):
        def local(x):
            ex = ex_cls("spatial")
            li, ri = ex.left_right_halo_exchange(x[:, :2], x[:, -2:])
            return li, ri

        return jax.shard_map(
            local, mesh=sp_mesh, in_specs=P(None, "spatial"),
            out_specs=(P(None, "spatial"), P(None, "spatial")),
            check_vma=False,
        )(x)

    a = run(HaloExchangerSendRecv)
    b = run(HaloExchangerAllGather)
    c = run(HaloExchangerPeer)  # collapses to SendRecv on TPU
    for ga, gb, gc in zip(a, b, c):
        np.testing.assert_array_equal(np.asarray(ga), np.asarray(gb))
        np.testing.assert_array_equal(np.asarray(ga), np.asarray(gc))


def test_nocomm_swaps_locally(sp_mesh):
    """The reference's own warning: NoComm is a perf stand-in, it swaps the
    local halos instead of exchanging with neighbors."""
    x = _x(2)

    def local(x):
        ex = HaloExchangerNoComm("spatial")
        li, ri = ex.left_right_halo_exchange(x[:, :1], x[:, -1:])
        return li, ri

    li, ri = jax.shard_map(
        local, mesh=sp_mesh, in_specs=P(None, "spatial"),
        out_specs=(P(None, "spatial"), P(None, "spatial")),
        check_vma=False,
    )(x)
    h_loc = H // SP
    for s in range(SP):
        np.testing.assert_array_equal(
            np.asarray(li[:, s]), np.asarray(x[:, (s + 1) * h_loc - 1])
        )
        np.testing.assert_array_equal(
            np.asarray(ri[:, s]), np.asarray(x[:, s * h_loc])
        )


def test_spatial_conv3x3_matches_dense(sp_mesh):
    """The SURVEY's halo-exchange pattern, proven: height-sharded SAME conv
    with ppermute halos == unsharded lax.conv."""
    x = _x(3)
    w = jax.random.normal(jax.random.PRNGKey(4), (3, 3, C, C)) * 0.2

    dense = jax.lax.conv_general_dilated(
        x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
    )

    def local(x, w):
        return spatial_conv3x3(x, w, HaloExchangerSendRecv("spatial"))

    sharded = jax.shard_map(
        local, mesh=sp_mesh, in_specs=(P(None, "spatial"), P()),
        out_specs=P(None, "spatial"), check_vma=False,
    )(x, w)
    np.testing.assert_allclose(
        np.asarray(sharded), np.asarray(dense), atol=1e-5
    )


def test_spatial_conv3x3_grads_match_dense(sp_mesh):
    x = _x(5)
    w = jax.random.normal(jax.random.PRNGKey(6), (3, 3, C, C)) * 0.2

    def dense_loss(x, w):
        y = jax.lax.conv_general_dilated(
            x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
        )
        return (y ** 2).sum()

    def local(x, w):
        y = spatial_conv3x3(x, w, HaloExchangerSendRecv("spatial"))
        loss = (y ** 2).sum()
        dx, dw = jax.grad(lambda x, w: (spatial_conv3x3(
            x, w, HaloExchangerSendRecv("spatial")) ** 2).sum(),
            argnums=(0, 1))(x, w)
        # w is replicated: its per-slab grads sum across the axis
        return loss, dx, jax.lax.psum(dw, "spatial")

    loss, dx, dw = jax.shard_map(
        local, mesh=sp_mesh, in_specs=(P(None, "spatial"), P()),
        out_specs=(P(), P(None, "spatial"), P()), check_vma=False,
    )(x, w)
    ref_dx, ref_dw = jax.grad(dense_loss, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(
        np.asarray(dx), np.asarray(ref_dx), rtol=1e-5, atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(dw), np.asarray(ref_dw), rtol=1e-5, atol=1e-4)


def test_spatial_bottleneck_matches_dense(sp_mesh):
    """SpatialBottleneck (halo conv + spatial-synced BN) == Bottleneck on
    the unsharded image, in training mode."""
    from apex_tpu.contrib.bottleneck import Bottleneck, SpatialBottleneck

    x = _x(7)
    dense_mod = Bottleneck(in_channels=C, bottleneck_channels=4,
                           out_channels=C)
    variables = dense_mod.init(jax.random.PRNGKey(8), x)

    y_dense, _ = dense_mod.apply(variables, x, mutable=["batch_stats"])

    sp_mod = SpatialBottleneck(in_channels=C, bottleneck_channels=4,
                               out_channels=C, axis_name="spatial")
    # build the spatial module's variables from the dense weights (init
    # can't run outside shard_map: the halo ppermute needs the bound axis)
    dp = variables["params"]
    p = {
        "conv1": dict(dp["conv1"]),
        "conv3": dict(dp["conv3"]),
        "conv2_kernel": dp["conv2"]["kernel"],
    }
    bs = {}
    for bn, c in (("bn1", 4), ("bn2", 4), ("bn3", C)):
        p[bn] = {"scale": dp[bn]["scale"], "bias": dp[bn]["bias"]}
        bs[bn] = {"mean": jnp.zeros((c,), jnp.float32),
                  "var": jnp.ones((c,), jnp.float32)}

    def local(p, bs, x):
        y, _ = sp_mod.apply({"params": p, "batch_stats": bs}, x,
                            mutable=["batch_stats"])
        return y

    y_sp = jax.shard_map(
        local,
        mesh=sp_mesh, in_specs=(P(), P(), P(None, "spatial")),
        out_specs=P(None, "spatial"), check_vma=False,
    )(p, bs, x)

    np.testing.assert_allclose(
        np.asarray(y_sp), np.asarray(y_dense), atol=2e-5
    )


def test_halo_pad_shapes(sp_mesh):
    x = _x(10)

    def local(x):
        return halo_pad_1d(x, 2, HaloExchangerSendRecv("spatial"))

    out = jax.shard_map(
        local, mesh=sp_mesh, in_specs=P(None, "spatial"),
        out_specs=P(None, "spatial"), check_vma=False,
    )(x)
    # each slab of 8 becomes 12 -> gathered [N, 4*12, W, C]
    assert out.shape == (N, SP * (H // SP + 4), W, C)
