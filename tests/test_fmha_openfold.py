"""contrib.fmha (packed-qkv varlen MHA) and contrib.openfold (pair-biased
attention + small-shape LayerNorm) vs eager references.

Mirrors the reference contrib test style (``apex/contrib/test/fmha/``,
the openfold_triton README's parity checks).
"""
import jax
import jax.numpy as jnp
import pytest

from apex_tpu.contrib.fmha import FMHA, fmha_varlen
from apex_tpu.contrib.openfold import (
    AttnTri,
    LayerNormSmallShapeOptImpl,
    attention_core,
    attention_reference,
    can_use_fused_attention,
    layer_norm_small_shape,
)
from apex_tpu.ops.flash_attention import mha_reference_varlen


# ---------------------------------------------------------------------------
# contrib.fmha
# ---------------------------------------------------------------------------


def _packed_qkv(key, lens, h=2, d=16):
    total = sum(lens)
    qkv = jax.random.normal(key, (total, 3, h, d))
    cu = jnp.asarray([0] + list(jnp.cumsum(jnp.asarray(lens))), jnp.int32)
    return qkv, cu, total


def test_fmha_varlen_matches_per_sequence_reference():
    qkv, cu, total = _packed_qkv(jax.random.PRNGKey(0), [24, 40, 16])
    out = fmha_varlen(qkv, cu)
    ref = mha_reference_varlen(qkv[:, 0], qkv[:, 1], qkv[:, 2], cu)
    assert out.shape == (total, 2, 16)
    assert jnp.abs(out - ref).max() < 2e-5


def test_fmha_module_hidden_layout_roundtrip():
    h, d = 2, 16
    hidden = h * d
    qkv, cu, total = _packed_qkv(jax.random.PRNGKey(1), [32, 32], h=h, d=d)
    mod = FMHA(hidden_size=hidden, num_attention_heads=h)
    out = mod(qkv.reshape(total, 3 * hidden), cu)
    ref = mha_reference_varlen(qkv[:, 0], qkv[:, 1], qkv[:, 2], cu)
    assert out.shape == (total, hidden)
    assert jnp.abs(out - ref.reshape(total, hidden)).max() < 2e-5


def test_fmha_dropout_inference_mode_off():
    """is_training=False disables dropout like the reference fmha."""
    qkv, cu, _ = _packed_qkv(jax.random.PRNGKey(2), [16, 16])
    mod = FMHA(hidden_size=32, num_attention_heads=2,
               attention_probs_dropout_prob=0.5)
    total = qkv.shape[0]
    flat = qkv.reshape(total, 96)
    o_eval = mod(flat, cu, is_training=False)
    o_eval2 = mod(flat, cu, is_training=False)
    assert jnp.abs(o_eval - o_eval2).max() == 0.0
    o_train = mod(flat, cu, is_training=True, dropout_seed=3)
    assert jnp.abs(o_train - o_eval).max() > 0.0


def test_fmha_bad_qkv_shape():
    with pytest.raises(ValueError, match="total, 3, h, d"):
        fmha_varlen(jnp.zeros((8, 2, 2, 4)), jnp.asarray([0, 8]))


# ---------------------------------------------------------------------------
# contrib.openfold attention
# ---------------------------------------------------------------------------


def test_openfold_attention_bias_matches_reference():
    key = jax.random.PRNGKey(3)
    ks = jax.random.split(key, 4)
    b, h, n, d = 3, 2, 32, 16
    q, k, v = (jax.random.normal(ks[i], (b, h, n, d)) for i in range(3))
    bias = jax.random.normal(ks[3], (1, h, n, n)) * 0.5
    out = attention_core(q, k, v, bias=bias)
    ref = attention_reference(q, k, v, bias=bias)
    assert jnp.abs(out - ref).max() < 2e-5


def test_openfold_attention_mask_and_bias_5dim():
    """The AlphaFold calling shape: [1, b, h, n, d] operands, [b, 1, 1, n]
    key mask, [1, h, n, n] pair bias."""
    key = jax.random.PRNGKey(4)
    ks = jax.random.split(key, 5)
    b, h, n, d = 2, 2, 32, 16
    q, k, v = (jax.random.normal(ks[i], (1, b, h, n, d)) for i in range(3))
    mask = jax.random.bernoulli(ks[3], 0.8, (b, 1, 1, n)).astype(jnp.float32)
    # keep at least one key per row alive (fully-masked rows follow the
    # flash kernel's zeros convention, not softmax-of-all--inf)
    mask = mask.at[:, :, :, 0].set(1.0)
    bias = jax.random.normal(ks[4], (h, n, n))[None] * 0.3
    out = AttnTri(q, k, v, mask, bias, 1e9)
    ref = attention_reference(q, k, v, mask=mask, bias=bias)
    assert out.shape == (1, b, h, n, d)
    assert jnp.abs(out - ref).max() < 2e-5


def test_openfold_attention_bias_grads():
    key = jax.random.PRNGKey(5)
    ks = jax.random.split(key, 4)
    b, h, n, d = 2, 2, 32, 16
    q, k, v = (jax.random.normal(ks[i], (b, h, n, d)) for i in range(3))
    bias = jax.random.normal(ks[3], (1, h, n, n)) * 0.5

    gf = jax.grad(lambda bb: jnp.sum(attention_core(q, k, v, bias=bb) ** 2))(bias)
    gr = jax.grad(lambda bb: jnp.sum(attention_reference(q, k, v, bias=bb) ** 2))(bias)
    assert gf.shape == bias.shape
    assert jnp.abs(gf - gr).max() < 5e-4


def test_openfold_can_use_fused_attention():
    assert isinstance(can_use_fused_attention((2, 2, 32, 16), True, True,
                                              interpret=True), bool)


# ---------------------------------------------------------------------------
# contrib.openfold layer norm
# ---------------------------------------------------------------------------


def test_openfold_layer_norm_matches_jax():
    key = jax.random.PRNGKey(6)
    x = jax.random.normal(key, (4, 8, 32))
    w = jax.random.normal(jax.random.fold_in(key, 1), (32,)) + 1.0
    b = jax.random.normal(jax.random.fold_in(key, 2), (32,))
    y = layer_norm_small_shape(x, (32,), w, b)
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    ref = (x - mu) / jnp.sqrt(var + 1e-5) * w + b
    assert jnp.abs(y - ref).max() < 1e-5
    # reference-named .apply alias
    y2 = LayerNormSmallShapeOptImpl.apply(x, (32,), w, b)
    assert jnp.abs(y - y2).max() == 0.0


def test_openfold_layer_norm_shape_validation():
    with pytest.raises(ValueError, match="normalized_shape"):
        layer_norm_small_shape(jnp.zeros((4, 8)), (16,), jnp.ones(16),
                               jnp.zeros(16))


def test_openfold_attention_per_key_bias_broadcasts():
    """A [.., 1, k] per-key bias (docstring-legal, broadcast over q) must
    work — the wrapper materialises the q/k dims before the kernel."""
    key = jax.random.PRNGKey(7)
    ks = jax.random.split(key, 4)
    b, h, n, d = 2, 2, 32, 16
    q, k, v = (jax.random.normal(ks[i], (b, h, n, d)) for i in range(3))
    bias = jax.random.normal(ks[3], (1, h, 1, n)) * 0.5
    out = attention_core(q, k, v, bias=bias)
    ref = attention_reference(q, k, v, bias=bias)
    assert jnp.abs(out - ref).max() < 2e-5


def test_openfold_attention_5dim_leading_dim_validated():
    key = jax.random.PRNGKey(8)
    ks = jax.random.split(key, 4)
    b, h, n, d = 2, 2, 16, 16
    q, k, v = (jax.random.normal(ks[i], (b, h, n, d)) for i in range(3))
    bad_mask = jnp.ones((2, b, 1, 1, n))
    with pytest.raises(ValueError, match="leading 1 dim"):
        attention_core(q, k, v, mask=bad_mask)
    with pytest.raises(ValueError, match="leading 1 dim"):
        attention_reference(q, k, v, mask=bad_mask)


def test_flash_bias_grad_false_returns_zeros():
    """bias_grad=False: constant-bias cotangent is zeros and fwd output is
    identical to bias_grad=True."""
    from apex_tpu.ops.flash_attention import flash_attention
    key = jax.random.PRNGKey(9)
    ks = jax.random.split(key, 4)
    q, k, v = (jax.random.normal(ks[i], (2, 2, 32, 16)) for i in range(3))
    bias = jax.random.normal(ks[3], (1, 2, 32, 32))
    o1 = flash_attention(q, k, v, bias=bias, block_q=16, block_k=16)
    o2 = flash_attention(q, k, v, bias=bias, bias_grad=False,
                         block_q=16, block_k=16)
    assert jnp.abs(o1 - o2).max() == 0.0
    db = jax.grad(lambda bb: jnp.sum(flash_attention(
        q, k, v, bias=bb, bias_grad=False, block_q=16, block_k=16) ** 2)
    )(bias)
    assert jnp.abs(db).max() == 0.0
    # dq still flows
    dq = jax.grad(lambda qq: jnp.sum(flash_attention(
        qq, k, v, bias=bias, bias_grad=False, block_q=16, block_k=16) ** 2)
    )(q)
    assert jnp.abs(dq).max() > 0.0


def test_openfold_mask_grad_finite_with_bias():
    """A general (non key-only) {0,1} mask folded to (mask-1)*inf must not
    leak inf-scaled terms into autodiff when a learned bias is present
    (stop_gradient on the folded mask; the reference returns no dmask)."""
    key = jax.random.PRNGKey(9)
    ks = jax.random.split(key, 5)
    b, h, n, d = 2, 2, 32, 16
    q, k, v = (jax.random.normal(ks[i], (b, h, n, d)) for i in range(3))
    bias = jax.random.normal(ks[3], (1, h, n, n)) * 0.5
    # per-(q,k) mask -> additive-fold path, with some masked entries
    mask = jax.random.bernoulli(ks[4], 0.8, (b, 1, n, n)).astype(jnp.float32)
    mask = mask.at[..., 0].set(1.0)  # keep every row alive

    def loss(m, bb):
        return jnp.sum(attention_core(q, k, v, mask=m, bias=bb) ** 2)

    dm, db = jax.grad(loss, argnums=(0, 1))(mask, bias)
    assert jnp.all(jnp.isfinite(db))
    # folded mask carries no gradient at all
    assert jnp.abs(dm).max() == 0.0
