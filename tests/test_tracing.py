"""ISSUE-17 observability: end-to-end request tracing, exact-sum latency
attribution, and the crash flight recorder.

Covers the satellites around the tracing tentpole:

- TaggedRecorder close() ownership — two tagged views over ONE shared
  JSONL stream, one replica's teardown must not close the file out from
  under the other (``owns_sink=False`` default);
- the unified cross-sink record schema — every persisting sink stamps
  ``t_wall`` through the same :func:`stamp_wall` choke point;
- ``read_jsonl`` post-mortem hardening — a torn FINAL line (writer
  SIGKILLed mid-write) is tolerated and counted, a mid-file tear still
  raises;
- the span-causality property — a chaos fleet (replica kill, forced
  preemption via fail_allocs, prefix eviction) under VirtualClock
  yields rooted span trees, monotone timestamps, exactly one terminal
  span per offered request, and TTFT attribution terms that sum to the
  measured TTFT within 1%;
- CI wiring — tools/trace_report.py CHECKS run tier-1 and its CLI exit
  codes hold; compare_bench gates ``trace_overhead_pct`` and the
  attribution-summary schema; the committed CPU-smoke artifact parses.
"""
import json
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

from apex_tpu import telemetry  # noqa: E402
from apex_tpu.telemetry import (  # noqa: E402
    JsonlRecorder,
    RingBufferRecorder,
    TaggedRecorder,
    read_jsonl,
)
from apex_tpu.telemetry.spans import ATTR_TERMS  # noqa: E402

import trace_report  # noqa: E402  (tools/)
from tools import compare_bench  # noqa: E402


# ---------------------------------------------------------------------------
# satellite 1: TaggedRecorder close() ownership
# ---------------------------------------------------------------------------
class TestTaggedRecorderOwnership:
    def test_shared_sink_survives_one_tagger_close(self, tmp_path):
        """The fleet topology: two replicas' TaggedRecorders over ONE
        JsonlRecorder. Tearing one replica down (close) must not close
        the shared stream — the survivor keeps recording."""
        path = tmp_path / "shared.jsonl"
        shared = JsonlRecorder(path, only_logging_process=False)
        a = TaggedRecorder(shared, replica_id=0)
        b = TaggedRecorder(shared, replica_id=1)
        a.record({"event": "x"})
        a.close()  # replica 0 dies
        b.record({"event": "y"})  # survivor must still reach the file
        shared.close()
        recs = read_jsonl(path)
        assert [(r["event"], r["replica_id"]) for r in recs] == [
            ("x", 0), ("y", 1)]

    def test_default_does_not_own_sink(self):
        assert TaggedRecorder(RingBufferRecorder()).owns_sink is False

    def test_owns_sink_true_cascades_close(self, tmp_path):
        path = tmp_path / "private.jsonl"
        private = JsonlRecorder(path, only_logging_process=False)
        t = TaggedRecorder(private, host=3, owns_sink=True)
        t.record({"event": "x"})
        t.close()
        t.record({"event": "after"})  # dropped: underlying file closed
        assert [r["event"] for r in read_jsonl(path)] == ["x"]


# ---------------------------------------------------------------------------
# satellite 2: unified t_wall stamping across sinks
# ---------------------------------------------------------------------------
class TestCrossSinkSchema:
    def test_every_persisting_sink_stamps_t_wall(self, tmp_path):
        """Schema canary: a record written through ANY persisting sink
        (JSONL file, in-memory ring, tagged view over either) carries
        ``t_wall`` — so ring-sourced flight-recorder dumps line up with
        the live JSONL stream on the same axis."""
        path = tmp_path / "t.jsonl"
        jsonl = JsonlRecorder(path, only_logging_process=False)
        jsonl.record({"event": "a"})
        jsonl.close()
        ring = RingBufferRecorder()
        ring.record({"event": "b"})
        tagged_ring = RingBufferRecorder()
        TaggedRecorder(tagged_ring, pod="p").record({"event": "c"})
        stamped = [read_jsonl(path)[0], ring.records[0],
                   tagged_ring.records[0]]
        for rec in stamped:
            assert rec["t_wall"] > 0, rec

    def test_existing_t_wall_wins(self):
        ring = RingBufferRecorder()
        ring.record({"event": "x", "t_wall": 123.25})
        assert ring.records[0]["t_wall"] == 123.25


# ---------------------------------------------------------------------------
# satellite 3: read_jsonl torn-tail tolerance
# ---------------------------------------------------------------------------
class TestReadJsonlTornTail:
    def test_torn_final_line_tolerated_and_counted(self, tmp_path):
        p = tmp_path / "torn.jsonl"
        good = [{"event": "span", "i": i} for i in range(3)]
        with open(p, "w") as f:
            for r in good:
                f.write(json.dumps(r) + "\n")
            f.write('{"event": "span", "i": 3, "tru')  # SIGKILL mid-write
        stats = {}
        recs = read_jsonl(p, stats=stats)
        assert recs == good
        assert stats["torn_lines"] == 1

    def test_clean_file_counts_zero_torn(self, tmp_path):
        p = tmp_path / "clean.jsonl"
        p.write_text('{"event": "a"}\n{"event": "b"}\n')
        stats = {}
        assert len(read_jsonl(p, stats=stats)) == 2
        assert stats["torn_lines"] == 0

    def test_mid_file_tear_still_raises(self, tmp_path):
        """Append-only format: corruption anywhere BEFORE the final
        line means the file is not what we wrote — that must raise, not
        be papered over."""
        p = tmp_path / "corrupt.jsonl"
        p.write_text('{"event": "a"}\n{"ev GARBAGE\n{"event": "b"}\n')
        with pytest.raises(json.JSONDecodeError):
            read_jsonl(p)


# ---------------------------------------------------------------------------
# satellite 4: span-causality property under chaos
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def chaos_trace():
    """One deterministic chaos fleet run: replica 0 killed mid-flight,
    forced preemption (alloc failures), prefix eviction — all under
    VirtualClock so every timestamp is a deterministic function of the
    instrumented code's own clock reads."""
    from serving_check import _tiny_cfg, _tiny_params

    from apex_tpu.resilience.chaos import ServingChaos
    from apex_tpu.serving import Request
    from apex_tpu.serving.fleet import ReplicaFleet
    from apex_tpu.serving.robustness import VirtualClock

    cfg = _tiny_cfg()
    params = _tiny_params(cfg)
    sink = telemetry.RingBufferRecorder(capacity=100000)
    chaos = ServingChaos()
    chaos.kill_replica_at(0, 2)
    chaos.evict_prefix_cache(2)
    chaos.fail_allocs(3)
    fleet = ReplicaFleet(cfg, params, n_replicas=2, sink=sink,
                         clock=VirtualClock(dt=0.01), chaos=chaos,
                         n_slots=2, num_pages=64)
    shared = [1, 2, 3, 4]
    reqs = [Request(rid=i, prompt=shared[: 2 + (i % 2)] + [5 + i],
                    max_new_tokens=4, arrival_step=i % 3)
            for i in range(8)]
    fleet.generate(reqs, max_steps=500)
    return list(sink.records), reqs, fleet


class TestSpanCausalityUnderChaos:
    def test_chaos_actually_fired(self, chaos_trace):
        _, _, fleet = chaos_trace
        assert fleet.replica_deaths >= 1

    def test_span_trees_are_rooted_and_monotone(self, chaos_trace):
        records, _, _ = chaos_trace
        traces = trace_report.build_traces(records)
        assert trace_report.validate(traces) == []

    def test_exactly_one_terminal_span_per_offered_request(
            self, chaos_trace):
        records, reqs, _ = chaos_trace
        traces = trace_report.build_traces(records)
        for r in reqs:
            assert r.trace is not None, f"rid={r.rid} never traced"
            spans = traces.get(r.trace.trace_id)
            assert spans, f"rid={r.rid}: no spans for {r.trace.trace_id}"
            terminals = [s for s in spans if s.get("terminal")]
            assert len(terminals) == 1, (r.rid, terminals)

    def test_children_start_within_parent_window(self, chaos_trace):
        records, _, _ = chaos_trace
        traces = trace_report.build_traces(records)
        for tid, spans in traces.items():
            if not tid.startswith("req-"):
                continue
            by_id = {s["span_id"]: s for s in spans}
            for s in spans:
                pid = s.get("parent_id")
                if pid is None:
                    continue
                assert s["t_start"] >= by_id[pid]["t_start"] - 1e-9, (
                    tid, s)

    def test_ttft_terms_sum_to_measured_ttft(self, chaos_trace):
        _, reqs, _ = chaos_trace
        checked = 0
        for r in reqs:
            if r.t_first_token is None or r.attr_ttft is None:
                continue
            measured = r.t_first_token - r.t_arrival
            if measured <= 0:
                continue
            total = sum(r.attr_ttft.values())
            assert abs(total - measured) / measured <= 0.01, (
                r.rid, total, measured, r.attr_ttft)
            checked += 1
        assert checked >= 1

    def test_e2e_terms_sum_to_measured_e2e(self, chaos_trace):
        _, reqs, _ = chaos_trace
        checked = 0
        for r in reqs:
            if r.attr is None or r.t_done is None or r.t_arrival is None:
                continue
            measured = r.t_done - r.t_arrival
            if measured <= 0:
                continue
            total = sum(r.attr.values())
            assert abs(total - measured) / measured <= 0.01, (
                r.rid, total, measured, r.attr)
            checked += 1
        assert checked >= 1

    def test_replica_death_dumps_black_box(self, chaos_trace):
        records, _, _ = chaos_trace
        boxes = [r for r in records if r.get("event") == "blackbox"]
        assert boxes and boxes[0]["reason"] == "replica_down"
        replayed = [r for r in records if r.get("blackbox_replay")]
        assert replayed, "black box should replay the dead engine's ring"

    def test_fleet_summary_carries_attribution(self, chaos_trace):
        _, _, fleet = chaos_trace
        att = fleet.last_stats["attribution"]
        assert tuple(att["terms"]) == ATTR_TERMS
        assert att["ttft_sum_rel_err_max"] <= 0.01
        assert set(att["ttft_ms"]) == set(ATTR_TERMS)


# ---------------------------------------------------------------------------
# satellite 6a: tools/trace_report.py tier-1 wiring
# ---------------------------------------------------------------------------
class TestTraceReportCLI:
    @pytest.mark.parametrize("check", sorted(trace_report.CHECKS))
    def test_each_check_passes(self, check):
        res = trace_report.CHECKS[check]()
        assert res["ok"], res

    def test_cli_self_exit_zero(self, capsys):
        rc = trace_report.main(
            ["--self", "--check", "detects_broken_causality", "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0 and out["ok"]

    def test_cli_failure_exit_one(self, monkeypatch):
        monkeypatch.setitem(trace_report.CHECKS, "seeded_fail",
                            lambda: {"ok": False})
        assert trace_report.main(["--self", "--check", "seeded_fail"]) == 1

    def test_cli_infra_error_exit_two(self, monkeypatch):
        def boom():
            raise RuntimeError("infra")

        monkeypatch.setitem(trace_report.CHECKS, "seeded_boom", boom)
        assert trace_report.main(["--self", "--check", "seeded_boom"]) == 2

    def test_report_exits_nonzero_on_broken_stream(self, tmp_path):
        """The CI contract: a span stream with an orphan parent is a
        broken trace — the report run must fail, not shrug."""
        p = tmp_path / "broken.jsonl"
        spans = [
            {"event": "span", "name": "request", "trace_id": "req-0",
             "span_id": 1, "parent_id": None, "t_start": 0.0,
             "t_end": 1.0, "terminal": True},
            {"event": "span", "name": "orphan", "trace_id": "req-0",
             "span_id": 2, "parent_id": 999, "t_start": 0.2,
             "t_end": 0.4},
        ]
        with open(p, "w") as f:
            for s in spans:
                f.write(json.dumps(s) + "\n")
        assert trace_report.main([str(p)]) == 1
        del spans[1]["parent_id"]
        spans[1]["t_end"] = 0.3
        with open(p, "w") as f:
            for s in spans:
                f.write(json.dumps(s) + "\n")
        assert trace_report.main([str(p)]) == 0


# ---------------------------------------------------------------------------
# satellite 6b: compare_bench gates (trace_overhead + attribution schema)
# ---------------------------------------------------------------------------
def _valid_attr_block():
    pct = {"p50": 1.0, "p90": 2.0, "p99": 3.0}
    return {
        "terms": list(compare_bench.ATTR_TERMS),
        "ttft_ms": {t: dict(pct) for t in compare_bench.ATTR_TERMS},
        "e2e_ms": {t: dict(pct) for t in compare_bench.ATTR_TERMS},
        "n_attributed": 4,
        "ttft_sum_rel_err_max": 0.0,
    }


class TestBenchWiring:
    def test_trace_overhead_leg_extracted(self):
        names = [m[0] for m in compare_bench.METRICS]
        assert "trace_overhead_pct" in names
        assert "trace_overhead_pct" in compare_bench.ABS_TOLERANCE
        legs = compare_bench.extract_legs(
            {"trace_overhead": {"overhead_pct": 0.4}})
        assert legs["trace_overhead_pct"] == -0.4  # lower-is-better

    def test_overhead_within_abs_tolerance_not_regression(self):
        base = {"trace_overhead": {"overhead_pct": 0.1}}
        new = {"trace_overhead": {"overhead_pct": 0.8}}
        cmp = compare_bench.compare(base, new, threshold=0.05)
        assert not any(r["leg"] == "trace_overhead_pct"
                       for r in cmp["regressions"])
        new = {"trace_overhead": {"overhead_pct": 2.0}}
        cmp = compare_bench.compare(base, new, threshold=0.05)
        assert any(r["leg"] == "trace_overhead_pct"
                   for r in cmp["regressions"])

    def test_attribution_schema_valid_block_passes(self):
        bench = {"serving_throughput": {"attribution": _valid_attr_block()},
                 "serving_fleet": {"attribution": _valid_attr_block()}}
        assert compare_bench.attribution_problems(bench) == []

    def test_attribution_schema_absent_block_is_fine(self):
        assert compare_bench.attribution_problems(
            {"serving_throughput": None}) == []
        assert compare_bench.attribution_problems({}) == []

    def test_attribution_schema_flags_drift(self):
        bad = _valid_attr_block()
        del bad["ttft_ms"]["decode"]  # missing term
        probs = compare_bench.attribution_problems(
            {"serving_fleet": {"attribution": bad}})
        assert any("ttft_ms" in p for p in probs)
        broken_sum = _valid_attr_block()
        broken_sum["ttft_sum_rel_err_max"] = 0.5  # identity broken
        probs = compare_bench.attribution_problems(
            {"serving_fleet": {"attribution": broken_sum}})
        assert any("rel_err" in p for p in probs)

    def test_compare_flags_malformed_attribution_as_regression(self):
        bad = _valid_attr_block()
        bad["terms"] = ["queue_wait"]
        new = {"serving_fleet": {"attribution": bad}}
        cmp = compare_bench.compare({}, new, threshold=0.05)
        assert any(r["leg"] == "attribution_schema"
                   for r in cmp["regressions"])

    def test_committed_cpu_smoke_artifact_parses(self):
        art = json.loads(
            (REPO / "bench_artifacts" /
             "trace_overhead_cpu_smoke.json").read_text())
        leg = art["trace_overhead"]
        assert leg["within_1pct"] is True
        assert leg["steps"] > 0 and leg["n_requests"] > 0
        assert compare_bench.attribution_problems(art) == []
