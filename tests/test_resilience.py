"""apex_tpu.resilience: fault-tolerant training machinery.

Covers the four pillars (ISSUE 5): preemption-safe checkpointing
(atomic writes, retention, async barrier, corruption fallback,
SIGTERM emergency flush), resumable TrainState, last-good rewind, and
the hang watchdog — each exercised through the chaos harness
(``apex_tpu.resilience.chaos``), plus the promoted retry policy and the
``tools/resilience_check.py --self`` CI smoke (the tier-1 wiring, like
``static_audit --self``). The subprocess crash/resume bit-exactness
test lives in ``tests/test_crash_resume.py``.
"""
import json
import os
import signal
import sys
import threading
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from apex_tpu.amp.scaler import LossScaler  # noqa: E402
from apex_tpu.checkpoint import (  # noqa: E402
    CheckpointCorruptError, load_checkpoint, save_checkpoint,
)
from apex_tpu.optimizers import FusedAdam  # noqa: E402
from apex_tpu.resilience import (  # noqa: E402
    ChaosError,
    ChaosMonkey,
    CheckpointManager,
    HangError,
    HangWatchdog,
    IndexedBatches,
    ResumableIterator,
    RetryPolicy,
    RewindController,
    RewindExhaustedError,
    StallingSink,
    TRANSIENT_COMPILE_POLICY,
    capture,
    corrupt_checkpoint,
    poison_grads,
    resume_or_init,
    retry_call,
    send_preemption,
)
from apex_tpu import telemetry  # noqa: E402
from apex_tpu.telemetry import numerics as tnum  # noqa: E402
from tools import resilience_check  # noqa: E402


# ---------------------------------------------------------------------------
# retry.py (satellite: promoted from bench.py)
# ---------------------------------------------------------------------------
class TestRetry:
    def test_success_no_retry(self):
        calls = []
        assert retry_call(lambda: calls.append(1) or 42) == 42
        assert len(calls) == 1

    def test_non_transient_surfaces_immediately(self):
        calls = []

        def boom():
            calls.append(1)
            raise ValueError("real failure")

        policy = RetryPolicy(attempts=4, retry_on=(OSError,))
        with pytest.raises(ValueError):
            retry_call(boom, policy=policy)
        assert len(calls) == 1

    def test_transient_retries_then_succeeds_with_telemetry(self):
        calls, events = [], []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("storage blip")
            return "ok"

        policy = RetryPolicy(attempts=4, retry_on=(OSError,),
                             base_delay=0.01, max_delay=0.02)
        slept = []
        out = retry_call(flaky, policy=policy, tag="t",
                         sink=events.append, sleep=slept.append)
        assert out == "ok" and len(calls) == 3
        assert [e["event"] for e in events] == ["retry", "retry"]
        assert events[0]["attempt"] == 1 and events[0]["of"] == 4
        assert "OSError" in events[0]["error"]
        # jittered exponential: each delay bounded by base * 2^k
        assert len(slept) == 2
        assert 0.0 <= slept[0] <= 0.01 and 0.0 <= slept[1] <= 0.02

    def test_exhausted_attempts_raise_last(self):
        policy = RetryPolicy(attempts=2, retry_on=(OSError,))
        with pytest.raises(OSError):
            retry_call(lambda: (_ for _ in ()).throw(OSError("x")),
                       policy=policy)

    def test_compile_transport_filter(self):
        # the historical bench filter: class AND message must match
        good = Exception("remote_compile: HTTP 500 mid-stream")
        bad = Exception("HTTP 500")  # no remote_compile marker
        assert TRANSIENT_COMPILE_POLICY.is_transient(good)
        assert not TRANSIENT_COMPILE_POLICY.is_transient(bad)

    def test_zero_base_delay_never_sleeps(self):
        policy = RetryPolicy(attempts=3, retry_on=(OSError,))
        slept = []
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 2:
                raise OSError("blip")
            return 1

        retry_call(flaky, policy=policy, sleep=slept.append)
        assert slept == []

    def test_deadline_bounds_total_retry_time(self):
        """ISSUE-10 satellite: `deadline=` is an overall wall-clock
        budget across all attempts — when elapsed + the next backoff
        would cross it, the loop gives up early (a retry_deadline
        event, the last exception surfaces) even with attempts left."""
        calls, events, slept = [], [], []
        t = {"now": 0.0}

        def fake_sleep(d):
            slept.append(d)
            t["now"] += d

        def always_fails():
            calls.append(1)
            t["now"] += 0.4  # each attempt burns 0.4s of fake time
            raise OSError("down")

        policy = RetryPolicy(attempts=10, retry_on=(OSError,),
                             base_delay=1.0, max_delay=1.0,
                             deadline=2.0,
                             rng=__import__("random").Random(0))
        with pytest.raises(OSError):
            retry_call(always_fails, policy=policy, sink=events.append,
                       sleep=fake_sleep, clock=lambda: t["now"])
        # far fewer than 10 attempts: the budget cut it off
        assert 1 <= len(calls) < 10
        assert events[-1]["event"] == "retry_deadline"
        assert events[-1]["deadline_s"] == 2.0
        assert t["now"] < 2.0 + 1.0  # never slept past the budget

    def test_deadline_none_keeps_attempt_count_semantics(self):
        """No deadline: the historical attempts-only behaviour, every
        attempt runs."""
        calls = []
        policy = RetryPolicy(attempts=3, retry_on=(OSError,))
        with pytest.raises(OSError):
            retry_call(lambda: calls.append(1) or
                       (_ for _ in ()).throw(OSError("x")),
                       policy=policy)
        assert len(calls) == 3

    def test_deadline_not_crossed_retries_normally(self):
        """A roomy deadline changes nothing: transient retries proceed
        and succeed."""
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("blip")
            return "ok"

        policy = RetryPolicy(attempts=5, retry_on=(OSError,),
                             base_delay=0.001, deadline=60.0)
        assert retry_call(flaky, policy=policy) == "ok"
        assert len(calls) == 3


# ---------------------------------------------------------------------------
# checkpoint.py hardening (satellite)
# ---------------------------------------------------------------------------
class TestCheckpointHardening:
    def test_atomic_save_failure_keeps_previous(self, tmp_path):
        p = str(tmp_path / "ck")
        save_checkpoint(p, {"w": jnp.arange(4.0)})

        class Unserializable:
            pass

        with pytest.raises(Exception):
            save_checkpoint(p, {"w": Unserializable()})
        # the failed write neither clobbered the old tree nor left tmp
        back = load_checkpoint(p)
        np.testing.assert_array_equal(np.asarray(back["w"]),
                                      np.arange(4.0))
        assert not [d for d in os.listdir(tmp_path) if ".tmp-" in d]

    def test_truncated_checkpoint_raises_typed_error(self, tmp_path):
        p = str(tmp_path / "ck")
        state = {"w": jnp.arange(64.0)}
        save_checkpoint(p, state)
        corrupt_checkpoint(p)
        with pytest.raises(CheckpointCorruptError) as ei:
            load_checkpoint(p, target=state)
        assert ei.value.path == os.path.abspath(p)
        assert ei.value.__cause__ is not None

    def test_missing_checkpoint_is_file_not_found(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            load_checkpoint(str(tmp_path / "nope"))

    def test_overwrite_false_refuses_before_writing(self, tmp_path):
        p = str(tmp_path / "ck")
        save_checkpoint(p, {"w": jnp.zeros(2)})
        with pytest.raises(FileExistsError):
            save_checkpoint(p, {"w": jnp.ones(2)}, overwrite=False)
        # it failed BEFORE staging: no tmp tree was created
        assert not [d for d in os.listdir(tmp_path) if ".tmp-" in d]

    def test_dead_writer_tmp_swept_on_next_save(self, tmp_path):
        """A crashed previous process's full-size partial tree (pid in
        the name, writer gone) is cleaned by the next save."""
        p = str(tmp_path / "ck")
        dead = f"{p}.tmp-999999999"  # no such pid
        os.makedirs(dead)
        save_checkpoint(p, {"w": jnp.zeros(2)})
        assert not os.path.exists(dead)
        assert os.path.exists(p)


# ---------------------------------------------------------------------------
# CheckpointManager (tentpole pillar 1)
# ---------------------------------------------------------------------------
def _mini_state(step, fill, *, opt=None, params=None):
    params = params if params is not None else {
        "w": jnp.full((8,), float(fill), jnp.bfloat16),
        "b": jnp.full((4,), float(fill), jnp.float32)}
    opt_state = opt.init(params) if opt is not None else None
    return capture(step, params, opt_state, data={"position": step})


class TestCheckpointManager:
    def test_save_restore_roundtrip_with_packed_state(self, tmp_path):
        opt = FusedAdam(lr=1e-2, packed=True, packed_interpret=True,
                        packed_chunk_size=256, master_weights=True)
        sc = LossScaler("dynamic")
        params = {"w": jnp.arange(8.0, dtype=jnp.bfloat16),
                  "b": jnp.ones((4,), jnp.float32)}
        opt_state = opt.init(params)
        g = jax.tree_util.tree_map(jnp.ones_like, params)
        params2, opt_state2 = opt.step(g, opt_state, params)
        sstate = sc.init_state()._replace(loss_scale=jnp.float32(512.0),
                                          consecutive_skips=jnp.int32(2))
        mon = tnum.NumericsMonitor(params)
        metrics = telemetry.accumulate(telemetry.init_metrics(),
                                       loss=jnp.float32(1.5), tokens=8)
        rng = jax.random.PRNGKey(7)
        st = capture(5, params2, opt_state2, scaler=sstate, rng=rng,
                     data={"position": 5}, metrics=metrics,
                     numerics=mon.init())
        mgr = CheckpointManager(str(tmp_path), keep_n=3)
        mgr.save(st, blocking=True)

        def init_fn():
            return capture(0, params, opt.init(params),
                           scaler=sc.init_state(),
                           rng=jax.random.PRNGKey(0),
                           data={"position": 0},
                           metrics=telemetry.init_metrics(),
                           numerics=mon.init())

        back, resumed = resume_or_init(mgr, init_fn)
        assert resumed and back.step == 5
        assert back.data == {"position": 5}
        # bit-exact across every leaf, packed flat buffers included
        for a, b in zip(jax.tree_util.tree_leaves((st.params, st.opt_state,
                                                   st.scaler, st.rng,
                                                   st.metrics)),
                        jax.tree_util.tree_leaves((back.params,
                                                   back.opt_state,
                                                   back.scaler, back.rng,
                                                   back.metrics))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert float(back.scaler.loss_scale) == 512.0
        assert int(back.scaler.consecutive_skips) == 2

    def test_retention_gc_keeps_newest(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_n=2)
        for s in (1, 2, 3, 4, 5):
            mgr.save(_mini_state(s, s))
        mgr.wait_until_finished()
        assert mgr.all_steps() == [4, 5]

    def test_emergency_checkpoints_survive_gc(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_n=1)
        mgr.save(_mini_state(1, 1), blocking=True, emergency=True)
        for s in (2, 3, 4):
            mgr.save(_mini_state(s, s), blocking=True)
        assert 1 in mgr.all_steps() and 4 in mgr.all_steps()

    def test_emergency_save_is_always_blocking(self, tmp_path):
        # a non-blocking emergency would clobber the single-slot async
        # tracking of the in-flight save it deliberately skipped the
        # barrier for — loud error, not a silent race
        mgr = CheckpointManager(str(tmp_path), async_save=True)
        with pytest.raises(ValueError, match="always blocking"):
            mgr.save(_mini_state(1, 1), blocking=False, emergency=True)
        mgr.save(_mini_state(1, 1), emergency=True)  # sync despite async_save
        assert mgr.all_steps() == [1]  # committed with no barrier needed

    def test_restore_explicit_missing_step_raises(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_n=2)
        for s in (3, 6):
            mgr.save(_mini_state(s, s), blocking=True)
        with pytest.raises(FileNotFoundError, match=r"step 9.*\[3, 6\]"):
            mgr.restore(_mini_state(0, 0), step=9)
        # in-range explicit step still restores
        assert mgr.restore(_mini_state(0, 0), step=3).step == 3

    def test_maybe_save_cadence(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), save_every=3)
        saved = [s for s in range(10)
                 if mgr.maybe_save(_mini_state(s, s))]
        mgr.wait_until_finished()
        assert saved == [3, 6, 9]
        assert mgr.all_steps() == [3, 6, 9][-mgr.keep_n:]

    def test_maybe_save_every_step_skips_step_zero(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path / "a"), save_every=1)
        saved = [s for s in range(4)
                 if mgr.maybe_save(_mini_state(s, s))]
        mgr.wait_until_finished()
        assert saved == [1, 2, 3]  # never the uninitialized step 0
        # save_every=0: every call, step 0 included
        mgr0 = CheckpointManager(str(tmp_path / "b"), save_every=0)
        assert mgr0.maybe_save(_mini_state(0, 0))
        mgr0.wait_until_finished()

    def test_async_failed_write_surfaces_at_barrier(self, tmp_path):
        chaos = ChaosMonkey().fail_write_at(2)
        rec = telemetry.RingBufferRecorder()
        mgr = CheckpointManager(str(tmp_path), chaos=chaos, sink=rec)
        mgr.save(_mini_state(2, 2))  # async; fails in the background
        with pytest.raises(ChaosError):
            mgr.wait_until_finished()
        assert "checkpoint_failed" in [r["event"] for r in rec.records]

    def test_failed_commit_leaves_previous_loadable(self, tmp_path):
        """The atomicity acceptance: a write failed mid-flight (after
        the array tree, before the rename) leaves the previous
        checkpoint fully loadable and the failed step invisible."""
        chaos = ChaosMonkey().fail_commit_at(4)
        mgr = CheckpointManager(str(tmp_path), chaos=chaos)
        mgr.save(_mini_state(2, 2), blocking=True)
        with pytest.raises(ChaosError):
            mgr.save(_mini_state(4, 4), blocking=True)
        assert mgr.all_steps() == [2]
        back = mgr.restore(_mini_state(0, 0))
        assert back.step == 2
        assert float(np.asarray(back.params["b"])[0]) == 2.0

    def test_corrupt_newest_falls_back_to_good(self, tmp_path):
        rec = telemetry.RingBufferRecorder()
        mgr = CheckpointManager(str(tmp_path), sink=rec)
        for s in (2, 4, 6):
            mgr.save(_mini_state(s, s), blocking=True)
        corrupt_checkpoint(str(tmp_path / "step_00000006"))
        corrupt_checkpoint(str(tmp_path / "step_00000004"))
        back = mgr.restore(_mini_state(0, 0))
        assert back.step == 2
        falls = [r for r in rec.records
                 if r["event"] == "checkpoint_fallback"]
        assert [f["step"] for f in falls] == [6, 4]

    def test_restore_none_when_empty(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        assert mgr.restore(_mini_state(0, 0)) is None
        st, resumed = resume_or_init(mgr, lambda: _mini_state(0, 0))
        assert not resumed and st.step == 0

    def test_all_checkpoints_failing_raises_not_reinit(self, tmp_path):
        """Checkpoints exist but none loads (here: a template whose
        structure no longer matches) — that must be a loud error, not a
        silent walk-off-the-end that lets resume_or_init restart the
        run from step 0."""
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(_mini_state(2, 2), blocking=True)
        bigger = capture(0, {"w": jnp.zeros((8,), jnp.bfloat16),
                             "b": jnp.zeros((4,)),
                             "extra": jnp.zeros((2,))},
                         None, data={"position": 0})
        rec = []
        mgr._record = rec.append
        with pytest.raises(CheckpointCorruptError):
            mgr.restore(bigger)
        assert rec and rec[0]["event"] == "checkpoint_fallback"

    def test_preemption_handler_flushes_emergency(self, tmp_path):
        rec = telemetry.RingBufferRecorder()
        mgr = CheckpointManager(str(tmp_path), keep_n=2, sink=rec)
        state_holder = {"state": _mini_state(7, 7)}
        mgr.install_preemption_handler(lambda: state_holder["state"])
        try:
            assert not mgr.preempted
            send_preemption(signal.SIGTERM)
            # handler runs synchronously in the main thread
            assert mgr.preempted
            assert 7 in mgr.all_steps()
            with open(tmp_path / "step_00000007" / "meta.json") as f:
                assert json.load(f)["emergency"] is True
            events = [r["event"] for r in rec.records]
            assert "preemption" in events and "checkpoint_saved" in events
        finally:
            mgr.uninstall_preemption_handler()
        # handler restored: SIGTERM handling back to whatever it was
        assert signal.getsignal(signal.SIGTERM) is not None

    def test_wait_bounded_by_watchdog(self, tmp_path):
        wd = HangWatchdog(timeout_s=0.3, poll_s=0.02)
        mgr = CheckpointManager(str(tmp_path), watchdog=wd)
        mgr._done.clear()  # simulate a wedged background write
        try:
            with pytest.raises(HangError) as ei:
                mgr.wait_until_finished()
            assert "wait_until_finished" in str(ei.value)
            assert "MainThread" in ei.value.stacks
        finally:
            mgr._done.set()
            wd.close()


# ---------------------------------------------------------------------------
# resumable iteration
# ---------------------------------------------------------------------------
class TestResumableIteration:
    def test_indexed_batches_roundtrip(self):
        it = IndexedBatches(lambda i: i * 10)
        assert [next(it) for _ in range(3)] == [0, 10, 20]
        st = it.state()
        it2 = IndexedBatches(lambda i: i * 10, position=st["position"])
        assert next(it2) == 30
        it2.skip(2)
        assert next(it2) == 60

    def test_iterator_drain_restore(self):
        it = ResumableIterator(lambda: iter(range(100)))
        assert [next(it) for _ in range(4)] == [0, 1, 2, 3]
        st = it.state()
        it.restore(st)
        assert next(it) == 4
        it.skip(5)
        assert next(it) == 10


# ---------------------------------------------------------------------------
# scaler consecutive-skip counter (satellite) + scaler_stall rule
# ---------------------------------------------------------------------------
class TestScalerStall:
    def test_consecutive_skips_counter(self):
        sc = LossScaler("dynamic", hysteresis=1)
        st = sc.init_state()
        for expect in (1, 2, 3):
            st = sc.update_scale(
                st._replace(found_inf=jnp.asarray(True)))
            assert int(st.consecutive_skips) == expect
        st = sc.update_scale(st)  # clean step resets the run
        assert int(st.consecutive_skips) == 0

    def test_static_scaler_also_counts(self):
        sc = LossScaler(128.0)
        st = sc.update_scale(
            sc.init_state()._replace(found_inf=jnp.asarray(True)))
        assert int(st.consecutive_skips) == 1

    def test_state_dict_roundtrip_includes_counter(self):
        sc = LossScaler("dynamic")
        st = sc.init_state()._replace(consecutive_skips=jnp.int32(5))
        sd = sc.state_dict(st)
        assert sd["consecutive_skips"] == 5
        back = sc.load_state_dict(sd)
        assert int(back.consecutive_skips) == 5
        # legacy dicts without the key load as zero
        del sd["consecutive_skips"]
        assert int(sc.load_state_dict(sd).consecutive_skips) == 0

    def test_scaler_stall_event_edge_triggered(self):
        """Past max_consecutive_skips the anomaly engine emits ONE
        scaler_stall (the rewind trigger) — not one per further skip."""
        params = {"w": jnp.ones((4,))}
        sc = LossScaler("dynamic", hysteresis=1)
        mon = tnum.NumericsMonitor(params, max_consecutive_skips=3)
        rec = telemetry.RingBufferRecorder()
        st, ns = sc.init_state(), mon.init()
        for _ in range(6):  # six consecutive overflowed updates
            st, ns = sc.update_scale(
                st._replace(found_inf=jnp.asarray(True)), numerics=ns)
            ns = mon.drain(ns, rec)
        jax.effects_barrier()
        stalls = [r for r in rec.records if r.get("kind") == "scaler_stall"]
        assert len(stalls) == 1
        assert stalls[0]["consecutive_skips"] == 3
        assert stalls[0]["max_consecutive_skips"] == 3
        # recovery then a second stall re-arms the edge
        st, ns = sc.update_scale(st, numerics=ns)  # clean
        ns = mon.drain(ns, rec)
        for _ in range(4):
            st, ns = sc.update_scale(
                st._replace(found_inf=jnp.asarray(True)), numerics=ns)
            ns = mon.drain(ns, rec)
        jax.effects_barrier()
        stalls = [r for r in rec.records if r.get("kind") == "scaler_stall"]
        assert len(stalls) == 2

    def test_stall_disabled_with_zero_budget(self):
        params = {"w": jnp.ones((4,))}
        sc = LossScaler("dynamic", hysteresis=1)
        mon = tnum.NumericsMonitor(params, max_consecutive_skips=0)
        rec = telemetry.RingBufferRecorder()
        st, ns = sc.init_state(), mon.init()
        for _ in range(5):
            st, ns = sc.update_scale(
                st._replace(found_inf=jnp.asarray(True)), numerics=ns)
            ns = mon.drain(ns, rec)
        jax.effects_barrier()
        assert not [r for r in rec.records
                    if r.get("kind") == "scaler_stall"]


# ---------------------------------------------------------------------------
# rewind (tentpole pillar 3)
# ---------------------------------------------------------------------------
class TestRewind:
    def test_ring_and_budget_trigger(self):
        ctl = RewindController(keep=2, skip_budget=3, snapshot_every=2)
        for s in (1, 2, 3, 4, 5, 6):
            ctl.offer(_mini_state(s, s), healthy=True)
        # snapshot_every=2 spacing, keep=2 -> ring holds {3, 5}
        assert [sn.step for sn in ctl._ring] == [3, 5]
        ctl.offer(_mini_state(7, 7),
                  consecutive_skips=jnp.int32(3))
        assert ctl.rewind_pending

    def test_anomaly_event_sink_triggers(self):
        ctl = RewindController()
        ctl.record({"event": "anomaly", "kind": "grad_spike"})
        assert not ctl.rewind_pending  # spikes alone do not rewind
        ctl.record({"event": "anomaly", "kind": "scaler_stall"})
        assert ctl.rewind_pending

    def test_rewind_restores_and_advances_data(self):
        rec = telemetry.RingBufferRecorder()
        ctl = RewindController(keep=2, recorder=rec)
        st = capture(4, {"w": jnp.full((4,), 4.0)}, None,
                     data={"position": 4})
        ctl.offer(st, healthy=True)
        it = IndexedBatches(lambda i: i, position=9)
        ctl.request_rewind("test trigger")
        back = ctl.rewind(data_iter=it, skip_batches=2, current_step=9)
        assert int(back.step) == 4
        np.testing.assert_array_equal(np.asarray(back.params["w"]),
                                      np.full((4,), 4.0))
        # the data stream does NOT rewind: current position + margin
        assert back.data == {"position": 11}
        assert not ctl.rewind_pending
        ev = [r for r in rec.records if r["event"] == "rewind"]
        assert len(ev) == 1
        assert ev[0]["to_step"] == 4 and ev[0]["step"] == 9
        assert ev[0]["trigger"] == "test trigger"

    def test_snapshot_is_donation_safe_copy(self):
        ctl = RewindController()
        w = jnp.arange(4.0)
        st = capture(1, {"w": w}, None)
        ctl.offer(st, healthy=True)
        snap_w = ctl._ring[0].state.params["w"]
        assert isinstance(snap_w, np.ndarray)
        # mutating the snapshot cannot touch the live array and vice versa
        snap_w[0] = 99.0
        assert float(w[0]) == 0.0

    def test_max_rewinds_exhausts(self):
        ctl = RewindController(max_rewinds=1)
        ctl.offer(_mini_state(1, 1), healthy=True)
        ctl.rewind()
        with pytest.raises(RewindExhaustedError):
            ctl.rewind()

    def test_rewind_without_snapshot_raises(self):
        with pytest.raises(RuntimeError):
            RewindController().rewind()

    def test_poison_grads_in_jit(self):
        grads = {"w": jnp.ones((4,), jnp.bfloat16)}

        @jax.jit
        def f(g, p):
            return poison_grads(g, p)

        clean = f(grads, False)
        np.testing.assert_array_equal(np.asarray(clean["w"], np.float32),
                                      np.ones(4))
        assert not np.any(np.isfinite(np.asarray(f(grads, True)["w"],
                                                 np.float32)))


# ---------------------------------------------------------------------------
# watchdog (tentpole pillar 4)
# ---------------------------------------------------------------------------
class TestWatchdog:
    def test_wait_completes_when_ready(self):
        with HangWatchdog(timeout_s=5.0, poll_s=0.01) as wd:
            ev = threading.Event()
            threading.Timer(0.05, ev.set).start()
            wd.wait(ev, "quick")  # returns, no raise
            assert wd.trips == 0

    def test_wait_trips_with_stack_dump_and_event(self):
        rec = telemetry.RingBufferRecorder()
        with HangWatchdog(timeout_s=0.2, poll_s=0.02, sink=rec) as wd:
            with pytest.raises(HangError) as ei:
                wd.wait(threading.Event(), "stuck drain")
            assert "stuck drain" in str(ei.value)
            assert "MainThread" in ei.value.stacks
        hangs = [r for r in rec.records if r["event"] == "hang"]
        assert len(hangs) == 1 and hangs[0]["what"] == "stuck drain"
        assert "MainThread" in hangs[0]["stacks"]

    def test_wait_predicate_form(self):
        t0 = time.monotonic()
        with HangWatchdog(timeout_s=5.0, poll_s=0.01) as wd:
            wd.wait(lambda: time.monotonic() - t0 > 0.05, "predicate")

    def test_armed_block_interrupted(self):
        """A stalled callback (chaos StallingSink shape) under armed()
        raises HangError instead of hanging the run."""
        sink = StallingSink(stall_s=30.0)
        with HangWatchdog(timeout_s=0.3, poll_s=0.02) as wd:
            with pytest.raises(HangError):
                with wd.armed("stalled telemetry drain"):
                    sink.record({"event": "x"})  # blocks ~30s unwatched
        sink.release()

    def test_armed_completes_without_trip(self):
        with HangWatchdog(timeout_s=5.0, poll_s=0.01) as wd:
            with wd.armed("fast block"):
                time.sleep(0.02)
            assert wd.trips == 0


# ---------------------------------------------------------------------------
# tools/resilience_check.py (satellite: CI smoke, tier-1 wiring)
# ---------------------------------------------------------------------------
class TestResilienceCheckCLI:
    @pytest.mark.parametrize("check", sorted(resilience_check.CHECKS))
    def test_each_check_passes(self, check):
        res = resilience_check.CHECKS[check]()
        assert res["ok"], res

    def test_cli_self_exit_zero(self, capsys):
        rc = resilience_check.main(["--self", "--check", "failed_write",
                                    "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0 and out["ok"]

    def test_cli_failure_exit_one(self, monkeypatch):
        monkeypatch.setitem(resilience_check.CHECKS, "seeded_fail",
                            lambda: {"ok": False})
        assert resilience_check.main(
            ["--self", "--check", "seeded_fail"]) == 1

    def test_cli_infra_error_exit_two(self, monkeypatch):
        def boom():
            raise RuntimeError("infra")

        monkeypatch.setitem(resilience_check.CHECKS, "seeded_boom", boom)
        assert resilience_check.main(
            ["--self", "--check", "seeded_boom"]) == 2


# ---------------------------------------------------------------------------
# bench wiring (satellite: resilience_overhead leg in compare_bench)
# ---------------------------------------------------------------------------
class TestBenchWiring:
    def test_compare_bench_extracts_resilience_overhead(self):
        from tools import compare_bench

        names = [m[0] for m in compare_bench.METRICS]
        assert "resilience_overhead_pct" in names
        assert "resilience_overhead_pct" in compare_bench.ABS_TOLERANCE
        legs = compare_bench.extract_legs(
            {"resilience_overhead": {"overhead_pct": 0.4}})
        assert legs["resilience_overhead_pct"] == -0.4  # lower-is-better

    def test_overhead_within_tolerance_not_regression(self):
        from tools import compare_bench

        base = {"resilience_overhead": {"overhead_pct": 0.1}}
        new = {"resilience_overhead": {"overhead_pct": 0.8}}
        cmp = compare_bench.compare(base, new, threshold=0.05)
        assert not [r for r in cmp["regressions"]
                    if r["leg"] == "resilience_overhead_pct"]
        worse = {"resilience_overhead": {"overhead_pct": 1.5}}
        cmp = compare_bench.compare(base, worse, threshold=0.05)
        assert [r for r in cmp["regressions"]
                if r["leg"] == "resilience_overhead_pct"]
