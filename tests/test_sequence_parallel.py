"""End-to-end sequence parallelism: GPT with ``sequence_parallel=True`` on
a TP=8 mesh must match the dense single-device model (loss + grads), and
the ``to_model_parallel`` backward distinction of
``gather_from_sequence_parallel_region`` is pinned numerically.

Reference: SP paths ``apex/transformer/tensor_parallel/layers.py:311-437``
and ``mappings.py:231-250``; test idiom from
``tests/L0/run_transformer/test_layers.py`` (TP-vs-dense equivalence).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer import tensor_parallel as tp
from apex_tpu.transformer.testing import (
    GPTConfig,
    gpt_loss,
    gpt_partition_specs,
    init_gpt_params,
)

TP = 8


@pytest.fixture(autouse=True)
def _init_parallel():
    parallel_state.initialize_model_parallel(tensor_model_parallel_size_=TP)
    yield
    parallel_state.destroy_model_parallel()


def _cfg(**kw):
    defaults = dict(
        num_layers=2,
        hidden_size=32,
        num_attention_heads=8,
        vocab_size=128,
        max_position_embeddings=32,
        hidden_dropout=0.0,
        attention_dropout=0.0,
        tensor_model_parallel_size=1,
    )
    defaults.update(kw)
    return GPTConfig(**defaults)


def test_gpt_sp_matches_dense():
    """GPT with sequence_parallel=True, TP=8: loss + grads == dense."""
    cfg_dense = _cfg()
    cfg_sp = _cfg(tensor_model_parallel_size=TP, sequence_parallel=True)
    mesh = parallel_state.get_mesh()
    params = init_gpt_params(cfg_dense, jax.random.PRNGKey(7))
    # seq 16 divisible by TP=8
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, 128)
    labels = jax.random.randint(jax.random.PRNGKey(3), (2, 16), 0, 128)

    dense_loss = gpt_loss(cfg_dense, params, tokens, labels)
    dense_grads = jax.grad(
        lambda p: gpt_loss(cfg_dense, p, tokens, labels)
    )(params)

    specs = gpt_partition_specs(cfg_sp)

    def local_loss(p, t, lab):
        return gpt_loss(cfg_sp, p, t, lab, axis_name="tensor")

    sp_loss = jax.shard_map(
        local_loss, mesh=mesh, in_specs=(specs, P(), P()), out_specs=P(),
        check_vma=True,
    )(params, tokens, labels)
    np.testing.assert_allclose(float(sp_loss), float(dense_loss), rtol=2e-4)

    sp_grads = jax.shard_map(
        jax.grad(local_loss), mesh=mesh,
        in_specs=(specs, P(), P()), out_specs=specs, check_vma=True,
    )(params, tokens, labels)
    for name in ("qkv_w", "fc1_w", "fc2_w", "input_ln_w", "post_ln_b"):
        np.testing.assert_allclose(
            np.asarray(sp_grads["layers"][name]),
            np.asarray(dense_grads["layers"][name]),
            atol=5e-4, err_msg=name,
        )
    np.testing.assert_allclose(
        np.asarray(sp_grads["embedding"]["word"]),
        np.asarray(dense_grads["embedding"]["word"]),
        atol=5e-4,
    )
    np.testing.assert_allclose(
        np.asarray(sp_grads["embedding"]["position"]),
        np.asarray(dense_grads["embedding"]["position"]),
        atol=5e-4,
    )


def test_gpt_sp_equals_tp_without_sp():
    """SP is a memory layout, not a math change: same loss as plain TP."""
    cfg_tp = _cfg(tensor_model_parallel_size=TP)
    cfg_sp = _cfg(tensor_model_parallel_size=TP, sequence_parallel=True)
    mesh = parallel_state.get_mesh()
    params = init_gpt_params(_cfg(), jax.random.PRNGKey(8))
    tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 16), 0, 128)
    labels = jax.random.randint(jax.random.PRNGKey(5), (2, 16), 0, 128)

    def run(cfg):
        return jax.shard_map(
            lambda p, t, lab: gpt_loss(cfg, p, t, lab, axis_name="tensor"),
            mesh=mesh,
            in_specs=(gpt_partition_specs(cfg), P(), P()),
            out_specs=P(), check_vma=True,
        )(params, tokens, labels)

    np.testing.assert_allclose(
        float(run(cfg_sp)), float(run(cfg_tp)), rtol=1e-5
    )


def test_gather_seq_to_model_parallel_backward_duality():
    """Pins the two backward behaviours of
    ``gather_from_sequence_parallel_region``:

    - ``to_model_parallel=True``: backward reduce-scatters, so per-rank
      partial cotangents SUM into each rank's grad slice;
    - ``to_model_parallel=False``: backward takes the rank's slice, so a
      replicated consumer's cotangent passes through unscaled (a
      reduce-scatter would multiply it by the axis size).
    """
    mesh = parallel_state.get_mesh()
    seq = TP * 2
    x = jax.random.normal(jax.random.PRNGKey(0), (seq, 3))

    # consumer whose cotangent is IDENTICAL on every rank (replicated math)
    def loss_with(to_mp):
        def f(x_local):
            full = tp.gather_from_sequence_parallel_region(
                x_local, "tensor", to_mp
            )
            return jnp.sum(full * full)

        return f

    # dense reference: d/dx sum(x^2) = 2x (per element of the local slice)
    expected = 2.0 * np.asarray(x)

    g_false = jax.shard_map(
        jax.grad(loss_with(False)), mesh=mesh,
        in_specs=P("tensor", None), out_specs=P("tensor", None),
        check_vma=False,
    )(x)
    np.testing.assert_allclose(np.asarray(g_false), expected, rtol=1e-6)

    # to_model_parallel=True on the same replicated consumer over-counts
    # by exactly the axis size (the reduce-scatter sums TP identical
    # copies) — this is WHY the reference has the flag.
    g_true = jax.shard_map(
        jax.grad(loss_with(True)), mesh=mesh,
        in_specs=P("tensor", None), out_specs=P("tensor", None),
        check_vma=False,
    )(x)
    np.testing.assert_allclose(np.asarray(g_true), TP * expected, rtol=1e-6)

    # and with a genuinely rank-varying consumer, True is the correct
    # pairing: grads match the dense computation
    w = jax.random.normal(jax.random.PRNGKey(1), (seq, 3))

    def varying_loss(x_local, w_local):
        full = tp.gather_from_sequence_parallel_region(
            x_local, "tensor", True
        )
        # each rank contributes only its w-slice's rows; psum restores
        # the global scalar
        local = jnp.sum(
            full
            * jax.lax.all_gather(w_local, "tensor", axis=0, tiled=True)
        ) / TP
        return local

    g = jax.shard_map(
        jax.grad(varying_loss), mesh=mesh,
        in_specs=(P("tensor", None), P("tensor", None)),
        out_specs=P("tensor", None), check_vma=False,
    )(x, w)
    np.testing.assert_allclose(np.asarray(g), np.asarray(w), rtol=1e-5)
