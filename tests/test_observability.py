"""Observability tests: named scopes in compiled HLO, collective structure
of TP linears, Timers.

The reference instruments with NVTX ranges (`apex/parallel/distributed.py:363`)
and Megatron `Timers`; here the analogues are `jax.named_scope` (trace-time
metadata that shows in `jax.profiler` traces and compiled-HLO op names) and
the same `Timers` class. The HLO assertions guard the "XLA owns
collective/compute overlap" design thesis: the compiled TP step must
actually contain the expected collectives (on TPU the scheduler turns these
into async start/done pairs overlapped with the GEMMs; the CPU backend
compiles them synchronously, so presence+placement is what CI can pin).
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.transformer.tensor_parallel import (
    column_parallel_linear,
    row_parallel_linear,
)


def _mesh():
    return Mesh(np.array(jax.devices()), ("tensor",))


@functools.cache
def _compiled_tp_step():
    mesh = _mesh()
    x = jnp.zeros((64, 128))
    wc = jnp.zeros((256 // 8, 128))
    wr = jnp.zeros((128, 256 // 8))
    tgt = jnp.zeros((64, 128))

    def f(x, wc, wr):
        def loss(x, wc, wr):
            y, _, _ = column_parallel_linear(
                x, wc, axis_name="tensor", gather_output=False)
            z, _, _ = row_parallel_linear(
                jnp.tanh(y), wr, axis_name="tensor", input_is_parallel=True)
            return jnp.mean((z - tgt) ** 2)

        # differentiate x too: d(x) exercises the column layer's backward
        # all-reduce (the copy_to transpose)
        return jax.grad(loss, argnums=(0, 1, 2))(x, wc, wr)

    g = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=(P(), P("tensor"), P(None, "tensor")),
        out_specs=(P(), P("tensor"), P(None, "tensor")), check_vma=True,
    ))
    return g.lower(x, wc, wr).compile().as_text()


def test_tp_linear_step_contains_expected_collectives():
    """Column(fwd copy/bwd all-reduce) + Row(fwd all-reduce) must compile to
    real all-reduces — if XLA ever elides or the mappings stop emitting
    them, gradients silently stop being synced."""
    txt = _compiled_tp_step()
    assert "all-reduce" in txt, "no all-reduce in compiled TP step"
    # fwd row-parallel reduce + bwd column-parallel dx reduce = >= 2
    assert txt.count("all-reduce") >= 2, txt.count("all-reduce")


def test_named_scopes_reach_compiled_hlo():
    """The NVTX-range analogue: apex_tpu named scopes must be visible in
    compiled-op metadata so profiler traces attribute time to library
    components."""
    txt = _compiled_tp_step()
    assert "apex_tpu.column_parallel_linear" in txt
    assert "apex_tpu.row_parallel_linear" in txt


def test_sync_gradients_scope_and_collective():
    from apex_tpu.parallel import sync_gradients

    mesh = Mesh(np.array(jax.devices()), ("data",))
    grads = {"w": jnp.ones((8, 8))}

    g = jax.jit(jax.shard_map(
        lambda t: sync_gradients(t, "data"), mesh=mesh,
        in_specs=P("data"), out_specs=P("data"), check_vma=False,
    ))
    txt = g.lower(grads).compile().as_text()
    assert "all-reduce" in txt
    assert "apex_tpu.sync_gradients" in txt


def test_pipeline_scope_and_ppermute():
    from apex_tpu.transformer import parallel_state
    from apex_tpu.transformer.pipeline_parallel import run_pipeline

    parallel_state.initialize_model_parallel(1, 4, devices=jax.devices()[:4])
    try:
        mesh = parallel_state.get_mesh()
        params = {"w": jnp.zeros((4, 8, 8))}
        inputs = jnp.zeros((4, 2, 8))
        targets = jnp.zeros((4, 2, 8))

        def stage(p, x):
            return jnp.tanh(x @ p["w"])

        def lossf(y, t):
            return jnp.mean((y - t) ** 2)

        f = jax.jit(lambda p, i, t: run_pipeline(
            mesh, stage, lossf, p, i, t, forward_only=True))
        txt = f.lower(params, inputs, targets).compile().as_text()
        assert "collective-permute" in txt, "pipeline hops must be ppermutes"
        assert "apex_tpu.pipeline_rounds" in txt
    finally:
        parallel_state.destroy_model_parallel()


def test_timers_measure_and_log():
    import time

    from apex_tpu.transformer.pipeline_parallel._timers import Timers

    timers = Timers()
    timers("step").start()
    time.sleep(0.01)
    timers("step").stop()
    dt = timers("step").elapsed(reset=False)
    assert 0.005 < dt < 1.0
    out = timers.log(["step"], reset=False)
    assert "step" in out


def test_packed_optimizer_named_scopes_reach_compiled_hlo():
    """The packed flat-buffer kernels must be attributable in profiler
    traces: their named scopes have to survive into compiled-op metadata
    (both the Pallas kernels on TPU and the XLA fallback exercised here
    carry them — the decorator wraps the whole op)."""
    from apex_tpu.optimizers import FusedAdam, FusedLAMB

    params = {"w": jnp.zeros((512,), jnp.bfloat16),
              "b": jnp.zeros((256,), jnp.bfloat16)}
    grads = {k: jnp.zeros_like(v) for k, v in params.items()}

    adam = FusedAdam(lr=1e-3, master_weights=True, packed=True)
    astate = adam.init(params)
    txt = jax.jit(lambda g, s, p: adam.step(g, s, p)).lower(
        grads, astate, params).compile().as_text()
    assert "apex_tpu.packed_adam" in txt

    lamb = FusedLAMB(lr=1e-3, packed=True)
    lstate = lamb.init(params)
    txt = jax.jit(lambda g, s, p: lamb.step(g, s, p)).lower(
        grads, lstate, params).compile().as_text()
    # both LAMB stages plus the per-tensor-norm reduction
    assert "apex_tpu.packed_lamb_stage1" in txt
    assert "apex_tpu.packed_scale_update" in txt
    assert "apex_tpu.packed_row_reduce" in txt


def test_flash_attention_named_scope_reaches_compiled_hlo():
    """Flash attention time must be attributable in traces (the r5 op
    breakdown's 14% 'apex_tpu.flash_attention' bucket depends on it)."""
    from apex_tpu.ops.flash_attention import flash_attention

    q = jnp.zeros((1, 2, 128, 64))
    try:
        txt = jax.jit(
            lambda q, k, v: flash_attention(q, k, v, causal=True)
        ).lower(q, q, q).compile().as_text()
    except AttributeError as e:  # pallas API gap on old jax (the same
        import pytest           # gap that fails the seed flash tests)

        pytest.skip(f"flash kernel unavailable on this jax: {e}")
    assert "apex_tpu.flash_attention" in txt


def test_sequence_parallel_linears_compile_to_gather_scatter_pair():
    """Megatron SP's defining collective structure: the column linear
    all-gathers the sequence-scattered input forward (reduce-scatter in
    backward), the row linear reduce-scatters forward — the compiled step
    must contain both collectives or SP is silently broken."""
    mesh = _mesh()
    S, B_, H_ = 32, 2, 128
    x = jnp.zeros((S, B_, H_))  # global; P("tensor") scatters the seq dim
    wc = jnp.zeros((256 // 8, H_))
    wr = jnp.zeros((H_, 256 // 8))

    def f(x, wc, wr):
        def loss(x, wc, wr):
            y, _, _ = column_parallel_linear(
                x, wc, axis_name="tensor", gather_output=False,
                sequence_parallel_enabled=True)
            z, _, _ = row_parallel_linear(
                jnp.tanh(y), wr, axis_name="tensor", input_is_parallel=True,
                sequence_parallel_enabled=True)
            return jnp.sum(z ** 2)

        return jax.grad(loss, argnums=(0, 1, 2))(x, wc, wr)

    g = jax.jit(jax.shard_map(
        f, mesh=mesh,
        in_specs=(P("tensor"), P("tensor"), P(None, "tensor")),
        out_specs=(P("tensor"), P("tensor"), P(None, "tensor")),
        check_vma=True,
    ))
    txt = g.lower(x, wc, wr).compile().as_text()
    # fwd+bwd of the PAIR: column fwd all-gather + row bwd all-gather, and
    # row fwd reduce-scatter + column bwd reduce-scatter — count-based so a
    # single layer regressing (e.g. to a plain all-reduce) still fails
    assert txt.count("all-gather") >= 2, txt.count("all-gather")
    assert txt.count("reduce-scatter") >= 2, txt.count("reduce-scatter")
