"""Telemetry subsystem tests: in-jit MetricsState accumulate/drain,
recorder sinks, scaler counter wiring, bubble-fraction math, tick hooks.

Design contract pinned here: instrumentation lives INSIDE the jitted step
(device accumulators + async ``jax.debug.callback`` drains under
``lax.cond``) and adds no host syncs; window stats reset per drain while
overflow/growth counters are cumulative; the pipeline bubble accounting
must reproduce the textbook ``(p-1)/(m+p-1)`` and the 1F1B module's
documented ``(D+pp-1)/T`` fraction.
"""
import functools
import json

import jax
import jax.numpy as jnp
import pytest

from apex_tpu import telemetry


# ---------------------------------------------------------------------------
# MetricsState accumulate / drain
# ---------------------------------------------------------------------------

def test_metrics_accumulate_and_drain_every_n():
    rec = telemetry.RingBufferRecorder()
    m = telemetry.init_metrics()

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(m, loss):
        m = telemetry.accumulate(m, loss=loss, tokens=64)
        m = telemetry.drain(m, rec, every_n=3, tag="unit")
        return m, loss + 1.0

    loss = jnp.float32(1.0)
    for _ in range(7):
        m, loss = step(m, loss)
    jax.effects_barrier()

    # drains at total_steps 3 and 6 only
    assert len(rec.records) == 2
    r0, r1 = rec.records
    assert r0["step"] == 3 and r1["step"] == 6
    assert r0["steps_in_window"] == 3 and r1["steps_in_window"] == 3
    # window means: losses 1,2,3 -> 2.0; 4,5,6 -> 5.0
    assert r0["loss"] == pytest.approx(2.0)
    assert r1["loss"] == pytest.approx(5.0)
    assert r0["tag"] == "unit"
    # window tokens reset, cumulative tokens do not
    assert r0["tokens"] == pytest.approx(192.0)
    assert r1["total_tokens"] == pytest.approx(384.0)
    # second drain carries wall-dt derived rates
    assert "wall_dt_s" in r1 and r1["steps_per_sec"] > 0
    # the undrained 7th step stays in the device window
    assert int(m.window_steps) == 1 and int(m.total_steps) == 7


def test_metrics_grad_and_param_norms():
    grads = {"a": jnp.full((3,), 2.0), "b": jnp.full((4,), -2.0)}
    m = telemetry.accumulate(telemetry.init_metrics(), grads=grads,
                             params={"w": jnp.full((9,), 1.0)})
    assert float(m.grad_norm_sum) == pytest.approx((4.0 * 7) ** 0.5)
    assert float(m.param_norm_sum) == pytest.approx(3.0)
    with pytest.raises(ValueError):
        telemetry.accumulate(m, grads=grads, grad_norm=1.0)


def test_metrics_drain_bytes_per_step_reports_gbps():
    rec = telemetry.RingBufferRecorder()
    m = telemetry.init_metrics()

    @jax.jit
    def step(m):
        m = telemetry.accumulate(m)
        return telemetry.drain(m, rec, every_n=1, bytes_per_step=1e9)

    for _ in range(3):
        m = step(m)
    jax.effects_barrier()
    assert len(rec.records) == 3
    # first drain has no previous timestamp; later ones derive GB/s
    assert "achieved_gbps" not in rec.records[0]
    assert rec.records[-1]["achieved_gbps"] > 0


def test_metrics_state_donatable():
    """Every field must be its own buffer or donation breaks (the
    f(donate(a), donate(a)) XLA error)."""
    m = telemetry.init_metrics()
    step = jax.jit(lambda m: telemetry.accumulate(m, loss=1.0),
                   donate_argnums=(0,))
    m = step(m)
    m = step(m)
    assert int(m.total_steps) == 2


# ---------------------------------------------------------------------------
# LossScaler -> cumulative skip/growth counters
# ---------------------------------------------------------------------------

def test_scaler_update_scale_feeds_metrics_counters():
    from apex_tpu.amp.scaler import LossScaler

    sc = LossScaler("dynamic", init_scale=4.0, scale_window=2,
                    hysteresis=1)
    st = sc.init_state()
    m = telemetry.init_metrics()

    # overflow step: counts a skip, scale backs off 4 -> 2
    st = st._replace(found_inf=jnp.asarray(True))
    st, m = sc.update_scale(st, m)
    assert int(m.overflow_skips) == 1 and int(m.scale_growths) == 0
    assert float(m.loss_scale) == pytest.approx(2.0)

    # two clean steps: scale grows 2 -> 4 at the window
    st, m = sc.update_scale(st, m)
    st, m = sc.update_scale(st, m)
    assert int(m.overflow_skips) == 1
    assert int(m.scale_growths) == 1
    assert float(m.loss_scale) == pytest.approx(4.0)

    # metrics=None keeps the original single-return API
    st2 = sc.update_scale(st)
    assert isinstance(st2, type(st))


def test_scaler_metrics_inside_jit():
    from apex_tpu.amp.scaler import LossScaler

    sc = LossScaler("dynamic", init_scale=8.0, scale_window=1000)

    @jax.jit
    def step(st, m, found):
        st = st._replace(found_inf=found)
        st, m = sc.update_scale(st, m)
        return st, m

    st, m = sc.init_state(), telemetry.init_metrics()
    st, m = step(st, m, jnp.asarray(True))
    st, m = step(st, m, jnp.asarray(True))
    st, m = step(st, m, jnp.asarray(False))
    assert int(m.overflow_skips) == 2


# ---------------------------------------------------------------------------
# recorders
# ---------------------------------------------------------------------------

def test_jsonl_recorder_roundtrip(tmp_path):
    path = tmp_path / "t.jsonl"
    with telemetry.JsonlRecorder(path) as rec:
        rec.record({"event": "metrics", "step": 1,
                    "loss": jnp.float32(2.5)})
        rec.add_scalar("step-time", 0.125, 7)
    out = telemetry.read_jsonl(path)
    assert len(out) == 2
    assert out[0]["loss"] == pytest.approx(2.5)  # numpy scalar jsonable
    assert all("t_wall" in r for r in out)
    assert out[1] == {**out[1], "event": "scalar", "name": "step-time",
                      "value": 0.125, "step": 7}


def test_jsonl_recorder_nonfinite_values_stay_parseable(tmp_path):
    path = tmp_path / "nan.jsonl"
    with telemetry.JsonlRecorder(path) as rec:
        rec.record({"loss": float("nan"), "scale": float("inf")})
    (r,) = telemetry.read_jsonl(path)
    assert r["loss"] == "nan" and r["scale"] == "inf"
    json.dumps(r)  # strict-json parseable


def test_jsonl_recorder_rank_gating(tmp_path):
    # this process is rank 0 of 1: an explicit other-rank gate must drop
    path = tmp_path / "other_rank.jsonl"
    rec = telemetry.JsonlRecorder(path, log_rank=3)
    rec.record({"x": 1})
    rec.close()
    assert not path.exists()
    assert telemetry.is_logging_process() is True
    assert telemetry.is_logging_process(3) is False


def test_multi_and_ring_recorder():
    ring = telemetry.RingBufferRecorder(capacity=2)
    multi = telemetry.MultiRecorder(ring, telemetry.NullRecorder())
    for i in range(4):
        multi.record({"i": i})
    assert [r["i"] for r in ring.records] == [2, 3]  # ring capacity


def test_timers_sink_and_log_rank():
    from apex_tpu.transformer.pipeline_parallel._timers import Timers

    ring = telemetry.RingBufferRecorder()
    timers = Timers(sink=ring)
    timers("io").start()
    timers("io").stop()
    out = timers.log(["io"], reset=False, iteration=11)
    assert "io" in out
    assert ring.records[-1]["event"] == "timers"
    assert ring.records[-1]["iteration"] == 11
    assert "io" in ring.records[-1]["ms"]
    # Timers.write duck-types onto recorders via add_scalar
    timers.write(["io"], ring, 12)
    assert ring.records[-1]["event"] == "scalar"
    assert ring.records[-1]["name"] == "io-time"
    # an explicit non-resident log rank suppresses printing but still
    # returns the formatted line (and still records to the sink)
    t2 = Timers(log_rank=5, sink=ring)
    t2("x").start(); t2("x").stop()
    assert "x" in t2.log(["x"])


# ---------------------------------------------------------------------------
# pipeline bubble accounting
# ---------------------------------------------------------------------------

def test_bubble_fraction_textbook_formula():
    # the scan schedule IS the textbook fraction (p-1)/(m+p-1)
    for pp, m in [(2, 4), (4, 8), (4, 16), (8, 64)]:
        assert telemetry.analytic_bubble_fraction(pp, m) == pytest.approx(
            (pp - 1) / (m + pp - 1))
    # interleaving shrinks the fraction (same pp, same microbatches)
    assert (telemetry.analytic_bubble_fraction(4, 8, 2)
            < telemetry.analytic_bubble_fraction(4, 8, 1))
    # pp=1: no bubble anywhere
    assert telemetry.analytic_bubble_fraction(1, 4) == 0.0
    assert telemetry.analytic_bubble_fraction(1, 4, 1, "1f1b") == 0.0


def test_bubble_fraction_1f1b_matches_module_docs():
    # fwd_bwd_1f1b: T = n*vpp + D + pp-1, D = (vpp-1)*pp + (pp-1);
    # wasted half-ticks sum to (D + pp - 1)/T
    for pp, n, vpp in [(4, 8, 1), (4, 8, 2), (8, 16, 2)]:
        d = (vpp - 1) * pp + (pp - 1)
        t = n * vpp + d + (pp - 1)
        assert telemetry.analytic_bubble_fraction(
            pp, n, vpp, "1f1b") == pytest.approx((d + pp - 1) / t)
        assert telemetry.schedule_ticks(pp, n, vpp, "1f1b") == t


def test_tick_phases_counts_consistent():
    pp, n, vpp = 4, 8, 2
    phases = telemetry.tick_phases(pp, n, vpp, "1f1b")
    total = telemetry.schedule_ticks(pp, n, vpp, "1f1b")
    assert len(phases) == pp
    for r, row in enumerate(phases):
        assert len(row) == total
        # every rank forwards and backwards exactly n*vpp stream items
        f = sum(p in ("warmup", "steady") for p in row)
        b = sum(p in ("cooldown", "steady") for p in row)
        assert f == n * vpp and b == n * vpp
        # idle ticks grow with rank for this schedule: 2r
        assert sum(p == "idle" for p in row) == 2 * r
    # scan schedule: active ticks are steady, pp-1 idle on every rank
    for row in telemetry.tick_phases(pp, n, 1, "scan"):
        assert sum(p == "idle" for p in row) == pp - 1
        assert sum(p == "steady" for p in row) == n


def test_bubble_report_prices_the_bubble():
    rep = telemetry.bubble_report(4, 8, 1, "scan", tick_time_s=1e-3)
    assert rep["total_ticks"] == 11
    assert rep["analytic_bubble_fraction"] == pytest.approx(3 / 11)
    assert rep["reference_bubble_fraction"] == pytest.approx(3 / 11)
    assert rep["step_ms"] == pytest.approx(11.0)
    assert rep["bubble_ms_per_step"] == pytest.approx(3.0)
    with pytest.raises(ValueError):
        telemetry.bubble_report(4, 8, 1, "nope")


def test_tick_timeline_report_classifies_phases():
    tl = telemetry.TickTimeline()
    # rank 0 of a pp=2, n=2 1f1b run: F ticks 0..1, B ticks 1+?; feed a
    # hand-built sequence instead of deriving one
    seq = [(0, True, False), (1, True, True), (2, True, True),
           (3, False, True), (4, False, False)]
    for t, af, ab in seq:
        tl.hook(t, 0, af, ab)
    rep = tl.report("1f1b")
    (rank0,) = rep["per_rank"]
    assert rank0["ticks"] == {"warmup": 1, "steady": 2, "cooldown": 1,
                              "idle": 1}
    # tick-count accounting: (idle + 0.5*(warmup+cooldown)) / total
    assert rep["measured_bubble_fraction_ticks"] == pytest.approx(
        (1 + 0.5 * 2) / 5)
    # scan relabels its active (F-only) ticks as steady
    tl2 = telemetry.TickTimeline()
    tl2.hook(0, 1, False, False)
    tl2.hook(1, 1, True, False)
    rep2 = tl2.report("scan")
    assert rep2["per_rank"][0]["ticks"] == {"idle": 1, "steady": 1}


def test_emit_tick_fires_from_jitted_scan():
    from apex_tpu.transformer.pipeline_parallel.schedules.common import (
        emit_tick,
    )

    tl = telemetry.TickTimeline()

    @jax.jit
    def run():
        def body(c, t):
            emit_tick(tl, t, jnp.int32(0), t < 4, t >= 2)
            return c, None
        c, _ = jax.lax.scan(body, 0.0, jnp.arange(6))
        return c

    run()
    jax.effects_barrier()
    rep = tl.report("1f1b")
    assert rep["n_events"] == 6
    assert rep["per_rank"][0]["ticks"] == {"warmup": 2, "steady": 2,
                                           "cooldown": 2}
    # timing is attached from the second event on
    assert sum(rep["per_rank"][0]["phase_seconds"].values()) >= 0


def test_no_pipelining_microbatch_hook_forward_only():
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        forward_backward_no_pipelining,
    )

    tl = telemetry.TickTimeline()
    params = {"w": jnp.eye(4)}
    mbs = jnp.ones((3, 2, 4))
    loss, grads = forward_backward_no_pipelining(
        lambda p, x: x @ p["w"], lambda y, e: jnp.mean(y ** 2),
        params, mbs, forward_only=True, microbatch_hook=tl,
    )
    jax.effects_barrier()
    assert grads is None
    assert tl.report("scan")["n_events"] == 3
    # numerics are identical with the hook attached
    loss_bare, _ = forward_backward_no_pipelining(
        lambda p, x: x @ p["w"], lambda y, e: jnp.mean(y ** 2),
        params, mbs, forward_only=True,
    )
    assert float(loss) == pytest.approx(float(loss_bare))


def test_no_pipelining_hook_fires_on_gradient_path():
    """This schedule's scan is never differentiated THROUGH (grad runs
    inside the body), so the hook must fire on the gradient path too —
    with unchanged gradients."""
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        forward_backward_no_pipelining,
    )

    tl = telemetry.TickTimeline()
    params = {"w": jnp.eye(4)}
    mbs = jnp.ones((2, 2, 4))
    loss, grads = forward_backward_no_pipelining(
        lambda p, x: x @ p["w"], lambda y, e: jnp.mean(y ** 2),
        params, mbs, microbatch_hook=tl,
    )
    jax.effects_barrier()
    assert tl.report("1f1b")["n_events"] == 2
    # backward-active flag rides the emission on the grad path
    assert all(ev["active_b"] for ev in tl.events)
    _, grads_bare = forward_backward_no_pipelining(
        lambda p, x: x @ p["w"], lambda y, e: jnp.mean(y ** 2),
        params, mbs,
    )
    assert jnp.allclose(grads["w"], grads_bare["w"])


@pytest.mark.skipif(
    not (hasattr(jax.lax, "axis_size") and hasattr(jax, "shard_map")),
    reason="pipeline schedules need jax.lax.axis_size/jax.shard_map "
           "(newer jax); schedule runtime is already untestable on this "
           "version",
)
def test_1f1b_tick_hook_timeline_matches_analytic():
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_1f1b \
        import pipeline_forward_backward_1f1b

    pp, n = 4, 8
    mesh = Mesh(np.array(jax.devices()[:pp]), ("pipeline",))
    tl = telemetry.TickTimeline()
    params = {"w": jnp.zeros((pp, 8, 8))}
    inputs = jnp.zeros((n, 2, 8))
    targets = jnp.zeros((n, 2, 8))

    def local(p, i, t):
        p = jax.tree_util.tree_map(lambda q: q[0], p)
        loss, _, _ = pipeline_forward_backward_1f1b(
            lambda pc, x: jnp.tanh(x @ pc["w"]),
            lambda y, e: jnp.mean((y - e) ** 2),
            p, i, t, axis_name="pipeline", tick_hook=tl)
        return loss

    f = jax.jit(jax.shard_map(
        local, mesh=mesh, in_specs=(P("pipeline"), P(), P()),
        out_specs=P(), check_vma=False))
    f(params, inputs, targets)
    jax.effects_barrier()

    total = telemetry.schedule_ticks(pp, n, 1, "1f1b")
    rep = tl.report("1f1b")
    assert rep["n_events"] == pp * total
    # measured tick-count fraction equals the analytic fraction exactly
    # (every tick executes; phases are derived from the same flags)
    assert rep["measured_bubble_fraction_ticks"] == pytest.approx(
        telemetry.analytic_bubble_fraction(pp, n, 1, "1f1b"))
    # phase counts agree with the analytic per-rank timeline
    analytic = telemetry.tick_phases(pp, n, 1, "1f1b")
    for rank_rep in rep["per_rank"]:
        r = rank_rep["rank"]
        want = {}
        for ph in analytic[r]:
            want[ph] = want.get(ph, 0) + 1
        assert rank_rep["ticks"] == want


# ---------------------------------------------------------------------------
# tracing: fixture-parsed xplane events + cost-analysis fallback
# ---------------------------------------------------------------------------

def test_aggregate_op_times_fixture():
    events = [
        ("%convolution_tanh_fusion.3 = bf16[4,4] fusion(...)", 100),
        ("%convolution_tanh_fusion.9 = bf16[4,4] fusion(...)", 50),
        ("%while.7 = (s32[], f32[8]) while(...)", 1000),  # container
        ("%conditional.2 = f32[] conditional(...)", 500),  # container
        ("%apex_tpu_flash_fwd.65 = (bf16[8]) custom-call(...)", 200),
        ("%copy-done", 25),
    ]
    total, per_op = telemetry.aggregate_op_times(events)
    assert total == 375  # containers excluded, suffixes merged
    assert per_op == {
        ("convolution_tanh_fusion", "matmul/conv"): 150,
        ("apex_tpu_flash_fwd", "attention-kernel"): 200,
        ("copy-done", "data-movement"): 25,
    }


def test_aggregate_generic_fusions_split_by_hlo_category():
    """The round-5 misattribution: every generic %fusion.N merged into
    one 'fusion' op booked as elementwise, hiding the dense GEMMs. With
    the profiler's hlo_category stat they stay separate."""
    events = [
        ("%fusion.1 = bf16[4,4] fusion(...)", 700, "convolution fusion"),
        ("%fusion.2 = f32[4] fusion(...)", 200, "loop fusion"),
        ("%fusion.3 = f32[4] fusion(...)", 100, None),  # no stat
    ]
    total, per_op = telemetry.aggregate_op_times(events)
    assert total == 1000
    assert per_op == {
        ("fusion", "matmul/conv"): 700,
        ("fusion", "fusion(elementwise)"): 200,
        ("fusion", "fusion(unattributed)"): 100,
    }


def test_breakdown_table_fixture():
    total, per_op = telemetry.aggregate_op_times([
        ("%dot_fusion.1 = ...", 3_000_000),
        ("%all-reduce.2 = ...", 1_000_000),
    ])
    table = telemetry.breakdown_table(total, per_op, n_steps=2, top=1)
    assert table["source"] == "xplane"
    assert table["device_ms_per_step"] == pytest.approx(0.002)
    assert len(table["ops"]) == 1  # top=1
    assert table["ops"][0]["op"] == "dot_fusion"
    assert table["ops"][0]["category"] == "matmul/conv"
    assert table["ops"][0]["pct"] == pytest.approx(75.0)
    assert table["categories"]["collective"]["pct"] == pytest.approx(25.0)
    assert telemetry.breakdown_table(0, {}) is None


def test_breakdown_table_accepts_legacy_name_keyed_per_op():
    # pre-fix captures keyed per_op by bare name; the table still builds
    table = telemetry.breakdown_table(
        1_000_000, {"dot_fusion": 750_000, "copy": 250_000})
    assert table["categories"]["matmul/conv"]["pct"] == pytest.approx(75.0)
    assert table["categories"]["data-movement"]["pct"] == pytest.approx(25.0)


def test_profile_step_cost_analysis_fallback_on_cpu():
    @jax.jit
    def step(x):
        return (jnp.tanh(x @ x),)

    table = telemetry.profile_step(step, (jnp.ones((32, 32)),), n_steps=2)
    assert table is not None
    assert table["source"] == "cost_analysis"
    assert table["flops_per_step"] > 0
    assert table["arithmetic_intensity"] is None or \
        table["arithmetic_intensity"] > 0


def test_trace_session_parse_after_exit_only():
    with telemetry.trace_session() as sess:
        jnp.ones((4,)).block_until_ready()
        with pytest.raises(RuntimeError):
            sess.op_breakdown()
    # CPU backend: no TPU device plane -> no xplane table
    assert sess.op_breakdown() is None


def test_trace_session_usable_after_traced_block_raises():
    """The profiler stops (and writes) even when the block raises; the
    session must be parseable afterwards, not stuck 'active'."""
    with pytest.raises(ValueError, match="boom"):
        with telemetry.trace_session() as sess:
            raise ValueError("boom")
    assert sess.active is False
    assert sess.op_breakdown() is None  # no device plane on CPU


# ---------------------------------------------------------------------------
# packed-optimizer sweep bytes (the GB/s-per-drain denominator)
# ---------------------------------------------------------------------------

def test_packed_state_sweep_bytes():
    from apex_tpu.optimizers import FusedAdam, FusedSGD

    params = {"w": jnp.zeros((2048,), jnp.bfloat16)}
    adam = FusedAdam(lr=1e-3, master_weights=True, packed=True).init(params)
    # bf16 grads read + params write (2+2) + fp32 m, v, master r/w (24)
    assert adam.sweep_bytes() == 28 * adam.spec.total
    sgd = FusedSGD(lr=0.1, momentum=0.9, packed=True).init(params)
    # bf16 in/out + fp32 momentum r/w
    assert sgd.sweep_bytes() == 12 * sgd.spec.total
