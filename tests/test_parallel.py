"""Tests for apex_tpu.parallel: DDP grad sync, SyncBatchNorm, LARC.

Mirrors the reference's distributed test strategy (SURVEY.md §4):
cross-rank equality after sync, SyncBN vs single-device BN equivalence
(``tests/distributed/synced_batchnorm/``), LARC behavioural checks
(``tests/L0/run_amp/test_larc.py``) — on an 8-virtual-device CPU mesh.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from apex_tpu.parallel import (
    DistributedDataParallel,
    LARC,
    flatten,
    larc_adjust_gradients,
    sync_batch_norm,
    sync_gradients,
    unflatten,
)
from apex_tpu.optimizers import FusedSGD


def _mesh():
    return Mesh(np.array(jax.devices()), ("data",))


def test_flatten_unflatten_roundtrip():
    tree = {
        "a": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
        "b": (jnp.ones((4,), jnp.bfloat16), jnp.zeros((2, 2), jnp.float32)),
    }
    flat = flatten(tree)
    assert flat.ndim == 1 and flat.size == 6 + 4 + 4
    out = jax.tree_util.tree_map(np.asarray, unflatten(flat, tree))
    ref = jax.tree_util.tree_map(np.asarray, tree)
    for o, r in zip(jax.tree_util.tree_leaves(out), jax.tree_util.tree_leaves(ref)):
        np.testing.assert_array_equal(o, np.asarray(r, dtype=o.dtype))


@pytest.mark.parametrize("fp32,predivide", [(False, 1.0), (True, 4.0)])
def test_sync_gradients_mean(fp32, predivide):
    mesh = _mesh()
    grads = jnp.arange(8 * 3, dtype=jnp.float32).reshape(8, 3)

    f = shard_map(
        functools.partial(
            sync_gradients,
            axis_name="data",
            gradient_average=True,
            allreduce_always_fp32=fp32,
            gradient_predivide_factor=predivide,
        ),
        mesh=mesh,
        in_specs=P("data", None),
        out_specs=P("data", None),
    )
    out = np.asarray(f(grads))
    expected = np.broadcast_to(np.asarray(grads).mean(0), (1, 3))
    for r in range(8):
        np.testing.assert_allclose(out[r], expected[0], rtol=1e-6)


def test_sync_gradients_sum():
    mesh = _mesh()
    grads = jnp.ones((8, 4), jnp.float32)
    f = shard_map(
        functools.partial(sync_gradients, axis_name="data", gradient_average=False),
        mesh=mesh, in_specs=P("data", None), out_specs=P("data", None),
    )
    np.testing.assert_allclose(np.asarray(f(grads)), 8.0)


def test_ddp_wrap_grad_fn_and_broadcast():
    mesh = _mesh()
    ddp = DistributedDataParallel(axis_name="data")

    def loss_fn(w, x):
        return jnp.sum((x @ w) ** 2)

    w = jnp.ones((4, 2), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 4))

    def per_shard(w, x):
        g = ddp.wrap_grad_fn(jax.grad(loss_fn))(w, x)
        return g

    g_sync = shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(None, None), P("data", None)),
        out_specs=P(None, None), check_rep=False,
    )(w, x)
    # synced grads equal the mean of per-shard grads
    per = [np.asarray(jax.grad(loss_fn)(w, x[i : i + 1])) for i in range(8)]
    np.testing.assert_allclose(np.asarray(g_sync), np.mean(per, 0), rtol=1e-5)

    # broadcast_params makes shards identical to shard 0's value
    p = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)
    out = shard_map(
        ddp.broadcast_params, mesh=mesh, in_specs=P("data", None),
        out_specs=P("data", None),
    )(p)
    np.testing.assert_allclose(np.asarray(out).ravel(), 0.0)


@pytest.mark.parametrize("channel_last", [True, False])
def test_syncbn_matches_global_bn(channel_last):
    """Stats over 8 shards must equal single-device stats over the full batch
    (reference tests/distributed/synced_batchnorm/)."""
    mesh = _mesh()
    key = jax.random.PRNGKey(1)
    n, h, w, c = 16, 4, 4, 6
    x = jax.random.normal(key, (n, h, w, c), jnp.float32) * 3 + 1
    if not channel_last:
        x = jnp.transpose(x, (0, 3, 1, 2))
    weight = jnp.linspace(0.5, 1.5, c)
    bias = jnp.linspace(-1, 1, c)
    rm, rv = jnp.zeros((c,)), jnp.ones((c,))

    def local(xs):
        return sync_batch_norm(
            xs, weight, bias, rm, rv, training=True, axis_name="data",
            channel_last=channel_last,
        )

    y, new_rm, new_rv = shard_map(
        local, mesh=mesh, in_specs=P("data"),
        out_specs=(P("data"), P(), P()),
    )(x)

    y_ref, rm_ref, rv_ref = sync_batch_norm(
        x, weight, bias, rm, rv, training=True, axis_name=None,
        channel_last=channel_last,
    )
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=1e-5)
    np.testing.assert_allclose(np.asarray(new_rm), np.asarray(rm_ref), atol=1e-5)
    np.testing.assert_allclose(np.asarray(new_rv), np.asarray(rv_ref), atol=1e-4)


def test_syncbn_eval_and_fuse_relu():
    c = 3
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 5, c))
    rm = jnp.array([0.1, -0.2, 0.3])
    rv = jnp.array([1.0, 2.0, 0.5])
    y, rm2, rv2 = sync_batch_norm(
        x, None, None, rm, rv, training=False, axis_name=None, fuse_relu=True
    )
    ref = (x - rm) / np.sqrt(np.asarray(rv) + 1e-5)
    np.testing.assert_allclose(np.asarray(y), np.maximum(np.asarray(ref), 0), atol=1e-5)
    assert rm2 is rm and rv2 is rv


def test_syncbn_flax_module():
    import flax.linen as nn  # noqa: F401
    from apex_tpu.parallel import SyncBatchNorm

    m = SyncBatchNorm(num_features=4, axis_name=None)
    x = jax.random.normal(jax.random.PRNGKey(3), (8, 4))
    vars0 = m.init(jax.random.PRNGKey(0), x)
    y, mutated = m.apply(vars0, x, mutable=["batch_stats"])
    assert y.shape == x.shape
    # batch-normalised output: near zero mean / unit var per channel
    np.testing.assert_allclose(np.asarray(y).mean(0), 0.0, atol=1e-5)
    assert not np.allclose(
        np.asarray(mutated["batch_stats"]["mean"]), 0.0
    )


def test_larc_clip_scales_small_grads():
    params = {"w": jnp.ones((10,)) * 2.0}
    grads = {"w": jnp.full((10,), 1e-4)}
    lr = 0.1
    out = larc_adjust_gradients(
        grads, params, lr, trust_coefficient=0.02, clip=True
    )
    # adaptive_lr = 0.02*||p||/||g|| >> lr → clip to 1 → unchanged
    np.testing.assert_allclose(np.asarray(out["w"]), 1e-4, rtol=1e-6)

    big = {"w": jnp.full((10,), 100.0)}
    out2 = larc_adjust_gradients(big, params, lr, trust_coefficient=0.02, clip=True)
    p_norm = np.linalg.norm(np.asarray(params["w"]))
    g_norm = np.linalg.norm(np.asarray(big["w"]))
    adaptive = 0.02 * p_norm / (g_norm + 1e-8)
    np.testing.assert_allclose(
        np.asarray(out2["w"]), 100.0 * adaptive / lr, rtol=1e-5
    )


def test_larc_no_clip_uses_adaptive_lr_directly():
    # clip=False: grads scaled by adaptive_lr itself (effective lr =
    # lr * adaptive_lr), matching reference apex/parallel/LARC.py:97-99.
    params = {"w": jnp.full((10,), 2.0)}
    grads = {"w": jnp.full((10,), 100.0)}
    out = larc_adjust_gradients(
        grads, params, lr=0.1, trust_coefficient=0.02, clip=False
    )
    p_norm = np.linalg.norm(np.asarray(params["w"]))
    g_norm = np.linalg.norm(np.asarray(grads["w"]))
    adaptive = 0.02 * p_norm / (g_norm + 1e-8)
    np.testing.assert_allclose(np.asarray(out["w"]), 100.0 * adaptive, rtol=1e-5)


def test_larc_zero_grad_left_untouched():
    # zero-norm branch leaves grads alone — no weight-decay fold
    # (reference LARC.py:84 guards the whole adjustment).
    params = {"w": jnp.full((4,), 3.0)}
    grads = {"w": jnp.zeros((4,))}
    out = larc_adjust_gradients(
        grads, params, lr=0.1, trust_coefficient=0.02, clip=True,
        weight_decay=0.1,
    )
    np.testing.assert_array_equal(np.asarray(out["w"]), 0.0)


def test_convert_syncbn_model():
    import flax.linen as nn
    from apex_tpu.parallel import SyncBatchNorm, convert_syncbn_model

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = nn.Dense(8)(x)
            x = nn.BatchNorm(use_running_average=not train)(x)
            return x

    class Outer(nn.Module):
        body: nn.Module

        @nn.compact
        def __call__(self, x):
            return self.body(x)

    converted = convert_syncbn_model(Outer(body=Net()), axis_name=None)
    assert isinstance(converted.body, nn.Module)
    # a bare BatchNorm converts to SyncBatchNorm and initialises fine
    bn = convert_syncbn_model(nn.BatchNorm(use_running_average=False), axis_name=None)
    assert isinstance(bn, SyncBatchNorm)
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 5))
    variables = bn.init(jax.random.PRNGKey(1), x)
    assert variables["params"]["scale"].shape == (5,)
    y, _ = bn.apply(variables, x, mutable=["batch_stats"])
    assert y.shape == x.shape


def test_larc_wrapper_steps():
    opt = LARC(FusedSGD(lr=0.1, momentum=0.9), trust_coefficient=0.02)
    params = {"w": jnp.ones((4,), jnp.float32)}
    state = opt.init(params)
    grads = {"w": jnp.full((4,), 0.5)}
    new_params, state = opt.step(grads, state, params)
    assert not np.allclose(np.asarray(new_params["w"]), 1.0)
    # momentum state advanced
    new_params2, _ = opt.step(grads, state, new_params)
    assert not np.allclose(np.asarray(new_params2["w"]), np.asarray(new_params["w"]))
