"""Tests for tools/lint_determinism.py — the host-determinism AST pass.

Red tests prove each violation class actually fires on seeded source;
the green test pins the real serving/resilience/telemetry tree clean,
which is the tier-1 guarantee the VirtualClock replay oracles lean on.
"""
import subprocess
import sys
import textwrap
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools.lint_determinism import (  # noqa: E402
    DEFAULT_PATHS,
    REPO_ROOT,
    lint_paths,
    lint_source,
    main,
)


def _codes(violations):
    return sorted(v.code for v in violations)


# ---------------------------------------------------------------------------
# red: each violation class fires
# ---------------------------------------------------------------------------

def test_wall_clock_violation_fires():
    src = textwrap.dedent("""
        import time

        def latency():
            return time.time()
    """)
    v = lint_source(src, "seeded.py")
    assert _codes(v) == ["wall_clock"]
    assert v[0].line == 5
    assert v[0].func == "latency"
    assert v[0].symbol == "time.time"


def test_wall_clock_catches_aliases_and_from_imports():
    src = textwrap.dedent("""
        import time as t
        from time import monotonic as mono

        def a():
            return t.monotonic_ns()

        def b():
            return mono()
    """)
    v = lint_source(src, "seeded.py")
    assert _codes(v) == ["wall_clock", "wall_clock"]
    assert {x.symbol for x in v} == {"t.monotonic_ns", "mono"}


def test_wall_clock_ignores_perf_counter():
    # perf_counter is interval timing, not a wall clock — bench code
    # uses it freely and the lint must not cry wolf
    src = "import time\n\ndef f():\n    return time.perf_counter()\n"
    assert lint_source(src, "x.py") == []


def test_global_rng_violation_fires():
    src = textwrap.dedent("""
        import random
        import numpy as np

        def jitter():
            return random.uniform(0, 1) + np.random.rand()
    """)
    v = lint_source(src, "seeded.py")
    assert _codes(v) == ["global_rng", "global_rng"]
    assert {x.symbol for x in v} == {"random.uniform", "np.random.rand"}


def test_unseeded_rng_ctor_and_default_factory_fire():
    src = textwrap.dedent("""
        import random
        from dataclasses import dataclass, field

        import numpy as np

        def fresh():
            return np.random.default_rng()

        @dataclass
        class P:
            rng: random.Random = field(default_factory=random.Random)
    """)
    v = lint_source(src, "seeded.py")
    assert _codes(v) == ["unseeded_rng", "unseeded_rng"]


def test_seeded_rng_is_clean():
    src = textwrap.dedent("""
        import random

        import numpy as np

        def fresh(seed):
            a = np.random.default_rng(seed)
            b = np.random.default_rng(seed=seed)
            return a, b, random.Random(0)
    """)
    assert lint_source(src, "x.py") == []


# ---------------------------------------------------------------------------
# choke points and waivers
# ---------------------------------------------------------------------------

def test_choke_point_functions_are_exempt():
    src = textwrap.dedent("""
        import time

        def stamp_wall(rec):
            rec.setdefault("t_wall", time.time())
            return rec

        def _read_clock(self):
            return time.monotonic()
    """)
    assert lint_source(src, "x.py") == []


def test_line_waiver_suppresses_only_that_line():
    src = textwrap.dedent("""
        import time

        def f():
            a = time.time()  # det-lint: ok (lease beat, wall-domain)
            b = time.time()
            return a, b
    """)
    v = lint_source(src, "x.py")
    assert _codes(v) == ["wall_clock"]
    assert v[0].line == 6


def test_def_line_waiver_covers_whole_function():
    src = textwrap.dedent("""
        import time

        def spans():  # det-lint: ok (MTTR spans, wall-domain)
            a = time.time()
            b = time.monotonic()
            return a, b

        def other():
            return time.time()
    """)
    v = lint_source(src, "x.py")
    assert _codes(v) == ["wall_clock"]
    assert v[0].func == "other"


# ---------------------------------------------------------------------------
# green: the real tree is clean — the tier-1 determinism gate
# ---------------------------------------------------------------------------

def test_determinism_planes_are_clean():
    violations = lint_paths()
    assert violations == [], "\n".join(
        f"{v.path}:{v.line}: [{v.code}] {v.symbol} — {v.message}"
        for v in violations)


def test_cli_exit_codes(tmp_path):
    clean = tmp_path / "clean.py"
    clean.write_text("import time\n\ndef stamp_wall(r):\n"
                     "    r['t'] = time.time()\n    return r\n")
    dirty = tmp_path / "dirty.py"
    dirty.write_text("import time\n\ndef f():\n    return time.time()\n")
    assert main([str(clean)]) == 0
    assert main([str(dirty), "--json"]) == 1


def test_cli_runs_as_script():
    # the tier-1 harness invokes the file directly; keep that path alive
    proc = subprocess.run(
        [sys.executable, "tools/lint_determinism.py", "--json"],
        cwd=REPO_ROOT, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert '"ok": true' in proc.stdout
    assert all(p.startswith("apex_tpu") for p in DEFAULT_PATHS)
