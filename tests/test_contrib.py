"""Tests for the contrib kernel pack.

Mirrors reference contrib suites (``apex/contrib/test/``): each component
vs an independent reference implementation — torch CPU where the reference
compares against torch modules (group_norm, clip_grad), hand numpy math
elsewhere (xentropy, focal_loss, sparsity).
"""
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu.contrib.clip_grad import clip_grad_norm_
from apex_tpu.contrib.focal_loss import FocalLoss, focal_loss
from apex_tpu.contrib.group_norm import GroupNorm, group_norm_nhwc
from apex_tpu.contrib.index_mul_2d import index_mul_2d
from apex_tpu.contrib.layer_norm import FastLayerNorm, FastLayerNormFN
from apex_tpu.contrib.sparsity import ASP, create_mask
from apex_tpu.contrib.xentropy import SoftmaxCrossEntropyLoss, softmax_cross_entropy_loss


# ---------------------------------------------------------------- clip_grad


def _rand_tree(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return {
        "w": jax.random.normal(ks[0], (5, 7)),
        "b": jax.random.normal(ks[1], (7,)) * 3.0,
        "nested": [jax.random.normal(ks[2], (2, 3, 4))],
    }


def test_clip_grad_norm_matches_torch():
    grads = _rand_tree()
    tleaves = [torch.tensor(np.asarray(g), requires_grad=True)
               for g in jax.tree_util.tree_leaves(grads)]
    for t in tleaves:
        t.grad = t.detach().clone()
    max_norm = 1.7
    tnorm = torch.nn.utils.clip_grad_norm_(tleaves, max_norm)

    clipped, norm = clip_grad_norm_(grads, max_norm)
    np.testing.assert_allclose(float(norm), float(tnorm), rtol=1e-6)
    for ours, t in zip(jax.tree_util.tree_leaves(clipped), tleaves):
        np.testing.assert_allclose(np.asarray(ours), t.grad.numpy(), rtol=1e-5)


def test_clip_grad_norm_no_clip_below_threshold():
    grads = {"a": jnp.ones((2, 2)) * 0.1}
    clipped, norm = clip_grad_norm_(grads, 100.0)
    np.testing.assert_allclose(np.asarray(clipped["a"]), 0.1, rtol=1e-6)
    np.testing.assert_allclose(float(norm), 0.2, rtol=1e-6)


def test_clip_grad_norm_inf_norm():
    grads = {"a": jnp.array([1.0, -5.0]), "b": jnp.array([[3.0]])}
    clipped, norm = clip_grad_norm_(grads, 1.0, norm_type=math.inf)
    assert float(norm) == 5.0
    np.testing.assert_allclose(np.asarray(clipped["a"]),
                               np.array([0.2, -1.0]), rtol=1e-5)


def test_clip_grad_norm_jits():
    grads = _rand_tree(1)
    f = jax.jit(lambda g: clip_grad_norm_(g, 1.0))
    clipped, norm = f(grads)
    ref_norm = math.sqrt(sum(float(jnp.sum(g.astype(jnp.float32) ** 2))
                             for g in jax.tree_util.tree_leaves(grads)))
    np.testing.assert_allclose(float(norm), ref_norm, rtol=1e-5)
    del clipped


# ----------------------------------------------------------------- xentropy


def _np_smoothed_ce(logits, labels, smoothing, padding_idx):
    x = np.asarray(logits, np.float64)
    lse = np.log(np.sum(np.exp(x - x.max(-1, keepdims=True)), -1)) + x.max(-1)
    picked = x[np.arange(len(labels)), labels]
    loss = smoothing * (lse - x.mean(-1)) + (1 - smoothing) * (lse - picked)
    loss[np.asarray(labels) == padding_idx] = 0.0
    return loss


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_xentropy_vs_numpy(smoothing):
    k = jax.random.PRNGKey(3)
    logits = jax.random.normal(k, (9, 13)) * 4.0
    labels = jnp.array([0, 1, 5, 12, 3, 0, 7, 2, 9])
    ours = softmax_cross_entropy_loss(logits, labels, smoothing, padding_idx=0)
    ref = _np_smoothed_ce(logits, labels, smoothing, 0)
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=1e-5, atol=1e-6)
    # padding rows (label==0) give zero loss AND zero gradient
    g = jax.grad(lambda lg: jnp.sum(
        softmax_cross_entropy_loss(lg, labels, smoothing, 0)))(logits)
    assert float(jnp.abs(g[0]).max()) == 0.0 and float(jnp.abs(g[5]).max()) == 0.0
    assert float(jnp.abs(g[1]).max()) > 0.0


def test_xentropy_apply_shim_and_torch_parity():
    # smoothing=0, no padding hit -> plain torch F.cross_entropy(reduction=none)
    logits = jax.random.normal(jax.random.PRNGKey(0), (6, 11))
    labels = jnp.array([1, 2, 3, 4, 5, 10])
    ours = SoftmaxCrossEntropyLoss.apply(logits, labels, 0.0, padding_idx=-100)
    ref = torch.nn.functional.cross_entropy(
        torch.tensor(np.asarray(logits)),
        torch.tensor(np.asarray(labels), dtype=torch.long),
        reduction="none")
    np.testing.assert_allclose(np.asarray(ours), ref.numpy(), rtol=1e-5)


# --------------------------------------------------------------- group_norm


@pytest.mark.parametrize("act", [None, "swish"])
def test_group_norm_nhwc_vs_torch(act):
    n, h, w, c, g = 2, 5, 6, 16, 4
    x = jax.random.normal(jax.random.PRNGKey(7), (n, h, w, c))
    weight = jax.random.normal(jax.random.PRNGKey(8), (c,)) * 0.2 + 1.0
    bias = jax.random.normal(jax.random.PRNGKey(9), (c,)) * 0.1
    y = group_norm_nhwc(x, g, weight, bias, eps=1e-5, act=act)

    tx = torch.tensor(np.asarray(x)).permute(0, 3, 1, 2)  # NHWC -> NCHW
    gn = torch.nn.GroupNorm(g, c, eps=1e-5)
    with torch.no_grad():
        gn.weight.copy_(torch.tensor(np.asarray(weight)))
        gn.bias.copy_(torch.tensor(np.asarray(bias)))
    ty = gn(tx)
    if act == "swish":
        ty = ty * torch.sigmoid(ty)
    ty = ty.permute(0, 2, 3, 1).detach().numpy()
    np.testing.assert_allclose(np.asarray(y), ty, rtol=1e-4, atol=1e-5)


def test_group_norm_module_and_grads():
    m = GroupNorm(num_groups=2, num_channels=8, act="silu")
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 3, 3, 8))
    v = m.init(jax.random.PRNGKey(1), x)
    y, grads = jax.value_and_grad(
        lambda vv: jnp.sum(m.apply(vv, x) ** 2))(v)
    assert np.isfinite(float(y))
    for leaf in jax.tree_util.tree_leaves(grads):
        assert np.all(np.isfinite(np.asarray(leaf)))
    with pytest.raises(ValueError):
        m.apply(v, jnp.zeros((1, 2, 2, 4)))


def test_group_norm_bad_args():
    x = jnp.zeros((1, 2, 2, 6))
    with pytest.raises(ValueError):
        group_norm_nhwc(x, 4, None, None)  # 6 % 4 != 0
    with pytest.raises(ValueError):
        group_norm_nhwc(x, 2, None, None, act="relu")


# --------------------------------------------------------------- focal_loss


def _np_focal(logits, targets, npos, num_real, alpha, gamma, smoothing):
    p = np.asarray(logits, np.float64)
    y = np.asarray(targets)
    ncls = p.shape[-1]
    ids = np.arange(ncls)
    is_pos = (y[..., None] == ids) & (y[..., None] >= 0)
    t = np.where(is_pos, 1 - smoothing + smoothing / 2, smoothing / 2)
    sig = 1 / (1 + np.exp(-p))
    bce = -t * np.log(sig) - (1 - t) * np.log1p(-sig)
    coeff = np.where(is_pos, alpha * (1 - sig) ** gamma, (1 - alpha) * sig ** gamma)
    elem = coeff * bce
    valid = (y[..., None] != -2) & (ids < num_real)
    return np.sum(np.where(valid, elem, 0.0)) / npos


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_focal_loss_vs_numpy(smoothing):
    k = jax.random.PRNGKey(11)
    logits = jax.random.normal(k, (4, 6, 8)) * 2.0  # padded to 8, 7 real
    targets = jnp.array([[0, 3, -1, 6, -2, 2],
                         [1, -1, -1, 5, 0, -2],
                         [-1, -1, -1, -1, -1, -1],
                         [4, 4, 4, -2, -2, 0]])
    npos = 9.0
    ours = focal_loss(logits, targets, jnp.float32(npos), 7, 0.25, 2.0, smoothing)
    ref = _np_focal(logits, targets, npos, 7, 0.25, 2.0, smoothing)
    np.testing.assert_allclose(float(ours), ref, rtol=1e-5)


def test_focal_loss_ignore_and_padding_have_no_grad():
    logits = jax.random.normal(jax.random.PRNGKey(1), (2, 3, 8))
    targets = jnp.array([[0, -2, 1], [-2, -2, 2]])
    g = jax.grad(lambda lg: FocalLoss.apply(
        lg, targets, jnp.float32(3.0), 6, 0.25, 2.0))(logits)
    # ignored examples (-2): zero grad everywhere
    assert float(jnp.abs(g[0, 1]).max()) == 0.0
    assert float(jnp.abs(g[1, 0]).max()) == 0.0
    # padded classes (>= num_real_classes=6): zero grad
    assert float(jnp.abs(g[..., 6:]).max()) == 0.0
    assert float(jnp.abs(g[0, 0, :6]).max()) > 0.0


# ------------------------------------------------------------- index_mul_2d


def test_index_mul_2d_forward_and_grads():
    in1 = jax.random.normal(jax.random.PRNGKey(0), (5, 4))
    in2 = jax.random.normal(jax.random.PRNGKey(1), (7, 4))
    idx = jnp.array([0, 2, 2, 4, 1, 0, 3])
    out = index_mul_2d(in1, in2, idx)
    np.testing.assert_allclose(np.asarray(out), np.asarray(in1)[np.asarray(idx)]
                               * np.asarray(in2), rtol=1e-6)

    # backward: grad_in1 is a scatter-add over duplicate indices
    g1, g2 = jax.grad(lambda a, b: jnp.sum(index_mul_2d(a, b, idx) ** 2),
                      argnums=(0, 1))(in1, in2)
    n1, n2, nidx = map(np.asarray, (in1, in2, idx))
    ref_g1 = np.zeros_like(n1)
    for i, j in enumerate(nidx):
        ref_g1[j] += 2 * (n1[j] * n2[i]) * n2[i]
    np.testing.assert_allclose(np.asarray(g1), ref_g1, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(g2), 2 * n1[nidx] * n2 * n1[nidx],
                               rtol=1e-5, atol=1e-6)

    # double backward exists (reference ships a dedicated kernel for it)
    h = jax.grad(lambda a: jnp.sum(jax.grad(
        lambda aa: jnp.sum(index_mul_2d(aa, in2, idx) ** 2))(a) ** 2))(in1)
    assert np.all(np.isfinite(np.asarray(h)))


def test_index_mul_2d_contract_checks():
    with pytest.raises(RuntimeError):
        index_mul_2d(jnp.zeros((2, 2, 2)), jnp.zeros((2, 2)), jnp.array([0]))
    with pytest.raises(RuntimeError):
        index_mul_2d(jnp.zeros((2, 2)), jnp.zeros((3, 2)), jnp.array([0, 1]))
    with pytest.raises(RuntimeError):
        index_mul_2d(jnp.zeros((2, 2)), jnp.zeros((2, 2), jnp.bfloat16),
                     jnp.array([0, 1]))


# ----------------------------------------------------------------- sparsity


def test_create_mask_m4n2_keeps_two_largest_of_four():
    w = jnp.array([[0.1, -5.0, 3.0, 0.2, 1.0, 2.0, -3.0, 0.0]])
    mask = create_mask(w, "m4n2_1d")
    np.testing.assert_array_equal(
        np.asarray(mask), [[0, 1, 1, 0, 0, 1, 1, 0]])
    assert mask.dtype == w.dtype


@pytest.mark.parametrize("shape", [(8,), (6, 8), (6, 8, 3), (6, 8, 3, 3)])
def test_create_mask_density_and_rank_dispatch(shape):
    w = jax.random.normal(jax.random.PRNGKey(2), shape)
    mask = create_mask(w, "m4n2_1d")
    assert mask.shape == w.shape
    np.testing.assert_allclose(float(jnp.mean(mask)), 0.5)
    # every 4-group along the input-channel direction (axis 1 for rank>=2,
    # axis 0 for rank 1) has exactly 2 kept
    m = np.asarray(mask)
    if m.ndim >= 2:
        m = np.moveaxis(m, 1, -1)  # channel dim last
    groups = m.reshape(-1, 4)
    np.testing.assert_array_equal(groups.sum(1), 2)


def test_asp_workflow_and_wrapped_step():
    from apex_tpu.optimizers import FusedSGD

    params = {"dense": jax.random.normal(jax.random.PRNGKey(0), (8, 8)),
              "bias": jnp.ones((8,))}
    asp = ASP(mask_calculator="m4n2_1d",
              whitelist=lambda path, p: p.ndim == 2)
    masks = asp.compute_sparse_masks(params)
    np.testing.assert_allclose(float(jnp.mean(masks["dense"])), 0.5)
    np.testing.assert_allclose(np.asarray(masks["bias"]), 1.0)  # not whitelisted

    pruned = asp.apply_masks(params, masks)
    assert float(jnp.sum(pruned["dense"] == 0)) >= 32

    opt = FusedSGD(lr=0.5)
    state = opt.init(pruned)
    grads = jax.tree_util.tree_map(jnp.ones_like, pruned)
    step = asp.wrap_step(opt.step, masks)
    new_params, _ = step(grads, state, pruned)
    # masked slots stay exactly zero after the update
    np.testing.assert_array_equal(
        np.asarray(new_params["dense"] == 0), np.asarray(masks["dense"] == 0))
    # unmasked slots moved
    moved = np.asarray(new_params["dense"] != pruned["dense"])
    assert moved[np.asarray(masks["dense"]) == 1].all()


def test_asp_rejects_permutation():
    with pytest.raises(NotImplementedError):
        ASP(allow_permutation=True)


# --------------------------------------------------------- contrib layer_norm


def test_fast_layer_norm_vs_torch():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 32)) * 2.0
    gamma = jax.random.normal(jax.random.PRNGKey(1), (32,)) * 0.1 + 1.0
    beta = jax.random.normal(jax.random.PRNGKey(2), (32,)) * 0.1
    y = FastLayerNormFN.apply(x, gamma, beta, 1e-5)
    ref = torch.nn.functional.layer_norm(
        torch.tensor(np.asarray(x)), (32,),
        torch.tensor(np.asarray(gamma)), torch.tensor(np.asarray(beta)), 1e-5)
    np.testing.assert_allclose(np.asarray(y), ref.numpy(), rtol=1e-4, atol=1e-5)


def test_fast_layer_norm_module():
    m = FastLayerNorm(hidden_size=16, memory_efficient=True)
    x = jax.random.normal(jax.random.PRNGKey(0), (3, 16))
    v = m.init(jax.random.PRNGKey(1), x)
    y = m.apply(v, x)
    np.testing.assert_allclose(float(jnp.mean(y)), 0.0, atol=1e-5)


# ------------------------------------------------------------- import smoke


def test_all_public_names_import():
    import importlib
    import apex_tpu

    for name in ("amp", "optimizers", "normalization", "multi_tensor_apply",
                 *apex_tpu._LAZY_SUBMODULES):
        assert getattr(apex_tpu, name) is not None
    contrib = importlib.import_module("apex_tpu.contrib")
    # EVERY contrib subpackage must import (round-2 regression: a stub
    # __init__ made `import apex_tpu.contrib` itself raise)
    for sub in ("optimizers",) + contrib._LAZY:
        mod = importlib.import_module(f"apex_tpu.contrib.{sub}")
        for name in getattr(mod, "__all__", ()):
            assert hasattr(mod, name), f"contrib.{sub}.{name}"


def test_lm_head_cross_entropy_matches_unfused():
    """Chunk-fused head GEMM + CE == full-logits reference, loss AND grads
    (incl. d(head_weight) accumulated across chunks by the scan transpose)."""
    from apex_tpu.contrib.xentropy import lm_head_cross_entropy

    n, h, v = 64, 16, 96
    hid = jax.random.normal(jax.random.PRNGKey(0), (n, h))
    w = jax.random.normal(jax.random.PRNGKey(1), (v, h)) * 0.3
    labels = jax.random.randint(jax.random.PRNGKey(2), (n,), 0, v)

    def fused(hid, w):
        return jnp.mean(lm_head_cross_entropy(hid, w, labels, chunk_size=16))

    def unfused(hid, w):
        logits = hid @ w.T
        logp = jax.nn.log_softmax(logits, axis=-1)
        return jnp.mean(-jnp.take_along_axis(logp, labels[:, None], 1)[:, 0])

    np.testing.assert_allclose(
        float(fused(hid, w)), float(unfused(hid, w)), rtol=1e-6)
    gf = jax.grad(fused, argnums=(0, 1))(hid, w)
    gr = jax.grad(unfused, argnums=(0, 1))(hid, w)
    for a, b, name in zip(gf, gr, ("d_hidden", "d_head_weight")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6, err_msg=name)

    with pytest.raises(ValueError, match="divisible"):
        lm_head_cross_entropy(hid, w, labels, chunk_size=24)


@pytest.mark.parametrize("save_dtype", [None, jnp.bfloat16])
def test_lm_head_cross_entropy_unroll_parity(save_dtype):
    """unroll=True (concatenate lowering, the docs/dus_bucket.md A/B
    knob) is numerically identical to the rolled scan, fwd and bwd."""
    from apex_tpu.contrib.xentropy import lm_head_cross_entropy

    n, h, v = 64, 16, 96
    hid = jax.random.normal(jax.random.PRNGKey(0), (n, h))
    w = jax.random.normal(jax.random.PRNGKey(1), (v, h)) * 0.3
    labels = jax.random.randint(jax.random.PRNGKey(2), (n,), 0, v)

    def loss(hid, w, unroll):
        return jnp.mean(lm_head_cross_entropy(
            hid, w, labels, chunk_size=16, save_logits_dtype=save_dtype,
            unroll=unroll))

    l0, g0 = jax.value_and_grad(
        lambda a, b: loss(a, b, False), argnums=(0, 1))(hid, w)
    l1, g1 = jax.value_and_grad(
        lambda a, b: loss(a, b, True), argnums=(0, 1))(hid, w)
    np.testing.assert_allclose(float(l1), float(l0), rtol=1e-6)
    for a, b in zip(g1, g0):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# sparsity: channel-permutation search (permutation_search_kernels)
# ---------------------------------------------------------------------------


def test_apply_2_to_4_structure_and_kept_sum():
    from apex_tpu.contrib.sparsity import apply_2_to_4, sum_after_2_to_4

    m = jax.random.normal(jax.random.PRNGKey(0), (16, 12))
    pruned = apply_2_to_4(m)
    groups = np.asarray(pruned).reshape(16, 3, 4)
    assert ((groups != 0).sum(-1) <= 2).all()
    # kept sum equals the brute-force top-2 magnitude per group
    a = np.abs(np.asarray(m)).reshape(16, 3, 4)
    top2 = np.sort(a, axis=-1)[..., 2:].sum()
    assert abs(float(sum_after_2_to_4(m)) - top2) < 1e-4
    with pytest.raises(ValueError, match="multiple of 4"):
        apply_2_to_4(jnp.zeros((4, 6)))


def test_channel_swap_search_improves_and_is_valid():
    from apex_tpu.contrib.sparsity import (
        channel_swap_search,
        sum_after_2_to_4,
    )

    m = jax.random.normal(jax.random.PRNGKey(1), (24, 16))
    base = float(sum_after_2_to_4(m))
    perm, kept = channel_swap_search(np.asarray(m), max_iters=100)
    assert sorted(perm.tolist()) == list(range(16))
    permuted_kept = float(sum_after_2_to_4(m[:, perm]))
    assert abs(permuted_kept - kept) < 1e-3
    assert permuted_kept >= base - 1e-5  # never worse than identity


def test_channel_swap_search_escape_needs_key():
    from apex_tpu.contrib.sparsity import channel_swap_search

    m = np.random.default_rng(2).standard_normal((8, 8)).astype(np.float32)
    with pytest.raises(ValueError, match="requires key"):
        channel_swap_search(m, max_iters=50, escape_attempts=2)
    perm, _ = channel_swap_search(
        m, max_iters=50, escape_attempts=2, key=jax.random.PRNGKey(3)
    )
    assert sorted(perm.tolist()) == list(range(8))


def test_permutation_C_K_pair_preserves_composition():
    """Consumer-C + producer-K permutation leaves the composed network
    function unchanged (the identity the reference's fx graph pass
    maintains, permutation_lib.py apply_permutation_in_{C,K}_dim)."""
    from apex_tpu.contrib.sparsity import (
        apply_permutation_C,
        apply_permutation_K,
        channel_swap_search,
    )

    rng = np.random.default_rng(4)
    W1 = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)  # producer
    W2 = jnp.asarray(rng.standard_normal((4, 16)), jnp.float32)  # consumer
    x = jnp.asarray(rng.standard_normal((8,)), jnp.float32)
    perm, _ = channel_swap_search(np.asarray(W2), max_iters=50)
    y = W2 @ (W1 @ x)
    y_perm = apply_permutation_C(W2, perm) @ (apply_permutation_K(W1, perm) @ x)
    assert jnp.abs(y - y_perm).max() < 1e-4


def test_exhaustive_search_canonical_perm_counts():
    """The unique-combination generator matches the reference's counts
    (exhaustive_search.py: 35 for 8 cols / width 4, 5775 for 12)."""
    from apex_tpu.contrib.sparsity.permutation_search import (
        _canonical_group_perms,
    )

    p8 = _canonical_group_perms(8)
    assert p8.shape == (35, 8)
    # first entry is the identity (greedy gain baseline relies on it)
    np.testing.assert_array_equal(p8[0], np.arange(8))
    assert _canonical_group_perms(12).shape == (5775, 12)


def test_exhaustive_search_finds_global_optimum_single_window():
    """With c == window_size the search IS a global exhaustive search:
    check against direct enumeration of all 35 assignments."""
    from apex_tpu.contrib.sparsity.permutation_search import (
        _canonical_group_perms,
        exhaustive_search,
        sum_after_2_to_4,
    )

    m = np.random.default_rng(42).normal(size=(8, 8)).astype(np.float32)
    perm, kept = exhaustive_search(m, escape_attempts=0)
    best = max(
        float(sum_after_2_to_4(jnp.asarray(m)[:, p]))
        for p in _canonical_group_perms(8)
    )
    np.testing.assert_allclose(kept, best, rtol=1e-6)
    np.testing.assert_allclose(
        float(sum_after_2_to_4(jnp.asarray(m)[:, perm])), kept, rtol=1e-6)


def test_exhaustive_search_beats_greedy_on_seeded_cases():
    """VERDICT round-3 item 6 done-criterion: warm-started from the greedy
    channel-swap result, the exhaustive window search never loses and
    strictly improves on several seeds."""
    from apex_tpu.contrib.sparsity import (
        channel_swap_search,
        exhaustive_search,
        sum_after_2_to_4,
    )

    strict_wins = 0
    for seed in range(8):
        m = np.random.default_rng(seed).normal(size=(16, 16)).astype(
            np.float32)
        pg, kg = channel_swap_search(np.asarray(m), max_iters=200)
        pe, ke = exhaustive_search(
            m, escape_attempts=4, key=jax.random.PRNGKey(seed),
            initial_permutation=pg,
        )
        # the reported kept is achieved by the returned permutation
        np.testing.assert_allclose(
            float(sum_after_2_to_4(jnp.asarray(m)[:, pe])), ke, rtol=1e-5)
        assert ke >= kg - 1e-4, (seed, kg, ke)
        strict_wins += ke > kg + 1e-4
    assert strict_wins >= 2, strict_wins


def test_exhaustive_search_validation_and_small_inputs():
    from apex_tpu.contrib.sparsity import exhaustive_search

    with pytest.raises(ValueError, match="multiple"):
        exhaustive_search(np.ones((4, 6)))
    with pytest.raises(ValueError, match="window_size"):
        exhaustive_search(np.ones((4, 8)), window_size=6)
    with pytest.raises(ValueError, match="requires key"):
        exhaustive_search(np.ones((4, 16)), escape_attempts=2)
    # fewer stripes than the window: identity, no search
    perm, kept = exhaustive_search(np.ones((4, 4)), escape_attempts=0)
    np.testing.assert_array_equal(perm, np.arange(4))
