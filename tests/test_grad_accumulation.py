"""Tests for fp32 main_grad accumulation (gradient-accumulation fusion).

Mirrors the contract of `fused_weight_gradient_mlp_cuda.wgrad_gemm_accum_fp32`
(`/root/reference/apex/transformer/tensor_parallel/layers.py:415-424`):
bf16 compute, fp32 accumulate-into-buffer, per-microbatch grads never all
live.
"""
import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.transformer.tensor_parallel import (
    accumulate_main_grads,
    init_main_grads,
    wgrad_gemm_accum_fp16,
    wgrad_gemm_accum_fp32,
)

jax.config.update("jax_enable_x64", False)


def test_wgrad_gemm_accum_fp32_matches_einsum_and_accumulates():
    k = jax.random.PRNGKey(0)
    x = jax.random.normal(k, (6, 4, 16), jnp.bfloat16)  # [s, b, in]
    dy = jax.random.normal(jax.random.PRNGKey(1), (6, 4, 8), jnp.bfloat16)
    main = jnp.full((8, 16), 0.5, jnp.float32)

    out = wgrad_gemm_accum_fp32(x, dy, main)
    ref = 0.5 + np.einsum(
        "ko,ki->oi",
        np.asarray(dy, np.float32).reshape(-1, 8),
        np.asarray(x, np.float32).reshape(-1, 16),
    )
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)

    # repeated calls keep accumulating (beta=1 semantics)
    out2 = wgrad_gemm_accum_fp32(x, dy, out)
    np.testing.assert_allclose(np.asarray(out2), 2 * ref - 0.5, rtol=1e-5,
                               atol=1e-5)


def test_wgrad_gemm_accum_fp16_keeps_buffer_dtype():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 16), jnp.bfloat16)
    dy = jax.random.normal(jax.random.PRNGKey(1), (4, 8), jnp.bfloat16)
    main = jnp.zeros((8, 16), jnp.bfloat16)
    out = wgrad_gemm_accum_fp16(x, dy, main)
    assert out.dtype == jnp.bfloat16


def _micro_grad_fn(params, micro):
    """One microbatch's grads of a small bf16 MLP."""
    x, y = micro

    def loss(p):
        h = jnp.tanh(x @ p["w1"].astype(x.dtype))
        out = h @ p["w2"].astype(x.dtype)
        return jnp.mean((out.astype(jnp.float32) - y) ** 2)

    return jax.grad(loss)(params)


def _setup(n_micro=32, mbs=4, h=16):
    params = {
        "w1": jax.random.normal(jax.random.PRNGKey(0), (h, h), jnp.bfloat16) * 0.5,
        "w2": jax.random.normal(jax.random.PRNGKey(1), (h, h), jnp.bfloat16) * 0.5,
    }
    xs = jax.random.normal(jax.random.PRNGKey(2), (n_micro, mbs, h), jnp.bfloat16)
    ys = jax.random.normal(jax.random.PRNGKey(3), (n_micro, mbs, h), jnp.float32)
    return params, (xs, ys)


def test_accumulate_matches_summed_per_microbatch_grads():
    params, micros = _setup()
    acc = accumulate_main_grads(_micro_grad_fn, params, micros)

    # reference: materialise every per-microbatch grad sequentially (same
    # per-microbatch computation as the scan — vmap would batch the GEMMs
    # and round bf16 differently), sum in fp32
    xs, ys = micros
    summed = init_main_grads(params)
    for i in range(xs.shape[0]):
        g = _micro_grad_fn(params, (xs[i], ys[i]))
        summed = jax.tree_util.tree_map(
            lambda a, gi: a + gi.astype(jnp.float32), summed, g
        )
    # per-microbatch grads are bf16 and round differently across XLA
    # compilations (scan body vs eager) — allow n_micro ulps of bf16 noise
    for k in params:
        assert acc[k].dtype == jnp.float32
        tol = 32 * 0.0079 * float(jnp.abs(summed[k]).max())
        np.testing.assert_allclose(
            np.asarray(acc[k]), np.asarray(summed[k]), atol=tol
        )


def test_fp32_accumulation_beats_bf16_accumulation():
    """The point of the fp32 buffer: accumulating many bf16 microbatch grads
    in bf16 loses precision; the fp32 buffer must track the fp32 sum better."""
    params, micros = _setup(n_micro=64)
    acc_fp32 = accumulate_main_grads(_micro_grad_fn, params, micros)

    # bf16-buffer accumulation (what naive bf16 grad accumulation does)
    def tick(acc, micro):
        g = _micro_grad_fn(params, micro)
        return jax.tree_util.tree_map(
            lambda a, gi: (a + gi).astype(jnp.bfloat16), acc, g
        ), None

    acc_bf16, _ = jax.lax.scan(
        tick,
        jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.bfloat16), params
        ),
        micros,
    )

    # exact reference in fp32 via vmap+sum
    per = jax.vmap(lambda m: _micro_grad_fn(params, m))(micros)
    exact = jax.tree_util.tree_map(
        lambda g: jnp.sum(g.astype(jnp.float32), axis=0), per
    )

    for k in params:
        err32 = float(jnp.abs(acc_fp32[k] - exact[k]).max())
        err16 = float(
            jnp.abs(acc_bf16[k].astype(jnp.float32) - exact[k]).max()
        )
        assert err32 < err16, f"{k}: fp32 accum {err32} !< bf16 accum {err16}"


def test_fp32_buffer_dtype_enforced():
    """The reference raises on unsupported main_grad dtypes
    (tensor_parallel/layers.py:415-427) — no silent promotion."""
    import pytest

    x = jnp.zeros((4, 8), jnp.bfloat16)
    dy = jnp.zeros((4, 6), jnp.bfloat16)
    with pytest.raises(ValueError, match="fp32 main_grad"):
        wgrad_gemm_accum_fp32(x, dy, jnp.zeros((6, 8), jnp.bfloat16))

    params, micros = _setup(n_micro=2)
    with pytest.raises(ValueError, match="fp32"):
        accumulate_main_grads(
            _micro_grad_fn, params, micros,
            main_grads=jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.bfloat16), params
            ),
        )


def test_accumulate_continues_existing_buffer():
    params, micros = _setup(n_micro=8)
    first = accumulate_main_grads(_micro_grad_fn, params, micros)
    resumed = accumulate_main_grads(
        _micro_grad_fn, params, micros, main_grads=first
    )
    for k in params:
        np.testing.assert_allclose(
            np.asarray(resumed[k]), 2 * np.asarray(first[k]), rtol=1e-5,
            atol=1e-5,
        )


def test_accumulation_is_a_scan_not_unrolled():
    """Memory contract: ONE scan over microbatches, so only one microbatch's
    grads are live at a time (no stacked per-microbatch grads)."""
    params, micros = _setup(n_micro=16)
    jaxpr = jax.make_jaxpr(
        lambda p, m: accumulate_main_grads(_micro_grad_fn, p, m)
    )(params, micros)
    scans = [e for e in jaxpr.jaxpr.eqns if e.primitive.name == "scan"]
    assert len(scans) == 1 and scans[0].params["length"] == 16
