"""Tests for mlp, fused_dense, fp16_utils, RNN.

Mirrors reference L0 suites: ``test_mlp.py`` (MLP vs nn.Sequential),
fused_dense test, ``run_fp16util``, ``test_rnn.py``.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.fused_dense import (
    FusedDense,
    FusedDenseGeluDense,
    dense_no_bias,
    fused_dense,
    fused_dense_gelu_dense,
)
from apex_tpu.fp16_utils import (
    FP16_Optimizer,
    DynamicLossScaler,
    convert_network,
    master_params_to_model_params,
    model_grads_to_master_grads,
    network_to_half,
    prep_param_lists,
    to_python_float,
)
from apex_tpu.mlp import MLP, mlp
from apex_tpu.optimizers import FusedAdam, FusedSGD


def test_mlp_matches_sequential():
    sizes = [7, 9, 5]
    ws = [
        jax.random.normal(jax.random.PRNGKey(i), (sizes[i + 1], sizes[i])) * 0.3
        for i in range(2)
    ]
    bs = [jnp.ones((sizes[i + 1],)) * 0.1 for i in range(2)]
    x = jax.random.normal(jax.random.PRNGKey(9), (4, 7))

    # mlp_cuda applies the activation after every layer including the last
    y = mlp(x, ws, bs, activation="relu")
    ref = jax.nn.relu(jax.nn.relu(x @ ws[0].T + bs[0]) @ ws[1].T + bs[1])
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-5)

    y_sig = mlp(x, ws, bs, activation="sigmoid")
    ref_sig = jax.nn.sigmoid(
        jax.nn.sigmoid(x @ ws[0].T + bs[0]) @ ws[1].T + bs[1]
    )
    np.testing.assert_allclose(np.asarray(y_sig), np.asarray(ref_sig), atol=1e-5)

    with pytest.raises(TypeError):
        mlp(x, ws, bs, activation="tanh")


def test_mlp_module_and_grads():
    m = MLP([6, 8, 4], bias=True, activation="relu")
    x = jax.random.normal(jax.random.PRNGKey(0), (3, 6))
    variables = m.init(jax.random.PRNGKey(1), x)
    y = m.apply(variables, x)
    assert y.shape == (3, 4)
    g = jax.grad(lambda v: jnp.sum(m.apply(v, x) ** 2))(variables)
    assert jnp.isfinite(
        jnp.concatenate([l.ravel() for l in jax.tree_util.tree_leaves(g)])
    ).all()


def test_fused_dense_functions():
    x = jax.random.normal(jax.random.PRNGKey(2), (5, 8))
    w = jax.random.normal(jax.random.PRNGKey(3), (6, 8)) * 0.2
    b = jnp.linspace(-1, 1, 6)
    np.testing.assert_allclose(
        np.asarray(fused_dense(x, w, b)), np.asarray(x @ w.T + b), atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(dense_no_bias(x, w)), np.asarray(x @ w.T), atol=1e-5
    )
    w2 = jax.random.normal(jax.random.PRNGKey(4), (3, 6)) * 0.2
    b2 = jnp.zeros((3,))
    y = fused_dense_gelu_dense(x, w, b, w2, b2)
    ref = jax.nn.gelu(x @ w.T + b, approximate=True) @ w2.T + b2
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-5)


def test_fused_dense_modules():
    x = jax.random.normal(jax.random.PRNGKey(5), (2, 8))
    m = FusedDense(8, 4)
    v = m.init(jax.random.PRNGKey(6), x)
    assert m.apply(v, x).shape == (2, 4)

    m2 = FusedDenseGeluDense(8, 16, 4)
    v2 = m2.init(jax.random.PRNGKey(7), x)
    assert m2.apply(v2, x).shape == (2, 4)


# --- fp16_utils -------------------------------------------------------------

def test_network_conversion_keeps_norms_fp32():
    params = {
        "dense": {"kernel": jnp.ones((3, 3)), "bias": jnp.zeros((3,))},
        "bn_1": {"scale": jnp.ones((3,)), "bias": jnp.zeros((3,))},
        "step": jnp.array(0, jnp.int32),
    }
    half = network_to_half(params)
    assert half["dense"]["kernel"].dtype == jnp.bfloat16
    assert half["bn_1"]["scale"].dtype == jnp.bfloat16  # network_to_half: all
    assert half["step"].dtype == jnp.int32  # non-float untouched

    conv = convert_network(params)
    assert conv["dense"]["kernel"].dtype == jnp.bfloat16
    assert conv["bn_1"]["scale"].dtype == jnp.float32  # norm kept fp32


def test_master_param_roundtrip():
    params = {"w": jnp.ones((4,), jnp.bfloat16)}
    model_p, masters = prep_param_lists(params)
    assert masters["w"].dtype == jnp.float32
    masters = jax.tree_util.tree_map(lambda m: m + 0.25, masters)
    back = master_params_to_model_params(model_p, masters)
    assert back["w"].dtype == jnp.bfloat16
    grads = model_grads_to_master_grads({"w": jnp.ones((4,), jnp.bfloat16)})
    assert grads["w"].dtype == jnp.float32
    assert to_python_float(jnp.float32(3.5)) == 3.5


def test_fp16_optimizer_converges_and_skips_overflow():
    opt = FP16_Optimizer(FusedAdam(lr=0.1), dynamic_loss_scale=True)
    params = {"w": jnp.ones((8,), jnp.bfloat16)}
    state = opt.init(params)

    def loss_fn(p):
        return jnp.sum(p["w"].astype(jnp.float32) ** 2)

    for _ in range(5):
        grads = jax.grad(
            lambda p: opt.scale_loss(state, loss_fn(p))
        )(params)
        params, state = opt.step(grads, state, params)
    assert float(loss_fn(params)) < 8.0  # decreased from 8

    # overflow injection: params unchanged, scale halved
    before = np.asarray(state.masters["w"])
    scale_before = float(state.scaler.loss_scale)
    inf_grads = {"w": jnp.full((8,), jnp.inf, jnp.bfloat16)}
    params, state = opt.step(inf_grads, state, params)
    np.testing.assert_array_equal(np.asarray(state.masters["w"]), before)
    assert float(state.scaler.loss_scale) == scale_before / 2

    # checkpoint roundtrip
    sd = opt.state_dict(state)
    state2 = opt.load_state_dict(sd, state)
    np.testing.assert_array_equal(
        np.asarray(state2.masters["w"]), np.asarray(state.masters["w"])
    )


def test_fp16_optimizer_grad_clip():
    opt = FP16_Optimizer(FusedSGD(lr=1.0))
    grads = {"w": jnp.full((4,), 10.0)}
    clipped = opt.clip_master_grads(grads, max_norm=1.0)
    assert abs(float(jnp.linalg.norm(clipped["w"])) - 1.0) < 1e-4


def test_dynamic_loss_scaler_legacy():
    s = DynamicLossScaler(init_scale=16.0, scale_window=2)
    assert not s.has_overflow({"g": jnp.ones(3)})
    assert s.has_overflow({"g": jnp.array([1.0, jnp.inf])})
    s.update_scale(True)
    assert s.loss_scale == 8.0
    s.update_scale(False)
    s.update_scale(False)
    assert s.loss_scale == 16.0  # regrown after window


# --- RNN --------------------------------------------------------------------

@pytest.mark.parametrize("factory_name", ["LSTM", "GRU", "Tanh", "ReLU", "mLSTM"])
def test_rnn_models_run_and_differentiate(factory_name):
    import apex_tpu.RNN as RNNpkg

    factory = getattr(RNNpkg, factory_name)
    model = factory(input_size=5, hidden_size=7, num_layers=2)
    params = model.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (6, 3, 5))  # [s, b, f]
    y, finals = model(params, x)
    assert y.shape == (6, 3, 7)
    g = jax.grad(lambda p: jnp.sum(model(p, x)[0] ** 2))(params)
    leaves = jax.tree_util.tree_leaves(g)
    assert all(bool(jnp.isfinite(l).all()) for l in leaves)
    assert any(float(jnp.abs(l).max()) > 0 for l in leaves)


def test_rnn_bidirectional_and_proj():
    from apex_tpu.RNN import LSTM

    model = LSTM(4, 6, 1, bidirectional=True, output_size=3, batch_first=True)
    params = model.init(jax.random.PRNGKey(2))
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 5, 4))  # [b, s, f]
    y, _ = model(params, x)
    assert y.shape == (2, 5, 3)


def test_lstm_matches_manual_unroll():
    from apex_tpu.RNN import LSTM
    from apex_tpu.RNN.cells import LSTMCell

    model = LSTM(3, 4, 1)
    params = model.init(jax.random.PRNGKey(4))
    x = jax.random.normal(jax.random.PRNGKey(5), (5, 2, 3))
    y, _ = model(params, x)

    cp = params["layers"][0][0]
    h = jnp.zeros((2, 4))
    c = jnp.zeros((2, 4))
    outs = []
    for t in range(5):
        h, c = LSTMCell(cp, x[t], (h, c))
        outs.append(h)
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(jnp.stack(outs)), atol=1e-6
    )


def test_megatron_arguments_surface():
    """The expanded Megatron flag surface (VERDICT r2 weak #8): reference
    command lines parse, validation catches inconsistencies, and derived
    fields land (params_dtype, data_parallel_size, ffn default)."""
    from apex_tpu.transformer.testing.arguments import parse_args

    args = parse_args(args=[
        "--num-layers", "4", "--hidden-size", "64",
        "--num-attention-heads", "4", "--seq-length", "32",
        "--max-position-embeddings", "32", "--micro-batch-size", "2",
        "--global-batch-size", "16", "--bf16", "--sequence-parallel",
        "--tensor-model-parallel-size", "2", "--world-size", "8",
        "--recompute-granularity", "full", "--recompute-method", "uniform",
        "--lr", "1e-4", "--lr-decay-style", "cosine",
        "--save", "/tmp/ck", "--save-interval", "100",
        "--tensorboard-dir", "/tmp/tb", "--log-interval", "10",
        "--DDP-impl", "local", "--distributed-backend", "nccl",
        "--no-bias-gelu-fusion", "--rampup-batch-size", "4", "4", "100",
    ])
    assert args.params_dtype == "bfloat16"
    assert args.data_parallel_size == 4
    assert args.ffn_hidden_size == 256
    assert args.kv_channels == 16
    assert args.sequence_parallel  # tp=2 keeps it on
    assert args.bias_gelu_fusion is False
    assert args.accumulate_allreduce_grads_in_fp32 is True

    import pytest as _pytest
    with _pytest.raises(ValueError, match="divisible"):
        parse_args(args=["--num-layers", "2", "--hidden-size", "64",
                         "--num-attention-heads", "4",
                         "--micro-batch-size", "3",
                         "--global-batch-size", "16", "--world-size", "1"])
    with _pytest.raises(ValueError, match="recompute-method"):
        parse_args(args=["--num-layers", "2", "--hidden-size", "64",
                         "--num-attention-heads", "4",
                         "--recompute-method", "uniform", "--world-size", "1"])
    with _pytest.raises(ValueError, match="warmup"):
        parse_args(args=["--num-layers", "2", "--hidden-size", "64",
                         "--num-attention-heads", "4",
                         "--lr-warmup-fraction", "0.1",
                         "--lr-warmup-iters", "10", "--world-size", "1"])


def test_megatron_arguments_reference_l0_lines_and_deprecations():
    """VERDICT r3 item 8: the flag surface covers every reference
    add_argument; actual reference L0 command lines (gpt_scaling_test.py:81)
    parse unchanged; deprecated spellings upgrade or fail exactly as the
    reference does (arguments.py:105-131,:151-161)."""
    from apex_tpu.transformer.testing.arguments import parse_args

    # the reference gpt_scaling_test command line, verbatim flags
    args = parse_args(args=(
        "--micro-batch-size 1 --num-layers 16 --hidden-size 128 "
        "--num-attention-heads 16 --max-position-embeddings 128 "
        "--seq-length 128 --tensor-model-parallel-size 2 "
        "--pipeline-model-parallel-size 4 --cpu-offload "
        "--world-size 8"
    ).split())
    assert args.cpu_offload and args.pipeline_model_parallel_size == 4

    # recompute shorthand upgrades (reference :115-131)
    args = parse_args(args=(
        "--num-layers 4 --hidden-size 64 --num-attention-heads 4 "
        "--checkpoint-activations --world-size 1"
    ).split())
    assert args.recompute_granularity == "full"
    assert args.recompute_method == "uniform"
    args = parse_args(args=(
        "--num-layers 4 --hidden-size 64 --num-attention-heads 4 "
        "--recompute-activations --world-size 1"
    ).split())
    assert args.recompute_granularity == "selective"

    # hard-removed spellings error like the reference asserts
    import pytest as _pytest
    for bad, match in (
        ("--batch-size 4", "micro-batch-size"),
        ("--warmup 100", "lr-warmup-fraction"),
        ("--model-parallel-size 2", "tensor-model-parallel-size"),
    ):
        with _pytest.raises(ValueError, match=match):
            parse_args(args=(
                "--num-layers 2 --hidden-size 64 --num-attention-heads 4 "
                "--world-size 1 " + bad
            ).split())

    # per-stage virtual pipelining derives the virtual size (:151-161)
    args = parse_args(args=(
        "--num-layers 16 --hidden-size 64 --num-attention-heads 4 "
        "--pipeline-model-parallel-size 4 "
        "--num-layers-per-virtual-pipeline-stage 2 --world-size 4"
    ).split())
    assert args.virtual_pipeline_model_parallel_size == 2

    # torch.distributed.launch's --local_rank folds into --local-rank
    args = parse_args(args=(
        "--num-layers 2 --hidden-size 64 --num-attention-heads 4 "
        "--local_rank 3 --world-size 1"
    ).split())
    assert args.local_rank == 3

    # biencoder + vision groups exist with reference defaults
    args = parse_args(args=(
        "--num-layers 2 --hidden-size 64 --num-attention-heads 4 "
        "--world-size 1 --ict-head-size 128 --vision-backbone-type swin "
        "--dino-teacher-temp 0.05 --retriever-report-topk-accuracies 1 5 20"
    ).split())
    assert args.ict_head_size == 128
    assert args.vision_backbone_type == "swin"
    assert args.retriever_report_topk_accuracies == [1, 5, 20]
    assert args.indexer_batch_size == 128 and args.num_classes == 1000


def test_megatron_arguments_cover_reference_flag_set():
    """Every --flag the reference's arguments.py registers is accepted
    here (mechanical diff, so the surface cannot silently regress)."""
    import re

    from apex_tpu.transformer.testing import arguments as A

    ref_path = "/root/reference/apex/transformer/testing/arguments.py"
    try:
        ref_src = open(ref_path).read()
    except OSError:
        import pytest as _pytest
        _pytest.skip("reference tree unavailable")
    ref_flags = set(re.findall(r"add_argument\(\s*['\"](--[\w-]+)", ref_src))
    our_src = open(A.__file__).read()
    our_flags = set(re.findall(r"add_argument\(\s*['\"](--[\w-]+)", our_src))
    missing = sorted(ref_flags - our_flags)
    assert not missing, missing
