"""Transducer (RNN-T) tests — joint and loss vs independent references.

Mirrors the reference suite style (`apex/contrib/test/transducer/`):
the joint vs explicit broadcast math + packing bookkeeping, the loss vs
a pure-numpy alpha DP, and gradient sanity.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.transducer import (
    TransducerJoint,
    TransducerLoss,
    transducer_joint,
    transducer_loss,
)


def _joint_inputs(key=0, b=3, t=5, u=4, h=8):
    ks = jax.random.split(jax.random.PRNGKey(key), 2)
    f = jax.random.normal(ks[0], (b, t, h))
    g = jax.random.normal(ks[1], (b, u, h))
    f_len = jnp.array([5, 3, 4])
    g_len = jnp.array([4, 2, 3])
    return f, g, f_len, g_len


def test_joint_unpacked_matches_broadcast():
    f, g, f_len, g_len = _joint_inputs()
    out = transducer_joint(f, g, f_len, g_len)
    ref = np.asarray(f)[:, :, None, :] + np.asarray(g)[:, None, :, :]
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-6)


def test_joint_relu_and_mask_probe():
    f, g, f_len, g_len = _joint_inputs(1)
    j = TransducerJoint(relu=True, probe_mask=True)
    out = j(f, g, f_len, g_len)
    ref = np.maximum(
        np.asarray(f)[:, :, None, :] + np.asarray(g)[:, None, :, :], 0.0)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-6)
    assert j.mask_probe and j.mask_probe[0].shape == out.shape


def test_joint_packing():
    f, g, f_len, g_len = _joint_inputs(2)
    batch_offset = jnp.cumsum(f_len * g_len)
    packed_batch = int(batch_offset[-1])
    out = transducer_joint(
        f, g, f_len, g_len, pack_output=True,
        batch_offset=batch_offset, packed_batch=packed_batch)
    assert out.shape == (packed_batch, f.shape[-1])
    # row for (b, t, u) is f[b, t] + g[b, u], laid out t-major per batch
    fn, gn = np.asarray(f), np.asarray(g)
    starts = np.concatenate([[0], np.asarray(batch_offset)[:-1]])
    for bb in range(f.shape[0]):
        for tt in range(int(f_len[bb])):
            for uu in range(int(g_len[bb])):
                row = starts[bb] + tt * int(g_len[bb]) + uu
                np.testing.assert_allclose(
                    np.asarray(out[row]), fn[bb, tt] + gn[bb, uu], rtol=1e-6)


def test_joint_dropout_training_only():
    f, g, f_len, g_len = _joint_inputs(3)
    j = TransducerJoint(dropout=True, dropout_prob=0.5)
    out_eval = j(f, g, f_len, g_len, training=False)
    ref = np.asarray(f)[:, :, None, :] + np.asarray(g)[:, None, :, :]
    np.testing.assert_allclose(np.asarray(out_eval), ref, rtol=1e-6)
    out_train = j(f, g, f_len, g_len, training=True,
                  dropout_key=jax.random.PRNGKey(0))
    zeros = float((np.asarray(out_train) == 0).mean())
    assert 0.3 < zeros < 0.7  # ~half dropped


def _np_rnnt_loss(x, label, f_len, y_len, blank):
    """Pure-numpy alpha DP (Graves 2012) per utterance."""
    x = np.asarray(x, np.float64)
    logp = x - np.log(np.sum(np.exp(
        x - x.max(-1, keepdims=True)), -1, keepdims=True)) - x.max(
            -1, keepdims=True)
    b = x.shape[0]
    losses = []
    for i in range(b):
        T, U = int(f_len[i]), int(y_len[i]) + 1
        alpha = np.full((T, U), -np.inf)
        alpha[0, 0] = 0.0
        for t in range(T):
            for u in range(U):
                if t == 0 and u == 0:
                    continue
                c = []
                if t > 0:
                    c.append(alpha[t - 1, u] + logp[i, t - 1, u, blank])
                if u > 0:
                    c.append(alpha[t, u - 1]
                             + logp[i, t, u - 1, label[i, u - 1]])
                alpha[t, u] = np.logaddexp.reduce(c)
        losses.append(-(alpha[T - 1, U - 1] + logp[i, T - 1, U - 1, blank]))
    return np.array(losses)


def _loss_inputs(key=0, b=3, t=6, u_max=5, v=7):
    x = jax.random.normal(jax.random.PRNGKey(key), (b, t, u_max, v)) * 2.0
    label = jax.random.randint(
        jax.random.PRNGKey(key + 1), (b, u_max - 1), 0, v - 1)
    f_len = jnp.array([6, 4, 5])
    y_len = jnp.array([4, 2, 3])
    return x, label, f_len, y_len


def test_loss_matches_numpy_dp():
    x, label, f_len, y_len = _loss_inputs()
    blank = 6
    ours = transducer_loss(x, label, f_len, y_len, blank)
    ref = _np_rnnt_loss(x, label, f_len, y_len, blank)
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=1e-5)


def test_loss_grads_flow_only_into_valid_region():
    x, label, f_len, y_len = _loss_inputs(4)
    blank = 6
    g = jax.grad(lambda x: jnp.sum(
        transducer_loss(x, label, f_len, y_len, blank)))(x)
    g = np.asarray(g)
    assert np.all(np.isfinite(g))
    # utterance 1 has f_len 4: time steps >= 4 must get zero grad
    assert np.abs(g[1, 4:]).max() == 0.0
    assert np.abs(g[1, :4]).max() > 0.0
    # grads sum to ~0 over vocab for softmax-composed loss? no — but the
    # total emission mass constraint: d(loss)/dx sums to 0 per (b,t,u)
    # slot actually holds for log_softmax outputs
    np.testing.assert_allclose(g.sum(-1), 0.0, atol=1e-5)


def test_loss_module_and_alpha_probe():
    x, label, f_len, y_len = _loss_inputs(5)
    mod = TransducerLoss()
    dbg = []
    out = mod(x, label, f_len, y_len, 6, debug_list=dbg)
    assert out.shape == (3,)
    assert dbg and dbg[0].shape == (3, x.shape[1], x.shape[2])


def test_loss_packed_input_matches_dense():
    x, label, f_len, y_len = _loss_inputs(6)
    blank = 6
    b, t, u_max, v = x.shape
    # pack: per batch, rows (t, u) for t < f_len, u <= y_len, t-major
    batch_offset = jnp.cumsum(f_len * (y_len + 1))
    rows = []
    for i in range(b):
        for tt in range(int(f_len[i])):
            for uu in range(int(y_len[i]) + 1):
                rows.append(np.asarray(x[i, tt, uu]))
    packed = jnp.asarray(np.stack(rows))

    dense_loss_v = transducer_loss(x, label, f_len, y_len, blank)
    mod = TransducerLoss(packed_input=True)
    packed_loss = mod(packed, label, f_len, y_len, blank,
                      batch_offset=batch_offset, max_f_len=t)
    np.testing.assert_allclose(
        np.asarray(packed_loss), np.asarray(dense_loss_v), rtol=1e-5)


def test_loss_packed_requires_args():
    x, label, f_len, y_len = _loss_inputs(7)
    with pytest.raises(ValueError):
        TransducerLoss(packed_input=True)(
            x.reshape(-1, x.shape[-1]), label, f_len, y_len, 6)


def test_joint_mask_probe_under_jit_via_return_mask():
    """The value-returning probe works under jit (a mutated Python list
    would hold a stale tracer — review r3 finding)."""
    f, g, f_len, g_len = _joint_inputs(8)

    @jax.jit
    def run(f, g):
        return transducer_joint(f, g, f_len, g_len, relu=True,
                                return_mask=True)

    out, mask = run(f, g)
    out2, mask2 = run(f * 2, g * 2)
    np.testing.assert_array_equal(np.asarray(mask), np.asarray(out) > 0)
    assert not np.array_equal(np.asarray(out), np.asarray(out2))

    # the module attribute keeps only the latest eager call's mask
    j = TransducerJoint(relu=True, probe_mask=True)
    j(f, g, f_len, g_len)
    j(f, g, f_len, g_len)
    assert len(j.mask_probe) == 1


def test_loss_return_alphas_value_api():
    x, label, f_len, y_len = _loss_inputs(9)

    @jax.jit
    def run(x):
        return transducer_loss(x, label, f_len, y_len, 6, return_alphas=True)

    losses, alphas = run(x)
    assert alphas.shape == (3, x.shape[1], x.shape[2])
    np.testing.assert_allclose(
        np.asarray(losses),
        np.asarray(transducer_loss(x, label, f_len, y_len, 6)), rtol=1e-6)


def test_joint_packed_mask_matches_packed_output():
    """With pack_output + return_mask the mask is packed row-for-row with
    the output (review r3: a dense mask against a packed output is
    unusable)."""
    f, g, f_len, g_len = _joint_inputs(10)
    batch_offset = jnp.cumsum(f_len * g_len)
    packed_batch = int(batch_offset[-1])
    out, mask = transducer_joint(
        f, g, f_len, g_len, pack_output=True, relu=True,
        batch_offset=batch_offset, packed_batch=packed_batch,
        return_mask=True)
    assert mask.shape == out.shape
    np.testing.assert_array_equal(np.asarray(mask), np.asarray(out) > 0)
