"""Elastic multi-host training service (ISSUE-15).

Covers the three tentpole pieces — the fake-host :class:`Supervisor`
(death/hang detection, world restart, reshape), the two-phase
multi-host checkpoint commit (``shard-<h>.part`` staging, filesystem
rendezvous, rank-0 ``COMMIT`` promotion, markerless-step-is-garbage),
and topology-elastic resume (bit-exact re-flattening of packed
FusedAdam + GradBuckets state across world sizes) — plus the
satellites: fsync durability of the base manager's rename commit,
multi-writer-safe stale-tmp sweeping (seeded-violation red tests),
restore fallback over a partially-committed multi-host step, the
attributable :class:`HangWatchdog` context, and the bench/CLI wiring.

The full chaos trace (kills mid-part-write and mid-barrier, a
heartbeat wedge, a topology reshape — final loss records byte-exact)
is in the slow tier; its tier-1 coverage rides the ``elastic_resume`` /
``host_kill`` legs of ``tools/resilience_check.py --self``
(parametrized into the quick tier by ``tests/test_resilience.py``).
"""
import json
import os
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu import analysis
from apex_tpu.multi_tensor_apply.packing import ROW, PackSpec
from apex_tpu.resilience import (
    BarrierNotReady,
    ChaosError,
    ChaosHost,
    CheckpointManager,
    ElasticCheckpointManager,
    HangError,
    HangWatchdog,
    Heartbeat,
    Supervisor,
    WorldFailedError,
    capture,
    pack_spec_for_world,
    reflatten_flat,
    world_chunk_size,
)
from apex_tpu.resilience._elastic_host import (
    build_world,
    init_params,
    reference_records,
)
from apex_tpu.telemetry import RingBufferRecorder

REPO = Path(__file__).parent.parent


# ---------------------------------------------------------------------------
# world-aware layouts + re-flattening
# ---------------------------------------------------------------------------
class TestWorldLayout:
    def test_world_chunk_size_divisibility(self):
        assert world_chunk_size(256, 4) == 4 * ROW
        assert world_chunk_size(4 * ROW, 4) == 4 * ROW
        assert world_chunk_size(4 * ROW + 1, 4) == 8 * ROW
        with pytest.raises(ValueError):
            world_chunk_size(256, 0)

    @pytest.mark.parametrize("world", [1, 2, 4])
    def test_pack_spec_for_world_shard_clean(self, world):
        spec = pack_spec_for_world(init_params(), world, chunk_size=256)
        assert not analysis.check_pack_spec(spec, shard_count=world)
        bounds = spec.shard_bounds(world)
        assert bounds[0][0] == 0 and bounds[-1][1] == spec.total
        for lo, hi in bounds:
            assert (hi - lo) % ROW == 0

    def test_shard_bounds_red_indivisible(self):
        spec = PackSpec({"w": jnp.zeros((8,))}, chunk_size=ROW)
        assert spec.total == ROW
        with pytest.raises(ValueError, match="not divisible"):
            spec.shard_bounds(3)

    def test_grad_buckets_for_world_layouts_differ(self):
        _, b2, _, _ = build_world(2)
        _, b4, _, _ = build_world(4)
        # different worlds genuinely lay out differently (the reshard
        # path is not a no-op) yet both shard cleanly
        assert b2.spec.total != b4.spec.total
        assert b2.spec.offsets != b4.spec.offsets
        assert not analysis.check_pack_spec(b2.spec, shard_count=2)
        assert not analysis.check_pack_spec(b4.spec, shard_count=4)


class TestReflatten:
    def _filled(self, spec, seed=0):
        buf = np.zeros((spec.total,), np.float32)
        rng = np.random.default_rng(seed)
        mask = spec.valid_mask()
        buf[mask] = rng.standard_normal(int(mask.sum())).astype(np.float32)
        return buf

    def test_roundtrip_bitwise(self):
        _, b2, _, _ = build_world(2)
        _, b4, _, _ = build_world(4)
        buf = self._filled(b4.spec)
        out = reflatten_flat(b4.spec, b2.spec, buf)
        back = reflatten_flat(b2.spec, b4.spec, out)
        np.testing.assert_array_equal(back, buf)
        # per-leaf values unchanged bit-for-bit
        a = b4.spec.unpack(buf, cast=False)
        b = b2.spec.unpack(out, cast=False)
        for la, lb in zip(jax.tree_util.tree_leaves(a),
                          jax.tree_util.tree_leaves(b)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))

    def test_mismatched_templates_raise(self):
        _, b2, _, _ = build_world(2)
        other = PackSpec({"x": jnp.zeros((64, 64))}, chunk_size=1024)
        with pytest.raises(ValueError, match="different leaf"):
            reflatten_flat(other, b2.spec, np.zeros((other.total,),
                                                    np.float32))

    def test_wrong_length_buffer_raises(self):
        _, b2, _, _ = build_world(2)
        with pytest.raises(ValueError, match="shape"):
            reflatten_flat(b2.spec, b2.spec,
                           np.zeros((b2.spec.total + 1,), np.float32))

    def test_check_reshard_red_and_green(self):
        _, b2, _, _ = build_world(2)
        _, b4, _, _ = build_world(4)
        assert not analysis.check_reshard(b4.spec, b2.spec,
                                          old_count=4, new_count=2)
        other = PackSpec({"x": jnp.zeros((64, 64))}, chunk_size=1024)
        findings = analysis.check_reshard(other, b2.spec)
        assert any(f.code == "reshard_leaf_mismatch" for f in findings)


# ---------------------------------------------------------------------------
# two-phase multi-host commit
# ---------------------------------------------------------------------------
def _fresh_state(world, step=0, position=0):
    p, b, o, s = build_world(world)
    return capture(step, p, o.init(p), scaler=s.init_state(),
                   rng=jax.random.PRNGKey(42),
                   data={"position": position})


def _save_world(root, state, world, rec=None, barrier_timeout_s=30.0):
    """All-hosts save through W in-process manager instances (hosts > 0
    async — they wait for host 0's COMMIT in the background)."""
    mgrs = [ElasticCheckpointManager(root, host=h, world=world, sink=rec,
                                     barrier_timeout_s=barrier_timeout_s)
            for h in range(world)]
    for m in mgrs[1:]:
        m.save(state, blocking=False)
    mgrs[0].save(state, blocking=True)
    for m in mgrs[1:]:
        m.wait_until_finished()
    return mgrs


class TestTwoPhaseCommit:
    def test_commit_layout_and_marker(self, tmp_path):
        root = str(tmp_path / "ckpt")
        state = _fresh_state(2, step=3, position=3)
        mgrs = _save_world(root, state, 2)
        d = tmp_path / "ckpt" / "step_00000003"
        assert sorted(os.listdir(d)) == ["COMMIT", "shard-0.part",
                                         "shard-1.part"]
        commit = json.loads((d / "COMMIT").read_text())
        assert commit["world"] == 2 and commit["step"] == 3
        assert mgrs[0].all_steps() == [3]
        meta1 = json.loads(
            (d / "shard-1.part" / "meta.json").read_text())
        assert meta1["host"] == 1 and meta1["pid"] == os.getpid()
        assert "data" not in meta1  # replicated host state rides shard 0

    def test_markerless_step_is_garbage_fallback(self, tmp_path):
        """Satellite: restore over a PARTIALLY committed multi-host
        step (some shards present, no COMMIT) must skip back to the
        prior good step, emit ``checkpoint_fallback``, and raise
        nothing."""
        root = str(tmp_path / "ckpt")
        rec = RingBufferRecorder()
        good = _fresh_state(2, step=4, position=4)
        _save_world(root, good, 2, rec=rec)
        # a torn newer save: one shard landed, COMMIT never written
        torn = tmp_path / "ckpt" / "step_00000006" / "shard-0.part"
        torn.mkdir(parents=True)
        (torn / "meta.json").write_text(json.dumps(
            {"step": 6, "host": 0, "world": 2, "pid": os.getpid()}))
        m = ElasticCheckpointManager(root, host=0, world=2, sink=rec)
        restored = m.restore(_fresh_state(2))
        assert restored is not None and restored.step == 4
        falls = [r for r in rec.records
                 if r["event"] == "checkpoint_fallback"]
        assert [r["step"] for r in falls] == [6]
        assert "COMMIT" in falls[0]["error"] or "uncommitted" in \
            falls[0]["error"]

    def test_no_commit_without_all_shards(self, tmp_path):
        """Rank 0's barrier times out when a peer never lands its
        shard; the step stays markerless and the failure surfaces as a
        checkpoint_failed event + BarrierNotReady."""
        root = str(tmp_path / "ckpt")
        rec = RingBufferRecorder()
        m0 = ElasticCheckpointManager(root, host=0, world=2, sink=rec,
                                      barrier_timeout_s=0.5)
        with pytest.raises(BarrierNotReady):
            m0.save(_fresh_state(2, step=3), blocking=True)
        d = tmp_path / "ckpt" / "step_00000003"
        assert not (d / "COMMIT").exists()
        assert m0.all_steps() == []
        assert any(r["event"] == "checkpoint_failed"
                   for r in rec.records)
        # and restore never touches the markerless garbage
        assert m0.restore(_fresh_state(2)) is None

    def test_emergency_flush_commits_alone_and_restores(self, tmp_path):
        """A preemption flush cannot barrier (peers got the same
        SIGTERM at other steps): any host commits a complete
        world-of-1 checkpoint alone, and restore reshards it onto the
        real world like any topology change."""
        root = str(tmp_path / "ckpt")
        rec = RingBufferRecorder()
        _, state = reference_records(2, 3)  # non-trivial moments
        m1 = ElasticCheckpointManager(root, host=1, world=2, sink=rec,
                                      barrier_timeout_s=5.0)
        m1.save(state, emergency=True)  # NO peers ever show up
        assert m1.all_steps() == [3]
        commit = json.loads(
            (tmp_path / "ckpt" / "step_00000003" / "COMMIT").read_text())
        assert commit["world"] == 1 and commit["emergency"] is True
        m0 = ElasticCheckpointManager(root, host=0, world=2, sink=rec)
        restored = m0.restore(_fresh_state(2))
        assert restored.step == 3 and restored.data == {"position": 3}
        for a, b in zip(jax.tree_util.tree_leaves(restored.opt_state),
                        jax.tree_util.tree_leaves(state.opt_state)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # GC treats it as an emergency checkpoint (retention-exempt)
        assert m0._is_emergency(str(tmp_path / "ckpt" /
                                    "step_00000003"))

    def test_barrier_rejects_stale_shard_from_other_world(self, tmp_path):
        """A dead incarnation's shard at a DIFFERENT world size must
        not satisfy the rendezvous — committing it would mix row
        layouts across topologies."""
        root = str(tmp_path / "ckpt")
        stale = tmp_path / "ckpt" / "step_00000003" / "shard-1.part"
        stale.mkdir(parents=True)
        (stale / "meta.json").write_text(json.dumps(
            {"step": 3, "host": 1, "world": 4, "pid": 1}))
        m0 = ElasticCheckpointManager(root, host=0, world=2,
                                      barrier_timeout_s=0.5)
        with pytest.raises(BarrierNotReady):
            m0.save(_fresh_state(2, step=3), blocking=True)
        assert not (tmp_path / "ckpt" / "step_00000003" /
                    "COMMIT").exists()


class TestElasticReshard:
    def test_restore_onto_other_worlds_bitwise(self, tmp_path):
        root = str(tmp_path / "ckpt")
        rec = RingBufferRecorder()
        # a real trained state (non-zero moments) at W=2
        _, head = reference_records(2, 3)
        _save_world(root, head, 2, rec=rec)
        s2 = head.opt_state.spec
        for new_world in (1, 4):
            m = ElasticCheckpointManager(root, host=0, world=new_world,
                                         sink=rec)
            restored = m.restore(_fresh_state(new_world))
            assert restored.step == 3
            assert restored.data == {"position": 3}
            sN = restored.opt_state.spec
            assert not analysis.check_pack_spec(sN,
                                               shard_count=new_world)
            for name in ("exp_avg", "exp_avg_sq", "master_params"):
                a = s2.unpack(np.asarray(getattr(head.opt_state, name)),
                              cast=False)
                b = sN.unpack(
                    np.asarray(getattr(restored.opt_state, name)),
                    cast=False)
                for la, lb in zip(jax.tree_util.tree_leaves(a),
                                  jax.tree_util.tree_leaves(b)):
                    np.testing.assert_array_equal(np.asarray(la),
                                                  np.asarray(lb))
            # scalars and replicated leaves ride along bit-exactly
            assert np.asarray(restored.opt_state.step) == \
                np.asarray(head.opt_state.step)
            for la, lb in zip(
                    jax.tree_util.tree_leaves(restored.params),
                    jax.tree_util.tree_leaves(head.params)):
                np.testing.assert_array_equal(np.asarray(la),
                                              np.asarray(lb))
        assert any(r["event"] == "checkpoint_reshard"
                   for r in rec.records)

    def test_resumed_records_bit_identical_to_uninterrupted(self, tmp_path):
        """The acceptance oracle in-process: W=4 head + W'=2 tail ==
        uninterrupted W'=2 run, byte-for-byte (f32 hex records)."""
        root = str(tmp_path / "ckpt")
        head_records, head = reference_records(4, 3)
        _save_world(root, head, 4)
        m = ElasticCheckpointManager(root, host=0, world=2)
        restored = m.restore(_fresh_state(2))
        tail_records, _ = reference_records(2, 6, start_state=restored)
        ref_records, _ = reference_records(2, 6)
        assert {**head_records, **tail_records} == ref_records


# ---------------------------------------------------------------------------
# satellite: fsync durability of the rename commit
# ---------------------------------------------------------------------------
class TestFsyncDurability:
    def test_commit_fsyncs_staged_tree_and_parent(self, tmp_path,
                                                  monkeypatch):
        from apex_tpu.resilience import manager as mgr_mod

        trees, dirs = [], []
        real_tree, real_dir = mgr_mod.fsync_tree, mgr_mod.fsync_dir
        monkeypatch.setattr(mgr_mod, "fsync_tree",
                            lambda p: (trees.append(p),
                                       real_tree(p))[1])
        monkeypatch.setattr(mgr_mod, "fsync_dir",
                            lambda p: (dirs.append(p), real_dir(p))[1])
        fsyncs = []
        real_fsync = os.fsync
        monkeypatch.setattr(os, "fsync",
                            lambda fd: (fsyncs.append(fd),
                                        real_fsync(fd))[1])
        m = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
        m.save(capture(2, {"w": jnp.arange(4.0)}, None), blocking=True)
        # the WHOLE staged tree (arrays + meta) flushed before the
        # rename, the parent directory after it
        assert any(".tmp-" in p for p in trees)
        assert m.root in dirs
        assert fsyncs  # per-file payload fsyncs actually happened

    def test_injected_fsync_fault_fails_clean(self, tmp_path,
                                              monkeypatch):
        """A fault in the new durability window (fail_commit_at-style:
        after the array write, around the rename) must fail the save
        cleanly — tmp swept, prior steps loadable."""
        from apex_tpu.resilience import manager as mgr_mod

        m = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
        template = capture(0, {"w": jnp.arange(4.0)}, None)
        m.save(capture(2, {"w": jnp.full((4,), 2.0)}, None),
               blocking=True)

        def flaky(p):
            raise ChaosError("injected fsync fault")

        monkeypatch.setattr(mgr_mod, "fsync_tree", flaky)
        with pytest.raises(ChaosError):
            m.save(capture(4, {"w": jnp.full((4,), 4.0)}, None),
                   blocking=True)
        monkeypatch.undo()
        leftovers = [n for n in os.listdir(m.root) if ".tmp-" in n]
        assert leftovers == []
        restored = m.restore(template)
        assert restored.step == 2
        assert float(restored.params["w"][0]) == 2.0


# ---------------------------------------------------------------------------
# satellite: multi-writer-safe stale-tmp sweep (seeded-violation reds)
# ---------------------------------------------------------------------------
@pytest.fixture
def live_foreign_pid():
    """A real live process that is NOT us — the concurrent fake host
    whose in-flight save a sweep must never delete."""
    proc = subprocess.Popen([sys.executable, "-c",
                             "import time; time.sleep(60)"])
    yield proc.pid
    proc.kill()
    proc.wait()


def _dead_pid():
    proc = subprocess.Popen([sys.executable, "-c", "pass"])
    proc.wait()
    return proc.pid


class TestMultiWriterSweep:
    def test_base_sweep_spares_live_writer(self, tmp_path,
                                           live_foreign_pid):
        """Seeded violation: a live concurrent host's in-flight
        ``step_*.tmp-<pid>`` staging tree survives a restarting peer's
        init sweep; a dead writer's is reclaimed."""
        root = tmp_path / "ckpt"
        live = root / f"step_00000006.tmp-{live_foreign_pid}"
        dead = root / f"step_00000008.tmp-{_dead_pid()}"
        for d in (live, dead):
            d.mkdir(parents=True)
            (d / "sentinel").write_text("x")
        CheckpointManager(str(root))
        assert live.exists(), \
            "sweep deleted a LIVE concurrent writer's in-flight save"
        assert not dead.exists()

    def test_elastic_sweep_spares_live_shard_writer(self, tmp_path,
                                                    live_foreign_pid):
        root = tmp_path / "ckpt"
        step = root / "step_00000004"
        live_tmp = step / f"shard-1.tmp-{live_foreign_pid}"
        dead_tmp = step / f"shard-2.tmp-{_dead_pid()}"
        for d in (live_tmp, dead_tmp):
            d.mkdir(parents=True)
        ElasticCheckpointManager(str(root), host=0, world=2)
        assert live_tmp.exists(), \
            "sweep deleted a LIVE host's in-flight shard staging"
        assert not dead_tmp.exists()

    def test_elastic_sweep_markerless_garbage_rules(self, tmp_path,
                                                    live_foreign_pid):
        root = str(tmp_path / "ckpt")
        # newest committed step: 6
        _save_world(root, _fresh_state(2, step=6, position=6), 2)

        def seed_partial(step, pid):
            d = tmp_path / "ckpt" / f"step_{step:08d}" / "shard-0.part"
            d.mkdir(parents=True)
            (d / "meta.json").write_text(json.dumps(
                {"step": step, "host": 0, "world": 2, "pid": pid}))
            return d.parent

        older_dead = seed_partial(2, _dead_pid())
        older_live = seed_partial(4, live_foreign_pid)
        newer_dead = seed_partial(8, _dead_pid())
        # a markerless OLD step holding ONLY a live peer's phase-1
        # staging (no .part yet): deadness must consider the tmp's
        # filename pid, not just .part metas
        older_live_tmp = (tmp_path / "ckpt" / "step_00000003"
                          / f"shard-1.tmp-{live_foreign_pid}")
        older_live_tmp.mkdir(parents=True)
        ElasticCheckpointManager(root, host=1, world=2)
        assert not older_dead.exists()  # garbage: old + dead writers
        assert older_live.exists(), \
            "sweep deleted a step a LIVE writer is still saving"
        assert older_live_tmp.exists(), \
            "sweep deleted a step a LIVE writer is still STAGING into"
        # >= newest commit: a live world may be (re)writing it
        assert newer_dead.exists()


# ---------------------------------------------------------------------------
# ChaosHost + Heartbeat
# ---------------------------------------------------------------------------
class TestChaosHost:
    def test_spec_roundtrip(self):
        c = (ChaosHost().kill_at_step(7).kill_in_shard_write_at(6)
             .kill_in_barrier_at(5).wedge_heartbeat_at(9, 2.5))
        assert ChaosHost.parse(c.to_spec()).to_spec() == c.to_spec()
        assert ChaosHost.parse("").to_spec() == ""
        with pytest.raises(ValueError, match="unknown chaos fault"):
            ChaosHost.parse("explode@3")

    def test_take_fires_once_at_or_past_step(self, monkeypatch):
        died = []
        monkeypatch.setattr(ChaosHost, "_die",
                            staticmethod(lambda: died.append(True)))
        c = ChaosHost().kill_at_step(5)
        c.at_step_boundary(4)
        assert not died
        c.at_step_boundary(6)  # past the armed step still fires
        assert len(died) == 1
        c.at_step_boundary(7)  # once only
        assert len(died) == 1
        assert c.faults_fired == [("kill", 6)]

    def test_wedge_take(self):
        c = ChaosHost().wedge_heartbeat_at(3, 1.5)
        assert c.take_wedge(2) is None
        assert c.take_wedge(3) == 1.5
        assert c.take_wedge(4) is None


class TestHeartbeat:
    def test_beat_read_age(self, tmp_path):
        path = str(tmp_path / "hb" / "hb-1")
        assert Heartbeat.age_s(path) is None
        hb = Heartbeat(path, host=1)
        hb.beat(7)
        rec = Heartbeat.read(path)
        assert rec["host"] == 1 and rec["step"] == 7
        assert Heartbeat.age_s(path) < 5.0


# ---------------------------------------------------------------------------
# the supervisor (non-jax children: fast)
# ---------------------------------------------------------------------------
def _script_host(tmp_path, body_by_incarnation):
    """build_cmd for tiny non-jax hosts: each incarnation runs the
    python -c body chosen for it (formatted with host/heartbeat)."""

    def build_cmd(host, world, incarnation):
        body = body_by_incarnation[min(incarnation,
                                       len(body_by_incarnation) - 1)]
        hb = os.path.join(str(tmp_path / "hb"), f"hb-{host}")
        return [sys.executable, "-c", body.format(hb=hb, host=host)]

    return build_cmd


BEAT_AND_EXIT0 = "open(r'{hb}', 'w').close()"
BEAT_AND_DIE = ("import sys; open(r'{hb}', 'w').close(); "
                "sys.exit(3 if {host} == 1 else 0)")
BEAT_AND_HANG = ("import time; open(r'{hb}', 'w').close(); "
                 "time.sleep(60 if {host} == 1 else 0)")


class TestSupervisor:
    def test_death_restart_and_recovery(self, tmp_path):
        rec = RingBufferRecorder()
        sup = Supervisor(
            _script_host(tmp_path, [BEAT_AND_DIE, BEAT_AND_EXIT0]),
            2, heartbeat_dir=str(tmp_path / "hb"),
            heartbeat_timeout_s=30.0, max_restarts=2, sink=rec)
        summary = sup.run()
        assert summary["ok"] and summary["restarts"] == 1
        inc = summary["incidents"][0]
        assert inc["kind"] == "host_death" and inc["host"] == 1
        assert inc["recovery_s"] is not None
        events = [r["event"] for r in rec.records]
        assert "host_death" in events and "world_restart" in events
        death = next(r for r in rec.records
                     if r["event"] == "host_death")
        assert death["host"] == 1 and death["rank"] == 1

    def test_hang_detection_kills_and_restarts(self, tmp_path):
        rec = RingBufferRecorder()
        sup = Supervisor(
            _script_host(tmp_path, [BEAT_AND_HANG, BEAT_AND_EXIT0]),
            2, heartbeat_dir=str(tmp_path / "hb"),
            heartbeat_timeout_s=0.4, poll_s=0.02,
            max_restarts=2, sink=rec)
        t0 = time.monotonic()
        summary = sup.run()
        assert summary["ok"] and summary["restarts"] == 1
        assert summary["incidents"][0]["kind"] == "host_hang"
        assert summary["incidents"][0]["host"] == 1
        assert time.monotonic() - t0 < 30.0  # hung host was KILLED

    def test_max_restarts_raises_world_failed(self, tmp_path):
        sup = Supervisor(
            _script_host(tmp_path, [BEAT_AND_DIE]),
            2, heartbeat_dir=str(tmp_path / "hb"),
            max_restarts=1)
        with pytest.raises(WorldFailedError, match="host 1"):
            sup.run()
        assert sup.restarts == 2
        assert len(sup.incidents) == 2

    def test_reshape_on_restart(self, tmp_path):
        sup = Supervisor(
            _script_host(tmp_path, [BEAT_AND_DIE, BEAT_AND_EXIT0]),
            4, heartbeat_dir=str(tmp_path / "hb"), max_restarts=2,
            on_restart=lambda incarnation, world: 2)
        summary = sup.run()
        assert summary["ok"]
        assert summary["world_history"] == [4, 2]


# ---------------------------------------------------------------------------
# satellite: attributable hang events
# ---------------------------------------------------------------------------
class TestWatchdogContext:
    def test_ctor_context_tags_hang_events(self):
        rec = RingBufferRecorder()
        with HangWatchdog(timeout_s=0.1, poll_s=0.02, sink=rec,
                          context={"host": 3, "rank": 3}) as wd:
            with pytest.raises(HangError):
                wd.wait(threading.Event(), "supervised barrier")
        (hang,) = [r for r in rec.records if r["event"] == "hang"]
        assert hang["host"] == 3 and hang["rank"] == 3

    def test_per_call_context_wins(self):
        rec = RingBufferRecorder()
        with HangWatchdog(timeout_s=0.1, poll_s=0.02, sink=rec,
                          context={"host": 3, "step": 1}) as wd:
            with pytest.raises(HangError):
                wd.wait(threading.Event(), "supervised barrier",
                        context={"step": 9})
        (hang,) = [r for r in rec.records if r["event"] == "hang"]
        assert hang["host"] == 3 and hang["step"] == 9


# ---------------------------------------------------------------------------
# CLI + bench wiring
# ---------------------------------------------------------------------------
class TestSupervisorCLI:
    def test_parse_chaos_and_reshape(self):
        from tools import elastic_supervisor as es

        assert es.parse_chaos(["0:2:kill@7", "1:0:wedge@3:9"]) == {
            (0, 2): "kill@7", (1, 0): "wedge@3:9"}
        assert es.parse_reshape(["1:2", "3:1"]) == {1: 2, 3: 1}
        with pytest.raises(SystemExit):
            es.parse_chaos(["bogus"])
        with pytest.raises(SystemExit):
            es.parse_reshape(["bogus"])

    def test_host_program_exists(self):
        from tools import elastic_supervisor as es

        assert os.path.exists(es.HOST_PROGRAM)


class TestBenchWiring:
    def test_compare_bench_extracts_elastic_legs(self):
        from tools import compare_bench

        names = [m[0] for m in compare_bench.METRICS]
        assert "elastic_mttr_s" in names
        assert "elastic_save_overhead_pct" in names
        assert "elastic_mttr_s" in compare_bench.ABS_TOLERANCE
        legs = compare_bench.extract_legs(
            {"elastic_mttr": {"mttr_s": 3.2,
                              "save_overhead_pct": 12.5}})
        assert legs["elastic_mttr_s"] == -3.2  # lower-is-better
        assert legs["elastic_save_overhead_pct"] == -12.5

    def test_mttr_regression_gated_absolutely(self):
        from tools import compare_bench

        base = {"elastic_mttr": {"mttr_s": 3.0}}
        ok = {"elastic_mttr": {"mttr_s": 6.0}}  # within 5s abs tol
        cmp = compare_bench.compare(base, ok, threshold=0.05)
        assert not [r for r in cmp["regressions"]
                    if r["leg"] == "elastic_mttr_s"]
        bad = {"elastic_mttr": {"mttr_s": 20.0}}
        cmp = compare_bench.compare(base, bad, threshold=0.05)
        assert [r for r in cmp["regressions"]
                if r["leg"] == "elastic_mttr_s"]

    def test_cpu_smoke_artifact_committed(self):
        path = REPO / "bench_artifacts" / "elastic_mttr_cpu_smoke.json"
        with open(path) as f:
            smoke = json.load(f)
        leg = smoke["elastic_mttr"]
        assert leg["records_match"] is True
        assert leg["restarts"] >= 1
        assert leg["mttr_s"] > 0
        assert "save_overhead_pct" in leg

    def test_resilience_check_gained_elastic_legs(self):
        from tools import resilience_check

        assert "elastic_resume" in resilience_check.CHECKS
        assert "host_kill" in resilience_check.CHECKS


# ---------------------------------------------------------------------------
# the full chaos trace (slow tier; tier-1 coverage rides the CLI legs)
# ---------------------------------------------------------------------------
HOST_PROGRAM = str(REPO / "apex_tpu" / "resilience" / "_elastic_host.py")


def test_chaos_trace_kills_reshapes_byte_exact(tmp_path):
    """The acceptance chaos proof: a supervised 4-fake-host run suffers
    a SIGKILL mid-``.part``-write, restarts, RESHAPES to 2 hosts,
    suffers a heartbeat wedge (hang) and a SIGKILL mid-barrier, and
    still lands loss records byte-identical to an uninterrupted run —
    no markerless step is ever restored (a torn restore would diverge
    the records)."""
    steps, save_every = 14, 2
    run = tmp_path
    ckpt = str(run / "ckpt")
    losses = str(run / "losses.txt")
    chaos_by = {  # (incarnation, host) -> spec
        (0, 2): "kill_write@5",   # SIGKILL mid-.part write
        (1, 1): "wedge@8",        # heartbeat wedge -> host_hang
        (2, 0): "kill_barrier@10",  # SIGKILL mid commit barrier
    }

    def build_cmd(host, world, incarnation):
        return [sys.executable, HOST_PROGRAM,
                "--host", host, "--world", world, "--steps", steps,
                "--root", ckpt, "--losses", losses,
                "--heartbeat-dir", str(run / "hb"),
                "--save-every", save_every, "--barrier-timeout", 30,
                "--step-sleep", 0.1]

    def host_env(host, world, incarnation):
        env = {"PYTHONPATH": str(REPO) + os.pathsep
               + os.environ.get("PYTHONPATH", ""),
               "JAX_PLATFORMS": "cpu"}
        spec = chaos_by.get((incarnation, host))
        if spec:
            env["APEX_TPU_ELASTIC_CHAOS"] = spec
        return env

    rec = RingBufferRecorder()
    # heartbeat timeout must clear the first step's COMPILE window (a
    # cold host legitimately goes several seconds between its startup
    # beat and its first post-step beat) while staying far under the
    # wedge's stall — 10s does both on the CPU harness
    sup = Supervisor(
        build_cmd, 4, heartbeat_dir=str(run / "hb"),
        heartbeat_timeout_s=10.0, startup_timeout_s=120.0,
        poll_s=0.05, max_restarts=4,
        sink=rec, host_env=host_env,
        on_restart=lambda incarnation, world: 2 if incarnation == 0
        else world)
    summary = sup.run()
    assert summary["ok"], summary
    assert summary["restarts"] == 3
    assert summary["world_history"] == [4, 2, 2, 2]
    kinds = [i["kind"] for i in summary["incidents"]]
    assert kinds == ["host_death", "host_hang", "host_death"]

    records = {}
    with open(losses) as f:
        for line in f:
            if line.startswith("S "):
                _, s, hexval = line.split()
                step = int(s)
                if step in records:  # replays must also be identical
                    assert records[step] == hexval, \
                        f"replay diverged at step {step}"
                records[step] = hexval
    ref, _ = reference_records(2, steps)
    assert records == ref  # byte-exact final loss records
