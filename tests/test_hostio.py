"""Native hostio engine (apex_tpu/csrc/hostio.cpp): multithreaded
tensor<->file IO and bucket pack/unpack, vs the pure-Python fallback.

The TPU-native layer for the reference's host/native runtime components:
``csrc/gpu_direct_storage/gds.cpp`` (direct tensor<->file IO) and
``csrc/flatten_unflatten.cpp`` (apex_C bucket packing)."""
import numpy as np
import pytest

from apex_tpu.ops import hostio


def _arrays(seed=0):
    rng = np.random.default_rng(seed)
    return [
        rng.standard_normal((17, 33)).astype(np.float32),
        rng.integers(0, 255, (5,), dtype=np.uint8),
        rng.standard_normal((128, 64)).astype(np.float32),
        np.asarray(3.25, np.float64).reshape(()),
    ]


def test_native_library_builds():
    """g++ is baked into the image: the native path must be live (the
    fallback exists for sandboxed consumers, not for CI)."""
    assert hostio.native_available()


def test_write_read_roundtrip(tmp_path):
    arrs = _arrays(1)
    path = str(tmp_path / "blob.bin")
    offsets = hostio.write_arrays(path, arrs, threads=4)
    back = hostio.read_arrays(
        path, [(a.shape, a.dtype) for a in arrs], offsets, threads=4
    )
    for a, b in zip(arrs, back):
        np.testing.assert_array_equal(a, b)
    assert hostio.file_size(path) >= max(
        o + a.nbytes for o, a in zip(offsets, arrs)
    )


def test_explicit_offsets_and_overwrite(tmp_path):
    path = str(tmp_path / "slots.bin")
    a = np.arange(16, dtype=np.int64)
    b = np.arange(16, 32, dtype=np.int64)
    hostio.write_arrays(path, [a, b], offsets=[0, 1024])
    hostio.write_arrays(path, [b], offsets=[0])  # overwrite slot 0
    (r0,) = hostio.read_arrays(path, [(a.shape, a.dtype)], [0])
    (r1,) = hostio.read_arrays(path, [(b.shape, b.dtype)], [1024])
    np.testing.assert_array_equal(r0, b)
    np.testing.assert_array_equal(r1, b)


def test_flatten_unflatten_roundtrip():
    arrs = _arrays(2)
    arena, offsets = hostio.flatten(arrs, threads=4)
    assert arena.dtype == np.uint8
    assert all(o % 64 == 0 for o in offsets)  # aligned layout
    back = hostio.unflatten(arena, arrs, offsets, threads=4)
    for a, b in zip(arrs, back):
        np.testing.assert_array_equal(a, b)


def test_fallback_matches_native(tmp_path, monkeypatch):
    """The pure-Python fallback must produce byte-identical files and
    round-trips."""
    arrs = _arrays(3)
    p_native = str(tmp_path / "native.bin")
    hostio.write_arrays(p_native, arrs)
    arena_native, off = hostio.flatten(arrs)

    monkeypatch.setattr(hostio, "load_hostio", lambda: None)
    p_py = str(tmp_path / "py.bin")
    offsets = hostio.write_arrays(p_py, arrs)
    with open(p_native, "rb") as f1, open(p_py, "rb") as f2:
        assert f1.read() == f2.read()
    back = hostio.read_arrays(
        p_py, [(a.shape, a.dtype) for a in arrs], offsets
    )
    for a, b in zip(arrs, back):
        np.testing.assert_array_equal(a, b)
    arena_py, _ = hostio.flatten(arrs)
    np.testing.assert_array_equal(arena_native, arena_py)
    back2 = hostio.unflatten(arena_py, arrs, off)
    for a, b in zip(arrs, back2):
        np.testing.assert_array_equal(a, b)


def test_read_missing_file_raises(tmp_path):
    with pytest.raises(OSError):
        hostio.read_arrays(
            str(tmp_path / "nope.bin"), [((4,), np.float32)], [0]
        )


def test_read_past_eof_raises(tmp_path):
    path = str(tmp_path / "short.bin")
    hostio.write_arrays(path, [np.zeros(4, np.float32)])
    with pytest.raises((OSError, EOFError)):
        hostio.read_arrays(path, [((1024,), np.float32)], [0])


def test_gdsfile_rides_hostio(tmp_path):
    """GDSFile keeps its raw-bytes format over the native engine."""
    import jax.numpy as jnp

    from apex_tpu.contrib.gpu_direct_storage import GDSFile

    x = jnp.arange(1024, dtype=jnp.float32).reshape(32, 32)
    y = jnp.ones((8,), jnp.int32) * 7
    path = str(tmp_path / "gds.bin")
    with GDSFile(path, "w") as f:
        f.save_data(x)
        f.save_data(y)
    with GDSFile(path, "r") as f:
        rx = f.load_data(jnp.zeros_like(x))
        ry = f.load_data(jnp.zeros_like(y))
    assert jnp.array_equal(rx, x) and jnp.array_equal(ry, y)
    # format check: raw little-endian bytes back-to-back (reference parity)
    with open(path, "rb") as fh:
        raw = fh.read()
    assert raw[: x.nbytes] == np.asarray(x).tobytes()
    assert raw[x.nbytes : x.nbytes + y.nbytes] == np.asarray(y).tobytes()


def test_offsets_count_validation(tmp_path):
    path = str(tmp_path / "v.bin")
    a = np.zeros(4, np.float32)
    with pytest.raises(ValueError, match="offsets"):
        hostio.write_arrays(path, [a, a], offsets=[0])
    hostio.write_arrays(path, [a])
    with pytest.raises(ValueError, match="offsets"):
        hostio.read_arrays(path, [(a.shape, a.dtype)] * 2, [0])
    arena, offs = hostio.flatten([a, a])
    with pytest.raises(ValueError, match="offsets"):
        hostio.unflatten(arena, [a, a], offs[:1])


def test_fd_based_io(tmp_path):
    import os

    path = str(tmp_path / "fd.bin")
    arrs = _arrays(4)
    fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
    try:
        offsets = hostio.write_arrays(fd, arrs)
        back = hostio.read_arrays(
            fd, [(a.shape, a.dtype) for a in arrs], offsets
        )
    finally:
        os.close(fd)
    for a, b in zip(arrs, back):
        np.testing.assert_array_equal(a, b)


def test_gdsfile_use_after_close_raises(tmp_path):
    import jax.numpy as jnp

    from apex_tpu.contrib.gpu_direct_storage.gds import _GDSFile

    path = str(tmp_path / "closed.bin")
    f = _GDSFile(path, "w")
    f.save_data(jnp.ones(4))
    f.close()
    with pytest.raises(ValueError, match="closed"):
        f.save_data(jnp.ones(4))
    g = _GDSFile(path, "r")
    g.close()
    with pytest.raises(ValueError, match="closed"):
        g.load_data(jnp.zeros(4))


def test_unflatten_noncontiguous_arena():
    a = np.arange(64, dtype=np.float32)
    arena, offs = hostio.flatten([a])
    # a strided f32 view of the same bytes must be accepted
    arena_f32 = arena.view(np.float32)
    wide = np.zeros((arena_f32.size, 2), np.float32)
    wide[:, 0] = arena_f32
    (back,) = hostio.unflatten(wide[:, 0], [a], offs)
    np.testing.assert_array_equal(a, back)

def test_unflatten_out_of_bounds_offset_raises():
    a = np.arange(64, dtype=np.float32)
    arena, offs = hostio.flatten([a])
    with pytest.raises(ValueError, match="out of bounds"):
        hostio.unflatten(arena, [a], [arena.nbytes - 4])
    with pytest.raises(ValueError, match="out of bounds"):
        hostio.unflatten(arena, [a], [-8])
