"""Tests for Megatron-style batch samplers (apex_tpu.transformer._data).

Mirrors the semantics of the reference `apex/transformer/_data/_batchsampler.py`:
rank-sliced sequential batching with exact resume, and per-epoch deterministic
shuffling with mid-epoch resume.
"""
import pytest

from apex_tpu.transformer._data import (
    MegatronPretrainingRandomSampler,
    MegatronPretrainingSampler,
)


class TestMegatronPretrainingSampler:
    def test_ranks_partition_global_batch(self):
        # dp=2, local=4: ranks see disjoint halves of each global batch of 8.
        per_rank = []
        for rank in range(2):
            s = MegatronPretrainingSampler(
                total_samples=32,
                consumed_samples=0,
                local_minibatch_size=4,
                data_parallel_rank=rank,
                data_parallel_size=2,
            )
            per_rank.append(list(s))
        assert per_rank[0][0] == [0, 1, 2, 3]
        assert per_rank[1][0] == [4, 5, 6, 7]
        # Together the ranks cover every sample exactly once.
        flat = sorted(i for rank_batches in per_rank for b in rank_batches for i in b)
        assert flat == list(range(32))

    def test_resume_continues_where_left_off(self):
        full = list(
            MegatronPretrainingSampler(
                total_samples=64,
                consumed_samples=0,
                local_minibatch_size=4,
                data_parallel_rank=0,
                data_parallel_size=2,
            )
        )
        resumed = list(
            MegatronPretrainingSampler(
                total_samples=64,
                consumed_samples=24,  # 3 global batches of 8 consumed
                local_minibatch_size=4,
                data_parallel_rank=0,
                data_parallel_size=2,
            )
        )
        assert resumed == full[3:]

    def test_drop_last(self):
        s = MegatronPretrainingSampler(
            total_samples=10,
            consumed_samples=0,
            local_minibatch_size=4,
            data_parallel_rank=0,
            data_parallel_size=1,
            drop_last=True,
        )
        batches = list(s)
        assert batches == [[0, 1, 2, 3], [4, 5, 6, 7]]
        s2 = MegatronPretrainingSampler(
            total_samples=10,
            consumed_samples=0,
            local_minibatch_size=4,
            data_parallel_rank=0,
            data_parallel_size=1,
            drop_last=False,
        )
        assert list(s2)[-1] == [8, 9]

    def test_partial_tail_split_across_ranks(self):
        # 10 samples, dp=2, local=4: global batch of 8, then tail [8, 9]
        # which must be split one sample per rank (not rank-sliced to empty)
        tails = []
        for rank in range(2):
            s = MegatronPretrainingSampler(
                total_samples=10,
                consumed_samples=0,
                local_minibatch_size=4,
                data_parallel_rank=rank,
                data_parallel_size=2,
                drop_last=False,
            )
            tails.append(list(s)[-1])
        assert tails == [[8], [9]]

    def test_rampup_batch_size_setter(self):
        s = MegatronPretrainingSampler(
            total_samples=32,
            consumed_samples=0,
            local_minibatch_size=2,
            data_parallel_rank=0,
            data_parallel_size=2,
        )
        s.local_minibatch_size = 4
        assert s.local_minibatch_size == 4
        assert s.local_minibatch_times_data_parallel_size == 8
        assert list(s)[0] == [0, 1, 2, 3]

    def test_validation(self):
        with pytest.raises(RuntimeError):
            MegatronPretrainingSampler(0, 0, 4, 0, 1)
        with pytest.raises(RuntimeError):
            MegatronPretrainingSampler(8, 8, 4, 0, 1)
        with pytest.raises(RuntimeError):
            MegatronPretrainingSampler(8, 0, 4, 2, 2)


class TestMegatronPretrainingRandomSampler:
    def _make(self, rank, consumed=0, total=64, local=4, dp=2):
        return MegatronPretrainingRandomSampler(
            total_samples=total,
            consumed_samples=consumed,
            local_minibatch_size=local,
            data_parallel_rank=rank,
            data_parallel_size=dp,
        )

    def test_epoch_deterministic_and_rank_disjoint(self):
        a = list(self._make(rank=0))
        b = list(self._make(rank=0))
        assert a == b  # same epoch seed → same permutation
        r0 = {i for batch in self._make(rank=0) for i in batch}
        r1 = {i for batch in self._make(rank=1) for i in batch}
        assert not (r0 & r1)  # contiguous rank buckets are disjoint
        assert r0 | r1 == set(range(64))

    def test_resume_mid_epoch(self):
        full = list(self._make(rank=0, consumed=0))
        # consumed=16 → 2 global batches of 8 done → skip 2 local batches
        resumed = list(self._make(rank=0, consumed=16))
        assert resumed == full[2:]

    def test_new_epoch_reshuffles(self):
        epoch0 = list(self._make(rank=0, consumed=0))
        epoch1 = list(self._make(rank=0, consumed=64))
        assert epoch0 != epoch1
        assert {i for b in epoch0 for i in b} == {i for b in epoch1 for i in b}

    def test_consumed_samples_tracking(self):
        s = self._make(rank=0, consumed=0)
        n = len(list(s))
        assert s.consumed_samples == n * 8  # 8 = local*dp consumed per yield

    def test_rampup_recomputes_tail(self):
        s = self._make(rank=0, total=64, local=4, dp=2)
        assert s.last_batch_size == 0
        s.local_minibatch_size = 3
        assert s.last_batch_size == 64 % 6
        # resume at end of the (new) epoch still iterates (epoch 1 starts)
        s2 = self._make(rank=0, total=64, local=4, dp=2, consumed=60)
        s2.local_minibatch_size = 3
        assert len(list(s2)) > 0

    def test_validation(self):
        with pytest.raises(ValueError):
            MegatronPretrainingRandomSampler(0, 0, 4, 0, 1)
        with pytest.raises(ValueError):
            MegatronPretrainingRandomSampler(8, 0, 0, 0, 1)
        with pytest.raises(ValueError):
            MegatronPretrainingRandomSampler(8, 0, 4, 2, 2)
        with pytest.raises(ValueError):
            # less than one global batch: nothing to shuffle
            MegatronPretrainingRandomSampler(6, 0, 4, 0, 2)
