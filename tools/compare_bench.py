"""Diff bench results and flag per-leg regressions.

``bench.py`` prints one JSON object per run; the driver archives them as
``BENCH_r0N.json`` capture files (``{"n", "cmd", "rc", "tail",
"parsed"}`` — ``parsed`` is the bench dict, or null when the captured
tail was truncated, in which case the tail itself is re-parsed here).
This tool compares two results — or a whole trajectory — leg by leg and
exits non-zero when any leg regressed beyond the threshold, so CI
catches both performance regressions and silent bench schema drift
(a leg disappearing from the output is reported, not ignored).

Usage::

    python tools/compare_bench.py BASE.json NEW.json [--threshold 0.05]
    python tools/compare_bench.py --trajectory BENCH_r0*.json

Legs are extracted by dotted path; every metric is oriented so HIGHER is
better (``step_ms``-style values are inverted at extraction).

Beyond scalar legs, the op-breakdown *category* table
(``op_breakdown.categories``) is diffed in percentage points of device
time: an overhead category (``fusion(elementwise)``, ``data-movement``,
...) growing its share by more than ``OP_CATEGORY_THRESHOLD_PP`` is
flagged as a regression the same way a throughput leg is — the shape of
the profile is an invariant ISSUE-9 paid for.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

# (leg name, dotted path into the bench dict, higher_is_better)
METRICS: Tuple[Tuple[str, str, bool], ...] = (
    ("gpt_tokens_per_sec", "value", True),
    ("gpt_true_mfu", "true_mfu", True),
    ("gpt_vs_xla_attention", "vs_xla_attention", True),
    ("bert_tokens_per_sec", "bert_large_lamb.tokens_per_sec", True),
    ("resnet_images_per_sec", "resnet50_o2.images_per_sec", True),
    ("packed_opt_gbps", "packed_optimizer.gbps_achieved", True),
    ("packed_opt_vs_pytree", "packed_optimizer.vs_pytree", True),
    ("fp8_gemm_vs_bf16", "fp8_e4m3_gemm_vs_bf16", True),
    ("fp8_model_tokens_per_sec", "gpt2_345m_fp8.tokens_per_sec", True),
    ("serving_tokens_per_sec", "serving_throughput.tokens_per_sec", True),
    ("serving_p50_ms", "serving_throughput.p50_ms", False),
    ("serving_p99_ms", "serving_throughput.p99_ms", False),
    ("serving_occupancy", "serving_throughput.occupancy", True),
    ("serving_goodput", "serving_overload.goodput_tokens_per_sec", True),
    ("serving_slo_attainment", "serving_overload.slo_attainment", True),
    ("prefix_ttft_p99_ms", "prefix_reuse.ttft_p99_ms", False),
    ("prefix_hit_rate", "prefix_reuse.hit_rate", True),
    ("prefix_flops_saved", "prefix_reuse.prefill_flops_saved", True),
    ("serving_overload_ttft_p99_ms", "serving_overload.ttft_p99_ms", False),
    ("spec_goodput", "spec_decode.goodput_tokens_per_sec", True),
    ("spec_accept_rate", "spec_decode.accept_rate", True),
    ("spec_tokens_per_step", "spec_decode.tokens_per_step", True),
    ("fleet_slo_attainment", "serving_fleet.slo_attainment", True),
    ("fleet_goodput", "serving_fleet.goodput_tokens_per_sec", True),
    ("fleet_requests_lost", "serving_fleet.requests_lost", False),
    ("fleet_ttft_p99_ms", "serving_fleet.ttft_p99_ms", False),
    # ISSUE-18 fleet health plane: the alert→degrade closed loop on the
    # ramping-overload A/B — the guarded arm's attainment must not
    # regress, and the burn-rate alert must keep firing early (steps
    # from ramp start to the first firing slo_attainment alert)
    ("slo_guard_attainment", "serving_slo_guard.guarded_attainment",
     True),
    ("alert_detection_steps", "serving_slo_guard.alert_detection_steps",
     False),
    # ISSUE-16 tensor-parallel serving: the TP arm of the equal-chip
    # DP-vs-TP A/B — aggregate decode throughput and p99 request
    # latency of the shard_mapped engine must not regress
    ("serving_tp_tokens_per_sec", "serving_tp.tokens_per_sec", True),
    ("serving_tp_p99_ms", "serving_tp.p99_ms", False),
    ("telemetry_overhead_pct", "telemetry_overhead.overhead_pct", False),
    ("resilience_overhead_pct", "resilience_overhead.overhead_pct", False),
    # ISSUE-17 request tracing: the serving A/B pricing span emission +
    # the attribution ledger + the flight ring; the <=1% claim is an
    # absolute 1pp gate like the other overhead legs
    ("trace_overhead_pct", "trace_overhead.overhead_pct", False),
    # ISSUE-14 flat-buffer gradient lifecycle A/B: the flat leg must stay
    # faster than the per-leaf historical step, and the XLA-cost-model
    # ratios must stay below parity (bytes_ratio < 1.0 is the acceptance
    # number; a rise back toward 1 is a regression even if wall time
    # noise hides it)
    ("grad_lifecycle_speedup", "grad_lifecycle.speedup", True),
    ("grad_lifecycle_bytes_ratio", "grad_lifecycle.bytes_ratio", False),
    ("grad_lifecycle_steps_per_sec",
     "grad_lifecycle.flat.steps_per_sec", True),
    # ISSUE-15 elastic training service: time-to-resume after a host
    # kill (restart + restore + rendezvous) and the per-step cost of
    # the armed two-phase save/commit machinery
    ("elastic_mttr_s", "elastic_mttr.mttr_s", False),
    ("elastic_save_overhead_pct",
     "elastic_mttr.save_overhead_pct", False),
    # ISSUE-20 real-process fleet: zero-loss failover across actual
    # SIGKILLed worker subprocesses — requests_lost is gated absolutely
    # at 0 (one lost request is a regression), MTTR covers subprocess
    # relaunch + jax import + engine rebuild, goodput/attainment must
    # not regress under the injected death+hang
    ("proc_fleet_requests_lost", "serving_proc_fleet.requests_lost",
     False),
    ("proc_fleet_mttr_s", "serving_proc_fleet.mttr_s", False),
    ("proc_fleet_goodput",
     "serving_proc_fleet.goodput_tokens_per_sec", True),
    ("proc_fleet_slo_attainment",
     "serving_proc_fleet.slo_attainment", True),
)

# legs whose expected value is ~0, where a relative threshold would turn
# sub-point noise into a "regression": compared with an ABSOLUTE
# tolerance (same units as the metric) instead of a fraction of |base|
ABS_TOLERANCE = {
    "telemetry_overhead_pct": 1.0,  # percentage points (the <=1% claim)
    "resilience_overhead_pct": 1.0,  # ditto (docs/resilience.md)
    "trace_overhead_pct": 1.0,  # ditto (docs/observability.md tracing)
    # the zero-loss failover contract: the expected value is exactly 0,
    # so ONE lost request must regress — a relative threshold over a
    # zero base would wave any count through (or inf-flag noise)
    "fleet_requests_lost": 0.5,  # requests (docs/serving.md fleet)
    # CPU MTTR is dominated by interpreter+jax startup (seconds of
    # noise on a loaded host); the overhead pct carries the tensorstore
    # per-save commit latency against a ~50ms simulated step, which
    # swings with host load — gate drift, not noise
    "elastic_mttr_s": 5.0,  # seconds (docs/resilience.md elastic)
    "elastic_save_overhead_pct": 12.0,  # percentage points
    # the process fleet's zero-loss contract, same shape as
    # fleet_requests_lost; MTTR = SIGKILL detect -> restarted worker's
    # ready frame, dominated by interpreter+jax startup on CPU
    "proc_fleet_requests_lost": 0.5,  # requests (docs/serving.md)
    "proc_fleet_mttr_s": 10.0,  # seconds (subprocess relaunch noise)
    # detection is denominated in fleet steps and the expected value is
    # a couple dozen; a relative threshold over a small base would flag
    # single-boundary jitter in when the window fills
    "alert_detection_steps": 16.0,  # fleet steps (docs/observability.md)
}

# op-breakdown category diffing (ISSUE-9): a run whose *shape* of device
# time shifted back toward the memory-bound buckets is a regression even
# when throughput noise hides it. Only the overhead categories are gated
# on GROWTH — shares sum to 100, so winning back elementwise time
# necessarily grows the matmul/attention shares (that is the point, not
# a regression).
OP_CATEGORY_THRESHOLD_PP = 2.0  # percentage points of device time
OVERHEAD_CATEGORIES = (
    "fusion(elementwise)",
    "fusion(unattributed)",
    "data-movement",
    "other",
)


def op_category_pcts(bench: Optional[dict]) -> Optional[Dict[str, float]]:
    """``{category: pct-of-device-time}`` from a bench capture's
    ``op_breakdown.categories`` table; None when the capture has no
    breakdown (fast mode, pre-telemetry rounds)."""
    ob = (bench or {}).get("op_breakdown")
    cats = (ob or {}).get("categories") if isinstance(ob, dict) else None
    if not isinstance(cats, dict):
        return None
    out: Dict[str, float] = {}
    for name, entry in cats.items():
        pct = entry.get("pct") if isinstance(entry, dict) else entry
        if isinstance(pct, (int, float)) and not isinstance(pct, bool):
            out[name] = float(pct)
    return out or None


def category_shift(base_pcts: Dict[str, float],
                   new_pcts: Dict[str, float]) -> List[dict]:
    """Per-category pct-point deltas, largest growth first. Categories
    present on one side only count as 0 on the other (a category
    appearing/disappearing IS a shift)."""
    shifts = []
    for cat in sorted(set(base_pcts) | set(new_pcts)):
        b = base_pcts.get(cat, 0.0)
        n = new_pcts.get(cat, 0.0)
        shifts.append({"category": cat, "base_pct": round(b, 2),
                       "new_pct": round(n, 2),
                       "delta_pp": round(n - b, 2)})
    shifts.sort(key=lambda s: -s["delta_pp"])
    return shifts


# the latency-attribution partition (must mirror
# apex_tpu.telemetry.ATTR_TERMS — duplicated here so the gate works on
# archived captures without importing the package)
ATTR_TERMS = ("queue_wait", "cached_skip", "prefill_compute", "decode",
              "replay", "migration")

# legs that carry an ``attribution`` block (ISSUE-17); absent blocks are
# fine (old captures, tracing off), malformed ones are schema drift
ATTRIBUTED_LEGS = ("serving_throughput", "serving_fleet")


def attribution_problems(bench: Optional[dict]) -> List[str]:
    """Schema-validate the ``attribution`` summary carried by the
    serving legs: the full term set, per-term percentile dicts, and the
    exact-sum identity (``ttft_sum_rel_err_max`` <= 1%) — the contract
    docs/observability.md promises downstream dashboards."""
    problems: List[str] = []
    for leg in ATTRIBUTED_LEGS:
        att = _dig(bench or {}, f"{leg}.attribution")
        if att is None:
            continue
        if not isinstance(att, dict):
            problems.append(f"{leg}.attribution: not a dict")
            continue
        if tuple(att.get("terms") or ()) != ATTR_TERMS:
            problems.append(
                f"{leg}.attribution.terms != {list(ATTR_TERMS)}")
        for block in ("ttft_ms", "e2e_ms"):
            d = att.get(block)
            if not isinstance(d, dict) or set(d) != set(ATTR_TERMS):
                problems.append(
                    f"{leg}.attribution.{block}: missing/extra terms")
                continue
            for t, p in d.items():
                if not isinstance(p, dict) or not {
                        "p50", "p90", "p99"} <= set(p):
                    problems.append(
                        f"{leg}.attribution.{block}.{t}: "
                        "missing percentiles")
                    break
        err = att.get("ttft_sum_rel_err_max")
        if not isinstance(err, (int, float)) or err > 0.01:
            problems.append(
                f"{leg}.attribution.ttft_sum_rel_err_max={err!r} "
                "(terms must sum to measured TTFT within 1%)")
    return problems


# legs that carry a static per-program ``comm_volume`` report
# ({program: {collective: {count, bytes, axes}}} — see
# apex_tpu.analysis.comm_volume); the gpt headline's report rides inside
# the ``audit`` block
COMM_LEGS = ("serving_tp",)


def comm_reports(bench: Optional[dict]) -> Dict[str, dict]:
    """Every static comm report a capture carries, flattened to
    ``{"leg.program": {collective: {count, bytes, ...}}}``. Empty for
    captures that predate the comm model."""
    out: Dict[str, dict] = {}
    for leg in COMM_LEGS:
        cv = _dig(bench or {}, f"{leg}.comm_volume")
        if isinstance(cv, dict):
            for prog, colls in cv.items():
                if isinstance(colls, dict):
                    out[f"{leg}.{prog}"] = colls
    cv = _dig(bench or {}, "audit.comm_volume")
    if isinstance(cv, dict) and cv:
        out["gpt_headline"] = cv
    return out


def _dig(d: dict, path: str):
    cur = d
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def load_bench(path: str) -> Optional[dict]:
    """Load a bench result: a raw bench dict, or a driver capture whose
    ``parsed`` (or, failing that, last ``tail`` line) holds it. ``None``
    when nothing parseable is found (truncated capture)."""
    with open(path) as f:
        d = json.load(f)
    if "metric" in d or "value" in d:
        return d
    if isinstance(d.get("parsed"), dict):
        return d["parsed"]
    tail = d.get("tail")
    if isinstance(tail, str):
        for line in reversed(tail.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    return json.loads(line)
                except json.JSONDecodeError:
                    continue
    return None


def extract_legs(bench: dict) -> Dict[str, float]:
    """Numeric per-leg values, oriented so higher is better."""
    out: Dict[str, float] = {}
    for name, path, higher in METRICS:
        v = _dig(bench, path)
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        out[name] = float(v) if higher else -float(v)
    return out


def audit_status(bench: Optional[dict]) -> Optional[dict]:
    """The static-audit summary a bench capture carries (``"audit"`` in
    bench.py's output: the headline step traced and checked by
    ``apex_tpu.analysis`` — see tools/static_audit.py). ``None`` when
    the capture predates the auditor or skipped it (BENCH_AUDIT=0)."""
    a = (bench or {}).get("audit")
    if not isinstance(a, dict):
        return None
    return {"ok": a.get("ok"),
            "error": a.get("error"), "warning": a.get("warning"),
            "codes": a.get("codes")}


def compare(base: dict, new: dict, threshold: float = 0.05) -> dict:
    """Leg-by-leg comparison: a leg regresses when it is worse than base
    by more than ``threshold`` (fractional). Legs present on only one
    side are listed separately — schema drift must be visible."""
    a, b = extract_legs(base), extract_legs(new)
    higher = {name: h for name, _, h in METRICS}
    regressions: List[dict] = []
    improvements: List[dict] = []
    unchanged: List[str] = []
    for leg in sorted(set(a) & set(b)):
        va, vb = a[leg], b[leg]
        # report the ORIGINAL metric values (un-orient the inverted
        # lower-is-better legs) so e.g. a negative overhead_pct keeps
        # its sign in the triage output
        sign = 1.0 if higher[leg] else -1.0
        entry = {"leg": leg, "base": sign * va, "new": sign * vb}
        abs_tol = ABS_TOLERANCE.get(leg)
        if abs_tol is not None:
            # near-zero metric: absolute change, reported in the
            # original (un-oriented) units to match base/new
            delta = vb - va
            entry["delta_abs"] = round(sign * delta, 4)
            worse, better = delta < -abs_tol, delta > abs_tol
        else:
            # oriented values can be negative; ratio against magnitude
            # keeps the sign convention
            if va == 0:
                delta = (0.0 if vb == 0
                         else float("inf") * (1 if vb > va else -1))
            else:
                delta = (vb - va) / abs(va)
            entry["delta_pct"] = round(100.0 * delta, 2)
            worse, better = delta < -threshold, delta > threshold
        if worse:
            regressions.append(entry)
        elif better:
            improvements.append(entry)
        else:
            unchanged.append(leg)
    # op-breakdown category shape: an overhead category (elementwise
    # fusions, data movement) that grew its share of device time by more
    # than the pp threshold regressed — flagged exactly like a
    # throughput leg, because that is how the ISSUE-9 fused-tail wins
    # erode (silently, behind stable tokens/sec on a different chip)
    cat_report = None
    bp, np_ = op_category_pcts(base), op_category_pcts(new)
    if bp is not None and np_ is not None:
        shifts = category_shift(bp, np_)
        cat_report = {"threshold_pp": OP_CATEGORY_THRESHOLD_PP,
                      "shift": shifts}
        for s in shifts:
            if (s["category"] in OVERHEAD_CATEGORIES
                    and s["delta_pp"] > OP_CATEGORY_THRESHOLD_PP):
                regressions.append({
                    "leg": f"op_category:{s['category']}",
                    "base": s["base_pct"], "new": s["new_pct"],
                    "delta_pp": s["delta_pp"],
                })
    # static-audit status alongside the perf legs: a capture whose
    # headline step STOPPED auditing clean is a regression even when
    # every throughput number held (the invariant broke, the cost shows
    # up later / on different hardware)
    ab, an = audit_status(base), audit_status(new)
    if an is not None and an.get("ok") is False and (
            ab is None or ab.get("ok") is not False):
        regressions.append({
            "leg": "static_audit",
            "base": None if ab is None else ab.get("ok"),
            "new": False,
            "codes": an.get("codes"),
        })
    # static comm budgets (ISSUE-19): for every program both captures
    # report, the per-collective eqn COUNT is an exact pin (a collective
    # appearing unbudgeted is new communication; one vanishing is a lost
    # reduction — a numerics hazard, not a perf win), and the static
    # BYTES may not grow past the threshold — comm regressions caught at
    # trace time, off-TPU, before any wall-clock number moves
    comm_report = None
    cb, cn = comm_reports(base), comm_reports(new)
    shared_progs = sorted(set(cb) & set(cn))
    if shared_progs:
        comm_report = {"programs": shared_progs}
        for prog in shared_progs:
            for coll in sorted(set(cb[prog]) | set(cn[prog])):
                b_c = cb[prog].get(coll) or {}
                n_c = cn[prog].get(coll) or {}
                bc = int(b_c.get("count") or 0)
                nc = int(n_c.get("count") or 0)
                bby = int(b_c.get("bytes") or 0)
                nby = int(n_c.get("bytes") or 0)
                if nc != bc:
                    regressions.append({
                        "leg": f"comm_count:{prog}/{coll}",
                        "base": bc, "new": nc,
                    })
                elif nby > bby * (1.0 + threshold):
                    regressions.append({
                        "leg": f"comm_bytes:{prog}/{coll}",
                        "base": bby, "new": nby,
                        "delta_pct": round(
                            100.0 * (nby - bby) / bby, 2) if bby else None,
                    })
    # attribution-summary schema (ISSUE-17): a NEW capture whose serving
    # legs carry a malformed attribution block — or one whose terms no
    # longer sum to the measured TTFT — is drift, flagged like a perf leg
    attr_probs = attribution_problems(new)
    if attr_probs:
        regressions.append({"leg": "attribution_schema",
                            "base": None, "new": False,
                            "problems": attr_probs})
    return {
        "threshold_pct": round(100.0 * threshold, 2),
        "regressions": regressions,
        "improvements": improvements,
        "unchanged": unchanged,
        "only_in_base": sorted(set(a) - set(b)),
        "only_in_new": sorted(set(b) - set(a)),
        "audit": {"base": ab, "new": an},
        "op_categories": cat_report,
        "comm": comm_report,
    }


def compare_trajectory(paths: List[str], threshold: float = 0.05) -> dict:
    """Compare consecutive pairs along a trajectory of result files;
    unparseable captures are reported and skipped."""
    loaded = []
    skipped = []
    for p in paths:
        bench = load_bench(p)
        if bench is None:
            skipped.append(p)
        else:
            loaded.append((p, bench))
    steps = []
    for (pa, a), (pb, b) in zip(loaded, loaded[1:]):
        steps.append({"base": pa, "new": pb,
                      **compare(a, b, threshold=threshold)})
    return {"steps": steps, "skipped_unparseable": skipped}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("files", nargs="+", help="bench result files")
    ap.add_argument("--threshold", type=float, default=0.05,
                    help="fractional regression tolerance per leg "
                         "(default 0.05 = 5%%)")
    ap.add_argument("--trajectory", action="store_true",
                    help="compare consecutive pairs of all files instead "
                         "of exactly two")
    args = ap.parse_args(argv)

    if args.trajectory or len(args.files) != 2:
        if len(args.files) < 2:
            ap.error("need at least two files")
        report = compare_trajectory(args.files, threshold=args.threshold)
        if not report["steps"]:
            # nothing comparable (e.g. every capture truncated): the
            # gate must fail loudly, not wave the drift through
            print(json.dumps(report, indent=2))
            return 2
        regressed = any(s["regressions"] for s in report["steps"])
    else:
        base, new = (load_bench(p) for p in args.files)
        if base is None or new is None:
            print(json.dumps({"error": "unparseable bench file"}))
            return 2
        report = compare(base, new, threshold=args.threshold)
        regressed = bool(report["regressions"])
    print(json.dumps(report, indent=2))
    return 1 if regressed else 0


if __name__ == "__main__":
    sys.exit(main())
