"""trace_report: waterfalls, latency attribution, and causality
validation over apex_tpu span streams.

Every ``event == "span"`` record in a telemetry JSONL (the serving
sinks, the elastic checkpoint/supervisor sinks, a flight-recorder black
box) is one closed span; this tool groups them into traces, validates
causality, and renders the result:

    python tools/trace_report.py events.jsonl             # report
    python tools/trace_report.py events.jsonl --json
    python tools/trace_report.py events.jsonl --waterfall req-3
    python tools/trace_report.py --self                   # smokes
    python tools/trace_report.py --self --check chaos_fleet_trace

Validation is the point, not a side effect: the exit code is non-zero
when the stream's causality is broken —

- **orphan spans**: a ``parent_id`` that resolves to no span in the
  same trace (a hop emitted outside its request's tree);
- **unterminated requests**: a ``req-*`` trace with zero or more than
  one ``terminal`` span (every offered request must end exactly once);
- **non-monotone timestamps**: ``t_end < t_start`` on any span;
- **duplicate span ids** among live (non-black-box-replay) spans.

Black-box replays (``blackbox_replay: true``) are post-mortem COPIES of
spans that may also exist in the live stream; they are deduplicated by
``(trace_id, span_id)`` before validation so a crash dump never reads
as a duplicate-id violation.

Exit codes (CI contract, same as serving_check/resilience_check):
0 = valid / all checks pass, 1 = broken causality or a failed check,
2 = infra/usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
# tools/ itself, so `from serving_check import ...` resolves when this
# module is imported as `tools.trace_report` (tier-1 tests) rather than
# run as a script.
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# trace assembly

def build_traces(records) -> Dict[str, List[dict]]:
    """Group span records by trace id, deduplicating black-box replays
    against the live stream by ``(trace_id, span_id)`` (first record
    wins — the live span precedes its post-mortem copy)."""
    traces: Dict[str, List[dict]] = {}
    seen: set = set()
    for rec in records:
        if rec.get("event") != "span":
            continue
        key = (rec.get("trace_id"), rec.get("span_id"))
        if key in seen:
            continue
        seen.add(key)
        traces.setdefault(str(rec.get("trace_id")), []).append(rec)
    for spans in traces.values():
        spans.sort(key=lambda s: (s.get("t_start", 0.0),
                                  str(s.get("span_id"))))
    return traces


def validate(traces: Dict[str, List[dict]]) -> List[str]:
    """Every causality problem in the stream, as human-readable
    strings; an empty list means the trace set is sound."""
    problems: List[str] = []
    for tid, spans in sorted(traces.items()):
        ids = [s.get("span_id") for s in spans]
        id_set = set(ids)
        if len(ids) != len(id_set):
            dupes = sorted({str(i) for i in ids if ids.count(i) > 1})
            problems.append(
                f"{tid}: duplicate span id(s) {', '.join(dupes)}")
        for s in spans:
            pid = s.get("parent_id")
            if pid is not None and pid not in id_set:
                problems.append(
                    f"{tid}: orphan span {s.get('span_id')} "
                    f"({s.get('name')}) parent {pid} not in trace")
            t0, t1 = s.get("t_start"), s.get("t_end")
            if t0 is None or t1 is None or t1 < t0:
                problems.append(
                    f"{tid}: non-monotone span {s.get('span_id')} "
                    f"({s.get('name')}): t_start={t0} t_end={t1}")
        if tid.startswith("req-"):
            n_term = sum(bool(s.get("terminal")) for s in spans)
            if n_term != 1:
                problems.append(
                    f"{tid}: {n_term} terminal spans (every request "
                    "trace must end exactly once)")
    return problems


# ---------------------------------------------------------------------------
# rendering

def render_waterfall(spans: List[dict], width: int = 48) -> List[str]:
    """One trace as an indented text waterfall: root spans at depth 0,
    children under their parents, bars scaled to the trace extent."""
    by_id = {s.get("span_id"): s for s in spans}
    children: Dict[object, List[dict]] = {}
    roots: List[dict] = []
    for s in spans:
        pid = s.get("parent_id")
        if pid is not None and pid in by_id:
            children.setdefault(pid, []).append(s)
        else:
            roots.append(s)
    t_lo = min(s.get("t_start", 0.0) for s in spans)
    t_hi = max(s.get("t_end", 0.0) for s in spans)
    span_s = max(t_hi - t_lo, 1e-12)
    lines: List[str] = []

    def emit(s: dict, depth: int) -> None:
        t0, t1 = s.get("t_start", 0.0), s.get("t_end", 0.0)
        lo = int(round((t0 - t_lo) / span_s * width))
        hi = max(int(round((t1 - t_lo) / span_s * width)), lo + 1)
        bar = " " * lo + "#" * (hi - lo)
        name = "  " * depth + str(s.get("name"))
        mark = " *" if s.get("terminal") else ""
        lines.append(f"  {name:<24.24} |{bar:<{width + 1}}| "
                     f"{1e3 * (t1 - t0):8.2f} ms{mark}")
        for c in sorted(children.get(s.get("span_id"), []),
                        key=lambda x: (x.get("t_start", 0.0),
                                       str(x.get("span_id")))):
            emit(c, depth + 1)

    for r in sorted(roots, key=lambda x: (x.get("t_start", 0.0),
                                          str(x.get("span_id")))):
        emit(r, 0)
    return lines


def attribution_table(traces: Dict[str, List[dict]]) -> Optional[dict]:
    """Fold the terminal request spans' ``attr_ms`` / ``attr_ttft_ms``
    breakdowns into per-term percentiles + a dominant-cause tally over
    spans flagged ``slo_violated`` — the file-side twin of the live
    ``attribution`` summary block."""
    from apex_tpu.telemetry import ATTR_TERMS, percentiles

    attr: List[dict] = []
    ttft: List[dict] = []
    causes: Dict[str, int] = {}
    for tid, spans in traces.items():
        if not tid.startswith("req-"):
            continue
        for s in spans:
            if not s.get("terminal") or "attr_ms" not in s:
                continue
            attr.append(s["attr_ms"])
            if "attr_ttft_ms" in s:
                ttft.append(s["attr_ttft_ms"])
            cause = s.get("dominant_cause")
            if s.get("slo_violated") and cause:
                causes[cause] = causes.get(cause, 0) + 1
    if not attr:
        return None
    return {
        "terms": list(ATTR_TERMS),
        "n_attributed": len(attr),
        "e2e_ms": {t: percentiles([a.get(t, 0.0) for a in attr])
                   for t in ATTR_TERMS},
        "ttft_ms": {t: percentiles([a.get(t, 0.0) for a in ttft])
                    for t in ATTR_TERMS},
        "dominant_causes": causes,
    }


def report(path: str, *, waterfall: Optional[str] = None,
           max_waterfalls: int = 3) -> Tuple[dict, List[str]]:
    """Load + validate one span stream; returns ``(summary, lines)``
    where lines is the rendered text report."""
    from apex_tpu.telemetry import read_jsonl

    stats: Dict[str, int] = {}
    records = read_jsonl(path, stats=stats)
    traces = build_traces(records)
    problems = validate(traces)
    blackboxes = [r for r in records if r.get("event") == "blackbox"]
    summary = {
        "path": path,
        "records": len(records),
        "torn_lines": stats.get("torn_lines", 0),
        "traces": len(traces),
        "spans": sum(len(s) for s in traces.values()),
        "request_traces": sum(tid.startswith("req-") for tid in traces),
        "blackboxes": [{"reason": b.get("reason"),
                        "n_spans": b.get("n_spans")}
                       for b in blackboxes],
        "attribution": attribution_table(traces),
        "problems": problems,
        "ok": not problems,
    }
    lines = [f"trace report: {path}",
             f"  {summary['spans']} spans in {summary['traces']} traces "
             f"({summary['request_traces']} requests, "
             f"{len(blackboxes)} black boxes, "
             f"{summary['torn_lines']} torn tail line(s))"]
    shown = 0
    for tid in sorted(traces):
        if waterfall is not None:
            if tid != waterfall:
                continue
        elif not tid.startswith("req-") or shown >= max_waterfalls:
            continue
        lines.append(f"\n{tid}:")
        lines.extend(render_waterfall(traces[tid]))
        shown += 1
    att = summary["attribution"]
    if att is not None:
        lines.append("\nlatency attribution (ms, e2e p50/p90/p99):")
        for t in att["terms"]:
            p = att["e2e_ms"][t]
            lines.append(
                f"  {t:<16} {p.get('p50', 0.0):9.2f} "
                f"{p.get('p90', 0.0):9.2f} {p.get('p99', 0.0):9.2f}")
        if att["dominant_causes"]:
            lines.append(f"  dominant causes on SLO violators: "
                         f"{att['dominant_causes']}")
    if problems:
        lines.append("\nBROKEN CAUSALITY:")
        lines.extend(f"  {p}" for p in problems)
    else:
        lines.append("\ncausality: OK")
    return summary, lines


# ---------------------------------------------------------------------------
# self-checks (--self): the observability stack on its own traces

def _chaos_fleet_records():
    """One deterministic chaos fleet run (replica kill mid-trace,
    forced preemption, prefix eviction) under VirtualClock; returns
    (records, requests, fleet)."""
    from serving_check import _tiny_cfg, _tiny_params

    from apex_tpu import telemetry
    from apex_tpu.resilience.chaos import ServingChaos
    from apex_tpu.serving import Request
    from apex_tpu.serving.fleet import ReplicaFleet
    from apex_tpu.serving.robustness import VirtualClock

    cfg = _tiny_cfg()
    params = _tiny_params(cfg)
    sink = telemetry.RingBufferRecorder(capacity=100000)
    chaos = ServingChaos()
    chaos.kill_replica_at(0, 2)
    chaos.evict_prefix_cache(2)
    fleet = ReplicaFleet(cfg, params, n_replicas=2, sink=sink,
                         clock=VirtualClock(dt=0.01), chaos=chaos,
                         n_slots=2, num_pages=64)
    shared = [1, 2, 3, 4]
    reqs = [Request(rid=i, prompt=shared[: 2 + (i % 2)] + [5 + i],
                    max_new_tokens=4, arrival_step=i % 3)
            for i in range(8)]
    fleet.generate(reqs, max_steps=500)
    return list(sink.records), reqs, fleet


def check_chaos_fleet_trace() -> dict:
    """The acceptance trace: a chaos fleet (kill + eviction) under
    VirtualClock yields complete span trees — zero orphans, exactly one
    terminal span per offered request, monotone timestamps — and the
    TTFT attribution terms sum to the measured TTFT within 1%."""
    records, reqs, fleet = _chaos_fleet_records()
    traces = build_traces(records)
    problems = validate(traces)
    missing = [r.rid for r in reqs
               if getattr(r, "trace", None) is None
               or r.trace.trace_id not in traces]
    unterminated = [
        tid for tid, spans in traces.items() if tid.startswith("req-")
        and sum(bool(s.get("terminal")) for s in spans) != 1]
    rel_errs = []
    for r in reqs:
        if r.t_first_token is None or r.attr_ttft is None:
            continue
        measured = r.t_first_token - r.t_arrival
        if measured > 0:
            rel_errs.append(
                abs(sum(r.attr_ttft.values()) - measured) / measured)
    rel_err = max(rel_errs, default=0.0)
    att = fleet.last_stats.get("attribution")
    ok = (not problems and not missing and not unterminated
          and rel_err <= 0.01 and rel_errs and att is not None
          and fleet.replica_deaths >= 1)
    return {"ok": bool(ok), "problems": problems[:5],
            "missing_traces": missing, "unterminated": unterminated,
            "ttft_sum_rel_err_max": rel_err,
            "replica_deaths": fleet.replica_deaths,
            "n_spans": sum(len(s) for s in traces.values())}


def check_detects_broken_causality() -> dict:
    """The validator itself: a synthetic stream seeded with an orphan
    span, an unterminated request trace, and a non-monotone span must
    be flagged — three distinct problems, none missed."""
    records = [
        # sound trace (must NOT be flagged)
        {"event": "span", "name": "request", "trace_id": "req-0",
         "span_id": 1, "parent_id": None, "t_start": 0.0, "t_end": 2.0,
         "terminal": True},
        {"event": "span", "name": "prefill", "trace_id": "req-0",
         "span_id": 2, "parent_id": 1, "t_start": 0.5, "t_end": 1.0,
         "terminal": False},
        # orphan: parent 99 does not exist
        {"event": "span", "name": "admit", "trace_id": "req-1",
         "span_id": 3, "parent_id": 99, "t_start": 0.0, "t_end": 1.0,
         "terminal": True},
        # unterminated request trace
        {"event": "span", "name": "route", "trace_id": "req-2",
         "span_id": 4, "parent_id": None, "t_start": 0.0, "t_end": 0.0,
         "terminal": False},
        # non-monotone
        {"event": "span", "name": "step", "trace_id": "engine-steps",
         "span_id": 5, "parent_id": None, "t_start": 3.0, "t_end": 1.0,
         "terminal": False},
    ]
    problems = validate(build_traces(records))
    caught = {
        "orphan": any("orphan" in p for p in problems),
        "unterminated": any("terminal" in p and "req-2" in p
                            for p in problems),
        "non_monotone": any("non-monotone" in p for p in problems),
        "clean_trace_clean": not any("req-0" in p for p in problems),
    }
    return {"ok": all(caught.values()), **caught,
            "n_problems": len(problems)}


def check_blackbox_torn_tail() -> dict:
    """The crash path end to end: a flight-recorder black box written
    to disk, its final line torn mid-record (the crash), must still
    load — torn tail tolerated and counted, every intact span
    readable."""
    import tempfile

    from apex_tpu.telemetry import Tracer, read_jsonl

    tracer = Tracer(ring_capacity=16)
    for i in range(5):
        tracer.emit("engine_step", "engine-steps", float(i),
                    float(i) + 0.5, ring_only=True, step=i)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "blackbox.jsonl")
        tracer.dump_blackbox(reason="hang", path=path,
                             stacks="Thread 1: ...\n  in run_step")
        with open(path, "a") as f:
            f.write('{"event": "span", "name": "torn')  # the crash
        stats: Dict[str, int] = {}
        records = read_jsonl(path, stats=stats)
    header = records[0] if records else {}
    spans = [r for r in records if r.get("event") == "span"]
    ok = (stats.get("torn_lines") == 1 and len(spans) == 5
          and header.get("event") == "blackbox"
          and header.get("reason") == "hang"
          and "stacks" in header)
    return {"ok": bool(ok), "torn_lines": stats.get("torn_lines"),
            "spans_recovered": len(spans),
            "header_reason": header.get("reason")}


def check_report_roundtrip() -> dict:
    """report() over a real chaos-fleet stream written to disk: loads,
    validates clean, renders waterfalls + the attribution table, and
    agrees with the in-memory span count."""
    import tempfile

    from apex_tpu.telemetry import JsonlRecorder

    records, reqs, fleet = _chaos_fleet_records()
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "events.jsonl")
        rec = JsonlRecorder(path)
        for r in records:
            rec.record(r)
        rec.close()
        summary, lines = report(path)
    att = summary["attribution"]
    ok = (summary["ok"] and summary["request_traces"] == len(reqs)
          and att is not None and att["n_attributed"] == len(reqs)
          and any("#" in ln for ln in lines))
    return {"ok": bool(ok), "problems": summary["problems"][:5],
            "request_traces": summary["request_traces"],
            "spans": summary["spans"]}


CHECKS = {
    "chaos_fleet_trace": check_chaos_fleet_trace,
    "detects_broken_causality": check_detects_broken_causality,
    "blackbox_torn_tail": check_blackbox_torn_tail,
    "report_roundtrip": check_report_roundtrip,
}


def run_checks(names=None) -> dict:
    out = {"event": "trace_report_check", "checks": {}}
    ok = True
    for name in (list(names) if names else sorted(CHECKS)):
        res = CHECKS[name]()
        out["checks"][name] = res
        ok = ok and bool(res["ok"])
    out["ok"] = ok
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Waterfalls, latency attribution, and causality "
                    "validation over apex_tpu span streams")
    ap.add_argument("path", nargs="?",
                    help="telemetry JSONL (span stream / black box)")
    ap.add_argument("--self", action="store_true", dest="self_check",
                    help="run the built-in tracing smokes")
    ap.add_argument("--check", action="append", choices=sorted(CHECKS),
                    help="restrict --self to specific check(s)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full result as JSON")
    ap.add_argument("--waterfall", metavar="TRACE_ID",
                    help="render only this trace's waterfall")
    ap.add_argument("--max-waterfalls", type=int, default=3,
                    help="request waterfalls to render (default 3)")
    args = ap.parse_args(argv)

    if args.self_check:
        try:
            result = run_checks(args.check)
        except Exception as e:
            print(f"trace_report check failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(result, indent=2, default=str))
        else:
            for name, res in result["checks"].items():
                status = "PASS" if res["ok"] else "FAIL"
                detail = {k: v for k, v in res.items() if k != "ok"}
                print(f"{status}  {name}  {detail}")
            print("summary:", json.dumps({"ok": result["ok"]}))
        return 0 if result["ok"] else 1

    if not args.path:
        ap.error("nothing to do: pass a telemetry JSONL or --self")
    if not os.path.exists(args.path):
        print(f"no such file: {args.path}", file=sys.stderr)
        return 2
    try:
        summary, lines = report(args.path, waterfall=args.waterfall,
                                max_waterfalls=args.max_waterfalls)
    except Exception as e:
        print(f"trace_report failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(summary, indent=2, default=str))
    else:
        print("\n".join(lines))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
