"""Fleet health console: status table, error budgets, alerts, Prom text.

The read side of the fleet health plane (``apex_tpu.telemetry``
timeseries/slo/alerts): fold a telemetry JSONL stream — the one a
``ReplicaFleet``/``ServingEngine``/elastic service already writes — into
the :class:`~apex_tpu.telemetry.MetricsAggregator`, replay the SLO
trackers over it at the stream's own timestamps, and render:

- a per-replica health table (liveness, queue depth, occupancy, free
  pages, requests by status, deaths/restarts);
- per-SLO error budgets (state, budget remaining, attainment, episode
  counts) and the active alerts;
- ``--prom``: the Prometheus text exposition of every aggregated
  series (counters/gauges verbatim, histograms as summary quantiles).

Replay is a pure fold over the records — like the aggregator itself it
reads no clocks, so the same file always renders the same report.

``--self`` runs the health plane's own smokes (the tier-1 CI lane, same
contract as ``serving_check.py --self``):

- ``hist_accuracy``        sketch quantiles vs the exact
                           ``telemetry.percentiles`` reducer agree
                           within the documented ``alpha`` bucket error.
- ``merge_order``          per-replica sketches folded in any order
                           produce byte-identical snapshots.
- ``aggregation_determinism``  one event stream fed to two aggregators
                           (and shard-merged three ways) produces
                           byte-identical snapshot JSON.
- ``burn_rate_alert``      a ramping-overload synthetic stream fires
                           the fast-burn page BEFORE cumulative
                           attainment crosses the objective, fires the
                           episode exactly once, and resolves after
                           recovery (no flapping).
- ``responder_actions``    firing alerts drive the actuators: load
                           alert arms degradation on every live
                           replica and relaxes on resolve; an
                           availability alert restarts the dead
                           replica; a page mid-rolling-update aborts
                           the wave.
- ``prom_exposition``      the text exposition is well-formed and
                           consistent (every series line parses,
                           summary ``_sum``/``_count`` present).

Usage::

    python tools/fleet_status.py run.jsonl              # health table
    python tools/fleet_status.py run.jsonl --prom       # exposition
    python tools/fleet_status.py run.jsonl --json
    python tools/fleet_status.py workdir/               # per-replica
                      # JSONL directory (real-process fleet), merged
                      # fleet-wide by t_wall; torn tails tolerated
    python tools/fleet_status.py --self [--check NAME] [--json]

Exit codes (CI contract, same as serving_check/static_audit): 0 = all
checks pass / no SLO firing, 1 = a check failed or an alert is firing,
2 = infra/usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from collections import defaultdict
from typing import List, Optional

# script-mode invocation (`python tools/fleet_status.py ...`) puts
# tools/ at sys.path[0]; the repo root must be importable for apex_tpu
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# JSONL replay -> aggregator + SLO evaluation


def load_stream(path: str) -> list:
    """Records from one JSONL file — or from a DIRECTORY of them,
    merged by ``t_wall``: the real-process fleet writes one file per
    replica incarnation (``replica-<i>.<inc>.jsonl``), and a
    post-mortem wants the interleaved fleet-wide stream. Torn final
    lines (SIGKILLed writers) are skipped per file, exactly like the
    single-file path; records without a timestamp keep their per-file
    order and sort before stamped ones (stable sort on t_wall=-inf)."""
    from apex_tpu.telemetry import read_jsonl

    if not os.path.isdir(path):
        return read_jsonl(path)
    records = []
    for name in sorted(os.listdir(path)):
        if not name.endswith(".jsonl"):
            continue
        records.extend(read_jsonl(os.path.join(path, name)))
    records.sort(key=lambda r: (
        float(r["t_wall"]) if isinstance(r.get("t_wall"), (int, float))
        else float("-inf")))
    return records


def replay_records(records, *, slos=None, eval_every: int = 16):
    """Fold a record list into ``(aggregator, trackers, alerts_seen)``.

    SLO trackers are evaluated at the stream's own ``t_wall`` stamps
    (every ``eval_every`` records — the replay analogue of the fleet's
    per-boundary cadence); ``alert``/``response`` events already in the
    stream are collected verbatim so a post-mortem shows what the LIVE
    manager did, not just what replay would have done.
    """
    from apex_tpu.telemetry import MetricsAggregator, default_serving_slos

    agg = MetricsAggregator()
    trackers = slos if slos is not None else default_serving_slos()
    alerts_seen: List[dict] = []
    n = 0
    last_t: Optional[float] = None
    for rec in records:
        if rec.get("event") == "alert":
            alerts_seen.append(rec)
        agg.record(rec)
        n += 1
        t = rec.get("t_wall", rec.get("t"))
        if isinstance(t, (int, float)):
            last_t = float(t)
        if n % eval_every == 0 and last_t is not None:
            _evaluate(trackers, agg, last_t)
    if last_t is not None:
        _evaluate(trackers, agg, last_t)
    return agg, trackers, alerts_seen


def _evaluate(trackers, agg, now: float) -> None:
    for t in trackers:
        src = t.source
        if hasattr(src, "now"):
            src.now = now
        t.evaluate(agg, now)


# ---------------------------------------------------------------------------
# rendering


def _fmt(v, nd=4):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}g}"
    return str(v)


def format_table(headers: List[str], rows: List[List]) -> str:
    cells = [[_fmt(c) for c in row] for row in rows]
    widths = [max(len(h), *(len(r[i]) for r in cells)) if cells else len(h)
              for i, h in enumerate(headers)]
    line = "  ".join(h.ljust(w) for h, w in zip(headers, widths))
    sep = "  ".join("-" * w for w in widths)
    body = "\n".join(
        "  ".join(c.ljust(w) for c, w in zip(row, widths)) for row in cells)
    return "\n".join([line, sep, body]) if cells else "\n".join([line, sep])


def _series_by_replica(family: dict) -> dict:
    """{replica_id: value} from one metric family's label-keyed series
    (series without a replica_id label fold under "-")."""
    out: dict = defaultdict(float)
    for key, v in (family or {}).items():
        rid = dict(key).get("replica_id", "-")
        out[rid] += v
    return dict(out)


def fleet_table(agg) -> dict:
    """Per-replica health rows from the aggregated series."""
    up = _series_by_replica(agg.gauges.get("replica_up"))
    rows = {}
    for rid in sorted(up, key=str):
        rows[rid] = {"up": up[rid] > 0}
    for gauge, col in (("serving_queue_depth", "queue"),
                       ("serving_occupancy", "occupancy"),
                       ("serving_free_pages", "free_pages")):
        for rid, v in _series_by_replica(agg.gauges.get(gauge)).items():
            rows.setdefault(rid, {})[col] = v
    for counter, col in (("fleet_replica_down_total", "deaths"),
                         ("fleet_replica_restarts_total", "restarts"),
                         ("serving_rejects_total", "rejects"),
                         ("serving_sheds_total", "sheds")):
        for rid, v in _series_by_replica(agg.counters.get(counter)).items():
            rows.setdefault(rid, {})[col] = int(v)
    # requests by terminal status, re-keyed per replica
    for key, v in (agg.counters.get("requests_total") or {}).items():
        kd = dict(key)
        rid = kd.get("replica_id", "-")
        st = kd.get("status", "?")
        d = rows.setdefault(rid, {}).setdefault("requests", {})
        d[st] = d.get(st, 0) + int(v)
    return rows


def slo_table(trackers) -> List[dict]:
    return [{
        "name": t.slo.name,
        "state": t.state.value,
        "objective": t.slo.objective,
        "budget_remaining": round(t.budget.remaining, 4),
        "attainment": (round(t.budget.attainment, 4)
                       if t.budget.attainment is not None else None),
        "fired": t.fired_count,
        "resolved": t.resolved_count,
    } for t in sorted(trackers, key=lambda t: t.slo.name)]


def render_status(agg, trackers, alerts_seen) -> str:
    out = []
    reps = fleet_table(agg)
    if reps:
        out.append("fleet replicas")
        rows = []
        for rid, d in sorted(reps.items(), key=lambda kv: str(kv[0])):
            reqs = d.get("requests", {})
            rows.append([
                rid, "up" if d.get("up") else "DOWN",
                d.get("queue"), d.get("occupancy"), d.get("free_pages"),
                reqs.get("completed", 0),
                sum(v for k, v in reqs.items() if k != "completed"),
                d.get("deaths", 0), d.get("restarts", 0),
                d.get("rejects", 0), d.get("sheds", 0)])
        out.append(format_table(
            ["replica", "state", "queue", "occupancy", "free_pages",
             "completed", "not_completed", "deaths", "restarts",
             "rejects", "sheds"], rows))
    out.append("\nSLO error budgets")
    rows = [[s["name"], s["state"], s["objective"],
             s["budget_remaining"], s["attainment"], s["fired"],
             s["resolved"]] for s in slo_table(trackers)]
    out.append(format_table(
        ["slo", "state", "objective", "budget_left", "attainment",
         "fired", "resolved"], rows))
    firing = [t.slo.name for t in trackers if t.firing]
    out.append(f"\nactive alerts: {', '.join(firing) if firing else 'none'}")
    if alerts_seen:
        out.append(f"alert transitions in stream: {len(alerts_seen)} "
                   "(live AlertManager events)")
        for a in alerts_seen[-8:]:
            out.append(f"  t={_fmt(a.get('t'))} {a.get('name')}: "
                       f"{a.get('prev_state')} -> {a.get('state')} "
                       f"(burn fast={_fmt(a.get('burn_fast'))} "
                       f"slow={_fmt(a.get('burn_slow'))})")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# --self checks


def check_hist_accuracy() -> dict:
    """Sketch quantiles vs the exact reducer within the alpha bound."""
    import numpy as np

    from apex_tpu.telemetry import LogBucketHistogram, percentiles

    rng = np.random.default_rng(0)
    worst_nr = 0.0   # vs exact nearest-rank: the hard alpha bound
    worst_interp = 0.0  # vs percentiles(): holds on smooth streams
    cases = []
    for alpha in (0.05, 0.01):
        for dist in ("lognormal", "uniform", "bimodal"):
            if dist == "lognormal":
                vals = rng.lognormal(3.0, 1.0, size=4000)
            elif dist == "uniform":
                vals = rng.uniform(0.5, 500.0, size=4000)
            else:
                vals = np.concatenate([rng.normal(10, 1, 2000),
                                       rng.normal(1000, 50, 2000)])
                vals = np.abs(vals) + 1e-3
            h = LogBucketHistogram(alpha=alpha)
            for v in vals:
                h.add(float(v))
            srt = np.sort(vals)
            interp = percentiles(vals.tolist(), ps=(50, 90, 99))
            rel_nr = rel_in = 0.0
            for q in (50, 90, 99):
                got = h.quantile(q / 100.0)
                nr = float(srt[int(np.ceil(q / 100.0 * len(srt))) - 1])
                rel_nr = max(rel_nr, abs(got - nr) / nr)
                # interpolation comparison only where the stream is
                # smooth — in a bimodal gap the two conventions answer
                # different questions (see quantile()'s docstring)
                if dist != "bimodal":
                    rel_in = max(rel_in,
                                 abs(got - interp[f"p{q}"])
                                 / interp[f"p{q}"])
            worst_nr = max(worst_nr, rel_nr / alpha)
            worst_interp = max(worst_interp, rel_in / alpha)
            cases.append({"alpha": alpha, "dist": dist,
                          "rel_err_over_alpha": round(rel_nr / alpha, 3)})
    # nearest-rank: the documented alpha bound, every distribution;
    # percentiles(): same bound + a 1-order-stat interpolation allowance
    ok = worst_nr <= 1.0 + 1e-9 and worst_interp <= 1.5
    return {"ok": ok, "worst_vs_nearest_rank": round(worst_nr, 3),
            "worst_vs_percentiles": round(worst_interp, 3),
            "cases": cases}


def check_merge_order() -> dict:
    """Per-replica sketches fold order-independently, byte-identical."""
    import itertools
    import json as _json

    import numpy as np

    from apex_tpu.telemetry import LogBucketHistogram

    rng = np.random.default_rng(1)
    shards = []
    for _ in range(4):
        h = LogBucketHistogram()
        for v in rng.lognormal(2.0, 1.5, size=500):
            h.add(float(v))
        shards.append(h)
    snaps = set()
    for perm in itertools.permutations(range(4)):
        out = LogBucketHistogram()
        for i in perm:
            out.merge(shards[i])
        snaps.add(_json.dumps(out.snapshot(), sort_keys=True))
    # and against the single-stream fold
    return {"ok": len(snaps) == 1, "distinct_snapshots": len(snaps),
            "permutations": 24}


def check_aggregation_determinism() -> dict:
    """Same stream -> byte-identical aggregator snapshots."""
    import numpy as np

    from apex_tpu.telemetry import MetricsAggregator

    rng = np.random.default_rng(2)
    recs = []
    for i in range(300):
        rid = int(rng.integers(0, 3))
        if i % 3 == 0:
            recs.append({"event": "serving_step", "replica_id": rid,
                         "step": i, "queue_depth": int(rng.integers(0, 9)),
                         "occupancy": float(rng.uniform(0, 1)),
                         "free_pages": int(rng.integers(0, 64)),
                         "active": int(rng.integers(0, 4))})
        else:
            ok = bool(rng.random() > 0.2)
            recs.append({"event": "request_end", "replica_id": rid,
                         "rid": i, "status": "completed" if ok
                         else "timed_out", "reason": "eos",
                         "generated": int(rng.integers(1, 30)),
                         "preemptions": 0, "restarts": 0,
                         "slo_ok": ok,
                         "ttft_ms": float(rng.lognormal(3, 0.5)),
                         "latency_ms": float(rng.lognormal(5, 0.5)),
                         "labels": {"tenant": f"t{rid % 2}"}})
    a, b = MetricsAggregator(), MetricsAggregator()
    for r in recs:
        a.record(r)
    for r in recs:
        b.record(r)
    same_twice = a.snapshot_json() == b.snapshot_json()
    # merged sketch = the single-stream family fold regardless of how
    # the stream was sharded across aggregators
    merged = a.hist_merged("ttft_ms")
    per_rep = [MetricsAggregator() for _ in range(3)]
    for r in recs:
        per_rep[r["replica_id"]].record(r)
    from apex_tpu.telemetry import LogBucketHistogram

    fold = LogBucketHistogram()
    for p in per_rep:
        h = p.hist_merged("ttft_ms")
        if h is not None:
            fold.merge(h)
    shard_same = (merged is not None
                  and json.dumps(merged.snapshot(), sort_keys=True)
                  == json.dumps(fold.snapshot(), sort_keys=True))
    return {"ok": same_twice and shard_same, "same_twice": same_twice,
            "shard_merge_identical": shard_same}


def check_burn_rate_alert() -> dict:
    """Ramping overload: page fires before attainment crosses the
    objective, exactly one episode, resolves after recovery."""
    from apex_tpu.telemetry import MetricsAggregator, default_serving_slos

    agg = MetricsAggregator()
    trackers = default_serving_slos(attainment_objective=0.9,
                                    fast_window_s=10.0,
                                    slow_window_s=40.0)
    att = next(t for t in trackers if t.slo.name == "slo_attainment")
    rid = 0

    def submit(t, n_good, n_bad):
        nonlocal rid
        for ok in [True] * n_good + [False] * n_bad:
            rid += 1
            agg.record({"event": "request_end", "replica_id": 0,
                        "rid": rid,
                        "status": "completed" if ok else "timed_out",
                        "reason": "x", "generated": 4 if ok else 0,
                        "preemptions": 0, "restarts": 0, "slo_ok": ok})

    fired_at = None
    attainment_at_fire = None
    # phase 1: healthy traffic (t 0..90) — builds the budget runway a
    # cumulative metric would coast on long after service collapses
    t = 0.0
    while t < 90.0:
        submit(t, 8, 0)
        _evaluate(trackers, agg, t)
        t += 1.0
    # phase 2: ramping overload — bad fraction climbs each boundary
    bad = 0
    while t < 125.0:
        bad = min(8, bad + 2)
        submit(t, 8 - bad, bad)
        _evaluate(trackers, agg, t)
        if fired_at is None and att.firing:
            fired_at = t
            attainment_at_fire = att.budget.attainment
        t += 1.0
    # phase 3: recovery — long enough for the slow window to drain
    while t < 225.0:
        submit(t, 8, 0)
        _evaluate(trackers, agg, t)
        t += 1.0
    fired_before_collapse = (
        fired_at is not None and attainment_at_fire is not None
        and attainment_at_fire >= att.slo.objective)
    ok = (fired_before_collapse and att.fired_count == 1
          and att.state.value == "ok")
    return {"ok": ok, "fired_at": fired_at,
            "attainment_at_fire": (round(attainment_at_fire, 4)
                                   if attainment_at_fire is not None
                                   else None),
            "objective": att.slo.objective,
            "episodes": att.fired_count,
            "resolved": att.resolved_count,
            "final_state": att.state.value,
            "transitions": len(att.timeline)}


class _FakeAdmission:
    def __init__(self):
        self.degradation = None

    def arm_degradation(self, policy):
        self.degradation = policy

    def relax_degradation(self, policy=None):
        self.degradation = policy


class _FakeEngine:
    def __init__(self):
        self.admission = _FakeAdmission()


class _FakeReplica:
    def __init__(self, idx, live=True):
        self.idx = idx
        self.live = live
        self.engine = _FakeEngine()


class _FakeFleet:
    """Duck-typed stand-in exposing exactly the actuator surface
    FleetResponder drives (the real fleet is exercised in
    tests/test_fleet_health.py — this keeps --self in the CPU lane)."""

    def __init__(self):
        self.replicas = [_FakeReplica(0), _FakeReplica(1),
                         _FakeReplica(2, live=False)]
        self._swap_plan = {"params": object(), "queue": [1],
                           "current": 0, "requeued": set()}
        self.aborts = 0
        self.restarts = []

    def abort_rolling_update(self):
        self._swap_plan = None
        self.aborts += 1
        return 1

    def restart_replica(self, idx):
        self.restarts.append(idx)
        self.replicas[idx].live = True


def check_responder_actions() -> dict:
    """Alert transitions drive arm/relax, restart, abort."""
    from apex_tpu.telemetry import FleetResponder
    from apex_tpu.telemetry.slo import SLO, SLOTracker

    fleet = _FakeFleet()
    resp = FleetResponder(fleet)
    att = SLOTracker(SLO(name="slo_attainment", objective=0.9),
                     lambda agg: (0.0, 0.0))
    avail = SLOTracker(SLO(name="replica_available", objective=0.5,
                           kind="threshold", target=0.99,
                           higher_is_better=True),
                       lambda agg: None)

    def rec(tracker, state, prev, severity="page"):
        return {"name": tracker.slo.name, "state": state,
                "prev_state": prev, "severity": severity}

    actions = []
    # load alert fires -> degradation armed on live replicas + the
    # in-flight rolling update aborted (page severity)
    actions += resp.respond(att, rec(att, "firing", "ok"), now=1.0)
    armed = [r.engine.admission.degradation is not None
             for r in fleet.replicas if r.live]
    arm_ok = all(armed) and resp.armed and fleet.aborts == 1
    # availability fires -> dead replica restarted
    actions += resp.respond(avail, rec(avail, "firing", "pending"),
                            now=2.0)
    restart_ok = fleet.restarts == [2]
    # load alert resolves -> policies relaxed back (None here)
    actions += resp.respond(att, rec(att, "resolved", "firing",
                                     severity=None), now=3.0)
    relaxed = [r.engine.admission.degradation is None
               for r in fleet.replicas]
    relax_ok = all(relaxed) and not resp.armed
    kinds = sorted({a["action"] for a in actions})
    ok = arm_ok and restart_ok and relax_ok
    return {"ok": ok, "armed": arm_ok, "restarted": restart_ok,
            "relaxed": relax_ok, "action_kinds": kinds,
            "n_actions": len(actions)}


def check_prom_exposition() -> dict:
    """The exposition is well-formed: every line parses, summaries
    carry _sum/_count."""
    import re

    from apex_tpu.telemetry import MetricsAggregator

    agg = MetricsAggregator()
    for i in range(40):
        agg.record({"event": "request_end", "replica_id": i % 2,
                    "rid": i, "status": "completed", "reason": "eos",
                    "generated": 5, "preemptions": 0, "restarts": 0,
                    "slo_ok": True, "ttft_ms": 10.0 + i,
                    "latency_ms": 100.0 + i})
        agg.record({"event": "reject", "replica_id": i % 2,
                    "code": "queue_full"})
    text = agg.to_prom_text()
    line_re = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
        r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'
        r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})? '
        r'-?[0-9.einf]+$')
    bad_lines = []
    summaries = set()
    for ln in text.splitlines():
        if not ln:
            continue
        if ln.startswith("# TYPE "):
            parts = ln.split()
            if parts[3] == "summary":
                summaries.add(parts[2])
            continue
        if not line_re.match(ln):
            bad_lines.append(ln)
    sums_ok = all(f"{s}_sum" in text and f"{s}_count" in text
                  for s in summaries)
    ok = not bad_lines and sums_ok and summaries
    return {"ok": bool(ok), "bad_lines": bad_lines[:5],
            "summaries": sorted(summaries), "sums_ok": sums_ok}


CHECKS = {
    "hist_accuracy": check_hist_accuracy,
    "merge_order": check_merge_order,
    "aggregation_determinism": check_aggregation_determinism,
    "burn_rate_alert": check_burn_rate_alert,
    "responder_actions": check_responder_actions,
    "prom_exposition": check_prom_exposition,
}


def run_checks(names=None) -> dict:
    out = {"event": "fleet_status_check", "checks": {}}
    ok = True
    for name in (list(names) if names else sorted(CHECKS)):
        res = CHECKS[name]()
        out["checks"][name] = res
        ok = ok and bool(res["ok"])
    out["ok"] = ok
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Fleet health: status table, SLO budgets, alerts")
    ap.add_argument("jsonl", nargs="?",
                    help="telemetry JSONL stream to fold")
    ap.add_argument("--self", action="store_true", dest="self_check",
                    help="run the health plane's built-in smokes")
    ap.add_argument("--check", action="append", choices=sorted(CHECKS),
                    help="restrict --self to specific check(s)")
    ap.add_argument("--json", action="store_true",
                    help="emit machine-readable JSON")
    ap.add_argument("--prom", action="store_true",
                    help="emit the Prometheus text exposition")
    args = ap.parse_args(argv)

    if args.self_check:
        try:
            result = run_checks(args.check)
        except Exception as e:  # infra failure must not read as healthy
            print(f"fleet status check failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(result, indent=2, default=str))
        else:
            for name, res in result["checks"].items():
                status = "PASS" if res["ok"] else "FAIL"
                detail = {k: v for k, v in res.items()
                          if k not in ("ok", "cases", "bad_lines")}
                print(f"{status}  {name}  {detail}")
            print("summary:", json.dumps({"ok": result["ok"]}))
        return 0 if result["ok"] else 1

    if not args.jsonl:
        ap.error("nothing to do: pass a telemetry JSONL file/directory "
                 "or --self")
    try:
        records = load_stream(args.jsonl)
    except OSError as e:
        print(f"cannot read {args.jsonl}: {e}", file=sys.stderr)
        return 2
    agg, trackers, alerts_seen = replay_records(records)
    if args.prom:
        sys.stdout.write(agg.to_prom_text())
    elif args.json:
        print(json.dumps({
            "replicas": {str(k): v for k, v in fleet_table(agg).items()},
            "slos": slo_table(trackers),
            "firing": [t.slo.name for t in trackers if t.firing],
            "alerts_in_stream": alerts_seen,
            "dropped_series": agg.dropped_series,
        }, indent=2, default=str))
    else:
        print(render_status(agg, trackers, alerts_seen))
    return 1 if any(t.firing for t in trackers) else 0


if __name__ == "__main__":
    sys.exit(main())
