"""Static step auditor CLI — trace the repo's own hot paths, gate on findings.

``apex_tpu.analysis`` audits a traced step (jaxpr walk, no execution);
this tool self-hosts it on the steps the performance story depends on:

- ``gpt_step``         the headline bench configuration in miniature
                       (bf16 GPT + packed FusedAdam, donated carry);
- ``fused_block_step``  the PR-9 headline configuration: the same step
                       with the fused transformer-block tail kernels
                       (``ops/fused_block.py``) and the
                       ``selective_elementwise`` remat policy;
- ``packed_adam_step``  the packed FusedAdam sweep (flat fp32 state,
                       masters, in-place Pallas kernels);
- ``packed_lamb_step``  the packed FusedLAMB two-stage step;
- ``ddp_step``         the bucketed flat-buffer gradient lifecycle:
                       shard_map GPT step with GradBuckets psum-per-
                       bucket, flat amp unscale + found_inf, and the
                       packed FusedAdam fed the reduced buffer directly;
- ``tp_step``          the tensor-parallel serving decode step: a
                       ``ServingEngine(tp=2)`` program shard_mapped
                       over the ``(tensor,)`` submesh (head-sharded
                       paged pool, Megatron GEMM sharding,
                       vocab-parallel sampler), donation and callback
                       gating intact through the wrapper;
- ``telemetry_drain``  the in-jit metrics accumulate + cond-gated async
                       drain path;
- ``tp_serving_comm``  the tp_step program again, audited against its
                       declared ``CollectiveBudget`` (the 3-psum pin,
                       the closed ``tensor`` axis set, and a per-gather
                       byte cap — the "no pool-scale gather" invariant,
                       machine-checked);
- ``ddp_comm``         the ddp_step program audited against the
                       bucketed-sync budget: exactly ``n_buckets``
                       psums for gradients plus one for the pmean'd
                       loss, all over the ``data`` axis.

Usage::

    python tools/static_audit.py --self              # table, exit 1 on errors
    python tools/static_audit.py --self --json       # machine-readable
    python tools/static_audit.py --self --target gpt_step
    python tools/static_audit.py --self --fail-on warning

Exit codes (CI contract, like ``tools/health_report.py``): 0 = clean at
the gated severity, 1 = findings at/above it, 2 = infra/usage error. The
JSON output is deterministic (sorted findings, no timestamps) so a
golden-fixture test pins it (``tests/test_static_audit.py``).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

# script-mode invocation (`python tools/static_audit.py ...`) puts tools/
# at sys.path[0]; the repo root must be importable for apex_tpu
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# self-audit targets: (fn, args, audit kwargs) builders. Tracing only —
# tiny configs keep a full CPU run in seconds; the invariants checked
# (donation, gating, aliasing, alignment) are size-independent.
# ---------------------------------------------------------------------------
def build_gpt_step():
    """The headline bench leg's shape: bf16 GPT, packed FusedAdam with
    masters, params+state donated, loss carried (bench.py:bench_gpt)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.transformer.testing import (
        GPTConfig, gpt_loss, init_gpt_params,
    )

    cfg = GPTConfig(
        num_layers=2, num_attention_heads=4, hidden_size=128,
        vocab_size=512, max_position_embeddings=128,
        hidden_dropout=0.0, attention_dropout=0.0,
        compute_dtype=jnp.bfloat16, layer_unroll=-1,
    )
    params = jax.tree_util.tree_map(
        lambda p: p.astype(jnp.bfloat16),
        init_gpt_params(cfg, jax.random.PRNGKey(0)))
    opt = FusedAdam(lr=1e-4, master_weights=True, packed=True,
                    packed_interpret=True)
    opt_state = opt.init(params)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 128), 0, cfg.vocab_size)
    labels = jnp.roll(tokens, -1, axis=1)

    def train_step(params, opt_state, loss_prev):
        loss, grads = jax.value_and_grad(
            lambda p: gpt_loss(cfg, p, tokens, labels))(params)
        params, opt_state = opt.step(grads, opt_state, params)
        return params, opt_state, loss

    step = jax.jit(train_step, donate_argnums=(0, 1))
    return step, (params, opt_state, jnp.float32(0)), {}


def build_fused_block_step():
    """gpt_step with the fused-block tail kernels + selective_elementwise
    remat — the PR-9 headline shape. The kernels run interpreted so the
    REAL pallas calls (and their named scopes / dtype flow) are in the
    traced jaxpr on a CPU host, not the XLA fallback."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.transformer.testing import (
        GPTConfig, gpt_loss, init_gpt_params,
    )

    cfg = GPTConfig(
        num_layers=2, num_attention_heads=4, hidden_size=128,
        vocab_size=512, max_position_embeddings=128,
        hidden_dropout=0.0, attention_dropout=0.0,
        compute_dtype=jnp.bfloat16, layer_unroll=-1,
        fused_block=True, fused_block_interpret=True,
        recompute_granularity="selective_elementwise",
    )
    params = jax.tree_util.tree_map(
        lambda p: p.astype(jnp.bfloat16),
        init_gpt_params(cfg, jax.random.PRNGKey(0)))
    opt = FusedAdam(lr=1e-4, master_weights=True, packed=True,
                    packed_interpret=True)
    opt_state = opt.init(params)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 128), 0, cfg.vocab_size)
    labels = jnp.roll(tokens, -1, axis=1)

    def train_step(params, opt_state, loss_prev):
        loss, grads = jax.value_and_grad(
            lambda p: gpt_loss(cfg, p, tokens, labels))(params)
        params, opt_state = opt.step(grads, opt_state, params)
        return params, opt_state, loss

    step = jax.jit(train_step, donate_argnums=(0, 1))
    return step, (params, opt_state, jnp.float32(0)), {}


def _packed_opt_target(opt_cls, **opt_kw):
    import jax
    import jax.numpy as jnp

    params = {f"w{i}": jnp.zeros((4096,), jnp.bfloat16) for i in range(4)}
    grads = {k: jnp.full((4096,), 1e-3, jnp.bfloat16) for k in params}
    opt = opt_cls(packed=True, packed_interpret=True,
                  packed_chunk_size=4096, master_weights=True, **opt_kw)
    state = opt.init(params)
    step = jax.jit(lambda g, s, p: opt.step(g, s, p), donate_argnums=(1, 2))
    return step, (grads, state, params), {"min_bytes": 4096}


def build_packed_adam_step():
    """The packed FusedAdam sweep: flat fp32 m/v/masters stepped by the
    in-place chunked kernel (ops/packed_optimizer.packed_adam_apply)."""
    from apex_tpu.optimizers import FusedAdam

    return _packed_opt_target(FusedAdam, lr=1e-3)


def build_packed_lamb_step():
    """The packed FusedLAMB two-stage step (stage1 + per-tensor trust
    ratios via segment_sum + scale_update)."""
    from apex_tpu.optimizers import FusedLAMB

    return _packed_opt_target(FusedLAMB, lr=1e-3)


def build_ddp_step():
    """The bucketed flat-buffer gradient lifecycle (ISSUE-14), fused
    spelling: bf16 GPT under shard_map on a 'data' mesh, grads
    bucket-reduced RAW (GradBuckets / one psum per bucket,
    gradient_average deferred), read-only ``found_inf_flat`` off the
    bucket buffers, and ONE ``step_flat`` update sweep — the bucket
    concat arrives lazily (BucketBuffers), unscale + average ride
    ``grad_scale`` into the kernel's inv_scale, overflow skip is the
    kernels' in-sweep noop flag, and next-step params are master-buffer
    views. params+state+scaler donated. The invariants gated: bucket
    buffers donated through to the aliased kernels (no
    double-donation), ONE fp32 upcast for the whole lifecycle (no
    double_cast round-trips), no ungated callbacks, and the bucketed
    PackSpec's layout legality (chunk-aligned bucket bounds)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from apex_tpu.amp import LossScaler
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.parallel import DistributedDataParallel, GradBuckets
    from apex_tpu.transformer.testing import (
        GPTConfig, gpt_loss, init_gpt_params,
    )

    cfg = GPTConfig(
        num_layers=2, num_attention_heads=4, hidden_size=128,
        vocab_size=512, max_position_embeddings=128,
        hidden_dropout=0.0, attention_dropout=0.0,
        compute_dtype=jnp.bfloat16, layer_unroll=-1,
    )
    params = jax.tree_util.tree_map(
        lambda p: p.astype(jnp.bfloat16),
        init_gpt_params(cfg, jax.random.PRNGKey(0)))
    buckets = GradBuckets(params, bucket_cap_mb=0.5)
    opt = FusedAdam(lr=1e-4, master_weights=True, packed=True,
                    packed_interpret=True, packed_spec=buckets.spec)
    opt_state = opt.init(params)
    # gradient_average=False: the /world is deferred into grad_scale
    # (the fused lifecycle's one multiply)
    ddp = DistributedDataParallel(axis_name="data",
                                  gradient_average=False,
                                  bucket_cap_mb=0.5)
    world = len(jax.devices())
    scaler = LossScaler(loss_scale="dynamic", init_scale=2.0 ** 4)
    sstate = scaler.init_state()
    # batch divisible by any world size the audit runs under (1 device
    # standalone, 8 under the pytest harness)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (8, 128), 0, cfg.vocab_size)
    labels = jnp.roll(tokens, -1, axis=1)
    mesh = Mesh(np.array(jax.devices()), ("data",))

    def shard_step(params, opt_state, sstate, tokens, labels):
        def scaled_loss(p):
            loss = gpt_loss(cfg, p, tokens, labels)
            return scaler.scale_loss(sstate, loss.astype(jnp.float32))

        _, grads = jax.value_and_grad(scaled_loss)(params)
        bufs, _ = ddp.reduce_flat(grads, buckets=buckets, concat=False)
        new_sstate = scaler.found_inf_flat(sstate, bufs)
        new_opt_state = opt.step_flat(
            bufs, opt_state, found_inf=new_sstate.found_inf,
            grad_scale=new_sstate.loss_scale * world)
        params = buckets.unpack(new_opt_state.master_params)
        opt_state = new_opt_state
        new_sstate = scaler.update_scale(new_sstate)
        loss = jax.lax.pmean(
            gpt_loss(cfg, params, tokens, labels).astype(jnp.float32),
            "data")
        return params, opt_state, new_sstate, loss

    wrapped = shard_map(
        shard_step, mesh=mesh,
        in_specs=(P(), P(), P(), P("data"), P("data")),
        out_specs=(P(), P(), P(), P()),
        check_rep=False)
    step = jax.jit(lambda p, s, ss: wrapped(p, s, ss, tokens, labels),
                   donate_argnums=(0, 1, 2))
    return step, (params, opt_state, sstate), {}


def build_tp_step():
    """The tensor-parallel serving decode step (ISSUE-16): a
    ``ServingEngine(tp=N)`` 1-token program — shard_mapped over the
    ``(tensor,)`` submesh with the head-sharded paged pool, Megatron
    column/row GEMM sharding and the vocab-parallel sampler. tp=2 when
    the host exposes >= 2 devices (the pytest harness forces 8 virtual
    CPU devices), else the tp=1 program (identical code path, no
    collectives). Gated invariants: KV/slot/metrics still donated
    through the shard_map wrapper, telemetry callback still cond-gated
    (and OUTSIDE the shard_map), pool PackSpec chunk-aligned per
    shard."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.serving import ServingEngine
    from apex_tpu.transformer.testing import GPTConfig, init_gpt_params

    tp = 2 if len(jax.devices()) >= 2 else 1
    cfg = GPTConfig(
        num_layers=2, num_attention_heads=4, hidden_size=64,
        vocab_size=128, max_position_embeddings=64,
        hidden_dropout=0.0, attention_dropout=0.0,
        compute_dtype=jnp.float32,
    )
    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    eng = ServingEngine(cfg, params, n_slots=2, tp=tp,
                        use_kernel=False, telemetry_every=4)
    fn, args = eng.step_program()
    return fn, args, {"pack_specs": [eng.spec.pack_spec],
                      "shard_count": eng.tp}


def build_telemetry_drain():
    """The sync-free metrics path: on-device accumulate + the async
    drain that must stay behind lax.cond (telemetry/metrics.py)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu import telemetry

    sink = telemetry.NullRecorder()

    def step(metrics, loss):
        metrics = telemetry.accumulate(metrics, loss=loss, tokens=256)
        metrics = telemetry.drain(metrics, sink, every_n=10)
        return metrics, loss * jnp.float32(0.5)

    jitted = jax.jit(step, donate_argnums=(0,))
    return jitted, (telemetry.init_metrics(), jnp.float32(0)), {}


def build_tp_serving_comm():
    """The tp_step program under its declared communication contract
    (ISSUE-19): the decode program may contain exactly 3 psums (attn
    row-GEMM tail, MLP row-GEMM tail, vocab-parallel sampler), 2
    all_gathers and one pmax/pmin pair (the sampler's cross-shard
    argmax plumbing), all over the ``tensor`` axis only, and no single
    gather may materialize >= 1 MiB (the pool-scale-gather ban from
    ISSUE-16, previously only a grep over the jaxpr text). At tp=1 the
    same program must contain NO collectives at all."""
    fn, args, kw = build_tp_step()

    from apex_tpu.analysis import CollectiveBudget

    if (kw.get("shard_count") or 1) > 1:
        budget = CollectiveBudget(
            counts={"psum": 3, "all_gather": 2, "pmax": 1, "pmin": 1},
            axes=("tensor",), max_gather_bytes=1 << 20)
    else:
        budget = CollectiveBudget(counts={}, axes=())
    return fn, args, dict(kw, collective_budget=budget)


def build_ddp_comm():
    """The ddp_step program under the bucketed gradient-sync budget:
    exactly ``n_buckets`` psums for the flat gradient buffers plus one
    for the pmean'd loss (pmean lowers to psum + divide), every one of
    them over the ``data`` axis — the machine form of the PR-14
    psum-count==n_buckets jaxpr pin."""
    fn, args, kw = build_ddp_step()

    from apex_tpu.parallel import DistributedDataParallel, GradBuckets

    buckets = GradBuckets(args[0], bucket_cap_mb=0.5)
    ddp = DistributedDataParallel(axis_name="data",
                                  gradient_average=False,
                                  bucket_cap_mb=0.5)
    # +1: the pmean'd loss rides the same axis outside the buckets
    budget = ddp.collective_budget(buckets, extra_psums=1)
    return fn, args, dict(kw, collective_budget=budget)


TARGETS = {
    "gpt_step": build_gpt_step,
    "fused_block_step": build_fused_block_step,
    "packed_adam_step": build_packed_adam_step,
    "packed_lamb_step": build_packed_lamb_step,
    "ddp_step": build_ddp_step,
    "tp_step": build_tp_step,
    "telemetry_drain": build_telemetry_drain,
    "tp_serving_comm": build_tp_serving_comm,
    "ddp_comm": build_ddp_comm,
}


def run_self_audit(targets=None, rules=None):
    """Audit every (selected) self-target; returns the stable result dict."""
    from apex_tpu import analysis

    names = list(targets) if targets else sorted(TARGETS)
    out = {"event": "static_audit", "targets": {}}
    ok = True
    for name in names:
        fn, args, kw = TARGETS[name]()
        if rules:
            kw = dict(kw, rules=rules)
        report = analysis.audit_step(fn, *args, name=name, **kw)
        out["targets"][name] = report.to_dict()
        ok = ok and report.ok
    out["ok"] = ok
    return out


def summarize(result: dict) -> dict:
    """The one-line summary bench.py/compare_bench.py embed: counts per
    severity plus the distinct finding codes (stable, sorted)."""
    counts = {"error": 0, "warning": 0, "info": 0}
    codes = set()
    for t in result["targets"].values():
        for sev, n in t["counts"].items():
            counts[sev] += n
        codes.update(f["code"] for f in t["findings"])
    return {"ok": result["ok"], **counts, "codes": sorted(codes)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Static jaxpr audit of apex_tpu's own training steps")
    ap.add_argument("--self", action="store_true", dest="self_audit",
                    help="audit the repo's headline steps (required mode)")
    ap.add_argument("--target", action="append", choices=sorted(TARGETS),
                    help="restrict to specific target(s)")
    ap.add_argument("--rules", help="comma-separated rule subset "
                                    "(default: all)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full result as JSON")
    ap.add_argument("--fail-on", choices=["error", "warning"],
                    default="error",
                    help="exit non-zero at this severity (default error)")
    args = ap.parse_args(argv)
    if not args.self_audit:
        ap.error("nothing to do: pass --self (audit the repo's own steps)")

    rules = tuple(r for r in (args.rules or "").split(",") if r) or None
    try:
        result = run_self_audit(targets=args.target, rules=rules)
    except Exception as e:  # infra failure must not read as "clean"
        print(f"static audit failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(result, indent=2))
    else:
        from apex_tpu.analysis import AuditReport, Finding

        for name, t in result["targets"].items():
            rep = AuditReport(name, [
                Finding(f["rule"], f["code"], f["severity"], f["message"],
                        f.get("where", ""), f.get("data"))
                for f in t["findings"]], tuple(t["rules_run"]))
            print(rep.table())
            print()
        print("summary:", json.dumps(summarize(result)))

    gate = {"error": ("error",), "warning": ("error", "warning")}[args.fail_on]
    bad = sum(t["counts"][s] for t in result["targets"].values()
              for s in gate)
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
