"""Serving smoke — fast CI proof the paged-KV decode engine is correct.

Like ``tools/static_audit.py --self`` and ``tools/resilience_check.py``,
this self-hosts the subsystem on a tiny model, small enough for the
tier-1 CPU lane:

- ``decode_parity``   the flash-decode kernel (interpret mode — the
                      REAL kernel body) and the XLA fallback both match
                      the dense gathered reference on ragged page
                      tables, including empty (fully-masked) slots.
- ``token_identity``  ``ServingEngine.generate`` over a staggered
                      continuous-batching trace (admits mid-flight,
                      evictions, shared slots) is token-identical to the
                      per-request dense-attention greedy decode loop
                      (``serving.reference_decode`` — the full training
                      forward recomputed per token).

Prefix-cache / chunked-prefill legs (ISSUE-12 — the token-identity
oracle extended verbatim):

- ``chunked_prefill_identity``  the SAME staggered trace run at
                      several ``prefill_chunk`` sizes (including a
                      chunk larger than any prompt) emits exactly the
                      token-at-a-time engine's tokens — chunked prompt
                      ingestion changes step count, never content.
- ``prefix_hit_identity``  requests sharing prompt heads (and one
                      exact-duplicate prompt) run twice on one engine:
                      the warm pass MUST hit the radix/hash prefix
                      cache (skipping that prefill work) and both
                      passes MUST be byte-identical to the cold dense
                      reference; the duplicate's first decode write
                      exercises the COW fork; zero reader-held pages
                      remain.
- ``step_audit``      the jitted decode step passes the PR-4 static
                      auditor clean: KV cache / slot state / metrics
                      donated, no ungated callbacks, PackSpec layout
                      verified — with the in-jit telemetry drain ARMED,
                      so the cond-gating is what is being audited.

Sampling / speculative-decoding legs (ISSUE-13 — tokens/step > 1
without giving up the identity oracle):

- ``spec_greedy_identity``  greedy decode with ``spec_k > 0`` (n-gram
                      draft -> one-pass verify -> longest-matched-
                      prefix accept) is token-identical to plain
                      greedy on the staggered trace, AND on a
                      repetition-heavy trace it must actually accept:
                      fewer engine steps, decode tokens/step > 1.
- ``sampled_seeded_identity``  temperature/top-k/top-p decode with the
                      carried (seed, rid, position) hash-counter PRNG
                      is byte-identical to the seeded dense reference
                      (``reference_sample_decode``), speculation off
                      and on, greedy riders in the same batch.

Chaos legs (``serving.robustness`` + ``resilience.ServingChaos`` — the
engine must DEGRADE, not corrupt, under injected faults):

- ``poison_quarantine``  a chaos-poisoned (non-finite-logits) request
                         terminates ``FAILED`` with slot/step
                         provenance while every other request's tokens
                         stay identical to the dense greedy reference;
                         zero page leaks.
- ``timeout_eviction``   a request past its latency budget is evicted
                         and finalized ``TIMED_OUT`` (pages freed,
                         structured ``request_end`` event) while the
                         unbudgeted request completes token-identically.
- ``kill_recover``       a chaos kill mid-flight + ``recover_from``:
                         the fresh engine replays all in-flight
                         requests to completion, token-identical to an
                         uninterrupted run.

Fleet legs (``serving.fleet`` — ISSUE-11: the multi-replica router
must hold the zero-loss contract under replica outages):

- ``fleet_kill_migrate``  3 CPU-faked replicas, one killed mid-storm
                          by ``ServingChaos.kill_replica_at``: every
                          in-flight request of the dead replica
                          migrates to the survivors on the replay
                          carrier and completes token-identical to an
                          undisturbed run — requests_lost MUST be 0.
- ``fleet_drain_join``    a rolling weight update mid-traffic: each
                          replica drains, swaps weights via
                          ``cast_params_for_inference``, rejoins —
                          zero dropped requests, and post-update
                          traffic decodes per the NEW weights.

Real-process fleet leg (``serving.proc_fleet`` — ISSUE-20: the same
zero-loss contract against replicas that actually DIE):

- ``proc_fleet_failover`` 3 worker SUBPROCESSES (one ServingEngine
                          each, framed pipe transport + heartbeat
                          files): one is SIGKILLed MID-FRAME and
                          another's heartbeat wedged in the same run —
                          the FleetSupervisor detects death (exit) and
                          hang (staleness), restarts both, migrates
                          their in-flight work on the replay carrier:
                          requests_lost == 0, every token
                          byte-identical to the dense reference, torn
                          reply frame + torn telemetry line counted,
                          zero page leaks.

Tensor-parallel leg (ISSUE-16 — the identity oracle over the TP
sharding):

- ``tp_identity``     ``ServingEngine(tp=2/4)`` on the virtual-device
                      CPU mesh is byte-identical to the tp=1 engine
                      across a staggered trace with chunked prefill,
                      speculation, mixed sampled/greedy slots and
                      forced preemption — and each TP program's jaxpr
                      carries exactly 3 psums (2 sublayer tails + 1
                      fused sampler reduction).

Usage::

    python tools/serving_check.py --self           # table, exit 1 on fail
    python tools/serving_check.py --self --json
    python tools/serving_check.py --self --check decode_parity

Exit codes (CI contract, same as static_audit/resilience_check): 0 = all
checks pass, 1 = a check failed, 2 = infra/usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

# script-mode invocation (`python tools/serving_check.py ...`) puts
# tools/ at sys.path[0]; the repo root must be importable for apex_tpu
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _tiny_cfg():
    import jax.numpy as jnp

    from apex_tpu.transformer.testing import GPTConfig

    return GPTConfig(
        num_layers=2, hidden_size=64, num_attention_heads=4,
        vocab_size=128, max_position_embeddings=64,
        hidden_dropout=0.0, attention_dropout=0.0,
        params_dtype=jnp.float32, compute_dtype=jnp.float32)


def _tiny_params(cfg):
    import jax

    from apex_tpu.transformer.testing import init_gpt_params

    params = init_gpt_params(cfg, jax.random.PRNGKey(0))
    # position-sensitive continuations (a plain random init greedy-
    # decodes into a fixed point, which would under-exercise the cache)
    params["embedding"]["position"] = (
        params["embedding"]["position"] * 40.0)
    return params


def check_decode_parity() -> dict:
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.ops.flash_decode import (
        flash_decode, paged_decode_reference,
    )

    rng = np.random.default_rng(0)
    P, n, ps, d, B, mp = 8, 4, 16, 16, 5, 3
    k_pages = jnp.asarray(rng.normal(size=(P, n, ps, d)), jnp.float32)
    v_pages = jnp.asarray(rng.normal(size=(P, n, ps, d)), jnp.float32)
    q = jnp.asarray(rng.normal(size=(B, n, d)), jnp.float32)
    pt = jnp.asarray(rng.integers(1, P, size=(B, mp)), jnp.int32)
    lens = jnp.asarray([0, 5, 16, 33, 48], jnp.int32)

    ref = np.asarray(paged_decode_reference(q, k_pages, v_pages, pt, lens))
    xla = np.asarray(flash_decode(q, k_pages, v_pages, pt, lens,
                                  use_kernel=False))
    kern = np.asarray(flash_decode(q, k_pages, v_pages, pt, lens,
                                   interpret=True))
    xla_err = float(np.abs(xla - ref).max())
    kern_err = float(np.abs(kern - ref).max())
    empty_zero = float(np.abs(kern[0]).max()) == 0.0
    ok = xla_err < 1e-5 and kern_err < 1e-4 and empty_zero
    return {"ok": ok, "xla_max_err": xla_err, "kernel_max_err": kern_err,
            "empty_slot_zero": empty_zero}


def check_token_identity() -> dict:
    import numpy as np

    from apex_tpu.serving import Request, ServingEngine, reference_decode

    cfg = _tiny_cfg()
    params = _tiny_params(cfg)
    rng = np.random.default_rng(7)
    lens = (14, 11, 13, 9)
    reqs = [
        Request(prompt=list(rng.integers(0, cfg.vocab_size, size=L)),
                max_new_tokens=8, arrival_step=2 * i)
        for i, L in enumerate(lens)
    ]
    # tiny pool -> real continuous batching: shared slots, staggered
    # admits, at least the possibility of preemption
    eng = ServingEngine(cfg, params, n_slots=2, num_pages=5,
                        max_prompt_len=16)
    out = eng.generate(reqs, max_steps=2000)
    eng.scheduler.check_invariants()
    mismatches = []
    for r in reqs:
        ref = reference_decode(cfg, params, r.prompt, r.max_new_tokens)
        if out[r.rid] != ref:
            mismatches.append({"rid": r.rid, "engine": out[r.rid],
                               "reference": ref})
    ok = (not mismatches
          and eng.last_stats["completed"] == len(reqs)
          and eng.scheduler.allocator.used_count == 0)
    return {"ok": ok, "mismatches": mismatches,
            "steps": eng.last_stats["steps"],
            "occupancy": eng.last_stats["occupancy"],
            "preemptions": eng.last_stats["preemptions"]}


def check_chunked_prefill_identity() -> dict:
    import numpy as np

    from apex_tpu.serving import Request, ServingEngine, reference_decode

    cfg = _tiny_cfg()
    params = _tiny_params(cfg)

    def mk():
        rng = np.random.default_rng(7)
        return [
            Request(prompt=list(rng.integers(0, cfg.vocab_size, size=L)),
                    max_new_tokens=8, arrival_step=2 * i)
            for i, L in enumerate((14, 11, 13, 9))
        ]

    refs = {i: reference_decode(cfg, params, r.prompt, r.max_new_tokens)
            for i, r in enumerate(mk())}
    mismatches, steps = [], {}
    for chunk in (1, 3, 8, 16):
        reqs = mk()
        # tiny pool: the chunked path must survive real continuous
        # batching (shared slots, preemption) too, not just ingestion
        eng = ServingEngine(cfg, params, n_slots=2, num_pages=5,
                            max_prompt_len=16, prefill_chunk=chunk)
        out = eng.generate(reqs, max_steps=2000)
        eng.scheduler.check_invariants()
        steps[chunk] = eng.last_stats["steps"]
        for i, r in enumerate(reqs):
            if out[r.rid] != refs[i]:
                mismatches.append({"chunk": chunk, "req": i,
                                   "engine": out[r.rid],
                                   "reference": refs[i]})
        if eng.scheduler.allocator.used_count != 0:
            mismatches.append({"chunk": chunk, "page_leaks":
                               eng.scheduler.allocator.used_count})
    # chunked ingestion must actually shorten the trace
    speedup_ok = steps[8] < steps[1]
    ok = not mismatches and speedup_ok
    return {"ok": ok, "mismatches": mismatches, "steps_by_chunk": steps,
            "chunked_fewer_steps": speedup_ok}


def check_prefix_hit_identity() -> dict:
    import numpy as np

    from apex_tpu.serving import Request, ServingEngine, reference_decode

    cfg = _tiny_cfg()
    params = _tiny_params(cfg)
    rng = np.random.default_rng(11)
    head = list(rng.integers(0, cfg.vocab_size, size=32))
    prompts = [
        head[:32] + list(rng.integers(0, cfg.vocab_size, size=6)),
        head[:32] + list(rng.integers(0, cfg.vocab_size, size=4)),
        list(head[:32]),   # page-aligned full-prompt duplicate (COW)
    ]
    eng = ServingEngine(cfg, params, n_slots=2, num_pages=24,
                        prefill_chunk=4)
    cold = [Request(prompt=list(p), max_new_tokens=6) for p in prompts]
    out_cold = eng.generate(cold, max_steps=2000)
    warm = [Request(prompt=list(p), max_new_tokens=6) for p in prompts]
    out_warm = eng.generate(warm, max_steps=2000)
    eng.scheduler.check_invariants()
    st = eng.last_stats["prefix_cache"]
    mismatches = []
    for p, c, w in zip(prompts, cold, warm):
        ref = reference_decode(cfg, params, p, 6)
        if out_cold[c.rid] != ref:
            mismatches.append({"pass": "cold", "engine": out_cold[c.rid],
                               "reference": ref})
        if out_warm[w.rid] != ref:
            mismatches.append({"pass": "warm", "engine": out_warm[w.rid],
                               "reference": ref})
    ok = (not mismatches
          and st["hits"] == len(prompts)           # every warm prompt hit
          and st["hit_tokens"] >= 3 * 32           # at least the heads
          and eng.scheduler.allocator.used_count == 0)
    return {"ok": ok, "mismatches": mismatches, "prefix_cache": st,
            "page_leaks": eng.scheduler.allocator.used_count}


def check_spec_greedy_identity() -> dict:
    """The lossless contract: speculative decoding (``spec_k > 0``)
    under greedy sampling is TOKEN-IDENTICAL to plain greedy decode —
    on the staggered continuous-batching trace (tiny pool: shared
    slots, possible preemption) AND on a repetition-heavy trace where
    drafting actually accepts (position-independent model -> cyclic
    greedy decode), where it must also finish in fewer engine steps
    with decode tokens/step > 1."""
    import numpy as np

    from apex_tpu.serving import Request, ServingEngine, reference_decode

    cfg = _tiny_cfg()
    params = _tiny_params(cfg)

    def mk():
        rng = np.random.default_rng(7)
        return [
            Request(prompt=list(rng.integers(0, cfg.vocab_size, size=L)),
                    max_new_tokens=8, arrival_step=2 * i)
            for i, L in enumerate((14, 11, 13, 9))
        ]

    refs = {i: reference_decode(cfg, params, r.prompt, r.max_new_tokens)
            for i, r in enumerate(mk())}
    mismatches = []
    reqs = mk()
    eng = ServingEngine(cfg, params, n_slots=2, num_pages=6,
                        max_prompt_len=16, spec_k=3)
    out = eng.generate(reqs, max_steps=2000)
    eng.scheduler.check_invariants()
    for i, r in enumerate(reqs):
        if out[r.rid] != refs[i]:
            mismatches.append({"req": i, "engine": out[r.rid],
                               "reference": refs[i]})
    if eng.scheduler.allocator.used_count:
        mismatches.append({"page_leaks":
                           eng.scheduler.allocator.used_count})
    # the accepting half: a cyclic (position-free) model repeats, so
    # the n-gram draft nails the continuation — speculation must BOTH
    # stay lossless and actually go below one pass per token
    import jax

    cyc = jax.tree_util.tree_map(lambda x: x, params)
    cyc["embedding"]["position"] = params["embedding"]["position"] * 0.0
    rng = np.random.default_rng(3)
    prompt = list(rng.integers(0, cfg.vocab_size, size=8))
    ref = reference_decode(cfg, cyc, prompt, 24)
    stats = {}
    for k in (0, 4):
        req = Request(prompt=list(prompt), max_new_tokens=24)
        eng = ServingEngine(cfg, cyc, n_slots=2, num_pages=12,
                            max_prompt_len=48, prefill_chunk=4,
                            spec_k=k)
        out = eng.generate([req], max_steps=500)
        eng.scheduler.check_invariants()
        if out[req.rid] != ref:
            mismatches.append({"cyclic_spec_k": k, "engine": out[req.rid],
                               "reference": ref})
        stats[k] = {"steps": eng.last_stats["steps"],
                    "accept_rate": eng.last_stats["accept_rate"],
                    "tokens_per_step": eng.last_stats["tokens_per_step"]}
    speedup_ok = stats[4]["steps"] < stats[0]["steps"]
    accept_ok = ((stats[4]["accept_rate"] or 0) > 0
                 and (stats[4]["tokens_per_step"] or 0) > 1)
    ok = not mismatches and speedup_ok and accept_ok
    return {"ok": ok, "mismatches": mismatches,
            "cyclic_stats": stats, "spec_fewer_steps": speedup_ok,
            "spec_accepting": accept_ok}


def check_sampled_seeded_identity() -> dict:
    """Non-greedy decode is BYTE-identical to the seeded dense
    reference (``reference_sample_decode``: same temperature/top-k/
    top-p filters, same (seed, rid, position) hash-counter draws) —
    with speculation off AND on, across a mixed sampled/greedy batch
    on a tiny pool."""
    import numpy as np

    from apex_tpu.serving import (
        Request, SamplingParams, ServingEngine, reference_sample_decode,
    )

    cfg = _tiny_cfg()
    params = _tiny_params(cfg)
    sps = [SamplingParams(temperature=0.9, top_k=20, seed=11),
           SamplingParams(temperature=1.2, top_p=0.85, seed=42),
           None,  # greedy rider in the same batch
           SamplingParams(temperature=0.7, top_k=12, top_p=0.9, seed=7)]

    def mk():
        rng = np.random.default_rng(5)
        return [Request(prompt=list(rng.integers(0, cfg.vocab_size,
                                                 size=L)),
                        max_new_tokens=8, arrival_step=i, sampling=sp,
                        rid=31_000 + i)
                for i, (L, sp) in enumerate(zip((12, 9, 11, 8), sps))]

    refs = {i: reference_sample_decode(cfg, params, r.prompt,
                                       r.max_new_tokens,
                                       sampling=r.sampling, rid=r.rid)
            for i, r in enumerate(mk())}
    mismatches = []
    for k in (0, 3):
        reqs = mk()
        eng = ServingEngine(cfg, params, n_slots=2, num_pages=6,
                            max_prompt_len=16, prefill_chunk=3,
                            spec_k=k)
        out = eng.generate(reqs, max_steps=2000)
        eng.scheduler.check_invariants()
        for i, r in enumerate(reqs):
            if out[r.rid] != refs[i]:
                mismatches.append({"spec_k": k, "req": i,
                                   "engine": out[r.rid],
                                   "reference": refs[i]})
        if eng.scheduler.allocator.used_count:
            mismatches.append({"spec_k": k, "page_leaks":
                               eng.scheduler.allocator.used_count})
    return {"ok": not mismatches, "mismatches": mismatches}


def check_step_audit() -> dict:
    from apex_tpu.serving import ServingEngine
    from apex_tpu.telemetry import RingBufferRecorder

    cfg = _tiny_cfg()
    params = _tiny_params(cfg)
    # prefill_chunk > 1 and spec_k > 0 arm ALL THREE programs — the
    # audit covers the 1-token, chunked-prefill and speculative steps
    eng = ServingEngine(cfg, params, n_slots=2, num_pages=8,
                        max_prompt_len=16, telemetry_every=4,
                        prefill_chunk=3, spec_k=2,
                        sink=RingBufferRecorder())
    try:
        report = eng.audit()
    except AssertionError as e:
        return {"ok": False, "error": str(e)[:2000]}
    return {"ok": report.ok, **report.counts(),
            "codes": sorted(set(report.codes()))}


def check_poison_quarantine() -> dict:
    import numpy as np

    from apex_tpu.resilience import ServingChaos
    from apex_tpu.serving import (
        Request, RequestStatus, ServingEngine, reference_decode,
    )
    from apex_tpu.telemetry import RingBufferRecorder

    cfg = _tiny_cfg()
    params = _tiny_params(cfg)
    rng = np.random.default_rng(13)
    reqs = [
        Request(prompt=list(rng.integers(0, cfg.vocab_size, size=L)),
                max_new_tokens=6)
        for L in (6, 9, 4)
    ]
    chaos = ServingChaos().poison_request(reqs[1].rid, at_step=7)
    ring = RingBufferRecorder()
    eng = ServingEngine(cfg, params, n_slots=2, num_pages=12,
                        max_prompt_len=16, chaos=chaos, sink=ring)
    out = eng.generate(list(reqs), max_steps=2000)
    eng.scheduler.check_invariants()
    victim = reqs[1]
    mismatches = []
    for r in (reqs[0], reqs[2]):
        ref = reference_decode(cfg, params, r.prompt, r.max_new_tokens)
        if out[r.rid] != ref:
            mismatches.append({"rid": r.rid, "engine": out[r.rid],
                               "reference": ref})
    fails = [e for e in ring.events("request_end")
             if e["status"] == "failed"]
    ok = (victim.status is RequestStatus.FAILED
          and (victim.failure or {}).get("kind") == "nonfinite_logits"
          and (victim.failure or {}).get("step") == 7
          and not mismatches
          and len(fails) == 1
          and eng.scheduler.allocator.used_count == 0)
    return {"ok": ok, "victim_status": victim.status.value,
            "failure": victim.failure, "mismatches": mismatches,
            "page_leaks": eng.scheduler.allocator.used_count}


def check_timeout_eviction() -> dict:
    import numpy as np

    from apex_tpu.serving import (
        Request, RequestStatus, ServingEngine, VirtualClock,
        reference_decode,
    )
    from apex_tpu.telemetry import RingBufferRecorder

    cfg = _tiny_cfg()
    params = _tiny_params(cfg)
    rng = np.random.default_rng(17)
    free = Request(prompt=list(rng.integers(0, cfg.vocab_size, size=6)),
                   max_new_tokens=6)
    # one slot: the budgeted request waits behind `free` and expires
    hurried = Request(
        prompt=list(rng.integers(0, cfg.vocab_size, size=6)),
        max_new_tokens=6, latency_budget_ms=5000.0)
    ring = RingBufferRecorder()
    eng = ServingEngine(cfg, params, n_slots=1, num_pages=8,
                        max_prompt_len=16, clock=VirtualClock(dt=1.0),
                        sink=ring)
    out = eng.generate([free, hurried], max_steps=500)
    eng.scheduler.check_invariants()
    ref = reference_decode(cfg, params, free.prompt, free.max_new_tokens)
    touts = [e for e in ring.events("request_end")
             if e["status"] == "timed_out"]
    ok = (hurried.status is RequestStatus.TIMED_OUT
          and free.status is RequestStatus.COMPLETED
          and out[free.rid] == ref
          and len(touts) == 1 and touts[0]["rid"] == hurried.rid
          and eng.scheduler.allocator.used_count == 0)
    return {"ok": ok, "hurried_status": hurried.status.value,
            "hurried_reason": hurried.end_reason,
            "page_leaks": eng.scheduler.allocator.used_count}


def check_kill_recover() -> dict:
    import numpy as np

    from apex_tpu.resilience import ChaosError, ServingChaos
    from apex_tpu.serving import (
        Request, RequestStatus, ServingEngine, reference_decode,
    )

    cfg = _tiny_cfg()
    params = _tiny_params(cfg)
    rng = np.random.default_rng(23)
    reqs = [
        Request(prompt=list(rng.integers(0, cfg.vocab_size, size=L)),
                max_new_tokens=6, arrival_step=i)
        for i, L in enumerate((8, 5, 11))
    ]
    chaos = ServingChaos().kill_engine_at(10)
    eng = ServingEngine(cfg, params, n_slots=2, num_pages=12,
                        max_prompt_len=16, chaos=chaos)
    died = False
    try:
        eng.generate(list(reqs), max_steps=2000)
    except ChaosError:
        died = True
    if not died:
        return {"ok": False, "error": "chaos kill did not fire"}
    eng2, survivors = ServingEngine.recover_from(eng)
    eng2.generate(survivors, max_steps=2000)
    eng2.scheduler.check_invariants()
    mismatches = []
    for r in reqs:
        ref = reference_decode(cfg, params, r.prompt, r.max_new_tokens)
        if list(r.out_tokens) != ref:
            mismatches.append({"rid": r.rid, "engine": list(r.out_tokens),
                               "reference": ref})
    ok = (not mismatches
          and all(r.status is RequestStatus.COMPLETED for r in reqs)
          and len(survivors) >= 1
          and eng2.scheduler.allocator.used_count == 0)
    return {"ok": ok, "recovered": len(survivors),
            "restarts": [r.restarts for r in reqs],
            "mismatches": mismatches,
            "page_leaks": eng2.scheduler.allocator.used_count}


def check_fleet_kill_migrate() -> dict:
    import numpy as np

    from apex_tpu.resilience import ServingChaos
    from apex_tpu.serving import (
        ReplicaFleet, ReplicaState, Request, RequestStatus,
        reference_decode,
    )
    from apex_tpu.telemetry import RingBufferRecorder

    cfg = _tiny_cfg()
    params = _tiny_params(cfg)
    rng = np.random.default_rng(29)
    reqs = [
        Request(prompt=list(rng.integers(0, cfg.vocab_size,
                                         size=int(rng.integers(4, 12)))),
                max_new_tokens=6, arrival_step=i)
        for i in range(9)
    ]
    chaos = ServingChaos().kill_replica_at(1, 6)
    ring = RingBufferRecorder()
    fleet = ReplicaFleet(cfg, params, n_replicas=3, sink=ring,
                         chaos=chaos, n_slots=2, num_pages=12,
                         max_prompt_len=24)
    out = fleet.generate(reqs, max_steps=3000)
    fleet.check_invariants()
    st = fleet.last_stats
    migrated_rids = {e["rid"] for e in ring.events("migrate")}
    mismatches = []
    for r in reqs:
        ref = reference_decode(cfg, params, r.prompt, r.max_new_tokens)
        if out[r.rid] != ref:
            mismatches.append({"rid": r.rid, "engine": out[r.rid],
                               "reference": ref})
    ok = (st["replica_deaths"] == 1
          and st["requests_lost"] == 0
          and st["migrated"] >= 1
          and bool(migrated_rids)
          and fleet.replicas[1].state is ReplicaState.DEAD
          and not mismatches
          and all(r.status is RequestStatus.COMPLETED for r in reqs)
          and fleet.page_leaks() == 0)
    return {"ok": ok, "requests_lost": st["requests_lost"],
            "migrated": st["migrated"],
            "replica_deaths": st["replica_deaths"],
            "mismatches": mismatches, "page_leaks": fleet.page_leaks()}


def check_fleet_drain_join() -> dict:
    import jax
    import numpy as np

    from apex_tpu.serving import (
        ReplicaFleet, Request, RequestStatus, reference_decode,
    )
    from apex_tpu.telemetry import RingBufferRecorder

    cfg = _tiny_cfg()
    params = _tiny_params(cfg)
    params2 = jax.tree_util.tree_map(lambda x: x, params)
    params2["embedding"]["position"] = (
        params["embedding"]["position"] * 0.5)
    rng = np.random.default_rng(31)
    ring = RingBufferRecorder()
    fleet = ReplicaFleet(cfg, params, n_replicas=2, sink=ring,
                         n_slots=2, num_pages=12, max_prompt_len=16)
    phase1 = [Request(prompt=list(rng.integers(0, cfg.vocab_size,
                                               size=6)),
                      max_new_tokens=5, arrival_step=i)
              for i in range(4)]
    fleet.schedule_rolling_update(params2)
    out1 = fleet.generate(phase1, max_steps=2000)
    st = fleet.last_stats
    swaps = ring.events("weight_swap")
    # zero-drop contract: every phase-1 request completed (on the old
    # or new weights, depending on when its replica swapped)
    drops = [r.rid for r in phase1
             if r.status is not RequestStatus.COMPLETED]
    mismatches = []
    for r in phase1:
        refs = (reference_decode(cfg, params, r.prompt,
                                 r.max_new_tokens),
                reference_decode(cfg, params2, r.prompt,
                                 r.max_new_tokens))
        if out1[r.rid] not in refs:
            mismatches.append({"rid": r.rid, "engine": out1[r.rid]})
    # post-update traffic must decode per the NEW weights everywhere
    phase2 = [Request(prompt=list(rng.integers(0, cfg.vocab_size,
                                               size=6)),
                      max_new_tokens=5) for _ in range(4)]
    out2 = fleet.generate(phase2, max_steps=2000)
    for r in phase2:
        ref2 = reference_decode(cfg, params2, r.prompt,
                                r.max_new_tokens)
        if out2[r.rid] != ref2:
            mismatches.append({"rid": r.rid, "engine": out2[r.rid],
                               "reference": ref2})
    ok = (fleet.rolling_update_done
          and len(swaps) == 2
          and not drops
          and st["requests_lost"] == 0
          and not mismatches
          and fleet.page_leaks() == 0)
    return {"ok": ok, "swaps": len(swaps), "dropped": drops,
            "requests_lost": st["requests_lost"],
            "mismatches": mismatches, "page_leaks": fleet.page_leaks()}


def check_tp_identity() -> dict:
    """The tensor-parallel oracle (ISSUE-16): a ``ServingEngine(tp=N)``
    on the virtual-device CPU mesh emits EXACTLY the tp=1 engine's
    tokens — across a staggered continuous-batching trace with chunked
    prefill, speculative decoding, mixed sampled/greedy slots (incl. a
    no-filter high-temperature row: the full-vocab distributed Gumbel
    draw) and forced preemption (tiny pool). Byte-identity, not
    tolerance: head-sharded attention and column/row GEMM shards
    compute bitwise the same values, and the vocab-parallel sampler's
    candidate gather reproduces the replicated filter exactly. Skipped
    (vacuous pass) when the host exposes only 1 device."""
    import jax
    import numpy as np

    from apex_tpu.serving import Request, SamplingParams, ServingEngine

    n_dev = len(jax.devices())
    tps = [t for t in (2, 4) if t <= n_dev]
    if not tps:
        return {"ok": True, "skipped": "single-device host", "tps": []}

    cfg = _tiny_cfg()
    params = _tiny_params(cfg)

    def mk():
        rng = np.random.default_rng(19)
        sps = [None,
               SamplingParams(temperature=0.9, top_k=12, top_p=0.9,
                              seed=17),
               SamplingParams(temperature=1.4, seed=23),  # no filters
               None,
               SamplingParams(temperature=0.8, top_p=0.8, seed=29)]
        return [Request(prompt=list(rng.integers(0, cfg.vocab_size,
                                                 size=L)),
                        max_new_tokens=8, arrival_step=2 * i,
                        sampling=sp, rid=47_000 + i)
                for i, (L, sp) in enumerate(zip((14, 11, 13, 9, 12),
                                                sps))]

    def run(tp):
        # tiny pool -> shared slots / possible preemption; chunked
        # prefill + speculation arm all three jitted programs
        eng = ServingEngine(cfg, params, n_slots=2, num_pages=6,
                            max_prompt_len=16, prefill_chunk=3,
                            spec_k=2, tp=tp)
        out = eng.generate(mk(), max_steps=2000)
        eng.scheduler.check_invariants()
        leaks = eng.scheduler.allocator.used_count
        return out, eng.last_stats, leaks

    base, base_stats, base_leaks = run(1)
    mismatches = []
    psums = {}
    for tp in tps:
        out, stats, leaks = run(tp)
        psums[tp] = stats["psum_per_program"]
        for rid in base:
            if out.get(rid) != base[rid]:
                mismatches.append({"tp": tp, "rid": rid,
                                   "tp_engine": out.get(rid),
                                   "tp1": base[rid]})
        if leaks:
            mismatches.append({"tp": tp, "page_leaks": leaks})
    # the collective budget: 2 sublayer tails + 1 fused sampler psum
    psum_ok = all(all(v == 3 for v in p.values()) for p in psums.values())
    ok = not mismatches and psum_ok and base_leaks == 0
    return {"ok": ok, "tps": tps, "mismatches": mismatches,
            "psum_per_program": psums, "psum_budget_ok": psum_ok}


def check_proc_fleet_failover() -> dict:
    """The real-process chaos bar (ISSUE-20): 3 worker SUBPROCESSES,
    one SIGKILLed mid-frame and another wedged (heartbeat stalled) in
    the SAME run — the FleetSupervisor must detect both (death by exit,
    hang by staleness), SIGKILL + restart them, and migrate their
    in-flight work: every offered request reaches exactly one terminal
    state, requests_lost == 0, survivor AND migrant tokens
    byte-identical to the undisturbed dense reference, zero page leaks,
    and the torn reply frame + torn telemetry line are COUNTED, never
    crashed on."""
    import tempfile

    import numpy as np

    from apex_tpu.resilience import ServingChaos
    from apex_tpu.serving import (
        FleetSupervisor, Request, RequestStatus, reference_decode,
    )
    from apex_tpu.serving.worker import model_from_spec
    from apex_tpu.telemetry import read_jsonl

    spec = {"kind": "tiny_gpt",
            "engine": {"n_slots": 2, "num_pages": 8,
                       "max_prompt_len": 16}}
    cfg, params = model_from_spec(spec)
    rng = np.random.default_rng(11)
    reqs = [
        Request(prompt=list(rng.integers(0, cfg.vocab_size,
                                         size=int(rng.integers(7, 14)))),
                max_new_tokens=6, arrival_step=i)
        for i in range(8)
    ]
    chaos = (ServingChaos()
             .kill_worker_at(1, 4, mid_frame=True)
             .wedge_worker_at(2, 6, stall_s=60.0))
    wd = tempfile.mkdtemp(prefix="serving-proc-")
    with FleetSupervisor(spec, 3, workdir=wd, chaos=chaos,
                         heartbeat_timeout_s=2.0, rpc_timeout_s=6.0,
                         startup_timeout_s=240.0) as sup:
        sup.launch()
        out = sup.generate(reqs, max_steps=2000)
        st = sup.last_stats
        leaks = sup.page_leaks()
    kinds = sorted(i["kind"] for i in st["incidents"])
    mismatches = []
    for r in reqs:
        ref = reference_decode(cfg, params, r.prompt, r.max_new_tokens)
        if out[r.rid] != ref:
            mismatches.append({"rid": r.rid, "worker": out[r.rid],
                               "reference": ref})
    # the killed worker's torn telemetry line must read back tolerantly
    import glob

    telem_stats = {}
    telem_records = 0
    for path in sorted(glob.glob(os.path.join(wd, "replica-*.jsonl"))):
        telem_records += len(read_jsonl(path, stats=telem_stats))
    ok = (kinds == ["worker_death", "worker_hang"]
          and st["requests_lost"] == 0
          and st["migrated"] >= 1
          and st["replica_deaths"] == 2
          and st["mttr_s"] is not None
          and st["torn_frames"] >= 1
          and not mismatches
          and all(r.status is RequestStatus.COMPLETED for r in reqs)
          and leaks == 0
          and telem_records > 0
          and telem_stats.get("torn_lines", 0) >= 1)
    return {"ok": ok, "incidents": kinds,
            "requests_lost": st["requests_lost"],
            "migrated": st["migrated"], "mttr_s": st["mttr_s"],
            "torn_frames": st["torn_frames"],
            "torn_telemetry_lines": telem_stats.get("torn_lines", 0),
            "mismatches": mismatches, "page_leaks": leaks}


CHECKS = {
    "decode_parity": check_decode_parity,
    "tp_identity": check_tp_identity,
    "chunked_prefill_identity": check_chunked_prefill_identity,
    "prefix_hit_identity": check_prefix_hit_identity,
    "spec_greedy_identity": check_spec_greedy_identity,
    "sampled_seeded_identity": check_sampled_seeded_identity,
    "fleet_kill_migrate": check_fleet_kill_migrate,
    "fleet_drain_join": check_fleet_drain_join,
    "proc_fleet_failover": check_proc_fleet_failover,
    "token_identity": check_token_identity,
    "step_audit": check_step_audit,
    "poison_quarantine": check_poison_quarantine,
    "timeout_eviction": check_timeout_eviction,
    "kill_recover": check_kill_recover,
}


def run_checks(names=None) -> dict:
    out = {"event": "serving_check", "checks": {}}
    ok = True
    for name in (list(names) if names else sorted(CHECKS)):
        res = CHECKS[name]()
        out["checks"][name] = res
        ok = ok and bool(res["ok"])
    out["ok"] = ok
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Self-check of apex_tpu.serving on its own stack")
    ap.add_argument("--self", action="store_true", dest="self_check",
                    help="run the built-in serving smokes (required mode)")
    ap.add_argument("--check", action="append", choices=sorted(CHECKS),
                    help="restrict to specific check(s)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full result as JSON")
    args = ap.parse_args(argv)
    if not args.self_check:
        ap.error("nothing to do: pass --self (run the serving smokes)")

    try:
        result = run_checks(args.check)
    except Exception as e:  # infra failure must not read as "correct"
        print(f"serving check failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(result, indent=2, default=str))
    else:
        for name, res in result["checks"].items():
            status = "PASS" if res["ok"] else "FAIL"
            detail = {k: v for k, v in res.items()
                      if k not in ("ok", "mismatches")}
            print(f"{status}  {name}  {detail}")
        print("summary:", json.dumps({"ok": result["ok"]}))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
