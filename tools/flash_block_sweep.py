"""Flash-attention block-size sweep across shapes (VERDICT r4 #8).

Measures fwd and fwd+bwd kernel self-times from xplane traces for
(seq, head_dim) in {512, 1024, 2048, 4096} x {64, 128} over candidate
(block_q, block_k) tilings, differentiating w.r.t. q, k AND v with all
cotangents consumed — differentiating w.r.t. q alone lets XLA dead-code
-eliminate the dkv kernel and reports a fantasy bwd time (the round-5
regression this file exists to prevent).

Run on a real TPU:  PYTHONPATH=. python tools/flash_block_sweep.py
Prints one line per (shape, tiling) plus a per-shape best; the measured
conclusions live in ``ops/flash_attention._bwd_block_table`` and the
sweep results table in ``docs/flash_block_sweep.md``.
"""
import glob
import sys
import tempfile
from collections import defaultdict

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")

from apex_tpu.ops.flash_attention import flash_attention  # noqa: E402

REPS = 8
# keep total tokens comparable across seq (b*s = 32k tokens, n=8 heads)
CONFIGS = [
    # (b, n, s, d)
    (32, 8, 512, 64), (16, 8, 1024, 64), (8, 8, 2048, 64), (4, 8, 4096, 64),
    (32, 8, 512, 128), (16, 8, 1024, 128), (8, 8, 2048, 128),
    (4, 8, 4096, 128),
]
CAND = [(1024, 1024), (1024, 512), (512, 1024), (512, 512), (2048, 2048),
        (4096, 4096), (2048, 1024), (1024, 2048)]


def kernel_ms(trace_dir):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2
    per = defaultdict(int)
    for path in glob.glob(trace_dir + "/**/*.xplane.pb", recursive=True):
        xs = xplane_pb2.XSpace()
        xs.ParseFromString(open(path, "rb").read())
        for plane in xs.planes:
            if "/device:TPU" not in plane.name:
                continue
            for line in plane.lines:
                if line.name != "XLA Ops":
                    continue
                for ev in line.events:
                    nm = plane.event_metadata[ev.metadata_id].name
                    if "apex_tpu" in nm:
                        per["kernels"] += ev.duration_ps
    return per["kernels"] / 1e9 / REPS


def main():
    for b, n, s, d in CONFIGS:
        ks = jax.random.split(jax.random.PRNGKey(0), 4)
        q = jax.random.normal(ks[0], (b, n, s, d), jnp.bfloat16)
        k = jax.random.normal(ks[1], (b, n, s, d), jnp.bfloat16)
        v = jax.random.normal(ks[2], (b, n, s, d), jnp.bfloat16)
        do = jax.random.normal(ks[3], (b, n, s, d), jnp.bfloat16)
        best = None
        for bq, bk in CAND:
            if bq > s or bk > s:
                continue

            def loss(qq, kk, vv):
                o = flash_attention(
                    qq, kk, vv, causal=True, block_q=bq, block_k=bk,
                    bwd_block_q=bq, bwd_block_k=bk,
                )
                return jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32))

            g = jax.grad(loss, argnums=(0, 1, 2))

            @jax.jit
            def step(x):
                dq, dk, dv = g(x, k, v)
                # consume every cotangent so nothing is DCE'd
                return (dq + dk + dv).astype(jnp.bfloat16) * 1e-6 + q

            try:
                x = q
                for _ in range(2):
                    x = step(x)
                float(jnp.sum(x[0, 0, 0, :2].astype(jnp.float32)))
                dtr = tempfile.mkdtemp(prefix=f"fbs_{s}_{d}_{bq}_{bk}_")
                with jax.profiler.trace(dtr):
                    for _ in range(REPS):
                        x = step(x)
                    float(jnp.sum(x[0, 0, 0, :2].astype(jnp.float32)))
            except Exception as e:  # e.g. VMEM OOM at whole-seq bwd tiles
                msg = str(e).splitlines()[0][:70] if str(e) else type(e).__name__
                print(f"s={s:4d} d={d:3d} bq={bq:4d} bk={bk:4d} "
                      f"FAILED: {msg}", flush=True)
                continue
            t = kernel_ms(dtr)
            print(f"s={s:4d} d={d:3d} bq={bq:4d} bk={bk:4d} "
                  f"kernels {t:7.3f} ms", flush=True)
            if best is None or t < best[0]:
                best = (t, bq, bk)
        print(f"s={s:4d} d={d:3d} BEST bq={best[1]} bk={best[2]} "
              f"{best[0]:.3f} ms", flush=True)


if __name__ == "__main__":
    main()
