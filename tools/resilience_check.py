"""Resilience chaos smoke — fast CI proof the fault tolerance works.

Like ``tools/static_audit.py --self``, this self-hosts the subsystem on
the repo's own machinery, small enough for the tier-1 CPU lane:

- ``nan_rewind``       a tiny packed-FusedAdam train loop hits a chaos-
                       poisoned data window (persistent NaN grads); the
                       scaler's consecutive-skip counter crosses the
                       budget, the numerics engine emits ONE edge-
                       triggered ``scaler_stall``, the RewindController
                       rewinds ONCE past the window, and training
                       finishes finite.
- ``failed_write``     a checkpoint commit fails mid-flight (chaos) and
                       the newest COMMITTED checkpoint is corrupted
                       post-hoc; restore falls back to the newest good
                       step — atomicity + typed-corruption fallback.
- ``watchdog``         a stalled wait trips the hang watchdog with an
                       all-thread stack dump instead of hanging.
- ``elastic_resume``   a W=4 two-phase-committed checkpoint (flat
                       packed FusedAdam + GradBuckets state, sharded by
                       rows across 4 manager instances) restores onto a
                       W'=2 world: the re-flattened state continues the
                       loss records BYTE-identically to an
                       uninterrupted W'=2 run, ``check_pack_spec(spec,
                       shard_count=2)`` is clean, and a newer
                       MARKERLESS step (a torn multi-host save) is
                       skipped with a ``checkpoint_fallback`` event —
                       never restored.
- ``host_kill``        a supervised 2-fake-host world (real
                       subprocesses) suffers a SIGKILL mid-run; the
                       supervisor detects the death, restarts the
                       world, auto-resume picks up from a COMMITTED
                       step > 0, and every loss record matches the
                       uninterrupted reference byte-for-byte.

Usage::

    python tools/resilience_check.py --self           # table, exit 1 on fail
    python tools/resilience_check.py --self --json
    python tools/resilience_check.py --self --check nan_rewind

Exit codes (CI contract, same as static_audit/health_report): 0 = all
checks pass, 1 = a check failed, 2 = infra/usage error.
"""
from __future__ import annotations

import argparse
import functools
import json
import os
import shutil
import sys
import tempfile

# script-mode invocation (`python tools/resilience_check.py ...`) puts
# tools/ at sys.path[0]; the repo root must be importable for apex_tpu
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def check_nan_rewind() -> dict:
    """Persistent-NaN injection -> exactly one stall, one rewind, finite
    training afterwards."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.amp.scaler import LossScaler
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.resilience import (
        ChaosMonkey, IndexedBatches, RewindController, capture,
        poison_grads,
    )
    from apex_tpu.telemetry import MultiRecorder, RingBufferRecorder
    from apex_tpu.telemetry import numerics as tnum

    params = {"w": jnp.ones((16,), jnp.float32)}
    opt = FusedAdam(lr=1e-2, packed=True, packed_interpret=True,
                    packed_chunk_size=256)
    sc = LossScaler("dynamic", init_scale=2.0 ** 4, hysteresis=1)
    mon = tnum.NumericsMonitor(params, max_consecutive_skips=3)
    rec = RingBufferRecorder()
    ctl = RewindController(keep=2, skip_budget=3, recorder=rec,
                           max_rewinds=2)
    sink = MultiRecorder(rec, ctl)
    chaos = ChaosMonkey().poison_batches(range(6, 10))
    it = IndexedBatches(
        lambda i: jnp.full((16,), 0.1 * ((i % 5) + 1), jnp.float32))

    @jax.jit
    def step(x, poison, params, opt_state, sstate, nstate):
        loss, grads = jax.value_and_grad(
            lambda p: jnp.sum(p["w"] * x))(params)
        grads = poison_grads(grads, poison)
        grads, sstate, nstate = sc.unscale(
            sstate, grads, numerics=(mon, nstate))
        params, opt_state = opt.step(
            grads, opt_state, params, found_inf=sstate.found_inf)
        sstate, nstate = sc.update_scale(sstate, numerics=nstate)
        nstate = mon.drain(nstate, sink)
        return loss, params, opt_state, sstate, nstate

    opt_state, sstate, nstate = opt.init(params), sc.init_state(), mon.init()
    losses, stepno, rewinds = [], 0, 0
    while stepno < 18:
        x = next(it)
        poison = chaos.should_poison(it.position - 1)
        loss, params, opt_state, sstate, nstate = step(
            x, poison, params, opt_state, sstate, nstate)
        losses.append(float(loss))
        stepno += 1
        st = capture(stepno, params, opt_state, scaler=sstate,
                     numerics=nstate, data=it.state())
        ctl.offer(st, consecutive_skips=sstate.consecutive_skips)
        jax.effects_barrier()  # the stall event must land before poll
        if ctl.rewind_pending:
            restored = ctl.rewind(data_iter=it, skip_batches=4,
                                  current_step=stepno)
            params = jax.device_put(restored.params)
            opt_state = jax.device_put(restored.opt_state)
            sstate = jax.device_put(restored.scaler)
            nstate = jax.device_put(restored.numerics)
            stepno = int(restored.step)
            rewinds += 1
    jax.effects_barrier()
    kinds = [r.get("kind") or r["event"] for r in rec.records]
    tail_finite = bool(np.all(np.isfinite(losses[-4:])))
    ok = (rewinds == 1 and kinds.count("scaler_stall") == 1
          and kinds.count("rewind") == 1 and tail_finite)
    return {"ok": ok, "rewinds": rewinds,
            "scaler_stall_events": kinds.count("scaler_stall"),
            "rewind_events": kinds.count("rewind"),
            "tail_finite": tail_finite, "events": kinds}


def check_failed_write() -> dict:
    """A commit that dies mid-flight + post-hoc corruption of the newest
    checkpoint: the previous good step stays loadable."""
    import jax.numpy as jnp

    from apex_tpu.resilience import (
        ChaosError, ChaosMonkey, CheckpointManager, capture,
        corrupt_checkpoint,
    )
    from apex_tpu.telemetry import RingBufferRecorder

    root = tempfile.mkdtemp(prefix="apex_tpu_resilience_check_")
    try:
        rec = RingBufferRecorder()
        chaos = ChaosMonkey().fail_commit_at(6)
        mgr = CheckpointManager(root, keep_n=3, sink=rec, chaos=chaos)
        params = {"w": jnp.arange(8.0)}
        template = capture(0, params, None)
        for s in (2, 4):
            mgr.save(capture(s, {"w": jnp.full((8,), float(s))}, None))
        mgr.wait_until_finished()
        # injected failure AFTER the tmp tree is written, BEFORE commit
        mgr.save(capture(6, {"w": jnp.full((8,), 6.0)}, None))
        failed_surfaced = False
        try:
            mgr.wait_until_finished()
        except ChaosError:
            failed_surfaced = True
        after_fail = mgr.restore(template)
        atomic_ok = (after_fail is not None and after_fail.step == 4
                     and float(after_fail.params["w"][0]) == 4.0)
        # post-hoc corruption of the newest committed step -> fallback
        corrupt_checkpoint(os.path.join(root, "step_00000004"))
        fell_back = mgr.restore(template)
        fallback_ok = fell_back is not None and fell_back.step == 2
        events = [r["event"] for r in rec.records]
        ok = (failed_surfaced and atomic_ok and fallback_ok
              and "checkpoint_failed" in events
              and "checkpoint_fallback" in events)
        return {"ok": ok, "failed_surfaced": failed_surfaced,
                "atomic_ok": atomic_ok, "fallback_ok": fallback_ok,
                "events": events}
    finally:
        shutil.rmtree(root, ignore_errors=True)


def check_watchdog() -> dict:
    """A stalled blocking point trips the watchdog with a stack dump
    instead of hanging."""
    import threading

    from apex_tpu.resilience import HangError, HangWatchdog
    from apex_tpu.telemetry import RingBufferRecorder

    rec = RingBufferRecorder()
    with HangWatchdog(timeout_s=0.3, poll_s=0.02, sink=rec) as wd:
        never = threading.Event()
        tripped, has_stacks = False, False
        try:
            wd.wait(never, "stalled callback drain")
        except HangError as e:
            tripped = True
            has_stacks = "MainThread" in e.stacks
    hang_events = [r for r in rec.records if r["event"] == "hang"]
    ok = tripped and has_stacks and len(hang_events) == 1
    return {"ok": ok, "tripped": tripped, "has_stacks": has_stacks,
            "hang_events": len(hang_events)}


def check_elastic_resume() -> dict:
    """W=4 committed shards -> W'=2 world: bit-identical continuation,
    shard-clean new layout, markerless garbage never restored."""
    import jax
    import json as _json

    from apex_tpu import analysis
    from apex_tpu.resilience import ElasticCheckpointManager, capture
    from apex_tpu.resilience._elastic_host import (
        build_world, init_params, reference_records,
    )
    from apex_tpu.telemetry import RingBufferRecorder

    root = tempfile.mkdtemp(prefix="apex_tpu_elastic_check_")
    try:
        W, W2, head, total = 4, 2, 4, 8
        # head of the run at W=4, committed via 4 manager instances
        ref_head, head_state = reference_records(W, head)
        rec = RingBufferRecorder()
        mgrs = [ElasticCheckpointManager(root, host=h, world=W, sink=rec,
                                         barrier_timeout_s=30.0)
                for h in range(W)]
        for m in mgrs[1:]:
            m.save(head_state, blocking=False)  # wait for COMMIT async
        mgrs[0].save(head_state, blocking=True)
        for m in mgrs[1:]:
            m.wait_until_finished()

        # a TORN newer save: one shard landed, no COMMIT marker
        torn = os.path.join(root, "step_00000006", "shard-1.part")
        os.makedirs(torn)
        with open(os.path.join(torn, "meta.json"), "w") as f:
            _json.dump({"step": 6, "host": 1, "world": W,
                        "pid": os.getpid()}, f)

        # restore onto the SHRUNK world
        def fresh2():
            p, b2, o2, s2 = build_world(W2)
            return capture(0, p, o2.init(p), scaler=s2.init_state(),
                           rng=jax.random.PRNGKey(42),
                           data={"position": 0})

        m2 = ElasticCheckpointManager(root, host=0, world=W2, sink=rec,
                                      barrier_timeout_s=30.0)
        restored = m2.restore(fresh2())
        resumed_from = int(restored.step) if restored else None
        spec2 = restored.opt_state.spec if restored else None
        findings = (analysis.check_pack_spec(spec2, shard_count=W2)
                    if spec2 is not None else ["no spec"])
        tail, _ = reference_records(W2, total, start_state=restored)
        ref_all, _ = reference_records(W2, total)
        events = [r["event"] for r in rec.records]
        fallbacks = [r for r in rec.records
                     if r["event"] == "checkpoint_fallback"]
        ok = (resumed_from == head
              and not findings
              and {**ref_head, **tail} == ref_all
              and any(r.get("step") == 6 for r in fallbacks)
              and "checkpoint_reshard" in events)
        return {"ok": ok, "resumed_from": resumed_from,
                "spec_findings": [str(f) for f in findings],
                "records_match": {**ref_head, **tail} == ref_all,
                "markerless_skipped": any(r.get("step") == 6
                                          for r in fallbacks),
                "resharded": "checkpoint_reshard" in events,
                "events": events}
    finally:
        shutil.rmtree(root, ignore_errors=True)


def check_host_kill() -> dict:
    """Supervised 2-host world + SIGKILL: restart, resume from a
    committed step, byte-identical loss records."""
    import sys as _sys

    from apex_tpu.resilience import Supervisor
    from apex_tpu.resilience._elastic_host import reference_records
    from apex_tpu.telemetry import RingBufferRecorder

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    host_program = os.path.join(repo, "apex_tpu", "resilience",
                                "_elastic_host.py")
    run_dir = tempfile.mkdtemp(prefix="apex_tpu_host_kill_")
    try:
        ckpt = os.path.join(run_dir, "ckpt")
        losses = os.path.join(run_dir, "losses.txt")
        steps, world = 8, 2

        def build_cmd(host, w, incarnation):
            return [_sys.executable, host_program,
                    "--host", host, "--world", w, "--steps", steps,
                    "--root", ckpt, "--losses", losses,
                    "--heartbeat-dir", os.path.join(run_dir, "hb"),
                    "--save-every", 2, "--barrier-timeout", 30,
                    "--step-sleep", 0.1]

        def host_env(host, w, incarnation):
            env = {"PYTHONPATH": repo + os.pathsep
                   + os.environ.get("PYTHONPATH", ""),
                   "JAX_PLATFORMS": "cpu"}
            if incarnation == 0 and host == 1:
                env["APEX_TPU_ELASTIC_CHAOS"] = "kill@7"
            return env

        rec = RingBufferRecorder()
        sup = Supervisor(build_cmd, world,
                         heartbeat_dir=os.path.join(run_dir, "hb"),
                         heartbeat_timeout_s=60.0,
                         startup_timeout_s=120.0, max_restarts=2,
                         sink=rec, host_env=host_env)
        summary = sup.run()

        # parse host 0's appended records; find the restart point
        seq, records = [], {}
        with open(losses) as f:
            for line in f:
                if line.startswith("S "):
                    _, s, hexval = line.split()
                    seq.append(int(s))
                    records[int(s)] = hexval
        resume_points = [seq[i + 1] for i in range(len(seq) - 1)
                         if seq[i + 1] <= seq[i]]
        resumed_from_commit = bool(resume_points) and min(
            resume_points) > 0
        ref, _ = reference_records(world, steps)
        ok = (summary["ok"] and summary["restarts"] == 1
              and summary["incidents"][0]["kind"] == "host_death"
              and resumed_from_commit
              and records == ref)
        return {"ok": ok, "restarts": summary["restarts"],
                "incidents": summary["incidents"],
                "resume_points": resume_points,
                "resumed_from_commit": resumed_from_commit,
                "records_match": records == ref,
                "n_records": len(records)}
    finally:
        shutil.rmtree(run_dir, ignore_errors=True)


CHECKS = {
    "nan_rewind": check_nan_rewind,
    "failed_write": check_failed_write,
    "watchdog": check_watchdog,
    "elastic_resume": check_elastic_resume,
    "host_kill": check_host_kill,
}


def run_checks(names=None) -> dict:
    out = {"event": "resilience_check", "checks": {}}
    ok = True
    for name in (list(names) if names else sorted(CHECKS)):
        res = CHECKS[name]()
        out["checks"][name] = res
        ok = ok and bool(res["ok"])
    out["ok"] = ok
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Chaos smoke of apex_tpu.resilience on its own stack")
    ap.add_argument("--self", action="store_true", dest="self_check",
                    help="run the built-in chaos smokes (required mode)")
    ap.add_argument("--check", action="append", choices=sorted(CHECKS),
                    help="restrict to specific check(s)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full result as JSON")
    args = ap.parse_args(argv)
    if not args.self_check:
        ap.error("nothing to do: pass --self (run the chaos smokes)")

    try:
        result = run_checks(args.check)
    except Exception as e:  # infra failure must not read as "resilient"
        print(f"resilience check failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(result, indent=2, default=str))
    else:
        for name, res in result["checks"].items():
            status = "PASS" if res["ok"] else "FAIL"
            detail = {k: v for k, v in res.items()
                      if k not in ("ok", "events")}
            print(f"{status}  {name}  {detail}")
        print("summary:", json.dumps({"ok": result["ok"]}))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
