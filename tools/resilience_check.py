"""Resilience chaos smoke — fast CI proof the fault tolerance works.

Like ``tools/static_audit.py --self``, this self-hosts the subsystem on
the repo's own machinery, small enough for the tier-1 CPU lane:

- ``nan_rewind``       a tiny packed-FusedAdam train loop hits a chaos-
                       poisoned data window (persistent NaN grads); the
                       scaler's consecutive-skip counter crosses the
                       budget, the numerics engine emits ONE edge-
                       triggered ``scaler_stall``, the RewindController
                       rewinds ONCE past the window, and training
                       finishes finite.
- ``failed_write``     a checkpoint commit fails mid-flight (chaos) and
                       the newest COMMITTED checkpoint is corrupted
                       post-hoc; restore falls back to the newest good
                       step — atomicity + typed-corruption fallback.
- ``watchdog``         a stalled wait trips the hang watchdog with an
                       all-thread stack dump instead of hanging.

Usage::

    python tools/resilience_check.py --self           # table, exit 1 on fail
    python tools/resilience_check.py --self --json
    python tools/resilience_check.py --self --check nan_rewind

Exit codes (CI contract, same as static_audit/health_report): 0 = all
checks pass, 1 = a check failed, 2 = infra/usage error.
"""
from __future__ import annotations

import argparse
import functools
import json
import os
import shutil
import sys
import tempfile

# script-mode invocation (`python tools/resilience_check.py ...`) puts
# tools/ at sys.path[0]; the repo root must be importable for apex_tpu
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def check_nan_rewind() -> dict:
    """Persistent-NaN injection -> exactly one stall, one rewind, finite
    training afterwards."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.amp.scaler import LossScaler
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.resilience import (
        ChaosMonkey, IndexedBatches, RewindController, capture,
        poison_grads,
    )
    from apex_tpu.telemetry import MultiRecorder, RingBufferRecorder
    from apex_tpu.telemetry import numerics as tnum

    params = {"w": jnp.ones((16,), jnp.float32)}
    opt = FusedAdam(lr=1e-2, packed=True, packed_interpret=True,
                    packed_chunk_size=256)
    sc = LossScaler("dynamic", init_scale=2.0 ** 4, hysteresis=1)
    mon = tnum.NumericsMonitor(params, max_consecutive_skips=3)
    rec = RingBufferRecorder()
    ctl = RewindController(keep=2, skip_budget=3, recorder=rec,
                           max_rewinds=2)
    sink = MultiRecorder(rec, ctl)
    chaos = ChaosMonkey().poison_batches(range(6, 10))
    it = IndexedBatches(
        lambda i: jnp.full((16,), 0.1 * ((i % 5) + 1), jnp.float32))

    @jax.jit
    def step(x, poison, params, opt_state, sstate, nstate):
        loss, grads = jax.value_and_grad(
            lambda p: jnp.sum(p["w"] * x))(params)
        grads = poison_grads(grads, poison)
        grads, sstate, nstate = sc.unscale(
            sstate, grads, numerics=(mon, nstate))
        params, opt_state = opt.step(
            grads, opt_state, params, found_inf=sstate.found_inf)
        sstate, nstate = sc.update_scale(sstate, numerics=nstate)
        nstate = mon.drain(nstate, sink)
        return loss, params, opt_state, sstate, nstate

    opt_state, sstate, nstate = opt.init(params), sc.init_state(), mon.init()
    losses, stepno, rewinds = [], 0, 0
    while stepno < 18:
        x = next(it)
        poison = chaos.should_poison(it.position - 1)
        loss, params, opt_state, sstate, nstate = step(
            x, poison, params, opt_state, sstate, nstate)
        losses.append(float(loss))
        stepno += 1
        st = capture(stepno, params, opt_state, scaler=sstate,
                     numerics=nstate, data=it.state())
        ctl.offer(st, consecutive_skips=sstate.consecutive_skips)
        jax.effects_barrier()  # the stall event must land before poll
        if ctl.rewind_pending:
            restored = ctl.rewind(data_iter=it, skip_batches=4,
                                  current_step=stepno)
            params = jax.device_put(restored.params)
            opt_state = jax.device_put(restored.opt_state)
            sstate = jax.device_put(restored.scaler)
            nstate = jax.device_put(restored.numerics)
            stepno = int(restored.step)
            rewinds += 1
    jax.effects_barrier()
    kinds = [r.get("kind") or r["event"] for r in rec.records]
    tail_finite = bool(np.all(np.isfinite(losses[-4:])))
    ok = (rewinds == 1 and kinds.count("scaler_stall") == 1
          and kinds.count("rewind") == 1 and tail_finite)
    return {"ok": ok, "rewinds": rewinds,
            "scaler_stall_events": kinds.count("scaler_stall"),
            "rewind_events": kinds.count("rewind"),
            "tail_finite": tail_finite, "events": kinds}


def check_failed_write() -> dict:
    """A commit that dies mid-flight + post-hoc corruption of the newest
    checkpoint: the previous good step stays loadable."""
    import jax.numpy as jnp

    from apex_tpu.resilience import (
        ChaosError, ChaosMonkey, CheckpointManager, capture,
        corrupt_checkpoint,
    )
    from apex_tpu.telemetry import RingBufferRecorder

    root = tempfile.mkdtemp(prefix="apex_tpu_resilience_check_")
    try:
        rec = RingBufferRecorder()
        chaos = ChaosMonkey().fail_commit_at(6)
        mgr = CheckpointManager(root, keep_n=3, sink=rec, chaos=chaos)
        params = {"w": jnp.arange(8.0)}
        template = capture(0, params, None)
        for s in (2, 4):
            mgr.save(capture(s, {"w": jnp.full((8,), float(s))}, None))
        mgr.wait_until_finished()
        # injected failure AFTER the tmp tree is written, BEFORE commit
        mgr.save(capture(6, {"w": jnp.full((8,), 6.0)}, None))
        failed_surfaced = False
        try:
            mgr.wait_until_finished()
        except ChaosError:
            failed_surfaced = True
        after_fail = mgr.restore(template)
        atomic_ok = (after_fail is not None and after_fail.step == 4
                     and float(after_fail.params["w"][0]) == 4.0)
        # post-hoc corruption of the newest committed step -> fallback
        corrupt_checkpoint(os.path.join(root, "step_00000004"))
        fell_back = mgr.restore(template)
        fallback_ok = fell_back is not None and fell_back.step == 2
        events = [r["event"] for r in rec.records]
        ok = (failed_surfaced and atomic_ok and fallback_ok
              and "checkpoint_failed" in events
              and "checkpoint_fallback" in events)
        return {"ok": ok, "failed_surfaced": failed_surfaced,
                "atomic_ok": atomic_ok, "fallback_ok": fallback_ok,
                "events": events}
    finally:
        shutil.rmtree(root, ignore_errors=True)


def check_watchdog() -> dict:
    """A stalled blocking point trips the watchdog with a stack dump
    instead of hanging."""
    import threading

    from apex_tpu.resilience import HangError, HangWatchdog
    from apex_tpu.telemetry import RingBufferRecorder

    rec = RingBufferRecorder()
    with HangWatchdog(timeout_s=0.3, poll_s=0.02, sink=rec) as wd:
        never = threading.Event()
        tripped, has_stacks = False, False
        try:
            wd.wait(never, "stalled callback drain")
        except HangError as e:
            tripped = True
            has_stacks = "MainThread" in e.stacks
    hang_events = [r for r in rec.records if r["event"] == "hang"]
    ok = tripped and has_stacks and len(hang_events) == 1
    return {"ok": ok, "tripped": tripped, "has_stacks": has_stacks,
            "hang_events": len(hang_events)}


CHECKS = {
    "nan_rewind": check_nan_rewind,
    "failed_write": check_failed_write,
    "watchdog": check_watchdog,
}


def run_checks(names=None) -> dict:
    out = {"event": "resilience_check", "checks": {}}
    ok = True
    for name in (list(names) if names else sorted(CHECKS)):
        res = CHECKS[name]()
        out["checks"][name] = res
        ok = ok and bool(res["ok"])
    out["ok"] = ok
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Chaos smoke of apex_tpu.resilience on its own stack")
    ap.add_argument("--self", action="store_true", dest="self_check",
                    help="run the built-in chaos smokes (required mode)")
    ap.add_argument("--check", action="append", choices=sorted(CHECKS),
                    help="restrict to specific check(s)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full result as JSON")
    args = ap.parse_args(argv)
    if not args.self_check:
        ap.error("nothing to do: pass --self (run the chaos smokes)")

    try:
        result = run_checks(args.check)
    except Exception as e:  # infra failure must not read as "resilient"
        print(f"resilience check failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(result, indent=2, default=str))
    else:
        for name, res in result["checks"].items():
            status = "PASS" if res["ok"] else "FAIL"
            detail = {k: v for k, v in res.items()
                      if k not in ("ok", "events")}
            print(f"{status}  {name}  {detail}")
        print("summary:", json.dumps({"ok": result["ok"]}))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
